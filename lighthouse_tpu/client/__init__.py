"""Beacon-node client assembly (ref beacon_node/client/src/builder.rs:74-786
+ beacon_node/src/lib.rs ProductionBeaconNode).

``ClientBuilder`` chains the same construction steps the reference does —
chain, processor, network service, HTTP API, metrics, slasher, notifier —
and ``Client`` owns their lifecycles.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..store.hot_cold import HotColdDB, StoreConfig
from ..store.kv import LevelStore
from ..types.spec import ChainSpec
from ..utils.logging import get_logger, init_logging
from ..utils.slot_clock import ManualSlotClock, SystemTimeSlotClock
from .notifier import Notifier

log = get_logger("client")


@dataclass
class ClientConfig:
    datadir: str | None = None  # None = in-memory stores
    http_enabled: bool = True
    http_port: int = 0  # 0 = ephemeral
    metrics_enabled: bool = False
    metrics_port: int = 0
    slasher_enabled: bool = False
    validator_monitor_auto: bool = False
    validator_monitor_indices: tuple = ()
    interop_validators: int = 16
    genesis_time: int | None = None  # None = now
    debug_level: str = "info"
    use_system_clock: bool = True
    listen_port: int | None = None  # TCP gossip/RPC listener (None = no p2p)
    boot_nodes: str = ""  # comma-separated UDP boot-node addresses
    boot_enrs: str = ""   # comma-separated hex ENRs (discv5-style discovery)


class Client:
    def __init__(self, chain, op_pool, http_server, metrics_server,
                 slasher_service, notifier, network_service=None):
        self.chain = chain
        self.op_pool = op_pool
        self.http_server = http_server
        self.metrics_server = metrics_server
        self.slasher_service = slasher_service
        self.notifier = notifier
        self.network_service = network_service
        self._shutdown = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "Client":
        if self.http_server is not None:
            self.http_server.start()
            log.info("Beacon API started", url=self.http_server.url)
        if self.metrics_server is not None:
            self.metrics_server.start()
            log.info("Metrics server started", url=self.metrics_server.url)
        if self.notifier is not None:
            self.notifier.start()
        if self.slasher_service is not None:
            self._slasher_ticker = threading.Thread(
                target=self._run_slasher_ticks, daemon=True,
                name="slasher-tick",
            )
            self._slasher_ticker.start()
            self._threads.append(self._slasher_ticker)
        if self.chain.eth1_service is not None:
            th = threading.Thread(
                target=self._run_eth1_polls, daemon=True, name="eth1-poll"
            )
            th.start()
            self._threads.append(th)
        # the warmup thread is deliberately NOT joined on stop: it runs one
        # uninterruptible best-effort compile and exits — joining it would
        # stall every shutdown behind XLA for no correctness gain
        threading.Thread(
            target=self._warmup_bls, daemon=True, name="bls-warmup"
        ).start()
        return self

    def _run_slasher_ticks(self) -> None:
        """Per-slot slasher batch processing (the reference's timer task at
        slot_offset into each slot, slasher/service/src/service.rs)."""
        sps = self.chain.spec.preset.SECONDS_PER_SLOT
        while not self._shutdown.wait(sps):
            try:
                self.slasher_service.tick()
            except Exception as e:  # noqa: BLE001 — keep the timer alive
                log.warning("Slasher tick failed", error=str(e))

    def _run_eth1_polls(self) -> None:
        """Periodic eth1 follow poll (eth1/src/service.rs update interval)."""
        sps = self.chain.spec.preset.SECONDS_PER_SLOT
        while not self._shutdown.wait(sps):
            try:
                self.chain.eth1_service.update()
            except Exception as e:  # noqa: BLE001 — keep polling
                log.warn("Eth1 poll failed", error=str(e))

    def _warmup_bls(self) -> None:
        """Compile the verification kernels off the serving path so the first
        block publish doesn't pay XLA compilation inside an HTTP request."""
        from .. import bls

        try:
            t0 = time.monotonic()
            ok = bls.warmup()
            if bls.get_backend() == "tpu":
                import hashlib

                from ..bls import tpu_backend as tb

                root = hashlib.sha256(b"lighthouse-tpu-warmup").digest()
                sk = bls.SecretKey.from_bytes((7).to_bytes(32, "big"))
                sig = sk.sign(root).serialize()
                tb.verify_indexed_sets_device(
                    self.chain.pubkey_cache.device_array(),
                    [([0], root, sig)],
                )
            log.info(
                "BLS backend warm",
                backend=bls.get_backend(),
                healthy=ok,
                seconds=round(time.monotonic() - t0, 1),
            )
        except Exception as e:  # noqa: BLE001 — warmup is best-effort
            log.warning("BLS warmup failed", error=str(e))

    def stop(self) -> None:
        self._shutdown.set()
        for th in self._threads:
            # the periodic loops wake from their interval wait the moment
            # the shutdown event sets, so these joins return in ms
            th.join(timeout=2.0)
        if self.notifier is not None:
            self.notifier.stop()
        if self.http_server is not None:
            self.http_server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.network_service is not None:
            self.network_service.stop()
        # persist fork choice + op pool + slasher for the next boot
        # (persisted_fork_choice.rs / operation_pool persistence.rs) —
        # through the same crash-point barriers the import path uses
        try:
            from ..op_pool import persistence as pool_persist

            self.chain.persist_fork_choice()
            pool_persist.persist(self.chain.store, self.op_pool)
            if self.slasher_service is not None:
                persist = getattr(self.slasher_service.slasher, "persist", None)
                if persist is not None:
                    persist()
        except Exception as e:  # noqa: BLE001 — shutdown must not fail
            log.warn("Persistence on shutdown failed", error=str(e))

    def wait_for_shutdown(self) -> None:
        """Block until stop() or KeyboardInterrupt (Environment's shutdown
        channel, common/task_executor/src/lib.rs:205)."""
        try:
            while not self._shutdown.wait(0.5):
                pass
        except KeyboardInterrupt:
            log.info("Shutting down", reason="interrupt")
            self.stop()


class ClientBuilder:
    def __init__(self, spec: ChainSpec, config: ClientConfig | None = None):
        self.spec = spec
        self.config = config or ClientConfig()
        self._genesis_state = None
        self._slot_clock = None
        self._eth1 = None

    def interop_genesis(self) -> "ClientBuilder":
        from ..state_transition.genesis import interop_genesis_state

        genesis_time = (
            int(time.time())
            if self.config.genesis_time is None
            else self.config.genesis_time
        )
        self._genesis_state = interop_genesis_state(
            self.spec, self.config.interop_validators, genesis_time
        )
        return self

    def genesis_state(self, state) -> "ClientBuilder":
        """Boot from a provided state (the checkpoint-sync seam:
        client/src/builder.rs genesis-state branch)."""
        self._genesis_state = state
        return self

    def checkpoint_sync(self, url: str, state_id: str = "finalized") -> "ClientBuilder":
        """Fetch a trusted finalized state over HTTP and anchor the chain on
        it (client/src/builder.rs checkpoint-sync genesis branch; history is
        filled backwards by sync, not required to serve)."""
        from ..api_client import BeaconNodeHttpClient
        from ..types.containers import for_preset

        version, raw = BeaconNodeHttpClient(url).get_state_ssz(state_id)
        ns = for_preset(self.spec.preset.name)
        state = ns.state_types[version].decode(raw)
        log.info(
            "Checkpoint state fetched",
            url=url, slot=int(state.slot), fork=version,
        )
        self._genesis_state = state
        return self

    def eth1_service(self, service) -> "ClientBuilder":
        """Attach a deposit/eth1-data bridge (eth1/Eth1Service)."""
        self._eth1 = service
        return self

    def slot_clock(self, clock) -> "ClientBuilder":
        self._slot_clock = clock
        return self

    GENESIS_TIME_KEY = b"genesis_time_v1"

    def build(self) -> Client:
        cfg = self.config
        init_logging(cfg.debug_level)

        if cfg.datadir:
            import os

            os.makedirs(cfg.datadir, exist_ok=True)
            # the production node fsyncs every WAL commit (power-loss
            # durability); the test/simulation tier leaves fsync off
            store = HotColdDB(
                hot=LevelStore(
                    os.path.join(cfg.datadir, "chain.db"), fsync=True
                ),
                cold=LevelStore(
                    os.path.join(cfg.datadir, "freezer.db"), fsync=True
                ),
                config=StoreConfig(),
            )
        else:
            store = HotColdDB()

        if self._genesis_state is None:
            # an interop genesis must be the SAME one across restarts —
            # time.time() at each boot makes the datadir's whole chain
            # foreign to the new anchor and recovery silently degrades to
            # genesis. The first boot records its genesis time; later
            # boots re-derive the identical deterministic genesis from it.
            stored = store.get_meta(self.GENESIS_TIME_KEY)
            if stored is not None and self.config.genesis_time is None:
                self.config.genesis_time = int(stored.decode())
            self.interop_genesis()
            if cfg.datadir and stored is None:
                store.put_meta(
                    self.GENESIS_TIME_KEY,
                    str(int(self._genesis_state.genesis_time)).encode(),
                )
        state = self._genesis_state

        clock = self._slot_clock
        if clock is None:
            clock = (
                SystemTimeSlotClock(
                    int(state.genesis_time), self.spec.preset.SECONDS_PER_SLOT
                )
                if cfg.use_system_clock
                else ManualSlotClock(0)
            )
        # the restart-from-disk path (beacon_chain/recovery.py): WAL replay
        # already ran inside the LevelStore opens; recovery adopts the
        # persisted fork choice (head + weights + finality) and rehydrates
        # the op pool — a fresh in-memory boot degrades to the same call
        # with empty stores
        from ..beacon_chain.recovery import recover_node_state

        # interop nodes have no real engine-API endpoint; once bellatrix is
        # scheduled, block production needs SOME execution layer or every
        # post-merge proposal dies on "payload parent hash mismatch" (the
        # default payload stands in pre-merge only). The reference's interop
        # mode runs its mock_execution_layer for the same reason — the mock's
        # genesis block hash is what interop_genesis_state anchors the
        # payload-header chain on.
        execution_layer = None
        from ..types.spec import FAR_FUTURE_EPOCH

        if self.spec.bellatrix_fork_epoch != FAR_FUTURE_EPOCH:
            from ..execution_layer.mock import MockExecutionLayer

            execution_layer = MockExecutionLayer()
        chain, op_pool, recovered = recover_node_state(
            self.spec, state, store, slot_clock=clock,
            execution_layer=execution_layer,
        )
        if self._eth1 is not None:
            chain.eth1_service = self._eth1
        if recovered["fork_choice_restored"]:
            log.info(
                "Fork choice restored",
                nodes=recovered["fc_nodes"],
                head=chain.head.root.hex()[:10],
            )
        if recovered["pool_restored"]:
            log.info("Op pool restored", attestations=recovered["pool_restored"])

        network_service = None
        if cfg.listen_port is not None:
            from ..network import BeaconNodeService, GossipsubTransport

            discovery = None
            boot_enrs = [
                b.strip() for b in cfg.boot_enrs.split(",") if b.strip()
            ]
            if boot_enrs:
                from ..network.discovery import DiscoveryService
                from ..types.helpers import compute_fork_digest

                st = chain.head.state
                digest = compute_fork_digest(
                    bytes(st.fork.current_version),
                    bytes(st.genesis_validators_root),
                )
                discovery = DiscoveryService(fork_digest=digest).start()
            transport = GossipsubTransport(
                self.spec, port=cfg.listen_port, discovery=discovery
            )
            network_service = BeaconNodeService(
                transport.local_addr, self.spec, transport=transport,
                chain=chain, op_pool=op_pool,
            )
            if discovery is not None:
                from ..network.discovery import ENR

                for hexenr in boot_enrs:
                    try:
                        enr, _ = ENR.decode(bytes.fromhex(hexenr))
                        discovery.bootstrap(enr)
                    except (ValueError, OSError) as e:
                        log.warn("Bad boot ENR", error=str(e))
                transport.discover_enr()
                log.info(
                    "ENR discovery active",
                    enr=discovery.enr.encode().hex(),
                    known=len(discovery.table),
                )
            for boot in [b.strip() for b in cfg.boot_nodes.split(",") if b.strip()]:
                try:
                    transport.discover(boot)
                except OSError as e:
                    log.warn("Boot node unreachable", addr=boot, error=str(e))
            for peer in transport.peers():
                try:
                    network_service.connect(peer)
                except ConnectionError as e:
                    log.warn("Peer handshake failed", peer=peer, error=str(e))
            log.info(
                "P2P listening", addr=transport.local_addr,
                peers=len(transport.peers()),
            )

        http_server = None
        if cfg.http_enabled:
            from ..http_api import BeaconApiServer

            http_server = BeaconApiServer(
                chain, op_pool=op_pool, port=cfg.http_port,
                network_service=network_service,
                load_monitor=getattr(
                    network_service, "load_monitor", None
                ),
            )

        metrics_server = None
        if cfg.metrics_enabled:
            from ..http_metrics import MetricsServer

            metrics_server = MetricsServer(
                port=cfg.metrics_port, datadir=cfg.datadir
            )

        slasher_service = None
        if cfg.slasher_enabled:
            from ..slasher import SlasherService, make_slasher

            # the engine-backed slasher behind LIGHTHOUSE_SLASHER_BACKEND
            # (device-resident span store / numpy twin); the seed per-row
            # Slasher remains importable as the DB-backed reference twin.
            # The checkpoint store only rides a durable (WAL) datadir —
            # compressing the full span planes into a MemoryStore every
            # tick is wasted work that dies with the process (same gate as
            # the chain's per-import fork-choice persist)
            ckpt_store = store.hot if cfg.datadir else None
            slasher = make_slasher(ckpt_store, chain.ns)
            slasher_service = SlasherService(chain, slasher, op_pool)
            # subscribe to the chain's ingest seams (service.rs gossip taps)
            chain.block_observers.append(slasher_service.block_observed)
            chain.attestation_observers.append(
                slasher_service.attestation_observed
            )

        if cfg.validator_monitor_auto or cfg.validator_monitor_indices:
            from ..beacon_chain.validator_monitor import ValidatorMonitor

            chain.validator_monitor = ValidatorMonitor(
                chain, indices=cfg.validator_monitor_indices,
                auto=cfg.validator_monitor_auto,
            )

        notifier = Notifier(chain)
        return Client(
            chain, op_pool, http_server, metrics_server, slasher_service,
            notifier, network_service=network_service,
        )
