"""Key-value store abstraction (store/src/lib.rs KeyValueStore trait).

Columns mirror the reference's ``DBColumn`` byte prefixes; ``MemoryStore`` is
the test/in-process backend (``memory_store.rs``), ``LevelStore`` a
file-backed write-ahead log with an in-memory index (standing in for LevelDB
until the C++ engine lands — same interface, durable AND crash-safe: every
commit is one checksummed frame, so a kill mid-write can only ever tear the
tail, and replay truncates the tear instead of resurrecting half a batch).
"""

from __future__ import annotations

import enum
import os
import struct
import threading
import zlib


class DBColumn(enum.Enum):
    BeaconBlock = b"blk"
    BeaconState = b"ste"
    BeaconStateSummary = b"ssy"
    BeaconBlobs = b"blb"
    ForkChoice = b"frk"
    PubkeyCache = b"pkc"
    BeaconChain = b"bch"
    OpPool = b"opo"
    Eth1Cache = b"etc"
    HotDiff = b"hdf"
    ColdState = b"cst"
    ColdStateDiff = b"cdf"
    Metadata = b"met"
    LightClientUpdate = b"lcu"
    # slasher (slasher/src/database.rs database table names)
    SlasherTargets = b"stg"
    SlasherAttesterRecords = b"sar"
    SlasherIndexedAtts = b"sia"
    SlasherAttIdByHash = b"sih"
    SlasherProposals = b"spr"
    SlasherMeta = b"smt"


class KeyValueStore:
    def get(self, column: DBColumn, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: DBColumn, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: DBColumn, key: bytes) -> None:
        raise NotImplementedError

    def exists(self, column: DBColumn, key: bytes) -> bool:
        return self.get(column, key) is not None

    def iter_column(self, column: DBColumn):
        raise NotImplementedError

    def do_atomically(self, ops: list) -> None:
        """Apply a batch ALL-OR-NOTHING.

        ``ops``: list of ``("put", col, key, val)`` | ``("delete", col, key)``.

        Contract (every backend must honor it): either every op in the batch
        becomes visible or none does — to concurrent readers AND across a
        crash at any instant. Callers rely on this for multi-key sequences
        (block import, the finalization migration, slasher checkpoints):
        observing a partially-applied batch after a kill is a durability
        bug, not a degraded mode. Backends therefore stage + validate the
        whole batch BEFORE mutating anything, and commit it through one
        atomic step (one dict merge, one framed log append).
        """
        for key, value in _stage_ops(ops):
            # base implementation: per-op dispatch after full validation.
            # Crash-atomicity is the backend's job; backends with real
            # durability (LevelStore) override this with a single frame.
            col, raw = key
            if value is None:
                self.delete(col, raw)
            else:
                self.put(col, raw, value)

    def compact(self) -> None:
        pass

    def close(self) -> None:
        pass


def _stage_ops(ops: list) -> list:
    """Validate + normalize a ``do_atomically`` batch BEFORE any mutation.

    Returns ``[((column, key), value | None), ...]`` (None = delete). Any
    malformed op raises here, while the store is still untouched — a batch
    can never be half-applied because its tail failed to parse.
    """
    staged = []
    for op in ops:
        if not op or op[0] not in ("put", "delete"):
            raise ValueError(f"bad atomic op {op!r}")
        if op[0] == "put":
            _, col, key, val = op
            staged.append(((col, bytes(key)), bytes(val)))
        else:
            _, col, key = op
            staged.append(((col, bytes(key)), None))
    return staged


class MemoryStore(KeyValueStore):
    """Thread-safe dict store (memory_store.rs)."""

    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    @staticmethod
    def _k(column: DBColumn, key: bytes) -> bytes:
        return column.value + b"/" + key

    def get(self, column, key):
        with self._lock:
            return self._data.get(self._k(column, key))

    def put(self, column, key, value):
        with self._lock:
            self._data[self._k(column, key)] = bytes(value)

    def delete(self, column, key):
        with self._lock:
            self._data.pop(self._k(column, key), None)

    def iter_column(self, column):
        prefix = column.value + b"/"
        with self._lock:
            items = [
                (k[len(prefix):], v)
                for k, v in self._data.items()
                if k.startswith(prefix)
            ]
        return iter(sorted(items))

    def do_atomically(self, ops):
        # stage first (validation can raise), THEN mutate under the lock:
        # dict set/pop on staged bytes cannot fail, so the batch is applied
        # whole or not at all even when an op mid-list is malformed
        staged = _stage_ops(ops)
        with self._lock:
            for (col, key), value in staged:
                k = col.value + b"/" + key
                if value is None:
                    self._data.pop(k, None)
                else:
                    self._data[k] = value

    def __len__(self):
        return len(self._data)


# -- the write-ahead log ------------------------------------------------------

_FRAME_MAGIC = 0x4C57414C   # "LWAL"
_COMMIT_MAGIC = 0x434D4954  # "CMIT"
_FRAME_HDR = struct.Struct("<III")   # magic, n_records, payload_len
_REC_HDR = struct.Struct("<BIII")    # op, klen, vlen, crc32(op|key|val)
_COMMIT = struct.Struct("<II")       # commit magic, crc32(payload)


def _rec_crc(op: int, key: bytes, val: bytes) -> int:
    return zlib.crc32(val, zlib.crc32(key, zlib.crc32(bytes([op]))))


class LevelStore(KeyValueStore):
    """Durable append-log store: framed WAL commits + in-memory index.

    File format: a sequence of commit *frames*, each one atomic batch::

        [u32 magic][u32 n_records][u32 payload_len]
          payload: n_records x ([u8 op][u32 klen][u32 vlen][u32 rec_crc]
                                [key][value])
        [u32 commit_magic][u32 payload_crc]

    ``put``/``delete`` write a one-record frame; ``do_atomically`` writes the
    whole batch as ONE frame, so a crash at any byte either commits the batch
    or leaves a torn tail. Replay verifies the commit marker + per-record
    checksums and TRUNCATES the file at the first incomplete/corrupt frame
    (the torn tail a kill mid-write leaves) — a multi-key sequence can never
    be observed half-applied after a restart. A pre-WAL (unframed) log is
    detected on open and rewritten in place through compaction.

    Compaction writes the survivor set to ``<path>.compact`` as one frame and
    ``os.replace``s it over the log; a leftover ``.compact`` from a crash in
    that window is deleted on reopen, never replayed. ``fsync=True`` adds an
    fsync per commit (the real-node configuration; the test/simulation tier
    keeps it off — the crash harness tears writes at the API layer, not with
    power loss). ``recovery_stats`` reports what replay saw; the restart
    harness folds it into the recovery metrics. Plays the role of
    ``leveldb_store.rs`` until the native engine arrives.
    """

    _PUT, _DEL = 1, 2

    #: append-only logs need a growth bound: once the file exceeds the floor
    #: and live values are under the fraction, a commit triggers compaction
    #: (the periodic full-checkpoint writers — slasher planes every tick —
    #: otherwise grow the log by a dead frame per slot, forever)
    AUTO_COMPACT_MIN_BYTES = 4 * 1024 * 1024
    AUTO_COMPACT_LIVE_FRAC = 0.25

    def __init__(
        self,
        path: str,
        fsync: bool = False,
        owner: str | None = None,
        auto_compact: bool = True,
    ):
        self.path = path
        self.fsync = fsync
        self.owner = owner  # crash-point attribution (testing harness)
        self.auto_compact = auto_compact
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (offset, vlen)
        self._live_bytes = 0  # sum of live value lengths (compaction trigger)
        self._lock = threading.RLock()
        self.recovery_stats = {
            "replayed_frames": 0,
            "replayed_records": 0,
            "truncated_bytes": 0,
            "stale_compact_removed": 0,
            "legacy_upgraded": False,
        }
        tmp = path + ".compact"
        if os.path.exists(tmp):
            # a crash inside compact() left a partial (or complete but
            # unadopted) rewrite: the log itself is still the truth — the
            # tmp file is IGNORED and removed, never replayed
            os.unlink(tmp)
            self.recovery_stats["stale_compact_removed"] = 1
        self._fh = open(path, "a+b")
        self._replay()

    # -- replay ------------------------------------------------------------

    def _replay(self):
        self._fh.seek(0)
        data = self._fh.read()
        # < 4 bytes can be neither a frame header nor a legacy record:
        # it is a torn tail (a kill mid-first-append), handled below
        if len(data) >= 4 and struct.unpack_from("<I", data, 0)[0] != _FRAME_MAGIC:
            # pre-WAL log (the unframed [op][klen][vlen][key][val] stream):
            # replay with the legacy parser, then rewrite framed in place
            self._replay_legacy(data)
            self.recovery_stats["legacy_upgraded"] = True
            self.compact()
            return
        pos = 0
        while pos + _FRAME_HDR.size <= len(data):
            magic, n_records, plen = _FRAME_HDR.unpack_from(data, pos)
            end = pos + _FRAME_HDR.size + plen + _COMMIT.size
            if magic != _FRAME_MAGIC or end > len(data):
                break  # torn tail
            payload_off = pos + _FRAME_HDR.size
            payload = data[payload_off : payload_off + plen]
            cmagic, ccrc = _COMMIT.unpack_from(data, payload_off + plen)
            if cmagic != _COMMIT_MAGIC or ccrc != zlib.crc32(payload):
                break  # uncommitted / torn frame
            staged = self._parse_frame(payload, payload_off, n_records)
            if staged is None:
                break  # per-record corruption inside the frame
            for key, loc in staged:
                if loc is None:
                    self._index_del(key)
                else:
                    self._index_set(key, loc)
            self.recovery_stats["replayed_frames"] += 1
            self.recovery_stats["replayed_records"] += n_records
            pos = end
        if pos < len(data):
            # torn tail: drop it ON DISK too, so future appends never
            # interleave with garbage
            self.recovery_stats["truncated_bytes"] = len(data) - pos
            self._fh.truncate(pos)
            self._fh.flush()

    def _parse_frame(self, payload: bytes, payload_off: int, n_records: int):
        """[(key, (voff, vlen) | None)] for one frame, or None if any
        record fails its checksum."""
        staged, rpos = [], 0
        for _ in range(n_records):
            if rpos + _REC_HDR.size > len(payload):
                return None
            op, klen, vlen, crc = _REC_HDR.unpack_from(payload, rpos)
            rpos += _REC_HDR.size
            if rpos + klen + vlen > len(payload) or op not in (
                self._PUT, self._DEL
            ):
                return None
            key = payload[rpos : rpos + klen]
            val = payload[rpos + klen : rpos + klen + vlen]
            if crc != _rec_crc(op, key, val):
                return None
            voff = payload_off + rpos + klen
            rpos += klen + vlen
            staged.append(
                (key, (voff, vlen) if op == self._PUT else None)
            )
        return staged

    def _replay_legacy(self, data: bytes) -> None:
        """The seed's unframed record stream (discard-tail semantics)."""
        pos = 0
        while pos + 9 <= len(data):
            op, klen, vlen = struct.unpack_from("<BII", data, pos)
            pos += 9
            if pos + klen + vlen > len(data):
                break  # truncated tail: discard
            key = data[pos : pos + klen]
            pos += klen
            if op == self._PUT:
                self._index_set(key, (pos, vlen))
            else:
                self._index_del(key)
            pos += vlen

    # -- index bookkeeping -------------------------------------------------

    def _index_set(self, k: bytes, loc: tuple[int, int]) -> None:
        old = self._index.get(k)
        if old is not None:
            self._live_bytes -= old[1]
        self._index[k] = loc
        self._live_bytes += loc[1]

    def _index_del(self, k: bytes) -> None:
        old = self._index.pop(k, None)
        if old is not None:
            self._live_bytes -= old[1]

    # -- commit ------------------------------------------------------------

    @staticmethod
    def _maybe_crash(stage: str, owner, tear_capable: bool = True):
        """Crash-point hook (resilience/crashpoints.py): inert unless the
        LIGHTHOUSE_FAULT_INJECT grammar armed a kill/tear plan. The WAL
        owns its byte streams, so its barriers are tear-capable."""
        from ..resilience.crashpoints import maybe_crash

        return maybe_crash(stage, owner=owner, tear_capable=tear_capable)

    def _sync(self) -> None:
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def _commit_frame(self, staged: list) -> None:
        """Write one atomic frame for ``staged`` ([( (col,key), val|None )])
        and apply it to the index only once fully on disk. Caller holds the
        lock. The ``store.commit`` crash point fires here: ``kill`` dies
        before a single byte is written, ``tear`` persists a prefix of the
        frame (the torn tail replay must truncate) and then dies.
        """
        if not staged:
            return
        recs, keys = [], []
        for (col, key), value in staged:
            k = col.value + b"/" + key
            if value is None:
                op, val = self._DEL, b""
            else:
                op, val = self._PUT, value
            recs.append(
                _REC_HDR.pack(op, len(k), len(val), _rec_crc(op, k, val))
                + k + val
            )
            keys.append((k, val if value is not None else None))
        payload = b"".join(recs)
        frame = (
            _FRAME_HDR.pack(_FRAME_MAGIC, len(recs), len(payload))
            + payload
            + _COMMIT.pack(_COMMIT_MAGIC, zlib.crc32(payload))
        )
        action = self._maybe_crash("store.commit", self.owner)
        self._fh.seek(0, os.SEEK_END)
        start = self._fh.tell()
        if action == "tear":
            # simulate a kill mid-write: persist a deterministic prefix of
            # the frame, then die. Replay truncates exactly this tear.
            self._fh.write(frame[: max(1, len(frame) // 2)])
            self._sync()
            from ..resilience.crashpoints import raise_crash

            raise_crash("store.commit", self.owner, torn=True)
        self._fh.write(frame)
        self._sync()
        # index update AFTER the bytes are down (a failed write never
        # publishes a location)
        payload_off = start + _FRAME_HDR.size
        rpos = 0
        for k, val in keys:
            rpos += _REC_HDR.size + len(k)
            if val is None:
                self._index_del(k)
                rpos += 0
            else:
                self._index_set(k, (payload_off + rpos, len(val)))
                rpos += len(val)
        end = start + len(frame)
        if (
            self.auto_compact
            and end >= self.AUTO_COMPACT_MIN_BYTES
            and self._live_bytes < int(end * self.AUTO_COMPACT_LIVE_FRAC)
        ):
            # mostly-dead log (e.g. a full-checkpoint writer overwriting one
            # key per slot): fold it down so the file stays O(live set)
            self.compact()

    @staticmethod
    def _k(column: DBColumn, key: bytes) -> bytes:
        return column.value + b"/" + key

    def get(self, column, key):
        k = self._k(column, key)
        with self._lock:
            loc = self._index.get(k)
            if loc is None:
                return None
            off, vlen = loc
            self._fh.seek(off)
            return self._fh.read(vlen)

    def put(self, column, key, value):
        with self._lock:
            self._commit_frame([((column, bytes(key)), bytes(value))])

    def delete(self, column, key):
        with self._lock:
            if self._k(column, bytes(key)) in self._index:
                self._commit_frame([((column, bytes(key)), None)])

    def do_atomically(self, ops):
        staged = _stage_ops(ops)
        with self._lock:
            self._commit_frame(staged)

    def iter_column(self, column):
        prefix = column.value + b"/"
        with self._lock:
            keys = sorted(k for k in self._index if k.startswith(prefix))
            return iter([(k[len(prefix):], self.get(column, k[len(prefix):])) for k in keys])

    def compact(self):
        with self._lock:
            action = self._maybe_crash("store.compact", self.owner)
            tmp = self.path + ".compact"
            # stream record-by-record: the live set can be GBs of states,
            # so only one value is ever resident (the payload length and
            # commit CRC are computed without materializing the frame)
            items = sorted(self._index.items())
            payload_len = sum(
                _REC_HDR.size + len(k) + vlen for k, (_, vlen) in items
            )
            frame_len = _FRAME_HDR.size + payload_len + _COMMIT.size
            # tear = die after a deterministic PREFIX of the byte stream
            # (same cut as the frame-materializing implementation): the
            # half-written .compact must be discarded on reopen
            cut = max(1, frame_len // 2) if action == "tear" else None
            with open(tmp, "wb") as out:
                written = 0

                def emit(chunk: bytes) -> None:
                    nonlocal written
                    if cut is not None and written + len(chunk) >= cut:
                        out.write(chunk[: cut - written])
                        out.flush()
                        from ..resilience.crashpoints import raise_crash

                        raise_crash("store.compact", self.owner, torn=True)
                    out.write(chunk)
                    written += len(chunk)

                emit(_FRAME_HDR.pack(_FRAME_MAGIC, len(items), payload_len))
                crc = 0
                for k, (off, vlen) in items:
                    self._fh.seek(off)
                    v = self._fh.read(vlen)
                    rec = (
                        _REC_HDR.pack(
                            self._PUT, len(k), len(v),
                            _rec_crc(self._PUT, k, v),
                        )
                        + k + v
                    )
                    crc = zlib.crc32(rec, crc)
                    emit(rec)
                emit(_COMMIT.pack(_COMMIT_MAGIC, crc))
                out.flush()
                if self.fsync:
                    os.fsync(out.fileno())
            # the window the reopen path must survive: a kill here leaves a
            # COMPLETE .compact beside the (still authoritative) log
            # not a byte-stream barrier: a tear plan here degrades to kill
            # (the replace window is all-or-nothing by construction)
            self._maybe_crash(
                "store.compact.replace", self.owner, tear_capable=False
            )
            self._fh.close()
            os.replace(tmp, self.path)
            if self.fsync:
                dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            self._fh = open(self.path, "a+b")
            new_index, rpos = {}, _FRAME_HDR.size
            for k, (_, vlen) in items:
                rpos += _REC_HDR.size + len(k)
                new_index[k] = (rpos, vlen)
                rpos += vlen
            self._index = new_index
            self._live_bytes = sum(vlen for _, (_, vlen) in items)

    def close(self):
        self._fh.close()
