"""Key-value store abstraction (store/src/lib.rs KeyValueStore trait).

Columns mirror the reference's ``DBColumn`` byte prefixes; ``MemoryStore`` is
the test/in-process backend (``memory_store.rs``), ``LevelStore`` a
file-backed backend over a sorted on-disk log + in-memory index (standing in
for LevelDB until the C++ engine lands — same interface, durable)."""

from __future__ import annotations

import enum
import os
import struct
import threading


class DBColumn(enum.Enum):
    BeaconBlock = b"blk"
    BeaconState = b"ste"
    BeaconStateSummary = b"ssy"
    BeaconBlobs = b"blb"
    ForkChoice = b"frk"
    PubkeyCache = b"pkc"
    BeaconChain = b"bch"
    OpPool = b"opo"
    Eth1Cache = b"etc"
    HotDiff = b"hdf"
    ColdState = b"cst"
    ColdStateDiff = b"cdf"
    Metadata = b"met"
    # slasher (slasher/src/database.rs database table names)
    SlasherTargets = b"stg"
    SlasherAttesterRecords = b"sar"
    SlasherIndexedAtts = b"sia"
    SlasherAttIdByHash = b"sih"
    SlasherProposals = b"spr"
    SlasherMeta = b"smt"


class KeyValueStore:
    def get(self, column: DBColumn, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: DBColumn, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: DBColumn, key: bytes) -> None:
        raise NotImplementedError

    def exists(self, column: DBColumn, key: bytes) -> bool:
        return self.get(column, key) is not None

    def iter_column(self, column: DBColumn):
        raise NotImplementedError

    def do_atomically(self, ops: list) -> None:
        """ops: list of ("put", col, key, val) | ("delete", col, key)."""
        for op in ops:
            if op[0] == "put":
                self.put(op[1], op[2], op[3])
            else:
                self.delete(op[1], op[2])

    def compact(self) -> None:
        pass

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    """Thread-safe dict store (memory_store.rs)."""

    def __init__(self):
        self._data: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    @staticmethod
    def _k(column: DBColumn, key: bytes) -> bytes:
        return column.value + b"/" + key

    def get(self, column, key):
        with self._lock:
            return self._data.get(self._k(column, key))

    def put(self, column, key, value):
        with self._lock:
            self._data[self._k(column, key)] = bytes(value)

    def delete(self, column, key):
        with self._lock:
            self._data.pop(self._k(column, key), None)

    def iter_column(self, column):
        prefix = column.value + b"/"
        with self._lock:
            items = [
                (k[len(prefix):], v)
                for k, v in self._data.items()
                if k.startswith(prefix)
            ]
        return iter(sorted(items))

    def do_atomically(self, ops):
        with self._lock:
            super().do_atomically(ops)

    def __len__(self):
        return len(self._data)


class LevelStore(KeyValueStore):
    """Durable append-log store with in-memory index and periodic compaction.

    File format: sequence of records ``[u8 op][u32 klen][u32 vlen][key][val]``.
    On open the log is replayed; ``compact`` rewrites only live records. Plays
    the role of ``leveldb_store.rs`` until the native engine arrives."""

    _PUT, _DEL = 1, 2

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (offset, vlen)
        self._lock = threading.RLock()
        self._fh = open(path, "a+b")
        self._replay()

    def _replay(self):
        self._fh.seek(0)
        data = self._fh.read()
        pos = 0
        while pos + 9 <= len(data):
            op, klen, vlen = struct.unpack_from("<BII", data, pos)
            pos += 9
            if pos + klen + vlen > len(data):
                break  # truncated tail: discard
            key = data[pos : pos + klen]
            pos += klen
            if op == self._PUT:
                self._index[key] = (pos, vlen)
            else:
                self._index.pop(key, None)
            pos += vlen

    def _append(self, op: int, key: bytes, value: bytes = b"") -> int:
        self._fh.seek(0, os.SEEK_END)
        start = self._fh.tell()
        self._fh.write(struct.pack("<BII", op, len(key), len(value)))
        self._fh.write(key)
        voff = start + 9 + len(key)
        self._fh.write(value)
        self._fh.flush()
        return voff

    @staticmethod
    def _k(column: DBColumn, key: bytes) -> bytes:
        return column.value + b"/" + key

    def get(self, column, key):
        k = self._k(column, key)
        with self._lock:
            loc = self._index.get(k)
            if loc is None:
                return None
            off, vlen = loc
            self._fh.seek(off)
            return self._fh.read(vlen)

    def put(self, column, key, value):
        k = self._k(column, key)
        with self._lock:
            voff = self._append(self._PUT, k, bytes(value))
            self._index[k] = (voff, len(value))

    def delete(self, column, key):
        k = self._k(column, key)
        with self._lock:
            if k in self._index:
                self._append(self._DEL, k)
                self._index.pop(k, None)

    def iter_column(self, column):
        prefix = column.value + b"/"
        with self._lock:
            keys = sorted(k for k in self._index if k.startswith(prefix))
            return iter([(k[len(prefix):], self.get(column, k[len(prefix):])) for k in keys])

    def compact(self):
        with self._lock:
            tmp = self.path + ".compact"
            with open(tmp, "wb") as out:
                new_index = {}
                for k, (off, vlen) in sorted(self._index.items()):
                    self._fh.seek(off)
                    v = self._fh.read(vlen)
                    start = out.tell()
                    out.write(struct.pack("<BII", self._PUT, len(k), len(v)))
                    out.write(k)
                    out.write(v)
                    new_index[k] = (start + 9 + len(k), len(v))
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a+b")
            self._index = new_index

    def close(self):
        self._fh.close()
