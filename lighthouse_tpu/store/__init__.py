"""Storage: column KV abstraction, MemoryStore, hot/cold DB.

Twin of ``beacon_node/store``: ``KeyValueStore`` trait + ``MemoryStore`` +
``HotColdDB`` split (``hot_cold_store.rs:51-81``).
"""

from .kv import DBColumn, KeyValueStore, MemoryStore, LevelStore
from .hot_cold import HotColdDB, StoreConfig
