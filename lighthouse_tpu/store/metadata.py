"""On-disk schema metadata + migrations (ref store/src/metadata.rs,
beacon_chain/src/schema_change.rs).

The store records its schema version and hierarchy config under Metadata
keys; opening a database written by a different schema runs the registered
migrations in order (or fails loudly if a step is missing) — never silent
reinterpretation of old bytes.
"""

from __future__ import annotations

import json

from .kv import DBColumn

CURRENT_SCHEMA_VERSION = 2
_VERSION_KEY = b"schema_version"
_CONFIG_KEY = b"store_config"

# version -> migration fn(store) upgrading version -> version+1
MIGRATIONS: dict[int, callable] = {}


def migration(from_version: int):
    def deco(fn):
        MIGRATIONS[from_version] = fn
        return fn

    return deco


@migration(1)
def _v1_to_v2(store) -> None:
    """v1 keyed cold states by state_root with ad-hoc zlib compression; v2
    keys the freezer by slot with hierarchical diffs. v1 entries cannot be
    re-layered without replaying the chain, so they are DELETED — the v2
    freezer refills from finalization. Loud in-place removal beats silent
    misreads of root-keyed bytes through slot-keyed accessors."""
    ops = []
    for col in (DBColumn.ColdState, DBColumn.ColdStateDiff):
        for key, _ in list(store.cold.iter_column(col)):
            if len(key) == 32:  # v1 root key (v2 keys are 8-byte slots)
                ops.append(("delete", col, key))
    for key, _ in list(store.cold.iter_column(DBColumn.BeaconStateSummary)):
        ops.append(("delete", DBColumn.BeaconStateSummary, key))
    if ops:
        # one atomic batch: a crash mid-migration must never leave a
        # half-deleted v1 freezer behind a v2 version stamp
        store.cold.do_atomically(ops)


def apply_schema_migrations(store) -> None:
    """Version lives in the COLD db next to the data it versions, so
    replacing the hot DB (routine for a hot/cold split) can't skip
    migrations. A vintage freezer with data but no version stamp is v1."""

    def put_version(v: int) -> None:
        store.cold.put(
            DBColumn.Metadata, _VERSION_KEY, v.to_bytes(8, "little")
        )

    raw = store.cold.get(DBColumn.Metadata, _VERSION_KEY)
    if raw is None:
        has_v1_data = any(
            True for _ in store.cold.iter_column(DBColumn.ColdState)
        )
        version = 1 if has_v1_data else CURRENT_SCHEMA_VERSION
        if not has_v1_data:
            put_version(CURRENT_SCHEMA_VERSION)
            return
    else:
        version = int.from_bytes(raw, "little")
    while version < CURRENT_SCHEMA_VERSION:
        fn = MIGRATIONS.get(version)
        if fn is None:
            raise RuntimeError(
                f"no migration from store schema v{version}; "
                f"current is v{CURRENT_SCHEMA_VERSION}"
            )
        fn(store)
        version += 1
        put_version(version)


def check_config_consistency(store, hierarchy_exponents: tuple) -> None:
    """The diff hierarchy is immutable once data is written. It lives in
    the FREEZER's metadata (the reference keeps it in the cold DB's
    on-disk config) so reopening just the cold history still validates."""
    raw = store.cold.get(DBColumn.Metadata, _CONFIG_KEY)
    if raw is None:
        store.cold.put(
            DBColumn.Metadata,
            _CONFIG_KEY,
            json.dumps({"exponents": list(hierarchy_exponents)}).encode(),
        )
        return
    stored = tuple(json.loads(raw.decode())["exponents"])
    if stored != tuple(hierarchy_exponents):
        raise RuntimeError(
            f"store hierarchy exponents {stored} != configured "
            f"{tuple(hierarchy_exponents)}"
        )
