"""Hot/cold split database (hot_cold_store.rs:51-81).

Hot DB: recent states + all blocks since the split. Cold DB: finalized
history — full state snapshots every ``slots_per_restore_point`` with
zlib-compressed SSZ diff-bases in between (the hdiff layer will upgrade this
to hierarchical binary diffs). States are keyed by state_root; block/state
summaries let iterators walk ancestor chains without loading full states.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from .kv import DBColumn, KeyValueStore, MemoryStore


@dataclass
class StoreConfig:
    slots_per_restore_point: int = 32
    compression_level: int = 1


@dataclass
class Split:
    """Hot/cold boundary (finalization watermark)."""

    slot: int = 0
    state_root: bytes = b"\x00" * 32


class HotColdDB:
    """Stores SSZ-encoded blocks/states; callers own (de)serialization of
    typed containers — the chain layer passes classes per fork."""

    def __init__(
        self,
        hot: KeyValueStore | None = None,
        cold: KeyValueStore | None = None,
        config: StoreConfig | None = None,
    ):
        self.hot = hot or MemoryStore()
        self.cold = cold or MemoryStore()
        self.config = config or StoreConfig()
        self.split = Split()

    # -- blocks -----------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block_ssz: bytes) -> None:
        self.hot.put(DBColumn.BeaconBlock, block_root, signed_block_ssz)

    def get_block(self, block_root: bytes) -> bytes | None:
        return self.hot.get(DBColumn.BeaconBlock, block_root)

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(DBColumn.BeaconBlock, block_root)

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(DBColumn.BeaconBlock, block_root)

    # -- hot states -------------------------------------------------------------

    def put_state(self, state_root: bytes, state_ssz: bytes, slot: int) -> None:
        self.hot.put(DBColumn.BeaconState, state_root, state_ssz)
        self.hot.put(
            DBColumn.BeaconStateSummary,
            state_root,
            slot.to_bytes(8, "little"),
        )

    def get_state(self, state_root: bytes) -> bytes | None:
        s = self.hot.get(DBColumn.BeaconState, state_root)
        if s is not None:
            return s
        return self.load_cold_state(state_root)

    def state_slot(self, state_root: bytes) -> int | None:
        b = self.hot.get(DBColumn.BeaconStateSummary, state_root)
        return int.from_bytes(b, "little") if b else None

    def delete_state(self, state_root: bytes) -> None:
        self.hot.delete(DBColumn.BeaconState, state_root)
        self.hot.delete(DBColumn.BeaconStateSummary, state_root)

    # -- cold states (freezer) ----------------------------------------------------

    def migrate_to_cold(self, state_root: bytes, slot: int) -> None:
        """Move a finalized state hot -> cold. Snapshot at restore points,
        compressed full-state otherwise (diff chain upgrade pending)."""
        ssz = self.hot.get(DBColumn.BeaconState, state_root)
        if ssz is None:
            return
        compressed = zlib.compress(ssz, self.config.compression_level)
        col = (
            DBColumn.ColdState
            if slot % self.config.slots_per_restore_point == 0
            else DBColumn.ColdStateDiff
        )
        self.cold.put(col, state_root, compressed)
        self.cold.put(
            DBColumn.BeaconStateSummary, slot.to_bytes(8, "little"), state_root
        )
        self.delete_state(state_root)
        if slot > self.split.slot:
            self.split = Split(slot=slot, state_root=state_root)

    def load_cold_state(self, state_root: bytes) -> bytes | None:
        for col in (DBColumn.ColdState, DBColumn.ColdStateDiff):
            c = self.cold.get(col, state_root)
            if c is not None:
                return zlib.decompress(c)
        return None

    def cold_state_root_at_slot(self, slot: int) -> bytes | None:
        return self.cold.get(
            DBColumn.BeaconStateSummary, slot.to_bytes(8, "little")
        )

    # -- metadata ----------------------------------------------------------------

    def put_meta(self, key: bytes, value: bytes) -> None:
        self.hot.put(DBColumn.Metadata, key, value)

    def get_meta(self, key: bytes) -> bytes | None:
        return self.hot.get(DBColumn.Metadata, key)
