"""Hot/cold split database (hot_cold_store.rs:51-81).

Hot DB: recent states + all blocks since the split. Cold DB: finalized
history as a hierarchical-diff freezer (hdiff.py): full snapshots at the
coarsest layer cadence, sectioned diffs between, block-replay for slots
below the finest layer. Cold entries are keyed by SLOT; a root<->slot
summary map serves by-root lookups.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from dataclasses import dataclass, field

from .hdiff import (
    DiffFrom,
    HDiff,
    HDiffBuffer,
    HierarchyConfig,
    ReplayFrom,
    Snapshot,
    storage_strategy,
)
from .kv import DBColumn, KeyValueStore, MemoryStore


@dataclass
class StoreConfig:
    compression_level: int = 1
    hierarchy: HierarchyConfig = field(default_factory=HierarchyConfig)
    buffer_cache_size: int = 4


@dataclass
class Split:
    """Hot/cold boundary (finalization watermark)."""

    slot: int = 0
    state_root: bytes = b"\x00" * 32


class HotColdDB:
    """Stores SSZ-encoded blocks/states; callers own (de)serialization of
    typed containers — the chain layer passes classes per fork."""

    def __init__(
        self,
        hot: KeyValueStore | None = None,
        cold: KeyValueStore | None = None,
        config: StoreConfig | None = None,
    ):
        self.hot = hot or MemoryStore()
        self.cold = cold or MemoryStore()
        self.config = config or StoreConfig()
        self.split = Split()
        self._buffer_cache: OrderedDict[int, HDiffBuffer] = OrderedDict()
        from .metadata import apply_schema_migrations, check_config_consistency

        apply_schema_migrations(self)
        check_config_consistency(self, self.config.hierarchy.exponents)

    # -- atomic batches ----------------------------------------------------------

    def do_atomically(self, ops: list, db: str = "hot") -> None:
        """One all-or-nothing batch against the hot (default) or cold KV
        store (kv.KeyValueStore.do_atomically contract). The multi-key
        sequences of block import and the finalization migration go through
        here so a crash mid-sequence can never be observed after replay."""
        (self.hot if db == "hot" else self.cold).do_atomically(ops)

    def atomic_block_import(
        self,
        block_root: bytes,
        signed_block_ssz: bytes,
        state_root: bytes,
        state_ssz: bytes,
        slot: int,
        extra_meta: dict | None = None,
    ) -> None:
        """The block-import barrier: block + post-state + slot summary
        (+ any metadata riders) committed as ONE hot frame."""
        ops = [
            ("put", DBColumn.BeaconBlock, block_root, signed_block_ssz),
            ("put", DBColumn.BeaconState, state_root, state_ssz),
            (
                "put",
                DBColumn.BeaconStateSummary,
                state_root,
                int(slot).to_bytes(8, "little"),
            ),
        ]
        for key, value in (extra_meta or {}).items():
            ops.append(("put", DBColumn.Metadata, key, value))
        self.hot.do_atomically(ops)

    # -- blocks -----------------------------------------------------------------

    def put_block(self, block_root: bytes, signed_block_ssz: bytes) -> None:
        self.hot.put(DBColumn.BeaconBlock, block_root, signed_block_ssz)

    def get_block(self, block_root: bytes) -> bytes | None:
        return self.hot.get(DBColumn.BeaconBlock, block_root)

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(DBColumn.BeaconBlock, block_root)

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(DBColumn.BeaconBlock, block_root)

    # -- blob sidecars (the separate blobs DB of the reference store) -----------

    def put_blob_sidecars(self, block_root: bytes, sidecar_ssz: list) -> None:
        """Length-prefixed concatenation of the block's sidecar encodings
        (hot_cold_store.rs put_blobs; blobs live beside blocks, pruned by the
        same finalization migrator)."""
        out = b"".join(
            len(s).to_bytes(4, "little") + s for s in sidecar_ssz
        )
        self.hot.put(DBColumn.BeaconBlobs, block_root, out)

    def get_blob_sidecars(self, block_root: bytes) -> list | None:
        raw = self.hot.get(DBColumn.BeaconBlobs, block_root)
        if raw is None:
            return None
        out, off = [], 0
        while off < len(raw):
            n = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            out.append(raw[off : off + n])
            off += n
        return out

    def delete_blob_sidecars(self, block_root: bytes) -> None:
        self.hot.delete(DBColumn.BeaconBlobs, block_root)

    # -- hot states -------------------------------------------------------------

    def put_state(self, state_root: bytes, state_ssz: bytes, slot: int) -> None:
        # state bytes + slot summary are one logical record: commit them as
        # one frame so a crash can't leave a state without its summary
        self.hot.do_atomically(
            [
                ("put", DBColumn.BeaconState, state_root, state_ssz),
                (
                    "put",
                    DBColumn.BeaconStateSummary,
                    state_root,
                    slot.to_bytes(8, "little"),
                ),
            ]
        )

    def get_state(self, state_root: bytes) -> bytes | None:
        s = self.hot.get(DBColumn.BeaconState, state_root)
        if s is not None:
            return s
        return self.load_cold_state(state_root)

    def state_slot(self, state_root: bytes) -> int | None:
        b = self.hot.get(DBColumn.BeaconStateSummary, state_root)
        return int.from_bytes(b, "little") if b else None

    def delete_state(self, state_root: bytes) -> None:
        self.hot.do_atomically(
            [
                ("delete", DBColumn.BeaconState, state_root),
                ("delete", DBColumn.BeaconStateSummary, state_root),
            ]
        )

    # -- cold states (freezer, hierarchical diffs) --------------------------------

    @staticmethod
    def _slot_key(slot: int) -> bytes:
        return slot.to_bytes(8, "big")

    def store_cold_state(self, state, state_root: bytes, block_root: bytes) -> None:
        """Freeze a finalized state per its layer strategy: snapshot /
        diff-vs-parent-layer / summary-only (replayed on read). Also records
        slot<->root maps and the canonical slot->block_root chain the
        replayer walks (hot_cold_store.rs store_cold_state*)."""
        slot = int(state.slot)
        strategy = storage_strategy(self.config.hierarchy, slot)
        if isinstance(strategy, ReplayFrom) and not self._has_cold_state(
            strategy.slot
        ) and not self._has_cold_state(self.replay_anchor(slot)):
            # the replay layer has no reachable anchor below (skipped-slot
            # hole): store a diff at the finest layer instead of losing it
            strategy = DiffFrom(slot - slot % self.config.hierarchy.moduli[0])
        # collect the freeze as ONE cold frame: state bytes (or diff) + both
        # summary directions commit together — a crash mid-freeze can never
        # leave a summary pointing at state bytes that were never written
        ops = []
        if isinstance(strategy, Snapshot):
            ssz = type(state).encode(state)
            ops.append(
                (
                    "put",
                    DBColumn.ColdState,
                    self._slot_key(slot),
                    zlib.compress(ssz, self.config.compression_level),
                )
            )
        elif isinstance(strategy, DiffFrom):
            base = self._cold_buffer(strategy.slot)
            if base is None:
                # parent layer missing (pre-genesis-anchor history): snapshot
                ssz = type(state).encode(state)
                ops.append(
                    (
                        "put",
                        DBColumn.ColdState,
                        self._slot_key(slot),
                        zlib.compress(ssz, self.config.compression_level),
                    )
                )
            else:
                target = HDiffBuffer.from_state(state)
                diff = HDiff.compute(base, target)
                ops.append(
                    (
                        "put",
                        DBColumn.ColdStateDiff,
                        self._slot_key(slot),
                        diff.blob,
                    )
                )
        # ReplayFrom: state bytes not stored; the summary alone suffices
        ops.append(
            (
                "put",
                DBColumn.BeaconStateSummary,
                self._slot_key(slot),
                state_root + block_root,
            )
        )
        ops.append(
            ("put", DBColumn.BeaconStateSummary, state_root, self._slot_key(slot))
        )
        self.cold.do_atomically(ops)
        if slot > self.split.slot:
            self.split = Split(slot=slot, state_root=state_root)

    def _cold_buffer(self, slot: int) -> HDiffBuffer | None:
        """Reconstruct the HDiffBuffer at a stored layer slot (snapshot +
        diff chain), with a small LRU for repeated freezes."""
        cached = self._buffer_cache.get(slot)
        if cached is not None:
            self._buffer_cache.move_to_end(slot)
            return cached
        strategy = storage_strategy(self.config.hierarchy, slot)
        blob = self.cold.get(DBColumn.ColdState, self._slot_key(slot))
        if blob is not None:
            state_cls = self._state_cls_at(slot)
            if state_cls is None:
                return None
            buf = HDiffBuffer.from_state(
                state_cls.decode(zlib.decompress(blob))
            )
        elif isinstance(strategy, DiffFrom):
            diff_blob = self.cold.get(
                DBColumn.ColdStateDiff, self._slot_key(slot)
            )
            base = self._cold_buffer(strategy.slot)
            if diff_blob is None or base is None:
                return None
            buf = HDiff(diff_blob).apply(base)
        else:
            return None
        self._buffer_cache[slot] = buf
        while len(self._buffer_cache) > self.config.buffer_cache_size:
            self._buffer_cache.popitem(last=False)
        return buf

    # fork-aware decoding hook: the chain sets this to map slot -> state class
    state_cls_for_slot = None

    def _state_cls_at(self, slot: int):
        if self.state_cls_for_slot is None:
            return None
        return self.state_cls_for_slot(slot)

    def get_cold_state(self, slot: int):
        """Typed state at a stored cold slot, or None (slots on a replay
        layer return None — use replay_anchor + block replay)."""
        buf = self._cold_buffer(slot)
        if buf is None:
            return None
        cls = self._state_cls_at(slot)
        return buf.into_state(cls) if cls else None

    def replay_anchor(self, slot: int) -> int:
        """Closest slot at or below ``slot`` with actually-stored state
        bytes. The nominal layer slot can be a hole when it was skipped
        (no block, so no post-state was ever frozen there) — walk down
        until a stored snapshot/diff exists."""
        s = storage_strategy(self.config.hierarchy, slot)
        anchor = s.slot if isinstance(s, ReplayFrom) else slot
        while anchor > 0 and not self._has_cold_state(anchor):
            anchor -= 1
        return anchor

    def _has_cold_state(self, slot: int) -> bool:
        key = self._slot_key(slot)
        return (
            self.cold.exists(DBColumn.ColdState, key)
            or self.cold.exists(DBColumn.ColdStateDiff, key)
        )

    def cold_slot_for_root(self, state_root: bytes) -> int | None:
        raw = self.cold.get(DBColumn.BeaconStateSummary, state_root)
        return int.from_bytes(raw, "big") if raw else None

    def cold_summary_at_slot(self, slot: int):
        """(state_root, block_root) recorded when the slot froze."""
        raw = self.cold.get(DBColumn.BeaconStateSummary, self._slot_key(slot))
        if raw is None or len(raw) != 64:
            return None
        return raw[:32], raw[32:]

    def load_cold_state(self, state_root: bytes) -> bytes | None:
        """By-root cold lookup returning SSZ bytes (compat shim)."""
        slot = self.cold_slot_for_root(state_root)
        if slot is None:
            return None
        state = self.get_cold_state(slot)
        return type(state).encode(state) if state is not None else None

    # -- metadata ----------------------------------------------------------------

    def put_meta(self, key: bytes, value: bytes) -> None:
        self.hot.put(DBColumn.Metadata, key, value)

    def get_meta(self, key: bytes) -> bytes | None:
        return self.hot.get(DBColumn.Metadata, key)
