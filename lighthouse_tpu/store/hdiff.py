"""Hierarchical state diffs for the freezer (ref store/src/hdiff.rs:33-40).

The reference splits each diff into per-field sections chosen by entropy
profile (hdiff.rs HDiff docs): balances as compressed u64 deltas,
inactivity scores likewise, validators as per-entry replacements,
historical roots/summaries as append-only tails, and the remaining state
bytes through xdelta3. Here the same sectioning is kept, with the generic
section as a vectorized XOR delta + zlib — SSZ states are structurally
stable so unchanged regions become zero runs that compress to almost
nothing, and the whole delta computes as one numpy op instead of a
byte-level match loop.

Layering (hdiff.rs HierarchyConfig): ascending ``exponents`` define diff
layers; the coarsest is the full-snapshot cadence. ``storage_strategy``
maps a slot to Snapshot / DiffFrom(parent slot) / ReplayFrom(closest
stored slot).
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field

import numpy as np

_MAGIC = b"HDF1"


@dataclass(frozen=True)
class HierarchyConfig:
    exponents: tuple = (5, 9, 11, 13, 16, 18, 21)  # ref StoreConfig default

    def __post_init__(self):
        if any(
            a >= b for a, b in zip(self.exponents, self.exponents[1:])
        ):
            raise ValueError("hierarchy exponents must be strictly ascending")

    @property
    def moduli(self) -> list[int]:
        """Descending: [snapshot cadence, ..., finest diff cadence]."""
        return [1 << e for e in reversed(self.exponents)]


@dataclass(frozen=True)
class Snapshot:
    pass


@dataclass(frozen=True)
class DiffFrom:
    slot: int


@dataclass(frozen=True)
class ReplayFrom:
    slot: int


def storage_strategy(config: HierarchyConfig, slot: int):
    """How the freezer stores ``slot`` (hdiff.rs HierarchyModuli)."""
    moduli = config.moduli
    if slot % moduli[0] == 0:
        return Snapshot()
    for coarser, m in zip(moduli, moduli[1:]):
        if slot % m == 0:
            return DiffFrom(slot - slot % coarser)
    return ReplayFrom(slot - slot % moduli[-1])


# -- section codecs ---------------------------------------------------------------


def _u64_delta(base: np.ndarray, target: np.ndarray) -> bytes:
    """Wrapping difference of the common prefix + appended tail, zlib'd —
    balances change every epoch but by small amounts, so deltas are
    leading-zero-heavy (hdiff.rs CompressedU64Diff rationale)."""
    n = min(base.size, target.size)
    if target.size < base.size:
        raise ValueError("u64 section shrank; deletions unsupported")
    delta = (target[:n] - base[:n]).astype(np.uint64)
    tail = target[n:]
    raw = struct.pack("<II", n, tail.size) + delta.tobytes() + tail.tobytes()
    return zlib.compress(raw, 3)


def _u64_apply(base: np.ndarray, blob: bytes) -> np.ndarray:
    raw = zlib.decompress(blob)
    n, n_tail = struct.unpack_from("<II", raw)
    delta = np.frombuffer(raw[8 : 8 + 8 * n], dtype=np.uint64)
    tail = np.frombuffer(raw[8 + 8 * n : 8 + 8 * (n + n_tail)], dtype=np.uint64)
    return np.concatenate([(base[:n] + delta).astype(np.uint64), tail])


def _bytes_xor(base: bytes, target: bytes) -> bytes:
    """Vectorized XOR delta over the common prefix + raw tail."""
    n = min(len(base), len(target))
    a = np.frombuffer(base[:n], dtype=np.uint8)
    b = np.frombuffer(target[:n], dtype=np.uint8)
    raw = (
        struct.pack("<II", n, len(target) - n)
        + (a ^ b).tobytes()
        + target[n:]
    )
    return zlib.compress(raw, 3)


def _bytes_xor_apply(base: bytes, blob: bytes) -> bytes:
    raw = zlib.decompress(blob)
    n, n_tail = struct.unpack_from("<II", raw)
    a = np.frombuffer(base[:n], dtype=np.uint8)
    d = np.frombuffer(raw[8 : 8 + n], dtype=np.uint8)
    return (a ^ d).tobytes() + raw[8 + n : 8 + n + n_tail]


def _validators_delta(base_enc: list[bytes], target_enc: list[bytes]) -> bytes:
    """Per-entry replacement list (hdiff.rs ValidatorsDiff): the Validator
    record rarely changes, so comparing entries directly beats generic
    binary diffing by ~10x on mainnet-size registries."""
    if len(target_enc) < len(base_enc):
        raise ValueError("validator registry shrank")
    out = bytearray()
    count = 0
    for i, t in enumerate(target_enc):
        if i >= len(base_enc) or base_enc[i] != t:
            out += struct.pack("<I", i) + t
            count += 1
    return zlib.compress(struct.pack("<II", count, len(target_enc)) + bytes(out), 3)


def _validators_apply(base_enc: list[bytes], blob: bytes, entry_len: int) -> list[bytes]:
    raw = zlib.decompress(blob)
    count, total = struct.unpack_from("<II", raw)
    out = list(base_enc) + [b""] * (total - len(base_enc))
    off = 8
    for _ in range(count):
        (i,) = struct.unpack_from("<I", raw, off)
        off += 4
        out[i] = raw[off : off + entry_len]
        off += entry_len
    return out[:total]


def _append_only(base: list[bytes], target: list[bytes]) -> bytes:
    if target[: len(base)] != base:
        raise ValueError("append-only section rewrote history")
    return b"".join(target[len(base) :])


# -- buffer + diff ---------------------------------------------------------------


class HDiffBuffer:
    """Sectioned working form of a state (hdiff.rs HDiffBuffer)."""

    def __init__(self, state_rest: bytes, balances, inactivity, validators,
                 hist_roots, hist_summaries):
        self.state_rest = state_rest
        self.balances = np.asarray(balances, dtype=np.uint64)
        self.inactivity = np.asarray(inactivity, dtype=np.uint64)
        self.validators = validators  # list of encoded entries
        self.hist_roots = hist_roots  # list of 32B roots
        self.hist_summaries = hist_summaries  # list of encoded entries

    @classmethod
    def from_state(cls, state) -> "HDiffBuffer":
        from ..types.containers import HistoricalSummary, Validator

        hollow = state.copy()
        balances = np.asarray(state.balances, dtype=np.uint64)
        inactivity = np.asarray(
            getattr(state, "inactivity_scores", []), dtype=np.uint64
        )
        validators = [Validator.encode(v) for v in state.validators]
        hist_roots = [bytes(r) for r in state.historical_roots]
        hist_summaries = [
            HistoricalSummary.encode(h)
            for h in getattr(state, "historical_summaries", [])
        ]
        hollow.balances = np.zeros(0, dtype=np.uint64)
        hollow.validators = []
        hollow.historical_roots = []
        if hasattr(hollow, "inactivity_scores"):
            hollow.inactivity_scores = np.zeros(0, dtype=np.uint64)
        if hasattr(hollow, "historical_summaries"):
            hollow.historical_summaries = []
        rest = type(state).encode(hollow)
        return cls(rest, balances, inactivity, validators, hist_roots,
                   hist_summaries)

    def into_state(self, state_cls):
        from ..types.containers import HistoricalSummary, Validator

        state = state_cls.decode(self.state_rest)
        state.balances = self.balances.copy()
        state.validators = [Validator.decode(v) for v in self.validators]
        state.historical_roots = list(self.hist_roots)
        if hasattr(state, "inactivity_scores"):
            state.inactivity_scores = self.inactivity.copy()
        if hasattr(state, "historical_summaries"):
            state.historical_summaries = [
                HistoricalSummary.decode(h) for h in self.hist_summaries
            ]
        return state


_VALIDATOR_LEN = 121  # fixed SSZ size of a Validator entry


class HDiff:
    """Serialized hierarchical diff between two HDiffBuffers."""

    def __init__(self, blob: bytes):
        self.blob = blob

    @classmethod
    def compute(cls, base: HDiffBuffer, target: HDiffBuffer) -> "HDiff":
        sections = [
            _bytes_xor(base.state_rest, target.state_rest),
            _u64_delta(base.balances, target.balances),
            _u64_delta(base.inactivity, target.inactivity),
            _validators_delta(base.validators, target.validators),
            zlib.compress(_append_only(base.hist_roots, target.hist_roots), 3),
            zlib.compress(
                _append_only(base.hist_summaries, target.hist_summaries), 3
            ),
        ]
        out = bytearray(_MAGIC)
        for s in sections:
            out += struct.pack("<I", len(s)) + s
        return cls(bytes(out))

    def apply(self, base: HDiffBuffer) -> HDiffBuffer:
        if self.blob[:4] != _MAGIC:
            raise ValueError("bad hdiff blob")
        off = 4
        sections = []
        for _ in range(6):
            (n,) = struct.unpack_from("<I", self.blob, off)
            off += 4
            sections.append(self.blob[off : off + n])
            off += n
        rest = _bytes_xor_apply(base.state_rest, sections[0])
        balances = _u64_apply(base.balances, sections[1])
        inactivity = _u64_apply(base.inactivity, sections[2])
        validators = _validators_apply(
            base.validators, sections[3], _VALIDATOR_LEN
        )
        roots_tail = zlib.decompress(sections[4])
        hist_roots = base.hist_roots + [
            roots_tail[i : i + 32] for i in range(0, len(roots_tail), 32)
        ]
        summ_tail = zlib.decompress(sections[5])
        _SUMMARY_LEN = 64
        hist_summaries = base.hist_summaries + [
            summ_tail[i : i + _SUMMARY_LEN]
            for i in range(0, len(summ_tail), _SUMMARY_LEN)
        ]
        return HDiffBuffer(
            rest, balances, inactivity, validators, hist_roots, hist_summaries
        )
