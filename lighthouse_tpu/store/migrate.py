"""Finalization migrator (ref store/src/migrate.rs + BackgroundMigrator).

On finalization advance: freeze the canonical finalized states into the
cold hierarchy, delete non-canonical (abandoned-fork) hot data, and release
the chain's in-memory state handles — the fix for unbounded `_states`
growth. The reference runs this on a background thread; here it runs
inline under the chain lock (the freeze itself is a handful of diffs).

Crash-safety (ISSUE 12): the migration is the canonical multi-key sequence
a kill used to tear. It now runs in two phases — (1) freeze every canonical
state into the cold store (each freeze is one atomic cold frame, and a
duplicate freeze on a re-run is byte-idempotent), then (2) prune ALL hot
data in one atomic hot batch. A crash between the phases leaves harmless
hot/cold duplicates that the next finalization pass re-prunes; a crash
inside either phase is absorbed by the store's frame atomicity. In-memory
maps are only updated after phase 2 commits.
"""

from __future__ import annotations

from .kv import DBColumn


class BackgroundMigrator:
    def __init__(self, store):
        self.store = store
        self.last_finalized_slot = 0

    def process_finalization(self, chain, finalized_root: bytes, finalized_slot: int) -> dict:
        """Migrate everything strictly below the finalized slot.

        ``chain`` supplies the in-memory block/state maps; canonicality is
        decided by walking parent links from the finalized block.
        """
        if finalized_slot <= self.last_finalized_slot:
            return {"frozen": 0, "pruned": 0}

        # canonical ancestor roots of the finalized block (incl. itself)
        canonical = set()
        root = finalized_root
        while root in chain._blocks:
            canonical.add(root)
            root = bytes(chain._blocks[root].message.parent_root)
        canonical.add(chain.genesis_block_root)

        from ..resilience.crashpoints import maybe_crash
        from ..utils.metrics import STORE_FREEZE_TIMES

        owner = getattr(self.store.hot, "owner", None)
        frozen_roots, pruned_roots, prune_ops = [], [], []
        for block_root in list(chain._states):
            if block_root == chain.genesis_block_root:
                continue  # the genesis anchor stays resident
            state = chain._states[block_root]
            slot = int(state.slot)
            if slot >= finalized_slot or block_root == finalized_root:
                continue
            state_root = state.tree_root()
            if block_root in canonical:
                # phase 1: freeze into the cold hierarchy (atomic per state)
                with STORE_FREEZE_TIMES.time():
                    self.store.store_cold_state(state, state_root, block_root)
                maybe_crash("migrate.finalization", owner=owner)
                prune_ops.append(("delete", DBColumn.BeaconState, state_root))
                prune_ops.append(
                    ("delete", DBColumn.BeaconStateSummary, state_root)
                )
                # the signed block stays in the store; the decoded in-memory
                # copy is dropped after the prune commits (bounds _blocks
                # alongside _states)
                frozen_roots.append(block_root)
            else:
                # abandoned fork: drop block + state entirely (migrate.rs
                # abandoned-forks pruning)
                if chain._blocks.get(block_root) is not None:
                    prune_ops.append(
                        ("delete", DBColumn.BeaconBlock, block_root)
                    )
                prune_ops.append(("delete", DBColumn.BeaconState, state_root))
                prune_ops.append(
                    ("delete", DBColumn.BeaconStateSummary, state_root)
                )
                pruned_roots.append(block_root)

        # phase 2: ONE atomic hot prune — a kill either leaves everything
        # (plus idempotent cold duplicates) or nothing
        if prune_ops:
            self.store.do_atomically(prune_ops)
        for block_root in frozen_roots:
            chain._blocks.pop(block_root, None)
            del chain._states[block_root]
        for block_root in pruned_roots:
            chain._blocks.pop(block_root, None)
            del chain._states[block_root]
        self.last_finalized_slot = finalized_slot
        from ..utils.logging import get_logger

        get_logger("store.migrate").info(
            "Finalization migration",
            finalized_slot=finalized_slot,
            frozen=len(frozen_roots),
            pruned=len(pruned_roots),
        )
        return {"frozen": len(frozen_roots), "pruned": len(pruned_roots)}
