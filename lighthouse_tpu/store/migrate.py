"""Finalization migrator (ref store/src/migrate.rs + BackgroundMigrator).

On finalization advance: freeze the canonical finalized states into the
cold hierarchy, delete non-canonical (abandoned-fork) hot data, and release
the chain's in-memory state handles — the fix for unbounded `_states`
growth. The reference runs this on a background thread; here it runs
inline under the chain lock (the freeze itself is a handful of diffs).
"""

from __future__ import annotations


class BackgroundMigrator:
    def __init__(self, store):
        self.store = store
        self.last_finalized_slot = 0

    def process_finalization(self, chain, finalized_root: bytes, finalized_slot: int) -> dict:
        """Migrate everything strictly below the finalized slot.

        ``chain`` supplies the in-memory block/state maps; canonicality is
        decided by walking parent links from the finalized block.
        """
        if finalized_slot <= self.last_finalized_slot:
            return {"frozen": 0, "pruned": 0}

        # canonical ancestor roots of the finalized block (incl. itself)
        canonical = set()
        root = finalized_root
        while root in chain._blocks:
            canonical.add(root)
            root = bytes(chain._blocks[root].message.parent_root)
        canonical.add(chain.genesis_block_root)

        from ..utils.metrics import STORE_FREEZE_TIMES

        frozen = pruned = 0
        for block_root in list(chain._states):
            if block_root == chain.genesis_block_root:
                continue  # the genesis anchor stays resident
            state = chain._states[block_root]
            slot = int(state.slot)
            if slot >= finalized_slot or block_root == finalized_root:
                continue
            if block_root in canonical:
                state_root = state.tree_root()
                with STORE_FREEZE_TIMES.time():
                    self.store.store_cold_state(state, state_root, block_root)
                self.store.delete_state(state_root)
                # the signed block stays in the store; drop the decoded
                # in-memory copy (bounds _blocks alongside _states)
                chain._blocks.pop(block_root, None)
                frozen += 1
            else:
                # abandoned fork: drop block + state entirely (migrate.rs
                # abandoned-forks pruning)
                blk = chain._blocks.get(block_root)
                if blk is not None:
                    self.store.delete_block(block_root)
                state_root = state.tree_root()
                self.store.delete_state(state_root)
                chain._blocks.pop(block_root, None)
                pruned += 1
            del chain._states[block_root]
        self.last_finalized_slot = finalized_slot
        from ..utils.logging import get_logger

        get_logger("store.migrate").info(
            "Finalization migration",
            finalized_slot=finalized_slot, frozen=frozen, pruned=pruned,
        )
        return {"frozen": frozen, "pruned": pruned}
