"""Serve an in-process ExecutionEngine (+ Eth1Provider) over HTTP JSON-RPC.

The socket-facing face of the mock EL: what ``MockExecutionLayer`` provides
in-process, this exposes as a real engine-API endpoint with JWT checking, so
the HTTP client stack (``http.py``, ``eth1/http_provider.py``) is exercised
against genuine sockets in tests — the reference's mock EL serves HTTP the
same way (``execution_layer/src/test_utils/mod.rs`` + ``handle_rpc.rs``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .auth import JwtKey
from .http import (
    ENGINE_CAPABILITIES,
    attributes_from_json,
    data,
    payload_from_json,
    payload_to_json,
    qty,
    status_to_json,
    undata,
    unqty,
)


class ExecutionJsonRpcServer:
    """HTTP JSON-RPC server over an ExecutionEngine and/or Eth1Provider."""

    def __init__(self, engine=None, eth1=None, ns=None, jwt_key: JwtKey | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 deposit_contract_address: bytes = b"\x11" * 20):
        self.engine = engine
        self.eth1 = eth1
        self.jwt_key = jwt_key
        self.deposit_contract_address = deposit_contract_address
        # fork payload classes for decoding engine_newPayload bodies
        self._payload_classes = []
        if ns is not None:
            for name in (
                "ExecutionPayloadDeneb",
                "ExecutionPayloadCapella",
                "ExecutionPayloadBellatrix",
            ):
                cls = getattr(ns, name, None)
                if cls is not None:
                    self._payload_classes.append(cls)
        self.requests_served = 0
        self.auth_failures = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                outer._handle(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"el-rpc-{self.url}",
        )

    def start(self) -> "ExecutionJsonRpcServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # -- request handling ---------------------------------------------------

    def _handle(self, req) -> None:
        if self.jwt_key is not None:
            auth = req.headers.get("Authorization", "")
            token = auth.removeprefix("Bearer ").strip()
            if not auth.startswith("Bearer ") or not self.jwt_key.validate_token(token):
                self.auth_failures += 1
                req.send_response(401)
                req.end_headers()
                return
        try:
            length = int(req.headers.get("Content-Length", 0))
            body = json.loads(req.rfile.read(length))
            result = self._dispatch(body["method"], body.get("params", []))
            reply = {"jsonrpc": "2.0", "id": body.get("id"), "result": result}
        except Exception as e:  # noqa: BLE001 — protocol boundary
            reply = {
                "jsonrpc": "2.0",
                "id": None,
                "error": {"code": -32000, "message": str(e)},
            }
        out = json.dumps(reply).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(out)))
        req.end_headers()
        req.wfile.write(out)
        self.requests_served += 1

    def _payload_cls_for(self, obj: dict):
        has_blob = "blobGasUsed" in obj
        has_wd = "withdrawals" in obj
        for cls in self._payload_classes:
            names = {n for n, _ in cls.FIELDS}
            if ("blob_gas_used" in names) == has_blob and (
                "withdrawals" in names
            ) == has_wd:
                return cls
        raise ValueError("no payload class registered for this payload shape")

    def _dispatch(self, method: str, params: list):
        if method == "engine_exchangeCapabilities":
            return ENGINE_CAPABILITIES
        if method.startswith("engine_newPayload"):
            payload = payload_from_json(params[0], self._payload_cls_for(params[0]))
            return status_to_json(self.engine.notify_new_payload(payload))
        if method.startswith("engine_forkchoiceUpdated"):
            state, attrs = params[0], params[1] if len(params) > 1 else None
            status, payload_id = self.engine.forkchoice_updated(
                undata(state["headBlockHash"]),
                undata(state["finalizedBlockHash"]),
                attributes_from_json(attrs),
            )
            return {
                "payloadStatus": status_to_json(status),
                "payloadId": data(payload_id) if payload_id else None,
            }
        if method.startswith("engine_getPayload"):
            version = int(method[-1])
            payload_id = undata(params[0])
            cls = None
            for c in self._payload_classes:
                names = {n for n, _ in c.FIELDS}
                if version == 3 and "blob_gas_used" in names:
                    cls = c
                    break
                if version == 2 and "withdrawals" in names and "blob_gas_used" not in names:
                    cls = c
                    break
                if version == 1 and "withdrawals" not in names:
                    cls = c
                    break
            if cls is None:
                raise ValueError(f"no payload class for {method}")
            payload = self.engine.get_payload(payload_id, cls)
            obj = payload_to_json(payload)
            if version >= 2:
                return {"executionPayload": obj, "blockValue": qty(0)}
            return obj
        # -- eth1 namespace -------------------------------------------------
        if method == "eth_blockNumber":
            return qty(self.eth1.latest_block_number())
        if method == "eth_getBlockByNumber":
            tag = params[0]
            number = (
                self.eth1.latest_block_number()
                if tag == "latest"
                else unqty(tag)
            )
            blk = self.eth1.get_block(number)
            return {
                "number": qty(blk.number),
                "hash": data(blk.hash),
                "parentHash": data(blk.parent_hash),
                "timestamp": qty(blk.timestamp),
            }
        if method == "eth_getLogs":
            from ..eth1.http_provider import encode_deposit_log

            f = params[0]
            logs = self.eth1.get_deposit_logs(
                unqty(f["fromBlock"]), unqty(f["toBlock"])
            )
            return [
                encode_deposit_log(log, self.deposit_contract_address)
                for log in logs
            ]
        raise ValueError(f"unknown method {method}")
