"""Engine-API over HTTP JSON-RPC with JWT auth.

Twin of ``execution_layer/src/engine_api/http.rs``: a JSON-RPC 2.0 client
speaking ``engine_newPayloadV1..V3``, ``engine_forkchoiceUpdatedV1..V3``,
``engine_getPayloadV1..V3`` and ``engine_exchangeCapabilities`` to a real
(or mock-served) execution client, authenticated per request with a fresh
HS256 JWT (``auth.rs``). ``HttpExecutionEngine`` adapts the wire protocol to
the in-process ``ExecutionEngine`` seam, so the beacon chain is transport-
blind: the same chain code runs against ``MockExecutionLayer`` in-process or
any EL over a socket.

Engine-API JSON conventions: QUANTITY = minimal 0x-hex integers, DATA =
0x-hex byte strings, field names camelCase.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .auth import JwtKey
from .engine import (
    ExecutionEngine,
    PayloadAttributes,
    PayloadStatus,
    PayloadStatusV1,
)

ENGINE_CAPABILITIES = [
    "engine_newPayloadV1",
    "engine_newPayloadV2",
    "engine_newPayloadV3",
    "engine_forkchoiceUpdatedV1",
    "engine_forkchoiceUpdatedV2",
    "engine_forkchoiceUpdatedV3",
    "engine_getPayloadV1",
    "engine_getPayloadV2",
    "engine_getPayloadV3",
    "engine_exchangeCapabilities",
]


class EngineApiError(Exception):
    """JSON-RPC error from the EL (or transport failure)."""

    def __init__(self, message: str, code: int | None = None):
        super().__init__(message)
        self.code = code


def qty(v: int) -> str:
    """Engine-API QUANTITY: minimal big-endian hex, 0x-prefixed."""
    return hex(int(v))


def data(b: bytes) -> str:
    """Engine-API DATA: 0x-hex bytes."""
    return "0x" + bytes(b).hex()


def unqty(s: str) -> int:
    return int(s, 16)


def undata(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


# -- payload <-> engine-API JSON codecs -------------------------------------

def payload_to_json(payload) -> dict:
    """ExecutionPayload container -> engine-API ExecutionPayloadV1/2/3 JSON."""
    out = {
        "parentHash": data(payload.parent_hash),
        "feeRecipient": data(payload.fee_recipient),
        "stateRoot": data(payload.state_root),
        "receiptsRoot": data(payload.receipts_root),
        "logsBloom": data(payload.logs_bloom),
        "prevRandao": data(payload.prev_randao),
        "blockNumber": qty(payload.block_number),
        "gasLimit": qty(payload.gas_limit),
        "gasUsed": qty(payload.gas_used),
        "timestamp": qty(payload.timestamp),
        "extraData": data(payload.extra_data),
        "baseFeePerGas": qty(payload.base_fee_per_gas),
        "blockHash": data(payload.block_hash),
        "transactions": [data(tx) for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):
        out["withdrawals"] = [
            {
                "index": qty(w.index),
                "validatorIndex": qty(w.validator_index),
                "address": data(w.address),
                "amount": qty(w.amount),
            }
            for w in payload.withdrawals
        ]
    if hasattr(payload, "blob_gas_used"):
        out["blobGasUsed"] = qty(payload.blob_gas_used)
        out["excessBlobGas"] = qty(payload.excess_blob_gas)
    return out


def payload_from_json(obj: dict, payload_cls):
    """Engine-API ExecutionPayload JSON -> the fork's container class."""
    kwargs = dict(
        parent_hash=undata(obj["parentHash"]),
        fee_recipient=undata(obj["feeRecipient"]),
        state_root=undata(obj["stateRoot"]),
        receipts_root=undata(obj["receiptsRoot"]),
        logs_bloom=undata(obj["logsBloom"]),
        prev_randao=undata(obj["prevRandao"]),
        block_number=unqty(obj["blockNumber"]),
        gas_limit=unqty(obj["gasLimit"]),
        gas_used=unqty(obj["gasUsed"]),
        timestamp=unqty(obj["timestamp"]),
        extra_data=undata(obj["extraData"]),
        base_fee_per_gas=unqty(obj["baseFeePerGas"]),
        block_hash=undata(obj["blockHash"]),
        transactions=[undata(tx) for tx in obj["transactions"]],
    )
    payload = payload_cls(**kwargs)
    field_names = {n for n, _ in payload_cls.FIELDS}
    if "withdrawals" in obj and "withdrawals" in field_names:
        from ..types.containers import Withdrawal

        payload.withdrawals = [
            Withdrawal(
                index=unqty(w["index"]),
                validator_index=unqty(w["validatorIndex"]),
                address=undata(w["address"]),
                amount=unqty(w["amount"]),
            )
            for w in obj["withdrawals"]
        ]
    if "blobGasUsed" in obj and "blob_gas_used" in field_names:
        payload.blob_gas_used = unqty(obj["blobGasUsed"])
        payload.excess_blob_gas = unqty(obj["excessBlobGas"])
    return payload


def status_from_json(obj: dict) -> PayloadStatusV1:
    return PayloadStatusV1(
        status=PayloadStatus(obj["status"]),
        latest_valid_hash=(
            undata(obj["latestValidHash"])
            if obj.get("latestValidHash")
            else None
        ),
        validation_error=obj.get("validationError"),
    )


def status_to_json(st: PayloadStatusV1) -> dict:
    return {
        "status": st.status.value,
        "latestValidHash": (
            data(st.latest_valid_hash) if st.latest_valid_hash else None
        ),
        "validationError": st.validation_error,
    }


def attributes_to_json(attrs: PayloadAttributes) -> dict:
    out = {
        "timestamp": qty(attrs.timestamp),
        "prevRandao": data(attrs.prev_randao),
        "suggestedFeeRecipient": data(attrs.suggested_fee_recipient),
    }
    if attrs.withdrawals is not None:
        out["withdrawals"] = [
            {
                "index": qty(w.index),
                "validatorIndex": qty(w.validator_index),
                "address": data(w.address),
                "amount": qty(w.amount),
            }
            for w in attrs.withdrawals
        ]
    if attrs.parent_beacon_block_root is not None:
        out["parentBeaconBlockRoot"] = data(attrs.parent_beacon_block_root)
    return out


def attributes_from_json(obj: dict | None) -> PayloadAttributes | None:
    if obj is None:
        return None
    withdrawals = None
    if "withdrawals" in obj:
        from ..types.containers import Withdrawal

        withdrawals = [
            Withdrawal(
                index=unqty(w["index"]),
                validator_index=unqty(w["validatorIndex"]),
                address=undata(w["address"]),
                amount=unqty(w["amount"]),
            )
            for w in obj["withdrawals"]
        ]
    return PayloadAttributes(
        timestamp=unqty(obj["timestamp"]),
        prev_randao=undata(obj["prevRandao"]),
        suggested_fee_recipient=undata(obj["suggestedFeeRecipient"]),
        withdrawals=withdrawals,
    )


# -- the JSON-RPC client -----------------------------------------------------

class JsonRpcClient:
    """Minimal JSON-RPC 2.0 over HTTP with per-request JWT (http.rs)."""

    def __init__(self, url: str, jwt_key: JwtKey | None = None,
                 timeout: float = 8.0):
        self.url = url
        self.jwt_key = jwt_key
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, params: list):
        self._id += 1
        body = json.dumps(
            {
                "jsonrpc": "2.0",
                "method": method,
                "params": params,
                "id": self._id,
            }
        ).encode()
        headers = {"Content-Type": "application/json"}
        if self.jwt_key is not None:
            headers["Authorization"] = "Bearer " + self.jwt_key.generate_token()
        req = urllib.request.Request(self.url, data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                reply = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise EngineApiError(
                f"{method}: HTTP {e.code} {e.reason}", code=e.code
            ) from e
        except (urllib.error.URLError, TimeoutError, json.JSONDecodeError) as e:
            raise EngineApiError(f"{method}: {e}") from e
        if "error" in reply and reply["error"] is not None:
            err = reply["error"]
            raise EngineApiError(
                f"{method}: {err.get('message')}", code=err.get("code")
            )
        return reply.get("result")


class HttpExecutionEngine(ExecutionEngine):
    """The ExecutionEngine seam over engine-API HTTP JSON-RPC.

    Chooses the engine method version from the payload/attributes shape
    (withdrawals -> V2, blob gas -> V3), mirroring http.rs's fork-aware
    dispatch. Capability negotiation happens on first use and is cached.
    """

    def __init__(self, url: str, jwt_key: JwtKey | str | None = None,
                 timeout: float = 8.0):
        if isinstance(jwt_key, str):
            jwt_key = JwtKey.from_file(jwt_key)
        self.rpc = JsonRpcClient(url, jwt_key, timeout=timeout)
        self._capabilities: set[str] | None = None

    # -- capability negotiation (http.rs exchange_capabilities) ------------

    def exchange_capabilities(self) -> set[str]:
        if self._capabilities is None:
            result = self.rpc.call(
                "engine_exchangeCapabilities", [ENGINE_CAPABILITIES]
            )
            self._capabilities = set(result or [])
        return self._capabilities

    def _pick(self, base: str, version: int) -> str:
        """Highest supported method version <= the fork's preferred one."""
        caps = self.exchange_capabilities()
        for v in range(version, 0, -1):
            name = f"{base}V{v}"
            if name in caps:
                return name
        # ELs predating exchangeCapabilities: assume the preferred version
        return f"{base}V{version}"

    @staticmethod
    def _payload_version(payload) -> int:
        if hasattr(payload, "blob_gas_used"):
            return 3
        if hasattr(payload, "withdrawals"):
            return 2
        return 1

    # -- ExecutionEngine seam ----------------------------------------------

    def notify_new_payload(self, payload) -> PayloadStatusV1:
        version = self._payload_version(payload)
        method = self._pick("engine_newPayload", version)
        params = [payload_to_json(payload)]
        if method.endswith("V3"):
            # versioned hashes + parent beacon block root (Deneb): supplied
            # by the caller's DA layer; default to empty/zero here
            params += [[], data(b"\x00" * 32)]
        result = self.rpc.call(method, params)
        return status_from_json(result)

    def forkchoice_updated(
        self,
        head_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes: PayloadAttributes | None = None,
    ) -> tuple[PayloadStatusV1, bytes | None]:
        version = 1
        if payload_attributes is not None:
            if payload_attributes.parent_beacon_block_root is not None:
                version = 3  # Cancun: V3 required post-deneb (-38005 on V2)
            elif payload_attributes.withdrawals is not None:
                version = 2
        method = self._pick("engine_forkchoiceUpdated", version)
        state = {
            "headBlockHash": data(head_block_hash),
            "safeBlockHash": data(head_block_hash),
            "finalizedBlockHash": data(finalized_block_hash),
        }
        attrs = (
            attributes_to_json(payload_attributes)
            if payload_attributes is not None
            else None
        )
        result = self.rpc.call(method, [state, attrs])
        status = status_from_json(result["payloadStatus"])
        payload_id = (
            undata(result["payloadId"]) if result.get("payloadId") else None
        )
        return status, payload_id

    def get_payload(self, payload_id: bytes, payload_cls):
        version = 1
        names = {n for n, _ in payload_cls.FIELDS}
        if "blob_gas_used" in names:
            version = 3
        elif "withdrawals" in names:
            version = 2
        method = self._pick("engine_getPayload", version)
        result = self.rpc.call(method, [data(payload_id)])
        obj = result["executionPayload"] if version >= 2 else result
        return payload_from_json(obj, payload_cls)
