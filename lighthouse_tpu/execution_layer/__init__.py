"""Execution layer — the engine-API seam and its mock implementation.

Twin of ``/root/reference/beacon_node/execution_layer``: the beacon chain
talks to an execution client through three engine methods
(``engine_newPayload`` / ``engine_forkchoiceUpdated`` / ``engine_getPayload``,
``execution_layer/src/engine_api/mod.rs``), and ships a full in-process mock
(``execution_layer/src/test_utils/mock_execution_layer.rs`` +
``ExecutionBlockGenerator``) so merge-era blocks import without a real EL.
The HTTP JSON-RPC transport for a real client plugs in behind the same
``ExecutionEngine`` interface.
"""

from .auth import JwtKey  # noqa: F401
from .engine import (  # noqa: F401
    ExecutionEngine,
    PayloadAttributes,
    PayloadStatus,
    PayloadStatusV1,
)
from .http import EngineApiError, HttpExecutionEngine  # noqa: F401
from .json_server import ExecutionJsonRpcServer  # noqa: F401
from .mock import ExecutionBlockGenerator, MockExecutionLayer  # noqa: F401
