"""Engine-API JWT authentication (HS256).

Twin of ``execution_layer/src/engine_api/auth.rs``: the CL and EL share a
32-byte hex secret (the ``jwtsecret`` file); every engine-API HTTP request
carries ``Authorization: Bearer <jwt>`` where the JWT is HS256-signed with
an ``iat`` claim within +-60s of the EL's clock.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time

JWT_WINDOW_SECS = 60  # iat drift the server accepts (auth.rs parity)


def _b64url(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _b64url_decode(data: bytes) -> bytes:
    return base64.urlsafe_b64decode(data + b"=" * (-len(data) % 4))


class JwtKey:
    """The shared 32-byte engine-API secret."""

    def __init__(self, secret: bytes):
        if len(secret) != 32:
            raise ValueError("jwt secret must be exactly 32 bytes")
        self.secret = secret

    @classmethod
    def from_hex(cls, text: str) -> "JwtKey":
        text = text.strip()
        if text.startswith("0x"):
            text = text[2:]
        return cls(bytes.fromhex(text))

    @classmethod
    def from_file(cls, path: str) -> "JwtKey":
        with open(path) as f:
            return cls.from_hex(f.read())

    @classmethod
    def generate(cls, path: str | None = None) -> "JwtKey":
        key = cls(os.urandom(32))
        if path is not None:
            with open(path, "w") as f:
                f.write("0x" + key.secret.hex())
        return key

    def generate_token(self, iat: int | None = None) -> str:
        """Fresh HS256 JWT with an ``iat`` claim (auth.rs generate_token)."""
        header = _b64url(json.dumps({"typ": "JWT", "alg": "HS256"}).encode())
        claims = _b64url(
            json.dumps({"iat": int(iat if iat is not None else time.time())}).encode()
        )
        signing_input = header + b"." + claims
        sig = hmac.new(self.secret, signing_input, hashlib.sha256).digest()
        return (signing_input + b"." + _b64url(sig)).decode()

    def validate_token(self, token: str, now: int | None = None) -> bool:
        """Server-side check: signature + iat window. Constant-time compare."""
        try:
            header_b, claims_b, sig_b = token.encode().split(b".")
            expected = hmac.new(
                self.secret, header_b + b"." + claims_b, hashlib.sha256
            ).digest()
            if not hmac.compare_digest(expected, _b64url_decode(sig_b)):
                return False
            header = json.loads(_b64url_decode(header_b))
            if header.get("alg") != "HS256":
                return False
            claims = json.loads(_b64url_decode(claims_b))
            iat = int(claims["iat"])
        except (ValueError, KeyError):
            return False
        now = int(now if now is not None else time.time())
        return abs(now - iat) <= JWT_WINDOW_SECS
