"""Engine-API types and the ExecutionEngine interface.

Parity: ``execution_layer/src/engine_api/mod.rs`` (PayloadStatusV1 statuses,
forkchoiceUpdated/newPayload/getPayload shapes) reduced to the in-process
interface the chain consumes.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class PayloadStatus(enum.Enum):
    """engine_newPayload / forkchoiceUpdated statuses (PayloadStatusV1)."""

    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"
    INVALID_BLOCK_HASH = "INVALID_BLOCK_HASH"


@dataclass
class PayloadStatusV1:
    status: PayloadStatus
    latest_valid_hash: bytes | None = None
    validation_error: str | None = None


@dataclass
class PayloadAttributes:
    """forkchoiceUpdated payload-build request (PayloadAttributesV2/V3)."""

    timestamp: int
    prev_randao: bytes
    suggested_fee_recipient: bytes = b"\x00" * 20
    withdrawals: list | None = None  # capella+
    parent_beacon_block_root: bytes | None = None  # deneb+ (V3)


class ExecutionEngine:
    """What the beacon chain needs from an execution client."""

    def notify_new_payload(self, payload) -> PayloadStatusV1:
        raise NotImplementedError

    def forkchoice_updated(
        self,
        head_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes: PayloadAttributes | None = None,
    ) -> tuple[PayloadStatusV1, bytes | None]:
        """Returns (status, payload_id or None)."""
        raise NotImplementedError

    def get_payload(self, payload_id: bytes, payload_cls):
        """payload_cls is the fork's ExecutionPayload container class."""
        raise NotImplementedError
