"""Mock execution layer: deterministic in-process payload chain.

Twin of ``execution_layer/src/test_utils/{mock_execution_layer,
execution_block_generator}.rs``: builds execution payloads whose block hashes
are deterministic functions of their contents, tracks the valid-hash set, and
exposes the fault-injection toggles the reference's hook system provides
(``test_utils/hook.rs``; ``all_payloads_valid``-style switches at
``test_utils.rs:524``): force SYNCING (optimistic import) or INVALID.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from .engine import (
    ExecutionEngine,
    PayloadAttributes,
    PayloadStatus,
    PayloadStatusV1,
)

GENESIS_BLOCK_HASH = hashlib.sha256(b"lighthouse_tpu mock execution genesis").digest()


def compute_block_hash(payload) -> bytes:
    """Deterministic 'execution block hash': hash of the header-identifying
    fields (the mock's stand-in for the EL's RLP header hash)."""
    h = hashlib.sha256()
    h.update(bytes(payload.parent_hash))
    h.update(bytes(payload.prev_randao))
    h.update(int(payload.block_number).to_bytes(8, "little"))
    h.update(int(payload.timestamp).to_bytes(8, "little"))
    h.update(int(payload.gas_limit).to_bytes(8, "little"))
    for tx in payload.transactions:
        h.update(hashlib.sha256(bytes(tx)).digest())
    for w in getattr(payload, "withdrawals", []):
        h.update(type(w).encode(w))
    return h.digest()


@dataclass
class ExecutionBlockGenerator:
    """Tracks the mock execution chain: known-valid block hashes and block
    numbers, and builds child payloads on request."""

    blocks: dict = field(
        default_factory=lambda: {GENESIS_BLOCK_HASH: 0}
    )  # hash -> number

    def produce_payload(
        self,
        payload_cls,
        parent_hash: bytes,
        timestamp: int,
        prev_randao: bytes,
        fee_recipient: bytes = b"\x00" * 20,
        withdrawals: list | None = None,
        transactions: list | None = None,
    ):
        if parent_hash not in self.blocks:
            raise ValueError(f"unknown parent execution block {parent_hash.hex()[:16]}")
        number = self.blocks[parent_hash] + 1
        payload = payload_cls(
            parent_hash=parent_hash,
            fee_recipient=fee_recipient,
            state_root=hashlib.sha256(b"el-state-%d" % number).digest(),
            receipts_root=hashlib.sha256(b"receipts-%d" % number).digest(),
            prev_randao=prev_randao,
            block_number=number,
            gas_limit=30_000_000,
            gas_used=21_000,
            timestamp=timestamp,
            base_fee_per_gas=7,
            transactions=transactions or [],
        )
        if withdrawals is not None and hasattr(payload, "withdrawals"):
            payload.withdrawals = withdrawals
        payload.block_hash = compute_block_hash(payload)
        self.blocks[payload.block_hash] = number
        return payload


class MockExecutionLayer(ExecutionEngine):
    """In-process engine with fault injection.

    ``all_payloads_valid`` (default) accepts any structurally-consistent
    payload; ``syncing`` answers SYNCING (drives the chain's optimistic-import
    path); ``invalid`` rejects everything (drives invalidation propagation).
    """

    def __init__(self):
        self.generator = ExecutionBlockGenerator()
        self.mode = "valid"  # valid | syncing | invalid
        self._payload_requests: dict[bytes, object] = {}
        self.head_hash = GENESIS_BLOCK_HASH
        self.finalized_hash = b"\x00" * 32

    # -- fault injection hooks (test_utils/hook.rs analog) -----------------

    def set_mode(self, mode: str) -> None:
        assert mode in ("valid", "syncing", "invalid")
        self.mode = mode

    # -- engine API --------------------------------------------------------

    def notify_new_payload(self, payload) -> PayloadStatusV1:
        if self.mode == "syncing":
            return PayloadStatusV1(PayloadStatus.SYNCING)
        if self.mode == "invalid":
            return PayloadStatusV1(
                PayloadStatus.INVALID, latest_valid_hash=self.head_hash,
                validation_error="mock: forced invalid",
            )
        if bytes(payload.block_hash) != compute_block_hash(payload):
            return PayloadStatusV1(
                PayloadStatus.INVALID_BLOCK_HASH,
                validation_error="block hash mismatch",
            )
        if bytes(payload.parent_hash) not in self.generator.blocks:
            return PayloadStatusV1(PayloadStatus.SYNCING)
        self.generator.blocks.setdefault(
            bytes(payload.block_hash), int(payload.block_number)
        )
        return PayloadStatusV1(
            PayloadStatus.VALID, latest_valid_hash=bytes(payload.block_hash)
        )

    def forkchoice_updated(
        self,
        head_block_hash: bytes,
        finalized_block_hash: bytes,
        payload_attributes: PayloadAttributes | None = None,
    ) -> tuple[PayloadStatusV1, bytes | None]:
        if self.mode == "syncing":
            return PayloadStatusV1(PayloadStatus.SYNCING), None
        if self.mode == "invalid":
            return (
                PayloadStatusV1(
                    PayloadStatus.INVALID,
                    validation_error="mock: forced invalid",
                ),
                None,
            )
        if head_block_hash not in self.generator.blocks:
            return PayloadStatusV1(PayloadStatus.SYNCING), None
        self.head_hash = head_block_hash
        self.finalized_hash = finalized_block_hash
        payload_id = None
        if payload_attributes is not None:
            payload_id = hashlib.sha256(
                head_block_hash
                + int(payload_attributes.timestamp).to_bytes(8, "little")
                + payload_attributes.prev_randao
            ).digest()[:8]
            self._payload_requests[payload_id] = (
                head_block_hash,
                payload_attributes,
            )
        return (
            PayloadStatusV1(PayloadStatus.VALID, latest_valid_hash=head_block_hash),
            payload_id,
        )

    def get_payload(self, payload_id: bytes, payload_cls):
        head_hash, attrs = self._payload_requests.pop(payload_id)
        return self.generator.produce_payload(
            payload_cls,
            parent_hash=head_hash,
            timestamp=attrs.timestamp,
            prev_randao=attrs.prev_randao,
            fee_recipient=attrs.suggested_fee_recipient,
            withdrawals=attrs.withdrawals,
        )
