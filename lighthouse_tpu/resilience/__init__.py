"""Fault-domain layer between the serving engines and the device backends.

Three pieces (ISSUE 7):

* ``faults``     — the fault taxonomy (transient / oom / hang / corruption),
  classifier, and the process-global classified-fault ring that replaces
  every silent ``except Exception`` drop on the device path.
* ``supervisor`` — per-domain backend supervisors: watchdog deadlines for
  hang detection, bounded jittered-backoff retry for transients, and a
  HEALTHY → DEGRADED → QUARANTINED circuit breaker driving a degradation
  ladder (full device shape → reduced batch shape → native/oracle CPU
  fallback) so a device fault degrades throughput instead of dropping work.
* ``inject``     — the seeded, env-gated deterministic fault injector
  (``LIGHTHOUSE_FAULT_INJECT``) that the chaos harness uses to make any
  supervised stage raise, hang, or corrupt on the Nth call.

Import-light: no jax anywhere in this package — supervisors wrap device
calls, they never trace into them, so the jit-facing call boundary is
byte-identical (the analysis CLI's supervisor pass proves zero added
steady-state recompiles).

Canonical fault domains:

* ``bls_supervisor()``   — the batched BLS verify path
  (``beacon_chain.chain._batch_verify_items`` and through it the firehose).
* ``epoch_supervisor()`` — the device epoch engine
  (``epoch_engine.engine.process_epoch_on_device``).
* ``slasher_supervisor()`` — the device-resident slasher span store
  (``slasher.engine.SpanStore``; injection stage ``slasher.sweep``).
* ``kzg_supervisor()`` — the device-batched KZG cell-proof engine
  (``kzg.engine.verify_cell_proof_batch``; injection stage
  ``kzg.cell_batch_verify`` with rungs ``device_full`` / ``device_reduced``
  / ``cpu_oracle``). Data availability fails CLOSED: a fully faulted
  ladder returns "not verified", never "available".
* ``lc_supervisor()`` — the device-batched light-client update engine
  (``light_client/engine.py``; injection stage ``lc.batch_verify`` with
  the same three rungs). Fails CLOSED: a faulted ladder never reports a
  light-client session verified.
"""

from __future__ import annotations

from .faults import (  # noqa: F401
    FaultKind,
    FaultRecord,
    SupervisedFault,
    WatchdogTimeout,
    classify,
    classify_text,
    clear_fault_log,
    recent_faults,
    record_fault,
)
from .crashpoints import (  # noqa: F401
    InjectedCrash,
    maybe_crash,
)
from .inject import (  # noqa: F401
    ENV_VAR as INJECT_ENV_VAR,
    FaultInjector,
    InjectedFault,
    injector,
    maybe_fault,
)
from .supervisor import (  # noqa: F401
    BackendSupervisor,
    HealthState,
    SupervisorConfig,
    all_supervisors,
    get_supervisor,
    reset_all,
    run_with_deadline,
    snapshot_all,
)

BLS_DOMAIN = "bls_device"
EPOCH_DOMAIN = "epoch_device"
SLASHER_DOMAIN = "slasher_device"
KZG_DOMAIN = "kzg_device"
LC_DOMAIN = "lc_device"


def bls_supervisor() -> BackendSupervisor:
    """The fault domain guarding batched BLS device verification."""
    return get_supervisor(BLS_DOMAIN)


def epoch_supervisor() -> BackendSupervisor:
    """The fault domain guarding the device epoch engine."""
    return get_supervisor(EPOCH_DOMAIN)


def slasher_supervisor() -> BackendSupervisor:
    """The fault domain guarding the device-resident slasher span store
    (``slasher/engine.py``): a faulted ``slasher.sweep`` restores the host
    checkpoint + replays the pair journal on the numpy twin, so demotion
    never drops evidence."""
    return get_supervisor(SLASHER_DOMAIN)


def kzg_supervisor() -> BackendSupervisor:
    """The fault domain guarding device-batched KZG cell verification
    (``kzg/engine.py``). A column whose proof batch cannot be verified on
    ANY rung is treated as unverified — the availability checker never
    marks a block available off a faulted ladder (fail closed)."""
    return get_supervisor(KZG_DOMAIN)


def lc_supervisor() -> BackendSupervisor:
    """The fault domain guarding device-batched light-client update
    verification (``light_client/engine.py``; injection stage
    ``lc.batch_verify`` with rungs ``device_full`` / ``device_reduced`` /
    ``cpu_oracle``). Fails CLOSED: a session that cannot be verified on
    ANY rung is reported unverified — a faulted ladder never reports a
    light-client session verified."""
    return get_supervisor(LC_DOMAIN)


def health_snapshot() -> dict:
    """Fault-domain health for /health + monitoring: per-domain supervisor
    snapshots plus the most recent classified faults."""
    return {
        "supervisors": snapshot_all(),
        "recent_faults": recent_faults(16),
        "injection_active": injector.active(),
    }
