"""Backend supervisor: watchdog, bounded retry, and the degradation ladder.

One ``BackendSupervisor`` guards one fault domain (the BLS device backend,
the epoch engine, a bench engine). Every supervised call runs through
``run_ladder(stage, rungs)`` where ``rungs`` is the degradation ladder for
that call — typically::

    (full device shape, reduced batch shape, native/oracle CPU fallback)

Policy per classified fault kind (``faults.classify``):

* TRANSIENT  — retried in place up to ``max_retries`` with seeded jittered
  backoff; only then does the ladder descend.
* OOM        — no same-shape retry (futile); descend immediately: the next
  rung is the reduced shape.
* HANG       — watchdog fired; the worker thread may be stranded inside the
  device client forever (it cannot be killed). Descend immediately; the
  stranded-thread count is capped (``max_hung_threads``) — past the cap the
  domain is hard-quarantined so a wedged tunnel cannot accumulate threads.
* CORRUPTION — device numerics suspect; jump straight to the LAST rung
  (CPU fallback) and quarantine.

Health state machine (circuit breaker)::

    HEALTHY --fault--> DEGRADED --fault--> QUARANTINED
       ^                  |                     |
       +--(promote_after  |                     | probation_s cool-off,
       |   consecutive    |                     | then ONE probe call at
       |   full-rung OKs) |                     | the full rung
       +------------------+---- probe OK -------+

* HEALTHY     — calls start at rung 0 (full device shape).
* DEGRADED    — calls start at rung 1 (reduced shape); every
  ``probe_every``-th call starts at rung 0 as a promotion probe.
* QUARANTINED — calls start at the last rung (CPU fallback; device never
  touched); after ``probation_s`` the next call probes rung 0. A probe
  success re-promotes one level; ``promote_after`` consecutive full-rung
  successes then restore HEALTHY. Never total loss of service: whatever
  the state, some rung answers — a call fails only when every rung faults
  (``SupervisedFault``, counted as ``exhausted``; callers fail CLOSED).

Everything is observable: per-domain health gauge, fault/demotion/promotion/
retry/fallback counters in ``utils.metrics``, and ``snapshot()`` for
/health, bench records, and the chaos assertions.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass
from enum import IntEnum

from ..utils.metrics import (
    RESILIENCE_DEMOTIONS,
    RESILIENCE_FALLBACK_CALLS,
    RESILIENCE_HEALTH,
    RESILIENCE_PROMOTIONS,
    RESILIENCE_RETRIES,
    RESILIENCE_WATCHDOG_TIMEOUTS,
)
from . import faults
from .faults import FaultKind, SupervisedFault, WatchdogTimeout
from .inject import maybe_fault


class HealthState(IntEnum):
    HEALTHY = 0
    DEGRADED = 1
    QUARANTINED = 2


def _default_deadline() -> float:
    # generous by default: a COLD first call legitimately spends minutes in
    # XLA compilation (the r3 pathology hit 461 s at toy shape) — the
    # watchdog must catch wedged-forever, not slow-compile. Benches and the
    # hunter tighten it via the env var once caches are warm.
    return float(os.environ.get("LIGHTHOUSE_WATCHDOG_S", "600"))


@dataclass
class SupervisorConfig:
    deadline_s: float | None = None     # None -> LIGHTHOUSE_WATCHDOG_S (600)
    max_retries: int = 2                # transient retries per rung
    backoff_base_s: float = 0.05
    backoff_max_s: float = 1.0
    seed: int = 0                       # jitter determinism (chaos runs)
    promote_after: int = 3              # full-rung OKs to climb one level
    probe_every: int = 4                # DEGRADED: probe rung 0 every Nth call
    probation_s: float = 5.0            # QUARANTINED cool-off before a probe
    max_hung_threads: int = 4           # stranded watchdog workers cap

    def resolved_deadline(self) -> float | None:
        d = self.deadline_s if self.deadline_s is not None else _default_deadline()
        return d if d and d > 0 else None


class BackendSupervisor:
    def __init__(self, name: str, config: SupervisorConfig | None = None):
        self.name = name
        self.config = config or SupervisorConfig()
        seed = int(os.environ.get("LIGHTHOUSE_RESILIENCE_SEED",
                                  str(self.config.seed)))
        self._rng = random.Random(seed)
        self._lock = threading.RLock()
        self.state = HealthState.HEALTHY
        self._streak = 0                # consecutive full-rung successes
        self._calls_since_demotion = 0
        self._quarantined_at: float | None = None
        self._hung_threads = 0
        self._hard_quarantined = False
        # counters (all monotonic; exposed via snapshot() + metrics)
        self.calls = 0
        self.retries = 0
        self.demotions = 0
        self.promotions = 0
        self.fallback_calls = 0         # answered below rung 0
        self.watchdog_timeouts = 0
        self.exhausted = 0              # every rung failed (fail-closed)
        self.faults_seen = 0
        RESILIENCE_HEALTH.set(0, domain=name)

    # -- health machine ----------------------------------------------------

    def _set_state(self, new: HealthState) -> None:
        """Caller holds the lock."""
        if new == self.state:
            return
        if new > self.state:
            self.demotions += 1
            RESILIENCE_DEMOTIONS.inc(domain=self.name)
            self._calls_since_demotion = 0
        else:
            self.promotions += 1
            RESILIENCE_PROMOTIONS.inc(domain=self.name)
        self.state = new
        self._streak = 0
        self._quarantined_at = (
            time.monotonic() if new == HealthState.QUARANTINED else None
        )
        RESILIENCE_HEALTH.set(int(new), domain=self.name)

    def _probation_due(self) -> bool:
        return (
            self._quarantined_at is not None
            and time.monotonic() - self._quarantined_at >= self.config.probation_s
        )

    def device_allowed(self) -> bool:
        """May the full device rung be attempted right now? (The epoch
        engine's cheap pre-check: in quarantine the device path is skipped
        entirely until probation, without binding a mirror first.)"""
        with self._lock:
            if self._hard_quarantined:
                return False
            if self.state != HealthState.QUARANTINED:
                return True
            return self._probation_due()

    def note_fallback(self, rung: str = "external") -> None:
        """Record that the caller served this request from its own fallback
        path (the epoch engine's numpy twin lives outside the ladder)."""
        with self._lock:
            self.fallback_calls += 1
        RESILIENCE_FALLBACK_CALLS.inc(domain=self.name, rung=rung)

    def _start_rung(self, n_rungs: int, cpu_idx: int | None) -> int | None:
        """First ladder rung for this call, or None when quarantine demands
        a device-free rung and the ladder has none (caller fails closed)."""
        with self._lock:
            self._calls_since_demotion += 1
            if self.state == HealthState.HEALTHY:
                return 0
            if self.state == HealthState.DEGRADED:
                if self._calls_since_demotion % self.config.probe_every == 0:
                    return 0            # promotion probe
                return min(1, n_rungs - 1)
            if self._probation_due():
                return 0                # quarantine probation probe
            # QUARANTINED: the device is not trusted — only a cpu* rung may
            # serve; a ladder without one fails closed
            return cpu_idx

    def _on_full_rung_success(self) -> None:
        with self._lock:
            if self.state == HealthState.QUARANTINED:
                self._set_state(HealthState.DEGRADED)
                self._streak = 1
            elif self.state == HealthState.DEGRADED:
                self._streak += 1
                if self._streak >= self.config.promote_after:
                    self._set_state(HealthState.HEALTHY)
            else:
                self._streak += 1

    def _on_rung_fault(self, kind: FaultKind) -> None:
        with self._lock:
            self._streak = 0
            if kind == FaultKind.CORRUPTION:
                target = HealthState.QUARANTINED
            elif self.state == HealthState.HEALTHY:
                target = HealthState.DEGRADED
            else:
                target = HealthState.QUARANTINED
            if (
                target == HealthState.QUARANTINED
                and self.state == HealthState.QUARANTINED
            ):
                # a failed probation probe restarts the cool-off clock
                self._quarantined_at = time.monotonic()
            self._set_state(target)

    # -- watchdog ----------------------------------------------------------

    def _with_watchdog(self, stage: str, fn):
        # one daemon thread per supervised call (~50-100us): noise next to
        # the ms-scale device dispatch it guards. If a profile ever shows
        # it on the serving path, the upgrade is a persistent worker with a
        # request queue — same hang semantics, amortized thread cost.
        deadline = self.config.resolved_deadline()
        if deadline is None:
            return fn()
        box: dict = {}
        done = threading.Event()
        timed_out = threading.Event()

        def worker():
            try:
                box["v"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                box["e"] = e
            finally:
                done.set()
                # the timeout-vs-completion decision is made under the
                # supervisor lock below; taking the same lock here makes the
                # hung-thread accounting race-free in both interleavings
                with self._lock:
                    if timed_out.is_set():
                        # the stranded call eventually returned: un-count it,
                        # and lift the hard quarantine once the backlog
                        # drains — the domain then recovers through the
                        # NORMAL probation path instead of staying pinned
                        # to the last rung until process restart
                        self._hung_threads -= 1
                        if self._hung_threads < self.config.max_hung_threads:
                            self._hard_quarantined = False

        # watchdog workers are deliberately never joined: a wedged device
        # call cannot be killed, so the hang model ABANDONS the thread and
        # counts it against max_hung_threads instead (bounded by the hard
        # quarantine); done.wait(deadline) is the bounded reclaim
        th = threading.Thread(  # lint: allow(unjoined-thread)
            target=worker, daemon=True, name=f"watchdog-{self.name}-{stage}"
        )
        th.start()
        if not done.wait(deadline):
            with self._lock:
                if not done.is_set():   # decide under the lock: truly hung
                    timed_out.set()
                    self._hung_threads += 1
                    self.watchdog_timeouts += 1
                    if self._hung_threads >= self.config.max_hung_threads:
                        # a wedged tunnel must not accumulate threads
                        self._hard_quarantined = True
                        self._set_state(HealthState.QUARANTINED)
                    fire = True
                else:
                    fire = False        # result arrived at the deadline: use it
            if fire:
                RESILIENCE_WATCHDOG_TIMEOUTS.inc(domain=self.name, stage=stage)
                raise WatchdogTimeout(stage, deadline)
        if "e" in box:
            raise box["e"]
        return box["v"]

    # -- the supervised call -----------------------------------------------

    def _backoff(self, attempt: int) -> float:
        base = min(
            self.config.backoff_max_s,
            self.config.backoff_base_s * (2 ** (attempt - 1)),
        )
        with self._lock:
            jitter = self._rng.uniform(0.5, 1.0)
        return base * jitter

    def _attempt_rung(self, stage: str, rung_name: str, fn, rung_idx: int):
        """One ladder rung with bounded transient retries. Raises the last
        exception when the rung is out of retries (ladder descends)."""
        attempt = 0
        while True:
            attempt += 1
            with self._lock:
                self.calls += 1
            # bare stage names target the primary rung; lower rungs are
            # addressable as "stage/rung" (see inject.py)
            inj_name = stage if rung_idx == 0 else f"{stage}/{rung_name}"

            def guarded():
                # injection runs INSIDE the watchdog so a hang-mode plan is
                # detected the way a real wedged call would be
                maybe_fault(inj_name)
                return fn()

            try:
                return self._with_watchdog(stage, guarded)
            except Exception as e:  # noqa: BLE001 — classified below
                kind = faults.classify(e)
                with self._lock:
                    self.faults_seen += 1
                faults.record_fault(
                    stage, e, kind=kind, domain=self.name, rung=rung_name,
                    attempt=attempt,
                )
                retryable = (
                    kind == FaultKind.TRANSIENT
                    and attempt <= self.config.max_retries
                )
                if not retryable:
                    raise
                with self._lock:
                    self.retries += 1
                RESILIENCE_RETRIES.inc(domain=self.name, stage=stage)
                time.sleep(self._backoff(attempt))

    def run_ladder(self, stage: str, rungs) -> object:
        """Run one supervised call down the degradation ladder.

        ``rungs``: sequence of ``(rung_name, thunk)``, full shape first,
        CPU fallback last. Returns the first rung result; raises
        ``SupervisedFault`` only when every reachable rung faulted.
        A ``False`` verdict from a verifier is a RESULT, never a fault —
        the supervisor only ever reacts to exceptions.

        Rung names starting with ``cpu`` mark device-free rungs: under a
        HARD quarantine (hung-thread cap hit — the backend is wedged with
        stranded threads) only those are eligible; a ladder with no cpu
        rung fails closed immediately rather than feeding more threads
        into the wedge.
        """
        rungs = list(rungs)
        n = len(rungs)
        cpu = next(
            (i for i, (nm, _) in enumerate(rungs) if nm.startswith("cpu")),
            None,
        )
        with self._lock:
            hard = self._hard_quarantined
        last: BaseException | None = None
        r = cpu if hard else self._start_rung(n, cpu)
        if r is None:  # quarantined ladder with no device-free rung
            with self._lock:
                self.exhausted += 1
            raise SupervisedFault(stage, None)
        while r < n:
            name, fn = rungs[r]
            try:
                result = self._attempt_rung(stage, name, fn, r)
            except Exception as e:  # noqa: BLE001 — rung exhausted
                last = e
                kind = faults.classify(e)
                self._on_rung_fault(kind)
                if kind == FaultKind.CORRUPTION:
                    # device numerics suspect: NOTHING device-shaped can be
                    # trusted — only a cpu* rung may finish this call
                    if cpu is None or cpu <= r:
                        break
                    r = cpu
                else:
                    r += 1
                continue
            if r == 0:
                self._on_full_rung_success()
            else:
                with self._lock:
                    self.fallback_calls += 1
                RESILIENCE_FALLBACK_CALLS.inc(domain=self.name, rung=name)
            return result
        with self._lock:
            self.exhausted += 1
        raise SupervisedFault(stage, last)

    def run(self, stage: str, fn):
        """Single-rung supervised call (watchdog + retries + health), for
        domains whose fallback lives outside the ladder (epoch engine)."""
        return self.run_ladder(stage, ((stage.rsplit(".", 1)[-1], fn),))

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state.name,
                "calls": self.calls,
                "faults": self.faults_seen,
                "retries": self.retries,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "fallback_calls": self.fallback_calls,
                "watchdog_timeouts": self.watchdog_timeouts,
                "hung_threads": self._hung_threads,
                "hard_quarantined": self._hard_quarantined,
                "exhausted": self.exhausted,
            }

    def reset(self) -> None:
        """Test hook: back to a fresh HEALTHY supervisor (counters zeroed)."""
        with self._lock:
            self.state = HealthState.HEALTHY
            self._streak = 0
            self._calls_since_demotion = 0
            self._quarantined_at = None
            self._hung_threads = 0
            self._hard_quarantined = False
            self.calls = self.retries = self.demotions = 0
            self.promotions = self.fallback_calls = self.watchdog_timeouts = 0
            self.exhausted = self.faults_seen = 0
            self._rng = random.Random(self.config.seed)
        RESILIENCE_HEALTH.set(0, domain=self.name)


# -- process-global registry ----------------------------------------------------

_REGISTRY: dict[str, BackendSupervisor] = {}
_REGISTRY_LOCK = threading.Lock()


def get_supervisor(
    name: str, config: SupervisorConfig | None = None
) -> BackendSupervisor:
    """Named supervisor, one per fault domain, created on first use.
    ``config`` only applies on creation — a domain's policy is process-wide."""
    with _REGISTRY_LOCK:
        sup = _REGISTRY.get(name)
        if sup is None:
            sup = _REGISTRY[name] = BackendSupervisor(name, config)
        return sup


def all_supervisors() -> dict[str, BackendSupervisor]:
    with _REGISTRY_LOCK:
        return dict(_REGISTRY)


def snapshot_all() -> dict:
    """{domain: snapshot} for every supervisor that has been created —
    the /health payload and the bench-record integrity stamp."""
    return {name: sup.snapshot() for name, sup in all_supervisors().items()}


def reset_all() -> None:
    """Test hook: reset every registered supervisor to HEALTHY."""
    for sup in all_supervisors().values():
        sup.reset()


def run_with_deadline(stage: str, fn, deadline_s: float):
    """Standalone watchdog call (no health machine): used by the TPU hunter
    to bound probe helpers — raises ``WatchdogTimeout`` on a hang."""
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            box["v"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["e"] = e
        finally:
            done.set()

    # same abandonment contract as _with_watchdog: the probe thread may be
    # wedged inside the device client and cannot be joined
    th = threading.Thread(target=worker, daemon=True, name=f"watchdog-{stage}")  # lint: allow(unjoined-thread)
    th.start()
    if not done.wait(deadline_s):
        raise WatchdogTimeout(stage, deadline_s)
    if "e" in box:
        raise box["e"]
    return box["v"]
