"""Crash-point injection: deterministic process-kill simulation at
persistence barriers (ISSUE 12).

The fault injector (``inject.py``) makes a supervised *device* stage raise a
fault the resilience ladder absorbs. Crash points are the opposite contract:
they simulate the process DYING at a persistence barrier — nothing may
absorb them, because nothing absorbs a ``kill -9``. Hence
``InjectedCrash`` derives from ``BaseException``: every ``except Exception``
handler on the stack (observer shields, chaos-mode proposal tolerance,
supervisor rungs) lets it through, exactly like a real kill, and only the
test driver — playing the role of the operating system — catches it and
marks the node dead.

Two modes ride the existing ``LIGHTHOUSE_FAULT_INJECT`` grammar:

* ``mode=kill`` — die BEFORE the barrier's bytes are written (the op never
  happened);
* ``mode=tear`` — persist a deterministic prefix of the write, then die
  (the torn-tail case WAL replay must truncate). Only barriers that own a
  byte stream honor tear (``store.commit``, ``store.compact``); elsewhere
  it degrades to kill.

Enumerable barrier stages (the crash-point sweep kills at the Nth firing of
each): ``store.commit`` (every WAL frame: block import, state writes, the
finalization migration's freeze/prune batches, slasher checkpoints...),
``store.compact`` / ``store.compact.replace``, ``persist.fork_choice``,
``persist.op_pool``, ``persist.slasher``, ``persist.slashing_protection``,
``migrate.finalization``. Counting a sweep's total barriers needs no extra
machinery: install a never-firing plan (``at=10**9``) and read its
``calls`` counter back from ``injector.plans()``.
"""

from __future__ import annotations

from .inject import injector

CRASH_MODES = ("kill", "tear")


class InjectedCrash(BaseException):
    """The process "died" at a persistence barrier. BaseException on
    purpose — see the module docstring; only the chaos driver catches it."""

    def __init__(self, stage: str, owner: str | None = None, torn: bool = False):
        what = "torn write" if torn else "killed"
        suffix = f" [{owner}]" if owner else ""
        super().__init__(f"injected crash: {what} at {stage}{suffix}")
        self.stage = stage
        self.owner = owner
        self.torn = torn


def raise_crash(stage: str, owner: str | None = None, torn: bool = False):
    raise InjectedCrash(stage, owner=owner, torn=torn)


def maybe_crash(
    stage: str, owner: str | None = None, tear_capable: bool = False
) -> str | None:
    """The barrier hook. Returns ``None`` (no plan fired) or ``"tear"``
    (only when the caller declared ``tear_capable`` — it owns the byte
    stream: persist a prefix, then call ``raise_crash(..., torn=True)``).
    ``kill`` raises here; a ``tear`` plan at a barrier that cannot tear
    degrades to kill. Inert — one attribute read — unless
    ``LIGHTHOUSE_FAULT_INJECT`` armed plans."""
    if not injector.active():
        return None
    action = injector.crash_action(stage)
    if action == "kill" or (action == "tear" and not tear_capable):
        raise_crash(stage, owner=owner)
    return action
