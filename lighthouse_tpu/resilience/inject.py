"""Deterministic, env-gated fault injector for supervised stages.

The chaos harness needs faults that are *reproducible*: the Nth device call
fails, every run, regardless of wall clock or thread timing. Each supervised
stage keeps a per-plan call counter, and a plan fires purely as a function
of that counter — no randomness on the firing decision (the ``seed`` field
exists so stochastic modes stay reproducible if ever added, and is embedded
in the plan's repr for provenance).

Activation is env-gated: ``LIGHTHOUSE_FAULT_INJECT`` is parsed once on
first use (tests use ``install()``/``clear()``/``reload_env()`` directly).
An empty/unset variable means the injector is completely inert — the hot
path pays one attribute read.

Spec grammar (clauses joined with ``|``, fields with ``;``)::

    LIGHTHOUSE_FAULT_INJECT="stage=bls.batch_verify;mode=raise;kind=transient;every=5"
    LIGHTHOUSE_FAULT_INJECT="stage=epoch.sweep;mode=hang;hang_s=0.5;at=3|stage=firehose.device_verify;mode=corrupt;at=2;times=1"

Fields:

* ``stage``  (required) — supervised stage name. Bare names match the
  *primary* (full-device) rung only; ``stage/rung`` targets a specific
  ladder rung; a trailing ``*`` prefix-matches.
* ``mode``   — ``raise`` (default), ``hang`` (sleep past the watchdog
  deadline), ``corrupt`` (raise a limb-bound-assert-shaped error, the
  *detected*-corruption fault: the certifier's bound asserts are exactly
  what turns silent bad numerics into a classified fault), or the crash
  modes ``kill`` / ``tear`` (simulate the process dying at a persistence
  barrier — consumed ONLY through ``crash_action`` by the crash-point
  hooks in ``crashpoints.py``, never by ``before_call``, so a supervised
  device stage can never accidentally absorb a "process death").
* ``kind``   — for ``raise``: ``transient`` (default) or ``oom``.
* ``every=K`` / ``at=N`` — fire on every Kth call / only on the Nth call.
* ``times=T`` — stop after T firings (default unlimited).
* ``hang_s`` — sleep length for ``hang`` (default 0.25 s).
* ``seed``   — recorded for provenance; reserved for stochastic modes.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from .faults import FaultKind

ENV_VAR = "LIGHTHOUSE_FAULT_INJECT"


class InjectedFault(RuntimeError):
    """A fault raised by the injector; carries its taxonomy kind so
    ``faults.classify`` never has to guess."""

    def __init__(self, kind: FaultKind, stage: str, call_no: int):
        msg = {
            FaultKind.TRANSIENT: "injected transient host error",
            FaultKind.OOM: "injected RESOURCE_EXHAUSTED: out of memory "
                           "allocating device buffer",
            FaultKind.CORRUPTION: "injected limb bound assert tripped: "
                                  "corrupted device output",
            FaultKind.HANG: "injected hang",
        }[kind]
        super().__init__(f"{msg} (stage={stage}, call #{call_no})")
        self.fault_kind = kind.value
        self.stage = stage
        self.call_no = call_no


@dataclass
class _Plan:
    stage: str
    mode: str = "raise"                 # raise | hang | corrupt
    kind: FaultKind = FaultKind.TRANSIENT
    every: int | None = None
    at: int | None = None
    times: int | None = None
    hang_s: float = 0.25
    seed: int = 0
    calls: int = 0
    fired: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def matches(self, stage: str) -> bool:
        if self.stage.endswith("*"):
            return stage.startswith(self.stage[:-1])
        return stage == self.stage

    def should_fire(self) -> bool:
        """Count this call; decide deterministically. Thread-safe: the
        counter is the only shared decision input."""
        with self._lock:
            self.calls += 1
            if self.times is not None and self.fired >= self.times:
                return False
            hit = False
            if self.at is not None:
                hit = self.calls == self.at
            elif self.every is not None:
                hit = self.calls % self.every == 0
            if hit:
                self.fired += 1
            return hit

    def as_dict(self) -> dict:
        return {
            "stage": self.stage, "mode": self.mode, "kind": self.kind.value,
            "every": self.every, "at": self.at, "times": self.times,
            "hang_s": self.hang_s, "seed": self.seed,
            "calls": self.calls, "fired": self.fired,
        }


def _parse_clause(clause: str) -> _Plan:
    kw: dict = {}
    for pair in clause.split(";"):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(f"bad injection field {pair!r} (want key=value)")
        k, v = (s.strip() for s in pair.split("=", 1))
        if k == "stage":
            kw["stage"] = v
        elif k == "mode":
            if v not in ("raise", "hang", "corrupt", "kill", "tear"):
                raise ValueError(f"unknown injection mode {v!r}")
            kw["mode"] = v
        elif k == "kind":
            kw["kind"] = FaultKind(v)
        elif k in ("every", "at", "times", "seed"):
            kw[k] = int(v)
        elif k == "hang_s":
            kw["hang_s"] = float(v)
        else:
            raise ValueError(f"unknown injection field {k!r}")
    if "stage" not in kw:
        raise ValueError(f"injection clause missing stage=: {clause!r}")
    if kw.get("mode") == "corrupt":
        kw["kind"] = FaultKind.CORRUPTION
    if "every" not in kw and "at" not in kw:
        kw["at"] = 1
    return _Plan(**kw)


class FaultInjector:
    """Process-global registry of injection plans (see module docstring)."""

    def __init__(self):
        self._plans: list[_Plan] = []
        self._lock = threading.Lock()
        self._env_loaded = False

    # -- configuration -----------------------------------------------------

    def install(self, spec: str) -> list[_Plan]:
        """Parse + add plans from a spec string. Returns the new plans."""
        plans = [_parse_clause(c) for c in spec.split("|") if c.strip()]
        with self._lock:
            self._env_loaded = True  # explicit install overrides env gating
            self._plans.extend(plans)
        return plans

    def clear(self) -> None:
        with self._lock:
            self._plans = []
            self._env_loaded = True

    def reload_env(self) -> None:
        """Drop all plans and re-read LIGHTHOUSE_FAULT_INJECT."""
        with self._lock:
            self._plans = []
            self._env_loaded = False
        self._ensure_env()

    def _ensure_env(self) -> None:
        if self._env_loaded:
            return
        with self._lock:
            if self._env_loaded:
                return
            self._env_loaded = True
            spec = os.environ.get(ENV_VAR, "").strip()
            if spec:
                self._plans.extend(
                    _parse_clause(c) for c in spec.split("|") if c.strip()
                )

    def active(self) -> bool:
        self._ensure_env()
        return bool(self._plans)

    def plans(self) -> list[dict]:
        self._ensure_env()
        with self._lock:
            return [p.as_dict() for p in self._plans]

    # -- the supervised-stage hook ----------------------------------------

    def before_call(self, stage: str) -> None:
        """Called by the supervisor at every rung invocation with the
        injection-qualified stage name. May sleep (hang) or raise."""
        self._ensure_env()
        if not self._plans:
            return
        with self._lock:
            plans = list(self._plans)
        for p in plans:
            if p.mode in ("kill", "tear"):
                continue  # crash plans fire only via crash_action
            if not p.matches(stage) or not p.should_fire():
                continue
            if p.mode == "hang":
                time.sleep(p.hang_s)  # a *slow* call: the watchdog decides
                continue
            raise InjectedFault(p.kind, stage, p.calls)

    def crash_action(self, stage: str) -> str | None:
        """Called by crash-point hooks (``crashpoints.maybe_crash``) at
        every persistence barrier. Counts the call on each matching
        kill/tear plan and returns the mode of the first plan that fires
        (``"kill"`` | ``"tear"``), else None. Counters are crash-plan
        private: ``before_call`` never ticks them, so "the Nth persistence
        op" is exact regardless of interleaved device-fault plans."""
        self._ensure_env()
        if not self._plans:
            return None
        with self._lock:
            plans = list(self._plans)
        action = None
        for p in plans:
            if p.mode not in ("kill", "tear") or not p.matches(stage):
                continue
            if p.should_fire() and action is None:
                action = p.mode
        return action


injector = FaultInjector()
maybe_fault = injector.before_call
