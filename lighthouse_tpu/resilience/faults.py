"""Fault taxonomy + classifier for the device fault domain.

Every failure on a device path (BLS batch verify, epoch sweep, firehose
pipeline stage, TPU probe) is classified into one of four kinds before any
policy decision is made — replacing the bare ``except Exception`` blocks
that used to drop a batch silently:

* ``TRANSIENT``  — host/tunnel hiccup (connection reset, UNAVAILABLE,
  ABORTED): safe to retry in place with jittered backoff.
* ``OOM``        — device allocation failure (RESOURCE_EXHAUSTED,
  ``MemoryError``): retrying the same shape is futile; the degradation
  ladder drops to a reduced batch shape.
* ``HANG``       — a call that blew past its watchdog deadline (the wedged
  TPU tunnel of TPU_WINDOW_LOG fame). The device may still be executing;
  the worker thread cannot be killed, so the supervisor counts the stranded
  thread and demotes.
* ``CORRUPTION`` — a tripped limb-bound assert, NaN, or parity mismatch:
  the device's *numerics* are suspect, so no device rung can be trusted —
  the ladder jumps straight to the native/oracle CPU fallback.

Classification is type-first (``WatchdogTimeout``, ``MemoryError``,
``TimeoutError``, injected faults carry their kind), then marker-based on
the rendered message — XLA surfaces everything as ``XlaRuntimeError`` with
a gRPC-style status prefix, so the text is the only signal available.
Unknown faults default to TRANSIENT: one bounded retry is cheap, and the
ladder below it keeps the verdict honest either way.

Classified faults are appended to a process-global ring (``recent_faults``)
and counted into ``utils.metrics`` so degradation is observable from the
``/metrics`` and ``/health`` surfaces.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum

from ..utils.metrics import RESILIENCE_FAULTS


class FaultKind(str, Enum):
    TRANSIENT = "transient"
    OOM = "oom"
    HANG = "hang"
    CORRUPTION = "corruption"


class WatchdogTimeout(TimeoutError):
    """A supervised call exceeded its watchdog deadline (classified HANG)."""

    def __init__(self, stage: str, deadline_s: float):
        super().__init__(
            f"{stage}: no result within the {deadline_s:.3g}s watchdog deadline"
        )
        self.stage = stage
        self.deadline_s = deadline_s


class SupervisedFault(RuntimeError):
    """Every rung of a supervised ladder failed. Carries the last underlying
    fault; callers treat it as "this work has no trustworthy verdict" (fail
    closed — never a false verify)."""

    def __init__(self, stage: str, last: BaseException | None):
        super().__init__(f"{stage}: all rungs exhausted ({last!r})")
        self.stage = stage
        self.last = last


# marker tables, matched against the lowercased "TypeName: message" render.
# Order matters: oom > hang > corruption > transient — a RESOURCE_EXHAUSTED
# message saying "limit exceeded" is an OOM-shaped status, not a hang, and
# a misread sends the hunter to a BIGGER rung that will OOM again.
_HANG_MARKERS = ("watchdog deadline", "deadline_exceeded", "timed out",
                 "timeout", "hung", "wedged", "exceeded")
_OOM_MARKERS = ("resource_exhausted", "out of memory", "memoryerror",
                "failed to allocate", "allocation failure", "oom")
_CORRUPTION_MARKERS = ("limb bound", "bound assert", "out_bound", "nan",
                       "corrupt", "parity mismatch", "checkify")
_TRANSIENT_MARKERS = ("unavailable", "aborted", "connection", "broken pipe",
                      "internal", "cancelled", "socket", "reset by peer",
                      "transient")


def classify_text(text: str) -> FaultKind:
    """Classify a rendered error message / subprocess note (the hunter's
    probe notes come through here — a subprocess killed by its timeout is
    the out-of-process watchdog firing)."""
    low = text.lower()
    for markers, kind in (
        (_OOM_MARKERS, FaultKind.OOM),
        (_HANG_MARKERS, FaultKind.HANG),
        (_CORRUPTION_MARKERS, FaultKind.CORRUPTION),
        (_TRANSIENT_MARKERS, FaultKind.TRANSIENT),
    ):
        if any(m in low for m in markers):
            return kind
    return FaultKind.TRANSIENT


def classify(exc: BaseException) -> FaultKind:
    """Fault kind for an exception raised on a supervised device path."""
    injected = getattr(exc, "fault_kind", None)  # inject.InjectedFault
    if injected is not None:
        return FaultKind(injected)
    if isinstance(exc, WatchdogTimeout):
        return FaultKind.HANG
    if isinstance(exc, MemoryError):
        return FaultKind.OOM
    if isinstance(exc, (FloatingPointError, AssertionError)):
        return FaultKind.CORRUPTION
    if isinstance(exc, TimeoutError):
        return FaultKind.HANG
    return classify_text(f"{type(exc).__name__}: {exc}")


@dataclass
class FaultRecord:
    """One classified fault event (the structured record that replaces a
    silent drop)."""

    stage: str
    kind: FaultKind
    error: str
    domain: str = ""
    rung: str = ""
    attempt: int = 1
    ts: float = field(default_factory=time.time)
    # OOM faults only: the static-memory model's view of the faulting
    # domain (certified peak bytes, live residency gauge, tier margin) —
    # a demotion report says what the planner predicted
    memory: dict | None = None

    def as_dict(self) -> dict:
        d = {
            "stage": self.stage,
            "kind": self.kind.value,
            "error": self.error,
            "domain": self.domain,
            "rung": self.rung,
            "attempt": self.attempt,
            "ts": self.ts,
        }
        if self.memory is not None:
            d["memory"] = self.memory
        return d


_LOG_DEPTH = 512
_log: deque = deque(maxlen=_LOG_DEPTH)
_log_lock = threading.Lock()


def record_fault(
    stage: str,
    exc: BaseException | str,
    kind: FaultKind | None = None,
    domain: str = "",
    rung: str = "",
    attempt: int = 1,
) -> FaultRecord:
    """Classify + append one fault to the process ring and the metrics
    registry. Returns the record (callers log/propagate it as they like)."""
    if kind is None:
        kind = classify(exc) if isinstance(exc, BaseException) else classify_text(exc)
    err = (
        f"{type(exc).__name__}: {exc}" if isinstance(exc, BaseException) else str(exc)
    )
    mem = None
    if kind is FaultKind.OOM:
        try:
            from ..analysis.memory import fault_memory_context

            mem = fault_memory_context(domain or stage)
        except Exception:  # noqa: BLE001 — enrichment never fails a record
            mem = None
    rec = FaultRecord(
        stage=stage, kind=kind, error=err[:500], domain=domain, rung=rung,
        attempt=attempt, memory=mem,
    )
    with _log_lock:
        _log.append(rec)
    RESILIENCE_FAULTS.inc(domain=domain or stage, stage=stage, kind=kind.value)
    return rec


def recent_faults(n: int = 32) -> list[dict]:
    """Most recent classified faults, newest last (the /health payload)."""
    with _log_lock:
        return [r.as_dict() for r in list(_log)[-n:]]


def clear_fault_log() -> None:
    """Test hook: empty the ring so scenarios assert on their own faults."""
    with _log_lock:
        _log.clear()
