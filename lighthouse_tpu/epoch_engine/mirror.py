"""Device-resident columnar mirror of the validator registry.

The numpy epoch path re-gathers every validator's fields out of Python
objects into ``_Cols`` arrays each epoch — an O(n) interpreted loop that
dwarfs the arithmetic at mainnet scale. The mirror gathers ONCE per state
lineage, keeps the epoch-processing registry columns as device arrays
(struct-of-arrays, including the derived electra ``compounding``-credential
plane), and between epochs applies only the rows the block-level
delta journal (``deltas.py``) marked dirty: a handful of slashings/exits/
deposits per epoch instead of a million-object sweep.

Host numpy shadows of the same columns serve two jobs: computing the dirty
rows' new values without a device round-trip, and diffing kernel outputs so
the post-sweep write-back touches only the Python validator objects that
actually changed. Balances / inactivity / participation live as numpy arrays
on the state already and are re-uploaded wholesale each epoch (a flat
device_put, not an object gather); the mirror accounts every host<->device
byte so the ``--epoch`` bench can report the delta-update traffic.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .deltas import install_journal, journal_of
from .kernels import FAR_FUTURE_EPOCH, bucket

_REG_DTYPES = {
    "effective": np.uint64,
    "slashed": np.bool_,
    "activation": np.uint64,
    "exit": np.uint64,
    "withdrawable": np.uint64,
    "eligibility": np.uint64,
    "compounding": np.bool_,
}

_FIELD_ATTRS = {
    "effective": "effective_balance",
    "slashed": "slashed",
    "activation": "activation_epoch",
    "exit": "exit_epoch",
    "withdrawable": "withdrawable_epoch",
    "eligibility": "activation_eligibility_epoch",
}

# columns derived from validator fields rather than read off an attribute.
# "compounding" feeds the electra per-validator max_effective_balance plane;
# mutation sites that rewrite withdrawal_credentials journal the row
# (switch_to_compounding_validator), and pre-electra credential changes
# (capella 0x00 -> 0x01) never flip the 0x02 test, so delta syncs stay exact.
_DERIVED = {
    "compounding": lambda v: bytes(v.withdrawal_credentials)[:1] == b"\x02",
}

# padding row: an inactive, zero-balance validator that every kernel stage
# provably ignores
_PAD_VALUES = {
    "effective": 0,
    "slashed": False,
    "activation": FAR_FUTURE_EPOCH,
    "exit": FAR_FUTURE_EPOCH,
    "withdrawable": FAR_FUTURE_EPOCH,
    "eligibility": FAR_FUTURE_EPOCH,
    "compounding": False,
}


def _field_value(v, name):
    getter = _DERIVED.get(name)
    if getter is not None:
        return getter(v)
    return getattr(v, _FIELD_ATTRS[name])


@dataclass
class MirrorStats:
    full_syncs: int = 0
    delta_syncs: int = 0
    dirty_rows: int = 0
    host_to_device_bytes: int = 0
    device_to_host_bytes: int = 0
    epochs: int = 0
    last_host_to_device_bytes: int = 0
    writeback_rows: int = 0

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class RegistryMirror:
    """Columnar registry mirror bound to one state object's lifetime."""

    def __init__(self, sharding=None):
        self.n = 0
        self.n_pad = 0
        self.device: dict = {}  # name -> jax array (padded)
        self.shadow: dict[str, np.ndarray] = {}  # name -> numpy (padded)
        self.sharding = sharding
        self.stats = MirrorStats()
        self._pubkey_map: dict[bytes, int] | None = None
        self._pubkey_n = 0

    # -- host<->device helpers -------------------------------------------

    def _put(self, arr: np.ndarray):
        import jax

        self.stats.host_to_device_bytes += arr.nbytes
        self.stats.last_host_to_device_bytes += arr.nbytes
        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(arr)

    def pad_and_put(self, arr: np.ndarray, fill=0):
        """Pad a per-validator host array to the shape bucket and upload
        (the per-epoch balances/participation/inactivity path)."""
        if arr.shape[0] != self.n_pad:
            padded = np.full(self.n_pad, fill, dtype=arr.dtype)
            padded[: arr.shape[0]] = arr
            arr = padded
        return self._put(arr)

    def put_aux(self, arr: np.ndarray):
        """Upload a small non-validator-axis array (the electra pending-queue
        columns): replicated across the mesh when the mirror shards the
        validator axis, so queue gathers do not force a resharding."""
        import jax

        self.stats.host_to_device_bytes += arr.nbytes
        self.stats.last_host_to_device_bytes += arr.nbytes
        if self.sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                arr, NamedSharding(self.sharding.mesh, PartitionSpec())
            )
        return jax.device_put(arr)

    def pubkey_map(self, state) -> dict[bytes, int]:
        """Lazy pubkey -> validator-index map over this mirror's state
        lineage (the registry is append-only and pubkeys are immutable, so
        the map only ever extends)."""
        vs = state.validators
        m = self._pubkey_map
        if m is None:
            m = {}
            self._pubkey_map = m
            self._pubkey_n = 0
        for i in range(self._pubkey_n, len(vs)):
            m[bytes(vs[i].pubkey)] = i
        self._pubkey_n = len(vs)
        return m

    # -- sync -------------------------------------------------------------

    def sync(self, state) -> None:
        """Bring the device registry columns up to date with the state's
        Python validator objects, by journal deltas when possible."""
        self.stats.last_host_to_device_bytes = 0
        vs = state.validators
        n = len(vs)
        j = journal_of(state)
        if not self.device or j is None or not j.valid or n < j.n_base:
            self._full_gather(state, n)
            return
        dirty = sorted(j.dirty.union(range(j.n_base, n)))
        dirty = [i for i in dirty if i < n]
        if n > self.n_pad:
            self._regrow(n)
        if dirty:
            self._apply_rows(vs, dirty)
        self.n = n
        j.reset(n)
        self.stats.delta_syncs += 1
        self.stats.dirty_rows += len(dirty)

    def _full_gather(self, state, n: int) -> None:
        vs = state.validators
        self.n = n
        self.n_pad = bucket(n)
        for name, dt in _REG_DTYPES.items():
            col = np.full(self.n_pad, _PAD_VALUES[name], dtype=dt)
            col[:n] = [_field_value(v, name) for v in vs]
            self.shadow[name] = col
            self.device[name] = self._put(col)
        j = journal_of(state)
        if j is None:
            install_journal(state, n)
        else:
            j.reset(n)
        self.stats.full_syncs += 1
        self._set_resident_gauge()

    def _regrow(self, n: int) -> None:
        new_pad = bucket(n)
        for name, dt in _REG_DTYPES.items():
            col = np.full(new_pad, _PAD_VALUES[name], dtype=dt)
            col[: self.n_pad] = self.shadow[name]
            self.shadow[name] = col
            self.device[name] = self._put(col)
        self.n_pad = new_pad
        self._set_resident_gauge()

    def _set_resident_gauge(self) -> None:
        from ..utils import metrics

        metrics.EPOCH_MIRROR_BYTES.set(
            sum(col.nbytes for col in self.shadow.values())
        )

    def _apply_rows(self, vs, rows: list[int]) -> None:
        idx = np.asarray(rows, dtype=np.int64)
        for name, dt in _REG_DTYPES.items():
            vals = np.asarray(
                [_field_value(vs[i], name) for i in rows], dtype=dt
            )
            self.shadow[name][idx] = vals
            self.device[name] = (
                self.device[name].at[idx].set(vals)
            )
            self.stats.host_to_device_bytes += vals.nbytes + idx.nbytes
            self.stats.last_host_to_device_bytes += vals.nbytes + idx.nbytes

    # -- post-sweep write-back --------------------------------------------

    def apply_outputs(self, state, outs: dict) -> None:
        """Adopt the kernel's new registry columns as the device-resident
        truth and write back only the changed rows to the Python objects."""
        vs = state.validators
        n = self.n
        changed_total = 0
        for name in _REG_DTYPES:
            if name not in outs:
                continue
            new_dev = outs[name]
            # owned host copy: the shadow must stay scatter-writable for the
            # next delta sync (views of device buffers are read-only)
            new_host = np.asarray(new_dev).copy()
            self.stats.device_to_host_bytes += new_host.nbytes
            old = self.shadow[name]
            changed = np.nonzero(new_host[:n] != old[:n])[0]
            if changed.size:
                attr = _FIELD_ATTRS[name]
                cast = bool if name == "slashed" else int
                for i in changed:
                    setattr(vs[int(i)], attr, cast(new_host[i]))
                changed_total += int(changed.size)
            self.shadow[name] = new_host
            self.device[name] = new_dev
        self.stats.writeback_rows += changed_total
        self.stats.epochs += 1
        j = journal_of(state)
        if j is not None:
            j.reset(n)
