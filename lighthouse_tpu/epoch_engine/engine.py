"""Single-pass device epoch processing behind ``process_epoch``.

Orchestrates one epoch boundary on the accelerator: sync the registry mirror
(delta scatter or first-bind gather), upload the flat per-epoch columns
(balances, inactivity, participation — or the phase0 attestation masks),
launch the fused sweep (kernels.py), apply the scalar justification /
finalization decisions to the checkpoint objects, write back the changed
registry rows, and run the residual host-side stages (vote/slashings/randao
resets, historical accumulators, participation rotation, sync-committee
rotation) in exactly the numpy path's order. Everything per-validator is the
one jitted kernel; everything here is O(changed rows + attestations).

Fork coverage: phase0, the altair family (altair/bellatrix/capella/deneb —
they share the participation-flag epoch transition and differ only in
constants baked into ``EpochConsts``), and electra. The electra sweep adds
the EIP-7251 stages on-device (balance-churned registry updates, the
pending-deposit cumulative sum with its scatter-add, the consolidation
scan, per-validator effective-balance caps); the only residual host work is
the part that cannot live on the validator axis — appending brand-new
validators for unknown-pubkey deposits (BLS proof-of-possession included)
and rebuilding the pending queues from the kernel's stop positions.
"""

from __future__ import annotations

import numpy as np

from .kernels import consts_for, queue_bucket, run_sweep
from .mirror import RegistryMirror

_SUPPORTED_FORKS = (
    "phase0", "altair", "bellatrix", "capella", "deneb", "electra",
)

_MIRROR_ATTR = "_epoch_mirror"


def supported_fork(fork: str) -> bool:
    return fork in _SUPPORTED_FORKS


def mirror_of(state, create: bool = False,
              sharding=None) -> RegistryMirror | None:
    m = getattr(state, _MIRROR_ATTR, None)
    if m is None and create:
        m = RegistryMirror(sharding=sharding)
        object.__setattr__(state, _MIRROR_ATTR, m)
    return m


def prepare_state(state, sharding=None) -> RegistryMirror | None:
    """Bind a mirror + delta journal ahead of the first epoch boundary so
    block processing starts journaling immediately (state_advance / chain
    warm-up hook). No-op for forks the kernel does not cover — an electra
    state would otherwise pay a full registry gather every epoch only for
    process_epoch_on_device to refuse it and the numpy path to invalidate
    the journal again."""
    if not supported_fork(getattr(state, "fork_name", "phase0")):
        return None
    m = mirror_of(state, create=True, sharding=sharding)
    if sharding is not None:
        m.sharding = sharding
    m.sync(state)
    return m


def _device_sweep(spec, state, sharding):
    """The DEVICE region of one epoch boundary: mirror bind/sync, column
    upload, the fused sweep, and full materialization of its outputs back
    to host numpy. No ``state`` mutation happens in here — materializing
    inside the supervised region means an async device fault surfaces
    *before* any host-side write-back, so a faulted boundary leaves the
    state byte-identical and the numpy path can take over (demotion
    parity)."""
    from ..state_transition.beacon_state_util import get_current_epoch

    fork = getattr(state, "fork_name", "phase0")
    mirror = mirror_of(state, create=True, sharding=sharding)
    mirror.sync(state)

    consts = consts_for(spec, fork)
    cur_ep = get_current_epoch(spec, state)
    cols = dict(mirror.device)
    cols["balances"] = mirror.pad_and_put(
        np.asarray(state.balances, dtype=np.uint64)
    )
    if fork == "phase0":
        _phase0_host_columns(spec, state, mirror, cols)
    else:
        cols["inactivity"] = mirror.pad_and_put(
            np.asarray(state.inactivity_scores, dtype=np.uint64)
        )
        cols["prev_part"] = mirror.pad_and_put(
            np.asarray(state.previous_epoch_participation, dtype=np.uint8)
        )
        cols["cur_part"] = mirror.pad_and_put(
            np.asarray(state.current_epoch_participation, dtype=np.uint8)
        )

    bits = np.asarray(state.justification_bits, dtype=bool)
    scalars = {
        "cur_epoch": np.uint64(cur_ep),
        "finalized_epoch": np.uint64(state.finalized_checkpoint.epoch),
        "prev_justified_epoch": np.uint64(
            state.previous_justified_checkpoint.epoch
        ),
        "cur_justified_epoch": np.uint64(
            state.current_justified_checkpoint.epoch
        ),
        "bits": bits.copy(),
        "slash_sum": np.uint64(
            int(np.asarray(state.slashings, dtype=np.uint64).sum())
        ),
    }
    if consts.family == "electra":
        _electra_queue_columns(state, mirror, consts, cols)
        scalars["earliest_exit_epoch"] = np.uint64(
            state.earliest_exit_epoch
        )
        scalars["exit_balance_to_consume"] = np.uint64(
            state.exit_balance_to_consume
        )
        scalars["deposit_balance_to_consume"] = np.uint64(
            state.deposit_balance_to_consume
        )
        scalars["eth1_deposit_index"] = np.uint64(state.eth1_deposit_index)
        scalars["deposit_requests_start_index"] = np.uint64(
            state.deposit_requests_start_index
        )

    outs = run_sweep(consts, cols, scalars)
    # force completion (keeping outputs device-resident for the mirror):
    # a deferred device error must fault HERE, inside the supervised
    # region, not during state write-back
    for v in outs.values():
        ready = getattr(v, "block_until_ready", None)
        if ready is not None:
            ready()
    return mirror, outs


def process_epoch_on_device(spec, state, sharding=None) -> bool:
    """Run one epoch transition through the device engine. Returns False
    (state untouched) when the state's fork family is not kernelized, when
    the ``epoch_device`` fault domain has the backend quarantined, or when
    the sweep faults — the numpy path then handles this boundary (the
    degradation ladder's device -> numpy demotion), and the supervisor's
    probation logic re-promotes the device backend later."""
    fork = getattr(state, "fork_name", "phase0")
    if not supported_fork(fork):
        return False
    from ..resilience import SupervisedFault, epoch_supervisor

    sup = epoch_supervisor()
    if not sup.device_allowed():
        sup.note_fallback(rung="numpy")
        return False
    try:
        mirror, outs = sup.run(
            "epoch.sweep", lambda: _device_sweep(spec, state, sharding)
        )
    except SupervisedFault:
        # device state is indeterminate: drop the mirror so a later attempt
        # re-binds from scratch, and let the numpy path own this boundary
        if getattr(state, _MIRROR_ATTR, None) is not None:
            object.__delattr__(state, _MIRROR_ATTR)
        sup.note_fallback(rung="numpy")
        return False

    _apply_justification(spec, state, outs)
    n = mirror.n
    state.balances = np.asarray(outs["balances"])[:n].copy()
    if fork != "phase0":
        state.inactivity_scores = np.asarray(outs["inactivity"])[:n].copy()
        mirror.stats.device_to_host_bytes += n * 8
    mirror.stats.device_to_host_bytes += n * 8
    mirror.apply_outputs(state, outs)

    from ..types.spec import fork_at_least

    if fork_at_least(fork, "electra"):
        _electra_host_finish(spec, state, mirror, outs)
    _host_tail(spec, state, fork)
    return True


# =============================================================================
# host-side stages
# =============================================================================


class _MaskCols:
    """The slice of ``per_epoch._Cols`` that ``_attesting_mask`` reads,
    served from the mirror's host shadows — no Python-object re-gather."""

    def __init__(self, mirror):
        self.n = mirror.n
        self.slashed = mirror.shadow["slashed"][: mirror.n]


def _phase0_host_columns(spec, state, mirror, cols) -> None:
    """Resolve phase0 pending attestations into per-validator columns: the
    unslashed source/target/head masks and the earliest-inclusion
    (delay, proposer) pair — the only stage that must walk attestations."""
    from ..state_transition.per_epoch import (
        _attesting_mask,
        _matching_attestations,
        _matching_head_attestations,
        _matching_target_attestations,
    )
    from ..state_transition.beacon_state_util import (
        get_attesting_indices,
        get_current_epoch,
    )

    hcols = _MaskCols(mirror)
    cur_ep = get_current_epoch(spec, state)
    n = hcols.n
    zeros = np.zeros(n, dtype=bool)
    prev_ep = max(cur_ep - 1, 0)
    cur_tgt = (
        _attesting_mask(
            spec, state,
            _matching_target_attestations(spec, state, cur_ep), hcols,
        )
        if cur_ep > 1
        else zeros
    )
    if cur_ep > 0:
        src_atts = _matching_attestations(spec, state, prev_ep)
        src = _attesting_mask(spec, state, src_atts, hcols)
        tgt = _attesting_mask(
            spec, state,
            _matching_target_attestations(spec, state, prev_ep), hcols,
        )
        head = _attesting_mask(
            spec, state,
            _matching_head_attestations(spec, state, prev_ep), hcols,
        )
    else:
        src_atts = []
        src = tgt = head = zeros
    earliest: dict[int, tuple[int, int]] = {}
    for a in src_atts:
        idx = get_attesting_indices(spec, state, a.data, a.aggregation_bits)
        for i in idx:
            i = int(i)
            cand = (int(a.inclusion_delay), int(a.proposer_index))
            if i not in earliest or cand[0] < earliest[i][0]:
                earliest[i] = cand
    incl_delay = np.ones(n, dtype=np.uint64)
    incl_proposer = np.zeros(n, dtype=np.int32)
    has_incl = np.zeros(n, dtype=bool)
    for i, (delay, proposer) in earliest.items():
        incl_delay[i] = delay
        incl_proposer[i] = proposer
        has_incl[i] = True
    cols["src_mask"] = mirror.pad_and_put(src, fill=False)
    cols["tgt_mask"] = mirror.pad_and_put(tgt, fill=False)
    cols["head_mask"] = mirror.pad_and_put(head, fill=False)
    cols["cur_tgt_mask"] = mirror.pad_and_put(cur_tgt, fill=False)
    cols["incl_delay"] = mirror.pad_and_put(incl_delay, fill=1)
    cols["incl_proposer"] = mirror.pad_and_put(incl_proposer, fill=0)
    cols["has_incl"] = mirror.pad_and_put(has_incl, fill=False)


class _MirrorPubkeyCtxt:
    """``lookup_pubkey_index`` context backed by the mirror's lazy pubkey
    map — the map auto-extends over registry appends, so a second pending
    deposit for a pubkey the previous one just added resolves to the new
    index exactly like the numpy twin's linear scan."""

    def __init__(self, mirror):
        self._mirror = mirror

    def lookup_pubkey_index(self, state, pubkey):
        return self._mirror.pubkey_map(state).get(bytes(pubkey))


def _electra_queue_columns(state, mirror, consts, cols) -> None:
    """Upload the electra pending-queue columns. Only the first
    MAX_PENDING_DEPOSITS_PER_EPOCH deposits can ever be examined by the
    sweep (every loop iteration advances the capped position counter), so
    the deposit columns are a FIXED shape — zero steady-state recompiles
    regardless of queue depth. Pubkeys resolve host-side against the
    mirror's map; unknown pubkeys (-1) are flagged for host application."""
    maxq = consts.max_pending_deposits_per_epoch
    pending = list(state.pending_deposits)[:maxq]
    dep_amount = np.zeros(maxq, dtype=np.uint64)
    dep_slot = np.zeros(maxq, dtype=np.uint64)
    dep_index = np.full(maxq, -1, dtype=np.int32)
    dep_valid = np.zeros(maxq, dtype=bool)
    if pending:
        pkmap = mirror.pubkey_map(state)
        for i, d in enumerate(pending):
            dep_amount[i] = int(d.amount)
            dep_slot[i] = int(d.slot)
            dep_index[i] = pkmap.get(bytes(d.pubkey), -1)
            dep_valid[i] = True
    cols["dep_amount"] = mirror.put_aux(dep_amount)
    cols["dep_slot"] = mirror.put_aux(dep_slot)
    cols["dep_index"] = mirror.put_aux(dep_index)
    cols["dep_valid"] = mirror.put_aux(dep_valid)

    cons = list(state.pending_consolidations)
    qc = queue_bucket(len(cons))
    con_src = np.zeros(qc, dtype=np.int32)
    con_tgt = np.zeros(qc, dtype=np.int32)
    con_valid = np.zeros(qc, dtype=bool)
    for i, c in enumerate(cons):
        con_src[i] = int(c.source_index)
        con_tgt[i] = int(c.target_index)
        con_valid[i] = True
    cols["con_src"] = mirror.put_aux(con_src)
    cols["con_tgt"] = mirror.put_aux(con_tgt)
    cols["con_valid"] = mirror.put_aux(con_valid)


def _electra_host_finish(spec, state, mirror, outs) -> None:
    """The residual host half of the electra stages, after the mirror
    write-back: apply unknown-pubkey deposits in queue order (registry
    appends + proof-of-possession checks), run the hysteresis update for
    rows appended after the kernel's effective-balance stage ran, rebuild
    the pending queues from the kernel's stop positions, and land the
    scalar churn carries."""
    from ..state_transition.electra import (
        apply_pending_deposit,
        get_max_effective_balance,
    )

    s = int(outs["dep_stop"])
    postponed = np.asarray(outs["dep_postponed"])
    host_mask = np.asarray(outs["dep_host"])
    pending = list(state.pending_deposits)
    n_pre = len(state.validators)
    ctxt = _MirrorPubkeyCtxt(mirror)
    for i in range(s):
        if host_mask[i]:
            apply_pending_deposit(spec, state, pending[i], ctxt)
    # hysteresis for appended validators (effective starts at 0; the numpy
    # twin's effective-balance loop runs after deposits and fixes them up)
    inc = spec.effective_balance_increment
    down = inc // 4
    up = inc // 4 * 5
    bal = np.asarray(state.balances, dtype=np.uint64)
    for i in range(n_pre, len(state.validators)):
        v = state.validators[i]
        b = int(bal[i])
        if b + down < int(v.effective_balance) or (
            int(v.effective_balance) + up < b
        ):
            v.effective_balance = min(
                b - b % inc, get_max_effective_balance(spec, v)
            )
    state.pending_deposits = pending[s:] + [
        pending[i] for i in range(s) if postponed[i]
    ]
    state.deposit_balance_to_consume = int(outs["dep_btc"])
    state.pending_consolidations = list(state.pending_consolidations)[
        int(outs["cons_consumed"]):
    ]
    if bool(outs["has_ejection"]):
        state.earliest_exit_epoch = int(outs["earliest_exit"])
        state.exit_balance_to_consume = int(outs["exit_btc"])


def _apply_justification(spec, state, outs) -> None:
    """Scalar checkpoint bookkeeping from the kernel's decision flags, in
    _weigh_justification_and_finalization's exact order."""
    if not bool(outs["do_just"]):
        return
    from ..state_transition.beacon_state_util import (
        get_block_root,
        get_current_epoch,
        get_previous_epoch,
    )
    from ..types.containers import Checkpoint

    prev_ep = get_previous_epoch(spec, state)
    cur_ep = get_current_epoch(spec, state)
    old_prev = state.previous_justified_checkpoint
    old_cur = state.current_justified_checkpoint
    state.previous_justified_checkpoint = state.current_justified_checkpoint
    if bool(outs["cj_prev"]):
        state.current_justified_checkpoint = Checkpoint(
            epoch=prev_ep, root=get_block_root(spec, state, prev_ep)
        )
    if bool(outs["cj_cur"]):
        state.current_justified_checkpoint = Checkpoint(
            epoch=cur_ep, root=get_block_root(spec, state, cur_ep)
        )
    state.justification_bits = np.asarray(outs["bits"], dtype=bool).copy()
    sel = int(outs["fin_sel"])
    if sel == 1:
        state.finalized_checkpoint = old_prev
    elif sel == 2:
        state.finalized_checkpoint = old_cur


def _host_tail(spec, state, fork: str) -> None:
    """The non-validator-axis epoch stages, in the numpy path's order."""
    from ..state_transition import per_epoch as pe

    pe.process_eth1_data_reset(spec, state)
    pe.process_slashings_reset(spec, state)
    pe.process_randao_mixes_reset(spec, state)
    pe.process_historical_roots_update(spec, state)
    if fork == "phase0":
        state.previous_epoch_attestations = list(
            state.current_epoch_attestations
        )
        state.current_epoch_attestations = []
    else:
        pe.process_participation_flag_updates(spec, state)
        pe.process_sync_committee_updates(spec, state)
