"""Single-pass device epoch processing behind ``process_epoch``.

Orchestrates one epoch boundary on the accelerator: sync the registry mirror
(delta scatter or first-bind gather), upload the flat per-epoch columns
(balances, inactivity, participation — or the phase0 attestation masks),
launch the fused sweep (kernels.py), apply the scalar justification /
finalization decisions to the checkpoint objects, write back the changed
registry rows, and run the residual host-side stages (vote/slashings/randao
resets, historical accumulators, participation rotation, sync-committee
rotation) in exactly the numpy path's order. Everything per-validator is the
one jitted kernel; everything here is O(changed rows + attestations).

Fork coverage: phase0 and the altair family (altair/bellatrix/capella/deneb
— they share the participation-flag epoch transition and differ only in
constants baked into ``EpochConsts``). Electra's pending-deposit /
consolidation sweeps are not kernelized; those states fall back to numpy.
"""

from __future__ import annotations

import numpy as np

from .kernels import consts_for, run_sweep
from .mirror import RegistryMirror

_SUPPORTED_FORKS = ("phase0", "altair", "bellatrix", "capella", "deneb")

_MIRROR_ATTR = "_epoch_mirror"


def supported_fork(fork: str) -> bool:
    return fork in _SUPPORTED_FORKS


def mirror_of(state, create: bool = False,
              sharding=None) -> RegistryMirror | None:
    m = getattr(state, _MIRROR_ATTR, None)
    if m is None and create:
        m = RegistryMirror(sharding=sharding)
        object.__setattr__(state, _MIRROR_ATTR, m)
    return m


def prepare_state(state, sharding=None) -> RegistryMirror | None:
    """Bind a mirror + delta journal ahead of the first epoch boundary so
    block processing starts journaling immediately (state_advance / chain
    warm-up hook). No-op for forks the kernel does not cover — an electra
    state would otherwise pay a full registry gather every epoch only for
    process_epoch_on_device to refuse it and the numpy path to invalidate
    the journal again."""
    if not supported_fork(getattr(state, "fork_name", "phase0")):
        return None
    m = mirror_of(state, create=True, sharding=sharding)
    if sharding is not None:
        m.sharding = sharding
    m.sync(state)
    return m


def _device_sweep(spec, state, sharding):
    """The DEVICE region of one epoch boundary: mirror bind/sync, column
    upload, the fused sweep, and full materialization of its outputs back
    to host numpy. No ``state`` mutation happens in here — materializing
    inside the supervised region means an async device fault surfaces
    *before* any host-side write-back, so a faulted boundary leaves the
    state byte-identical and the numpy path can take over (demotion
    parity)."""
    from ..state_transition.beacon_state_util import get_current_epoch

    fork = getattr(state, "fork_name", "phase0")
    mirror = mirror_of(state, create=True, sharding=sharding)
    mirror.sync(state)

    consts = consts_for(spec, fork)
    cur_ep = get_current_epoch(spec, state)
    cols = dict(mirror.device)
    cols["balances"] = mirror.pad_and_put(
        np.asarray(state.balances, dtype=np.uint64)
    )
    if fork == "phase0":
        _phase0_host_columns(spec, state, mirror, cols)
    else:
        cols["inactivity"] = mirror.pad_and_put(
            np.asarray(state.inactivity_scores, dtype=np.uint64)
        )
        cols["prev_part"] = mirror.pad_and_put(
            np.asarray(state.previous_epoch_participation, dtype=np.uint8)
        )
        cols["cur_part"] = mirror.pad_and_put(
            np.asarray(state.current_epoch_participation, dtype=np.uint8)
        )

    bits = np.asarray(state.justification_bits, dtype=bool)
    scalars = {
        "cur_epoch": np.uint64(cur_ep),
        "finalized_epoch": np.uint64(state.finalized_checkpoint.epoch),
        "prev_justified_epoch": np.uint64(
            state.previous_justified_checkpoint.epoch
        ),
        "cur_justified_epoch": np.uint64(
            state.current_justified_checkpoint.epoch
        ),
        "bits": bits.copy(),
        "slash_sum": np.uint64(
            int(np.asarray(state.slashings, dtype=np.uint64).sum())
        ),
    }

    outs = run_sweep(consts, cols, scalars)
    # force completion (keeping outputs device-resident for the mirror):
    # a deferred device error must fault HERE, inside the supervised
    # region, not during state write-back
    for v in outs.values():
        ready = getattr(v, "block_until_ready", None)
        if ready is not None:
            ready()
    return mirror, outs


def process_epoch_on_device(spec, state, sharding=None) -> bool:
    """Run one epoch transition through the device engine. Returns False
    (state untouched) when the state's fork family is not kernelized, when
    the ``epoch_device`` fault domain has the backend quarantined, or when
    the sweep faults — the numpy path then handles this boundary (the
    degradation ladder's device -> numpy demotion), and the supervisor's
    probation logic re-promotes the device backend later."""
    fork = getattr(state, "fork_name", "phase0")
    if not supported_fork(fork):
        return False
    from ..resilience import SupervisedFault, epoch_supervisor

    sup = epoch_supervisor()
    if not sup.device_allowed():
        sup.note_fallback(rung="numpy")
        return False
    try:
        mirror, outs = sup.run(
            "epoch.sweep", lambda: _device_sweep(spec, state, sharding)
        )
    except SupervisedFault:
        # device state is indeterminate: drop the mirror so a later attempt
        # re-binds from scratch, and let the numpy path own this boundary
        if getattr(state, _MIRROR_ATTR, None) is not None:
            object.__delattr__(state, _MIRROR_ATTR)
        sup.note_fallback(rung="numpy")
        return False

    _apply_justification(spec, state, outs)
    n = mirror.n
    state.balances = np.asarray(outs["balances"])[:n].copy()
    if fork != "phase0":
        state.inactivity_scores = np.asarray(outs["inactivity"])[:n].copy()
        mirror.stats.device_to_host_bytes += n * 8
    mirror.stats.device_to_host_bytes += n * 8
    mirror.apply_outputs(state, outs)

    _host_tail(spec, state, fork)
    return True


# =============================================================================
# host-side stages
# =============================================================================


class _MaskCols:
    """The slice of ``per_epoch._Cols`` that ``_attesting_mask`` reads,
    served from the mirror's host shadows — no Python-object re-gather."""

    def __init__(self, mirror):
        self.n = mirror.n
        self.slashed = mirror.shadow["slashed"][: mirror.n]


def _phase0_host_columns(spec, state, mirror, cols) -> None:
    """Resolve phase0 pending attestations into per-validator columns: the
    unslashed source/target/head masks and the earliest-inclusion
    (delay, proposer) pair — the only stage that must walk attestations."""
    from ..state_transition.per_epoch import (
        _attesting_mask,
        _matching_attestations,
        _matching_head_attestations,
        _matching_target_attestations,
    )
    from ..state_transition.beacon_state_util import (
        get_attesting_indices,
        get_current_epoch,
    )

    hcols = _MaskCols(mirror)
    cur_ep = get_current_epoch(spec, state)
    n = hcols.n
    zeros = np.zeros(n, dtype=bool)
    prev_ep = max(cur_ep - 1, 0)
    cur_tgt = (
        _attesting_mask(
            spec, state,
            _matching_target_attestations(spec, state, cur_ep), hcols,
        )
        if cur_ep > 1
        else zeros
    )
    if cur_ep > 0:
        src_atts = _matching_attestations(spec, state, prev_ep)
        src = _attesting_mask(spec, state, src_atts, hcols)
        tgt = _attesting_mask(
            spec, state,
            _matching_target_attestations(spec, state, prev_ep), hcols,
        )
        head = _attesting_mask(
            spec, state,
            _matching_head_attestations(spec, state, prev_ep), hcols,
        )
    else:
        src_atts = []
        src = tgt = head = zeros
    earliest: dict[int, tuple[int, int]] = {}
    for a in src_atts:
        idx = get_attesting_indices(spec, state, a.data, a.aggregation_bits)
        for i in idx:
            i = int(i)
            cand = (int(a.inclusion_delay), int(a.proposer_index))
            if i not in earliest or cand[0] < earliest[i][0]:
                earliest[i] = cand
    incl_delay = np.ones(n, dtype=np.uint64)
    incl_proposer = np.zeros(n, dtype=np.int32)
    has_incl = np.zeros(n, dtype=bool)
    for i, (delay, proposer) in earliest.items():
        incl_delay[i] = delay
        incl_proposer[i] = proposer
        has_incl[i] = True
    cols["src_mask"] = mirror.pad_and_put(src, fill=False)
    cols["tgt_mask"] = mirror.pad_and_put(tgt, fill=False)
    cols["head_mask"] = mirror.pad_and_put(head, fill=False)
    cols["cur_tgt_mask"] = mirror.pad_and_put(cur_tgt, fill=False)
    cols["incl_delay"] = mirror.pad_and_put(incl_delay, fill=1)
    cols["incl_proposer"] = mirror.pad_and_put(incl_proposer, fill=0)
    cols["has_incl"] = mirror.pad_and_put(has_incl, fill=False)


def _apply_justification(spec, state, outs) -> None:
    """Scalar checkpoint bookkeeping from the kernel's decision flags, in
    _weigh_justification_and_finalization's exact order."""
    if not bool(outs["do_just"]):
        return
    from ..state_transition.beacon_state_util import (
        get_block_root,
        get_current_epoch,
        get_previous_epoch,
    )
    from ..types.containers import Checkpoint

    prev_ep = get_previous_epoch(spec, state)
    cur_ep = get_current_epoch(spec, state)
    old_prev = state.previous_justified_checkpoint
    old_cur = state.current_justified_checkpoint
    state.previous_justified_checkpoint = state.current_justified_checkpoint
    if bool(outs["cj_prev"]):
        state.current_justified_checkpoint = Checkpoint(
            epoch=prev_ep, root=get_block_root(spec, state, prev_ep)
        )
    if bool(outs["cj_cur"]):
        state.current_justified_checkpoint = Checkpoint(
            epoch=cur_ep, root=get_block_root(spec, state, cur_ep)
        )
    state.justification_bits = np.asarray(outs["bits"], dtype=bool).copy()
    sel = int(outs["fin_sel"])
    if sel == 1:
        state.finalized_checkpoint = old_prev
    elif sel == 2:
        state.finalized_checkpoint = old_cur


def _host_tail(spec, state, fork: str) -> None:
    """The non-validator-axis epoch stages, in the numpy path's order."""
    from ..state_transition import per_epoch as pe

    pe.process_eth1_data_reset(spec, state)
    pe.process_slashings_reset(spec, state)
    pe.process_randao_mixes_reset(spec, state)
    pe.process_historical_roots_update(spec, state)
    if fork == "phase0":
        state.previous_epoch_attestations = list(
            state.current_epoch_attestations
        )
        state.current_epoch_attestations = []
    else:
        pe.process_participation_flag_updates(spec, state)
        pe.process_sync_committee_updates(spec, state)
