"""Block-level delta journal for the device-resident registry mirror.

The mirror (``mirror.py``) keeps the validator registry's epoch-processing
columns as device arrays. Between epochs, block processing mutates a handful
of validators (slashings, exits, deposits); re-gathering the whole registry
from Python objects each epoch would throw the device residency away. The
journal records exactly which validator indices were touched so the next
``RegistryMirror.sync`` scatters only those rows host→device.

The journal is attached to the state object itself (``state._registry_deltas``
— the same per-state convention as ``_committee_caches``), so ``state.copy()``
drops it and a copied state triggers a clean full re-gather. Mutation sites
that cannot be attributed to a single index (fork upgrades, the numpy epoch
path's field loops) call ``invalidate_registry_journal`` instead, forcing the
next sync to re-gather.

Import-light on purpose: no jax here — the journal marks run on every block
whether or not the device backend is active.
"""

from __future__ import annotations

_ATTR = "_registry_deltas"

# Past this many dirty rows a full columnar re-gather is cheaper than the
# per-row scatter bookkeeping; the journal degrades to "invalid" (full sync).
_MAX_TRACKED = 8192


class RegistryDeltaJournal:
    __slots__ = ("dirty", "valid", "n_base")

    def __init__(self, n_validators: int):
        self.dirty: set[int] = set()
        self.valid = True
        self.n_base = n_validators  # registry length at last sync

    def mark(self, index: int) -> None:
        if not self.valid:
            return
        self.dirty.add(int(index))
        if len(self.dirty) > _MAX_TRACKED:
            self.invalidate()

    def invalidate(self) -> None:
        self.valid = False
        self.dirty.clear()

    def reset(self, n_validators: int) -> None:
        self.dirty.clear()
        self.valid = True
        self.n_base = n_validators


def journal_of(state) -> RegistryDeltaJournal | None:
    return getattr(state, _ATTR, None)


def install_journal(state, n_validators: int) -> RegistryDeltaJournal:
    j = RegistryDeltaJournal(n_validators)
    object.__setattr__(state, _ATTR, j)
    return j


def mark_registry_delta(state, index: int) -> None:
    """Record that ``state.validators[index]`` was mutated (cheap no-op when
    no mirror is bound to this state)."""
    j = getattr(state, _ATTR, None)
    if j is not None:
        j.mark(index)


def invalidate_registry_journal(state) -> None:
    """Force the next mirror sync to re-gather (bulk/untracked mutation)."""
    j = getattr(state, _ATTR, None)
    if j is not None:
        j.invalidate()
