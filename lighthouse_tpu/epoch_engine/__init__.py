"""Device-resident epoch engine: jit/sharded single-pass epoch processing.

The backend seam mirrors ``lighthouse_tpu.bls``: a module-level registry
selected by ``set_backend`` or the ``LIGHTHOUSE_EPOCH_BACKEND`` environment
variable, with everything above it (``per_epoch.process_epoch``, and through
it ``state_advance``/``beacon_chain``) backend-blind.

Backends:

* ``numpy``  — the columnar host path in ``state_transition/per_epoch.py``.
* ``device`` — the fused jitted sweep (``engine.py`` + ``kernels.py``) over
  a device-resident registry mirror (``mirror.py``). Covers every fork
  through electra (three kernel families: phase0 / altair-like / electra
  with its pending-deposit + consolidation queue stages); a fork newer
  than the kernel families falls back to numpy per-state.
* ``auto``   — the default: ``device`` when an accelerator platform (tpu/
  gpu) backs JAX, ``numpy`` otherwise, so CPU-only test tiers never pay
  kernel compiles they didn't ask for.

This module stays import-light (no jax) — the journal marks in block
processing must stay free when the engine is off.
"""

from __future__ import annotations

import os

from .deltas import (  # noqa: F401 — re-exported for the mutation sites
    invalidate_registry_journal,
    journal_of,
    mark_registry_delta,
)

_BACKEND = os.environ.get("LIGHTHOUSE_EPOCH_BACKEND", "auto")
_AUTO_DECISION: bool | None = None


def set_backend(name: str) -> None:
    global _BACKEND, _AUTO_DECISION
    if name not in ("auto", "device", "numpy"):
        raise ValueError(f"unknown epoch backend {name!r}")
    _BACKEND = name
    _AUTO_DECISION = None


def get_backend() -> str:
    return _BACKEND


def _accelerator_present() -> bool:
    """auto-mode probe, memoized: is JAX backed by an accelerator? Never
    *initiates* a device tunnel probe beyond what jax.devices() implies —
    callers in CPU-only tiers have already pinned JAX_PLATFORMS=cpu."""
    global _AUTO_DECISION
    if _AUTO_DECISION is None:
        try:
            import jax

            _AUTO_DECISION = jax.devices()[0].platform in ("tpu", "gpu")
        except Exception:  # noqa: BLE001 — no jax / no devices: numpy path
            _AUTO_DECISION = False
    return _AUTO_DECISION


def device_backend_active() -> bool:
    if _BACKEND == "numpy":
        return False
    if _BACKEND == "device":
        return True
    return _accelerator_present()


def maybe_process_epoch_on_device(spec, state, sharding=None) -> bool:
    """The ``process_epoch`` seam: True when the device engine fully handled
    the epoch transition, False when the numpy path should run.

    The device engine runs inside the ``epoch_device`` fault domain
    (resilience.supervisor): a faulted or quarantined sweep returns False
    with the state untouched, so the numpy twin owns that boundary —
    demotion, never a crashed slot. Exceptions from the *write-back* phase
    deliberately propagate: by then the state is partially mutated, and
    demoting to a second full numpy transition would apply the epoch twice
    (silent consensus corruption is strictly worse than a loud crash)."""
    if not device_backend_active():
        return False
    from .engine import process_epoch_on_device

    return process_epoch_on_device(spec, state, sharding=sharding)


def prepare_state(state, sharding=None):
    """Bind mirror + delta journal ahead of the first boundary (chain /
    state_advance warm-up). No-op unless the device backend is active."""
    if not device_backend_active():
        return None
    from .engine import prepare_state as _prep

    return _prep(state, sharding=sharding)


def engine_stats(state) -> dict | None:
    """Mirror counters for observability / the --epoch bench."""
    from .engine import mirror_of

    m = mirror_of(state)
    return None if m is None else m.stats.as_dict()
