"""Fused single-pass epoch kernels over the validator axis.

One ``jit``-compiled sweep per fork family (phase0 / altair-like / electra)
computes everything ``per_epoch.py`` does per validator — justification
balances, inactivity scores, rewards/penalties, registry updates
(eligibility, ejections with exact exit-queue semantics, the churn-limited
activation queue), slashing penalties, and hysteresis effective-balance
updates — as one XLA program. The electra family adds the EIP-7251 stages
in the same idiom: balance-denominated exit churn as a prefix sum, the
pending-deposit queue as a masked cumulative sum against the
activation-exit budget with one scatter-add into balances, the
pending-consolidation queue as a short ``lax.scan``, and a per-validator
``max_effective_balance`` plane (compounding 2048 ETH vs 32 ETH
credentials). The validator axis is padded to a shape bucket so the
registry can grow without recompiling, and padding rows are arithmetic
no-ops (inactive, zero-balance, far-future epochs).

Bit-exactness contract: every expression mirrors the numpy path in
``state_transition/per_epoch.py`` including its uint64 wrap-around
semantics, so the parity suite (tests/test_epoch_engine.py) can assert
field-for-field identity. Sequential spec constructs are vectorized in
closed form:

* exit queue — ``initiate_validator_exit``'s per-validator loop assigns
  epoch ``eq0 + (min(c0, churn) + rank) // churn`` to the rank-th ejected
  validator, where ``eq0`` is the current max exit epoch and ``c0`` its
  occupancy (the loop only ever rolls one epoch forward at a time because
  ``eq0`` is the global max);
* activation queue — a device ``lexsort`` over (eligibility epoch, index)
  replaces the host sort, with the churn limit applied by sorted position.

Scalar decisions that touch non-array state (which checkpoint became
justified/finalized) are returned as flags; the host applies the Checkpoint
objects. Sharding: callers may lay the inputs out with a NamedSharding over
the validator axis — the reductions/sorts lower to cross-device collectives
under GSPMD, the same mesh machinery the BLS kernels use.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

FAR_FUTURE_EPOCH = 2**64 - 1

# phase0 constant (per_epoch.BASE_REWARDS_PER_EPOCH)
BASE_REWARDS_PER_EPOCH = 4
# altair participation weights (per_block.PARTICIPATION_FLAG_WEIGHTS)
PARTICIPATION_FLAG_WEIGHTS = (14, 26, 14)
WEIGHT_DENOMINATOR = 64
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2


class EpochConsts(NamedTuple):
    """Hashable spec snapshot baked into the jitted sweep (static arg)."""

    family: str  # "phase0" | "altair" | "electra"
    effective_balance_increment: int
    max_effective_balance: int
    ejection_balance: int
    min_per_epoch_churn_limit: int
    churn_limit_quotient: int
    max_seed_lookahead: int
    min_validator_withdrawability_delay: int
    min_epochs_to_inactivity_penalty: int
    base_reward_factor: int
    proposer_reward_quotient: int
    epochs_per_slashings_vector: int
    proportional_slashing_multiplier: int
    # phase0 only
    inactivity_penalty_quotient: int
    # altair family only
    inactivity_score_bias: int
    inactivity_score_recovery_rate: int
    inactivity_penalty_quotient_altair: int
    # deneb+ caps the activation churn
    cap_activation_churn: bool
    max_per_epoch_activation_churn_limit: int
    # electra family (EIP-7251 balance-denominated churn + pending queues)
    min_activation_balance: int = 0
    max_effective_balance_electra: int = 0
    min_per_epoch_churn_limit_electra: int = 0
    max_per_epoch_activation_exit_churn_limit: int = 0
    max_pending_deposits_per_epoch: int = 0
    slots_per_epoch: int = 0


def consts_for(spec, fork: str) -> EpochConsts:
    from ..types.spec import fork_at_least, proportional_slashing_multiplier_for

    if fork == "phase0":
        family = "phase0"
    elif fork_at_least(fork, "electra"):
        family = "electra"
    else:
        family = "altair"
    mult = proportional_slashing_multiplier_for(spec, fork)
    return EpochConsts(
        family=family,
        effective_balance_increment=spec.effective_balance_increment,
        max_effective_balance=spec.max_effective_balance,
        ejection_balance=spec.ejection_balance,
        min_per_epoch_churn_limit=spec.min_per_epoch_churn_limit,
        churn_limit_quotient=spec.churn_limit_quotient,
        max_seed_lookahead=spec.max_seed_lookahead,
        min_validator_withdrawability_delay=(
            spec.min_validator_withdrawability_delay
        ),
        min_epochs_to_inactivity_penalty=spec.min_epochs_to_inactivity_penalty,
        base_reward_factor=spec.base_reward_factor,
        proposer_reward_quotient=spec.proposer_reward_quotient,
        epochs_per_slashings_vector=spec.preset.EPOCHS_PER_SLASHINGS_VECTOR,
        proportional_slashing_multiplier=mult,
        inactivity_penalty_quotient=spec.inactivity_penalty_quotient,
        inactivity_score_bias=spec.inactivity_score_bias,
        inactivity_score_recovery_rate=spec.inactivity_score_recovery_rate,
        inactivity_penalty_quotient_altair=(
            spec.inactivity_penalty_quotient_altair
        ),
        cap_activation_churn=fork_at_least(fork, "deneb"),
        max_per_epoch_activation_churn_limit=(
            spec.max_per_epoch_activation_churn_limit
        ),
        min_activation_balance=spec.min_activation_balance,
        max_effective_balance_electra=spec.max_effective_balance_electra,
        min_per_epoch_churn_limit_electra=(
            spec.min_per_epoch_churn_limit_electra
        ),
        max_per_epoch_activation_exit_churn_limit=(
            spec.max_per_epoch_activation_exit_churn_limit
        ),
        max_pending_deposits_per_epoch=(
            spec.preset.MAX_PENDING_DEPOSITS_PER_EPOCH
        ),
        slots_per_epoch=spec.preset.SLOTS_PER_EPOCH,
    )


def bucket(n: int) -> int:
    """Validator-axis shape bucket: power of two >= 256 (multiple of any
    mesh size, and the registry grows without recompiles)."""
    b = 256
    while b < n:
        b *= 2
    return b


def queue_bucket(n: int) -> int:
    """Pending-consolidation-queue shape bucket: power of two >= 8, so the
    queue length only triggers a recompile on (rare) growth past a bucket."""
    b = 8
    while b < n:
        b *= 2
    return b


# =============================================================================
# kernel body (pure jnp; jitted via _compiled)
# =============================================================================


def _u64(x):
    import jax.numpy as jnp

    return jnp.uint64(x)


def _isqrt_u64(t):
    """Exact integer sqrt of a u64 scalar (values << 2^63). float64 seeds the
    root; two correction steps each way absorb the <=1-ulp rounding."""
    import jax.numpy as jnp

    s = jnp.floor(jnp.sqrt(t.astype(jnp.float64))).astype(jnp.uint64)
    one = _u64(1)
    for _ in range(2):
        s = jnp.where((s + one) * (s + one) <= t, s + one, s)
    for _ in range(2):
        s = jnp.where((s > 0) & (s * s > t), s - one, s)
    return s


def _justification(C, do_just, total, prev_tb, cur_tb, bits,
                   prev_jcp_ep, cur_jcp_ep, fin_ep, cur_ep):
    """New justification bits + checkpoint-update flags + finalized selector
    (0 none / 1 old-previous-justified / 2 old-current-justified)."""
    import jax.numpy as jnp

    three, two = _u64(3), _u64(2)
    cond_prev = do_just & (prev_tb * three >= total * two)
    cond_cur = do_just & (cur_tb * three >= total * two)
    nb0 = cond_cur
    nb1 = bits[0] | cond_prev
    nb2, nb3 = bits[1], bits[2]
    r1 = nb1 & nb2 & nb3 & (prev_jcp_ep + three == cur_ep)
    r2 = nb1 & nb2 & (prev_jcp_ep + two == cur_ep)
    r3 = nb0 & nb1 & nb2 & (cur_jcp_ep + two == cur_ep)
    r4 = nb0 & nb1 & (cur_jcp_ep + _u64(1) == cur_ep)
    fin_sel = jnp.where(
        do_just & (r3 | r4), 2, jnp.where(do_just & (r1 | r2), 1, 0)
    ).astype(jnp.int32)
    new_bits = jnp.stack([
        jnp.where(do_just, nb0, bits[0]),
        jnp.where(do_just, nb1, bits[1]),
        jnp.where(do_just, nb2, bits[2]),
        jnp.where(do_just, nb3, bits[3]),
    ])
    f_new = jnp.where(
        fin_sel == 2, cur_jcp_ep, jnp.where(fin_sel == 1, prev_jcp_ep, fin_ep)
    )
    return new_bits, cond_prev, cond_cur, fin_sel, f_new


def _registry_updates(C: EpochConsts, cur_ep, f_new, effective,
                      activation, exit_ep, withdrawable, eligibility,
                      active_cur):
    """Eligibility flags, vectorized exit queue, churn-limited activation
    queue (process_registry_updates, non-electra)."""
    import jax.numpy as jnp

    far = _u64(FAR_FUTURE_EPOCH)
    one = _u64(1)
    elig_new = jnp.where(
        (eligibility == far)
        & (effective == _u64(C.max_effective_balance)),
        cur_ep + one,
        eligibility,
    )
    n_active = jnp.sum(active_cur.astype(jnp.uint64))
    churn = jnp.maximum(
        _u64(C.min_per_epoch_churn_limit),
        n_active // _u64(C.churn_limit_quotient),
    )
    # -- ejections: exact initiate_validator_exit queue semantics ----------
    eject = (
        active_cur
        & (effective <= _u64(C.ejection_balance))
        & (exit_ep == far)
    )
    has_exit = exit_ep != far
    min_exit = cur_ep + one + _u64(C.max_seed_lookahead)
    eq0 = jnp.maximum(
        jnp.max(jnp.where(has_exit, exit_ep, _u64(0))), min_exit
    )
    c0 = jnp.sum((exit_ep == eq0).astype(jnp.uint64))
    c_eff = jnp.minimum(c0, churn)
    rank = jnp.cumsum(eject.astype(jnp.uint64)) - one  # valid where eject
    assigned = eq0 + (c_eff + rank) // churn
    exit_new = jnp.where(eject, assigned, exit_ep)
    wd_new = jnp.where(
        eject,
        assigned + _u64(C.min_validator_withdrawability_delay),
        withdrawable,
    )
    # -- activation queue: FIFO by (eligibility epoch, index) --------------
    cand = (elig_new <= f_new) & (activation == far)
    limit = churn
    if C.cap_activation_churn:
        limit = jnp.minimum(
            _u64(C.max_per_epoch_activation_churn_limit), limit
        )
    n = effective.shape[0]
    idx = jnp.arange(n, dtype=jnp.uint64)
    order = jnp.lexsort((idx, jnp.where(cand, elig_new, far)))
    pos = jnp.arange(n, dtype=jnp.uint64)
    sel_at_pos = (pos < limit) & cand[order]
    taken = jnp.zeros(n, dtype=bool).at[order].set(sel_at_pos)
    act_new = jnp.where(taken, min_exit, activation)
    return elig_new, exit_new, wd_new, act_new


def _slashings(C: EpochConsts, cur_ep, total, slash_sum, effective, slashed,
               withdrawable_snapshot, balances):
    import jax.numpy as jnp

    inc = _u64(C.effective_balance_increment)
    adjusted = jnp.minimum(
        slash_sum * _u64(C.proportional_slashing_multiplier), total
    )
    target_wd = cur_ep + _u64(C.epochs_per_slashings_vector // 2)
    hit = slashed & (withdrawable_snapshot == target_wd)
    if C.family == "electra":
        # EIP-7251 overflow-safe form: per-increment penalty first
        per_increment = adjusted // (total // inc)
        penalty = effective // inc * per_increment
    else:
        penalty = effective // inc * adjusted // total * inc
    dec = jnp.minimum(penalty, balances)
    return jnp.where(hit, balances - dec, balances)


def _effective_updates(C: EpochConsts, balances, effective, max_eff=None):
    import jax.numpy as jnp

    inc = _u64(C.effective_balance_increment)
    hysteresis = inc // _u64(4)
    down = hysteresis  # HYSTERESIS_DOWNWARD_MULTIPLIER = 1
    up = hysteresis * _u64(5)  # HYSTERESIS_UPWARD_MULTIPLIER = 5
    need = (balances + down < effective) | (effective + up < balances)
    if max_eff is None:
        max_eff = _u64(C.max_effective_balance)
    capped = jnp.minimum(balances - balances % inc, max_eff)
    return jnp.where(need, capped, effective)


def _altair_head(C: EpochConsts, cols, scalars):
    """The fork-independent front of the altair-family sweep: justification,
    inactivity updates, and rewards/penalties. Returns the intermediate
    planes both the altair and electra tails build on."""
    import jax.numpy as jnp

    effective = cols["effective"]
    slashed = cols["slashed"]
    activation = cols["activation"]
    exit_ep = cols["exit"]
    withdrawable = cols["withdrawable"]
    balances = cols["balances"]
    inact = cols["inactivity"]
    prev_part = cols["prev_part"]
    cur_part = cols["cur_part"]

    cur_ep = scalars["cur_epoch"]
    fin_ep = scalars["finalized_epoch"]
    prev_jcp_ep = scalars["prev_justified_epoch"]
    cur_jcp_ep = scalars["cur_justified_epoch"]
    bits = scalars["bits"]
    slash_sum = scalars["slash_sum"]

    inc = _u64(C.effective_balance_increment)
    zero, one = _u64(0), _u64(1)
    prev_ep = jnp.where(cur_ep > zero, cur_ep - one, zero)
    active_cur = (activation <= cur_ep) & (cur_ep < exit_ep)
    active_prev = (activation <= prev_ep) & (prev_ep < exit_ep)
    total = jnp.maximum(
        inc, jnp.sum(jnp.where(active_cur, effective, zero))
    )

    def flag_mask(part, flag, active_mask):
        return active_mask & ((part & np.uint8(1 << flag)) != 0) & ~slashed

    # --- justification & finalization ------------------------------------
    prev_tgt = flag_mask(prev_part, TIMELY_TARGET_FLAG_INDEX, active_prev)
    cur_tgt = flag_mask(cur_part, TIMELY_TARGET_FLAG_INDEX, active_cur)
    prev_tb = jnp.maximum(inc, jnp.sum(jnp.where(prev_tgt, effective, zero)))
    cur_tb = jnp.maximum(inc, jnp.sum(jnp.where(cur_tgt, effective, zero)))
    do_just = cur_ep > one
    new_bits, cj_prev, cj_cur, fin_sel, f_new = _justification(
        C, do_just, total, prev_tb, cur_tb, bits,
        prev_jcp_ep, cur_jcp_ep, fin_ep, cur_ep,
    )

    # --- inactivity updates (reads the just-updated finalized epoch) -----
    do_rp = cur_ep > zero
    eligible = active_prev | (slashed & (prev_ep + one < withdrawable))
    delay_i = prev_ep.astype(jnp.int64) - f_new.astype(jnp.int64)
    is_leak = delay_i > np.int64(C.min_epochs_to_inactivity_penalty)
    s = inact
    s1 = jnp.where(eligible & prev_tgt, s - jnp.minimum(one, s), s)
    s1 = jnp.where(
        eligible & ~prev_tgt, s1 + _u64(C.inactivity_score_bias), s1
    )
    s2 = jnp.where(
        eligible & ~is_leak,
        s1 - jnp.minimum(_u64(C.inactivity_score_recovery_rate), s1),
        s1,
    )
    inact_new = jnp.where(do_rp, s2, s)

    # --- rewards & penalties ---------------------------------------------
    total_increments = total // inc
    per_inc = inc * _u64(C.base_reward_factor) // _isqrt_u64(total)
    base = (effective // inc) * per_inc
    rewards = jnp.zeros_like(balances)
    penalties = jnp.zeros_like(balances)
    for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
        mask = flag_mask(prev_part, flag_index, active_prev)
        flag_balance = jnp.maximum(
            inc, jnp.sum(jnp.where(mask, effective, zero))
        )
        flag_increments = flag_balance // inc
        attesters = eligible & mask
        numer = base * (_u64(weight) * flag_increments)
        denom = total_increments * _u64(WEIGHT_DENOMINATOR)
        rewards = jnp.where(
            attesters & ~is_leak, rewards + numer // denom, rewards
        )
        if flag_index != TIMELY_HEAD_FLAG_INDEX:
            penalties = jnp.where(
                eligible & ~mask,
                penalties
                + base * _u64(weight) // _u64(WEIGHT_DENOMINATOR),
                penalties,
            )
    non_target = eligible & ~prev_tgt
    inact_denom = _u64(
        C.inactivity_score_bias * C.inactivity_penalty_quotient_altair
    )
    penalties = jnp.where(
        non_target,
        penalties + effective * inact_new // inact_denom,
        penalties,
    )
    bal = balances + jnp.where(do_rp, rewards, zero)
    pen = jnp.where(do_rp, penalties, zero)
    bal = bal - jnp.minimum(pen, bal)

    return {
        "bal": bal,
        "inact_new": inact_new,
        "bits": new_bits,
        "cj_prev": cj_prev,
        "cj_cur": cj_cur,
        "fin_sel": fin_sel,
        "f_new": f_new,
        "do_just": do_just,
        "active_cur": active_cur,
        "total": total,
    }


def _sweep_altair(C: EpochConsts, cols, scalars):
    effective = cols["effective"]
    slashed = cols["slashed"]
    activation = cols["activation"]
    exit_ep = cols["exit"]
    withdrawable = cols["withdrawable"]
    eligibility = cols["eligibility"]
    cur_ep = scalars["cur_epoch"]
    slash_sum = scalars["slash_sum"]

    h = _altair_head(C, cols, scalars)
    bal, f_new = h["bal"], h["f_new"]
    total, active_cur = h["total"], h["active_cur"]

    # --- registry updates / slashings / effective balances ---------------
    elig_new, exit_new, wd_new, act_new = _registry_updates(
        C, cur_ep, f_new, effective, activation, exit_ep,
        withdrawable, eligibility, active_cur,
    )
    bal = _slashings(
        C, cur_ep, total, slash_sum, effective, slashed, withdrawable, bal
    )
    eff_new = _effective_updates(C, bal, effective)

    return {
        "balances": bal,
        "inactivity": h["inact_new"],
        "effective": eff_new,
        "activation": act_new,
        "exit": exit_new,
        "withdrawable": wd_new,
        "eligibility": elig_new,
        "bits": h["bits"],
        "cj_prev": h["cj_prev"],
        "cj_cur": h["cj_cur"],
        "fin_sel": h["fin_sel"],
        "f_new": f_new,
        "do_just": h["do_just"],
    }


def _sweep_phase0(C: EpochConsts, cols, scalars):
    import jax.numpy as jnp

    effective = cols["effective"]
    slashed = cols["slashed"]
    activation = cols["activation"]
    exit_ep = cols["exit"]
    withdrawable = cols["withdrawable"]
    eligibility = cols["eligibility"]
    balances = cols["balances"]
    src_mask = cols["src_mask"]
    tgt_mask = cols["tgt_mask"]
    head_mask = cols["head_mask"]
    cur_tgt_mask = cols["cur_tgt_mask"]
    incl_delay = cols["incl_delay"]
    incl_proposer = cols["incl_proposer"]
    has_incl = cols["has_incl"]

    cur_ep = scalars["cur_epoch"]
    fin_ep = scalars["finalized_epoch"]
    prev_jcp_ep = scalars["prev_justified_epoch"]
    cur_jcp_ep = scalars["cur_justified_epoch"]
    bits = scalars["bits"]
    slash_sum = scalars["slash_sum"]

    inc = _u64(C.effective_balance_increment)
    zero, one = _u64(0), _u64(1)
    prev_ep = jnp.where(cur_ep > zero, cur_ep - one, zero)
    active_cur = (activation <= cur_ep) & (cur_ep < exit_ep)
    active_prev = (activation <= prev_ep) & (prev_ep < exit_ep)
    total = jnp.maximum(
        inc, jnp.sum(jnp.where(active_cur, effective, zero))
    )

    # --- justification (target masks are host-gathered, unslashed) -------
    prev_tb = jnp.maximum(
        inc, jnp.sum(jnp.where(tgt_mask, effective, zero))
    )
    cur_tb = jnp.maximum(
        inc, jnp.sum(jnp.where(cur_tgt_mask, effective, zero))
    )
    do_just = cur_ep > one
    new_bits, cj_prev, cj_cur, fin_sel, f_new = _justification(
        C, do_just, total, prev_tb, cur_tb, bits,
        prev_jcp_ep, cur_jcp_ep, fin_ep, cur_ep,
    )

    # --- rewards & penalties ---------------------------------------------
    do_rp = cur_ep > zero
    eligible = active_prev | (slashed & (prev_ep + one < withdrawable))
    delay_i = prev_ep.astype(jnp.int64) - f_new.astype(jnp.int64)
    is_leak = delay_i > np.int64(C.min_epochs_to_inactivity_penalty)
    base = (
        effective * _u64(C.base_reward_factor)
        // _isqrt_u64(total)
        // _u64(BASE_REWARDS_PER_EPOCH)
    )
    total_increments = total // inc
    rewards = jnp.zeros_like(balances)
    penalties = jnp.zeros_like(balances)
    for mask in (src_mask, tgt_mask, head_mask):
        att_balance = jnp.maximum(
            inc, jnp.sum(jnp.where(mask, effective, zero))
        )
        increments = att_balance // inc
        attesters = eligible & mask
        rewards = jnp.where(
            attesters,
            rewards
            + jnp.where(
                is_leak, base, base * increments // total_increments
            ),
            rewards,
        )
        penalties = jnp.where(eligible & ~mask, penalties + base, penalties)

    # proposer & inclusion-delay micro-rewards (earliest inclusion, host-
    # resolved into per-validator delay/proposer columns)
    ok = has_incl & ~slashed
    proposer_reward = base // _u64(C.proposer_reward_quotient)
    rewards = rewards.at[incl_proposer].add(
        jnp.where(ok, proposer_reward, zero)
    )
    safe_delay = jnp.where(ok, incl_delay, one)
    rewards = jnp.where(
        ok, rewards + (base - proposer_reward) // safe_delay, rewards
    )

    # inactivity-leak penalties
    leak_pen = (
        _u64(BASE_REWARDS_PER_EPOCH) * base
        - base // _u64(C.proposer_reward_quotient)
    )
    penalties = jnp.where(
        eligible & is_leak, penalties + leak_pen, penalties
    )
    delay_u = delay_i.astype(jnp.uint64)
    penalties = jnp.where(
        eligible & ~tgt_mask & is_leak,
        penalties
        + effective * delay_u // _u64(C.inactivity_penalty_quotient),
        penalties,
    )

    bal = balances + jnp.where(do_rp, rewards, zero)
    pen = jnp.where(do_rp, penalties, zero)
    bal = bal - jnp.minimum(pen, bal)

    # --- registry updates / slashings / effective balances ---------------
    elig_new, exit_new, wd_new, act_new = _registry_updates(
        C, cur_ep, f_new, effective, activation, exit_ep,
        withdrawable, eligibility, active_cur,
    )
    bal = _slashings(
        C, cur_ep, total, slash_sum, effective, slashed, withdrawable, bal
    )
    eff_new = _effective_updates(C, bal, effective)

    return {
        "balances": bal,
        "effective": eff_new,
        "activation": act_new,
        "exit": exit_new,
        "withdrawable": wd_new,
        "eligibility": elig_new,
        "bits": new_bits,
        "cj_prev": cj_prev,
        "cj_cur": cj_cur,
        "fin_sel": fin_sel,
        "f_new": f_new,
        "do_just": do_just,
    }


# =============================================================================
# electra family (EIP-7251 balance churn + pending deposit/consolidation queues)
# =============================================================================


def _balance_churn_limits(C: EpochConsts, total):
    """get_balance_churn_limit / get_activation_exit_churn_limit — the
    balance-denominated churn (EIP-7251), floored to the increment."""
    import jax.numpy as jnp

    inc = _u64(C.effective_balance_increment)
    churn = jnp.maximum(
        _u64(C.min_per_epoch_churn_limit_electra),
        total // _u64(C.churn_limit_quotient),
    )
    churn = churn - churn % inc
    aexit = jnp.minimum(
        _u64(C.max_per_epoch_activation_exit_churn_limit), churn
    )
    return churn, aexit


def _registry_updates_electra(C: EpochConsts, cur_ep, f_new, effective,
                              activation, exit_ep, withdrawable, eligibility,
                              active_cur, earliest_exit_in, exit_btc_in,
                              churn_aexit):
    """Electra process_registry_updates: MIN_ACTIVATION_BALANCE eligibility,
    balance-churned ejections in closed form, and limit-free activations.

    ``compute_exit_epoch_and_update_churn``'s sequential per-ejection loop
    collapses to a prefix sum: with ``E0 = max(earliest_exit, cur+1+lookahead)``
    and ``btc0`` the epoch's starting exit budget, the k-th ejection (index
    order, inclusive balance cumsum ``C_k``) lands on epoch
    ``E0 + ceil_div(max(C_k - btc0, 0), churn)`` — because each call only ever
    advances the shared ``earliest_exit_epoch`` / ``exit_balance_to_consume``
    pair by exactly the epochs its balance overflows the running budget."""
    import jax.numpy as jnp

    far = _u64(FAR_FUTURE_EPOCH)
    one = _u64(1)
    elig_new = jnp.where(
        (eligibility == far)
        & (effective >= _u64(C.min_activation_balance)),
        cur_ep + one,
        eligibility,
    )
    eject = (
        active_cur
        & (effective <= _u64(C.ejection_balance))
        & (exit_ep == far)
    )
    min_exit = cur_ep + one + _u64(C.max_seed_lookahead)
    e0 = jnp.maximum(earliest_exit_in, min_exit)
    btc0 = jnp.where(earliest_exit_in < e0, churn_aexit, exit_btc_in)
    csum = jnp.cumsum(jnp.where(eject, effective, _u64(0)))
    add = jnp.where(
        csum > btc0, (csum - btc0 - one) // churn_aexit + one, _u64(0)
    )
    assigned = e0 + add
    exit_new = jnp.where(eject, assigned, exit_ep)
    wd_new = jnp.where(
        eject,
        assigned + _u64(C.min_validator_withdrawability_delay),
        withdrawable,
    )
    has_ejection = jnp.any(eject)
    earliest_out = e0 + add[-1]
    btc_out = btc0 + add[-1] * churn_aexit - csum[-1]
    # activations: every finalized-eligible candidate activates (EIP-7251
    # throttles via the pending-deposit balance churn, not a queue limit)
    cand = (elig_new <= f_new) & (activation == far)
    act_new = jnp.where(cand, min_exit, activation)
    return (
        elig_new, exit_new, wd_new, act_new,
        has_ejection, earliest_out, btc_out,
    )


def _deposits_stage(C: EpochConsts, next_ep, f_new, exit_new, wd_new,
                    balances, dep_amount, dep_slot, dep_index, dep_valid,
                    dbtc_in, churn_aexit, eth1_deposit_index,
                    deposit_requests_start_index):
    """process_pending_deposits as a masked cumulative sum over the first
    MAX_PENDING_DEPOSITS_PER_EPOCH queue entries (the loop can never examine
    more: every iteration advances the capped position counter).

    The sequential loop's three break conditions become three stop
    positions — first gate failure (EIP-6110 bridge wait / finality wait),
    first churn overflow among budget-consuming entries, and queue/cap
    exhaustion — and the realized stop is their minimum. The churn break is
    only reachable strictly before the others (gates are tested first in
    the loop body), which is exactly when the numpy twin leaves
    ``is_churn_limit_reached`` True. Known-index applications scatter-add
    into balances here; unknown-pubkey entries (registry appends + their
    BLS proof-of-possession check) are flagged for the host."""
    import jax.numpy as jnp

    maxq = dep_amount.shape[0]
    zero = _u64(0)
    pos = jnp.arange(maxq, dtype=jnp.int32)
    big = jnp.int32(maxq)
    finalized_slot = f_new * _u64(C.slots_per_epoch)
    bridge_wait = (dep_slot > zero) & (
        eth1_deposit_index < deposit_requests_start_index
    )
    gate_fail = dep_valid & (bridge_wait | (dep_slot > finalized_slot))
    s_gate = jnp.min(jnp.where(gate_fail, pos, big))
    n_valid = jnp.sum(dep_valid.astype(jnp.int32))
    known = dep_index >= 0
    gi = jnp.clip(dep_index, 0, exit_new.shape[0] - 1)
    withdrawn = dep_valid & known & (wd_new[gi] < next_ep)
    exited = (
        dep_valid & known & ~withdrawn
        & (exit_new[gi] < _u64(FAR_FUTURE_EPOCH))
    )
    consumes = dep_valid & ~withdrawn & ~exited  # the budget-charged branch
    csum = jnp.cumsum(jnp.where(consumes, dep_amount, zero))
    available = dbtc_in + churn_aexit
    churn_hit = consumes & (csum > available)
    s_churn = jnp.min(jnp.where(churn_hit, pos, big))
    s_other = jnp.minimum(s_gate, n_valid)
    s = jnp.minimum(s_churn, s_other)
    churn_reached = s_churn < s_other
    processed = jnp.sum(jnp.where(consumes & (pos < s), dep_amount, zero))
    apply_dev = (pos < s) & known & (withdrawn | consumes)
    bal = balances.at[gi].add(jnp.where(apply_dev, dep_amount, zero))
    postponed = (pos < s) & exited
    host_apply = (pos < s) & dep_valid & ~known
    dbtc_out = jnp.where(churn_reached, available - processed, zero)
    return bal, s, postponed, host_apply, dbtc_out


def _consolidations_scan(C: EpochConsts, next_ep, slashed, effective,
                         wd_new, balances, con_src, con_tgt, con_valid):
    """process_pending_consolidations as a short ``lax.scan`` over the
    padded queue bucket: the sweep is order-dependent (duplicate sources /
    consolidation chains move running balances), so each step moves
    ``min(balance, effective)`` source→target against the carried balance
    plane. Slashed sources are skipped-but-consumed; the first live source
    still inside its withdrawability delay stops the sweep."""
    import jax
    import jax.numpy as jnp

    zero = _u64(0)

    def step(carry, inp):
        bal, stopped, consumed = carry
        src, tgt, valid = inp
        skip = slashed[src]
        stop_here = valid & ~skip & (wd_new[src] > next_ep)
        stopped = stopped | stop_here
        do = valid & ~stopped & ~skip
        amt = jnp.where(do, jnp.minimum(bal[src], effective[src]), zero)
        bal = bal.at[src].add(zero - amt)
        bal = bal.at[tgt].add(amt)
        consumed = consumed + (valid & ~stopped).astype(jnp.int32)
        return (bal, stopped, consumed), None

    (bal, _, consumed), _ = jax.lax.scan(
        step,
        (balances, jnp.bool_(False), jnp.int32(0)),
        (con_src, con_tgt, con_valid),
    )
    return bal, consumed


def _sweep_electra(C: EpochConsts, cols, scalars):
    import jax.numpy as jnp

    from ..ops.bls.fq import _cert

    effective = cols["effective"]
    slashed = cols["slashed"]
    activation = cols["activation"]
    exit_ep = cols["exit"]
    withdrawable = cols["withdrawable"]
    eligibility = cols["eligibility"]
    compounding = cols["compounding"]
    cur_ep = scalars["cur_epoch"]
    slash_sum = scalars["slash_sum"]

    # trace-time proof obligations (recorded by the bounds certifier when
    # its sink is installed; plain asserts otherwise). Shapes and consts
    # are static at trace, so these pin the u64/int32 headroom of the
    # electra-only arithmetic for every compiled specialization.
    n_pad = effective.shape[0]
    assert _cert(
        "epoch_validator_index_domain", n_pad, 2**31 - 1,
        "validator-axis gather/scatter indices fit int32",
    )
    assert _cert(
        "epoch_churn_cumsum_headroom",
        n_pad * C.max_effective_balance_electra
        * max(C.proportional_slashing_multiplier, 1),
        2**64 - 1,
        "balance prefix sums and the scaled slashing sum cannot wrap u64",
    )
    assert _cert(
        "epoch_deposit_plane_width",
        C.max_pending_deposits_per_epoch,
        cols["dep_amount"].shape[0],
        "deposit sweep never reads past the fixed queue plane",
    )

    h = _altair_head(C, cols, scalars)
    bal, f_new = h["bal"], h["f_new"]
    total, active_cur = h["total"], h["active_cur"]
    next_ep = cur_ep + _u64(1)

    _, churn_aexit = _balance_churn_limits(C, total)
    (
        elig_new, exit_new, wd_new, act_new,
        has_ejection, earliest_out, exit_btc_out,
    ) = _registry_updates_electra(
        C, cur_ep, f_new, effective, activation, exit_ep, withdrawable,
        eligibility, active_cur, scalars["earliest_exit_epoch"],
        scalars["exit_balance_to_consume"], churn_aexit,
    )
    bal = _slashings(
        C, cur_ep, total, slash_sum, effective, slashed, withdrawable, bal
    )
    # deposit/consolidation classification reads the POST-registry exit and
    # withdrawable planes — the numpy twin's loops run after the updates
    bal, dep_stop, dep_postponed, dep_host, dbtc_out = _deposits_stage(
        C, next_ep, f_new, exit_new, wd_new, bal,
        cols["dep_amount"], cols["dep_slot"], cols["dep_index"],
        cols["dep_valid"], scalars["deposit_balance_to_consume"],
        churn_aexit, scalars["eth1_deposit_index"],
        scalars["deposit_requests_start_index"],
    )
    bal, cons_consumed = _consolidations_scan(
        C, next_ep, slashed, effective, wd_new, bal,
        cols["con_src"], cols["con_tgt"], cols["con_valid"],
    )
    max_eff = jnp.where(
        compounding,
        _u64(C.max_effective_balance_electra),
        _u64(C.min_activation_balance),
    )
    eff_new = _effective_updates(C, bal, effective, max_eff=max_eff)

    return {
        "balances": bal,
        "inactivity": h["inact_new"],
        "effective": eff_new,
        "activation": act_new,
        "exit": exit_new,
        "withdrawable": wd_new,
        "eligibility": elig_new,
        "bits": h["bits"],
        "cj_prev": h["cj_prev"],
        "cj_cur": h["cj_cur"],
        "fin_sel": h["fin_sel"],
        "f_new": f_new,
        "do_just": h["do_just"],
        "dep_stop": dep_stop,
        "dep_postponed": dep_postponed,
        "dep_host": dep_host,
        "dep_btc": dbtc_out,
        "cons_consumed": cons_consumed,
        "has_ejection": has_ejection,
        "earliest_exit": earliest_out,
        "exit_btc": exit_btc_out,
    }


@functools.lru_cache(maxsize=16)
def _compiled(consts: EpochConsts):
    """One jitted sweep per (fork family x spec constants); XLA's own cache
    handles the per-shape-bucket specializations underneath."""
    import jax

    body = {
        "phase0": _sweep_phase0,
        "electra": _sweep_electra,
    }.get(consts.family, _sweep_altair)
    return jax.jit(functools.partial(body, consts))


def run_sweep(consts: EpochConsts, cols: dict, scalars: dict) -> dict:
    return _compiled(consts)(cols, scalars)
