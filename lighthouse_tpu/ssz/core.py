"""SSZ type system: basic/composite types, strict (de)serialization, tree roots.

Design: SSZ *types* are descriptor objects (instances of the classes below);
SSZ *values* are plain Python data — ints, bools, bytes, lists, numpy arrays
(fast path for uint lists/vectors), and ``Container`` subclasses. This mirrors
the reference's split between the ``Encode``/``Decode``/``TreeHash`` traits
and the container structs (``consensus/types``), without Rust's monomorphized
generics: a network preset is a set of descriptor instances.

Deserialization is strict: offset monotonicity, exact-length consumption, and
canonical bitlist delimiters are enforced (ssz_static EF-test discipline).
"""

from __future__ import annotations

import numpy as np

from .merkle import merkleize_chunks, mix_in_length, mix_in_selector

OFFSET_LEN = 4


class SSZError(Exception):
    pass


def _pack_bytes(data: bytes) -> np.ndarray:
    """bytes -> [ceil(n/32), 32] chunk rows (zero padded)."""
    n = (len(data) + 31) // 32
    buf = np.zeros((max(n, 1), 32), dtype=np.uint8)
    if data:
        flat = np.frombuffer(data, dtype=np.uint8)
        buf.reshape(-1)[: len(flat)] = flat
    if n == 0:
        return buf[:0]
    return buf[:n] if n else buf


class SSZType:
    is_fixed: bool = True

    def fixed_len(self) -> int:
        raise NotImplementedError

    def encode(self, value) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class UInt(SSZType):
    def __init__(self, byte_len: int):
        self.byte_len = byte_len

    def fixed_len(self):
        return self.byte_len

    def encode(self, value) -> bytes:
        return int(value).to_bytes(self.byte_len, "little")

    def decode(self, data: bytes):
        if len(data) != self.byte_len:
            raise SSZError(f"uint{self.byte_len * 8}: bad length {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return self.encode(value).ljust(32, b"\x00")

    def default(self):
        return 0

    def __repr__(self):
        return f"uint{self.byte_len * 8}"


class Boolean(SSZType):
    def fixed_len(self):
        return 1

    def encode(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def decode(self, data: bytes):
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise SSZError("boolean: invalid byte")

    def hash_tree_root(self, value) -> bytes:
        return self.encode(value).ljust(32, b"\x00")

    def default(self):
        return False

    def __repr__(self):
        return "boolean"


uint8 = UInt(1)
uint16 = UInt(2)
uint32 = UInt(4)
uint64 = UInt(8)
uint128 = UInt(16)
uint256 = UInt(32)
boolean = Boolean()

_NP_DTYPE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


class ByteVector(SSZType):
    def __init__(self, length: int):
        self.length = length

    def fixed_len(self):
        return self.length

    def encode(self, value) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise SSZError(f"ByteVector[{self.length}]: got {len(value)}")
        return value

    def decode(self, data: bytes):
        if len(data) != self.length:
            raise SSZError(f"ByteVector[{self.length}]: bad length {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize_chunks(_pack_bytes(self.encode(value)))

    def default(self):
        return b"\x00" * self.length

    def __repr__(self):
        return f"ByteVector[{self.length}]"


class ByteList(SSZType):
    is_fixed = False

    def __init__(self, limit: int):
        self.limit = limit

    def encode(self, value) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise SSZError(f"ByteList[{self.limit}]: got {len(value)}")
        return value

    def decode(self, data: bytes):
        if len(data) > self.limit:
            raise SSZError(f"ByteList[{self.limit}]: bad length {len(data)}")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        value = self.encode(value)
        root = merkleize_chunks(
            _pack_bytes(value), limit=(self.limit + 31) // 32
        )
        return mix_in_length(root, len(value))

    def default(self):
        return b""

    def __repr__(self):
        return f"ByteList[{self.limit}]"


class _Sequence(SSZType):
    """Shared machinery for Vector/List of arbitrary element type, with a
    numpy fast path when the element is a UInt."""

    def __init__(self, elem: SSZType):
        self.elem = elem

    def _encode_elems(self, values) -> bytes:
        e = self.elem
        if isinstance(e, UInt):
            arr = np.asarray(values, dtype=_NP_DTYPE.get(e.byte_len, object))
            if arr.dtype != object:
                return arr.astype(arr.dtype.newbyteorder("<")).tobytes()
            return b"".join(e.encode(v) for v in values)
        if e.is_fixed:
            return b"".join(e.encode(v) for v in values)
        parts = [e.encode(v) for v in values]
        head = len(parts) * OFFSET_LEN
        out = bytearray()
        for p in parts:
            out += head.to_bytes(OFFSET_LEN, "little")
            head += len(p)
        for p in parts:
            out += p
        return bytes(out)

    def _decode_elems(self, data: bytes, count_hint=None):
        e = self.elem
        if e.is_fixed:
            k = e.fixed_len()
            if len(data) % k:
                raise SSZError("sequence: length not multiple of element size")
            n = len(data) // k
            if isinstance(e, UInt) and e.byte_len in _NP_DTYPE:
                dt = np.dtype(_NP_DTYPE[e.byte_len]).newbyteorder("<")
                return list(
                    np.frombuffer(data, dtype=dt).astype(_NP_DTYPE[e.byte_len])
                )
            return [e.decode(data[i * k : (i + 1) * k]) for i in range(n)]
        if not data:
            return []
        first = int.from_bytes(data[:OFFSET_LEN], "little")
        if first % OFFSET_LEN or first == 0:
            raise SSZError("sequence: bad first offset")
        n = first // OFFSET_LEN
        offs = [
            int.from_bytes(data[i * OFFSET_LEN : (i + 1) * OFFSET_LEN], "little")
            for i in range(n)
        ]
        offs.append(len(data))
        if offs[0] != n * OFFSET_LEN:
            raise SSZError("sequence: first offset mismatch")
        out = []
        for i in range(n):
            if offs[i + 1] < offs[i]:
                raise SSZError("sequence: non-monotonic offsets")
            out.append(e.decode(data[offs[i] : offs[i + 1]]))
        return out

    def _elem_chunks(self, values) -> np.ndarray:
        e = self.elem
        if isinstance(e, (UInt, Boolean)):
            return _pack_bytes(self._encode_elems(values))
        roots = [e.hash_tree_root(v) for v in values]
        if not roots:
            return np.zeros((0, 32), dtype=np.uint8)
        return np.stack([np.frombuffer(r, dtype=np.uint8) for r in roots])

    def _chunk_limit(self, length: int) -> int:
        e = self.elem
        if isinstance(e, (UInt, Boolean)):
            return (length * e.fixed_len() + 31) // 32
        return length


class Vector(_Sequence):
    def __init__(self, elem: SSZType, length: int):
        super().__init__(elem)
        if length == 0:
            raise SSZError("Vector length must be > 0")
        self.length = length
        self.is_fixed = elem.is_fixed

    def fixed_len(self):
        return self.length * self.elem.fixed_len()

    def encode(self, value) -> bytes:
        if len(value) != self.length:
            raise SSZError(f"Vector[{self.length}]: got {len(value)}")
        return self._encode_elems(value)

    def decode(self, data: bytes):
        vals = self._decode_elems(data)
        if len(vals) != self.length:
            raise SSZError(f"Vector[{self.length}]: decoded {len(vals)}")
        return vals

    def hash_tree_root(self, value) -> bytes:
        if len(value) != self.length:
            raise SSZError(f"Vector[{self.length}]: got {len(value)}")
        return merkleize_chunks(
            self._elem_chunks(value), limit=self._chunk_limit(self.length)
        )

    def default(self):
        return [self.elem.default() for _ in range(self.length)]

    def __repr__(self):
        return f"Vector[{self.elem!r}, {self.length}]"


class List(_Sequence):
    is_fixed = False

    def __init__(self, elem: SSZType, limit: int):
        super().__init__(elem)
        self.limit = limit

    def encode(self, value) -> bytes:
        if len(value) > self.limit:
            raise SSZError(f"List[{self.limit}]: got {len(value)}")
        return self._encode_elems(value)

    def decode(self, data: bytes):
        vals = self._decode_elems(data)
        if len(vals) > self.limit:
            raise SSZError(f"List[{self.limit}]: decoded {len(vals)}")
        return vals

    def hash_tree_root(self, value) -> bytes:
        if len(value) > self.limit:
            raise SSZError(f"List[{self.limit}]: got {len(value)}")
        root = merkleize_chunks(
            self._elem_chunks(value), limit=self._chunk_limit(self.limit)
        )
        return mix_in_length(root, len(value))

    def default(self):
        return []

    def __repr__(self):
        return f"List[{self.elem!r}, {self.limit}]"


class Bitvector(SSZType):
    def __init__(self, length: int):
        if length == 0:
            raise SSZError("Bitvector length must be > 0")
        self.length = length

    def fixed_len(self):
        return (self.length + 7) // 8

    def encode(self, value) -> bytes:
        bits = np.asarray(value, dtype=bool)
        if bits.shape != (self.length,):
            raise SSZError(f"Bitvector[{self.length}]: got {bits.shape}")
        return np.packbits(bits, bitorder="little").tobytes()

    def decode(self, data: bytes):
        if len(data) != self.fixed_len():
            raise SSZError(f"Bitvector[{self.length}]: bad length")
        bits = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8), bitorder="little"
        )
        if bits[self.length :].any():
            raise SSZError("Bitvector: nonzero padding bits")
        return bits[: self.length].astype(bool)

    def hash_tree_root(self, value) -> bytes:
        return merkleize_chunks(
            _pack_bytes(self.encode(value)), limit=(self.length + 255) // 256
        )

    def default(self):
        return np.zeros(self.length, dtype=bool)

    def __repr__(self):
        return f"Bitvector[{self.length}]"


class Bitlist(SSZType):
    is_fixed = False

    def __init__(self, limit: int):
        self.limit = limit

    def encode(self, value) -> bytes:
        bits = np.asarray(value, dtype=bool)
        if bits.size > self.limit:
            raise SSZError(f"Bitlist[{self.limit}]: got {bits.size}")
        with_delim = np.concatenate([bits, [True]])
        return np.packbits(with_delim, bitorder="little").tobytes()

    def decode(self, data: bytes):
        if not data:
            raise SSZError("Bitlist: empty")
        if data[-1] == 0:
            raise SSZError("Bitlist: missing delimiter")
        bits = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8), bitorder="little"
        )
        # position of the delimiter = highest set bit
        top = int(np.max(np.nonzero(bits)[0]))
        n = top
        if n > self.limit:
            raise SSZError(f"Bitlist[{self.limit}]: decoded {n}")
        if len(data) != (n + 1 + 7) // 8:
            raise SSZError("Bitlist: non-canonical length")
        return bits[:n].astype(bool)

    def hash_tree_root(self, value) -> bytes:
        bits = np.asarray(value, dtype=bool)
        if bits.size > self.limit:
            raise SSZError(f"Bitlist[{self.limit}]: got {bits.size}")
        data = np.packbits(bits, bitorder="little").tobytes()
        root = merkleize_chunks(
            _pack_bytes(data) if bits.size else np.zeros((0, 32), np.uint8),
            limit=(self.limit + 255) // 256,
        )
        return mix_in_length(root, int(bits.size))

    def default(self):
        return np.zeros(0, dtype=bool)

    def __repr__(self):
        return f"Bitlist[{self.limit}]"


class Union(SSZType):
    is_fixed = False

    def __init__(self, options: list):
        self.options = options  # list of SSZType | None (None only at index 0)

    def encode(self, value) -> bytes:
        sel, v = value
        t = self.options[sel]
        if t is None:
            if v is not None:
                raise SSZError("Union: None option carries no value")
            return b"\x00"
        return bytes([sel]) + t.encode(v)

    def decode(self, data: bytes):
        if not data:
            raise SSZError("Union: empty")
        sel = data[0]
        if sel >= len(self.options):
            raise SSZError("Union: bad selector")
        t = self.options[sel]
        if t is None:
            if len(data) != 1:
                raise SSZError("Union: trailing bytes after None")
            return (0, None)
        return (sel, t.decode(data[1:]))

    def hash_tree_root(self, value) -> bytes:
        sel, v = value
        t = self.options[sel]
        root = b"\x00" * 32 if t is None else t.hash_tree_root(v)
        return mix_in_selector(root, sel)

    def default(self):
        t = self.options[0]
        return (0, None if t is None else t.default())


class Container(SSZType):
    """Subclass with a class attribute ``FIELDS: list[(name, SSZType)]``.
    The class doubles as the type descriptor and the value constructor."""

    FIELDS: list = []

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        cls._names = [n for n, _ in cls.FIELDS]
        cls._types = dict(cls.FIELDS)
        cls.is_fixed = all(t.is_fixed for _, t in cls.FIELDS)

    def __init__(self, **kwargs):
        for name, typ in self.FIELDS:
            if name in kwargs:
                setattr(self, name, kwargs.pop(name))
            else:
                setattr(self, name, typ.default())
        if kwargs:
            raise SSZError(f"{type(self).__name__}: unknown fields {list(kwargs)}")

    def __eq__(self, other):
        return type(self) is type(other) and all(
            _val_eq(getattr(self, n), getattr(other, n)) for n in self._names
        )

    def __repr__(self):
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._names[:4])
        more = "..." if len(self._names) > 4 else ""
        return f"{type(self).__name__}({inner}{more})"

    def copy(self):
        """Mutation-safe copy: nested containers are copied recursively
        (lists of containers copy each element), so in-place mutation of a
        copy never leaks into the original — required by the chain layer,
        which caches parent states and replays children off copies."""
        new = type(self).__new__(type(self))
        for n in self._names:
            v = getattr(self, n)
            if isinstance(v, Container):
                v = v.copy()
            elif isinstance(v, list):
                v = [x.copy() if isinstance(x, Container) else x for x in v]
            elif isinstance(v, np.ndarray):
                v = v.copy()
            setattr(new, n, v)
        return new

    # -- descriptor protocol (classmethods so the class IS the type) --

    @classmethod
    def fixed_len(cls) -> int:
        return sum(
            t.fixed_len() if t.is_fixed else OFFSET_LEN for _, t in cls.FIELDS
        )

    @classmethod
    def encode(cls, value=None) -> bytes:
        v = value
        fixed_parts, var_parts = [], []
        for name, t in cls.FIELDS:
            fv = getattr(v, name)
            if t.is_fixed:
                fixed_parts.append(t.encode(fv))
                var_parts.append(b"")
            else:
                fixed_parts.append(None)
                var_parts.append(t.encode(fv))
        head = sum(
            len(p) if p is not None else OFFSET_LEN for p in fixed_parts
        )
        out = bytearray()
        off = head
        for p, vp in zip(fixed_parts, var_parts):
            if p is not None:
                out += p
            else:
                out += off.to_bytes(OFFSET_LEN, "little")
                off += len(vp)
        for vp in var_parts:
            out += vp
        return bytes(out)

    def serialize(self) -> bytes:
        return type(self).encode(self)

    @classmethod
    def decode(cls, data: bytes):
        fixed_len = cls.fixed_len()
        if len(data) < fixed_len:
            raise SSZError(f"{cls.__name__}: truncated")
        pos = 0
        offsets, fixed_vals = [], {}
        var_fields = []
        for name, t in cls.FIELDS:
            if t.is_fixed:
                k = t.fixed_len()
                fixed_vals[name] = t.decode(data[pos : pos + k])
                pos += k
            else:
                off = int.from_bytes(data[pos : pos + OFFSET_LEN], "little")
                offsets.append(off)
                var_fields.append((name, t))
                pos += OFFSET_LEN
        if var_fields:
            if offsets[0] != fixed_len:
                raise SSZError(f"{cls.__name__}: first offset mismatch")
            offsets.append(len(data))
            for i, (name, t) in enumerate(var_fields):
                if offsets[i + 1] < offsets[i]:
                    raise SSZError(f"{cls.__name__}: non-monotonic offsets")
                fixed_vals[name] = t.decode(data[offsets[i] : offsets[i + 1]])
        elif len(data) != fixed_len:
            raise SSZError(f"{cls.__name__}: trailing bytes")
        obj = cls.__new__(cls)
        for name, _ in cls.FIELDS:
            setattr(obj, name, fixed_vals[name])
        return obj

    @classmethod
    def hash_tree_root(cls, value=None) -> bytes:
        v = value if value is not None else cls
        roots = np.stack(
            [
                np.frombuffer(t.hash_tree_root(getattr(v, n)), dtype=np.uint8)
                for n, t in cls.FIELDS
            ]
        )
        return merkleize_chunks(roots)

    def tree_root(self) -> bytes:
        return type(self).hash_tree_root(self)

    @classmethod
    def default(cls):
        return cls()


def _val_eq(a, b) -> bool:
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        return a.shape == b.shape and bool((a == b).all())
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_val_eq(x, y) for x, y in zip(a, b))
    return a == b


# -- free functions ---------------------------------------------------------------


def serialize(typ, value=None) -> bytes:
    if isinstance(typ, type) and issubclass(typ, Container):
        return typ.encode(value if value is not None else typ)
    if isinstance(typ, Container):
        return typ.serialize()
    return typ.encode(value)


def deserialize(typ, data: bytes):
    return typ.decode(data)


def hash_tree_root(typ, value=None) -> bytes:
    if isinstance(typ, Container):  # instance given directly
        return typ.tree_root()
    return typ.hash_tree_root(value)
