"""SSZ merkleization over vectorized SHA-256.

Chunks are [n, 32] uint8 rows; the tree reduction hashes all sibling pairs of
a level in one ``sha256_pairs`` call. Virtual zero-subtree padding (the
``ZERO_HASHES`` ladder) keeps a List[*, 2^40] with 5 elements costing 5 real
hashes per level, not 2^39. Parity: the ``tree_hash`` crate's merkleize_padded
(``/root/reference/consensus/tree_hash/src/merkle_hash.rs``).
"""

from __future__ import annotations

import numpy as np

from .sha256 import sha256_pairs

_MAX_DEPTH = 64

ZERO_HASHES = np.zeros((_MAX_DEPTH + 1, 32), dtype=np.uint8)
for _i in range(_MAX_DEPTH):
    ZERO_HASHES[_i + 1] = sha256_pairs(
        np.concatenate([ZERO_HASHES[_i], ZERO_HASHES[_i]])[None, :]
    )[0]


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def merkleize_chunks(chunks: np.ndarray, limit: int | None = None) -> bytes:
    """Merkle root of [n, 32] chunk rows, padded (virtually) to ``limit``
    leaves (or next_pow2(n) when limit is None)."""
    chunks = np.asarray(chunks, dtype=np.uint8).reshape(-1, 32)
    n = chunks.shape[0]
    if limit is not None and n > limit:
        raise ValueError(f"{n} chunks exceeds limit {limit}")
    leaves = limit if limit is not None else max(n, 1)
    depth = (next_pow2(leaves) - 1).bit_length()
    level = chunks
    for d in range(depth):
        m = level.shape[0]
        if m == 0:
            return bytes(ZERO_HASHES[depth])
        if m % 2:
            level = np.concatenate([level, ZERO_HASHES[d][None, :]], axis=0)
            m += 1
        level = sha256_pairs(level.reshape(m // 2, 64))
    if level.shape[0] == 0:
        return bytes(ZERO_HASHES[depth])
    return bytes(level[0])


def merkle_branch_from_chunks(
    chunks: np.ndarray, limit: int, index: int
) -> list[bytes]:
    """Sibling branch (bottom-up) for leaf ``index`` in the padded tree that
    ``merkleize_chunks(chunks, limit)`` roots — proof *generation*, the
    counterpart of ``is_valid_merkle_branch`` (ref merkle_proof's
    ``MerkleTree::generate_proof``; needed by BlobSidecar inclusion proofs
    and the light-client server)."""
    chunks = np.asarray(chunks, dtype=np.uint8).reshape(-1, 32)
    depth = (next_pow2(max(limit, 1)) - 1).bit_length()
    branch: list[bytes] = []
    level = chunks
    idx = index
    for d in range(depth):
        sib = idx ^ 1
        branch.append(
            bytes(level[sib]) if sib < level.shape[0] else bytes(ZERO_HASHES[d])
        )
        m = level.shape[0]
        if m % 2:
            level = np.concatenate([level, ZERO_HASHES[d][None, :]], axis=0)
            m += 1
        level = (
            sha256_pairs(level.reshape(m // 2, 64))
            if m
            else np.zeros((0, 32), np.uint8)
        )
        idx //= 2
    return branch


def fold_merkle_branch(leaf: bytes, branch: list[bytes], index: int) -> bytes:
    """Root implied by a leaf + sibling branch (direction bits from index)."""
    node = np.frombuffer(leaf, dtype=np.uint8)
    for i, sib in enumerate(branch):
        s = np.frombuffer(sib, dtype=np.uint8)
        pair = (
            np.concatenate([s, node])
            if (index >> i) & 1
            else np.concatenate([node, s])
        )
        node = sha256_pairs(pair[None, :])[0]
    return bytes(node)


def mix_in_length(root: bytes, length: int) -> bytes:
    block = np.zeros(64, dtype=np.uint8)
    block[:32] = np.frombuffer(root, dtype=np.uint8)
    block[32:40] = np.frombuffer(
        length.to_bytes(8, "little"), dtype=np.uint8
    )
    return bytes(sha256_pairs(block[None, :])[0])


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return mix_in_length(root, selector)
