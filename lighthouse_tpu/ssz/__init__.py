"""SSZ: SimpleSerialize type system, serialization, and merkleization.

The TPU-twin of the reference's ``consensus/types`` SSZ substrate (ethereum_ssz
+ tree_hash crates). Vectorized numpy SHA-256 makes whole-tree merkleization a
batched array op rather than a per-node call.
"""

from .core import (
    SSZError,
    boolean,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    Union,
    hash_tree_root,
    serialize,
    deserialize,
)
from .merkle import merkleize_chunks, mix_in_length, ZERO_HASHES
from .sha256 import sha256_pairs, sha256 as sha256_bytes
