"""Vectorized SHA-256 over numpy uint32 lanes.

``sha256_pairs`` compresses [n, 64]-byte blocks (two 32-byte tree nodes each)
into [n, 32] digests in one numpy pass — the primitive behind merkleization
(every interior node of an SSZ hash tree is sha256(left || right), a fixed
one-block-plus-padding schedule) and the swap-or-not shuffle rounds. For a
1M-validator state the registry tree is ~2M nodes; per-call hashlib would pay
2M Python round-trips, this pays ~21 vectorized rounds of 64 steps.

Parity: ``ethereum_hashing`` crate (the reference's sha256 with x86 SHA-NI —
here the SIMD lanes are numpy's, and jax variants can lower the same schedule
to TPU if profiling ever puts tree hashing on the critical path).
"""

from __future__ import annotations

import hashlib

import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)

# The padding block for a 64-byte message: 0x80, zeros, bit-length 512.
_PAD_BLOCK = np.zeros(16, dtype=np.uint32)
_PAD_BLOCK[0] = 0x80000000
_PAD_BLOCK[15] = 512


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: np.ndarray, w0: np.ndarray) -> np.ndarray:
    """One compression round. state [n, 8]; w0 [n, 16] big-endian words."""
    w = [w0[:, i] for i in range(16)]
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        w.append(w[i - 16] + s0 + w[i - 7] + s1)
    a, b, c, d, e, f, g, h = (state[:, i].copy() for i in range(8))
    for i in range(64):
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + _K[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    return state + np.stack([a, b, c, d, e, f, g, h], axis=1)


# Below this batch size, per-hash hashlib (C speed) beats the numpy path,
# whose ~128 python-level rounds cost ~1ms regardless of n.
_VECTOR_MIN = 2048


def sha256_pairs(blocks: np.ndarray) -> np.ndarray:
    """SHA-256 of n 64-byte messages. blocks [n, 64] uint8 -> [n, 32] uint8."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    n = blocks.shape[0]
    if n < _VECTOR_MIN:
        buf = blocks.tobytes()
        out = b"".join(
            hashlib.sha256(buf[64 * i : 64 * i + 64]).digest() for i in range(n)
        )
        return np.frombuffer(out, dtype=np.uint8).reshape(n, 32)
    w0 = blocks.view(">u4").astype(np.uint32).reshape(n, 16)
    state = np.broadcast_to(_H0, (n, 8))
    state = _compress(state, w0)
    state = _compress(state, np.broadcast_to(_PAD_BLOCK, (n, 16)))
    return np.ascontiguousarray(
        state.astype(">u4"), dtype=None
    ).view(np.uint8).reshape(n, 32)


def sha256_short(msgs: np.ndarray, msg_len: int) -> np.ndarray:
    """SHA-256 of n messages of a fixed length <= 55 bytes (single padded
    block, ONE compression). msgs [n, msg_len] uint8 -> [n, 32] uint8."""
    assert msg_len <= 55, "single-block padding only"
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n = msgs.shape[0]
    if n < _VECTOR_MIN:
        buf = msgs.tobytes()
        out = b"".join(
            hashlib.sha256(buf[msg_len * i : msg_len * (i + 1)]).digest()
            for i in range(n)
        )
        return np.frombuffer(out, dtype=np.uint8).reshape(n, 32)
    blocks = np.zeros((n, 64), dtype=np.uint8)
    blocks[:, :msg_len] = msgs
    blocks[:, msg_len] = 0x80
    bitlen = msg_len * 8
    blocks[:, 62] = (bitlen >> 8) & 0xFF
    blocks[:, 63] = bitlen & 0xFF
    w0 = blocks.view(">u4").astype(np.uint32).reshape(n, 16)
    state = _compress(np.broadcast_to(_H0, (n, 8)), w0)
    return np.ascontiguousarray(
        state.astype(">u4"), dtype=None
    ).view(np.uint8).reshape(n, 32)


def sha256(data: bytes) -> bytes:
    """Single-shot arbitrary-length hash (host convenience; hashlib-backed)."""
    return hashlib.sha256(data).digest()
