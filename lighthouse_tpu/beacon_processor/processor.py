"""Bounded priority-queue scheduler with batch forming.

Parity: ``/root/reference/beacon_node/beacon_processor/src/lib.rs`` — a
manager owns one bounded queue per ``WorkType`` (:555-680), pops strictly by
priority, spawns up to n workers, drops on overflow (:1-39,77-99), and folds
queued gossip attestations/aggregates into batches of up to 64
(:219-254,1074-1090). TPU-first deviation (SURVEY §7.7): batch sizes are
shape-bucketed and the cap is configurable upward — the device backend wants
larger, shape-stable batches; per-set poisoning fallback keeps the 64-limit's
error-fidelity rationale intact at any size.
"""

from __future__ import annotations

import enum
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..utils.metrics import (
    PROCESSOR_EXPIRED_DROPS,
    PROCESSOR_OVERFLOW_DROPS,
    PROCESSOR_QUEUE_LENGTH,
    PROCESSOR_WORK_EVENTS,
)


class WorkType(enum.Enum):
    # priority order: lower value = higher priority (lib.rs manager match order)
    ChainSegmentBackfill = 0
    GossipBlock = 1
    GossipBlobSidecar = 2
    RpcBlock = 3
    ChainSegment = 4
    GossipAggregate = 5
    GossipAttestation = 6
    UnknownBlockAggregate = 7
    UnknownBlockAttestation = 8
    GossipVoluntaryExit = 9
    GossipProposerSlashing = 10
    GossipAttesterSlashing = 11
    GossipSyncSignature = 12
    GossipSyncContribution = 13
    ApiRequestP0 = 14
    ApiRequestP1 = 15
    Status = 16
    BlocksByRangeRequest = 17
    BlocksByRootsRequest = 18
    LightClientUpdate = 19


# which queues are LIFO (freshest-first: attestations age out fast; lib.rs)
_LIFO = {
    WorkType.GossipAttestation,
    WorkType.GossipAggregate,
    WorkType.GossipSyncSignature,
}

# batchable work: (batch cap mirrors max_gossip_attestation_batch_size = 64,
# lib.rs:219-231; configurable upward for the device backend)
_BATCHABLE = {WorkType.GossipAttestation, WorkType.GossipAggregate}


@dataclass
class Work:
    """One unit of work. ``process_individual(item)`` handles a single item;
    ``process_batch(items)`` an entire batch (lib.rs:555-571).

    ``ingest_at``/``deadline`` carry the wire-ingest monotonic timestamp and
    the work's absolute expiry (loadshed.deadline): expired work is dropped
    BEFORE it reaches any handler or device dispatch. ``deadline=None``
    means the work never expires (the legacy behaviour)."""

    work_type: WorkType
    item: object
    process_individual: object = None
    process_batch: object = None
    ingest_at: float = field(default_factory=time.monotonic)
    deadline: float | None = None


@dataclass
class QueueLengths:
    """Per-type bounds scaled by active-validator count
    (BeaconProcessorQueueLengths::from_state, lib.rs:102-144)."""

    default: int = 16384
    overrides: dict = field(default_factory=dict)

    @classmethod
    def from_active_validators(cls, n_active: int) -> "QueueLengths":
        # 110% of one attestation per validator per epoch, min 128
        att = max(128, n_active * 11 // 10)
        return cls(
            overrides={
                WorkType.GossipAttestation: att,
                WorkType.GossipAggregate: max(128, att // 16),
                WorkType.UnknownBlockAttestation: max(128, att // 8),
            }
        )

    def limit(self, t: WorkType) -> int:
        return self.overrides.get(t, self.default)


@dataclass
class BeaconProcessorConfig:
    max_workers: int = 4
    max_batch_size: int = 64          # per-type batch cap (lib.rs:230)
    queue_lengths: QueueLengths = field(default_factory=QueueLengths)


class BeaconProcessor:
    """Manager + worker pool. ``synchronous=True`` runs work inline on
    ``submit``/``run_until_idle`` (the test mode); otherwise worker threads
    drain the queues continuously."""

    def __init__(self, config: BeaconProcessorConfig | None = None,
                 synchronous: bool = False, firehose=None):
        self.config = config or BeaconProcessorConfig()
        # optional streaming verification engine (firehose/engine.py):
        # batchable gossip work WITHOUT explicit handlers routes straight
        # into its intake instead of the generic queues
        self.firehose = firehose
        self.queues: dict[WorkType, deque] = {t: deque() for t in WorkType}
        self.dropped: dict[WorkType, int] = {t: 0 for t in WorkType}
        self.expired: dict[WorkType, int] = {t: 0 for t in WorkType}
        self.processed: dict[WorkType, int] = {t: 0 for t in WorkType}
        self.batches_formed = 0
        self._lock = threading.Lock()
        self._work_ready = threading.Condition(self._lock)
        self._shutdown = False
        self.synchronous = synchronous
        self._workers: list[threading.Thread] = []
        self._idle_workers = 0
        if not synchronous:
            for i in range(self.config.max_workers):
                w = threading.Thread(target=self._worker_loop, daemon=True,
                                     name=f"beacon-worker-{i}")
                w.start()
                self._workers.append(w)

    # -- submission (back-pressure at enqueue, drop on overflow) -----------------

    def submit(self, work: Work) -> bool:
        if work.deadline is not None and time.monotonic() > work.deadline:
            # already expired at ingest: never spend queue space or BLS
            # cycles on work whose client/inclusion window has passed
            with self._lock:
                self.expired[work.work_type] += 1
            PROCESSOR_EXPIRED_DROPS.inc(work_type=work.work_type.name)
            return False
        if (
            self.firehose is not None
            and work.work_type in _BATCHABLE
            and work.process_individual is None
            and work.process_batch is None
        ):
            # firehose-eligible gossip work: the engine owns batching,
            # back-pressure and verdict application end to end
            ok = self.firehose.submit(
                work.item, work_type=work.work_type,
                ingest_at=work.ingest_at, deadline=work.deadline,
            )
            with self._lock:
                if ok:
                    PROCESSOR_WORK_EVENTS.inc(work_type=work.work_type.name)
                else:
                    self.dropped[work.work_type] += 1
            return ok
        with self._lock:
            q = self.queues[work.work_type]
            if len(q) >= self.config.queue_lengths.limit(work.work_type):
                self.dropped[work.work_type] += 1
                PROCESSOR_OVERFLOW_DROPS.inc(work_type=work.work_type.name)
                if work.work_type not in _LIFO:
                    return False
                # freshest-first queues evict the OLDEST item (the tail)
                # and admit the fresh one: under overload the stale end of
                # an attestation queue is the least likely to still matter
                q.pop()
            if work.work_type in _LIFO:
                q.appendleft(work)
            else:
                q.append(work)
            PROCESSOR_WORK_EVENTS.inc(work_type=work.work_type.name)
            PROCESSOR_QUEUE_LENGTH.set(len(q), work_type=work.work_type.name)
            self._work_ready.notify()
        if self.synchronous:
            self.run_until_idle()
        return True

    # -- scheduling --------------------------------------------------------------

    def _expired_locked(self, w: Work, now: float) -> bool:
        """Deadline check at dispatch time; counts the drop. Caller holds
        the lock."""
        if w.deadline is None or now <= w.deadline:
            return False
        self.expired[w.work_type] += 1
        PROCESSOR_EXPIRED_DROPS.inc(work_type=w.work_type.name)
        return True

    def _pop_next(self):
        """Highest-priority nonempty queue -> one Work or a formed batch.
        Expired work is shed here — the last gate before any handler or
        BLS/device dispatch. Caller holds the lock."""
        for t in WorkType:
            q = self.queues[t]
            if not q:
                continue
            now = time.monotonic()
            if t in _BATCHABLE and len(q) > 1:
                n = min(len(q), self.config.max_batch_size)
                items = []
                while q and len(items) < n:
                    w = q.popleft()
                    if not self._expired_locked(w, now):
                        items.append(w)
                PROCESSOR_QUEUE_LENGTH.set(len(q), work_type=t.name)
                if not items:
                    continue
                if len(items) == 1:
                    return ("one", t, items[0])
                self.batches_formed += 1
                return ("batch", t, items)
            popped = q.popleft()
            while popped is not None and self._expired_locked(popped, now):
                popped = q.popleft() if q else None
            PROCESSOR_QUEUE_LENGTH.set(len(q), work_type=t.name)
            if popped is None:
                continue
            return ("one", t, popped)
        return None

    def _execute(self, popped) -> None:
        kind, t, payload = popped
        if kind == "batch":
            lead = payload[0]
            if lead.process_batch is not None:
                lead.process_batch([w.item for w in payload])
            else:
                for w in payload:
                    if w.process_individual:
                        w.process_individual(w.item)
            with self._lock:
                self.processed[t] += len(payload)
        else:
            if payload.process_individual:
                payload.process_individual(payload.item)
            elif payload.process_batch:
                payload.process_batch([payload.item])
            with self._lock:
                self.processed[t] += 1

    def run_until_idle(self) -> int:
        """Drain all queues inline; returns number of dispatches."""
        n = 0
        while True:
            with self._lock:
                popped = self._pop_next()
            if popped is None:
                return n
            self._execute(popped)
            n += 1

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                while not self._shutdown:
                    popped = self._pop_next()
                    if popped is not None:
                        break
                    self._work_ready.wait(timeout=0.1)
                if self._shutdown:
                    return
            self._execute(popped)

    def queue_len(self, t: WorkType) -> int:
        with self._lock:
            return len(self.queues[t])

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._work_ready.notify_all()
        for w in self._workers:
            w.join(timeout=1.0)
