"""Work scheduler (beacon_node/beacon_processor twin)."""

from .processor import (
    BeaconProcessor,
    BeaconProcessorConfig,
    Work,
    WorkType,
    QueueLengths,
)
from .reprocess import ReprocessQueue
