"""Re-scheduling of early/orphan work (work_reprocessing_queue.rs, 1,183 LoC).

Attestations for unknown blocks wait until the block arrives (or expire);
early-arriving blocks wait until their slot starts; backfill batches wait for
idle. Here the queue is slot-driven (the chain pokes ``on_slot`` /
``on_block_imported``) rather than tokio-timer-driven.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class QueuedUnknownBlockWork:
    block_root: bytes
    work: object
    queued_at_slot: int


EXPIRY_SLOTS = 2  # attestations are valid for ~1 epoch; requeue window


class ReprocessQueue:
    def __init__(self, resubmit):
        """``resubmit(work)`` re-enqueues into the BeaconProcessor."""
        self.resubmit = resubmit
        self._awaiting_block: dict[bytes, list] = defaultdict(list)
        self._early_blocks: list = []  # (slot, work)
        self._backfill: list = []
        self.expired = 0

    def queue_unknown_block_work(self, block_root: bytes, work, slot: int) -> None:
        self._awaiting_block[bytes(block_root)].append(
            QueuedUnknownBlockWork(bytes(block_root), work, slot)
        )

    def queue_early_block(self, slot: int, work) -> None:
        self._early_blocks.append((slot, work))

    def queue_backfill(self, work) -> None:
        self._backfill.append(work)

    def on_block_imported(self, block_root: bytes) -> int:
        """Release attestations that were waiting on this block."""
        released = self._awaiting_block.pop(bytes(block_root), [])
        for q in released:
            self.resubmit(q.work)
        return len(released)

    def on_slot(self, current_slot: int) -> None:
        # release due blocks
        due = [w for s, w in self._early_blocks if s <= current_slot]
        self._early_blocks = [
            (s, w) for s, w in self._early_blocks if s > current_slot
        ]
        for w in due:
            self.resubmit(w)
        # expire stale unknown-block waiters
        for root in list(self._awaiting_block):
            fresh = [
                q
                for q in self._awaiting_block[root]
                if q.queued_at_slot + EXPIRY_SLOTS >= current_slot
            ]
            self.expired += len(self._awaiting_block[root]) - len(fresh)
            if fresh:
                self._awaiting_block[root] = fresh
            else:
                del self._awaiting_block[root]

    def on_idle(self) -> None:
        if self._backfill:
            self.resubmit(self._backfill.pop(0))
