"""Umbrella CLI (ref lighthouse/src/main.rs:88-481 + beacon_node/src/cli.rs).

``python -m lighthouse_tpu <subcommand>``:

  bn               run a beacon node (HTTP API + metrics + optional slasher)
  vc               run a validator client against a beacon node
  account-manager  create EIP-2335 validator keystores
  version          print versions

Global flags select the spec preset and debug level; the spec-at-runtime
monomorphization of ``run::<E>()`` maps to preset selection here.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import __version__


def _spec(args):
    from .types.spec import ChainSpec, mainnet_spec, minimal_spec

    platform = getattr(args, "platform", "auto")
    if platform != "auto":
        # must land before the first device use (backend init is lazy; the
        # package import itself only sets config flags)
        import jax

        jax.config.update("jax_platforms", platform)
    backend = getattr(args, "bls_backend", None)
    if backend:
        from . import bls

        bls.set_backend(backend)
    epoch_backend = getattr(args, "epoch_backend", None)
    if epoch_backend:
        from . import epoch_engine

        epoch_engine.set_backend(epoch_backend)

    kwargs = {}
    for fork in ("altair", "bellatrix", "capella", "deneb", "electra"):
        v = getattr(args, f"{fork}_fork_epoch", None)
        if v is not None:
            kwargs[f"{fork}_fork_epoch"] = v
    if args.preset == "minimal":
        return minimal_spec(**kwargs)
    return mainnet_spec(**kwargs) if kwargs else mainnet_spec()


def _add_spec_flags(p):
    p.add_argument(
        "--preset", choices=("mainnet", "minimal"), default="mainnet",
        help="compile-time preset analog (EthSpec selection, main.rs:449)",
    )
    p.add_argument("--debug-level", default="info",
                   choices=("debug", "info", "warning", "error"))
    p.add_argument(
        "--bls-backend", default=None, choices=("tpu", "native", "oracle"),
        help="BLS backend (the reference's blst/fake_crypto cargo-feature "
             "seam, crypto/bls/src/lib.rs:8-18): tpu = JAX device kernels "
             "(the default), native = C++ CPU parity backend, oracle = pure "
             "Python. Unset = keep the process's current backend.",
    )
    p.add_argument(
        "--epoch-backend", default=None, choices=("auto", "device", "numpy"),
        help="epoch-processing backend (lighthouse_tpu/epoch_engine): "
             "device = fused jitted sweep over the device-resident registry "
             "mirror, numpy = columnar host path, auto = device iff an "
             "accelerator backs JAX. Unset = keep the process's current "
             "backend (env LIGHTHOUSE_EPOCH_BACKEND, default auto).",
    )
    p.add_argument(
        "--platform", default="auto", choices=("auto", "cpu", "tpu"),
        help="JAX platform: 'cpu' forces host execution even where an "
             "accelerator plugin force-selects itself (the devcpu.py recipe)",
    )
    for fork in ("altair", "bellatrix", "capella", "deneb", "electra"):
        p.add_argument(f"--{fork}-fork-epoch", type=int, default=None)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="lighthouse_tpu", description="TPU-native consensus client"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    bn = sub.add_parser("bn", help="beacon node")
    _add_spec_flags(bn)
    bn.add_argument("--datadir", default=None)
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--disable-http", action="store_true")
    bn.add_argument("--metrics", action="store_true")
    bn.add_argument("--metrics-port", type=int, default=5054)
    bn.add_argument("--slasher", action="store_true")
    bn.add_argument("--interop-validators", type=int, default=64)
    bn.add_argument("--genesis-time", type=int, default=None)
    bn.add_argument(
        "--listen-port", type=int, default=None,
        help="TCP gossip/RPC listener port (0 = ephemeral; unset = no p2p)",
    )
    bn.add_argument(
        "--boot-nodes", default="",
        help="comma-separated UDP boot-node addresses for peer discovery",
    )
    bn.add_argument(
        "--boot-enrs", default="",
        help="comma-separated hex ENRs for discv5-style discovery",
    )
    bn.add_argument(
        "--validator-monitor-auto", action="store_true",
        help="monitor every validator (validator_monitor.rs auto mode)",
    )
    bn.add_argument(
        "--validator-monitor-indices", default="",
        help="comma-separated validator indices to monitor",
    )
    bn.add_argument(
        "--checkpoint-sync-url", default=None,
        help="boot from another node's finalized state over HTTP instead of "
             "genesis (client/src/builder.rs checkpoint-sync branch)",
    )

    vc = sub.add_parser("vc", help="validator client")
    _add_spec_flags(vc)
    vc.add_argument(
        "--beacon-node", default="http://127.0.0.1:5052",
        help="beacon node URL(s), comma-separated for health-scored failover "
             "(beacon_node_fallback)",
    )
    vc.add_argument("--validators-dir", default=None)
    vc.add_argument("--password", default="")
    vc.add_argument("--interop-validators", type=int, default=0)
    vc.add_argument(
        "--enable-doppelganger-protection", action="store_true",
        help="hold back signing until liveness checks show no duplicate "
             "instance of our keys (doppelganger_service)",
    )
    vc.add_argument(
        "--keymanager-port", type=int, default=None,
        help="serve the keymanager API (keystores/remotekeys CRUD) on this "
             "port (0 = ephemeral)",
    )
    vc.add_argument(
        "--web3signer-url", default=None,
        help="register all keys served by this remote signer "
             "(signing_method/web3signer)",
    )

    am = sub.add_parser("account-manager", aliases=["am"],
                        help="create validator keystores")
    _add_spec_flags(am)
    am.add_argument("--output-dir", required=True)
    am.add_argument("--count", type=int, default=1)
    am.add_argument("--password", required=True)
    am.add_argument("--mnemonic-seed", default=None,
                    help="hex seed for EIP-2333 derivation (random if unset)")

    dm = sub.add_parser(
        "database-manager", aliases=["dm"],
        help="inspect/migrate/compact the on-disk stores (ref database_manager/)",
    )
    _add_spec_flags(dm)
    dm.add_argument("command_db", choices=("inspect", "version", "migrate", "compact"))
    dm.add_argument("--datadir", required=True)

    lcli = sub.add_parser(
        "lcli", help="dev utilities: skip-slots, transition-blocks, pretty-ssz"
    )
    _add_spec_flags(lcli)
    lcli.add_argument(
        "command_lcli", choices=("skip-slots", "transition-blocks", "pretty-ssz")
    )
    lcli.add_argument("--pre-state", help="input state SSZ file")
    lcli.add_argument("--output", help="output file (state SSZ / JSON)")
    lcli.add_argument("--slots", type=int, default=1)
    lcli.add_argument("--blocks", nargs="*", default=[], help="block SSZ files")
    lcli.add_argument("--type", dest="ssz_type", help="container name")
    lcli.add_argument("--ssz-file", help="SSZ input for pretty-ssz")

    vm = sub.add_parser(
        "validator-manager", aliases=["vm"],
        help="bulk create/import validators (ref validator_manager/)",
    )
    _add_spec_flags(vm)
    vm.add_argument("command_vm", choices=("create", "import", "list"))
    vm.add_argument("--output-dir")
    vm.add_argument("--keystores-dir")
    vm.add_argument("--count", type=int, default=1)
    vm.add_argument("--first-index", type=int, default=0)
    vm.add_argument("--password", default="")
    vm.add_argument("--mnemonic-seed", default=None)
    vm.add_argument("--vc-url", help="running VC keymanager API url")

    boot = sub.add_parser(
        "boot-node", help="UDP discovery rendezvous (ref boot_node/)"
    )
    boot.add_argument("--port", type=int, default=4242)
    boot.add_argument("--host", default="0.0.0.0")
    boot.add_argument(
        "--enr", action="store_true",
        help="serve discv5-style ENR discovery (prints this node's ENR hex)",
    )
    boot.add_argument(
        "--fork-digest", default="00000000",
        help="hex fork digest the ENR advertises (--enr mode)",
    )

    sub.add_parser("version", help="print version")
    return parser


def run_bn(args) -> "object":
    from .client import ClientBuilder, ClientConfig

    spec = _spec(args)
    cfg = ClientConfig(
        datadir=args.datadir,
        http_enabled=not args.disable_http,
        http_port=args.http_port,
        metrics_enabled=args.metrics,
        metrics_port=args.metrics_port,
        slasher_enabled=args.slasher,
        interop_validators=args.interop_validators,
        genesis_time=args.genesis_time,
        debug_level=args.debug_level,
        listen_port=args.listen_port,
        boot_nodes=args.boot_nodes,
        boot_enrs=args.boot_enrs,
        validator_monitor_auto=args.validator_monitor_auto,
        validator_monitor_indices=tuple(
            int(x) for x in args.validator_monitor_indices.split(",") if x
        ),
    )
    builder = ClientBuilder(spec, cfg)
    if args.checkpoint_sync_url:
        builder.checkpoint_sync(args.checkpoint_sync_url)
    return builder.build().start()


def run_vc(args):
    from .utils.logging import init_logging
    from .validator_client.runner import ProductionValidatorClient

    init_logging(args.debug_level)
    spec = _spec(args)
    vc = ProductionValidatorClient(
        spec, args.beacon_node,
        enable_doppelganger=args.enable_doppelganger_protection,
        keymanager_port=args.keymanager_port,
    )
    if args.validators_dir:
        vc.load_keystore_dir(args.validators_dir, args.password)
    if args.interop_validators:
        vc.load_interop_keys(args.interop_validators)
    if args.web3signer_url:
        vc.load_web3signer(args.web3signer_url)
    return vc.connect()


def run_account_manager(args) -> list[str]:
    """Derive EIP-2333 keys and write EIP-2335 keystores
    (account_manager validator create)."""
    from .keys.derivation import derive_sk_from_path
    from .keys.keystore import Keystore

    os.makedirs(args.output_dir, exist_ok=True)
    seed = (
        bytes.fromhex(args.mnemonic_seed)
        if args.mnemonic_seed
        else os.urandom(32)
    )
    written = []
    for i in range(args.count):
        path = f"m/12381/3600/{i}/0/0"
        sk = derive_sk_from_path(seed, path)
        ks = Keystore.encrypt(
            sk.to_bytes(32, "big"),
            args.password,
            path=path,
        )
        name = f"keystore-{i}.json"
        with open(os.path.join(args.output_dir, name), "w") as fh:
            fh.write(ks.to_json())
        written.append(name)
    print(json.dumps({"wrote": written, "dir": args.output_dir}))
    return written


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        print(f"lighthouse_tpu/{__version__}")
        return 0
    if args.command == "bn":
        client = run_bn(args)
        client.wait_for_shutdown()
        return 0
    if args.command == "vc":
        vc = run_vc(args)
        try:
            vc.run()
        except KeyboardInterrupt:
            vc.stop()
        return 0
    if args.command in ("account-manager", "am"):
        run_account_manager(args)
        return 0
    if args.command in ("database-manager", "dm"):
        from . import tools

        fn = {
            "inspect": tools.db_inspect, "version": tools.db_version,
            "migrate": tools.db_migrate, "compact": tools.db_compact,
        }[args.command_db]
        print(json.dumps(fn(args.datadir), indent=2))
        return 0
    if args.command == "lcli":
        from . import tools

        need = {
            "skip-slots": ("pre_state", "output"),
            "transition-blocks": ("pre_state", "output"),
            "pretty-ssz": ("ssz_file", "ssz_type"),
        }[args.command_lcli]
        missing = [n for n in need if not getattr(args, n)]
        if missing:
            build_parser().error(
                f"lcli {args.command_lcli} requires "
                + ", ".join("--" + n.replace("_", "-") for n in missing)
            )
        spec = _spec(args)
        if args.command_lcli == "skip-slots":
            with open(args.pre_state, "rb") as fh:
                out = tools.skip_slots(spec, fh.read(), args.slots)
            with open(args.output, "wb") as fh:
                fh.write(out)
            print(json.dumps({"wrote": args.output, "bytes": len(out)}))
        elif args.command_lcli == "transition-blocks":
            with open(args.pre_state, "rb") as fh:
                pre = fh.read()
            blocks = []
            for b in args.blocks:
                with open(b, "rb") as fh:
                    blocks.append(fh.read())
            out = tools.transition_blocks(spec, pre, blocks)
            with open(args.output, "wb") as fh:
                fh.write(out)
            print(json.dumps({"wrote": args.output, "bytes": len(out)}))
        else:
            with open(args.ssz_file, "rb") as fh:
                obj = tools.pretty_ssz(spec, args.ssz_type, fh.read())
            print(json.dumps(obj, indent=2))
        return 0
    if args.command in ("validator-manager", "vm"):
        from . import tools

        need = {
            "create": ("output_dir",),
            "import": ("keystores_dir", "vc_url"),
            "list": ("vc_url",),
        }[args.command_vm]
        missing = [n for n in need if not getattr(args, n)]
        if missing:
            build_parser().error(
                f"validator-manager {args.command_vm} requires "
                + ", ".join("--" + n.replace("_", "-") for n in missing)
            )
        if args.command_vm == "create":
            written = tools.vm_create(
                args.output_dir, args.count, args.password,
                args.mnemonic_seed, args.first_index,
            )
            print(json.dumps({"wrote": written, "dir": args.output_dir}))
        elif args.command_vm == "import":
            print(json.dumps(
                tools.vm_import(args.keystores_dir, args.password, args.vc_url)
            ))
        else:
            print(json.dumps(tools.vm_list(args.vc_url)))
        return 0
    if args.command == "boot-node":
        import time

        from .utils.logging import init_logging

        init_logging("info")
        if args.enr:
            from .network.discovery import DiscoveryService

            node = DiscoveryService(
                fork_digest=bytes.fromhex(args.fork_digest),
                ip=args.host, udp_port=args.port,
            ).start()
            print(json.dumps({"enr": node.enr.encode().hex()}), flush=True)
        else:
            from .network.boot_node import BootNode

            node = BootNode(host=args.host, port=args.port).start()
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            node.stop()
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
