"""Array-backed LMD-GHOST fork-choice graph.

Parity: ``/root/reference/consensus/proto_array/src/proto_array.rs`` and
``proto_array_fork_choice.rs:357``. Nodes live in an append-only array with
parent indices; weight propagation is a single reverse sweep applying score
deltas child→parent and recomputing best_child/best_descendant — O(n) per
call, no recursion. Votes (``VoteTracker``, ``:25``) are columnar numpy arrays
indexed by validator: the 1M-validator vote table is three uint64/int64
columns, and the per-epoch delta computation is a vectorized gather/scatter
(``fork_choice_test_definition`` semantics, TPU-friendly shape).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np


class ExecutionStatus(enum.Enum):
    """Optimistic-sync payload status (proto_array/src/proto_array_fork_choice.rs)."""

    VALID = "valid"
    INVALID = "invalid"
    OPTIMISTIC = "optimistic"  # not yet verified by an EL
    IRRELEVANT = "irrelevant"  # pre-merge block


@dataclass
class ProtoNode:
    root: bytes
    parent: int | None
    justified_epoch: int
    finalized_epoch: int
    slot: int
    state_root: bytes = b""
    target_root: bytes = b""
    execution_block_hash: bytes | None = None
    execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT
    weight: int = 0
    best_child: int | None = None
    best_descendant: int | None = None
    unrealized_justified_epoch: int | None = None
    unrealized_finalized_epoch: int | None = None


class ProtoArrayError(Exception):
    pass


class ProtoArrayForkChoice:
    def __init__(
        self,
        finalized_root: bytes,
        finalized_slot: int,
        justified_epoch: int,
        finalized_epoch: int,
        justified_root: bytes | None = None,
    ):
        self.nodes: list[ProtoNode] = []
        self.indices: dict[bytes, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.justified_root = justified_root or finalized_root
        self.finalized_root = finalized_root
        self.proposer_boost_root: bytes = b"\x00" * 32
        # votes: columnar (current_root_idx+1, next_root_idx+1, next_epoch);
        # 0 means "no vote" — index offset by one for vectorized handling
        self._vote_cur = np.zeros(0, dtype=np.int64)
        self._vote_next = np.zeros(0, dtype=np.int64)
        self._vote_epoch = np.zeros(0, dtype=np.uint64)
        self._old_balances = np.zeros(0, dtype=np.int64)  # last-applied balances
        self._root_ids: dict[bytes, int] = {}
        self._id_roots: list[bytes] = [b"\x00" * 32]  # id 0 = null
        # memoized descends-from-finalized, invalidated on finalization
        self._fin_desc_key: bytes | None = None
        self._fin_desc: dict[int, bool] = {}
        self.on_block(
            slot=finalized_slot,
            root=finalized_root,
            parent_root=None,
            state_root=b"\x00" * 32,
            target_root=finalized_root,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
            execution_status=ExecutionStatus.IRRELEVANT,
        )

    # -- roots <-> small ids for the vote table --------------------------------

    def _root_id(self, root: bytes) -> int:
        rid = self._root_ids.get(root)
        if rid is None:
            rid = len(self._id_roots)
            self._root_ids[root] = rid
            self._id_roots.append(root)
        return rid

    def _ensure_votes(self, n_validators: int) -> None:
        cur = self._vote_cur.shape[0]
        if n_validators > cur:
            grow = n_validators - cur
            self._vote_cur = np.concatenate([self._vote_cur, np.zeros(grow, np.int64)])
            self._vote_next = np.concatenate([self._vote_next, np.zeros(grow, np.int64)])
            self._vote_epoch = np.concatenate(
                [self._vote_epoch, np.zeros(grow, np.uint64)]
            )

    # -- block insertion (proto_array.rs on_block) ------------------------------

    def get_node(self, root: bytes):
        idx = self.indices.get(root)
        return self.nodes[idx] if idx is not None else None

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes | None,
        state_root: bytes,
        target_root: bytes,
        justified_epoch: int,
        finalized_epoch: int,
        execution_block_hash: bytes | None = None,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
        unrealized_justified_epoch: int | None = None,
        unrealized_finalized_epoch: int | None = None,
    ) -> None:
        if root in self.indices:
            return
        parent = self.indices.get(parent_root) if parent_root else None
        idx = len(self.nodes)
        self.nodes.append(
            ProtoNode(
                root=root,
                parent=parent,
                justified_epoch=justified_epoch,
                finalized_epoch=finalized_epoch,
                slot=slot,
                state_root=state_root,
                target_root=target_root,
                execution_block_hash=execution_block_hash,
                execution_status=execution_status,
                unrealized_justified_epoch=unrealized_justified_epoch,
                unrealized_finalized_epoch=unrealized_finalized_epoch,
            )
        )
        self.indices[root] = idx
        if parent is not None:
            self._maybe_update_best_child(parent, idx)

    # -- votes (proto_array_fork_choice.rs:432 process_attestation) -------------

    def process_attestation(
        self, validator_index: int, block_root: bytes, target_epoch: int
    ) -> None:
        self._ensure_votes(validator_index + 1)
        if target_epoch > self._vote_epoch[validator_index] or (
            self._vote_cur[validator_index] == 0
            and self._vote_next[validator_index] == 0
        ):
            self._vote_next[validator_index] = self._root_id(block_root)
            self._vote_epoch[validator_index] = target_epoch

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        a = self.indices.get(ancestor_root)
        d = self.indices.get(descendant_root)
        if a is None or d is None:
            return False
        a_slot = self.nodes[a].slot
        while d is not None and self.nodes[d].slot > a_slot:
            d = self.nodes[d].parent
        return d == a

    # -- head (find_head + apply_score_changes) ---------------------------------

    def find_head(
        self,
        justified_epoch: int,
        justified_root: bytes,
        finalized_epoch: int,
        justified_state_balances: np.ndarray,
        proposer_boost_root: bytes = b"\x00" * 32,
        proposer_score_boost: int = 0,
        equivocating_indices=(),
        current_slot: int | None = None,
        slots_per_epoch: int = 32,
    ) -> bytes:
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.justified_root = justified_root
        if current_slot is not None:
            self._current_epoch = current_slot // slots_per_epoch
        deltas = self._compute_deltas(justified_state_balances, equivocating_indices)
        self._apply_score_changes(deltas, proposer_boost_root, proposer_score_boost,
                                  justified_state_balances, slots_per_epoch)
        ji = self.indices.get(justified_root)
        if ji is None:
            raise ProtoArrayError(f"unknown justified root {justified_root.hex()[:16]}")
        best = self.nodes[ji].best_descendant
        head = self.nodes[best if best is not None else ji]
        if not self._node_is_viable_for_head(head):
            raise ProtoArrayError("best node not viable for head")
        return head.root

    def _compute_deltas(self, balances: np.ndarray, equivocating) -> np.ndarray:
        """Vectorized vote-delta sweep (proto_array/src/proto_array_fork_choice.rs
        compute_deltas): -balance at old vote root, +balance at new."""
        n = self._vote_cur.shape[0]
        deltas = np.zeros(len(self.nodes), dtype=np.int64)
        if n == 0:
            return deltas
        # old balance is subtracted at the previous vote root, new balance
        # added at the new one (compute_deltas in the reference keeps the
        # previously-applied balances for exactly this)
        old_bal = np.zeros(n, dtype=np.int64)
        m_old = min(n, self._old_balances.shape[0])
        old_bal[:m_old] = self._old_balances[:m_old]
        new_bal = np.zeros(n, dtype=np.int64)
        m = min(n, balances.shape[0])
        new_bal[:m] = balances[:m].astype(np.int64)
        if len(equivocating):
            eq = np.asarray(list(equivocating), dtype=np.int64)
            eq = eq[eq < n]
            new_bal[eq] = 0
            # equivocators' vote is removed and never re-added
            self._vote_next[eq] = 0
        # map vote ids -> node indices (-1 if unknown/pruned)
        id_to_idx = np.full(len(self._id_roots), -1, dtype=np.int64)
        for rid, root in enumerate(self._id_roots[1:], start=1):
            idx = self.indices.get(root)
            if idx is not None:
                id_to_idx[rid] = idx
        cur_idx = id_to_idx[self._vote_cur]
        next_idx = id_to_idx[self._vote_next]
        np.add.at(deltas, cur_idx[cur_idx >= 0], -old_bal[cur_idx >= 0])
        np.add.at(deltas, next_idx[next_idx >= 0], new_bal[next_idx >= 0])
        self._vote_cur = self._vote_next.copy()
        self._old_balances = new_bal
        return deltas

    def _apply_score_changes(
        self, deltas, proposer_boost_root, proposer_score_boost, balances,
        slots_per_epoch: int = 32,
    ):
        # proposer boost: committee-weight fraction added to one node; the
        # previously-applied boost is always removed first (the reference
        # stores the applied amount for exact reversal)
        boost = np.zeros(len(self.nodes), dtype=np.int64)
        prev_bi = self.indices.get(self.proposer_boost_root)
        if prev_bi is not None and getattr(self, "_prev_boost_score", 0):
            boost[prev_bi] -= self._prev_boost_score
        self._prev_boost_score = 0
        if proposer_boost_root != b"\x00" * 32 and proposer_score_boost:
            bi = self.indices.get(proposer_boost_root)
            total = int(balances.sum())
            # committee weight = total / slots_per_epoch (spec get_proposer_score)
            score = total // slots_per_epoch * proposer_score_boost // 100
            if bi is not None:
                boost[bi] += score
                self._prev_boost_score = score
        self.proposer_boost_root = proposer_boost_root

        total_delta = deltas + boost
        # reverse sweep: apply delta, push to parent, update best child links
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            node.weight += int(total_delta[i])
            if node.parent is not None:
                total_delta[node.parent] += total_delta[i]
                self._maybe_update_best_child(node.parent, i)

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """Spec ``filter_block_tree`` viability (post-Capella fork choice,
        mirrored by the reference's ``node_is_viable_for_head``): the node's
        voting source must match the store's justified checkpoint OR be
        within the two-epoch grace window (``voting_source.epoch + 2 >=
        current_epoch`` — what lets descendants of a checkpoint-sync anchor
        whose own justification lags the invented anchor checkpoint become
        head), and the node must descend from the finalized checkpoint."""
        if node.execution_status == ExecutionStatus.INVALID:
            return False
        cj = node.unrealized_justified_epoch
        j = cj if cj is not None else node.justified_epoch
        ok_j = (
            self.justified_epoch == 0
            or j == self.justified_epoch
            or j + 2 >= getattr(self, "_current_epoch", 0)
        )
        ok_f = self.finalized_epoch == 0 or self._descends_from_finalized(
            node
        )
        return ok_j and ok_f

    def _descends_from_finalized(self, node: ProtoNode) -> bool:
        """Memoized finalized-ancestry: viability runs per node on the head
        hot path, so the parent walk amortizes to O(1) per node instead of
        O(depth) (is_finalized_checkpoint_or_descendant in the reference)."""
        if self._fin_desc_key != self.finalized_root:
            self._fin_desc_key = self.finalized_root
            self._fin_desc = {}
        fi = self.indices.get(self.finalized_root)
        if fi is None:
            return True  # anchor not in the graph: nothing to filter on
        memo = self._fin_desc
        fslot = self.nodes[fi].slot
        path = []
        i = self.indices.get(node.root)
        while True:
            if i is None or self.nodes[i].slot < fslot:
                res = False
                break
            if i == fi:
                res = True
                break
            cached = memo.get(i)
            if cached is not None:
                res = cached
                break
            path.append(i)
            i = self.nodes[i].parent
        for p in path:
            memo[p] = res
        return res

    def _maybe_update_best_child(self, parent_idx: int, child_idx: int) -> None:
        parent = self.nodes[parent_idx]
        child = self.nodes[child_idx]
        child_viable = self._node_leads_to_viable_head(child)
        if parent.best_child == child_idx:
            if not child_viable:
                parent.best_child = None
                parent.best_descendant = None
                # re-scan children for a viable alternative
                for j, n in enumerate(self.nodes):
                    if n.parent == parent_idx and j != child_idx:
                        self._maybe_update_best_child(parent_idx, j)
            else:
                parent.best_descendant = (
                    child.best_descendant
                    if child.best_descendant is not None
                    else child_idx
                )
            return
        if not child_viable:
            return
        best = parent.best_child
        take = False
        if best is None:
            take = True
        else:
            bnode = self.nodes[best]
            if not self._node_leads_to_viable_head(bnode):
                take = True
            elif child.weight > bnode.weight:
                take = True
            elif child.weight == bnode.weight and child.root > bnode.root:
                take = True
        if take:
            parent.best_child = child_idx
            parent.best_descendant = (
                child.best_descendant if child.best_descendant is not None else child_idx
            )

    # -- invalidation (optimistic sync) -----------------------------------------

    def process_execution_payload_validation(self, root: bytes) -> None:
        idx = self.indices.get(root)
        while idx is not None:
            node = self.nodes[idx]
            if node.execution_status == ExecutionStatus.OPTIMISTIC:
                node.execution_status = ExecutionStatus.VALID
            idx = node.parent

    def process_execution_payload_invalidation(self, root: bytes) -> None:
        """Mark root and all its descendants INVALID
        (proto_array_fork_choice.rs:423)."""
        start = self.indices.get(root)
        if start is None:
            return
        bad = {start}
        self.nodes[start].execution_status = ExecutionStatus.INVALID
        for i in range(start + 1, len(self.nodes)):
            if self.nodes[i].parent in bad:
                bad.add(i)
                self.nodes[i].execution_status = ExecutionStatus.INVALID
        # force best-child recomputation from scratch on next find_head
        for n in self.nodes:
            if n.best_child in bad:
                n.best_child = None
                n.best_descendant = None

    # -- pruning ----------------------------------------------------------------

    def maybe_prune(self, finalized_root: bytes, prune_threshold: int = 256) -> None:
        fi = self.indices.get(finalized_root)
        if fi is None or fi < prune_threshold:
            return
        keep = self.nodes[fi:]
        offset = fi
        self.indices = {}
        for n in keep:
            n.parent = n.parent - offset if n.parent is not None and n.parent >= offset else None
            n.best_child = n.best_child - offset if n.best_child is not None and n.best_child >= offset else None
            n.best_descendant = (
                n.best_descendant - offset
                if n.best_descendant is not None and n.best_descendant >= offset
                else None
            )
        self.nodes = keep
        for i, n in enumerate(self.nodes):
            self.indices[n.root] = i
        self.finalized_root = finalized_root
        # node indices shifted: the index-keyed ancestry memo is stale
        self._fin_desc_key = None
        self._fin_desc = {}
