"""Fork-choice persistence (beacon_chain/src/persisted_fork_choice.rs).

Snapshots the proto-array graph (nodes, indices, vote columns) and the
fork-choice store (checkpoints, justified balances, equivocators) to one
JSON document in the store's metadata bucket, and rebuilds a live
``ForkChoice`` from it on boot — so a restarted node keeps its head, its
accumulated attestation weight, and its optimistic/invalid knowledge
instead of reverting to the anchor.
"""

from __future__ import annotations

import json

import numpy as np

from .fork_choice import ForkChoice, ForkChoiceStore, QueuedAttestation
from .proto_array import ExecutionStatus, ProtoArrayForkChoice, ProtoNode

META_KEY = b"fork_choice_v1"


def persist(store, fc: "ForkChoice") -> None:
    """The fork-choice persistence barrier: serialize + one metadata put
    (a single-key write — atomic at the WAL frame layer). The
    ``persist.fork_choice`` crash point lets the chaos sweep kill a node
    exactly between the block batch and this snapshot."""
    from ..resilience.crashpoints import maybe_crash

    maybe_crash(
        "persist.fork_choice", owner=getattr(store.hot, "owner", None)
    )
    store.put_meta(META_KEY, serialize_fork_choice(fc))

_hex = bytes.hex


def _unhex_opt(v):
    return bytes.fromhex(v) if v is not None else None


def serialize_fork_choice(fc: ForkChoice) -> bytes:
    proto, store = fc.proto, fc.store
    nodes = [
        {
            "root": _hex(n.root),
            "parent": n.parent,
            "je": n.justified_epoch,
            "fe": n.finalized_epoch,
            "slot": n.slot,
            "state_root": _hex(n.state_root),
            "target_root": _hex(n.target_root),
            "exec_hash": _hex(n.execution_block_hash)
            if n.execution_block_hash
            else None,
            "exec_status": n.execution_status.value,
            "weight": n.weight,
            "best_child": n.best_child,
            "best_descendant": n.best_descendant,
            "uje": n.unrealized_justified_epoch,
            "ufe": n.unrealized_finalized_epoch,
        }
        for n in proto.nodes
    ]
    doc = {
        "proto": {
            "nodes": nodes,
            "justified_epoch": proto.justified_epoch,
            "finalized_epoch": proto.finalized_epoch,
            "justified_root": _hex(proto.justified_root),
            "finalized_root": _hex(proto.finalized_root),
            "vote_cur": proto._vote_cur.tolist(),
            "vote_next": proto._vote_next.tolist(),
            "vote_epoch": proto._vote_epoch.tolist(),
            "old_balances": proto._old_balances.tolist(),
            "id_roots": [_hex(r) for r in proto._id_roots],
            "proposer_boost_root": _hex(proto.proposer_boost_root),
            "prev_boost_score": getattr(proto, "_prev_boost_score", 0),
        },
        "store": {
            "current_slot": store.current_slot,
            "justified_checkpoint": [
                store.justified_checkpoint[0],
                _hex(store.justified_checkpoint[1]),
            ],
            "finalized_checkpoint": [
                store.finalized_checkpoint[0],
                _hex(store.finalized_checkpoint[1]),
            ],
            "justified_balances": store.justified_balances.tolist(),
            "unrealized_justified": [
                store.unrealized_justified_checkpoint[0],
                _hex(store.unrealized_justified_checkpoint[1]),
            ]
            if store.unrealized_justified_checkpoint
            else None,
            "unrealized_finalized": [
                store.unrealized_finalized_checkpoint[0],
                _hex(store.unrealized_finalized_checkpoint[1]),
            ]
            if store.unrealized_finalized_checkpoint
            else None,
            "equivocating": sorted(int(i) for i in store.equivocating_indices),
            "proposer_boost_root": _hex(store.proposer_boost_root),
        },
        "queued_attestations": [
            {
                "slot": q.slot,
                "root": _hex(q.block_root),
                "indices": [int(i) for i in q.attesting_indices],
                "target_epoch": q.target_epoch,
            }
            for q in fc.queued_attestations
        ],
    }
    return json.dumps(doc).encode()


def restore_fork_choice(spec, blob: bytes) -> ForkChoice:
    doc = json.loads(blob)
    p = doc["proto"]
    proto = ProtoArrayForkChoice(
        finalized_root=bytes.fromhex(p["finalized_root"]),
        finalized_slot=0,
        justified_epoch=p["justified_epoch"],
        finalized_epoch=p["finalized_epoch"],
        justified_root=bytes.fromhex(p["justified_root"]),
    )
    proto.nodes = []
    proto.indices = {}
    for i, n in enumerate(p["nodes"]):
        node = ProtoNode(
            root=bytes.fromhex(n["root"]),
            parent=n["parent"],
            justified_epoch=n["je"],
            finalized_epoch=n["fe"],
            slot=n["slot"],
            state_root=bytes.fromhex(n["state_root"]),
            target_root=bytes.fromhex(n["target_root"]),
            execution_block_hash=_unhex_opt(n["exec_hash"]),
            execution_status=ExecutionStatus(n["exec_status"]),
            weight=n["weight"],
            best_child=n["best_child"],
            best_descendant=n["best_descendant"],
            unrealized_justified_epoch=n["uje"],
            unrealized_finalized_epoch=n["ufe"],
        )
        proto.nodes.append(node)
        proto.indices[node.root] = i
    proto._vote_cur = np.asarray(p["vote_cur"], dtype=np.int64)
    proto._vote_next = np.asarray(p["vote_next"], dtype=np.int64)
    proto._vote_epoch = np.asarray(p["vote_epoch"], dtype=np.uint64)
    proto._old_balances = np.asarray(p["old_balances"], dtype=np.int64)
    proto._id_roots = [bytes.fromhex(r) for r in p["id_roots"]]
    proto._root_ids = {r: i for i, r in enumerate(proto._id_roots) if i > 0}
    proto.proposer_boost_root = bytes.fromhex(p["proposer_boost_root"])
    proto._prev_boost_score = p.get("prev_boost_score", 0)

    s = doc["store"]
    store = ForkChoiceStore(
        current_slot=s["current_slot"],
        justified_checkpoint=(
            s["justified_checkpoint"][0],
            bytes.fromhex(s["justified_checkpoint"][1]),
        ),
        finalized_checkpoint=(
            s["finalized_checkpoint"][0],
            bytes.fromhex(s["finalized_checkpoint"][1]),
        ),
        justified_balances=np.asarray(
            s["justified_balances"], dtype=np.uint64
        ),
    )
    if s["unrealized_justified"]:
        store.unrealized_justified_checkpoint = (
            s["unrealized_justified"][0],
            bytes.fromhex(s["unrealized_justified"][1]),
        )
    if s["unrealized_finalized"]:
        store.unrealized_finalized_checkpoint = (
            s["unrealized_finalized"][0],
            bytes.fromhex(s["unrealized_finalized"][1]),
        )
    store.equivocating_indices = set(s["equivocating"])
    store.proposer_boost_root = bytes.fromhex(s["proposer_boost_root"])

    fc = ForkChoice(spec, store, proto)
    fc.queued_attestations = [
        QueuedAttestation(
            slot=q["slot"],
            block_root=bytes.fromhex(q["root"]),
            attesting_indices=q["indices"],
            target_epoch=q["target_epoch"],
        )
        for q in doc.get("queued_attestations", [])
    ]
    return fc
