"""Fork choice: proto-array LMD-GHOST + spec wrapper.

Twin of ``consensus/proto_array`` + ``consensus/fork_choice``.
"""

from .proto_array import ProtoArrayForkChoice, ExecutionStatus
from .fork_choice import ForkChoice, ForkChoiceStore
