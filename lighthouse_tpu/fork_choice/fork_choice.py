"""Spec fork choice over the proto-array (consensus/fork_choice twin).

Parity: ``/root/reference/consensus/fork_choice/src/fork_choice.rs`` —
``on_block`` (:648), ``on_attestation`` (:1045) with the one-slot queue
(:235), ``get_head`` (:474), proposer boost, and checkpoint management in a
``ForkChoiceStore`` (the beacon-chain layer supplies balances the way
``BeaconForkChoiceStore`` does).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..types.spec import ChainSpec
from .proto_array import ExecutionStatus, ProtoArrayForkChoice


class ForkChoiceError(Exception):
    pass


@dataclass
class QueuedAttestation:
    slot: int
    attesting_indices: list
    block_root: bytes
    target_epoch: int


@dataclass
class ForkChoiceStore:
    """Justified/finalized tracking + balances provider
    (fork_choice.rs ForkChoiceStore trait + BeaconForkChoiceStore)."""

    current_slot: int
    justified_checkpoint: tuple  # (epoch, root)
    finalized_checkpoint: tuple
    justified_balances: np.ndarray
    unrealized_justified_checkpoint: tuple | None = None
    unrealized_finalized_checkpoint: tuple | None = None
    equivocating_indices: set = field(default_factory=set)
    proposer_boost_root: bytes = b"\x00" * 32


class ForkChoice:
    def __init__(self, spec: ChainSpec, store: ForkChoiceStore, proto: ProtoArrayForkChoice):
        self.spec = spec
        self.store = store
        self.proto = proto
        self.queued_attestations: list[QueuedAttestation] = []

    @classmethod
    def from_anchor(
        cls, spec: ChainSpec, anchor_root: bytes, anchor_slot: int,
        justified_checkpoint, finalized_checkpoint, balances,
    ) -> "ForkChoice":
        proto = ProtoArrayForkChoice(
            finalized_root=anchor_root,
            finalized_slot=anchor_slot,
            justified_epoch=justified_checkpoint[0],
            finalized_epoch=finalized_checkpoint[0],
            justified_root=justified_checkpoint[1],
        )
        store = ForkChoiceStore(
            current_slot=anchor_slot,
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
            justified_balances=np.asarray(balances, dtype=np.uint64),
        )
        return cls(spec, store, proto)

    # -- time -------------------------------------------------------------------

    def update_time(self, current_slot: int) -> None:
        while self.store.current_slot < current_slot:
            self.store.current_slot += 1
            self.store.proposer_boost_root = b"\x00" * 32
            self._process_queued_attestations()

    def _process_queued_attestations(self) -> None:
        ready = [
            a for a in self.queued_attestations if a.slot < self.store.current_slot
        ]
        self.queued_attestations = [
            a for a in self.queued_attestations if a.slot >= self.store.current_slot
        ]
        for a in ready:
            for v in a.attesting_indices:
                self.proto.process_attestation(int(v), a.block_root, a.target_epoch)

    # -- blocks (fork_choice.rs:648) --------------------------------------------

    def on_block(
        self, current_slot: int, block, block_root: bytes, state,
        justified_balances=None,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
        is_first_block_in_slot: bool = False,
    ) -> None:
        self.update_time(current_slot)
        if block.slot > current_slot:
            raise ForkChoiceError("block from the future")
        fin_epoch, fin_root = self.store.finalized_checkpoint
        if block.slot <= self._finalized_slot():
            raise ForkChoiceError("block slot not beyond finalized")
        if fin_epoch and not self.proto.is_descendant(fin_root, bytes(block.parent_root)):
            raise ForkChoiceError("block does not descend from finalized root")

        # proposer boost: first block in its slot arriving timely
        if is_first_block_in_slot and block.slot == current_slot:
            self.store.proposer_boost_root = block_root

        sj = (state.current_justified_checkpoint.epoch,
              bytes(state.current_justified_checkpoint.root))
        sf = (state.finalized_checkpoint.epoch, bytes(state.finalized_checkpoint.root))
        if sj[0] > self.store.justified_checkpoint[0]:
            self.store.justified_checkpoint = sj
            if justified_balances is not None:
                self.store.justified_balances = np.asarray(
                    justified_balances, dtype=np.uint64
                )
        if sf[0] > self.store.finalized_checkpoint[0]:
            self.store.finalized_checkpoint = sf

        epoch = block.slot // self.spec.preset.SLOTS_PER_EPOCH
        target_slot = epoch * self.spec.preset.SLOTS_PER_EPOCH
        target_root = (
            block_root if block.slot == target_slot
            else self._ancestor_at_slot(bytes(block.parent_root), target_slot)
        )
        self.proto.on_block(
            slot=block.slot,
            root=block_root,
            parent_root=bytes(block.parent_root),
            state_root=bytes(block.state_root),
            target_root=target_root,
            justified_epoch=sj[0],
            finalized_epoch=sf[0],
            execution_status=execution_status,
        )

    def _ancestor_at_slot(self, root: bytes, slot: int) -> bytes:
        idx = self.proto.indices.get(root)
        while idx is not None and self.proto.nodes[idx].slot > slot:
            idx = self.proto.nodes[idx].parent
        return self.proto.nodes[idx].root if idx is not None else root

    def _finalized_slot(self) -> int:
        return self.spec.start_slot(self.store.finalized_checkpoint[0])

    # -- attestations (fork_choice.rs:1045) -------------------------------------

    def on_attestation(
        self, current_slot: int, indexed_attestation, is_from_block: bool = False
    ) -> None:
        self.update_time(current_slot)
        data = indexed_attestation.data
        block_root = bytes(data.beacon_block_root)
        if block_root not in self.proto.indices:
            raise ForkChoiceError("attestation for unknown block")
        block_slot = self.proto.nodes[self.proto.indices[block_root]].slot
        if block_slot > data.slot:
            raise ForkChoiceError("attestation for block newer than slot")
        if not is_from_block and data.slot >= current_slot:
            # queue for the next slot (1-slot delay rule, fork_choice.rs:235)
            self.queued_attestations.append(
                QueuedAttestation(
                    slot=data.slot,
                    attesting_indices=list(indexed_attestation.attesting_indices),
                    block_root=block_root,
                    target_epoch=data.target.epoch,
                )
            )
            return
        for v in indexed_attestation.attesting_indices:
            self.proto.process_attestation(int(v), block_root, data.target.epoch)

    def on_attester_slashing(self, indices) -> None:
        self.store.equivocating_indices.update(int(i) for i in indices)

    # -- head (fork_choice.rs:474) ----------------------------------------------

    def get_proposer_head(
        self,
        current_slot: int,
        canonical_head: bytes,
        re_org_threshold_pct: int = 20,
    ) -> bytes:
        """Proposer re-org heuristic (fork_choice.rs:522 get_proposer_head):
        when the head block arrived one slot late and carries little attesting
        weight, the proposer builds on its PARENT instead, orphaning the weak
        block. Conservative gate set:

          * the head is exactly one slot behind the proposal slot and its
            parent is exactly one slot behind the head (no skipped slots),
          * head weight < re_org_threshold_pct of one slot's committee weight,
          * finalization is recent (within two epochs),
          * only a single re-org step (parent must be canonical).
        Returns the root to build on (parent for a re-org, else the head)."""
        idx = self.proto.indices.get(bytes(canonical_head))
        if idx is None:
            return canonical_head
        node = self.proto.nodes[idx]
        if node.parent is None:
            return canonical_head
        parent = self.proto.nodes[node.parent]
        if int(node.slot) + 1 != current_slot:
            return canonical_head  # head is on time (or older than one slot)
        if int(parent.slot) + 1 != int(node.slot):
            return canonical_head  # skipped slot below the head: do not re-org
        f_epoch, _ = self.store.finalized_checkpoint
        epochs_since_final = (
            current_slot // self.spec.preset.SLOTS_PER_EPOCH - int(f_epoch)
        )
        if epochs_since_final > 2:
            return canonical_head  # unhealthy chain: never re-org
        total = int(self.store.justified_balances.sum())
        committee_weight = total // self.spec.preset.SLOTS_PER_EPOCH
        threshold = committee_weight * re_org_threshold_pct // 100
        if int(node.weight) >= threshold:
            return canonical_head  # the late block gathered real support
        return parent.root

    def get_head(self, current_slot: int) -> bytes:
        self.update_time(current_slot)
        j_epoch, j_root = self.store.justified_checkpoint
        f_epoch, _ = self.store.finalized_checkpoint
        return self.proto.find_head(
            justified_epoch=j_epoch,
            justified_root=j_root,
            finalized_epoch=f_epoch,
            justified_state_balances=self.store.justified_balances,
            proposer_boost_root=self.store.proposer_boost_root,
            proposer_score_boost=self.spec.proposer_score_boost,
            equivocating_indices=self.store.equivocating_indices,
            current_slot=current_slot,
            slots_per_epoch=self.spec.preset.SLOTS_PER_EPOCH,
        )
