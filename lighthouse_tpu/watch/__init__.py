"""Watch: standalone chain-analytics service (ref ``watch/``, 6,461 LoC).

The reference ingests the canonical chain into PostgreSQL via the Beacon API
and serves an HTTP query surface; here the database is stdlib SQLite (the
environment ships no postgres server) with the same shape: an updater that
backfills + follows canonical slots through the standard API, block metadata
extraction (proposer, attestation/deposit counts, graffiti, vote
participation), and a query API.

    db = WatchDB(path)
    svc = WatchService(db, beacon_url)
    svc.update()             # backfill + follow head
    server = WatchServer(db).start()   # /v1/slots/..., /v1/blocks/...
"""

from .db import WatchDB
from .server import WatchServer
from .service import WatchService

__all__ = ["WatchDB", "WatchServer", "WatchService"]
