"""Watch HTTP query API (ref watch/src/server).

    GET /v1/slots/lowest | /v1/slots/highest | /v1/slots/{slot}
    GET /v1/blocks/{slot}
    GET /v1/validators/{index}/blocks
    GET /v1/participation?lo=..&hi=..
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


class WatchServer:
    def __init__(self, db, host: str = "127.0.0.1", port: int = 0):
        self.db = db
        self._httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        self.port = self._httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "WatchServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)


def _make_handler(api: WatchServer):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _reply(self, code, payload):
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            u = urlparse(self.path)
            db = api.db
            try:
                m = re.match(r"^/v1/slots/(lowest|highest|\d+)$", u.path)
                if m:
                    which = m.group(1)
                    bounds = db.slot_bounds()
                    if which in ("lowest", "highest"):
                        if bounds is None:
                            self._reply(404, {"message": "no slots ingested"})
                            return
                        slot = bounds[0] if which == "lowest" else bounds[1]
                    else:
                        slot = int(which)
                    row = db.canonical_slot(slot)
                    if row is None:
                        self._reply(404, {"message": f"slot {slot} unknown"})
                    else:
                        self._reply(200, {"data": row})
                    return
                m = re.match(r"^/v1/blocks/(\d+)$", u.path)
                if m:
                    row = db.block(int(m.group(1)))
                    if row is None:
                        self._reply(404, {"message": "no block"})
                    else:
                        self._reply(200, {"data": row})
                    return
                m = re.match(r"^/v1/validators/(\d+)/blocks$", u.path)
                if m:
                    self._reply(
                        200,
                        {"data": db.blocks_by_proposer(int(m.group(1)))},
                    )
                    return
                if u.path == "/v1/participation":
                    q = {k: v[0] for k, v in parse_qs(u.query).items()}
                    self._reply(
                        200,
                        {
                            "data": db.participation(
                                int(q.get("lo", 0)), int(q.get("hi", 1 << 62))
                            )
                        },
                    )
                    return
                self._reply(404, {"message": f"no route {u.path}"})
            except Exception as e:  # noqa: BLE001 — API boundary
                self._reply(500, {"message": f"{type(e).__name__}: {e}"})

    return Handler
