"""Watch database: canonical slots + block metadata (ref watch/migrations).

SQLite tables mirroring the reference's diesel schema: ``canonical_slots``
(every slot, skipped or not, with its canonical root) and ``beacon_blocks``
(per-block analytics columns the reference's block-rewards/packing updaters
fill)."""

from __future__ import annotations

import sqlite3
import threading


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS canonical_slots (
                    slot INTEGER PRIMARY KEY,
                    root BLOB NOT NULL,
                    skipped INTEGER NOT NULL
                );
                CREATE TABLE IF NOT EXISTS beacon_blocks (
                    slot INTEGER PRIMARY KEY,
                    root BLOB NOT NULL,
                    parent_root BLOB NOT NULL,
                    proposer_index INTEGER NOT NULL,
                    graffiti TEXT NOT NULL,
                    attestation_count INTEGER NOT NULL,
                    deposit_count INTEGER NOT NULL,
                    exit_count INTEGER NOT NULL,
                    attesting_votes INTEGER NOT NULL
                );
                CREATE INDEX IF NOT EXISTS blocks_by_proposer
                    ON beacon_blocks(proposer_index);
                """
            )
            self._conn.commit()

    def close(self) -> None:
        self._conn.close()

    # -- writes -------------------------------------------------------------

    def put_canonical_slot(self, slot: int, root: bytes, skipped: bool) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO canonical_slots VALUES (?, ?, ?)",
                (slot, root, int(skipped)),
            )
            self._conn.commit()

    def put_block(self, row: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO beacon_blocks VALUES "
                "(:slot, :root, :parent_root, :proposer_index, :graffiti, "
                ":attestation_count, :deposit_count, :exit_count, "
                ":attesting_votes)",
                row,
            )
            self._conn.commit()

    # -- queries ------------------------------------------------------------

    def slot_bounds(self) -> tuple[int, int] | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT MIN(slot), MAX(slot) FROM canonical_slots"
            ).fetchone()
        return None if row[0] is None else (row[0], row[1])

    def canonical_slot(self, slot: int) -> dict | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT slot, root, skipped FROM canonical_slots WHERE slot=?",
                (slot,),
            ).fetchone()
        if row is None:
            return None
        return {"slot": row[0], "root": "0x" + row[1].hex(), "skipped": bool(row[2])}

    def block(self, slot: int) -> dict | None:
        with self._lock:
            cur = self._conn.execute(
                "SELECT * FROM beacon_blocks WHERE slot=?", (slot,)
            )
            row = cur.fetchone()
            cols = [d[0] for d in cur.description]
        if row is None:
            return None
        out = dict(zip(cols, row))
        for k in ("root", "parent_root"):
            out[k] = "0x" + out[k].hex()
        return out

    def blocks_by_proposer(self, proposer_index: int) -> list[int]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT slot FROM beacon_blocks WHERE proposer_index=? "
                "ORDER BY slot",
                (proposer_index,),
            ).fetchall()
        return [r[0] for r in rows]

    def participation(self, lo: int, hi: int) -> dict:
        """Aggregate attestation votes over a slot range (block-packing
        analytics)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT COUNT(*), SUM(attestation_count), SUM(attesting_votes) "
                "FROM beacon_blocks WHERE slot BETWEEN ? AND ?",
                (lo, hi),
            ).fetchone()
        return {
            "blocks": row[0] or 0,
            "attestations": row[1] or 0,
            "attesting_votes": row[2] or 0,
        }
