"""Watch updater: follow the canonical chain through the Beacon API.

Twin of ``watch/src/updater``: each ``update()`` walks from the last ingested
slot to the node's head, records canonical/skipped slots, and extracts
per-block analytics columns from the SSZ block bodies."""

from __future__ import annotations

import numpy as np

from ..api_client import BeaconNodeHttpClient
from ..types.containers import for_preset
from ..utils.logging import get_logger

log = get_logger("watch")


class WatchService:
    def __init__(self, db, beacon_url: str, spec):
        self.db = db
        self.client = BeaconNodeHttpClient(beacon_url)
        self.spec = spec
        self.ns = for_preset(spec.preset.name)

    def update(self) -> int:
        """Ingest up to the node's current head. Returns rows written."""
        from ..api_client import ApiClientError

        head = self.client.get_head_header()
        bounds = self.db.slot_bounds()
        start = 1 if bounds is None else bounds[1] + 1
        written = 0
        last_root = b"\x00" * 32  # pre-first-block skipped slots anchor here
        for slot in range(start, head["slot"] + 1):
            try:
                version, raw = self.client.get_block_ssz(slot)
            except ApiClientError as e:
                if e.code != 404:
                    raise  # transport/server errors must NOT look like skips
                self.db.put_canonical_slot(slot, last_root, skipped=True)
                written += 1
                continue
            sb = self.ns.block_types[version].decode(raw)
            blk = sb.message
            root = type(blk).hash_tree_root(blk)
            body = blk.body
            votes = sum(
                int(np.asarray(a.aggregation_bits).sum())
                for a in body.attestations
            )
            graffiti = bytes(body.graffiti).rstrip(b"\x00")
            self.db.put_canonical_slot(int(blk.slot), root, skipped=False)
            self.db.put_block(
                {
                    "slot": int(blk.slot),
                    "root": root,
                    "parent_root": bytes(blk.parent_root),
                    "proposer_index": int(blk.proposer_index),
                    "graffiti": graffiti.decode(errors="replace"),
                    "attestation_count": len(body.attestations),
                    "deposit_count": len(body.deposits),
                    "exit_count": len(body.voluntary_exits),
                    "attesting_votes": votes,
                }
            )
            last_root = root
            written += 1
        if written:
            log.info("Watch ingested", rows=written, head=head["slot"])
        return written
