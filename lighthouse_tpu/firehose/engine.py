"""The streaming verification engine: intake -> host prep -> device verify.

Two pipeline threads double-buffer the work:

  * the **prep thread** pulls fixed-shape batches from the
    ``AdaptiveBatcher`` and runs the host-side stage (committee/cache
    lookups, signature-set construction — everything before the device
    dispatch) for batch N+1;
  * the **device thread** runs batched verification (and bisection fallback
    on a poisoned batch) for batch N.

The handoff between them is a bounded queue of depth ``prep_depth`` (default
1): while the device verifies batch N, the host prepares N+1 and then blocks
— back-pressure propagates to the intake, where the batcher sheds
lowest-priority work instead of growing without bound. The intake itself
(``submit``) never blocks, so gossip/network threads stay responsive under
any device stall.

``synchronous=True`` disables the threads; ``drain()`` runs the pipeline
inline on the caller's thread (the deterministic test mode, mirroring
``BeaconProcessor(synchronous=True)``).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass

from ..beacon_processor.processor import WorkType
from ..resilience import faults
from ..utils.metrics import (
    FIREHOSE_BATCH_FILL,
    FIREHOSE_BATCHES_FORMED,
    FIREHOSE_QUEUE_LATENCY,
    FIREHOSE_VERIFIED,
    GOSSIP_VERDICT_LATENCY,
)
from .batcher import AdaptiveBatcher, FirehoseConfig, FirehoseItem
from .bisect import bisect_verify

_LATENCY_RESERVOIR = 4096  # most-recent queue latencies kept for percentiles


@dataclass
class FirehoseStats:
    submitted: int
    verified: int
    rejected: int
    errored: int
    dropped: int
    batches_formed: int
    p50_latency_s: float | None
    p99_latency_s: float | None
    device_faults: int = 0
    expired: int = 0
    # end-to-end gossip->verdict percentiles: measured from the WIRE-ingest
    # stamp when items carry one (falls back to intake enqueue time)
    p50_e2e_s: float | None = None
    p99_e2e_s: float | None = None

    def as_dict(self) -> dict:
        return {
            "submitted": self.submitted,
            "verified": self.verified,
            "rejected": self.rejected,
            "errored": self.errored,
            "dropped": self.dropped,
            "batches_formed": self.batches_formed,
            "p50_latency_s": self.p50_latency_s,
            "p99_latency_s": self.p99_latency_s,
            "device_faults": self.device_faults,
            "expired": self.expired,
            "p50_e2e_s": self.p50_e2e_s,
            "p99_e2e_s": self.p99_e2e_s,
        }


class FirehoseEngine:
    """Streaming batch scheduler between the work intake and the BLS device
    backend.

    ``prepare_fn(payloads) -> list[(group, meta) | Exception]`` is the host
    stage: one signature-set *group* (list of ``(indices, signing_root,
    sig_bytes)`` triples) per payload plus opaque ``meta`` handed to the
    result callback (e.g. the resolved IndexedAttestation), or an Exception
    marking that payload invalid before any crypto (unknown committee,
    malformed encoding, ...).

    ``verify_items_fn(flat_items) -> bool`` is the device stage: the batched
    RLC verifier (``BeaconChain._batch_verify_items`` shape). A poisoned
    batch is isolated by bisection (``bisect.bisect_verify``), never by
    per-set fallback.
    """

    def __init__(
        self,
        prepare_fn,
        verify_items_fn,
        config: FirehoseConfig | None = None,
        synchronous: bool = False,
        supervisor=None,
        fallback_verify_fn=None,
        shard_planner=None,
    ):
        self.config = config or FirehoseConfig()
        self.batcher = AdaptiveBatcher(self.config)
        self.prepare_fn = prepare_fn
        self.verify_items_fn = verify_items_fn
        # optional fault domain (resilience.BackendSupervisor): device calls
        # run down the degradation ladder full -> halved -> fallback_verify_fn
        # with watchdog + classified retries instead of failing the batch
        self.supervisor = supervisor
        self.fallback_verify_fn = fallback_verify_fn
        # optional sharded serving tier (firehose/sharding.MeshVerifier):
        # the prep thread stages per-shard sub-batches + H2D transfers for
        # batch N+1 while the device thread runs batch N over the mesh, and
        # verdicts come back per SHARD — a poisoned shard bisects only its
        # own groups. The planner carries its own fault-domain ladder
        # (mesh -> shrunken mesh -> single device -> CPU oracle), so it is
        # never combined with `supervisor` (that would double-wrap)
        self.shard_planner = shard_planner
        self.synchronous = synchronous
        # callback(payload, ok, meta) used when submit() gives none
        self.default_callback = None
        self.verified = 0
        self.rejected = 0          # bad signature (bisection-condemned)
        self.errored = 0           # prep-stage rejections
        self.batches_formed = 0
        self.device_faults = 0     # batches that lost their device verdict
        self._latencies: list[float] = []
        self._e2e_latencies: list[float] = []  # wire-ingest -> verdict
        self._stats_lock = threading.Lock()
        self._prepared: queue.Queue = queue.Queue(maxsize=self.config.prep_depth)
        self._threads: list[threading.Thread] = []
        self._stopping = False
        self._aborted = False      # stop() gave up on a wedged thread
        if not synchronous:
            for name, target in (
                ("firehose-prep", self._prep_loop),
                ("firehose-device", self._device_loop),
            ):
                th = threading.Thread(target=target, daemon=True, name=name)
                th.start()
                self._threads.append(th)

    # -- intake -------------------------------------------------------------------

    def submit(
        self,
        payload,
        work_type: WorkType = WorkType.GossipAttestation,
        callback=None,
        ingest_at: float | None = None,
        deadline: float | None = None,
    ) -> bool:
        """Non-blocking intake. Returns False when the item was shed.
        ``ingest_at``/``deadline`` propagate the wire-ingest stamp and the
        item's expiry (loadshed.deadline): expired items are shed at batch
        form time and end-to-end latency is measured from ``ingest_at``."""
        return self.batcher.submit(
            FirehoseItem(
                work_type=work_type, payload=payload, callback=callback,
                ingest_at=ingest_at, deadline=deadline,
            )
        )

    # -- pipeline stages ----------------------------------------------------------

    def _prep_batch(self, batch: list[FirehoseItem]):
        """Host stage: payloads -> signature-set groups (or Exceptions).
        With a shard planner attached, also stages the tick's per-shard
        sub-batches + host->device transfers (so they double-buffer against
        the device thread's in-flight verify)."""
        with self._stats_lock:
            self.batches_formed += 1
        FIREHOSE_BATCHES_FORMED.inc(work_type=batch[0].work_type.name)
        FIREHOSE_BATCH_FILL.observe(len(batch))
        groups = self.prepare_fn([it.payload for it in batch])
        staged = None
        if self.shard_planner is not None:
            real = [
                g for g in groups
                if not isinstance(g, Exception) and g[0]
            ]
            if real:
                staged = self.shard_planner.stage([g for g, _ in real])
        return batch, groups, staged

    def _supervised_verify(self, items) -> bool:
        """The device verify call, run through the fault domain when one is
        attached: full shape -> halved shapes -> CPU fallback, with watchdog
        + bounded transient retries. A ``False`` verdict is a result (it
        triggers bisection), never a fault."""
        if self.supervisor is None:
            return self.verify_items_fn(items)
        rungs = [("device_full", lambda: self.verify_items_fn(items))]
        if len(items) > 1:
            mid = (len(items) + 1) // 2

            def reduced():
                return self.verify_items_fn(items[:mid]) and self.verify_items_fn(
                    items[mid:]
                )

            rungs.append(("device_reduced", reduced))
        if self.fallback_verify_fn is not None:
            rungs.append(
                ("cpu_fallback", lambda: self.fallback_verify_fn(items))
            )
        return self.supervisor.run_ladder("firehose.device_verify", rungs)

    def _sharded_verdicts(self, groups, staged) -> dict[int, bool]:
        """Mesh path: per-SHARD verdicts from the planner, then bisection
        only among the groups of failed shards (a poisoned shard never
        forces a whole-tick bisection)."""
        per_group = self.shard_planner.verify_groups(groups, staged=staged)
        verdicts = {i: ok for i, ok in enumerate(per_group) if ok}
        bad = [i for i, ok in enumerate(per_group) if not ok]
        if bad:
            for i, ok in zip(
                bad,
                bisect_verify(
                    [groups[i] for i in bad],
                    self._supervised_verify,
                    assume_failed=True,
                ),
            ):
                verdicts[i] = ok
        return verdicts

    def _verify_batch(self, prepped) -> None:
        """Device stage: batched verify, bisection on failure, callbacks."""
        batch, entries, staged = prepped
        real = [
            (it, group, meta)
            for it, entry in zip(batch, entries)
            if not isinstance(entry, Exception)
            for group, meta in (entry,)
            if group
        ]
        verdicts: dict[int, bool] = {}
        device_failed = False
        if real:
            # a device fault must not strand the batch without verdicts:
            # every item still gets its callback, counted as errored —
            # and the fault is classified + recorded, never dropped silently
            try:
                if self.shard_planner is not None:
                    verdicts = self._sharded_verdicts(
                        [group for _, group, _ in real], staged
                    )
                elif self._supervised_verify(
                    [item for _, group, _ in real for item in group]
                ):
                    for i, _ in enumerate(real):
                        verdicts[i] = True
                else:
                    for i, ok in enumerate(
                        bisect_verify(
                            [group for _, group, _ in real],
                            self._supervised_verify,
                            assume_failed=True,
                        )
                    ):
                        verdicts[i] = ok
            except Exception as e:  # noqa: BLE001 — device fault fails the batch
                device_failed = True
                faults.record_fault(
                    "firehose.verify_batch", e, domain="firehose"
                )
                with self._stats_lock:
                    self.device_faults += 1
                for i, _ in enumerate(real):
                    verdicts[i] = False
        now = time.monotonic()
        n_ok = n_bad = n_err = 0
        lats = []
        e2e_lats = []
        ri = 0
        for it, entry in zip(batch, entries):
            meta = None
            if isinstance(entry, Exception) or not entry[0]:
                ok = False
                n_err += 1
                if not isinstance(entry, Exception):
                    meta = entry[1]
            else:
                ok = verdicts[ri]
                meta = real[ri][2]
                ri += 1
                if device_failed:
                    n_err += 1
                else:
                    n_ok += ok
                    n_bad += not ok
            lats.append(now - it.enqueued_at)
            e2e_lats.append(
                now - (it.ingest_at if it.ingest_at is not None
                       else it.enqueued_at)
            )
            cb = it.callback or self.default_callback
            if cb is not None:
                try:
                    cb(it.payload, ok, meta)
                except Exception:  # noqa: BLE001 — callbacks never kill the pipe
                    pass
        with self._stats_lock:
            self.verified += n_ok
            self.rejected += n_bad
            self.errored += n_err
            self._latencies.extend(lats)
            if len(self._latencies) > _LATENCY_RESERVOIR:
                del self._latencies[: -_LATENCY_RESERVOIR]
            self._e2e_latencies.extend(e2e_lats)
            if len(self._e2e_latencies) > _LATENCY_RESERVOIR:
                del self._e2e_latencies[: -_LATENCY_RESERVOIR]
        for v in lats:
            FIREHOSE_QUEUE_LATENCY.observe(v)
        for v in e2e_lats:
            GOSSIP_VERDICT_LATENCY.observe(v)
        FIREHOSE_VERIFIED.inc(n_ok, result="ok")
        if n_bad:
            FIREHOSE_VERIFIED.inc(n_bad, result="bad_signature")
        if n_err:
            FIREHOSE_VERIFIED.inc(n_err, result="prep_error")

    # -- threaded pipeline --------------------------------------------------------

    def _handoff(self, prepped) -> bool:
        """Abort-aware put onto the bounded prep->device queue: blocks at
        prep_depth for back-pressure, but stays cancellable so a wedged
        device thread can never pin the prep thread past ``stop()``."""
        while True:
            try:
                self._prepared.put(prepped, timeout=0.2)
                return True
            except queue.Full:
                if self._aborted:
                    return False

    def _prep_loop(self) -> None:
        while True:
            batch = self.batcher.next_batch()
            if batch is None:          # batcher closed and drained
                self._handoff(None)
                return
            try:
                prepped = self._prep_batch(batch)
            except Exception as e:  # noqa: BLE001 — poison batch, keep pumping
                # classified fault record instead of a silent poison
                faults.record_fault("firehose.prep", e, domain="firehose")
                prepped = (batch, [e] * len(batch), None)
            if not self._handoff(prepped):  # blocks at prep_depth: double buffer
                return

    def _device_loop(self) -> None:
        while True:
            try:
                prepped = self._prepared.get(timeout=0.2)
            except queue.Empty:
                if self._aborted:
                    return
                continue
            if prepped is None:
                return
            try:
                self._verify_batch(prepped)
            except Exception as e:  # noqa: BLE001 — a device fault drops one batch
                faults.record_fault("firehose.device_loop", e, domain="firehose")
                with self._stats_lock:
                    self.errored += len(prepped[0])
                    self.device_faults += 1

    # -- synchronous mode / shutdown ---------------------------------------------

    def drain(self) -> int:
        """Inline pipeline for ``synchronous=True``: form + prep + verify
        until the intake is empty. Returns batches processed."""
        n = 0
        while True:
            batch = self.batcher.form_now()
            if batch is None:
                return n
            self._verify_batch(self._prep_batch(batch))
            n += 1

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until everything ACCEPTED so far has a verdict or was
        evicted (or the timeout expires — a hard deadline: a wedged device
        call is recorded as a classified hang fault, never waited out).
        Threaded mode only. Gate-rejected submissions never enter
        ``submitted``, so only post-accept evictions count against it — a
        batch mid-verify keeps this False until its verdicts land."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._stats_lock:
                settled = self.verified + self.rejected + self.errored
            shed = self.batcher.evicted + sum(self.batcher.expired.values())
            if settled + shed >= self.batcher.submitted:
                return True
            time.sleep(0.005)
        faults.record_fault(
            "firehose.flush",
            f"flush timeout: verdicts still outstanding after {timeout:.1f}s",
            kind=faults.FaultKind.HANG,
            domain="firehose",
        )
        return False

    def stop(self, drain_timeout: float = 30.0) -> bool:
        """Drain + shut down the pipeline. ``drain_timeout`` is a HARD
        deadline across both threads: a device call wedged inside the
        backend cannot block shutdown forever — the wedge is recorded as a
        classified hang fault, the handoff queue is aborted so the prep
        thread exits, and the stranded daemon thread is abandoned. Returns
        True on a clean drain, False when a thread had to be abandoned."""
        if self.synchronous:
            self.drain()
            return True
        if not self._stopping:
            self._stopping = True
            self.batcher.close()
        deadline = time.monotonic() + drain_timeout
        for th in self._threads:
            th.join(timeout=max(0.0, deadline - time.monotonic()))
        alive = [th.name for th in self._threads if th.is_alive()]
        if not alive:
            return True
        faults.record_fault(
            "firehose.shutdown",
            f"threads {alive} still alive after the {drain_timeout:.1f}s "
            "drain deadline (wedged device call?)",
            kind=faults.FaultKind.HANG,
            domain="firehose",
        )
        self._aborted = True
        try:  # unwedge a prep thread blocked on the handoff queue
            while True:
                self._prepared.get_nowait()
        except queue.Empty:
            pass
        for th in self._threads:
            th.join(timeout=0.5)
        return False

    # -- reporting ----------------------------------------------------------------

    def total_dropped(self) -> int:
        return sum(self.batcher.dropped.values())

    @staticmethod
    def _percentile(sorted_vals: list[float], q: float) -> float | None:
        if not sorted_vals:
            return None
        idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
        return sorted_vals[idx]

    def stats(self) -> FirehoseStats:
        with self._stats_lock:
            lats = sorted(self._latencies)
            e2e = sorted(self._e2e_latencies)
            return FirehoseStats(
                submitted=self.batcher.submitted,
                verified=self.verified,
                rejected=self.rejected,
                errored=self.errored,
                dropped=self.total_dropped(),
                batches_formed=self.batches_formed,
                p50_latency_s=self._percentile(lats, 0.50),
                p99_latency_s=self._percentile(lats, 0.99),
                device_faults=self.device_faults,
                expired=sum(self.batcher.expired.values()),
                p50_e2e_s=self._percentile(e2e, 0.50),
                p99_e2e_s=self._percentile(e2e, 0.99),
            )

    def resilience(self) -> dict | None:
        """Attached fault-domain snapshot (None without a supervisor)."""
        return None if self.supervisor is None else self.supervisor.snapshot()
