"""Adaptive intake + fixed-shape batch forming for the gossip firehose.

Holds one bounded queue per ``WorkType`` (the scheduler's priority table,
``beacon_processor/processor.py``) and forms homogeneous batches for the
device backend:

  * a batch closes as soon as ``max_batch`` items of one type are buffered
    (a burst amortizes one device dispatch), or when the OLDEST buffered
    item of that type has waited ``deadline_s`` (a trickle never stalls);
  * batch sizes are padded downstream to the device backend's power-of-two
    plan shapes (``bls.tpu_backend.bucket``), so closing at ``max_batch``
    keeps every dispatch inside the precompiled bucket family;
  * the intake is bounded by ``intake_capacity`` across all types plus
    per-type caps. Overflow sheds the LOWEST-priority buffered work first
    (largest ``WorkType`` value — the inverse of the scheduler's pop order),
    so an attestation flood cannot starve aggregates, and ``submit`` never
    blocks the caller (the gossip/network thread).

Attestation-family queues are LIFO (freshest first — stale attestations age
out of fork-choice relevance fast), matching the scheduler's ``_LIFO`` set.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..beacon_processor.processor import WorkType, _LIFO
from ..utils.metrics import (
    FIREHOSE_DROPPED,
    FIREHOSE_EXPIRED,
    FIREHOSE_INTAKE_DEPTH,
)


@dataclass
class FirehoseItem:
    """One unit of streaming work plus its intake timestamp (queue-latency
    measurement runs enqueue -> verdict).

    ``ingest_at`` is the earlier WIRE-ingest stamp when the item rode the
    gossip pipeline before reaching the intake (end-to-end gossip->verdict
    latency runs from it); ``deadline`` is the absolute monotonic expiry —
    expired items are shed at batch-form time, before any device dispatch."""

    work_type: WorkType
    payload: object
    callback: object = None          # callback(payload, ok: bool) after verify
    enqueued_at: float = field(default_factory=time.monotonic)
    ingest_at: float | None = None
    deadline: float | None = None


@dataclass
class FirehoseConfig:
    max_batch: int = 64              # close a batch at this many items
    deadline_s: float = 0.010        # max wait on the oldest buffered item
    intake_capacity: int = 8192      # total buffered items across work types
    per_type_capacity: dict = field(default_factory=dict)  # WorkType -> cap
    prep_depth: int = 1              # prepared batches buffered ahead of device

    def type_limit(self, t: WorkType) -> int:
        return self.per_type_capacity.get(t, self.intake_capacity)


class AdaptiveBatcher:
    """Bounded multi-priority intake with deadline-driven batch forming."""

    def __init__(self, config: FirehoseConfig | None = None):
        self.config = config or FirehoseConfig()
        self._queues: dict[WorkType, deque] = {}
        self._depth = 0
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._closed = False
        self.dropped: dict[WorkType, int] = {}
        self.expired: dict[WorkType, int] = {}
        self.submitted = 0   # ACCEPTED items (gate rejections not included)
        self.evicted = 0     # accepted items later shed by back-pressure
        self.high_water = 0  # max total intake depth ever observed
        self._expired_out: list[FirehoseItem] = []  # await callbacks

    # -- intake (non-blocking; called from network/gossip threads) ---------------

    def submit(self, item: FirehoseItem) -> bool:
        """Buffer one item. Returns False when the item was shed. Never
        blocks: overflow evicts the lowest-priority buffered work (or
        rejects ``item`` itself when nothing buffered is lower priority)."""
        t = item.work_type
        with self._lock:
            if self._closed:
                return False
            q = self._queues.get(t)
            if q is None:
                q = self._queues[t] = deque()
            if len(q) >= self.config.type_limit(t):
                self._drop(t, 1)
                return False
            if self._depth >= self.config.intake_capacity:
                if not self._shed_lower_priority_than(t):
                    self._drop(t, 1)
                    return False
            if t in _LIFO:
                q.appendleft(item)
            else:
                q.append(item)
            self._depth += 1
            self.submitted += 1
            if self._depth > self.high_water:
                self.high_water = self._depth
            FIREHOSE_INTAKE_DEPTH.set(len(q), work_type=t.name)
            self._ready.notify()
        return True

    def _drop(self, t: WorkType, n: int) -> None:
        self.dropped[t] = self.dropped.get(t, 0) + n
        FIREHOSE_DROPPED.inc(n, work_type=t.name)

    def _shed_lower_priority_than(self, t: WorkType) -> bool:
        """Evict one buffered item of strictly lower priority than ``t``
        (higher WorkType value), preferring the lowest. Caller holds the
        lock. Returns False when ``t`` is itself the lowest priority."""
        for cand in sorted(self._queues, key=lambda w: w.value, reverse=True):
            if cand.value <= t.value:
                break
            q = self._queues[cand]
            if q:
                # shed the STALEST item of the victim type (queue tail for
                # LIFO types, head for FIFO) — freshest work survives
                q.pop() if cand in _LIFO else q.popleft()
                self._depth -= 1
                self.evicted += 1
                self._drop(cand, 1)
                FIREHOSE_INTAKE_DEPTH.set(len(q), work_type=cand.name)
                return True
        return False

    # -- batch forming (the pipeline's host thread) -------------------------------

    def depth(self, t: WorkType | None = None) -> int:
        with self._lock:
            if t is None:
                return self._depth
            return len(self._queues.get(t, ()))

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return sum(self.dropped.values()) + sum(self.expired.values())

    def oldest_age(self) -> float | None:
        """Age (s) of the oldest buffered item — the LoadMonitor's worker-lag
        signal. None when the intake is empty."""
        now = time.monotonic()
        with self._lock:
            best = None
            for t, q in self._queues.items():
                if not q:
                    continue
                oldest = q[-1] if t in _LIFO else q[0]
                if best is None or oldest.enqueued_at < best:
                    best = oldest.enqueued_at
            return None if best is None else now - best

    def _oldest_deadline(self) -> float | None:
        """Earliest flush time over nonempty queues. Caller holds the lock."""
        best = None
        for t, q in self._queues.items():
            if not q:
                continue
            # oldest item: tail for LIFO queues, head for FIFO
            oldest = q[-1] if t in _LIFO else q[0]
            flush_at = oldest.enqueued_at + self.config.deadline_s
            if best is None or flush_at < best:
                best = flush_at
        return best

    def _form_locked(self, force: bool) -> list[FirehoseItem] | None:
        """Highest-priority queue that is full-batch ready (or past its
        deadline, or ``force``) -> homogeneous batch. Caller holds lock."""
        now = time.monotonic()
        for t in sorted(self._queues, key=lambda w: w.value):
            q = self._queues[t]
            if not q:
                continue
            oldest = q[-1] if t in _LIFO else q[0]
            if (
                len(q) >= self.config.max_batch
                or force
                or now - oldest.enqueued_at >= self.config.deadline_s
            ):
                n = min(len(q), self.config.max_batch)
                batch = []
                expired = []
                while q and len(batch) < n:
                    it = q.popleft()
                    self._depth -= 1
                    # per-item deadline: expired work is shed HERE, the
                    # last host-side gate before device dispatch
                    if it.deadline is not None and now > it.deadline:
                        expired.append(it)
                        self.expired[t] = self.expired.get(t, 0) + 1
                        FIREHOSE_EXPIRED.inc(work_type=t.name)
                    else:
                        batch.append(it)
                FIREHOSE_INTAKE_DEPTH.set(len(q), work_type=t.name)
                # callbacks fire outside the lock (see _fire_expired)
                self._expired_out.extend(expired)
                if not batch:
                    continue
                return batch
        return None

    @property
    def expired_total(self) -> int:
        with self._lock:
            return sum(self.expired.values())

    def _fire_expired(self) -> None:
        """Deliver verdict=False callbacks for deadline-shed items, outside
        the intake lock (a callback may log, score a peer, or resubmit)."""
        with self._lock:
            out, self._expired_out = self._expired_out, []
        for it in out:
            if it.callback is not None:
                try:
                    # engine-style callbacks take (payload, ok, meta)
                    it.callback(it.payload, False, None)
                except TypeError:
                    it.callback(it.payload, False)

    def next_batch(self, timeout: float | None = None) -> list[FirehoseItem] | None:
        """Block until a batch is ready (full, or the oldest item's deadline
        expires), the batcher closes, or ``timeout`` elapses. Returns None
        on timeout/close with nothing buffered."""
        try:
            return self._next_batch_inner(timeout)
        finally:
            self._fire_expired()

    def _next_batch_inner(self, timeout):
        give_up = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                batch = self._form_locked(force=self._closed)
                if batch is not None:
                    return batch
                if self._closed:
                    return None
                wait_until = self._oldest_deadline()
                if give_up is not None and (
                    wait_until is None or give_up < wait_until
                ):
                    wait_until = give_up
                if wait_until is None:
                    self._ready.wait(timeout=0.05)
                else:
                    remaining = wait_until - time.monotonic()
                    if remaining <= 0:
                        if give_up is not None and time.monotonic() >= give_up:
                            return self._form_locked(force=False)
                        # deadline passed: form whatever is buffered
                        batch = self._form_locked(force=True)
                        if batch is not None:
                            return batch
                        continue
                    self._ready.wait(timeout=remaining)

    def form_now(self) -> list[FirehoseItem] | None:
        """Form a batch immediately regardless of deadlines (synchronous
        drain mode)."""
        try:
            with self._lock:
                return self._form_locked(force=True)
        finally:
            self._fire_expired()

    def close(self) -> None:
        """Stop accepting new work; ``next_batch`` drains what remains then
        returns None."""
        with self._lock:
            self._closed = True
            self._ready.notify_all()
