"""Gossip firehose verification engine.

The streaming layer between ``beacon_processor`` and the batched BLS device
backend (``bls.verify_signature_sets`` / ``bls.tpu_backend``). The reference
client survives the gossip attestation firehose through machinery this
package reproduces TPU-first:

  * **adaptive batching** (``batcher.py``) — fixed-shape signature-set
    batches (padded to the device backend's power-of-two plan shapes) formed
    under a latency deadline, so a trickle never stalls and a burst
    amortizes one device dispatch over many sets
    (``beacon_processor/src/lib.rs`` batch forming, :219-254);
  * **double-buffered pipeline** (``engine.py``) — host-side work
    (hash-to-field, signature parse, committee-cache lookups) for batch N+1
    overlaps device verification of batch N;
  * **back-pressure + shedding** (``batcher.py``) — a bounded intake with a
    per-WorkType drop policy mirroring ``beacon_processor/processor.py``
    (arXiv 2109.11677 flags unbounded verification queues as a DoS surface:
    back-pressure is a correctness property, not a nicety);
  * **bisection fallback** (``bisect.py``) — an aggregate batch failure is
    split-and-retried to isolate the poisoned set(s) in O(bad * log n)
    device calls instead of n per-set calls;
  * **attester/shuffling cache tier** (``attester_cache.py``) — committee
    resolution for gossip attestations off the full-state path
    (``beacon_chain/src/attester_cache.rs`` / ``shuffling_cache.rs`` parity);
  * **sharded serving tier** (``sharding.py``) — N fixed-shape sub-batches
    per tick data-parallel over the device mesh with per-shard verdicts and
    per-shard fault domains (mesh -> N/2 -> single -> CPU-oracle ladder),
    behind the ``LIGHTHOUSE_MESH_DEVICES`` seam (``bls/mesh.py``).
"""

from .attester_cache import (
    AttesterCacheTier,
    ShufflingCache,
    attester_shuffling_decision_slot,
)
from .batcher import AdaptiveBatcher, FirehoseConfig, FirehoseItem
from .bisect import bisect_verify
from .engine import FirehoseEngine, FirehoseStats
from .sharding import MeshVerifier, ShardPlan, plan_shards

__all__ = [
    "AdaptiveBatcher",
    "AttesterCacheTier",
    "FirehoseConfig",
    "FirehoseEngine",
    "FirehoseItem",
    "FirehoseStats",
    "MeshVerifier",
    "ShardPlan",
    "ShufflingCache",
    "attester_shuffling_decision_slot",
    "bisect_verify",
    "plan_shards",
]
