"""Split-and-retry isolation of poisoned signature-set batches.

A random-linear-combination batch verify returns one bit for the whole
batch. When it fails, the reference re-verifies every set individually
(``attestation_verification/batch.rs:109-113``) — n extra verifies for one
bad set. Bisection does it in O(bad * log n): verify each half, recurse into
failing halves only. Every recursion level still runs as *batched* device
calls, so the device shapes stay in the compiled bucket family.
"""

from __future__ import annotations


def bisect_verify(groups, verify_fn, assume_failed: bool = False) -> list[bool]:
    """Per-group verdicts for a batch of signature-set groups.

    ``groups``: list of groups, each a list of signature-set items that must
    verify *together* (one item for an unaggregated attestation; three for a
    SignedAggregateAndProof). ``verify_fn(flat_items) -> bool`` is the
    batched verifier. ``assume_failed=True`` skips the initial whole-batch
    call (the caller already saw it fail).

    Exactly the groups whose own items fail verification come back False;
    an RLC batch failure anywhere above them never condemns a good group.
    """
    groups = list(groups)
    verdicts = [True] * len(groups)

    def rec(lo: int, hi: int, known_failed: bool) -> None:
        items = [item for g in groups[lo:hi] for item in g]
        if not items:
            return
        if not known_failed and verify_fn(items):
            return
        if hi - lo == 1:
            verdicts[lo] = False
            return
        mid = (lo + hi) // 2
        # a failed parent batch does NOT mean both halves fail — re-verify each
        rec(lo, mid, False)
        rec(mid, hi, False)

    rec(0, len(groups), assume_failed)
    return verdicts
