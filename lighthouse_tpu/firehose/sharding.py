"""Shard-aware serving tier: group planning + per-shard fault domains.

The data-parallel layer between the firehose engine and the device mesh.
Deliberately jax-free (like ``resilience/``): the mesh arithmetic lives in
``bls/mesh.py`` + ``bls/tpu_backend.py`` and is injected as callables, so
every fault-domain decision here is unit-testable with stubs and the
supervisor wrappers never trace into a jit (the analysis suite's
zero-recompile + concurrency passes stay green).

Two pieces:

* ``plan_shards`` — forms N fixed-shape sub-batches per tick: whole
  signature-set *groups* (1 set per unaggregated attestation, 3 per
  aggregate) are least-loaded-assigned to shards so a group never straddles
  a shard boundary, and each sub-batch is padded to a shared power-of-two
  cap — padding per shard, not per mesh, so the compile family is keyed by
  the per-shard shape and a steady-state stream never recompiles.

* ``MeshVerifier`` — the per-shard fault domains and the mesh degradation
  ladder. One ``resilience`` supervisor per device (``bls_shard<i>``) plus
  one mesh-level supervisor (``bls_mesh``) drive the ladder::

      mesh N -> mesh N/2 -> ... -> single device -> CPU oracle

  A faulted shard demotes ONLY itself (its supervisor walks the normal
  HEALTHY -> DEGRADED -> QUARANTINED machinery); the mesh shrinks around it
  — first within the call (the ladder descends past the faulted shard) and
  then across calls (a quarantined shard leaves ``healthy`` until its
  probation probe, at which point the mesh re-grows; both transitions are
  visible in the resilience metrics). Verdict integrity is fail-closed:
  when every rung faults the call raises ``SupervisedFault`` and callers
  count the batch as errored — work may be dropped, never falsely verified.

  Injection seams (``LIGHTHOUSE_FAULT_INJECT``): ``mesh.shard<i>`` faults
  device i's pre-dispatch liveness check; ``bls.mesh_verify`` /
  ``bls.mesh_verify/mesh<k>`` target the mesh rungs themselves.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..resilience import SupervisorConfig, get_supervisor
from ..resilience.supervisor import run_with_deadline
from ..utils.metrics import MESH_ACTIVE_DEVICES, MESH_SHARD_VERDICTS

MESH_DOMAIN = "bls_mesh"
SHARD_DOMAIN_PREFIX = "bls_shard"


def pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _bucket(n: int, floor: int = 1) -> int:
    b = max(1, floor)
    while b < n:
        b *= 2
    return b


@dataclass
class ShardPlan:
    """One tick's shard assignment: ``shard_items[s]`` is shard s's
    sub-batch (item triples, ≤ ``cap``), ``group_shard[g]`` maps group g to
    its shard — the per-shard verdict vector indexes back to groups."""

    shard_items: list
    group_shard: list
    cap: int


def plan_shards(groups, n_shards: int, cap_floor: int = 4) -> ShardPlan:
    """Assign whole groups to shards, least-loaded-first (deterministic:
    ties go to the lowest shard index), then bucket the cap to the largest
    fill. Groups never straddle shards, so one shard's verdict covers each
    of its groups completely."""
    shard_items = [[] for _ in range(n_shards)]
    group_shard = []
    fills = [0] * n_shards
    for g in groups:
        s = min(range(n_shards), key=lambda i: (fills[i], i))
        shard_items[s].extend(g)
        group_shard.append(s)
        fills[s] += len(g)
    cap = _bucket(max([cap_floor] + fills))
    return ShardPlan(shard_items, group_shard, cap)


class MeshShrunk(RuntimeError):
    """Not enough healthy shards for a mesh rung — the ladder descends to
    the next (smaller) rung; health only changes via probation probes."""


class MeshVerifier:
    """Per-shard fault domains + the mesh degradation ladder (module
    docstring). All device work is injected:

    * ``dispatch_fn(shard_items, device_ids, staged=None, shard_cap=None)``
      -> per-shard verdict list (``bls.mesh.MeshBackend.dispatch``);
    * ``stage_fn(shard_items, device_ids, shard_cap)`` -> opaque staged
      arrays (prep-thread H2D double-buffering; optional);
    * ``single_fn(flat_items) -> bool`` — the single-device engine (the
      ladder's bit-identical-to-today rung);
    * ``oracle_fn(flat_items) -> bool`` — the device-free CPU rung of last
      resort;
    * ``probe_fn(device_id)`` — tiny per-device op for attributing an
      unattributed mesh fault to the shard that caused it (optional).

    Holds NO mutable state of its own — per-call state is call-local and
    cross-call health lives in the process-global supervisors, so instances
    are freely shared between the engine's prep and device threads.
    """

    def __init__(
        self,
        n_devices: int,
        dispatch_fn,
        single_fn=None,
        oracle_fn=None,
        stage_fn=None,
        probe_fn=None,
        cap_floor: int = 4,
        probe_deadline_s: float = 30.0,
        domain: str = MESH_DOMAIN,
        shard_domain_prefix: str = SHARD_DOMAIN_PREFIX,
    ):
        self.n_devices = pow2_floor(max(1, n_devices))
        self.dispatch_fn = dispatch_fn
        self.single_fn = single_fn
        self.oracle_fn = oracle_fn
        self.stage_fn = stage_fn
        self.probe_fn = probe_fn
        self.cap_floor = cap_floor
        self.probe_deadline_s = probe_deadline_s
        self.domain = domain
        # mesh-level supervisor: no in-place retries (a failed mesh rung
        # descends to the shrunken mesh instead of re-dispatching the same
        # shape — the smaller rung IS the retry)
        self.mesh_sup = get_supervisor(domain, SupervisorConfig(max_retries=0))
        # per-device fault domains; deadline 0 = no watchdog thread on the
        # (in-process, non-blocking) liveness check — the dispatch itself
        # runs under the mesh supervisor's watchdog
        self.shard_sups = [
            get_supervisor(
                f"{shard_domain_prefix}{i}",
                SupervisorConfig(deadline_s=0, max_retries=0),
            )
            for i in range(self.n_devices)
        ]

    # -- shard health -------------------------------------------------------

    def healthy_indices(self) -> list[int]:
        """Devices currently allowed to serve (a QUARANTINED shard leaves
        this set until its probation probe re-admits it — that exit/return
        is the cross-call mesh shrink/re-grow)."""
        return [
            i for i in range(self.n_devices)
            if self.shard_sups[i].device_allowed()
        ]

    def _check_shards(self, idxs, failed: set) -> None:
        """Pre-dispatch per-shard liveness seam: the ``mesh.shard<i>``
        injection point, run through each shard's OWN supervisor so a fault
        demotes exactly that shard."""
        for i in idxs:
            try:
                self.shard_sups[i].run(f"mesh.shard{i}", lambda: None)
            except Exception:
                failed.add(i)
                raise

    def _attribute(self, idxs, failed: set) -> None:
        """After an unattributed mesh dispatch fault: probe each
        participating device (bounded by ``run_with_deadline`` — a wedged
        device must not pin the serving thread) through its shard
        supervisor; faulted shards demote and leave the next rung's mesh.
        Attribution is best-effort — it must never mask the dispatch fault."""
        if self.probe_fn is None:
            return
        for i in idxs:
            try:
                self.shard_sups[i].run(
                    f"mesh.shard{i}.probe",
                    lambda i=i: run_with_deadline(
                        f"mesh.shard{i}.probe",
                        lambda: self.probe_fn(i),
                        self.probe_deadline_s,
                    ),
                )
            except Exception:  # noqa: BLE001 — recorded by the supervisor
                failed.add(i)

    # -- staging (prep-thread half of the double buffer) --------------------

    def stage(self, groups):
        """Host prep + per-shard H2D for one tick, run on the firehose prep
        thread while the device thread verifies the previous tick. Returns
        an opaque handle for ``verify_groups`` or None (no ``stage_fn``, a
        degraded mesh, or a staging fault — dispatch re-stages inline)."""
        if self.stage_fn is None or not groups:
            return None
        idxs = self._block_for(self.n_devices, set())
        if idxs is None:
            return None  # shrunken mesh: let the ladder pick the layout
        plan = plan_shards(groups, self.n_devices, self.cap_floor)
        try:
            arrays = self.stage_fn(plan.shard_items, tuple(idxs), plan.cap)
        except Exception:  # noqa: BLE001 — staging is an optimization only
            return None
        return {"plan": plan, "device_ids": list(idxs), "arrays": arrays}

    # -- the supervised mesh ladder ----------------------------------------

    def _block_for(self, size: int, failed: set) -> list[int] | None:
        """First aligned ``size``-device block with every member healthy.
        Shrunken meshes come from ALIGNED BLOCKS (0..N/2, N/2..N, ...), not
        arbitrary healthy subsets: the compile-family count stays bounded
        (≤ 2N-1 meshes ever), selection is deterministic, and blocks match
        real pod ICI locality."""
        allowed = set(self.healthy_indices()) - failed
        for start in range(0, self.n_devices, size):
            block = list(range(start, start + size))
            if all(i in allowed for i in block):
                return block
        return None

    def _mesh_rung(self, groups, size: int, failed: set, staged):
        def run():
            idxs = self._block_for(size, failed)
            if idxs is None:
                raise MeshShrunk(
                    f"no fully-healthy {size}-device block "
                    f"(failed={sorted(failed)})"
                )
            self._check_shards(idxs, failed)
            try:
                if staged is not None and staged["device_ids"] == idxs:
                    plan = staged["plan"]
                    verdicts = self.dispatch_fn(
                        None, tuple(idxs), staged=staged["arrays"]
                    )
                else:
                    plan = plan_shards(groups, size, self.cap_floor)
                    verdicts = self.dispatch_fn(
                        plan.shard_items, tuple(idxs), shard_cap=plan.cap
                    )
            except Exception:
                self._attribute(idxs, failed)
                raise
            MESH_ACTIVE_DEVICES.set(len(idxs), domain=self.domain)
            # the kernel reports False for a shard with no valid rows; an
            # empty shard is not a failure — count it apart so the
            # failed-shard counter stays a real health signal
            owned = set(plan.group_shard)
            for s, ok in enumerate(verdicts):
                if s not in owned:
                    MESH_SHARD_VERDICTS.inc(result="empty")
                else:
                    MESH_SHARD_VERDICTS.inc(result="ok" if ok else "failed")
            return [
                bool(verdicts[plan.group_shard[g]])
                for g in range(len(groups))
            ]

        return run

    def _rungs(self, groups, staged):
        rungs = []
        size = self.n_devices
        failed: set[int] = set()
        first = True
        while size > 1:
            rungs.append((
                f"mesh{size}",
                self._mesh_rung(groups, size, failed, staged if first else None),
            ))
            first = False
            size //= 2
        flat = [it for g in groups for it in g]
        n = len(groups)
        if self.single_fn is not None:
            # one verdict for the whole flat batch: True verifies every
            # group; False means "no attribution" — callers bisect
            rungs.append((
                "device_single", lambda: [bool(self.single_fn(flat))] * n
            ))
        if self.oracle_fn is not None:
            rungs.append((
                "cpu_oracle", lambda: [bool(self.oracle_fn(flat))] * n
            ))
        return rungs

    def verify_groups(self, groups, staged=None) -> list[bool]:
        """Per-GROUP verdicts for one tick (group g's bool is its shard's
        RLC verdict: True proves every set in the group). Raises
        ``SupervisedFault`` when every rung faulted — the caller fails
        closed (counts the batch errored, verifies nothing)."""
        groups = list(groups)
        if not groups:
            return []
        return self.mesh_sup.run_ladder(
            "bls.mesh_verify", self._rungs(groups, staged)
        )

    def verify_items(self, items) -> bool:
        """The ``_batch_verify_items`` drop-in: one bool for a flat item
        batch (each item its own group — per-shard verdicts simply sharpen
        the downstream bisection). Exceptions propagate like the ladder's."""
        return all(self.verify_groups([[it] for it in items]))

    # -- observability ------------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "n_devices": self.n_devices,
            "healthy": self.healthy_indices(),
            "mesh": self.mesh_sup.snapshot(),
            "shards": {
                i: s.snapshot() for i, s in enumerate(self.shard_sups)
            },
        }
