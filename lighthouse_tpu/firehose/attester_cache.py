"""Attester/shuffling cache tier: committee resolution off the full-state path.

Parity targets: ``beacon_chain/src/shuffling_cache.rs`` (CommitteeCache by
shuffling decision root) and ``attester_cache.rs`` (everything gossip
attestation verification needs, cached per (epoch, decision root) so the hot
path never clones or slot-advances a BeaconState).

The attester shuffling for epoch E is fixed by the RANDAO mix at the end of
epoch E-2 (seed lookahead 1), so its cache key is the **decision root**: the
block root at the last slot of epoch E-2 on the attestation's own chain.
Two states that agree on that root produce byte-identical committees — the
property ``tests/test_firehose.py`` pins across an epoch boundary. The
decision root itself is resolved through fork choice's proto-array ancestor
walk (no state access).

The signing domain needs only the fork schedule and the genesis validators
root, both known without a state, so a cache hit builds the complete
``(indices, signing_root, signature)`` triple for the device backend from
cached data alone.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..state_transition.beacon_state_util import (
    CommitteeCache,
    get_block_root_at_slot,
)
from ..types.helpers import compute_domain, compute_signing_root
from ..utils.metrics import FIREHOSE_SHUFFLING_CACHE


def attester_shuffling_decision_slot(spec, target_epoch: int) -> int:
    """Last slot of epoch E-2 — where the attester shuffling for epoch E is
    decided (``attestation_shuffling_decision_slot``). Saturates to 0 for
    the first two epochs."""
    if target_epoch < 2:
        return 0
    return spec.start_slot(target_epoch - 1) - 1


def attester_shuffling_decision_root(
    spec, state, target_epoch: int, block_root: bytes
) -> bytes:
    """Decision root from a state that holds the attestation's chain.
    Falls back to ``block_root`` when the state predates the decision slot
    (early-chain genesis case — the reference uses the state's own root
    there too)."""
    slot = attester_shuffling_decision_slot(spec, target_epoch)
    if state.slot <= slot:
        return block_root
    try:
        return bytes(get_block_root_at_slot(spec, state, slot))
    except Exception:  # noqa: BLE001 — out of historical range: no cache key
        return block_root


class ShufflingCache:
    """LRU of ``CommitteeCache`` keyed by (epoch, decision_root)
    (``shuffling_cache.rs``; the reference holds 16 entries)."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._entries: OrderedDict[tuple, CommitteeCache] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> CommitteeCache | None:
        with self._lock:
            cc = self._entries.get(key)
            if cc is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                FIREHOSE_SHUFFLING_CACHE.inc(result="hit")
            else:
                self.misses += 1
                FIREHOSE_SHUFFLING_CACHE.inc(result="miss")
            return cc

    def insert(self, key: tuple, cc: CommitteeCache) -> None:
        with self._lock:
            self._entries[key] = cc
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class AttesterCacheTier:
    """The gossip hot path's committee/pubkey resolution tier.

    ``committee_for`` answers from the shuffling cache when the decision
    root is resolvable through fork choice; ``state_fallback`` (wired by the
    chain to its full-state path) fills misses and doubles as the reference
    implementation the cache is pinned against.
    """

    def __init__(
        self,
        spec,
        genesis_validators_root: bytes,
        ancestor_at_slot=None,
        state_fallback=None,
        capacity: int = 16,
    ):
        self.spec = spec
        self.genesis_validators_root = bytes(genesis_validators_root)
        self.shuffling = ShufflingCache(capacity=capacity)
        # ancestor_at_slot(block_root, slot) -> root, via fork choice
        self.ancestor_at_slot = ancestor_at_slot
        # state_fallback(block_root, slot) -> state advanced to `slot`
        self.state_fallback = state_fallback

    # -- key resolution (no state access) ----------------------------------------

    def decision_key(self, target_epoch: int, beacon_block_root: bytes):
        """(epoch, decision_root) via the proto-array ancestor walk, or None
        when fork choice cannot resolve the chain (unknown block)."""
        if self.ancestor_at_slot is None:
            return None
        slot = attester_shuffling_decision_slot(self.spec, target_epoch)
        root = self.ancestor_at_slot(bytes(beacon_block_root), slot)
        if root is None:
            return None
        return (int(target_epoch), bytes(root))

    # -- committee resolution ------------------------------------------------------

    def committee_for(self, data) -> "object | None":
        """Committee (validator indices, numpy array) for an AttestationData,
        from cache when possible, else through the full-state fallback
        (which also populates the cache). None when the chain is unknown."""
        epoch = self.spec.compute_epoch_at_slot(int(data.slot))
        key = self.decision_key(epoch, bytes(data.beacon_block_root))
        cc = self.shuffling.get(key) if key is not None else None
        if cc is None:
            cc = self._fill(key, int(data.slot), bytes(data.beacon_block_root))
            if cc is None:
                return None
        return cc.committee(int(data.slot), int(data.index))

    def _fill(self, key, slot: int, block_root: bytes) -> CommitteeCache | None:
        if self.state_fallback is None:
            return None
        state = self.state_fallback(block_root, slot)
        if state is None:
            return None
        epoch = self.spec.compute_epoch_at_slot(slot)
        cc = CommitteeCache(self.spec, state, epoch)
        if key is None:
            # fork choice couldn't resolve the decision root; derive it from
            # the state we were handed so the NEXT lookup hits
            key = (
                epoch,
                attester_shuffling_decision_root(
                    self.spec, state, epoch, block_root
                ),
            )
        self.shuffling.insert(key, cc)
        return cc

    # -- signing-root construction (state-free) ------------------------------------

    def attester_domain(self, target_epoch: int) -> bytes:
        """DOMAIN_BEACON_ATTESTER at the target epoch from the fork schedule
        alone (equals ``get_domain(state, ...)`` for any state on schedule)."""
        return compute_domain(
            self.spec.DOMAIN_BEACON_ATTESTER,
            self.spec.fork_version_at_epoch(int(target_epoch)),
            self.genesis_validators_root,
        )

    def signing_root(self, data) -> bytes:
        return compute_signing_root(
            data, self.attester_domain(int(data.target.epoch))
        )
