"""Build + load the native BLS12-381 shared library.

Compiles lighthouse_tpu/native/bls12_381.cpp with g++ -O3 into
``_build/libbls12_381.so`` (cached; rebuilt when the source is newer) and
returns a configured ctypes handle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "bls12_381.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")
_LIB = os.path.join(_BUILD_DIR, "libbls12_381.so")

_lock = threading.Lock()
_lib = None


def _compile() -> None:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++",
        "-O3",
        "-march=native",
        "-fno-exceptions",
        "-fPIC",
        "-shared",
        _SRC,
        "-o",
        _LIB + ".tmp",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(_LIB + ".tmp", _LIB)


def load_bls() -> ctypes.CDLL:
    """Load (building if needed) and initialize the native BLS library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(
            _SRC
        ):
            _compile()
        lib = ctypes.CDLL(_LIB)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.bls_native_init.restype = ctypes.c_int
        lib.bls_sk_to_pk.argtypes = [u8p, u8p]
        lib.bls_sign.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.bls_hash_to_g2.argtypes = [u8p, ctypes.c_uint64, u8p]
        lib.bls_pk_validate.argtypes = [u8p]
        lib.bls_pk_validate.restype = ctypes.c_int
        lib.bls_sig_validate.argtypes = [u8p]
        lib.bls_sig_validate.restype = ctypes.c_int
        lib.bls_verify.argtypes = [u8p, u8p, ctypes.c_uint64, u8p]
        lib.bls_verify.restype = ctypes.c_int
        lib.bls_fast_aggregate_verify.argtypes = [
            ctypes.c_uint64,
            u8p,
            u8p,
            ctypes.c_uint64,
            u8p,
        ]
        lib.bls_fast_aggregate_verify.restype = ctypes.c_int
        lib.bls_aggregate_pubkeys.argtypes = [ctypes.c_uint64, u8p, u8p]
        lib.bls_aggregate_pubkeys.restype = ctypes.c_int
        lib.bls_aggregate_signatures.argtypes = [ctypes.c_uint64, u8p, u8p]
        lib.bls_aggregate_signatures.restype = ctypes.c_int
        lib.bls_verify_signature_sets.argtypes = [
            ctypes.c_uint64,
            u64p,
            u8p,
            u8p,
            u8p,
            u64p,
        ]
        lib.bls_verify_signature_sets.restype = ctypes.c_int
        lib.bls_g2_mul.argtypes = [u8p, u8p, u8p]
        lib.bls_g2_mul.restype = ctypes.c_int
        lib.bls_pk_decompress.argtypes = [u8p, u8p]
        lib.bls_pk_decompress.restype = ctypes.c_int
        lib.bls_verify_signature_sets_raw.argtypes = [
            ctypes.c_uint64,
            u64p,
            u8p,
            u8p,
            u8p,
            u64p,
        ]
        lib.bls_verify_signature_sets_raw.restype = ctypes.c_int
        rc = lib.bls_native_init()
        if rc != 0:
            raise RuntimeError(f"bls_native_init failed: {rc}")
        _lib = lib
        return _lib


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def _check_len(name: str, data: bytes, n: int) -> None:
    if len(data) != n:
        raise ValueError(f"{name} must be {n} bytes, got {len(data)}")


class NativeBls:
    """Bytes-level convenience wrapper over the C API (wire-format in/out)."""

    def __init__(self):
        self._lib = load_bls()

    def sk_to_pk(self, sk: bytes) -> bytes:
        _check_len("sk", sk, 32)
        out = (ctypes.c_uint8 * 48)()
        self._lib.bls_sk_to_pk(_buf(sk), out)
        return bytes(out)

    def sign(self, sk: bytes, msg: bytes) -> bytes:
        _check_len("sk", sk, 32)
        out = (ctypes.c_uint8 * 96)()
        self._lib.bls_sign(_buf(sk), _buf(msg), len(msg), out)
        return bytes(out)

    def hash_to_g2(self, msg: bytes) -> bytes:
        out = (ctypes.c_uint8 * 96)()
        self._lib.bls_hash_to_g2(_buf(msg), len(msg), out)
        return bytes(out)

    def pk_validate(self, pk: bytes) -> bool:
        _check_len("pk", pk, 48)
        return bool(self._lib.bls_pk_validate(_buf(pk)))

    def sig_validate(self, sig: bytes) -> bool:
        _check_len("sig", sig, 96)
        return bool(self._lib.bls_sig_validate(_buf(sig)))

    def verify(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        _check_len("pk", pk, 48)
        _check_len("sig", sig, 96)
        return bool(self._lib.bls_verify(_buf(pk), _buf(msg), len(msg), _buf(sig)))

    def fast_aggregate_verify(self, pks: list[bytes], msg: bytes, sig: bytes) -> bool:
        if not pks:
            return False
        for pk in pks:
            _check_len("pk", pk, 48)
        _check_len("sig", sig, 96)
        return bool(
            self._lib.bls_fast_aggregate_verify(
                len(pks), _buf(b"".join(pks)), _buf(msg), len(msg), _buf(sig)
            )
        )

    def aggregate_pubkeys(self, pks: list[bytes]) -> bytes:
        out = (ctypes.c_uint8 * 48)()
        rc = self._lib.bls_aggregate_pubkeys(len(pks), _buf(b"".join(pks)), out)
        if rc != 0:
            raise ValueError("invalid pubkey encoding")
        return bytes(out)

    def aggregate_signatures(self, sigs: list[bytes]) -> bytes:
        out = (ctypes.c_uint8 * 96)()
        rc = self._lib.bls_aggregate_signatures(len(sigs), _buf(b"".join(sigs)), out)
        if rc != 0:
            raise ValueError("invalid signature encoding")
        return bytes(out)

    def g2_mul(self, point: bytes, sk: bytes) -> bytes:
        out = (ctypes.c_uint8 * 96)()
        rc = self._lib.bls_g2_mul(_buf(point), _buf(sk), out)
        if rc != 0:
            raise ValueError("invalid point encoding")
        return bytes(out)

    def verify_signature_sets(
        self,
        pk_sets: list[list[bytes]],
        msgs: list[bytes],
        sigs: list[bytes],
        scalars: list[int],
    ) -> bool:
        """RLC batch verification (blst.rs:37-119 semantics): each set is
        (pubkeys, 32-byte message, signature); scalars are nonzero u64."""
        n = len(pk_sets)
        if n == 0:
            return False
        if not (len(msgs) == len(sigs) == len(scalars) == n):
            raise ValueError("set length mismatch")
        for s in pk_sets:
            for pk in s:
                _check_len("pk", pk, 48)
        for m, g in zip(msgs, sigs):
            _check_len("msg", m, 32)
            _check_len("sig", g, 96)
        counts = (ctypes.c_uint64 * n)(*[len(s) for s in pk_sets])
        pks = _buf(b"".join(b"".join(s) for s in pk_sets))
        rc = self._lib.bls_verify_signature_sets(
            n,
            counts,
            pks,
            _buf(b"".join(msgs)),
            _buf(b"".join(sigs)),
            (ctypes.c_uint64 * n)(*scalars),
        )
        if rc < 0:
            raise ValueError("malformed signature set input")
        return bool(rc)

    def pk_decompress(self, pk: bytes) -> bytes:
        """48B compressed -> 96B raw affine (cacheable, skips sqrt later)."""
        out = (ctypes.c_uint8 * 96)()
        if self._lib.bls_pk_decompress(_buf(pk), out) != 0:
            raise ValueError("invalid pubkey encoding")
        return bytes(out)

    def verify_signature_sets_raw(
        self,
        pk_sets: list[list[bytes]],
        msgs: list[bytes],
        sigs: list[bytes],
        scalars: list[int],
    ) -> bool:
        """Batch verification with 96B pre-decompressed (cached) pubkeys."""
        n = len(pk_sets)
        if n == 0:
            return False
        for s in pk_sets:
            for pk in s:
                _check_len("raw pk", pk, 96)
        for m, g in zip(msgs, sigs):
            _check_len("msg", m, 32)
            _check_len("sig", g, 96)
        counts = (ctypes.c_uint64 * n)(*[len(s) for s in pk_sets])
        pks = _buf(b"".join(b"".join(s) for s in pk_sets))
        rc = self._lib.bls_verify_signature_sets_raw(
            n,
            counts,
            pks,
            _buf(b"".join(msgs)),
            _buf(b"".join(sigs)),
            (ctypes.c_uint64 * n)(*scalars),
        )
        if rc < 0:
            raise ValueError("malformed signature set input")
        return bool(rc)
