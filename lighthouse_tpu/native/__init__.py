"""Native (C++) runtime components.

``bls12_381.cpp`` is the CPU parity backend for the BLS seam — the role blst
plays in the reference (``/root/reference/crypto/bls/Cargo.toml`` supranational
feature). Built on demand with g++ into a shared library cached next to the
source; loaded via ctypes (no pybind11 in this environment).
"""

from .build import load_bls  # noqa: F401
