// bls12_381.cpp — native CPU BLS12-381 backend for lighthouse_tpu.
//
// The framework's CPU parity backend and honest bench baseline: the role blst
// plays for the reference client (/root/reference/crypto/bls/src/impls/
// blst.rs:37-119 verify_multiple_aggregate_signatures; sign/verify at
// blst.rs:172-283). Algorithms mirror this repo's pure-Python oracle
// (lighthouse_tpu/ops/bls_oracle/*) — same tower (Fq2 = Fq[u]/(u^2+1),
// Fq6 = Fq2[v]/(v^3-(u+1)), Fq12 = Fq6[w]/(w^2-v)), same CLN projective
// Miller loop + mul_by_014 sparse folding as the device kernels
// (lighthouse_tpu/ops/bls/pairing.py), same x-chain final exponentiation.
//
// Arithmetic: 6x64-bit limbs, Montgomery form, CIOS multiplication via
// unsigned __int128. Single translation unit; built by native/build.py with
// g++ -O3 -shared. Derived constants (R^2, Montgomery inverse, Frobenius and
// psi coefficients) are computed at init from the modulus rather than
// hardcoded, so a limb typo cannot silently corrupt them.

#include <cstdint>
#include <cstring>
#include <cstdlib>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint32_t u32;
typedef uint8_t u8;

// ---------------------------------------------------------------------------
// Fp: 6x64 limbs, little-endian, Montgomery form
// ---------------------------------------------------------------------------

static const u64 P_LIMBS[6] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL};

// Subgroup order r (scalar field), little-endian.
static const u64 R_LIMBS[4] = {
    0xffffffff00000001ULL, 0x53bda402fffe5bfeULL,
    0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL};

static const u64 BLS_X_ABS = 0xd201000000010000ULL;  // |x|; x is negative

struct Fp {
  u64 l[6];
};

static u64 MONT_INV;  // -p^{-1} mod 2^64
static Fp R2;         // 2^768 mod p (Montgomery conversion factor)
static Fp FP_ONE;     // 2^384 mod p (1 in Montgomery form)
static const Fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static inline int fp_cmp_raw(const u64 a[6], const u64 b[6]) {
  for (int i = 5; i >= 0; i--) {
    if (a[i] < b[i]) return -1;
    if (a[i] > b[i]) return 1;
  }
  return 0;
}

static inline void fp_add(Fp &o, const Fp &a, const Fp &b) {
  u128 c = 0;
  for (int i = 0; i < 6; i++) {
    c += (u128)a.l[i] + b.l[i];
    o.l[i] = (u64)c;
    c >>= 64;
  }
  if (c || fp_cmp_raw(o.l, P_LIMBS) >= 0) {
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
      u128 d = (u128)o.l[i] - P_LIMBS[i] - (u64)br;
      o.l[i] = (u64)d;
      br = (d >> 64) ? 1 : 0;
    }
  }
}

static inline void fp_sub(Fp &o, const Fp &a, const Fp &b) {
  u128 br = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a.l[i] - b.l[i] - (u64)br;
    o.l[i] = (u64)d;
    br = (d >> 64) ? 1 : 0;
  }
  if (br) {
    u128 c = 0;
    for (int i = 0; i < 6; i++) {
      c += (u128)o.l[i] + P_LIMBS[i];
      o.l[i] = (u64)c;
      c >>= 64;
    }
  }
}

static inline void fp_neg(Fp &o, const Fp &a) {
  if (fp_cmp_raw(a.l, FP_ZERO.l) == 0) {
    o = FP_ZERO;
    return;
  }
  u128 br = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)P_LIMBS[i] - a.l[i] - (u64)br;
    o.l[i] = (u64)d;
    br = (d >> 64) ? 1 : 0;
  }
}

static inline void fp_dbl(Fp &o, const Fp &a) { fp_add(o, a, a); }

// CIOS Montgomery multiplication: o = a*b*2^-384 mod p.
static void fp_mul(Fp &o, const Fp &a, const Fp &b) {
  u64 t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  for (int i = 0; i < 6; i++) {
    u128 c = 0;
    for (int j = 0; j < 6; j++) {
      c = (u128)a.l[j] * b.l[i] + t[j] + (u64)c;
      t[j] = (u64)c;
      c >>= 64;
    }
    u128 s = (u128)t[6] + (u64)c;
    t[6] = (u64)s;
    t[7] = (u64)(s >> 64);

    u64 m = t[0] * MONT_INV;
    c = (u128)m * P_LIMBS[0] + t[0];
    c >>= 64;
    for (int j = 1; j < 6; j++) {
      c = (u128)m * P_LIMBS[j] + t[j] + (u64)c;
      t[j - 1] = (u64)c;
      c >>= 64;
    }
    s = (u128)t[6] + (u64)c;
    t[5] = (u64)s;
    t[6] = t[7] + (u64)(s >> 64);
    t[7] = 0;
  }
  if (t[6] || fp_cmp_raw(t, P_LIMBS) >= 0) {
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
      u128 d = (u128)t[i] - P_LIMBS[i] - (u64)br;
      t[i] = (u64)d;
      br = (d >> 64) ? 1 : 0;
    }
  }
  memcpy(o.l, t, 48);
}

static inline void fp_sqr(Fp &o, const Fp &a) { fp_mul(o, a, a); }

static inline bool fp_is_zero(const Fp &a) {
  u64 acc = 0;
  for (int i = 0; i < 6; i++) acc |= a.l[i];
  return acc == 0;
}

static inline bool fp_eq(const Fp &a, const Fp &b) {
  return memcmp(a.l, b.l, 48) == 0;
}

static void fp_to_mont(Fp &o, const Fp &a) { fp_mul(o, a, R2); }

static void fp_from_mont(Fp &o, const Fp &a) {
  Fp one_raw = {{1, 0, 0, 0, 0, 0}};
  fp_mul(o, a, one_raw);
}

// MSB-first square-and-multiply; exponent is nbits bits of e (little-endian limbs).
static void fp_pow(Fp &o, const Fp &base, const u64 *e, int nbits) {
  Fp r = FP_ONE;
  for (int i = nbits - 1; i >= 0; i--) {
    fp_sqr(r, r);
    if ((e[i / 64] >> (i % 64)) & 1) fp_mul(r, r, base);
  }
  o = r;
}

static u64 EXP_P_MINUS_2[6];   // p-2          (Fp inverse)
static u64 EXP_P_PLUS_1_D4[6]; // (p+1)/4      (Fp sqrt)
static u64 EXP_P_MINUS_3_D4[6]; // (p-3)/4     (Fq2 sqrt)
static u64 EXP_P_MINUS_1_D2[6]; // (p-1)/2     (Fq2 sqrt aux / psi_y exponent)
static u64 EXP_P_MINUS_1_D3[6]; // (p-1)/3     (frobenius / psi_x exponent)
static u64 EXP_P_MINUS_1_D6[6]; // (p-1)/6     (frobenius w coefficient)

static void fp_inv(Fp &o, const Fp &a) { fp_pow(o, a, EXP_P_MINUS_2, 381); }

// sqrt in Fp (p = 3 mod 4): a^((p+1)/4); returns false if not a QR.
static bool fp_sqrt(Fp &o, const Fp &a) {
  Fp c, c2;
  fp_pow(c, a, EXP_P_PLUS_1_D4, 380);
  fp_sqr(c2, c);
  if (!fp_eq(c2, a)) return false;
  o = c;
  return true;
}

// ---------------------------------------------------------------------------
// Fp2 = Fp[u]/(u^2+1)
// ---------------------------------------------------------------------------

struct Fp2 {
  Fp c0, c1;
};

static Fp2 FP2_ZERO, FP2_ONE;

static inline void fp2_add(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_add(o.c0, a.c0, b.c0);
  fp_add(o.c1, a.c1, b.c1);
}
static inline void fp2_sub(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  fp_sub(o.c0, a.c0, b.c0);
  fp_sub(o.c1, a.c1, b.c1);
}
static inline void fp2_neg(Fp2 &o, const Fp2 &a) {
  fp_neg(o.c0, a.c0);
  fp_neg(o.c1, a.c1);
}
static inline void fp2_dbl(Fp2 &o, const Fp2 &a) { fp2_add(o, a, a); }

static void fp2_mul(Fp2 &o, const Fp2 &a, const Fp2 &b) {
  Fp t0, t1, s0, s1, m;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(s0, a.c0, a.c1);
  fp_add(s1, b.c0, b.c1);
  fp_mul(m, s0, s1);
  fp_sub(o.c0, t0, t1);
  fp_sub(m, m, t0);
  fp_sub(o.c1, m, t1);
}

static void fp2_sqr(Fp2 &o, const Fp2 &a) {
  Fp s, d, m;
  fp_add(s, a.c0, a.c1);
  fp_sub(d, a.c0, a.c1);
  fp_mul(m, a.c0, a.c1);
  fp_mul(o.c0, s, d);
  fp_dbl(o.c1, m);
}

static inline void fp2_conj(Fp2 &o, const Fp2 &a) {
  o.c0 = a.c0;
  fp_neg(o.c1, a.c1);
}

// multiply by the Fq6 non-residue (u+1)
static inline void fp2_mul_nr(Fp2 &o, const Fp2 &a) {
  Fp t0, t1;
  fp_sub(t0, a.c0, a.c1);
  fp_add(t1, a.c0, a.c1);
  o.c0 = t0;
  o.c1 = t1;
}

static inline void fp2_mul_fp(Fp2 &o, const Fp2 &a, const Fp &s) {
  fp_mul(o.c0, a.c0, s);
  fp_mul(o.c1, a.c1, s);
}

static void fp2_inv(Fp2 &o, const Fp2 &a) {
  Fp t0, t1, t;
  fp_sqr(t0, a.c0);
  fp_sqr(t1, a.c1);
  fp_add(t, t0, t1);
  fp_inv(t, t);
  fp_mul(o.c0, a.c0, t);
  fp_mul(t, a.c1, t);
  fp_neg(o.c1, t);
}

static inline bool fp2_is_zero(const Fp2 &a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool fp2_eq(const Fp2 &a, const Fp2 &b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

static void fp2_pow(Fp2 &o, const Fp2 &base, const u64 *e, int nbits) {
  Fp2 r = FP2_ONE;
  for (int i = nbits - 1; i >= 0; i--) {
    fp2_sqr(r, r);
    if ((e[i / 64] >> (i % 64)) & 1) fp2_mul(r, r, base);
  }
  o = r;
}

// sqrt in Fp2 (p = 3 mod 4 complex method; oracle fields.py:104-118).
static bool fp2_sqrt(Fp2 &o, const Fp2 &a) {
  if (fp2_is_zero(a)) {
    o = FP2_ZERO;
    return true;
  }
  Fp2 a1, x0, alpha, cand, chk;
  fp2_pow(a1, a, EXP_P_MINUS_3_D4, 379);
  fp2_mul(x0, a1, a);
  fp2_mul(alpha, a1, x0);
  Fp2 minus_one;
  fp2_neg(minus_one, FP2_ONE);
  if (fp2_eq(alpha, minus_one)) {
    // cand = u * x0
    fp_neg(cand.c0, x0.c1);
    cand.c1 = x0.c0;
  } else {
    Fp2 b;
    fp2_add(b, alpha, FP2_ONE);
    fp2_pow(b, b, EXP_P_MINUS_1_D2, 380);
    fp2_mul(cand, b, x0);
  }
  fp2_sqr(chk, cand);
  if (!fp2_eq(chk, a)) return false;
  o = cand;
  return true;
}

// RFC 9380 sgn0 for Fp2 (canonical form parity).
static int fp2_sgn0(const Fp2 &a) {
  Fp c0, c1;
  fp_from_mont(c0, a.c0);
  fp_from_mont(c1, a.c1);
  int s0 = (int)(c0.l[0] & 1);
  int z0 = fp_is_zero(c0) ? 1 : 0;
  int s1 = (int)(c1.l[0] & 1);
  return s0 | (z0 & s1);
}

// ---------------------------------------------------------------------------
// Fp6 = Fp2[v]/(v^3 - (u+1))
// ---------------------------------------------------------------------------

struct Fp6 {
  Fp2 c0, c1, c2;
};

static Fp6 FP6_ZERO, FP6_ONE;
static Fp2 FROB6_C1[6], FROB6_C2[6];  // power-k coefficients, k in 0..5
static Fp2 FROB12_C1[12];

static inline void fp6_add(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  fp2_add(o.c0, a.c0, b.c0);
  fp2_add(o.c1, a.c1, b.c1);
  fp2_add(o.c2, a.c2, b.c2);
}
static inline void fp6_sub(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  fp2_sub(o.c0, a.c0, b.c0);
  fp2_sub(o.c1, a.c1, b.c1);
  fp2_sub(o.c2, a.c2, b.c2);
}
static inline void fp6_neg(Fp6 &o, const Fp6 &a) {
  fp2_neg(o.c0, a.c0);
  fp2_neg(o.c1, a.c1);
  fp2_neg(o.c2, a.c2);
}

static void fp6_mul(Fp6 &o, const Fp6 &a, const Fp6 &b) {
  Fp2 t0, t1, t2, s0, s1, m, r0, r1, r2;
  fp2_mul(t0, a.c0, b.c0);
  fp2_mul(t1, a.c1, b.c1);
  fp2_mul(t2, a.c2, b.c2);
  // c0 = ((a1+a2)(b1+b2) - t1 - t2)*nr + t0
  fp2_add(s0, a.c1, a.c2);
  fp2_add(s1, b.c1, b.c2);
  fp2_mul(m, s0, s1);
  fp2_sub(m, m, t1);
  fp2_sub(m, m, t2);
  fp2_mul_nr(r0, m);
  fp2_add(r0, r0, t0);
  // c1 = (a0+a1)(b0+b1) - t0 - t1 + t2*nr
  fp2_add(s0, a.c0, a.c1);
  fp2_add(s1, b.c0, b.c1);
  fp2_mul(m, s0, s1);
  fp2_sub(m, m, t0);
  fp2_sub(m, m, t1);
  fp2_mul_nr(r1, t2);
  fp2_add(r1, r1, m);
  // c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
  fp2_add(s0, a.c0, a.c2);
  fp2_add(s1, b.c0, b.c2);
  fp2_mul(m, s0, s1);
  fp2_sub(m, m, t0);
  fp2_sub(m, m, t2);
  fp2_add(r2, m, t1);
  o.c0 = r0;
  o.c1 = r1;
  o.c2 = r2;
}

static inline void fp6_sqr(Fp6 &o, const Fp6 &a) { fp6_mul(o, a, a); }

// multiply by v (the Fq12 non-residue)
static inline void fp6_mul_nr(Fp6 &o, const Fp6 &a) {
  Fp2 t;
  fp2_mul_nr(t, a.c2);
  Fp2 c0 = a.c0, c1 = a.c1;
  o.c0 = t;
  o.c1 = c0;
  o.c2 = c1;
}

static inline void fp6_mul_fp2(Fp6 &o, const Fp6 &a, const Fp2 &s) {
  fp2_mul(o.c0, a.c0, s);
  fp2_mul(o.c1, a.c1, s);
  fp2_mul(o.c2, a.c2, s);
}

static void fp6_inv(Fp6 &o, const Fp6 &a) {
  Fp2 t0, t1, t2, m, d, dinv;
  // t0 = a0^2 - (a1 a2) nr
  fp2_sqr(t0, a.c0);
  fp2_mul(m, a.c1, a.c2);
  fp2_mul_nr(m, m);
  fp2_sub(t0, t0, m);
  // t1 = a2^2 nr - a0 a1
  fp2_sqr(t1, a.c2);
  fp2_mul_nr(t1, t1);
  fp2_mul(m, a.c0, a.c1);
  fp2_sub(t1, t1, m);
  // t2 = a1^2 - a0 a2
  fp2_sqr(t2, a.c1);
  fp2_mul(m, a.c0, a.c2);
  fp2_sub(t2, t2, m);
  // denom = a0 t0 + (a2 t1 + a1 t2) nr
  Fp2 x, y;
  fp2_mul(x, a.c2, t1);
  fp2_mul(y, a.c1, t2);
  fp2_add(x, x, y);
  fp2_mul_nr(x, x);
  fp2_mul(d, a.c0, t0);
  fp2_add(d, d, x);
  fp2_inv(dinv, d);
  fp2_mul(o.c0, t0, dinv);
  fp2_mul(o.c1, t1, dinv);
  fp2_mul(o.c2, t2, dinv);
}

static void fp6_frob1(Fp6 &o, const Fp6 &a) {
  fp2_conj(o.c0, a.c0);
  Fp2 t;
  fp2_conj(t, a.c1);
  fp2_mul(o.c1, t, FROB6_C1[1]);
  fp2_conj(t, a.c2);
  fp2_mul(o.c2, t, FROB6_C2[1]);
}

static inline bool fp6_is_zero(const Fp6 &a) {
  return fp2_is_zero(a.c0) && fp2_is_zero(a.c1) && fp2_is_zero(a.c2);
}
static inline bool fp6_eq(const Fp6 &a, const Fp6 &b) {
  return fp2_eq(a.c0, b.c0) && fp2_eq(a.c1, b.c1) && fp2_eq(a.c2, b.c2);
}

// ---------------------------------------------------------------------------
// Fp12 = Fp6[w]/(w^2 - v)
// ---------------------------------------------------------------------------

struct Fp12 {
  Fp6 c0, c1;
};

static Fp12 FP12_ONE;

static void fp12_mul(Fp12 &o, const Fp12 &a, const Fp12 &b) {
  Fp6 t0, t1, s0, s1, m;
  fp6_mul(t0, a.c0, b.c0);
  fp6_mul(t1, a.c1, b.c1);
  fp6_add(s0, a.c0, a.c1);
  fp6_add(s1, b.c0, b.c1);
  fp6_mul(m, s0, s1);
  Fp6 nr;
  fp6_mul_nr(nr, t1);
  fp6_add(o.c0, t0, nr);
  fp6_sub(m, m, t0);
  fp6_sub(o.c1, m, t1);
}

static void fp12_sqr(Fp12 &o, const Fp12 &a) {
  // c0 = (a0+a1)(a0 + a1 nr) - t0 - t0 nr ; c1 = 2 t0   with t0 = a0 a1
  Fp6 t0, s0, s1, m, nr;
  fp6_mul(t0, a.c0, a.c1);
  fp6_add(s0, a.c0, a.c1);
  fp6_mul_nr(nr, a.c1);
  fp6_add(s1, a.c0, nr);
  fp6_mul(m, s0, s1);
  fp6_sub(m, m, t0);
  fp6_mul_nr(nr, t0);
  fp6_sub(o.c0, m, nr);
  fp6_add(o.c1, t0, t0);
}

static inline void fp12_conj(Fp12 &o, const Fp12 &a) {
  o.c0 = a.c0;
  fp6_neg(o.c1, a.c1);
}

static void fp12_inv(Fp12 &o, const Fp12 &a) {
  Fp6 t0, t1, t;
  fp6_sqr(t0, a.c0);
  fp6_sqr(t1, a.c1);
  fp6_mul_nr(t1, t1);
  fp6_sub(t, t0, t1);
  fp6_inv(t, t);
  fp6_mul(o.c0, a.c0, t);
  fp6_mul(t, a.c1, t);
  fp6_neg(o.c1, t);
}

static void fp12_frob1(Fp12 &o, const Fp12 &a) {
  fp6_frob1(o.c0, a.c0);
  Fp6 t;
  fp6_frob1(t, a.c1);
  fp2_mul(o.c1.c0, t.c0, FROB12_C1[1]);
  fp2_mul(o.c1.c1, t.c1, FROB12_C1[1]);
  fp2_mul(o.c1.c2, t.c2, FROB12_C1[1]);
}

static void fp12_frob(Fp12 &o, const Fp12 &a, int power) {
  Fp12 r = a;
  for (int i = 0; i < power % 12; i++) fp12_frob1(r, r);
  o = r;
}

static inline bool fp12_is_one(const Fp12 &a) {
  return fp6_eq(a.c0, FP6_ONE) && fp6_is_zero(a.c1);
}

// Granger-Scott cyclotomic squaring (oracle fields.py:290-312).
static void fp12_cyclotomic_sqr(Fp12 &o, const Fp12 &a) {
  const Fp2 &z0 = a.c0.c0, &z4 = a.c0.c1, &z3 = a.c0.c2;
  const Fp2 &z2 = a.c1.c0, &z1 = a.c1.c1, &z5 = a.c1.c2;
  Fp2 t0, t1, t2, t3, t4, t5, s, q;

  // fq4_square(a, b): (b^2 nr + a^2, (a+b)^2 - a^2 - b^2)
#define FQ4_SQUARE(ra, rb, xa, xb)     \
  {                                    \
    Fp2 pa, pb, ps;                    \
    fp2_sqr(pa, xa);                   \
    fp2_sqr(pb, xb);                   \
    fp2_add(ps, xa, xb);               \
    fp2_sqr(ps, ps);                   \
    fp2_mul_nr(ra, pb);                \
    fp2_add(ra, ra, pa);               \
    fp2_sub(ps, ps, pa);               \
    fp2_sub(rb, ps, pb);               \
  }

  FQ4_SQUARE(t0, t1, z0, z1);
  FQ4_SQUARE(t2, t3, z2, z3);
  FQ4_SQUARE(t4, t5, z4, z5);
#undef FQ4_SQUARE

  Fp2 r0, r1, r2, r3, r4, r5;
  // z0' = (t0 - z0)*2 + t0
  fp2_sub(s, t0, z0);
  fp2_dbl(s, s);
  fp2_add(r0, s, t0);
  // z1' = (t1 + z1)*2 + t1
  fp2_add(s, t1, z1);
  fp2_dbl(s, s);
  fp2_add(r1, s, t1);
  // z2' = (t5 nr + z2)*2 + t5 nr
  fp2_mul_nr(q, t5);
  fp2_add(s, q, z2);
  fp2_dbl(s, s);
  fp2_add(r2, s, q);
  // z3' = (t4 - z3)*2 + t4
  fp2_sub(s, t4, z3);
  fp2_dbl(s, s);
  fp2_add(r3, s, t4);
  // z4' = (t2 - z4)*2 + t2
  fp2_sub(s, t2, z4);
  fp2_dbl(s, s);
  fp2_add(r4, s, t2);
  // z5' = (t3 + z5)*2 + t3
  fp2_add(s, t3, z5);
  fp2_dbl(s, s);
  fp2_add(r5, s, t3);

  o.c0.c0 = r0;
  o.c0.c1 = r4;
  o.c0.c2 = r3;
  o.c1.c0 = r2;
  o.c1.c1 = r1;
  o.c1.c2 = r5;
}

// f^|x| for cyclotomic f (MSB-first over the 64-bit |x|).
static void fp12_cyc_exp_abs_x(Fp12 &o, const Fp12 &f) {
  Fp12 r = f;  // MSB consumed
  for (int i = 62; i >= 0; i--) {
    fp12_cyclotomic_sqr(r, r);
    if ((BLS_X_ABS >> i) & 1) fp12_mul(r, r, f);
  }
  o = r;
}

// ---------------------------------------------------------------------------
// Elliptic curves: G1 over Fp (y^2 = x^3 + 4), G2 over Fp2 (y^2 = x^3 + 4(u+1))
// Jacobian coordinates; generic over the field via templates.
// ---------------------------------------------------------------------------

template <class F>
struct FieldOps;

template <>
struct FieldOps<Fp> {
  static void add(Fp &o, const Fp &a, const Fp &b) { fp_add(o, a, b); }
  static void sub(Fp &o, const Fp &a, const Fp &b) { fp_sub(o, a, b); }
  static void neg(Fp &o, const Fp &a) { fp_neg(o, a); }
  static void mul(Fp &o, const Fp &a, const Fp &b) { fp_mul(o, a, b); }
  static void sqr(Fp &o, const Fp &a) { fp_sqr(o, a); }
  static void inv(Fp &o, const Fp &a) { fp_inv(o, a); }
  static bool is_zero(const Fp &a) { return fp_is_zero(a); }
  static bool eq(const Fp &a, const Fp &b) { return fp_eq(a, b); }
  static const Fp &one() { return FP_ONE; }
  static const Fp &zero() { return FP_ZERO; }
};

static Fp2 FP2_ZERO_C, FP2_ONE_C;  // aliases stable for template refs

template <>
struct FieldOps<Fp2> {
  static void add(Fp2 &o, const Fp2 &a, const Fp2 &b) { fp2_add(o, a, b); }
  static void sub(Fp2 &o, const Fp2 &a, const Fp2 &b) { fp2_sub(o, a, b); }
  static void neg(Fp2 &o, const Fp2 &a) { fp2_neg(o, a); }
  static void mul(Fp2 &o, const Fp2 &a, const Fp2 &b) { fp2_mul(o, a, b); }
  static void sqr(Fp2 &o, const Fp2 &a) { fp2_sqr(o, a); }
  static void inv(Fp2 &o, const Fp2 &a) { fp2_inv(o, a); }
  static bool is_zero(const Fp2 &a) { return fp2_is_zero(a); }
  static bool eq(const Fp2 &a, const Fp2 &b) { return fp2_eq(a, b); }
  static const Fp2 &one() { return FP2_ONE; }
  static const Fp2 &zero() { return FP2_ZERO; }
};

template <class F>
struct Jac {
  F X, Y, Z;  // Z == 0 -> infinity
};

template <class F>
struct Aff {
  F x, y;
  bool inf;
};

template <class F>
static void jac_set_inf(Jac<F> &p) {
  p.X = FieldOps<F>::one();
  p.Y = FieldOps<F>::one();
  p.Z = FieldOps<F>::zero();
}

template <class F>
static bool jac_is_inf(const Jac<F> &p) {
  return FieldOps<F>::is_zero(p.Z);
}

template <class F>
static void jac_from_aff(Jac<F> &o, const Aff<F> &a) {
  if (a.inf) {
    jac_set_inf(o);
    return;
  }
  o.X = a.x;
  o.Y = a.y;
  o.Z = FieldOps<F>::one();
}

template <class F>
static void jac_dbl(Jac<F> &o, const Jac<F> &p) {
  typedef FieldOps<F> O;
  if (jac_is_inf(p) || O::is_zero(p.Y)) {
    jac_set_inf(o);
    return;
  }
  F A, B, C, D, E, Fv, t, X3, Y3, Z3;
  O::sqr(A, p.X);
  O::sqr(B, p.Y);
  O::sqr(C, B);
  // D = 2((X+B)^2 - A - C)
  O::add(t, p.X, B);
  O::sqr(t, t);
  O::sub(t, t, A);
  O::sub(t, t, C);
  O::add(D, t, t);
  // E = 3A
  O::add(E, A, A);
  O::add(E, E, A);
  O::sqr(Fv, E);
  // X3 = F - 2D
  O::sub(X3, Fv, D);
  O::sub(X3, X3, D);
  // Y3 = E(D - X3) - 8C
  O::sub(t, D, X3);
  O::mul(Y3, E, t);
  O::add(t, C, C);
  O::add(t, t, t);
  O::add(t, t, t);
  O::sub(Y3, Y3, t);
  // Z3 = 2YZ
  O::mul(t, p.Y, p.Z);
  O::add(Z3, t, t);
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
}

template <class F>
static void jac_add(Jac<F> &o, const Jac<F> &p, const Jac<F> &q) {
  typedef FieldOps<F> O;
  if (jac_is_inf(p)) {
    o = q;
    return;
  }
  if (jac_is_inf(q)) {
    o = p;
    return;
  }
  F Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  O::sqr(Z1Z1, p.Z);
  O::sqr(Z2Z2, q.Z);
  O::mul(U1, p.X, Z2Z2);
  O::mul(U2, q.X, Z1Z1);
  O::mul(t, q.Z, Z2Z2);
  O::mul(S1, p.Y, t);
  O::mul(t, p.Z, Z1Z1);
  O::mul(S2, q.Y, t);
  F H, R;
  O::sub(H, U2, U1);
  O::sub(R, S2, S1);
  if (O::is_zero(H)) {
    if (O::is_zero(R)) {
      jac_dbl(o, p);
      return;
    }
    jac_set_inf(o);
    return;
  }
  F HH, HHH, V, X3, Y3, Z3;
  O::sqr(HH, H);
  O::mul(HHH, HH, H);
  O::mul(V, U1, HH);
  // X3 = R^2 - HHH - 2V
  O::sqr(X3, R);
  O::sub(X3, X3, HHH);
  O::sub(X3, X3, V);
  O::sub(X3, X3, V);
  // Y3 = R(V - X3) - S1*HHH
  O::sub(t, V, X3);
  O::mul(Y3, R, t);
  O::mul(t, S1, HHH);
  O::sub(Y3, Y3, t);
  // Z3 = Z1 Z2 H
  O::mul(t, p.Z, q.Z);
  O::mul(Z3, t, H);
  o.X = X3;
  o.Y = Y3;
  o.Z = Z3;
}

template <class F>
static void jac_neg(Jac<F> &o, const Jac<F> &p) {
  o = p;
  FieldOps<F>::neg(o.Y, p.Y);
}

// MSB-first double-and-add: o = [e] p, exponent little-endian limbs.
template <class F>
static void jac_mul(Jac<F> &o, const Jac<F> &p, const u64 *e, int nbits) {
  Jac<F> r;
  jac_set_inf(r);
  for (int i = nbits - 1; i >= 0; i--) {
    jac_dbl(r, r);
    if ((e[i / 64] >> (i % 64)) & 1) jac_add(r, r, p);
  }
  o = r;
}

template <class F>
static void jac_to_aff(Aff<F> &o, const Jac<F> &p) {
  typedef FieldOps<F> O;
  if (jac_is_inf(p)) {
    o.inf = true;
    o.x = O::zero();
    o.y = O::zero();
    return;
  }
  F zi, zi2, zi3;
  O::inv(zi, p.Z);
  O::sqr(zi2, zi);
  O::mul(zi3, zi2, zi);
  O::mul(o.x, p.X, zi2);
  O::mul(o.y, p.Y, zi3);
  o.inf = false;
}

static Fp G1_B;   // 4 (Montgomery)
static Fp2 G2_B;  // 4(u+1)
static Aff<Fp> G1_GEN;
static Aff<Fp2> G2_GEN;

template <class F>
static bool on_curve(const Aff<F> &p, const F &b) {
  typedef FieldOps<F> O;
  if (p.inf) return true;
  F y2, x3;
  O::sqr(y2, p.y);
  O::sqr(x3, p.x);
  O::mul(x3, x3, p.x);
  O::add(x3, x3, b);
  return O::eq(y2, x3);
}

// psi endomorphism on E2 (untwist-frobenius-twist):
// psi(x, y) = (conj(x) * PSI_CX, conj(y) * PSI_CY), with
// PSI_CX = (u+1)^-((p-1)/3), PSI_CY = (u+1)^-((p-1)/2) (computed at init).
static Fp2 PSI_CX, PSI_CY;

static void g2_psi(Aff<Fp2> &o, const Aff<Fp2> &p) {
  if (p.inf) {
    o = p;
    return;
  }
  Fp2 t;
  fp2_conj(t, p.x);
  fp2_mul(o.x, t, PSI_CX);
  fp2_conj(t, p.y);
  fp2_mul(o.y, t, PSI_CY);
  o.inf = false;
}

// G2 subgroup check via the psi endomorphism: P in subgroup iff psi(P) == [x]P
// (x negative: [x]P = -[|x|]P). Same check as the device kernel g2.subgroup_check.
static bool g2_in_subgroup(const Aff<Fp2> &p) {
  if (p.inf) return true;
  if (!on_curve(p, G2_B)) return false;
  Jac<Fp2> j, xp;
  jac_from_aff(j, p);
  u64 xabs[1] = {BLS_X_ABS};
  jac_mul(xp, j, xabs, 64);
  jac_neg(xp, xp);  // [x]P with x < 0
  Aff<Fp2> lhs, rhs;
  jac_to_aff(rhs, xp);
  g2_psi(lhs, p);
  if (lhs.inf || rhs.inf) return lhs.inf && rhs.inf;
  return fp2_eq(lhs.x, rhs.x) && fp2_eq(lhs.y, rhs.y);
}

// G1 subgroup check: [r]P == inf (pubkeys are validated once per cache insert,
// mirroring validator_pubkey_cache.rs, so this is off the hot path).
static bool g1_in_subgroup(const Aff<Fp> &p) {
  if (p.inf) return true;
  if (!on_curve(p, G1_B)) return false;
  Jac<Fp> j, rp;
  jac_from_aff(j, p);
  jac_mul(rp, j, R_LIMBS, 255);
  return jac_is_inf(rp);
}

// ---------------------------------------------------------------------------
// Pairing: CLN homogeneous-projective Miller loop on the M-twist with sparse
// mul_by_014 folding (port of lighthouse_tpu/ops/bls/pairing.py).
// ---------------------------------------------------------------------------

// f *= c0 + c1 v + c4 v w  (Fq2 coefficients at Fq6-slot positions 0, 1, 4)
static void fp12_mul_by_014(Fp12 &f, const Fp2 &c0, const Fp2 &c1,
                            const Fp2 &c4) {
  // t0 = a0 * (c0, c1, 0)
  Fp6 t0, t1, t2;
  {
    const Fp6 &x = f.c0;
    Fp2 m00, m11, mx, m20, m21, s0, s1;
    fp2_mul(m00, x.c0, c0);
    fp2_mul(m11, x.c1, c1);
    fp2_add(s0, x.c0, x.c1);
    fp2_add(s1, c0, c1);
    fp2_mul(mx, s0, s1);
    fp2_mul(m20, x.c2, c0);
    fp2_mul(m21, x.c2, c1);
    fp2_mul_nr(t0.c0, m21);
    fp2_add(t0.c0, t0.c0, m00);
    fp2_sub(t0.c1, mx, m00);
    fp2_sub(t0.c1, t0.c1, m11);
    fp2_add(t0.c2, m11, m20);
  }
  // t1 = a1 * (0, c4, 0) = (nr(x2 c4), x0 c4, x1 c4)
  {
    const Fp6 &x = f.c1;
    Fp2 n0, n1, n2;
    fp2_mul(n0, x.c0, c4);
    fp2_mul(n1, x.c1, c4);
    fp2_mul(n2, x.c2, c4);
    fp2_mul_nr(t1.c0, n2);
    t1.c1 = n0;
    t1.c2 = n1;
  }
  // t2 = (a0 + a1) * (c0, c1 + c4, 0)
  {
    Fp6 s;
    fp6_add(s, f.c0, f.c1);
    Fp2 c14;
    fp2_add(c14, c1, c4);
    Fp2 m00, m11, mx, m20, m21, s0, s1;
    fp2_mul(m00, s.c0, c0);
    fp2_mul(m11, s.c1, c14);
    fp2_add(s0, s.c0, s.c1);
    fp2_add(s1, c0, c14);
    fp2_mul(mx, s0, s1);
    fp2_mul(m20, s.c2, c0);
    fp2_mul(m21, s.c2, c14);
    fp2_mul_nr(t2.c0, m21);
    fp2_add(t2.c0, t2.c0, m00);
    fp2_sub(t2.c1, mx, m00);
    fp2_sub(t2.c1, t2.c1, m11);
    fp2_add(t2.c2, m11, m20);
  }
  // out0 = t0 + nr(t1); out1 = t2 - t0 - t1
  Fp6 nr1;
  fp6_mul_nr(nr1, t1);
  fp6_add(f.c0, t0, nr1);
  fp6_sub(f.c1, t2, t0);
  fp6_sub(f.c1, f.c1, t1);
}

struct MillerState {
  Fp2 X, Y, Z;  // homogeneous projective on the twist
};

// Doubling step (ops/bls/pairing.py:_dbl_step): returns line (c0, c1, c2).
static void miller_dbl_step(MillerState &r, Fp2 &lc0, Fp2 &lc1, Fp2 &lc2) {
  Fp2 aj, b, c, j, s, h, e, f3, t, u;
  fp2_mul(aj, r.X, r.Y);
  fp2_sqr(b, r.Y);
  fp2_sqr(c, r.Z);
  fp2_sqr(j, r.X);
  fp2_add(s, r.Y, r.Z);
  fp2_sqr(s, s);
  // h = s - b - c
  fp2_sub(h, s, b);
  fp2_sub(h, h, c);
  // e = 12 nr(c)
  fp2_mul_nr(e, c);
  fp2_add(t, e, e);       // 2
  fp2_add(t, t, t);       // 4
  fp2_add(u, t, t);       // 8
  fp2_add(e, u, t);       // 12
  // f3 = 3e
  fp2_add(f3, e, e);
  fp2_add(f3, f3, e);
  // X3 = 2 a' (b - f3)
  Fp2 bmf, m0;
  fp2_sub(bmf, b, f3);
  fp2_mul(m0, aj, bmf);
  fp2_add(r.X, m0, m0);
  // Y3 = (b + f3)^2 - 12 e^2
  Fp2 bpf, m1, m2;
  fp2_add(bpf, b, f3);
  fp2_sqr(m1, bpf);
  fp2_sqr(m2, e);
  fp2_add(t, m2, m2);
  fp2_add(t, t, t);
  fp2_add(u, t, t);
  fp2_add(t, u, t);  // 12 m2
  fp2_sub(r.Y, m1, t);
  // Z3 = 4 b h
  Fp2 m3;
  fp2_mul(m3, b, h);
  fp2_add(m3, m3, m3);
  fp2_add(r.Z, m3, m3);
  // line = (e - b, 3j, -h)
  fp2_sub(lc0, e, b);
  fp2_add(lc1, j, j);
  fp2_add(lc1, lc1, j);
  fp2_neg(lc2, h);
}

// Mixed addition step (ops/bls/pairing.py:_add_step).
static void miller_add_step(MillerState &r, const Fp2 &qx, const Fp2 &qy,
                            Fp2 &lc0, Fp2 &lc1, Fp2 &lc2) {
  Fp2 theta, lam, c, d, e, f, g, h, t;
  fp2_mul(t, qy, r.Z);
  fp2_sub(theta, r.Y, t);
  fp2_mul(t, qx, r.Z);
  fp2_sub(lam, r.X, t);
  fp2_sqr(c, theta);
  fp2_sqr(d, lam);
  fp2_mul(e, lam, d);
  fp2_mul(f, r.Z, c);
  fp2_mul(g, r.X, d);
  // h = e + f - 2g
  fp2_add(h, e, f);
  fp2_sub(h, h, g);
  fp2_sub(h, h, g);
  // X3 = lam h; Y3 = theta (g - h) - e Y; Z3 = Z e
  Fp2 gmh, t1, t2;
  fp2_sub(gmh, g, h);
  fp2_mul(t1, theta, gmh);
  fp2_mul(t2, e, r.Y);
  fp2_mul(r.X, lam, h);
  fp2_sub(r.Y, t1, t2);
  fp2_mul(r.Z, r.Z, e);
  // line = (theta qx - lam qy, -theta, lam)
  fp2_mul(t1, theta, qx);
  fp2_mul(t2, lam, qy);
  fp2_sub(lc0, t1, t2);
  fp2_neg(lc1, theta);
  lc2 = lam;
}

// Fold a line into f: f *= (c0, c1 * px, c2 * py) at positions (0, 1, 4).
static inline void miller_ell(Fp12 &f, const Fp2 &lc0, const Fp2 &lc1,
                              const Fp2 &lc2, const Fp &px, const Fp &py) {
  Fp2 c1, c4;
  fp2_mul_fp(c1, lc1, px);
  fp2_mul_fp(c4, lc2, py);
  fp12_mul_by_014(f, lc0, c1, c4);
}

// Miller loop accumulating into f (callers pass f = 1 and chain for batches).
// P affine in G1 (Montgomery), Q affine on the twist. Infinity on either side
// contributes the identity (skipped), matching oracle miller_loop.
static void miller_loop_acc(Fp12 &f, const Aff<Fp> &p, const Aff<Fp2> &q) {
  if (p.inf || q.inf) return;
  MillerState r;
  r.X = q.x;
  r.Y = q.y;
  r.Z = FP2_ONE;
  Fp2 lc0, lc1, lc2;
  Fp12 acc = FP12_ONE;
  for (int i = 62; i >= 0; i--) {
    fp12_sqr(acc, acc);
    miller_dbl_step(r, lc0, lc1, lc2);
    miller_ell(acc, lc0, lc1, lc2, p.x, p.y);
    if ((BLS_X_ABS >> i) & 1) {
      miller_add_step(r, q.x, q.y, lc0, lc1, lc2);
      miller_ell(acc, lc0, lc1, lc2, p.x, p.y);
    }
  }
  Fp12 conj;
  fp12_conj(conj, acc);  // x < 0
  fp12_mul(f, f, conj);
}

// Final exponentiation: easy part then hard part f^(3(p^4-p^2+1)/r) via the
// x-addition chain 3λ = (x-1)^2 (x+p) (x^2+p^2-1) + 3 (oracle pairing.py:154).
static void final_exponentiation(Fp12 &o, const Fp12 &fin) {
  Fp12 f, t, inv;
  // easy: f^(p^6-1), then ^(p^2+1)
  fp12_conj(t, fin);
  fp12_inv(inv, fin);
  fp12_mul(f, t, inv);
  fp12_frob(t, f, 2);
  fp12_mul(f, t, f);

#define EXP_X_MINUS_1(out, g)     \
  {                               \
    Fp12 gx;                      \
    fp12_cyc_exp_abs_x(gx, g);    \
    fp12_mul(gx, gx, g);          \
    fp12_conj(out, gx);           \
  }

  Fp12 m1, m2, m2x, m3, m3x, m3x2, m4;
  EXP_X_MINUS_1(m1, f);
  EXP_X_MINUS_1(m2, m1);
#undef EXP_X_MINUS_1
  fp12_cyc_exp_abs_x(m2x, m2);
  fp12_conj(m2x, m2x);
  fp12_frob(t, m2, 1);
  fp12_mul(m3, m2x, t);
  fp12_cyc_exp_abs_x(m3x, m3);
  fp12_conj(m3x, m3x);
  fp12_cyc_exp_abs_x(m3x2, m3x);
  fp12_conj(m3x2, m3x2);
  fp12_frob(t, m3, 2);
  fp12_mul(m4, m3x2, t);
  fp12_conj(t, m3);
  fp12_mul(m4, m4, t);
  // * f^3
  fp12_mul(t, f, f);
  fp12_mul(t, t, f);
  fp12_mul(o, m4, t);
}

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4)
// ---------------------------------------------------------------------------

static const u32 SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

struct Sha256 {
  u32 h[8];
  u8 buf[64];
  u64 len;
  int fill;
};

static inline u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }

static void sha_init(Sha256 &s) {
  static const u32 H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                            0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  memcpy(s.h, H0, sizeof(H0));
  s.len = 0;
  s.fill = 0;
}

static void sha_block(Sha256 &s, const u8 *p) {
  u32 w[64];
  for (int i = 0; i < 16; i++)
    w[i] = ((u32)p[4 * i] << 24) | ((u32)p[4 * i + 1] << 16) |
           ((u32)p[4 * i + 2] << 8) | p[4 * i + 3];
  for (int i = 16; i < 64; i++) {
    u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  u32 a = s.h[0], b = s.h[1], c = s.h[2], d = s.h[3];
  u32 e = s.h[4], f = s.h[5], g = s.h[6], hh = s.h[7];
  for (int i = 0; i < 64; i++) {
    u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    u32 ch = (e & f) ^ (~e & g);
    u32 t1 = hh + S1 + ch + SHA_K[i] + w[i];
    u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    u32 mj = (a & b) ^ (a & c) ^ (b & c);
    u32 t2 = S0 + mj;
    hh = g;
    g = f;
    f = e;
    e = d + t1;
    d = c;
    c = b;
    b = a;
    a = t1 + t2;
  }
  s.h[0] += a;
  s.h[1] += b;
  s.h[2] += c;
  s.h[3] += d;
  s.h[4] += e;
  s.h[5] += f;
  s.h[6] += g;
  s.h[7] += hh;
}

static void sha_update(Sha256 &s, const u8 *p, u64 n) {
  s.len += n;
  while (n) {
    if (s.fill == 0 && n >= 64) {
      sha_block(s, p);
      p += 64;
      n -= 64;
      continue;
    }
    u64 take = 64 - s.fill;
    if (take > n) take = n;
    memcpy(s.buf + s.fill, p, take);
    s.fill += (int)take;
    p += take;
    n -= take;
    if (s.fill == 64) {
      sha_block(s, s.buf);
      s.fill = 0;
    }
  }
}

static void sha_final(Sha256 &s, u8 out[32]) {
  u64 bits = s.len * 8;
  u8 pad = 0x80;
  sha_update(s, &pad, 1);
  u8 z = 0;
  while (s.fill != 56) sha_update(s, &z, 1);
  u8 lb[8];
  for (int i = 0; i < 8; i++) lb[i] = (u8)(bits >> (56 - 8 * i));
  sha_update(s, lb, 8);
  for (int i = 0; i < 8; i++) {
    out[4 * i] = (u8)(s.h[i] >> 24);
    out[4 * i + 1] = (u8)(s.h[i] >> 16);
    out[4 * i + 2] = (u8)(s.h[i] >> 8);
    out[4 * i + 3] = (u8)(s.h[i]);
  }
}

static void sha256(const u8 *p, u64 n, u8 out[32]) {
  Sha256 s;
  sha_init(s);
  sha_update(s, p, n);
  sha_final(s, out);
}

// ---------------------------------------------------------------------------
// Byte conversion + big-int helpers
// ---------------------------------------------------------------------------

static u64 HALF_P[6];  // (p-1)/2, raw

// raw o = 2*o mod p (o < p)
static void raw_shl1_mod_p(u64 o[6]) {
  u128 c = 0;
  for (int i = 0; i < 6; i++) {
    c += ((u128)o[i]) << 1;
    o[i] = (u64)c;
    c >>= 64;
  }
  if (c || fp_cmp_raw(o, P_LIMBS) >= 0) {
    u128 br = 0;
    for (int i = 0; i < 6; i++) {
      u128 d = (u128)o[i] - P_LIMBS[i] - (u64)br;
      o[i] = (u64)d;
      br = (d >> 64) ? 1 : 0;
    }
  }
}

// Interpret n big-endian bytes mod p -> Montgomery form.
static void fp_from_be_mod(Fp &o, const u8 *be, int n) {
  u64 r[6] = {0, 0, 0, 0, 0, 0};
  for (int i = 0; i < n; i++) {
    for (int k = 0; k < 8; k++) raw_shl1_mod_p(r);
    // r += be[i] (no overflow: r < p, byte < 256, p has slack)
    u128 c = be[i];
    for (int j = 0; j < 6 && c; j++) {
      c += r[j];
      r[j] = (u64)c;
      c >>= 64;
    }
    if (fp_cmp_raw(r, P_LIMBS) >= 0) {
      u128 br = 0;
      for (int j = 0; j < 6; j++) {
        u128 d = (u128)r[j] - P_LIMBS[j] - (u64)br;
        r[j] = (u64)d;
        br = (d >> 64) ? 1 : 0;
      }
    }
  }
  Fp raw;
  memcpy(raw.l, r, 48);
  fp_to_mont(o, raw);
}

// Strict 48-byte big-endian parse (must be < p) -> Montgomery. False if >= p.
static bool fp_from_be48(Fp &o, const u8 *be) {
  u64 r[6];
  for (int i = 0; i < 6; i++) {
    u64 v = 0;
    for (int k = 0; k < 8; k++) v = (v << 8) | be[8 * i + k];
    r[5 - i] = v;
  }
  if (fp_cmp_raw(r, P_LIMBS) >= 0) return false;
  Fp raw;
  memcpy(raw.l, r, 48);
  fp_to_mont(o, raw);
  return true;
}

static void fp_to_be48(const Fp &a, u8 *be) {
  Fp c;
  fp_from_mont(c, a);
  for (int i = 0; i < 6; i++) {
    u64 v = c.l[5 - i];
    for (int k = 0; k < 8; k++) be[8 * i + k] = (u8)(v >> (56 - 8 * k));
  }
}

// canonical(a) > (p-1)/2 ?
static bool fp_gt_half(const Fp &a) {
  Fp c;
  fp_from_mont(c, a);
  return fp_cmp_raw(c.l, HALF_P) > 0;
}

// Parse a big-endian hex string (no 0x) into Montgomery form.
static void fp_from_hex(Fp &o, const char *hex) {
  u8 be[48] = {0};
  int n = (int)strlen(hex);
  int nb = (n + 1) / 2;
  int off = 48 - nb;
  int i = 0;
  int hi = n & 1;  // odd length: first nibble is a lone hi nibble
  for (int b = 0; b < nb; b++) {
    u8 v = 0;
    for (int k = (b == 0 && hi) ? 1 : 0; k < 2; k++) {
      char ch = hex[i++];
      u8 d = (ch >= '0' && ch <= '9')   ? ch - '0'
             : (ch >= 'a' && ch <= 'f') ? ch - 'a' + 10
                                        : ch - 'A' + 10;
      v = (u8)((v << 4) | d);
    }
    be[off + b] = v;
  }
  bool ok = fp_from_be48(o, be);
  (void)ok;
}

// 256-bit big-endian bytes mod r (scalar order) -> 4 limbs little-endian.
static void scalar_from_be32_mod_r(u64 out[4], const u8 *be) {
  u64 t[4] = {0, 0, 0, 0};
  for (int i = 0; i < 32; i++) {
    for (int k = 0; k < 8; k++) {
      // t = 2t mod r
      u128 c = 0;
      for (int j = 0; j < 4; j++) {
        c += ((u128)t[j]) << 1;
        t[j] = (u64)c;
        c >>= 64;
      }
      bool ge = (bool)c;
      if (!ge) {
        ge = true;
        for (int j = 3; j >= 0; j--) {
          if (t[j] < R_LIMBS[j]) {
            ge = false;
            break;
          }
          if (t[j] > R_LIMBS[j]) break;
        }
      }
      if (ge) {
        u128 br = 0;
        for (int j = 0; j < 4; j++) {
          u128 d = (u128)t[j] - R_LIMBS[j] - (u64)br;
          t[j] = (u64)d;
          br = (d >> 64) ? 1 : 0;
        }
      }
    }
    u128 c = be[i];
    for (int j = 0; j < 4 && c; j++) {
      c += t[j];
      t[j] = (u64)c;
      c >>= 64;
    }
  }
  memcpy(out, t, 32);
}

// ---------------------------------------------------------------------------
// Point serialization (ZCash flags; oracle curves.py:241-318)
// ---------------------------------------------------------------------------

static bool g1_decompress(Aff<Fp> &o, const u8 *in) {
  int c_flag = (in[0] >> 7) & 1, i_flag = (in[0] >> 6) & 1,
      s_flag = (in[0] >> 5) & 1;
  if (!c_flag) return false;
  u8 be[48];
  memcpy(be, in, 48);
  be[0] &= 0x1f;
  if (i_flag) {
    for (int i = 0; i < 48; i++)
      if (be[i]) return false;
    if (s_flag) return false;
    o.inf = true;
    o.x = FP_ZERO;
    o.y = FP_ZERO;
    return true;
  }
  if (!fp_from_be48(o.x, be)) return false;
  Fp rhs;
  fp_sqr(rhs, o.x);
  fp_mul(rhs, rhs, o.x);
  fp_add(rhs, rhs, G1_B);
  if (!fp_sqrt(o.y, rhs)) return false;
  if (fp_gt_half(o.y) != (bool)s_flag) fp_neg(o.y, o.y);
  o.inf = false;
  return true;
}

static void g1_compress(const Aff<Fp> &p, u8 *out) {
  if (p.inf) {
    memset(out, 0, 48);
    out[0] = 0xc0;
    return;
  }
  fp_to_be48(p.x, out);
  out[0] |= 0x80 | (fp_gt_half(p.y) ? 0x20 : 0);
}

static bool fp2_gt_half_lex(const Fp2 &y) {
  if (!fp_is_zero(y.c1)) return fp_gt_half(y.c1);
  return fp_gt_half(y.c0);
}

static bool g2_decompress(Aff<Fp2> &o, const u8 *in) {
  int c_flag = (in[0] >> 7) & 1, i_flag = (in[0] >> 6) & 1,
      s_flag = (in[0] >> 5) & 1;
  if (!c_flag) return false;
  u8 be[96];
  memcpy(be, in, 96);
  be[0] &= 0x1f;
  if (i_flag) {
    for (int i = 0; i < 96; i++)
      if (be[i]) return false;
    if (s_flag) return false;
    o.inf = true;
    o.x = FP2_ZERO;
    o.y = FP2_ZERO;
    return true;
  }
  // layout: x.c1 first, then x.c0
  if (!fp_from_be48(o.x.c1, be)) return false;
  if (!fp_from_be48(o.x.c0, be + 48)) return false;
  Fp2 rhs;
  fp2_sqr(rhs, o.x);
  fp2_mul(rhs, rhs, o.x);
  fp2_add(rhs, rhs, G2_B);
  if (!fp2_sqrt(o.y, rhs)) return false;
  if (fp2_gt_half_lex(o.y) != (bool)s_flag) fp2_neg(o.y, o.y);
  o.inf = false;
  return true;
}

static void g2_compress(const Aff<Fp2> &p, u8 *out) {
  if (p.inf) {
    memset(out, 0, 96);
    out[0] = 0xc0;
    return;
  }
  fp_to_be48(p.x.c1, out);
  fp_to_be48(p.x.c0, out + 48);
  out[0] |= 0x80 | (fp2_gt_half_lex(p.y) ? 0x20 : 0);
}

// ---------------------------------------------------------------------------
// Hash-to-curve G2: BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380; port of
// lighthouse_tpu/ops/bls_oracle/hash_to_curve.py)
// ---------------------------------------------------------------------------

static const char DST[] = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";
#define DST_LEN 43

// expand_message_xmd for len_in_bytes = 256 (count=2, m=2, L=64)
static void expand_message_xmd_256(const u8 *msg, u64 msg_len, u8 out[256]) {
  u8 b0[32], bi[32];
  u8 dst_prime[DST_LEN + 1];
  memcpy(dst_prime, DST, DST_LEN);
  dst_prime[DST_LEN] = DST_LEN;
  // b0 = H(z_pad || msg || l_i_b_str || 0x00 || dst_prime)
  Sha256 s;
  sha_init(s);
  u8 zpad[64] = {0};
  sha_update(s, zpad, 64);
  sha_update(s, msg, msg_len);
  u8 lib[3] = {(u8)(256 >> 8), (u8)(256 & 0xff), 0x00};
  sha_update(s, lib, 3);
  sha_update(s, dst_prime, DST_LEN + 1);
  sha_final(s, b0);
  // b1 = H(b0 || 0x01 || dst_prime)
  sha_init(s);
  sha_update(s, b0, 32);
  u8 one = 1;
  sha_update(s, &one, 1);
  sha_update(s, dst_prime, DST_LEN + 1);
  sha_final(s, bi);
  memcpy(out, bi, 32);
  for (int i = 2; i <= 8; i++) {
    u8 tmp[32];
    for (int k = 0; k < 32; k++) tmp[k] = b0[k] ^ bi[k];
    sha_init(s);
    sha_update(s, tmp, 32);
    u8 ib = (u8)i;
    sha_update(s, &ib, 1);
    sha_update(s, dst_prime, DST_LEN + 1);
    sha_final(s, bi);
    memcpy(out + 32 * (i - 1), bi, 32);
  }
}

// SSWU + 3-isogeny constants (RFC 9380 8.8.2 and appendix E.3; values as in
// the oracle). Filled at init.
static Fp2 ISO_A, ISO_B, SSWU_Z;
static Fp2 ISO_XNUM[4], ISO_XDEN[3], ISO_YNUM[4], ISO_YDEN[4];
static Fp2 SSWU_MBA;  // -B/A precomputed
static Fp2 SSWU_BZA;  // B/(Z*A)

static void fp2_inv0(Fp2 &o, const Fp2 &a) {
  if (fp2_is_zero(a)) {
    o = FP2_ZERO;
    return;
  }
  fp2_inv(o, a);
}

// Simplified SWU mapping to the iso-curve E' (oracle map_to_curve_sswu).
static void map_to_curve_sswu(Aff<Fp2> &o, const Fp2 &u) {
  Fp2 u2, zu2, t, tv1, x1, gx1, x2, gx2, y;
  fp2_sqr(u2, u);
  fp2_mul(zu2, SSWU_Z, u2);
  // tv1 = inv0(Z^2 u^4 + Z u^2) = inv0(zu2^2 + zu2)
  fp2_sqr(t, zu2);
  fp2_add(t, t, zu2);
  fp2_inv0(tv1, t);
  if (fp2_is_zero(tv1)) {
    x1 = SSWU_BZA;
  } else {
    fp2_add(t, FP2_ONE, tv1);
    fp2_mul(x1, SSWU_MBA, t);
  }
  // gx1 = (x1^2 + A) x1 + B
  fp2_sqr(t, x1);
  fp2_add(t, t, ISO_A);
  fp2_mul(gx1, t, x1);
  fp2_add(gx1, gx1, ISO_B);
  fp2_mul(x2, zu2, x1);
  fp2_sqr(t, x2);
  fp2_add(t, t, ISO_A);
  fp2_mul(gx2, t, x2);
  fp2_add(gx2, gx2, ISO_B);
  Fp2 x;
  if (fp2_sqrt(y, gx1)) {
    x = x1;
  } else {
    bool ok = fp2_sqrt(y, gx2);
    (void)ok;  // RFC guarantee: gx2 is square when gx1 is not
    x = x2;
  }
  if (fp2_sgn0(u) != fp2_sgn0(y)) fp2_neg(y, y);
  o.x = x;
  o.y = y;
  o.inf = false;
}

static void iso_horner(Fp2 &o, const Fp2 *k, int n, const Fp2 &x) {
  Fp2 acc = k[n - 1];
  for (int i = n - 2; i >= 0; i--) {
    fp2_mul(acc, acc, x);
    fp2_add(acc, acc, k[i]);
  }
  o = acc;
}

static void iso_map(Aff<Fp2> &o, const Aff<Fp2> &p) {
  // alias-safe for &o == &p: finish all reads of p before writing o
  Fp2 xn, xd, yn, yd, t;
  iso_horner(xn, ISO_XNUM, 4, p.x);
  iso_horner(xd, ISO_XDEN, 3, p.x);
  iso_horner(yn, ISO_YNUM, 4, p.x);
  iso_horner(yd, ISO_YDEN, 4, p.x);
  fp2_inv(t, yd);
  fp2_mul(t, yn, t);
  fp2_mul(o.y, p.y, t);
  fp2_inv(t, xd);
  fp2_mul(o.x, xn, t);
  o.inf = false;
}

// Budroni-Pintore cofactor clearing: [x^2-x-1]P + [x-1]psi(P) + psi^2(2P).
// x negative: x^2-x-1 = |x|^2+|x|-1 >= 0; [x-1]Q = -[|x|+1]Q.
static void clear_cofactor_psi(Jac<Fp2> &o, const Aff<Fp2> &p) {
  u64 e1[3];
  u128 sq = (u128)BLS_X_ABS * BLS_X_ABS;
  u128 lo = (u128)(u64)sq + BLS_X_ABS - 1;
  e1[0] = (u64)lo;
  u128 hi = (u128)(u64)(sq >> 64) + (u64)(lo >> 64);
  e1[1] = (u64)hi;
  e1[2] = (u64)(hi >> 64);
  u64 e2[2];
  u128 xp1 = (u128)BLS_X_ABS + 1;
  e2[0] = (u64)xp1;
  e2[1] = (u64)(xp1 >> 64);

  Jac<Fp2> jp, t1, t2, t3;
  jac_from_aff(jp, p);
  jac_mul(t1, jp, e1, 129);  // [|x|^2+|x|-1]P
  Aff<Fp2> psip, psi2p2;
  g2_psi(psip, p);
  Jac<Fp2> jpsi;
  jac_from_aff(jpsi, psip);
  jac_mul(t2, jpsi, e2, 65);  // [|x|+1]psi(P)
  jac_neg(t2, t2);            // [x-1]psi(P)
  // psi^2(2P)
  Jac<Fp2> j2p;
  jac_dbl(j2p, jp);
  Aff<Fp2> a2p;
  jac_to_aff(a2p, j2p);
  g2_psi(psi2p2, a2p);
  g2_psi(psi2p2, psi2p2);
  jac_from_aff(t3, psi2p2);
  jac_add(o, t1, t2);
  jac_add(o, o, t3);
}

// Full hash_to_curve_g2 (affine out).
static void hash_to_g2(Aff<Fp2> &o, const u8 *msg, u64 msg_len) {
  u8 uni[256];
  expand_message_xmd_256(msg, msg_len, uni);
  Fp2 u0, u1;
  fp_from_be_mod(u0.c0, uni, 64);
  fp_from_be_mod(u0.c1, uni + 64, 64);
  fp_from_be_mod(u1.c0, uni + 128, 64);
  fp_from_be_mod(u1.c1, uni + 192, 64);
  Aff<Fp2> q0, q1;
  map_to_curve_sswu(q0, u0);
  iso_map(q0, q0);
  map_to_curve_sswu(q1, u1);
  iso_map(q1, q1);
  Jac<Fp2> j0, j1, sum, cleared;
  jac_from_aff(j0, q0);
  jac_from_aff(j1, q1);
  jac_add(sum, j0, j1);
  Aff<Fp2> asum;
  jac_to_aff(asum, sum);
  clear_cofactor_psi(cleared, asum);
  jac_to_aff(o, cleared);
}

// ---------------------------------------------------------------------------
// Init
// ---------------------------------------------------------------------------

static Aff<Fp> NEG_G1_GEN;
static bool INITIALIZED = false;

// long-divide the raw 6-limb value a by small d (exact or floor)
static void raw_div_small(u64 o[6], const u64 a[6], u64 d) {
  u128 rem = 0;
  for (int i = 5; i >= 0; i--) {
    u128 cur = (rem << 64) | a[i];
    o[i] = (u64)(cur / d);
    rem = cur % d;
  }
}

extern "C" int bls_native_init() {
  if (INITIALIZED) return 0;
  // MONT_INV = -p^{-1} mod 2^64 (Newton)
  u64 inv = 1;
  for (int i = 0; i < 6; i++) inv *= 2 - P_LIMBS[0] * inv;
  MONT_INV = (u64)(0 - inv);
  // FP_ONE = 2^384 mod p; R2 = 2^768 mod p
  u64 t[6] = {1, 0, 0, 0, 0, 0};
  for (int i = 0; i < 384; i++) raw_shl1_mod_p(t);
  memcpy(FP_ONE.l, t, 48);
  for (int i = 0; i < 384; i++) raw_shl1_mod_p(t);
  memcpy(R2.l, t, 48);
  // HALF_P = (p-1)/2
  u64 pm1[6];
  memcpy(pm1, P_LIMBS, 48);
  pm1[0] -= 1;  // p is odd
  raw_div_small(HALF_P, pm1, 2);
  // exponents
  memcpy(EXP_P_MINUS_2, P_LIMBS, 48);
  EXP_P_MINUS_2[0] -= 2;
  u64 pp1[6];
  memcpy(pp1, P_LIMBS, 48);
  pp1[0] += 1;  // no carry: p ends 0xaaab
  raw_div_small(EXP_P_PLUS_1_D4, pp1, 4);
  u64 pm3[6];
  memcpy(pm3, P_LIMBS, 48);
  pm3[0] -= 3;
  raw_div_small(EXP_P_MINUS_3_D4, pm3, 4);
  raw_div_small(EXP_P_MINUS_1_D2, pm1, 2);
  raw_div_small(EXP_P_MINUS_1_D3, pm1, 3);
  raw_div_small(EXP_P_MINUS_1_D6, pm1, 6);

  // tower constants
  FP2_ZERO.c0 = FP_ZERO;
  FP2_ZERO.c1 = FP_ZERO;
  FP2_ONE.c0 = FP_ONE;
  FP2_ONE.c1 = FP_ZERO;
  FP6_ZERO.c0 = FP2_ZERO;
  FP6_ZERO.c1 = FP2_ZERO;
  FP6_ZERO.c2 = FP2_ZERO;
  FP6_ONE.c0 = FP2_ONE;
  FP6_ONE.c1 = FP2_ZERO;
  FP6_ONE.c2 = FP2_ZERO;
  FP12_ONE.c0 = FP6_ONE;
  FP12_ONE.c1 = FP6_ZERO;

  // frobenius coefficients: xi = u+1
  Fp2 xi;
  xi.c0 = FP_ONE;
  xi.c1 = FP_ONE;
  fp2_pow(FROB6_C1[1], xi, EXP_P_MINUS_1_D3, 381);
  Fp2 xi2;
  fp2_sqr(xi2, xi);
  fp2_pow(FROB6_C2[1], xi2, EXP_P_MINUS_1_D3, 381);  // xi^(2(p-1)/3)
  fp2_pow(FROB12_C1[1], xi, EXP_P_MINUS_1_D6, 381);

  // psi coefficients: inverses of xi^((p-1)/3), xi^((p-1)/2)
  fp2_inv(PSI_CX, FROB6_C1[1]);
  Fp2 xi_half;
  fp2_pow(xi_half, xi, EXP_P_MINUS_1_D2, 381);
  fp2_inv(PSI_CY, xi_half);

  // curve constants
  Fp four_raw = {{4, 0, 0, 0, 0, 0}};
  fp_to_mont(G1_B, four_raw);
  G2_B.c0 = G1_B;
  G2_B.c1 = G1_B;

  // generators (canonical limbs, little-endian; spec constants)
  static const u64 G1X[6] = {0xfb3af00adb22c6bbULL, 0x6c55e83ff97a1aefULL,
                             0xa14e3a3f171bac58ULL, 0xc3688c4f9774b905ULL,
                             0x2695638c4fa9ac0fULL, 0x17f1d3a73197d794ULL};
  static const u64 G1Y[6] = {0x0caa232946c5e7e1ULL, 0xd03cc744a2888ae4ULL,
                             0x00db18cb2c04b3edULL, 0xfcf5e095d5d00af6ULL,
                             0xa09e30ed741d8ae4ULL, 0x08b3f481e3aaa0f1ULL};
  static const u64 G2X0[6] = {0xd48056c8c121bdb8ULL, 0x0bac0326a805bbefULL,
                              0xb4510b647ae3d177ULL, 0xc6e47ad4fa403b02ULL,
                              0x260805272dc51051ULL, 0x024aa2b2f08f0a91ULL};
  static const u64 G2X1[6] = {0xe5ac7d055d042b7eULL, 0x334cf11213945d57ULL,
                              0xb5da61bbdc7f5049ULL, 0x596bd0d09920b61aULL,
                              0x7dacd3a088274f65ULL, 0x13e02b6052719f60ULL};
  static const u64 G2Y0[6] = {0xe193548608b82801ULL, 0x923ac9cc3baca289ULL,
                              0x6d429a695160d12cULL, 0xadfd9baa8cbdd3a7ULL,
                              0x8cc9cdc6da2e351aULL, 0x0ce5d527727d6e11ULL};
  static const u64 G2Y1[6] = {0xaaa9075ff05f79beULL, 0x3f370d275cec1da1ULL,
                              0x267492ab572e99abULL, 0xcb3e287e85a763afULL,
                              0x32acd2b02bc28b99ULL, 0x0606c4a02ea734ccULL};
  Fp raw;
  memcpy(raw.l, G1X, 48);
  fp_to_mont(G1_GEN.x, raw);
  memcpy(raw.l, G1Y, 48);
  fp_to_mont(G1_GEN.y, raw);
  G1_GEN.inf = false;
  memcpy(raw.l, G2X0, 48);
  fp_to_mont(G2_GEN.x.c0, raw);
  memcpy(raw.l, G2X1, 48);
  fp_to_mont(G2_GEN.x.c1, raw);
  memcpy(raw.l, G2Y0, 48);
  fp_to_mont(G2_GEN.y.c0, raw);
  memcpy(raw.l, G2Y1, 48);
  fp_to_mont(G2_GEN.y.c1, raw);
  G2_GEN.inf = false;
  if (!on_curve(G1_GEN, G1_B) || !on_curve(G2_GEN, G2_B)) return -1;
  NEG_G1_GEN = G1_GEN;
  fp_neg(NEG_G1_GEN.y, G1_GEN.y);

  // SSWU constants: A' = 240u, B' = 1012(1+u), Z = -(2+u)
  Fp v240, v1012;
  Fp raw240 = {{240, 0, 0, 0, 0, 0}}, raw1012 = {{1012, 0, 0, 0, 0, 0}};
  fp_to_mont(v240, raw240);
  fp_to_mont(v1012, raw1012);
  ISO_A.c0 = FP_ZERO;
  ISO_A.c1 = v240;
  ISO_B.c0 = v1012;
  ISO_B.c1 = v1012;
  Fp two_raw = {{2, 0, 0, 0, 0, 0}}, m2, m1;
  fp_to_mont(m2, two_raw);
  fp_neg(SSWU_Z.c0, m2);
  fp_neg(SSWU_Z.c1, FP_ONE);
  (void)m1;
  // -B/A and B/(Z*A)
  Fp2 ainv, t2;
  fp2_inv(ainv, ISO_A);
  fp2_mul(SSWU_MBA, ISO_B, ainv);
  fp2_neg(SSWU_MBA, SSWU_MBA);
  fp2_mul(t2, SSWU_Z, ISO_A);
  fp2_inv(t2, t2);
  fp2_mul(SSWU_BZA, ISO_B, t2);

  // 3-isogeny constants (RFC 9380 E.3, as in oracle hash_to_curve.py)
#define K2(dst, h0, h1)        \
  fp_from_hex(dst.c0, h0);     \
  fp_from_hex(dst.c1, h1);
  K2(ISO_XNUM[0],
     "5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6",
     "5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97d6");
  K2(ISO_XNUM[1], "0",
     "11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71a");
  K2(ISO_XNUM[2],
     "11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71e",
     "8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38d");
  K2(ISO_XNUM[3],
     "171d6541fa38ccfaed6dea691f5fb614cb14b4e7f4e810aa22d6108f142b85757098e38d0f671c7188e2aaaaaaaa5ed1",
     "0");
  K2(ISO_XDEN[0], "0",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa63");
  K2(ISO_XDEN[1], "c",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa9f");
  ISO_XDEN[2] = FP2_ONE;
  K2(ISO_YNUM[0],
     "1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706",
     "1530477c7ab4113b59a4c18b076d11930f7da5d4a07f649bf54439d87d27e500fc8c25ebf8c92f6812cfc71c71c6d706");
  K2(ISO_YNUM[1], "0",
     "5c759507e8e333ebb5b7a9a47d7ed8532c52d39fd3a042a88b58423c50ae15d5c2638e343d9c71c6238aaaaaaaa97be");
  K2(ISO_YNUM[2],
     "11560bf17baa99bc32126fced787c88f984f87adf7ae0c7f9a208c6b4f20a4181472aaa9cb8d555526a9ffffffffc71c",
     "8ab05f8bdd54cde190937e76bc3e447cc27c3d6fbd7063fcd104635a790520c0a395554e5c6aaaa9354ffffffffe38f");
  K2(ISO_YNUM[3],
     "124c9ad43b6cf79bfbf7043de3811ad0761b0f37a1e26286b0e977c69aa274524e79097a56dc4bd9e1b371c71c718b10",
     "0");
  K2(ISO_YDEN[0],
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa8fb");
  K2(ISO_YDEN[1], "0",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffa9d3");
  K2(ISO_YDEN[2], "12",
     "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f6241eabfffeb153ffffb9feffffffffaa99");
  ISO_YDEN[3] = FP2_ONE;
#undef K2

  INITIALIZED = true;
  return 0;
}

// ---------------------------------------------------------------------------
// Public API
// ---------------------------------------------------------------------------

extern "C" void bls_sk_to_pk(const u8 sk[32], u8 out[48]) {
  u64 e[4];
  scalar_from_be32_mod_r(e, sk);
  Jac<Fp> g, r;
  jac_from_aff(g, G1_GEN);
  jac_mul(r, g, e, 255);
  Aff<Fp> a;
  jac_to_aff(a, r);
  g1_compress(a, out);
}

extern "C" void bls_sign(const u8 sk[32], const u8 *msg, u64 msg_len,
                         u8 out[96]) {
  u64 e[4];
  scalar_from_be32_mod_r(e, sk);
  Aff<Fp2> h;
  hash_to_g2(h, msg, msg_len);
  Jac<Fp2> j, r;
  jac_from_aff(j, h);
  jac_mul(r, j, e, 255);
  Aff<Fp2> a;
  jac_to_aff(a, r);
  g2_compress(a, out);
}

extern "C" void bls_hash_to_g2(const u8 *msg, u64 msg_len, u8 out[96]) {
  Aff<Fp2> h;
  hash_to_g2(h, msg, msg_len);
  g2_compress(h, out);
}

// key_validate (blst.rs:75 semantics): decompress + not-infinity + subgroup.
extern "C" int bls_pk_validate(const u8 pk[48]) {
  Aff<Fp> p;
  if (!g1_decompress(p, pk)) return 0;
  if (p.inf) return 0;
  return g1_in_subgroup(p) ? 1 : 0;
}

extern "C" int bls_sig_validate(const u8 sig[96]) {
  Aff<Fp2> s;
  if (!g2_decompress(s, sig)) return 0;
  if (s.inf) return 0;
  return g2_in_subgroup(s) ? 1 : 0;
}

static bool decompress_pks_sum(Jac<Fp> &acc, u64 n, const u8 *pks) {
  jac_set_inf(acc);
  for (u64 i = 0; i < n; i++) {
    Aff<Fp> p;
    if (!g1_decompress(p, pks + 48 * i)) return false;
    Jac<Fp> j;
    jac_from_aff(j, p);
    jac_add(acc, acc, j);
  }
  return true;
}

// core verification: e(pk, H(m)) * e(-g1, sig) == 1
static int verify_inner(const Aff<Fp> &pk, const u8 *msg, u64 msg_len,
                        const Aff<Fp2> &sig) {
  if (pk.inf || sig.inf) return 0;
  if (!g2_in_subgroup(sig)) return 0;
  Aff<Fp2> h;
  hash_to_g2(h, msg, msg_len);
  Fp12 f = FP12_ONE;
  miller_loop_acc(f, pk, h);
  miller_loop_acc(f, NEG_G1_GEN, sig);
  Fp12 r;
  final_exponentiation(r, f);
  return fp12_is_one(r) ? 1 : 0;
}

extern "C" int bls_verify(const u8 pk[48], const u8 *msg, u64 msg_len,
                          const u8 sig[96]) {
  Aff<Fp> p;
  Aff<Fp2> s;
  if (!g1_decompress(p, pk) || p.inf || !g1_in_subgroup(p)) return 0;
  if (!g2_decompress(s, sig)) return 0;
  return verify_inner(p, msg, msg_len, s);
}

// All signers signed the same message; pubkeys must be pre-validated
// (fast_aggregate_verify per the Eth2 spec; blst.rs aggregate path).
extern "C" int bls_fast_aggregate_verify(u64 n, const u8 *pks, const u8 *msg,
                                         u64 msg_len, const u8 sig[96]) {
  if (n == 0) return 0;
  Jac<Fp> acc;
  if (!decompress_pks_sum(acc, n, pks)) return 0;
  Aff<Fp> apk;
  jac_to_aff(apk, acc);
  Aff<Fp2> s;
  if (!g2_decompress(s, sig)) return 0;
  return verify_inner(apk, msg, msg_len, s);
}

extern "C" int bls_aggregate_pubkeys(u64 n, const u8 *pks, u8 out[48]) {
  Jac<Fp> acc;
  if (!decompress_pks_sum(acc, n, pks)) return -1;
  Aff<Fp> a;
  jac_to_aff(a, acc);
  g1_compress(a, out);
  return 0;
}

extern "C" int bls_aggregate_signatures(u64 n, const u8 *sigs, u8 out[96]) {
  Jac<Fp2> acc;
  jac_set_inf(acc);
  for (u64 i = 0; i < n; i++) {
    Aff<Fp2> s;
    if (!g2_decompress(s, sigs + 96 * i)) return -1;
    Jac<Fp2> j;
    jac_from_aff(j, s);
    jac_add(acc, acc, j);
  }
  Aff<Fp2> a;
  jac_to_aff(a, acc);
  g2_compress(a, out);
  return 0;
}

// Random-linear-combination batch verification over signature sets — the
// native twin of blst's verify_multiple_aggregate_signatures (blst.rs:37-119)
// and of tpu_backend._verify_kernel:
//   prod_i e(r_i * agg_pk_i, H(m_i)) * e(-g1, sum_i r_i * sig_i) == 1
// pk_counts[i] pubkeys per set (48B compressed each, concatenated in pks);
// msgs = n_sets * 32B message roots; sigs = n_sets * 96B; scalars nonzero u64.
// Returns 1 verified, 0 rejected, -1 malformed input.
extern "C" int bls_verify_signature_sets(u64 n_sets, const u64 *pk_counts,
                                         const u8 *pks, const u8 *msgs,
                                         const u8 *sigs, const u64 *scalars) {
  if (n_sets == 0) return 0;
  Fp12 f = FP12_ONE;
  Jac<Fp2> sig_acc;
  jac_set_inf(sig_acc);
  u64 pk_off = 0;
  for (u64 i = 0; i < n_sets; i++) {
    // aggregate this set's pubkeys
    Jac<Fp> agg;
    if (!decompress_pks_sum(agg, pk_counts[i], pks + 48 * pk_off)) return -1;
    pk_off += pk_counts[i];
    Aff<Fp> apk;
    jac_to_aff(apk, agg);
    if (apk.inf) return 0;
    // signature: subgroup check, then scale and accumulate
    Aff<Fp2> sig;
    if (!g2_decompress(sig, sigs + 96 * i)) return -1;
    if (sig.inf || !g2_in_subgroup(sig)) return 0;
    u64 r = scalars[i] ? scalars[i] : 1;
    Jac<Fp2> js, rs;
    jac_from_aff(js, sig);
    jac_mul(rs, js, &r, 64);
    jac_add(sig_acc, sig_acc, rs);
    // scaled pubkey against H(m)
    Jac<Fp> jp, rp;
    jac_from_aff(jp, apk);
    jac_mul(rp, jp, &r, 64);
    Aff<Fp> spk;
    jac_to_aff(spk, rp);
    Aff<Fp2> h;
    hash_to_g2(h, msgs + 32 * i, 32);
    miller_loop_acc(f, spk, h);
  }
  Aff<Fp2> sacc;
  jac_to_aff(sacc, sig_acc);
  miller_loop_acc(f, NEG_G1_GEN, sacc);
  Fp12 r;
  final_exponentiation(r, f);
  return fp12_is_one(r) ? 1 : 0;
}

// Debug exports (parity bisection in tests; raw 48-byte BE field elements).
extern "C" void bls_dbg_expand256(const u8 *msg, u64 len, u8 out[256]) {
  expand_message_xmd_256(msg, len, out);
}

extern "C" void bls_dbg_h2f(const u8 *msg, u64 len, u8 out[192]) {
  u8 uni[256];
  expand_message_xmd_256(msg, len, uni);
  Fp2 u0, u1;
  fp_from_be_mod(u0.c0, uni, 64);
  fp_from_be_mod(u0.c1, uni + 64, 64);
  fp_from_be_mod(u1.c0, uni + 128, 64);
  fp_from_be_mod(u1.c1, uni + 192, 64);
  fp_to_be48(u0.c0, out);
  fp_to_be48(u0.c1, out + 48);
  fp_to_be48(u1.c0, out + 96);
  fp_to_be48(u1.c1, out + 144);
}

extern "C" int bls_dbg_sswu(const u8 in[96], u8 out[192]) {
  Fp2 u;
  if (!fp_from_be48(u.c0, in) || !fp_from_be48(u.c1, in + 48)) return -1;
  Aff<Fp2> q;
  map_to_curve_sswu(q, u);
  fp_to_be48(q.x.c0, out);
  fp_to_be48(q.x.c1, out + 48);
  fp_to_be48(q.y.c0, out + 96);
  fp_to_be48(q.y.c1, out + 144);
  return 0;
}

extern "C" int bls_dbg_sswu_iso(const u8 in[96], u8 out[192]) {
  Fp2 u;
  if (!fp_from_be48(u.c0, in) || !fp_from_be48(u.c1, in + 48)) return -1;
  Aff<Fp2> q;
  map_to_curve_sswu(q, u);
  iso_map(q, q);
  fp_to_be48(q.x.c0, out);
  fp_to_be48(q.x.c1, out + 48);
  fp_to_be48(q.y.c0, out + 96);
  fp_to_be48(q.y.c1, out + 144);
  return 0;
}

extern "C" int bls_dbg_clear(const u8 in[192], u8 out[96]) {
  Aff<Fp2> p;
  if (!fp_from_be48(p.x.c0, in) || !fp_from_be48(p.x.c1, in + 48) ||
      !fp_from_be48(p.y.c0, in + 96) || !fp_from_be48(p.y.c1, in + 144))
    return -1;
  p.inf = false;
  Jac<Fp2> c;
  clear_cofactor_psi(c, p);
  Aff<Fp2> a;
  jac_to_aff(a, c);
  g2_compress(a, out);
  return 0;
}

// Decompress a pubkey to raw affine bytes (x||y, 48B BE each) for caching —
// the analog of ValidatorPubkeyCache keeping keys decompressed in memory.
extern "C" int bls_pk_decompress(const u8 in[48], u8 out[96]) {
  Aff<Fp> p;
  if (!g1_decompress(p, in) || p.inf) return -1;
  fp_to_be48(p.x, out);
  fp_to_be48(p.y, out + 48);
  return 0;
}

// Batch verification with pre-decompressed pubkeys (96B raw affine each) —
// the hot-path shape: keys come from the cache, signatures from the wire.
extern "C" int bls_verify_signature_sets_raw(u64 n_sets, const u64 *pk_counts,
                                             const u8 *pks_raw, const u8 *msgs,
                                             const u8 *sigs,
                                             const u64 *scalars) {
  if (n_sets == 0) return 0;
  Fp12 f = FP12_ONE;
  Jac<Fp2> sig_acc;
  jac_set_inf(sig_acc);
  u64 pk_off = 0;
  for (u64 i = 0; i < n_sets; i++) {
    Jac<Fp> agg;
    jac_set_inf(agg);
    for (u64 k = 0; k < pk_counts[i]; k++) {
      Aff<Fp> p;
      const u8 *raw = pks_raw + 96 * (pk_off + k);
      if (!fp_from_be48(p.x, raw) || !fp_from_be48(p.y, raw + 48)) return -1;
      p.inf = false;
      Jac<Fp> j;
      jac_from_aff(j, p);
      jac_add(agg, agg, j);
    }
    pk_off += pk_counts[i];
    Aff<Fp> apk;
    jac_to_aff(apk, agg);
    if (apk.inf) return 0;
    Aff<Fp2> sig;
    if (!g2_decompress(sig, sigs + 96 * i)) return -1;
    if (sig.inf || !g2_in_subgroup(sig)) return 0;
    u64 r = scalars[i] ? scalars[i] : 1;
    Jac<Fp2> js, rs;
    jac_from_aff(js, sig);
    jac_mul(rs, js, &r, 64);
    jac_add(sig_acc, sig_acc, rs);
    Jac<Fp> jp, rp;
    jac_from_aff(jp, apk);
    jac_mul(rp, jp, &r, 64);
    Aff<Fp> spk;
    jac_to_aff(spk, rp);
    Aff<Fp2> h;
    hash_to_g2(h, msgs + 32 * i, 32);
    miller_loop_acc(f, spk, h);
  }
  Aff<Fp2> sacc;
  jac_to_aff(sacc, sig_acc);
  miller_loop_acc(f, NEG_G1_GEN, sacc);
  Fp12 r;
  final_exponentiation(r, f);
  return fp12_is_one(r) ? 1 : 0;
}

// Scalar-multiply a compressed G2 point (tests/benches).
extern "C" int bls_g2_mul(const u8 in[96], const u8 sk[32], u8 out[96]) {
  Aff<Fp2> p;
  if (!g2_decompress(p, in)) return -1;
  u64 e[4];
  scalar_from_be32_mod_r(e, sk);
  Jac<Fp2> j, r;
  jac_from_aff(j, p);
  jac_mul(r, j, e, 255);
  Aff<Fp2> a;
  jac_to_aff(a, r);
  g2_compress(a, out);
  return 0;
}
