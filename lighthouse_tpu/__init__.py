"""lighthouse_tpu — a TPU-native Ethereum consensus-layer framework.

A ground-up rebuild of the capabilities of the reference client (Lighthouse,
``/root/reference``): SSZ types and the beacon state transition, fork choice, batched
signature-verification pipelines, a back-pressured scheduler, storage, networking,
validator client, and HTTP APIs — with the BLS12-381 batch-verification hot path
executed as JAX/XLA kernels on TPU.

Importing this package enables 64-bit JAX types: the big-integer limb kernels
accumulate 16-bit-limb products in uint64 lanes.
"""

try:
    import os as _os

    import jax as _jax

    _jax.config.update("jax_enable_x64", True)
    # Persistent XLA compilation cache: the BLS kernels are large programs and
    # this host compiles them slowly; warm runs (tests, benches, the chain)
    # must not re-pay compilation. Opt out with LIGHTHOUSE_TPU_NO_JIT_CACHE=1.
    if not _os.environ.get("LIGHTHOUSE_TPU_NO_JIT_CACHE"):
        # Partition by host CPU fingerprint: the workspace survives across
        # machines, and XLA:CPU AOT executables compiled for another host's
        # feature set abort at run time (cpu_aot_loader SIGILL warning).
        def _host_tag() -> str:
            import hashlib as _hl

            try:
                with open("/proc/cpuinfo") as _fh:
                    for _line in _fh:
                        if _line.startswith("flags"):
                            return _hl.sha256(_line.encode()).hexdigest()[:12]
            except OSError:
                pass
            import platform as _pl

            return _hl.sha256(_pl.processor().encode()).hexdigest()[:12]

        _cache_dir = _os.environ.get(
            "LIGHTHOUSE_TPU_JIT_CACHE",
            _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                          _os.pardir, ".jax_cache", _host_tag()),
        )
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except ImportError:  # the pure-Python oracle backend works without jax
    pass

__version__ = "0.2.0"
