"""lighthouse_tpu — a TPU-native Ethereum consensus-layer framework.

A ground-up rebuild of the capabilities of the reference client (Lighthouse,
``/root/reference``): SSZ types and the beacon state transition, fork choice, batched
signature-verification pipelines, a back-pressured scheduler, storage, networking,
validator client, and HTTP APIs — with the BLS12-381 batch-verification hot path
executed as JAX/XLA kernels on TPU.

Importing this package enables 64-bit JAX types: the big-integer limb kernels
accumulate 16-bit-limb products in uint64 lanes.
"""

try:
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)
except ImportError:  # the pure-Python oracle backend works without jax
    pass

__version__ = "0.1.0"
