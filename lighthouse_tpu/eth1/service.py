"""Eth1 service: follow the eth1 chain, vote on eth1_data, supply deposits.

Twin of ``beacon_node/eth1/src/service.rs`` + the op-side of
``beacon_chain/src/eth1_chain.rs``: poll the provider for new blocks and
deposit logs, then answer two block-production questions —

  * ``eth1_data_vote(state)``: the spec ``get_eth1_vote`` — candidate blocks
    inside the voting-period follow-distance window, tallied against the
    state's current votes, falling back to the state's eth1_data.
  * ``deposits_for_inclusion(state)``: the next provable deposits the state
    expects (eth1_deposit_index .. eth1_data.deposit_count, capped at
    MAX_DEPOSITS) with proofs against the state's deposit root.
"""

from __future__ import annotations

from ..types.containers import Eth1Data
from ..utils.logging import get_logger
from .deposit_cache import DepositCache
from .provider import Eth1Provider

log = get_logger("eth1")


class Eth1Service:
    def __init__(self, spec, provider: Eth1Provider,
                 follow_distance: int = 16):
        self.spec = spec
        self.provider = provider
        self.follow_distance = follow_distance
        self.deposits = DepositCache()
        self._synced_to = -1
        self._count_cursor = 0  # deposits attributed to blocks so far
        # block_number -> (hash, timestamp, deposit_count at that block);
        # pruned to ~2x the voting window
        self._blocks: dict[int, tuple[bytes, int, int]] = {}

    # -- ingest -------------------------------------------------------------

    def update(self) -> int:
        """Pull new blocks + deposit logs (the periodic poll). Returns the
        number of new deposit logs ingested."""
        head = self.provider.latest_block_number()
        if head <= self._synced_to:
            return 0
        new_logs = self.provider.get_deposit_logs(self._synced_to + 1, head)
        for lg in new_logs:
            self.deposits.insert_log(lg)
        count = self._count_cursor
        for n in range(self._synced_to + 1, head + 1):
            blk = self.provider.get_block(n)
            while (
                count < len(self.deposits.logs)
                and self.deposits.logs[count].block_number <= n
            ):
                count += 1
            prev_count = self._blocks.get(n - 1, (None, None, 0))[2]
            self._blocks[n] = (blk.hash, blk.timestamp, max(count, prev_count))
        self._count_cursor = count
        self._synced_to = head
        # header cache pruning BY TIMESTAMP: the voting window reaches back
        # one voting period + 2x the follow distance from the period start,
        # which itself can lag the eth1 head — keep twice that horizon
        period_secs = (
            self.spec.preset.slots_per_eth1_voting_period
            * self.spec.preset.SECONDS_PER_SLOT
        )
        latest_ts = self._blocks[head][1]
        horizon = latest_ts - 2 * (period_secs + 2 * self.follow_distance * 14)
        for n in [k for k, (_, ts, _c) in self._blocks.items() if ts < horizon]:
            del self._blocks[n]
        if new_logs:
            log.info(
                "Eth1 deposits ingested",
                new=len(new_logs), total=len(self.deposits),
            )
        return len(new_logs)

    # -- block production answers ------------------------------------------

    def _voting_candidates(self, state) -> list[Eth1Data]:
        spec = self.spec
        period_start = _voting_period_start_time(spec, state)
        follow_secs = self.follow_distance * 14  # SECONDS_PER_ETH1_BLOCK
        in_window = [
            n
            for n, (_, ts, _c) in self._blocks.items()
            if period_start - 2 * follow_secs <= ts <= period_start - follow_secs
        ]
        out = []
        root_cache: dict[int, bytes] = {}  # counts repeat across blocks
        for n in sorted(in_window, reverse=True):
            h, _ts, count = self._blocks[n]
            if count < int(state.eth1_data.deposit_count):
                continue  # deposit count may never decrease
            if count not in root_cache:
                root_cache[count] = self.deposits.deposit_root(count)
            out.append(
                Eth1Data(
                    deposit_root=root_cache[count],
                    deposit_count=count,
                    block_hash=h,
                )
            )
        return out

    def eth1_data_vote(self, state) -> Eth1Data:
        """spec ``get_eth1_vote``: majority of in-period votes among valid
        candidates, else the most recent candidate, else the state's own."""
        candidates = self._voting_candidates(state)
        if not candidates:
            return state.eth1_data
        roots = {Eth1Data.hash_tree_root(c): c for c in candidates}
        tally: dict[bytes, int] = {}
        for vote in state.eth1_data_votes:
            r = Eth1Data.hash_tree_root(vote)
            if r in roots:
                tally[r] = tally.get(r, 0) + 1
        if tally:
            best = max(tally.items(), key=lambda kv: kv[1])[0]
            return roots[best]
        return candidates[0]

    def deposits_for_inclusion(self, state, eth1_data=None) -> list:
        """The exact deposits the state transition will demand. ``eth1_data``
        overrides the state's (callers pass the post-vote data). A cache that
        cannot prove owed deposits is an ERROR — silently returning fewer
        than expected would make the proposer build an invalid block
        (Eth1Chain::DepositsUnknown semantics)."""
        data = state.eth1_data if eth1_data is None else eth1_data
        start = int(state.eth1_deposit_index)
        count = int(data.deposit_count)
        end = min(count, start + self.spec.preset.MAX_DEPOSITS)
        if end <= start:
            return []
        if count > len(self.deposits):
            raise RuntimeError(
                f"deposit cache not synced: state expects {count} deposits, "
                f"cache has {len(self.deposits)}"
            )
        return self.deposits.get_deposits(start, end, count)


def _voting_period_start_time(spec, state) -> int:
    period_slots = (
        spec.preset.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.preset.SLOTS_PER_EPOCH
    )
    start_slot = int(state.slot) - int(state.slot) % period_slots
    return int(state.genesis_time) + start_slot * spec.preset.SECONDS_PER_SLOT
