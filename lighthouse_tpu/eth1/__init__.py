"""Eth1 bridge: deposit-contract log ingestion, eth1-data voting, genesis.

Twin of ``beacon_node/eth1`` (3,721 LoC) + ``beacon_node/genesis``'s
eth1_genesis_service: a provider seam abstracts the execution-chain RPC
(``eth_getLogs``-shaped), the deposit cache keeps the incremental
deposit-contract merkle tree with proof generation, the service follows the
eth1 chain at a distance and supplies block production with eth1-data votes
and provable deposits.
"""

from .deposit_cache import DepositCache, DepositLog
from .genesis import eth1_genesis_state, is_valid_genesis_state
from .provider import Eth1Block, Eth1Provider, MockEth1Provider
from .service import Eth1Service

__all__ = [
    "DepositCache",
    "DepositLog",
    "Eth1Block",
    "Eth1Provider",
    "Eth1Service",
    "MockEth1Provider",
    "eth1_genesis_state",
    "is_valid_genesis_state",
]
