"""Deposit cache: the incremental deposit-contract merkle tree + proofs.

Twin of ``beacon_node/eth1/src/deposit_cache.rs``: ordered deposit logs, the
depth-32 sparse merkle tree the deposit contract maintains on chain, and
proof generation for block inclusion — each proof is the 32-branch plus the
little-endian count mix-in (depth 33), matching what
``process_deposit`` verifies (per_block.py / spec ``is_valid_merkle_branch``).
"""

from __future__ import annotations

from dataclasses import dataclass
from hashlib import sha256 as _sha

from ..types.containers import Deposit, DepositData

DEPOSIT_TREE_DEPTH = 32


def _h(a: bytes, b: bytes) -> bytes:
    return _sha(a + b).digest()


_ZERO_HASHES: list[bytes] = [b"\x00" * 32]
for _ in range(DEPOSIT_TREE_DEPTH):
    _ZERO_HASHES.append(_h(_ZERO_HASHES[-1], _ZERO_HASHES[-1]))


@dataclass
class DepositLog:
    """One DepositEvent from the contract (deposit_log.rs)."""

    data: DepositData
    block_number: int
    index: int


class DepositCache:
    def __init__(self):
        self.logs: list[DepositLog] = []
        self._leaves: list[bytes] = []

    def insert_log(self, log: DepositLog) -> None:
        if log.index != len(self.logs):
            raise ValueError(
                f"non-consecutive deposit index {log.index}, "
                f"expected {len(self.logs)}"
            )
        self.logs.append(log)
        self._leaves.append(DepositData.hash_tree_root(log.data))

    def __len__(self) -> int:
        return len(self.logs)

    # -- tree ---------------------------------------------------------------

    def _level_nodes(self, count: int) -> list[list[bytes]]:
        """All tree levels for the first ``count`` leaves (level 0 = leaves,
        zero-padded virtually)."""
        levels = [self._leaves[:count]]
        for d in range(DEPOSIT_TREE_DEPTH):
            prev = levels[-1]
            nxt = []
            for i in range(0, len(prev), 2):
                left = prev[i]
                right = prev[i + 1] if i + 1 < len(prev) else _ZERO_HASHES[d]
                nxt.append(_h(left, right))
            levels.append(nxt)
        return levels

    def deposit_root(self, count: int | None = None) -> bytes:
        """Contract ``get_deposit_root()``: tree root mixed with the count."""
        count = len(self.logs) if count is None else count
        levels = self._level_nodes(count)
        root = levels[-1][0] if levels[-1] else _ZERO_HASHES[DEPOSIT_TREE_DEPTH]
        return _h(root, count.to_bytes(32, "little"))

    def get_deposits(self, start: int, end: int, deposit_count: int) -> list[Deposit]:
        """Deposits [start, end) with proofs against the ``deposit_count``-leaf
        tree (what goes into a block; deposit_cache.rs get_deposits)."""
        if end > deposit_count or deposit_count > len(self.logs):
            raise ValueError("deposit range exceeds known logs")
        levels = self._level_nodes(deposit_count)
        out = []
        for i in range(start, end):
            branch = []
            idx = i
            for d in range(DEPOSIT_TREE_DEPTH):
                sib = idx ^ 1
                level = levels[d]
                branch.append(
                    level[sib] if sib < len(level) else _ZERO_HASHES[d]
                )
                idx >>= 1
            branch.append(deposit_count.to_bytes(32, "little"))
            out.append(Deposit(proof=branch, data=self.logs[i].data))
        return out
