"""HTTP JSON-RPC eth1 provider + DepositEvent ABI codec.

Twin of the reference's eth1 HTTP client (``beacon_node/eth1/src/service.rs``
JSON-RPC calls + ``deposit_log`` ABI decoding): ``HttpEth1Provider`` speaks
``eth_blockNumber`` / ``eth_getBlockByNumber`` / ``eth_getLogs`` to any
execution client and decodes the deposit contract's ``DepositEvent`` logs —
five dynamic ``bytes`` fields ABI-encoded as head offsets + padded tails,
with amount and index as 8-byte little-endian gwei/counter values.
"""

from __future__ import annotations

from ..execution_layer.http import JsonRpcClient, data, qty, undata, unqty
from ..types.containers import DepositData
from .deposit_cache import DepositLog
from .provider import Eth1Block, Eth1Provider

# keccak-free stand-in topic: the reference matches on the DepositEvent
# topic hash; we use a fixed 32-byte tag (no keccak in the stdlib)
DEPOSIT_EVENT_TOPIC = b"\xde\xb0\x51\x7e" + b"\x00" * 28


def _abi_tail(b: bytes) -> bytes:
    """ABI dynamic-bytes tail: u256 length + right-padded data."""
    pad = (-len(b)) % 32
    return len(b).to_bytes(32, "big") + b + b"\x00" * pad


def encode_deposit_event_data(log: DepositLog) -> bytes:
    """ABI-encode DepositEvent(bytes,bytes,bytes,bytes,bytes) data."""
    fields = [
        bytes(log.data.pubkey),
        bytes(log.data.withdrawal_credentials),
        int(log.data.amount).to_bytes(8, "little"),
        bytes(log.data.signature),
        int(log.index).to_bytes(8, "little"),
    ]
    tails = [_abi_tail(f) for f in fields]
    head_len = 32 * len(fields)
    offsets, off = [], head_len
    for t in tails:
        offsets.append(off.to_bytes(32, "big"))
        off += len(t)
    return b"".join(offsets) + b"".join(tails)


def decode_deposit_event_data(blob: bytes) -> tuple[list[bytes], int]:
    """Inverse of ``encode_deposit_event_data``: the five byte fields."""
    fields = []
    for i in range(5):
        off = int.from_bytes(blob[32 * i : 32 * (i + 1)], "big")
        n = int.from_bytes(blob[off : off + 32], "big")
        fields.append(blob[off + 32 : off + 32 + n])
    return fields


def encode_deposit_log(log: DepositLog, contract_address: bytes) -> dict:
    """DepositLog -> eth_getLogs JSON entry."""
    return {
        "address": data(contract_address),
        "topics": [data(DEPOSIT_EVENT_TOPIC)],
        "data": data(encode_deposit_event_data(log)),
        "blockNumber": qty(log.block_number),
    }


def decode_deposit_log(obj: dict) -> DepositLog:
    pubkey, creds, amount, sig, index = decode_deposit_event_data(
        undata(obj["data"])
    )
    return DepositLog(
        data=DepositData(
            pubkey=pubkey,
            withdrawal_credentials=creds,
            amount=int.from_bytes(amount, "little"),
            signature=sig,
        ),
        block_number=unqty(obj["blockNumber"]),
        index=int.from_bytes(index, "little"),
    )


class HttpEth1Provider(Eth1Provider):
    """Eth1Provider over JSON-RPC HTTP (no auth: public eth namespace)."""

    def __init__(self, url: str, deposit_contract_address: bytes = b"\x11" * 20,
                 timeout: float = 8.0):
        self.rpc = JsonRpcClient(url, jwt_key=None, timeout=timeout)
        self.deposit_contract_address = deposit_contract_address

    def latest_block_number(self) -> int:
        return unqty(self.rpc.call("eth_blockNumber", []))

    def get_block(self, number: int) -> Eth1Block:
        obj = self.rpc.call("eth_getBlockByNumber", [qty(number), False])
        return Eth1Block(
            number=unqty(obj["number"]),
            hash=undata(obj["hash"]),
            parent_hash=undata(obj["parentHash"]),
            timestamp=unqty(obj["timestamp"]),
        )

    def get_deposit_logs(self, from_block: int, to_block: int) -> list[DepositLog]:
        logs = self.rpc.call(
            "eth_getLogs",
            [
                {
                    "fromBlock": qty(from_block),
                    "toBlock": qty(to_block),
                    "address": data(self.deposit_contract_address),
                }
            ],
        )
        return [decode_deposit_log(o) for o in logs]
