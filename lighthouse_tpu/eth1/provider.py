"""Eth1 provider seam + in-process mock chain.

Twin of the reference's HTTP JSON-RPC eth1 client (``eth1/src/http.rs``): the
service only needs block-by-number reads and deposit-log ranges, so that is
the whole seam. ``MockEth1Provider`` plays the role of anvil + the deposit
contract in tests (``testing/eth1_test_rig``): deposits submitted to it are
assigned contract indices and surfaced as logs, blocks tick with timestamps.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from hashlib import sha256

from ..types.containers import DepositData
from .deposit_cache import DepositLog


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    parent_hash: bytes
    timestamp: int


class Eth1Provider:
    """What the eth1 service needs from the execution chain."""

    def latest_block_number(self) -> int:
        raise NotImplementedError

    def get_block(self, number: int) -> Eth1Block:
        raise NotImplementedError

    def get_deposit_logs(self, from_block: int, to_block: int) -> list[DepositLog]:
        raise NotImplementedError


class MockEth1Provider(Eth1Provider):
    """Deterministic in-process eth1 chain + deposit contract."""

    def __init__(self, genesis_timestamp: int = 0, block_interval: int = 14):
        self.block_interval = block_interval
        self._lock = threading.Lock()
        self._blocks: list[Eth1Block] = [
            Eth1Block(
                number=0,
                hash=sha256(b"eth1-genesis").digest(),
                parent_hash=b"\x00" * 32,
                timestamp=genesis_timestamp,
            )
        ]
        self._logs: list[DepositLog] = []

    # -- chain control (test driver side) ----------------------------------

    def mine_block(self) -> Eth1Block:
        with self._lock:
            prev = self._blocks[-1]
            blk = Eth1Block(
                number=prev.number + 1,
                hash=sha256(b"eth1-block-%d" % (prev.number + 1)).digest(),
                parent_hash=prev.hash,
                timestamp=prev.timestamp + self.block_interval,
            )
            self._blocks.append(blk)
            return blk

    def submit_deposit(self, data: DepositData) -> DepositLog:
        """The deposit contract's ``DepositEvent`` (lands in the NEXT block)."""
        with self._lock:
            log = DepositLog(
                data=data,
                block_number=self._blocks[-1].number + 1,
                index=len(self._logs),
            )
            self._logs.append(log)
        self.mine_block()
        return log

    # -- provider seam ------------------------------------------------------

    def latest_block_number(self) -> int:
        with self._lock:
            return self._blocks[-1].number

    def get_block(self, number: int) -> Eth1Block:
        with self._lock:
            return self._blocks[number]

    def get_deposit_logs(self, from_block: int, to_block: int) -> list[DepositLog]:
        with self._lock:
            return [
                l for l in self._logs
                if from_block <= l.block_number <= to_block
            ]
