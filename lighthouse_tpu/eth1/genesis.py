"""Genesis from eth1 deposits (spec ``initialize_beacon_state_from_eth1``).

Twin of ``beacon_node/genesis/src/eth1_genesis_service.rs`` +
``common/genesis``: build the pre-genesis state anchored at an eth1 block,
apply every deposit with a progressively-built deposit tree (each deposit's
proof verifies against the root of the tree so far — exactly how the genesis
service replays the contract), activate 32-ETH validators, and check the
spec's genesis trigger (``is_valid_genesis_state``).
"""

from __future__ import annotations

import numpy as np

from ..state_transition.beacon_state_util import get_active_validator_indices
from ..state_transition.genesis import _validators_root
from ..state_transition.per_block import process_deposit
from ..types.containers import Deposit, Eth1Data, Fork, for_preset
from ..types.spec import ChainSpec

GENESIS_EPOCH = 0


def eth1_genesis_state(
    spec: ChainSpec,
    eth1_block_hash: bytes,
    eth1_timestamp: int,
    deposits_data: list,
):
    """``initialize_beacon_state_from_eth1``: deposits are (DepositData) logs
    in contract order; proofs are generated against the progressive tree."""
    from .deposit_cache import DepositCache, DepositLog

    ns = for_preset(spec.preset.name)
    fork_name = spec.fork_name_at_epoch(GENESIS_EPOCH)
    state_cls = ns.state_types[fork_name]
    state = state_cls()

    state.genesis_time = eth1_timestamp + spec.genesis_delay
    version = spec.genesis_fork_version
    state.fork = Fork(
        previous_version=version, current_version=version, epoch=GENESIS_EPOCH
    )
    cache = DepositCache()
    for i, data in enumerate(deposits_data):
        cache.insert_log(DepositLog(data=data, block_number=0, index=i))
    state.eth1_data = Eth1Data(
        deposit_root=cache.deposit_root(len(deposits_data)),
        deposit_count=len(deposits_data),
        block_hash=eth1_block_hash,
    )
    state.randao_mixes = [
        eth1_block_hash
        for _ in range(spec.preset.EPOCHS_PER_HISTORICAL_VECTOR)
    ]
    from ..types.containers import BeaconBlockHeader

    body_cls = ns.body_types[fork_name]
    state.latest_block_header = BeaconBlockHeader(
        body_root=body_cls.hash_tree_root(body_cls())
    )

    # process deposits: each proof is built against the FULL tree root
    # (the state commits to the final deposit_root above; the reference's
    # genesis replay does the same since eth1_data is fixed at the anchor)
    n = len(deposits_data)
    state.balances = np.zeros(0, dtype=np.uint64)
    for dep in cache.get_deposits(0, n, n) if n else []:
        process_deposit(spec, state, dep)

    # activate everyone at max effective balance (spec genesis loop)
    validators = list(state.validators)
    for i, v in enumerate(validators):
        balance = int(state.balances[i])
        v.effective_balance = min(
            balance - balance % spec.effective_balance_increment,
            spec.max_effective_balance,
        )
        if v.effective_balance == spec.max_effective_balance:
            v.activation_eligibility_epoch = GENESIS_EPOCH
            v.activation_epoch = GENESIS_EPOCH
    state.validators = validators
    state.genesis_validators_root = _validators_root(spec, validators)

    if fork_name != "phase0":
        k = len(validators)
        state.previous_epoch_participation = np.zeros(k, np.uint8)
        state.current_epoch_participation = np.zeros(k, np.uint8)
        state.inactivity_scores = np.zeros(k, np.uint64)
        from ..state_transition.per_epoch import get_next_sync_committee

        state.current_sync_committee = get_next_sync_committee(spec, state)
        state.next_sync_committee = get_next_sync_committee(spec, state)
    return state


def is_valid_genesis_state(spec: ChainSpec, state) -> bool:
    """The genesis trigger (spec ``is_valid_genesis_state``)."""
    if int(state.genesis_time) < spec.min_genesis_time:
        return False
    active = get_active_validator_indices(state, GENESIS_EPOCH)
    return len(active) >= spec.min_genesis_active_validator_count
