"""Slasher persistence over the column KV store (ref slasher/src/database.rs).

The reference runs LMDB/MDBX/redb environments with seven tables
(database.rs, database/interface.rs); here the same record families live as
columns of the framework's ``KeyValueStore`` (store/kv.py), so the slasher
shares the node's storage engine instead of carrying its own.

Layout:
  SlasherTargets          v_chunk u32    -> stored_epoch u64 + zlib(min_d) + zlib(max_d)
  SlasherAttesterRecords  v u32, target u32 -> data_root 32B + att_id u64
  SlasherIndexedAtts      att_id u64     -> IndexedAttestation SSZ
  SlasherAttIdByHash      att htr 32B    -> att_id u64
  SlasherProposals        slot u64, proposer u64 -> SignedBeaconBlockHeader SSZ
  SlasherMeta             b"next_id"     -> u64

Target tiles are compressed whole-row (distances are overwhelmingly the
neutral element, so zlib gets the same ~wins the reference sees per 16-epoch
chunk, array.rs:169-192, without 256 tiny KV round-trips per row).
"""

from __future__ import annotations

import struct
import threading
import zlib

import numpy as np

from ..store.kv import DBColumn, KeyValueStore
from .arrays import empty_row
from .config import SlasherConfig


class SlasherDB:
    def __init__(self, store: KeyValueStore, config: SlasherConfig, types):
        """``types`` is the preset namespace from ``containers.for_preset``
        (needs .IndexedAttestation); header type is preset-independent."""
        from ..types.containers import SignedBeaconBlockHeader

        self.store = store
        self.config = config
        self.types = types
        self._header_t = SignedBeaconBlockHeader
        self._lock = threading.RLock()
        # Write-back row cache: the reference's LMDB pages double as its
        # working memory; ours is host RAM (TPU-adjacent), so rows stay
        # resident uncompressed and hit disk only on flush_rows().
        self._row_cache: dict[int, tuple] = {}
        self._dirty_rows: set[int] = set()

    # -- indexed attestations -------------------------------------------------

    def store_indexed_attestation(self, att, root: bytes | None = None) -> int:
        """Dedup by hash-tree-root; returns the attestation id
        (ref database.rs store_indexed_attestation). Pass ``root`` when the
        caller already hashed the attestation to avoid re-hashing."""
        t = type(att)
        if root is None:
            root = t.hash_tree_root(att)
        with self._lock:
            existing = self.store.get(DBColumn.SlasherAttIdByHash, root)
            if existing is not None:
                return struct.unpack("<Q", existing)[0]
            raw = self.store.get(DBColumn.SlasherMeta, b"next_id")
            att_id = struct.unpack("<Q", raw)[0] if raw else 1
            self.store.do_atomically(
                [
                    ("put", DBColumn.SlasherMeta, b"next_id",
                     struct.pack("<Q", att_id + 1)),
                    ("put", DBColumn.SlasherAttIdByHash, root,
                     struct.pack("<Q", att_id)),
                    ("put", DBColumn.SlasherIndexedAtts,
                     struct.pack(">Q", att_id), t.encode(att)),
                ]
            )
            return att_id

    def get_indexed_attestation(self, att_id: int):
        raw = self.store.get(
            DBColumn.SlasherIndexedAtts, struct.pack(">Q", att_id)
        )
        if raw is None:
            raise KeyError(f"slasher: missing indexed attestation {att_id}")
        return self.types.IndexedAttestation.decode(raw)

    # -- attester records (double-vote detection) -----------------------------

    @staticmethod
    def _record_key(validator_index: int, target_epoch: int) -> bytes:
        return struct.pack(">IQ", validator_index, target_epoch)

    def check_and_update_attester_record(
        self, validator_index: int, att, data_root: bytes, att_id: int
    ):
        """Returns None (not slashable) or the existing conflicting
        IndexedAttestation (double vote) — ref database.rs:585-640."""
        key = self._record_key(validator_index, int(att.data.target.epoch))
        with self._lock:
            raw = self.store.get(DBColumn.SlasherAttesterRecords, key)
            if raw is None:
                self.store.put(
                    DBColumn.SlasherAttesterRecords,
                    key,
                    data_root + struct.pack("<Q", att_id),
                )
                return None
        existing_root, existing_id = raw[:32], struct.unpack("<Q", raw[32:])[0]
        if existing_id == att_id or existing_root == data_root:
            return None
        return self.get_indexed_attestation(existing_id)

    def get_attestation_for_validator(self, validator_index: int, target_epoch: int):
        """Record lookup backing surround confirmation (ref array.rs:230-237)."""
        raw = self.store.get(
            DBColumn.SlasherAttesterRecords,
            self._record_key(validator_index, target_epoch),
        )
        if raw is None:
            raise KeyError(
                f"slasher: no record for validator {validator_index} "
                f"@ target {target_epoch}"
            )
        return self.get_indexed_attestation(struct.unpack("<Q", raw[32:])[0])

    # -- block proposals (proposer double votes) ------------------------------

    def check_or_insert_block_proposal(self, signed_header):
        """None if fresh/identical; existing SignedBeaconBlockHeader when the
        proposer signed a different block at the slot (ref database.rs:692-719)."""
        msg = signed_header.message
        key = struct.pack(">QQ", int(msg.slot), int(msg.proposer_index))
        with self._lock:
            raw = self.store.get(DBColumn.SlasherProposals, key)
            if raw is None:
                self.store.put(
                    DBColumn.SlasherProposals,
                    key,
                    self._header_t.encode(signed_header),
                )
                return None
        existing = self._header_t.decode(raw)
        if existing == signed_header:
            return None
        return existing

    # -- min/max target tiles -------------------------------------------------

    def load_row(self, validator_chunk_index: int):
        """(stored_epoch, min_d, max_d) for a validator-chunk row; fresh
        neutral tiles when the row has never been written."""
        with self._lock:
            cached = self._row_cache.get(validator_chunk_index)
            if cached is not None:
                return cached
        k, n = self.config.validator_chunk_size, self.config.history_length
        raw = self.store.get(
            DBColumn.SlasherTargets, struct.pack(">I", validator_chunk_index)
        )
        if raw is None:
            min_d, max_d = empty_row(k, n)
            row = (0, min_d, max_d)
        else:
            stored_epoch, min_len = struct.unpack_from("<QI", raw)
            off = 12
            min_d = np.frombuffer(
                zlib.decompress(raw[off : off + min_len]), dtype=np.uint16
            ).reshape(k, n).copy()
            max_d = np.frombuffer(
                zlib.decompress(raw[off + min_len :]), dtype=np.uint16
            ).reshape(k, n).copy()
            row = (stored_epoch, min_d, max_d)
        with self._lock:
            self._row_cache[validator_chunk_index] = row
        return row

    def store_row(self, validator_chunk_index: int, epoch: int, min_d, max_d):
        with self._lock:
            self._row_cache[validator_chunk_index] = (epoch, min_d, max_d)
            self._dirty_rows.add(validator_chunk_index)

    def flush_rows(self) -> int:
        """Persist dirty rows (the commit point of the reference's per-batch
        LMDB transaction, slasher.rs:98-107)."""
        with self._lock:
            dirty = [
                (rid, self._row_cache[rid]) for rid in sorted(self._dirty_rows)
            ]
        ops = []
        for rid, (epoch, min_d, max_d) in dirty:
            zmin = zlib.compress(np.ascontiguousarray(min_d).tobytes(), 1)
            zmax = zlib.compress(np.ascontiguousarray(max_d).tobytes(), 1)
            ops.append(
                (
                    "put",
                    DBColumn.SlasherTargets,
                    struct.pack(">I", rid),
                    struct.pack("<QI", epoch, len(zmin)) + zmin + zmax,
                )
            )
        if ops:
            self.store.do_atomically(ops)
        # only forget dirtiness once the write has succeeded — a failed
        # flush must stay retryable — and only for rows not re-dirtied
        # while the write ran unlocked (identity check against the snapshot)
        with self._lock:
            for rid, row in dirty:
                if self._row_cache.get(rid) is row:
                    self._dirty_rows.discard(rid)
        return len(ops)

    # -- pruning --------------------------------------------------------------

    def prune(self, current_epoch: int, slots_per_epoch: int) -> int:
        """Drop attester records / attestations / proposals older than the
        history window (ref database.rs prune)."""
        min_epoch = max(0, current_epoch - self.config.history_length + 1)
        dropped = 0
        live_ids = set()
        ops = []
        for key, raw in self.store.iter_column(DBColumn.SlasherAttesterRecords):
            _, target = struct.unpack(">IQ", key)
            if target < min_epoch:
                ops.append(("delete", DBColumn.SlasherAttesterRecords, key))
                dropped += 1
            else:
                live_ids.add(struct.unpack("<Q", raw[32:])[0])
        for key, raw in self.store.iter_column(DBColumn.SlasherAttIdByHash):
            att_id = struct.unpack("<Q", raw)[0]
            if att_id not in live_ids:
                ops.append(("delete", DBColumn.SlasherAttIdByHash, key))
                ops.append(
                    ("delete", DBColumn.SlasherIndexedAtts, struct.pack(">Q", att_id))
                )
        min_slot = min_epoch * slots_per_epoch
        for key, _ in self.store.iter_column(DBColumn.SlasherProposals):
            slot, _ = struct.unpack(">QQ", key)
            if slot < min_slot:
                ops.append(("delete", DBColumn.SlasherProposals, key))
                dropped += 1
        if ops:
            self.store.do_atomically(ops)
        return dropped
