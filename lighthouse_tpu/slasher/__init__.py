"""Slashing detection with device min/max target arrays (ref slasher/).

SURVEY.md §5 calls the reference's chunked 2D epoch x validator arrays "the
closest thing to blockwise attention" in the codebase; this package is that
workload rebuilt TPU-first — scatter + directional cumulative scans over
whole validator-chunk tiles instead of per-validator epoch walk loops.
"""

from .config import MAX_DISTANCE, SlasherConfig
from .db import SlasherDB
from .service import SlasherService
from .slasher import Slasher

__all__ = [
    "MAX_DISTANCE",
    "Slasher",
    "SlasherConfig",
    "SlasherDB",
    "SlasherService",
]
