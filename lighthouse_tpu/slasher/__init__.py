"""Slashing detection with device min/max target arrays (ref slasher/).

SURVEY.md §5 calls the reference's chunked 2D epoch x validator arrays "the
closest thing to blockwise attention" in the codebase; this package is that
workload rebuilt TPU-first — scatter + directional cumulative scans over
validator tiles instead of per-validator epoch walk loops.

Two implementations share the ``SlasherService`` surface:

* the seed per-row path (``slasher.py`` + ``arrays.py`` + ``db.py``):
  validator-chunk rows loaded through the KV store per batch — the
  DB-backed reference twin, kept as the parity oracle;
* the device-resident engine (``engine.py`` + ``kernels.py``): ONE
  ``[n_validators, history_length]`` span store living on device across
  ticks, per-batch update + double/surround detection as one fused sweep.

The backend seam mirrors ``LIGHTHOUSE_EPOCH_BACKEND``: ``set_backend`` or
the ``LIGHTHOUSE_SLASHER_BACKEND`` environment variable selects

* ``numpy``  — the engine on its field-for-field numpy twin (no jax import);
* ``device`` — the engine on the fused jitted sweep (``kernels.py``);
* ``auto``   — the default: ``device`` when an accelerator platform backs
  JAX, ``numpy`` otherwise, so CPU-only test tiers never pay kernel
  compiles they didn't ask for.

This module stays import-light (no jax, no engine import until
``make_slasher`` runs).
"""

from __future__ import annotations

import os

from .config import MAX_DISTANCE, SlasherConfig
from .db import SlasherDB
from .service import SlasherService
from .slasher import Slasher

__all__ = [
    "MAX_DISTANCE",
    "Slasher",
    "SlasherConfig",
    "SlasherDB",
    "SlasherService",
    "device_backend_active",
    "get_backend",
    "make_slasher",
    "set_backend",
]

_BACKEND = os.environ.get("LIGHTHOUSE_SLASHER_BACKEND", "auto")
_AUTO_DECISION: bool | None = None


def set_backend(name: str) -> None:
    global _BACKEND, _AUTO_DECISION
    if name not in ("auto", "device", "numpy"):
        raise ValueError(f"unknown slasher backend {name!r}")
    _BACKEND = name
    _AUTO_DECISION = None


def get_backend() -> str:
    return _BACKEND


def _accelerator_present() -> bool:
    """auto-mode probe, memoized (the epoch-engine pattern): never
    *initiates* a device tunnel probe beyond what jax.devices() implies —
    CPU-only tiers have already pinned JAX_PLATFORMS=cpu."""
    global _AUTO_DECISION
    if _AUTO_DECISION is None:
        try:
            import jax

            _AUTO_DECISION = jax.devices()[0].platform in ("tpu", "gpu")
        except Exception:  # noqa: BLE001 — no jax / no devices: numpy path
            _AUTO_DECISION = False
    return _AUTO_DECISION


def device_backend_active() -> bool:
    if _BACKEND == "numpy":
        return False
    if _BACKEND == "device":
        return True
    return _accelerator_present()


def make_slasher(store=None, types=None, config: SlasherConfig | None = None,
                 **kw):
    """Construct the engine-backed slasher behind the backend seam (the
    client / local-network assembly point). ``store`` is accepted for
    call-site compatibility with the seed ``Slasher``; the engine keeps its
    record index in memory and prunes it with the window.

    With no explicit config, the surveillance window comes from
    ``LIGHTHOUSE_SLASHER_HISTORY`` (default: the reference's 4096 epochs).
    The engine's planes are DENSE — 8 bytes per validator-epoch cell — so
    a large registry should size the window to its memory budget (1M
    validators x 4096 epochs ~ 32 GB; x 512 ~ 4 GB); the drop window for
    old evidence shrinks with it, exactly like a reference node configured
    with a shorter ``--slasher-history-length``.
    """
    from .engine import EngineSlasher

    if config is None:
        raw = os.environ.get("LIGHTHOUSE_SLASHER_HISTORY", "").strip()
        history = int(raw) if raw else SlasherConfig().history_length
        config = SlasherConfig(history_length=history)
    slasher = EngineSlasher(store, types, config, **kw)
    if store is not None:
        # restart-from-disk: rehydrate the surveillance window from the
        # last checkpoint (engine.persist) so pre-restart votes still
        # convict a post-restart equivocator
        try:
            slasher.restore()
        except Exception as e:  # noqa: BLE001 — corrupt checkpoint: start fresh
            from ..utils.logging import get_logger

            get_logger("slasher").warning(
                "Slasher checkpoint restore failed", error=str(e)
            )
    return slasher
