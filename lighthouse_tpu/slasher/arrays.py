"""Surround-vote min/max target arrays as fused device kernels.

Reference semantics (slasher/src/array.rs): for every validator the slasher
maintains, over a sliding window of ``history_length`` epochs,

  ``min_targets[v][e]`` = min target of v's attestations with source >  e
  ``max_targets[v][e]`` = max target of v's attestations with source <  e

A new attestation ``X`` surrounds an existing one iff ``X.target >
min_targets[v][X.source]`` and is surrounded iff ``X.target <
max_targets[v][X.source]`` (array.rs:219-244, 322-347).  The reference
maintains the invariant with per-validator epoch-by-epoch walk loops with
early exit, tiled into 16-epoch chunks to bound I/O (array.rs:246-272,
349-372).

TPU redesign — the walk loops are really *interval* min/max updates whose
intervals always extend to a window edge: attestation ``(s, t)`` applies
``min`` over cells ``[window_start, s-1]`` and ``max`` over ``[s+1,
current_epoch]``.  An entire batch therefore collapses to

  1. scatter-min of ``t`` at column ``s-1`` (resp. scatter-max at ``s+1``),
  2. one reverse (resp. forward) cumulative min (resp. max) scan along the
     epoch axis,
  3. an elementwise combine with the previous array.

No per-attestation loop, no early exit, no chunk tiling: the unit of work is
a whole ``[validator_chunk_size, history_length]`` row processed in one
``jit``.  Slashability checks read the post-update arrays, which is
order-safe because an attestation's own updates never touch the column its
check reads (min writes cols ``< s``, max writes cols ``> s``, the check
reads col ``s``); cross-attestation detections within a batch come out as a
superset of the reference's sequential ones, and every flagged pair is
re-confirmed host-side against the fetched record before a slashing is
emitted.

Storage is a linear window, newest epoch in the last column, encoded as
``target - epoch`` distances in uint16 exactly like the reference
(array.rs:14,84-99); distances are invariant under window shifts so epoch
advance is a roll + neutral fill rather than a rewrite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .config import MAX_DISTANCE

_INT_INF = np.int32(2**31 - 1)


def empty_row(validator_chunk_size: int, history_length: int):
    """Fresh (min_d, max_d) distance tiles with neutral elements.

    min neutral = MAX_DISTANCE (no attestation with source > e yet),
    max neutral = 0 (ref array.rs:211-213, 314-316).
    """
    k, n = validator_chunk_size, history_length
    return (
        np.full((k, n), MAX_DISTANCE, dtype=np.uint16),
        np.zeros((k, n), dtype=np.uint16),
    )


@functools.partial(jax.jit, static_argnames=("n",))
def _rows_update(min_d, max_d, delta, v_off, src, tgt, valid, cur, *, n):
    """Advance + batch-update + check for a stack of validator-chunk rows.

    min_d, max_d : uint16[R, K, N]   distance tiles (linear window layout)
    delta        : int32[R]          window advance per row (cur - stored_epoch)
    v_off, src, tgt : int32[R, P]    flattened (attestation x validator) pairs
    valid        : bool[R, P]        padding mask
    cur          : int32             current epoch (last column's epoch)

    Returns (new_min_d, new_max_d, min_target, max_target, min_flag, max_flag)
    where min_target/max_target are the per-pair post-update array reads used
    by the host to fetch the existing attestation on a flagged surround.
    """
    base = cur - (n - 1)
    e = base + jnp.arange(n, dtype=jnp.int32)  # epoch of each column

    # -- 1. window advance: shift left by delta, neutral-fill the new columns.
    j = jnp.arange(n, dtype=jnp.int32)

    def shift(d, dl, neutral):
        return jnp.where(j >= n - dl, neutral, jnp.roll(d, -dl, axis=-1))

    min_d = jax.vmap(lambda d, dl: shift(d, dl, jnp.uint16(MAX_DISTANCE)))(
        min_d, delta
    )
    max_d = jax.vmap(lambda d, dl: shift(d, dl, jnp.uint16(0)))(max_d, delta)

    old_min_t = e[None, None, :] + min_d.astype(jnp.int32)
    old_max_t = e[None, None, :] + max_d.astype(jnp.int32)
    k = min_d.shape[1]

    # -- 2. scatter + directional scan in the (int32) target domain.
    # Invalid / out-of-window columns are routed to index n, which scatter
    # mode="drop" discards.
    col_min = jnp.where(valid, src - 1 - base, n)
    col_min = jnp.where((col_min >= 0) & (col_min < n), col_min, n)
    col_max = jnp.where(valid, src + 1 - base, n)
    col_max = jnp.where((col_max >= 0) & (col_max < n), col_max, n)

    def scatter_min_row(vo, cm, t):
        z = jnp.full((k, n), _INT_INF, jnp.int32)
        return z.at[vo, cm].min(t, mode="drop")

    def scatter_max_row(vo, cm, t):
        z = jnp.full((k, n), -_INT_INF, jnp.int32)
        return z.at[vo, cm].max(t, mode="drop")

    scat_min = jax.vmap(scatter_min_row)(v_off, col_min, tgt)
    scat_max = jax.vmap(scatter_max_row)(v_off, col_max, tgt)

    # min_targets[e] aggregates attestations with source-1 >= e: suffix scan.
    suff_min = jax.lax.cummin(scat_min, axis=2, reverse=True)
    # max_targets[e] aggregates attestations with source+1 <= e: prefix scan.
    pref_max = jax.lax.cummax(scat_max, axis=2)

    new_min_t = jnp.minimum(old_min_t, suff_min)
    new_max_t = jnp.maximum(old_max_t, pref_max)

    new_min_d = jnp.clip(new_min_t - e[None, None, :], 0, MAX_DISTANCE).astype(
        jnp.uint16
    )
    new_max_d = jnp.clip(new_max_t - e[None, None, :], 0, MAX_DISTANCE).astype(
        jnp.uint16
    )

    # -- 3. post-update reads at each pair's own source column.
    col_s = jnp.clip(src - base, 0, n - 1)

    def read_row(d, vo, cs):
        return d[vo, cs]

    min_target = jax.vmap(read_row)(new_min_d, v_off, col_s).astype(
        jnp.int32
    ) + jax.vmap(lambda cs: e[cs])(col_s)
    max_target = jax.vmap(read_row)(new_max_d, v_off, col_s).astype(
        jnp.int32
    ) + jax.vmap(lambda cs: e[cs])(col_s)

    min_flag = valid & (tgt > min_target)
    max_flag = valid & (tgt < max_target)
    return new_min_d, new_max_d, min_target, max_target, min_flag, max_flag


def _bucket(x: int) -> int:
    b = 8
    while b < x:
        b *= 2
    return b


_ROW_GROUP = 8  # rows per kernel launch: keeps launch shapes stable and
#                 bounds the int32 working set (R x K x N x 4B per array)


def update_rows(rows, pairs, current_epoch: int, history_length: int):
    """Host wrapper: pad to shape buckets, run the kernel, unpad.

    rows  : list of (stored_epoch, min_d u16[K,N], max_d u16[K,N])
    pairs : list of list of (validator_offset, source, target) per row
    Returns (new_rows, results) where new_rows is [(min_d, max_d)] and
    results is per-row lists of (min_flag, min_target, max_flag, max_target)
    aligned with the input pairs.

    Launches are chunked to ``_ROW_GROUP`` rows so arbitrary batch spreads
    (every row dirty at mainnet) reuse one compiled shape per pair-bucket.
    """
    if not rows:
        return [], []
    if len(rows) > _ROW_GROUP:
        new_rows, results = [], []
        for off in range(0, len(rows), _ROW_GROUP):
            nr, res = update_rows(
                rows[off : off + _ROW_GROUP],
                pairs[off : off + _ROW_GROUP],
                current_epoch,
                history_length,
            )
            new_rows.extend(nr)
            results.extend(res)
        return new_rows, results
    n_real = len(rows)
    r = _ROW_GROUP if n_real > 1 else 1
    p = _bucket(max(1, max(len(ps) for ps in pairs)))
    if n_real < r:  # pad the last group to the fixed launch shape
        rows = list(rows) + [
            (current_epoch, rows[0][1], rows[0][2])
        ] * (r - n_real)
        pairs = list(pairs) + [[]] * (r - n_real)

    min_d = np.stack([row[1] for row in rows])
    max_d = np.stack([row[2] for row in rows])
    delta = np.asarray(
        [max(0, current_epoch - row[0]) for row in rows], dtype=np.int32
    )
    v_off = np.zeros((r, p), dtype=np.int32)
    src = np.zeros((r, p), dtype=np.int32)
    tgt = np.zeros((r, p), dtype=np.int32)
    valid = np.zeros((r, p), dtype=bool)
    for i, ps in enumerate(pairs):
        for q, (vo, s, t) in enumerate(ps):
            v_off[i, q], src[i, q], tgt[i, q], valid[i, q] = vo, s, t, True

    out = _rows_update(
        jnp.asarray(min_d),
        jnp.asarray(max_d),
        jnp.asarray(delta),
        jnp.asarray(v_off),
        jnp.asarray(src),
        jnp.asarray(tgt),
        jnp.asarray(valid),
        jnp.int32(current_epoch),
        n=history_length,
    )
    new_min, new_max, min_t, max_t, min_f, max_f = (np.asarray(o) for o in out)
    new_rows = [(new_min[i], new_max[i]) for i in range(n_real)]
    results = [
        [
            (bool(min_f[i, q]), int(min_t[i, q]), bool(max_f[i, q]), int(max_t[i, q]))
            for q in range(len(ps))
        ]
        for i, ps in enumerate(pairs[:n_real])
    ]
    return new_rows, results
