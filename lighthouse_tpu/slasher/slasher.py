"""Slasher orchestrator (ref slasher/src/slasher.rs).

Ingest queues -> validate/defer/drop -> dedup + persist records -> per-row
double-vote checks -> ONE fused device update per touched validator-chunk row
(arrays.py) -> host-side confirmation of flagged surrounds -> harvestable
slashings.

The reference walks each (attestation, validator) pair through sequential
chunk updates inside an LMDB transaction (slasher.rs:222-291); here every
touched row's full window is updated in a single batched kernel launch, and
only the flag confirmations (rare) do per-item host work.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from ..types.containers import AttestationData, ProposerSlashing
from .arrays import update_rows
from .config import SlasherConfig
from .db import SlasherDB


class Slasher:
    def __init__(self, store, types, config: SlasherConfig | None = None):
        self.config = config or SlasherConfig()
        self.config.validate()
        self.db = SlasherDB(store, self.config, types)
        self.types = types
        self._att_queue: list = []
        self._block_queue: list = []
        self._attester_slashings: dict[bytes, object] = {}
        self._proposer_slashings: dict[bytes, object] = {}
        self._lock = threading.Lock()

    # -- ingest (ref slasher.rs:87-95) ---------------------------------------

    def accept_attestation(self, indexed_attestation) -> None:
        with self._lock:
            self._att_queue.append(indexed_attestation)

    def accept_block_header(self, signed_header) -> None:
        with self._lock:
            self._block_queue.append(signed_header)

    # -- harvest (ref slasher.rs:69-77) --------------------------------------

    def get_attester_slashings(self) -> list:
        with self._lock:
            out = list(self._attester_slashings.values())
            self._attester_slashings.clear()
        return out

    def get_proposer_slashings(self) -> list:
        with self._lock:
            out = list(self._proposer_slashings.values())
            self._proposer_slashings.clear()
        return out

    # -- processing -----------------------------------------------------------

    def process_queued(self, current_epoch: int) -> dict:
        """Apply all queued blocks + attestations; returns batch stats
        (ref slasher.rs:98-107)."""
        with self._lock:
            blocks, self._block_queue = self._block_queue, []
            atts, self._att_queue = self._att_queue, []

        n_prop = self._process_blocks(blocks)
        stats = self._process_attestations(atts, current_epoch)
        stats["blocks_processed"] = len(blocks)
        stats["proposer_slashings"] = n_prop
        self.db.flush_rows()
        return stats

    def _process_blocks(self, blocks) -> int:
        found = 0
        for header in blocks:
            existing = self.db.check_or_insert_block_proposal(header)
            if existing is not None:
                slashing = ProposerSlashing(
                    signed_header_1=existing, signed_header_2=header
                )
                key = ProposerSlashing.hash_tree_root(slashing)
                with self._lock:
                    self._proposer_slashings.setdefault(key, slashing)
                found += 1
        return found

    def _validate(self, atts, current_epoch: int):
        """Split into (keep, deferred, dropped) — ref slasher.rs:336-368.

        Note the drop window is keyed on SOURCE epoch, matching the
        reference (slasher.rs:350-352): the min/max arrays only cover
        ``history_length`` epochs, so an attestation whose source has left
        the window cannot be recorded — bounded memory is the design
        trade-off, not an oversight.
        """
        keep, defer, dropped = [], [], 0
        for att in atts:
            src = int(att.data.source.epoch)
            tgt = int(att.data.target.epoch)
            if src > tgt or src + self.config.history_length <= current_epoch:
                dropped += 1
            elif tgt > current_epoch:
                defer.append(att)
            else:
                keep.append(att)
        return keep, defer, dropped

    def _process_attestations(self, atts, current_epoch: int) -> dict:
        keep, deferred, dropped = self._validate(atts, current_epoch)
        with self._lock:
            self._att_queue.extend(deferred)

        # Dedup identical indexed attestations, persist, and assign ids.
        batch = []  # (att, data_root, att_id)
        seen = set()
        t = self.types.IndexedAttestation
        for att in keep:
            root = t.hash_tree_root(att)
            if root in seen:
                continue
            seen.add(root)
            att_id = self.db.store_indexed_attestation(att, root=root)
            data_root = AttestationData.hash_tree_root(att.data)
            batch.append((att, data_root, att_id))

        n_double = self._check_double_votes(batch)
        n_surround = self._update_arrays(batch, current_epoch)
        return {
            "attestations_processed": len(atts),
            "attestations_valid": len(keep),
            "attestations_deferred": len(deferred),
            "attestations_dropped": dropped,
            "double_vote_slashings": n_double,
            "surround_slashings": n_surround,
        }

    def _emit_attester_slashing(self, surrounder, other) -> None:
        """attestation_1 must be the surrounding/existing attestation for the
        slashing to validate on chain (ref lib.rs:52-92)."""
        from ..utils.logging import get_logger

        get_logger("slasher").info(
            "Found attester slashing",
            target=int(other.data.target.epoch),
        )
        t = self.types.AttesterSlashing
        slashing = t(attestation_1=surrounder, attestation_2=other)
        key = t.hash_tree_root(slashing)
        with self._lock:
            self._attester_slashings.setdefault(key, slashing)

    def _check_double_votes(self, batch) -> int:
        found = 0
        for att, data_root, att_id in batch:
            for v in att.attesting_indices:
                existing = self.db.check_and_update_attester_record(
                    int(v), att, data_root, att_id
                )
                if existing is not None:
                    # double vote: existing first (ref lib.rs:63-77)
                    self._emit_attester_slashing(existing, att)
                    found += 1
        return found

    def _update_arrays(self, batch, current_epoch: int) -> int:
        """Group (attestation, validator) pairs by validator-chunk row, run
        the fused device update, confirm flags host-side."""
        by_row: dict[int, list] = defaultdict(list)  # row -> [(v_off, att)]
        for att, _, _ in batch:
            for v in att.attesting_indices:
                v = int(v)
                by_row[self.config.validator_chunk_index(v)].append(
                    (self.config.validator_offset(v), v, att)
                )
        if not by_row:
            return 0

        row_ids = sorted(by_row)
        rows, pairs = [], []
        for rid in row_ids:
            rows.append(self.db.load_row(rid))
            pairs.append(
                [
                    (vo, int(a.data.source.epoch), int(a.data.target.epoch))
                    for vo, _, a in by_row[rid]
                ]
            )
        new_rows, results = update_rows(
            rows, pairs, current_epoch, self.config.history_length
        )
        from ..utils.metrics import SLASHER_CHUNKS_UPDATED

        SLASHER_CHUNKS_UPDATED.inc(len(new_rows), array="minmax")

        found = 0
        for rid, (min_d, max_d), row_results in zip(row_ids, new_rows, results):
            self.db.store_row(rid, current_epoch, min_d, max_d)
            for (_, v, att), (min_f, min_t, max_f, max_t) in zip(
                by_row[rid], row_results
            ):
                found += self._confirm_surrounds(
                    v, att, min_f, min_t, max_f, max_t
                )
        return found

    def _confirm_surrounds(self, v, att, min_f, min_t, max_f, max_t) -> int:
        """Re-check a flagged pair against the fetched record; the flag alone
        can be a same-target double vote (ref array.rs:230-243 'Already
        DoubleVoted' branch), which the record path reports instead."""
        found = 0
        src = int(att.data.source.epoch)
        if min_f:
            try:
                existing = self.db.get_attestation_for_validator(v, min_t)
            except KeyError:
                existing = None
            if existing is not None and src < int(existing.data.source.epoch):
                self._emit_attester_slashing(att, existing)  # att surrounds
                found += 1
        if max_f:
            try:
                existing = self.db.get_attestation_for_validator(v, max_t)
            except KeyError:
                existing = None
            if existing is not None and int(existing.data.source.epoch) < src:
                self._emit_attester_slashing(existing, att)  # att surrounded
                found += 1
        return found

    def prune_database(self, current_epoch: int, slots_per_epoch: int) -> int:
        return self.db.prune(current_epoch, slots_per_epoch)
