"""Device-resident slasher engine: whole-network surveillance as one sweep.

Three layers, host-side glue only (the fused kernel lives in ``kernels.py``
and is imported ONLY on the device path, so the ``numpy`` backend never
pays a jax import):

* ``sweep_numpy`` — the field-for-field numpy twin of ``kernels.sweep``:
  same signature, same outputs, same window/scatter/scan/flag semantics.
  It is the parity oracle, the ``LIGHTHOUSE_SLASHER_BACKEND=numpy`` serving
  path, and the demotion target when the device faults.
* ``SpanStore`` — the ``[n_validators, history_length]`` min/max distance +
  vote-tag planes, device-resident across ticks. Epoch advance is a roll +
  neutral fill INSIDE the jitted sweep (traced delta: zero steady-state
  recompiles across epoch rolls). Runs under the ``slasher_device`` fault
  domain: a faulted sweep restores the last host checkpoint and replays the
  pair journal through the numpy twin — demotion never drops evidence —
  and the supervisor's probation logic re-promotes the device planes later.
  Optional data-parallel sharding over the validator axis
  (``LIGHTHOUSE_MESH_DEVICES`` via ``validator_sharding()``).
* ``EngineSlasher`` — the serving surface (same edges as the seed
  ``Slasher``: accept / process_queued / harvest / prune) built on the
  span store. The kernel only flags; every flagged pair is re-confirmed
  against the fetched attestation record before an ``AttesterSlashing`` is
  emitted ("One For All": the aggregate proves the set signed, the record
  proves which prior vote conflicts), so a demoted or even faulted sweep
  can never emit an unconfirmed slashing. Intake is bounded in PAIRS; any
  evidence shed (overflow, exhausted retries) is counted on the
  ``slasher_surveillance_gap`` metric — loud, never silent.
"""

from __future__ import annotations

import threading

import numpy as np

from ..utils.metrics import (
    SLASHER_PAIRS_SWEPT,
    SLASHER_SURVEILLANCE_GAP,
)
from .config import MAX_DISTANCE, SlasherConfig

_INT_INF = np.int64(2**31 - 1)
_VOTE_NONE = np.uint32(0xFFFFFFFF)
_MAX_EPOCH = 1 << 24  # kernels.MAX_EPOCH without the jax import


# =============================================================================
# numpy twin of kernels.sweep (field-for-field)
# =============================================================================


def empty_planes_np(n_validators_pad: int, history_length: int):
    """Twin of ``kernels.empty_planes`` (jax-free import path)."""
    v, n = n_validators_pad, history_length
    return (
        np.full((v, n), MAX_DISTANCE, dtype=np.uint16),
        np.zeros((v, n), dtype=np.uint16),
        np.zeros((v, n), dtype=np.uint32),
    )


def sweep_numpy(min_d, max_d, vote_h, delta, vidx, src, tgt, vh, valid, cur, n):
    """Pure-numpy twin of ``kernels.sweep`` — identical signature (``n``
    positional instead of jit-static) and identical outputs. Pure function:
    input planes are never mutated."""
    dl = int(min(max(int(delta), 0), n))
    if dl:
        min_d = np.roll(min_d, -dl, axis=1)
        max_d = np.roll(max_d, -dl, axis=1)
        vote_h = np.roll(vote_h, -dl, axis=1)
        min_d[:, n - dl:] = MAX_DISTANCE
        max_d[:, n - dl:] = 0
        vote_h[:, n - dl:] = 0
    else:
        min_d, max_d, vote_h = min_d.copy(), max_d.copy(), vote_h.copy()

    base = int(cur) - (n - 1)
    e = base + np.arange(n, dtype=np.int64)
    old_min_t = e[None, :] + min_d.astype(np.int64)
    old_max_t = e[None, :] + max_d.astype(np.int64)
    v_cap = min_d.shape[0]
    vidx = np.asarray(vidx, dtype=np.int64)
    src = np.asarray(src, dtype=np.int64)
    tgt = np.asarray(tgt, dtype=np.int64)
    vh = np.asarray(vh, dtype=np.uint32)
    valid = np.asarray(valid, dtype=bool)
    vi = np.clip(vidx, 0, v_cap - 1)

    def hits(col):
        return np.nonzero(valid & (col >= 0) & (col < n))[0]

    col_min = src - 1 - base
    col_max = src + 1 - base
    col_t = tgt - base

    scat_min = np.full((v_cap, n), _INT_INF, np.int64)
    k = hits(col_min)
    np.minimum.at(scat_min, (vi[k], col_min[k]), tgt[k])
    scat_max = np.full((v_cap, n), -_INT_INF, np.int64)
    k = hits(col_max)
    np.maximum.at(scat_max, (vi[k], col_max[k]), tgt[k])

    suff_min = np.minimum.accumulate(scat_min[:, ::-1], axis=1)[:, ::-1]
    pref_max = np.maximum.accumulate(scat_max, axis=1)
    new_min_t = np.minimum(old_min_t, suff_min)
    new_max_t = np.maximum(old_max_t, pref_max)
    new_min_d = np.clip(new_min_t - e[None, :], 0, MAX_DISTANCE).astype(np.uint16)
    new_max_d = np.clip(new_max_t - e[None, :], 0, MAX_DISTANCE).astype(np.uint16)

    col_t_c = np.clip(col_t, 0, n - 1)
    in_w = (col_t >= 0) & (col_t < n)
    pre = np.where(in_w, vote_h[vi, col_t_c], np.uint32(0))
    smin = np.full((v_cap, n), _VOTE_NONE, np.uint32)
    k = hits(col_t)
    np.minimum.at(smin, (vi[k], col_t[k]), vh[k])
    smax = np.zeros((v_cap, n), np.uint32)
    np.maximum.at(smax, (vi[k], col_t[k]), vh[k])
    new_vote_h = np.where(
        vote_h != 0, vote_h, np.where(smin != _VOTE_NONE, smin, np.uint32(0))
    )
    dbl_flag = valid & in_w & (
        ((pre != 0) & (pre != vh)) | (smin[vi, col_t_c] != smax[vi, col_t_c])
    )

    col_s = np.clip(src - base, 0, n - 1)
    min_target = new_min_d[vi, col_s].astype(np.int64) + e[col_s]
    max_target = new_max_d[vi, col_s].astype(np.int64) + e[col_s]
    min_flag = valid & (tgt > min_target)
    max_flag = valid & (tgt < max_target)
    return (
        new_min_d, new_max_d, new_vote_h,
        min_target.astype(np.int32), max_target.astype(np.int32),
        min_flag, max_flag, dbl_flag,
    )


def validator_sharding():
    """NamedSharding over a ``validators`` mesh axis when the serving mesh
    is on (``LIGHTHOUSE_MESH_DEVICES``), else None — the span planes then
    live data-parallel over the device mesh exactly like the PR-10 sharded
    registry mirror."""
    from ..bls import mesh as bls_mesh

    n = bls_mesh.serving_mesh_size()
    if n <= 1:
        return None
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("validators",))
    return NamedSharding(mesh, PartitionSpec("validators"))


# =============================================================================
# the device-resident span store
# =============================================================================


class SpanStore:
    """Whole-registry span planes with backend seam + fault-domain glue.

    Planes live device-resident across ticks on the device backend (host
    checkpoints every ``checkpoint_every`` sweeps + a pair journal make
    demotion lossless); on the numpy backend they are plain host arrays.
    One ``apply`` = one fused sweep (window advance included).
    """

    def __init__(
        self,
        history_length: int,
        use_device: bool | None = None,
        sharding=None,
        checkpoint_every: int = 32,
        pair_floor: int = 256,
        validator_floor: int = 256,
    ):
        # the distance encoding stores at most n-1 (saturating at the
        # MAX_DISTANCE sentinel like the reference), so the full reference
        # bound MAX_HISTORY_LENGTH = 65536 is representable
        if not 0 < history_length <= MAX_DISTANCE + 1:
            raise ValueError(f"span store: bad history_length {history_length}")
        if use_device is None:
            from . import device_backend_active

            use_device = device_backend_active()
        self.n_hist = history_length
        self.use_device = bool(use_device)
        self.sharding = sharding
        self.checkpoint_every = max(1, checkpoint_every)
        self.pair_floor = pair_floor
        self.validator_floor = validator_floor
        self.mode = "device" if self.use_device else "host"
        self.n = 0          # validators covered so far
        self.n_pad = 0      # plane height (power-of-two bucket)
        self.epoch = 0      # epoch of the planes' last column
        self.host = None    # authoritative planes (host mode) / checkpoint
        self.ckpt_epoch = 0
        self.dev = None     # live device planes (device mode)
        self.journal: list = []  # (vidx, src, tgt, vh, valid, epoch) since ckpt
        # counters (single-threaded caller: the slasher tick)
        self.sweeps = 0
        self.pairs_swept = 0
        self.demotions = 0
        self.promotions = 0
        self.checkpoints = 0

    # -- capacity ----------------------------------------------------------

    def ensure_capacity(self, n_validators: int) -> None:
        n = max(int(n_validators), 1)
        if self.host is not None and n <= self.n_pad:
            self.n = max(self.n, n)
            return
        new_pad = _bucket(n, self.validator_floor)
        planes = empty_planes_np(new_pad, self.n_hist)
        if self.host is not None:
            if self.mode == "device":
                # rare: sync device truth before regrow. A device fault here
                # must demote (checkpoint + journal replay reconstruct the
                # host truth losslessly), never escape unsupervised
                try:
                    self._checkpoint()
                except Exception as e:  # noqa: BLE001 — device fault
                    from ..resilience import faults

                    faults.record_fault(
                        "slasher.checkpoint", e, domain="slasher_device"
                    )
                    self._demote_and_replay()
            for new, old in zip(planes, self.host):
                new[: self.n_pad] = old
        self.host = list(planes)
        self.n = max(self.n, n)
        self.n_pad = new_pad
        self.ckpt_epoch = self.epoch
        self.journal.clear()
        if self.mode == "device" and not self._try_upload():
            self.mode = "host"
            self.demotions += 1

    # -- device plumbing ---------------------------------------------------

    def _put(self, arr):
        import jax

        if self.sharding is not None:
            return jax.device_put(arr, self.sharding)
        return jax.device_put(arr)

    def _upload(self) -> None:
        self.dev = [self._put(a) for a in self.host]
        from ..utils import metrics

        metrics.SLASHER_SPAN_PLANE_BYTES.set(
            sum(a.nbytes for a in self.host)
        )

    def _try_upload(self) -> bool:
        """Upload with the fault recorded instead of raised (regrow /
        promotion paths: the host planes stay authoritative on failure)."""
        try:
            self._upload()
            return True
        except Exception as e:  # noqa: BLE001 — device fault
            from ..resilience import faults

            faults.record_fault("slasher.upload", e, domain="slasher_device")
            self.dev = None
            return False

    def _checkpoint(self) -> None:
        """Adopt the device planes as the host checkpoint (device->host
        sync); clears the journal. Raises on a device fault — callers
        demote-and-replay, so a failed checkpoint loses nothing."""
        self.host = [np.asarray(a).copy() for a in self.dev]
        self.ckpt_epoch = self.epoch
        self.journal.clear()
        self.checkpoints += 1

    def _sup(self):
        from ..resilience import slasher_supervisor

        return slasher_supervisor()

    def _demote_and_replay(self) -> None:
        """Device planes are no longer trusted: restore the last host
        checkpoint and replay the journaled pair batches through the numpy
        twin. Every journaled batch is reconstructed exactly — demotion
        never drops evidence."""
        self.mode = "host"
        self.dev = None
        self.demotions += 1
        planes = [a.copy() for a in self.host]
        epoch = self.ckpt_epoch
        for vidx, src, tgt, vh, valid, ep in self.journal:
            out = sweep_numpy(
                planes[0], planes[1], planes[2],
                max(0, ep - epoch), vidx, src, tgt, vh, valid, ep, self.n_hist,
            )
            planes = list(out[:3])
            epoch = ep
        self.host = planes
        self.ckpt_epoch = epoch
        self.journal.clear()

    def _promote(self) -> bool:
        """Try to move the host planes back onto the device (probation
        probe / recovery). Returns True when the store is in device mode."""
        self._checkpointless_sync()
        if not self._try_upload():
            return False
        self.mode = "device"
        self.promotions += 1
        return True

    def _checkpointless_sync(self) -> None:
        self.ckpt_epoch = self.epoch
        self.journal.clear()

    # -- the sweep ---------------------------------------------------------

    def _pad_batch(self, vidx, src, tgt, vh):
        n_real = len(vidx)
        p = _bucket(max(1, n_real), self.pair_floor)
        pv = np.zeros(p, dtype=np.int32)
        ps = np.zeros(p, dtype=np.int32)
        pt = np.zeros(p, dtype=np.int32)
        ph = np.zeros(p, dtype=np.uint32)
        pm = np.zeros(p, dtype=bool)
        pv[:n_real] = vidx
        ps[:n_real] = src
        pt[:n_real] = tgt
        ph[:n_real] = vh
        pm[:n_real] = True
        return pv, ps, pt, ph, pm

    def _device_thunk(self, pv, ps, pt, ph, pm, delta, cur):
        import jax.numpy as jnp

        from .kernels import sweep

        out = sweep(
            self.dev[0], self.dev[1], self.dev[2],
            jnp.int32(delta),
            jnp.asarray(pv), jnp.asarray(ps), jnp.asarray(pt),
            jnp.asarray(ph), jnp.asarray(pm), jnp.int32(cur),
            n=self.n_hist,
        )
        # materialize INSIDE the supervised region: an async device fault
        # must surface here, before any state is adopted
        pair_res = tuple(np.asarray(o) for o in out[3:])
        for o in out[:3]:
            o.block_until_ready()
        return out[:3], pair_res

    def apply(self, vidx, src, tgt, vh, current_epoch: int) -> dict:
        """One fused sweep: window advance + batch update + candidate
        flags. Pair arrays are flattened (attestation x validator) rows;
        returns per-pair ``min_target/max_target/min_flag/max_flag/
        dbl_flag`` numpy arrays trimmed to the input length."""
        current_epoch = int(current_epoch)
        if current_epoch >= _MAX_EPOCH:
            raise ValueError(f"slasher: epoch {current_epoch} out of range")
        n_real = len(vidx)
        if n_real:
            self.ensure_capacity(int(np.max(vidx)) + 1)
        elif self.host is None:
            self.ensure_capacity(1)
        cur = max(current_epoch, self.epoch)
        delta = cur - self.epoch
        pv, ps, pt, ph, pm = self._pad_batch(vidx, src, tgt, vh)

        pair_res = None
        if self.use_device:
            sup = self._sup()
            if self.mode == "host" and sup.device_allowed():
                self._promote()
            if self.mode == "device":
                from ..resilience import SupervisedFault

                try:
                    planes, pair_res = sup.run(
                        "slasher.sweep",
                        lambda: self._device_thunk(pv, ps, pt, ph, pm, delta, cur),
                    )
                except SupervisedFault:
                    self._demote_and_replay()
                else:
                    self.dev = list(planes)
                    self.epoch = cur
                    self.journal.append((pv, ps, pt, ph, pm, cur))
                    if len(self.journal) >= self.checkpoint_every:
                        try:
                            self._checkpoint()
                        except Exception as e:  # noqa: BLE001 — device fault
                            from ..resilience import faults

                            faults.record_fault(
                                "slasher.checkpoint", e, domain="slasher_device"
                            )
                            # journal already holds this sweep: the replay
                            # reconstructs it — nothing is lost
                            self._demote_and_replay()
            if pair_res is None:
                sup.note_fallback(rung="numpy")
        if pair_res is None:
            out = sweep_numpy(
                self.host[0], self.host[1], self.host[2],
                delta, pv, ps, pt, ph, pm, cur, self.n_hist,
            )
            self.host = list(out[:3])
            self.ckpt_epoch = cur
            pair_res = out[3:]
            self.epoch = cur
        self.sweeps += 1
        self.pairs_swept += n_real
        SLASHER_PAIRS_SWEPT.inc(n_real, backend=self.mode)
        names = ("min_target", "max_target", "min_flag", "max_flag", "dbl_flag")
        return {k: np.asarray(v)[:n_real] for k, v in zip(names, pair_res)}

    # -- introspection -----------------------------------------------------

    def planes(self):
        """Current (min_d, max_d, vote_h) as host numpy arrays (parity
        tests / debugging; device mode syncs)."""
        if self.mode == "device":
            return tuple(np.asarray(a).copy() for a in self.dev)
        return tuple(a.copy() for a in self.host)

    def stats(self) -> dict:
        return {
            "backend": "device" if self.use_device else "numpy",
            "mode": self.mode,
            "n_validators": self.n,
            "n_pad": self.n_pad,
            "history_length": self.n_hist,
            "epoch": self.epoch,
            "sweeps": self.sweeps,
            "pairs_swept": self.pairs_swept,
            "demotions": self.demotions,
            "promotions": self.promotions,
            "checkpoints": self.checkpoints,
            "journal_depth": len(self.journal),
        }


def _bucket(x: int, floor: int = 1) -> int:
    b = max(1, floor)
    while b < x:
        b *= 2
    return b


# =============================================================================
# the engine-backed slasher (seed-Slasher surface)
# =============================================================================


class EngineSlasher:
    """Slasher on the device-resident span store. Same edges as the seed
    ``Slasher`` (accept_attestation / accept_block_header / process_queued /
    get_*_slashings / prune_database), so ``SlasherService`` drives either.

    Record state is an in-memory columnar index — ``{target_epoch: {v:
    att_id}}`` plus the attestation table — pruned with the window; the
    vote plane is its device shadow. Host work per batch is O(pairs) dict
    upkeep + O(flags) confirmation; all detection math is the one sweep.
    """

    MAX_BATCH_RETRIES = 3

    def __init__(
        self,
        store=None,
        types=None,
        config: SlasherConfig | None = None,
        backend: str | None = None,
        sharding=None,
        intake_capacity_pairs: int = 1 << 17,
        checkpoint_every: int = 32,
        validator_floor: int = 256,
    ):
        self.config = config or SlasherConfig()
        self.config.validate()
        self.store = store  # KV store for checkpoint persistence (optional)
        self.types = types
        use_device = None
        if backend is not None:
            if backend not in ("auto", "device", "numpy"):
                raise ValueError(f"unknown slasher backend {backend!r}")
            use_device = {"device": True, "numpy": False}.get(backend)
        self.span = SpanStore(
            self.config.history_length,
            use_device=use_device,
            sharding=sharding,
            checkpoint_every=checkpoint_every,
            validator_floor=validator_floor,
        )
        self.intake_capacity_pairs = intake_capacity_pairs
        self._att_queue: list = []
        self._queued_pairs = 0
        self._block_queue: list = []
        self._lock = threading.Lock()
        self._attester_slashings: dict[bytes, object] = {}
        self._proposer_slashings: dict[bytes, object] = {}
        # record index: the host truth behind the vote plane's candidates
        self._atts: dict[int, object] = {}          # att_id -> IndexedAttestation
        self._att_root: dict[int, bytes] = {}       # att_id -> data root
        self._root_to_id: dict[bytes, int] = {}     # att htr -> att_id
        self._id_to_root: dict[int, bytes] = {}     # att_id -> att htr
        self._records: dict[int, dict[int, int]] = {}  # target -> {v: att_id}
        # EVERY indexed attestation by target epoch — including ones whose
        # record slots were all already claimed — so pruning can never leak
        self._ids_by_target: dict[int, set[int]] = {}
        self._proposals: dict[tuple, object] = {}   # (slot, proposer) -> header
        self._next_id = 1
        self._batch_retries = 0
        self.shed_pairs = 0

    # -- ingest (seed surface) ---------------------------------------------

    def accept_attestation(self, indexed_attestation) -> None:
        k = max(1, len(indexed_attestation.attesting_indices))
        with self._lock:
            if self._queued_pairs + k > self.intake_capacity_pairs:
                self.shed_pairs += k
                SLASHER_SURVEILLANCE_GAP.inc(k, reason="intake_overflow")
                return
            self._att_queue.append(indexed_attestation)
            self._queued_pairs += k

    def accept_block_header(self, signed_header) -> None:
        with self._lock:
            self._block_queue.append(signed_header)

    # -- harvest -----------------------------------------------------------

    def get_attester_slashings(self) -> list:
        with self._lock:
            out = list(self._attester_slashings.values())
            self._attester_slashings.clear()
        return out

    def get_proposer_slashings(self) -> list:
        with self._lock:
            out = list(self._proposer_slashings.values())
            self._proposer_slashings.clear()
        return out

    # -- processing --------------------------------------------------------

    def process_queued(self, current_epoch: int) -> dict:
        with self._lock:
            blocks, self._block_queue = self._block_queue, []
            atts, self._att_queue = self._att_queue, []
            self._queued_pairs = 0

        n_prop = self._process_blocks(blocks)
        try:
            stats = self._process_attestations(atts, current_epoch)
            self._batch_retries = 0
        except Exception as e:  # noqa: BLE001 — evidence is never silently lost
            from ..resilience import faults

            faults.record_fault(
                "slasher.process", e, domain="slasher_device"
            )
            self._batch_retries += 1
            with self._lock:
                # deferred attestations were already re-queued inside
                # _process_attestations — re-prepend only what is not
                # queued yet, or pair accounting inflates and sheds
                # honest intake early
                queued = {id(a) for a in self._att_queue}
                fresh = [a for a in atts if id(a) not in queued]
                n_pairs = sum(len(a.attesting_indices) for a in fresh)
                if self._batch_retries <= self.MAX_BATCH_RETRIES:
                    self._att_queue[:0] = fresh  # retried ahead of new work
                    self._queued_pairs += n_pairs
                else:
                    self.shed_pairs += n_pairs
            if self._batch_retries > self.MAX_BATCH_RETRIES:
                SLASHER_SURVEILLANCE_GAP.inc(n_pairs, reason="batch_exhausted")
                self._batch_retries = 0
            stats = {
                "attestations_processed": len(atts),
                "attestations_valid": 0,
                "attestations_deferred": 0,
                "attestations_dropped": 0,
                "double_vote_slashings": 0,
                "surround_slashings": 0,
                "error": str(e),
            }
        stats["blocks_processed"] = len(blocks)
        stats["proposer_slashings"] = n_prop
        return stats

    def _process_blocks(self, blocks) -> int:
        from ..types.containers import ProposerSlashing

        found = 0
        for header in blocks:
            # per-header isolation: one malformed header must not discard
            # the rest of the tick's evidence (the queues were already
            # popped); the loss is one header, recorded and counted
            try:
                msg = header.message
                key = (int(msg.slot), int(msg.proposer_index))
                existing = self._proposals.get(key)
                if existing is None:
                    self._proposals[key] = header
                    continue
                if existing == header:
                    continue
                slashing = ProposerSlashing(
                    signed_header_1=existing, signed_header_2=header
                )
                root = ProposerSlashing.hash_tree_root(slashing)
            except Exception as e:  # noqa: BLE001 — loud, never silent
                from ..resilience import faults

                faults.record_fault(
                    "slasher.block", e, domain="slasher_device"
                )
                SLASHER_SURVEILLANCE_GAP.inc(1, reason="block_error")
                continue
            with self._lock:
                self._proposer_slashings.setdefault(root, slashing)
            found += 1
        return found

    def _validate(self, atts, current_epoch: int):
        """(keep, deferred, dropped) — drop window keyed on SOURCE epoch
        like the seed / reference (slasher.rs:350-352)."""
        keep, defer, dropped = [], [], 0
        for att in atts:
            src = int(att.data.source.epoch)
            tgt = int(att.data.target.epoch)
            if src > tgt or src + self.config.history_length <= current_epoch:
                dropped += 1
            elif tgt > current_epoch:
                defer.append(att)
            else:
                keep.append(att)
        return keep, defer, dropped

    def _dedup(self, keep) -> list:
        """Read-only dedup against the index and within the batch. Returns
        [(att, att_root, data_root)] — NOTHING is committed yet, so a
        faulted sweep can re-queue the batch and a later retry re-processes
        it in full (evidence is never silently skipped)."""
        from ..types.containers import AttestationData

        t = self.types.IndexedAttestation
        batch, seen = [], set()
        for att in keep:
            root = t.hash_tree_root(att)
            if root in self._root_to_id or root in seen:
                continue
            seen.add(root)
            batch.append((att, root, AttestationData.hash_tree_root(att.data)))
        return batch

    def _commit(self, batch) -> None:
        """Adopt a swept batch into the record index (ids, record slots,
        prune index). Runs AFTER the sweep succeeded — the transactional
        commit point of one tick."""
        for att, root, data_root in batch:
            att_id = self._next_id
            self._next_id += 1
            self._root_to_id[root] = att_id
            self._id_to_root[att_id] = root
            self._atts[att_id] = att
            self._att_root[att_id] = data_root
            tgt = int(att.data.target.epoch)
            self._ids_by_target.setdefault(tgt, set()).add(att_id)
            rec = self._records.setdefault(tgt, {})
            for v in att.attesting_indices:
                rec.setdefault(int(v), att_id)

    @staticmethod
    def _vote_tag(data_root: bytes) -> int:
        """Nonzero 32-bit tag of an attestation-data root (the vote plane's
        cell value; full roots are compared at confirmation time)."""
        return int.from_bytes(data_root[:4], "big") or 1

    def _process_attestations(self, atts, current_epoch: int) -> dict:
        keep, deferred, dropped = self._validate(atts, current_epoch)
        if deferred:
            with self._lock:
                self._att_queue.extend(deferred)
                self._queued_pairs += sum(
                    len(a.attesting_indices) for a in deferred
                )

        batch = self._dedup(keep)

        # flatten (attestation x validator) pairs for the one fused sweep
        vidx, src, tgt, vh, owner = [], [], [], [], []
        for att, _, data_root in batch:
            s = int(att.data.source.epoch)
            t = int(att.data.target.epoch)
            h = self._vote_tag(data_root)
            for v in att.attesting_indices:
                vidx.append(int(v))
                src.append(s)
                tgt.append(t)
                vh.append(h)
                owner.append((att, data_root))

        n_double = n_surround = 0
        if vidx:
            res = self.span.apply(
                np.asarray(vidx, dtype=np.int64),
                np.asarray(src, dtype=np.int64),
                np.asarray(tgt, dtype=np.int64),
                np.asarray(vh, dtype=np.uint32),
                current_epoch,
            )
            # commit BETWEEN sweep and confirmation: confirmation looks up
            # this batch's own records (intra-batch doubles/surrounds)
            self._commit(batch)
            n_double, n_surround = self._confirm(
                owner, vidx, src, tgt, res
            )
        elif self.span.host is not None or self.span.dev is not None:
            # no pairs this tick: still roll the window forward
            self.span.apply(
                np.asarray([], dtype=np.int64), np.asarray([], dtype=np.int64),
                np.asarray([], dtype=np.int64), np.asarray([], dtype=np.uint32),
                current_epoch,
            )
        return {
            "attestations_processed": len(atts),
            "attestations_valid": len(keep),
            "attestations_deferred": len(deferred),
            "attestations_dropped": dropped,
            "double_vote_slashings": n_double,
            "surround_slashings": n_surround,
        }

    # -- confirmation (the kernel flags, the record proves) ----------------

    def _lookup(self, v: int, target_epoch: int):
        att_id = self._records.get(int(target_epoch), {}).get(int(v))
        if att_id is None:
            return None, None
        return self._atts.get(att_id), self._att_root.get(att_id)

    def _emit(self, first, second) -> None:
        """attestation_1 must be the surrounding/existing attestation for
        the slashing to validate on chain (ref lib.rs:52-92)."""
        from ..utils.logging import get_logger

        get_logger("slasher").info(
            "Found attester slashing",
            target=int(second.data.target.epoch),
        )
        t = self.types.AttesterSlashing
        slashing = t(attestation_1=first, attestation_2=second)
        key = t.hash_tree_root(slashing)
        with self._lock:
            self._attester_slashings.setdefault(key, slashing)

    def _confirm(self, owner, vidx, src, tgt, res) -> tuple[int, int]:
        """Re-check every flagged pair against the fetched record. A flag
        alone is only a candidate (batch supersets, same-target doubles on
        the surround planes, tag conflicts): the record comparison is what
        authorizes emission."""
        n_double = n_surround = 0
        flagged = np.nonzero(
            res["min_flag"] | res["max_flag"] | res["dbl_flag"]
        )[0]
        for q in map(int, flagged):
            try:
                d, sr = self._confirm_pair(owner, vidx, src, tgt, res, q)
            except Exception as e:  # noqa: BLE001 — one bad pair must not
                # kill the rest of the batch's confirmations, and (since
                # the batch is committed by now) a retry would skip it —
                # count the loss loudly instead
                from ..resilience import faults

                faults.record_fault(
                    "slasher.confirm", e, domain="slasher_device"
                )
                SLASHER_SURVEILLANCE_GAP.inc(1, reason="confirm_error")
                continue
            n_double += d
            n_surround += sr
        return n_double, n_surround

    def _confirm_pair(self, owner, vidx, src, tgt, res, q) -> tuple[int, int]:
        n_double = n_surround = 0
        att, data_root = owner[q]
        v = vidx[q]
        s = src[q]
        if res["dbl_flag"][q]:
            existing, existing_root = self._lookup(v, tgt[q])
            if (
                existing is not None
                and existing_root != data_root
                and int(existing.data.target.epoch) == tgt[q]
            ):
                self._emit(existing, att)  # double: existing first
                n_double += 1
        if res["min_flag"][q]:
            existing, _ = self._lookup(v, int(res["min_target"][q]))
            if existing is not None and s < int(existing.data.source.epoch):
                self._emit(att, existing)  # att surrounds existing
                n_surround += 1
        if res["max_flag"][q]:
            existing, _ = self._lookup(v, int(res["max_target"][q]))
            if existing is not None and int(existing.data.source.epoch) < s:
                self._emit(existing, att)  # att is surrounded
                n_surround += 1
        return n_double, n_surround

    # -- pruning -----------------------------------------------------------

    def prune_database(self, current_epoch: int, slots_per_epoch: int) -> int:
        min_epoch = max(0, current_epoch - self.config.history_length + 1)
        dropped = 0
        # keyed on the full per-target id index, not the record slots: an
        # attestation whose slots were all claimed by an earlier one must
        # still age out of _atts/_root_to_id with its window
        for epoch in [e for e in self._ids_by_target if e < min_epoch]:
            for att_id in self._ids_by_target.pop(epoch):
                self._atts.pop(att_id, None)
                self._att_root.pop(att_id, None)
                root = self._id_to_root.pop(att_id, None)
                if root is not None:
                    self._root_to_id.pop(root, None)
                dropped += 1
            self._records.pop(epoch, None)
        min_slot = min_epoch * slots_per_epoch
        for key in [k for k in self._proposals if k[0] < min_slot]:
            del self._proposals[key]
            dropped += 1
        return dropped

    # -- persistence (restart-from-disk, ISSUE 12) -------------------------

    PERSIST_KEY = b"engine_v1"

    def persist(self, store=None) -> bool:
        """Checkpoint the record index + span planes into the KV store as
        ONE atomic write (``SlasherMeta`` column, the reference's slasher
        database tables collapsed into a compressed document).

        This closes the restart window the ROADMAP flagged: pre-restart
        votes used to live only in memory, so a determined equivocator
        could vote once, wait for a restart, and vote again unseen. With
        the checkpoint, the whole surveillance window (records + distance
        planes + pending, unharvested slashings) survives a kill at any
        persistence barrier. Planes are dense (8 B/validator-epoch before
        compression) — the same sizing note as ``make_slasher``'s window
        knob applies.
        """
        import base64
        import json as _json
        import zlib as _zlib

        store = store if store is not None else self.store
        if store is None:
            return False
        if self.span.host is None and self.span.dev is None:
            # nothing swept yet (a service tick before the first batch):
            # there are no planes to checkpoint, and treating the None as
            # a device fault would demote a healthy engine
            return False
        # snapshot the index under the intake lock...
        with self._lock:
            t_att = self.types.IndexedAttestation
            atts = {
                str(i): t_att.encode(a).hex() for i, a in self._atts.items()
            }
            records = {
                str(t): {str(v): i for v, i in rec.items()}
                for t, rec in self._records.items()
            }
            proposals = [
                type(h).encode(h).hex() for h in self._proposals.values()
            ]
            att_slashings = [
                type(s).encode(s).hex()
                for s in self._attester_slashings.values()
            ]
            prop_slashings = [
                type(s).encode(s).hex()
                for s in self._proposer_slashings.values()
            ]
            next_id = self._next_id
        # ...but sync the span planes OUTSIDE it (device mode materializes
        # the device arrays — a device call under the intake lock would
        # stall the gossip observers)
        try:
            planes = self.span.planes()
        except Exception as e:  # noqa: BLE001 — device fault during sync:
            # demote-and-replay reconstructs the host truth losslessly
            from ..resilience import faults

            faults.record_fault("slasher.checkpoint", e, domain="slasher_device")
            self.span._demote_and_replay()
            planes = self.span.planes()
        doc = {
            "version": 1,
            "history_length": self.config.history_length,
            "next_id": next_id,
            "atts": atts,
            "records": records,
            "proposals": proposals,
            "attester_slashings": att_slashings,
            "proposer_slashings": prop_slashings,
            "span": {
                "n": self.span.n,
                "epoch": self.span.epoch,
                "planes": [
                    {
                        "dtype": str(p.dtype),
                        "shape": list(p.shape),
                        "data": base64.b64encode(p.tobytes()).decode(),
                    }
                    for p in planes
                ],
            },
        }
        blob = _zlib.compress(_json.dumps(doc).encode(), 1)
        from ..resilience.crashpoints import maybe_crash
        from ..store.kv import DBColumn

        maybe_crash("persist.slasher", owner=getattr(store, "owner", None))
        store.put(DBColumn.SlasherMeta, self.PERSIST_KEY, blob)
        return True

    def restore(self, store=None) -> bool:
        """Rehydrate the record index + span planes from a ``persist``
        checkpoint. Derived maps (data roots, id<->root, per-target ids)
        are recomputed from the decoded attestations, so the checkpoint
        carries no redundant — and thus no possibly-inconsistent — state.
        Returns False (untouched engine) when no/incompatible checkpoint
        exists."""
        import base64
        import json as _json
        import zlib as _zlib

        import numpy as _np

        from ..store.kv import DBColumn
        from ..types.containers import AttestationData

        store = store if store is not None else self.store
        if store is None:
            return False
        blob = store.get(DBColumn.SlasherMeta, self.PERSIST_KEY)
        if blob is None:
            return False
        try:
            doc = _json.loads(_zlib.decompress(blob))
        except Exception:  # noqa: BLE001 — corrupt checkpoint: fresh start
            from ..utils.logging import get_logger

            get_logger("slasher").warning("Slasher checkpoint unreadable")
            return False
        if doc.get("history_length") != self.config.history_length:
            # window resize invalidates the planes' distance encoding
            return False
        t_att = self.types.IndexedAttestation
        from ..types.containers import ProposerSlashing, SignedBeaconBlockHeader

        # Decode the WHOLE checkpoint into locals before touching any engine
        # state: one record failing to decode (schema drift, truncated blob)
        # must leave the engine untouched per the contract above, not
        # half-populated with ids no record/plane state references.
        try:
            atts = {}
            for sid, hexed in doc["atts"].items():
                att_id = int(sid)
                att = t_att.decode(bytes.fromhex(hexed))
                atts[att_id] = (
                    att,
                    t_att.hash_tree_root(att),
                    AttestationData.hash_tree_root(att.data),
                )
            records = {
                int(tgt): {int(v): int(i) for v, i in rec.items()}
                for tgt, rec in doc["records"].items()
            }
            proposals = {}
            for hexed in doc["proposals"]:
                h = SignedBeaconBlockHeader.decode(bytes.fromhex(hexed))
                proposals[
                    (int(h.message.slot), int(h.message.proposer_index))
                ] = h
            att_slashings = {}
            for hexed in doc["attester_slashings"]:
                s = self.types.AttesterSlashing.decode(bytes.fromhex(hexed))
                att_slashings[self.types.AttesterSlashing.hash_tree_root(s)] = s
            prop_slashings = {}
            for hexed in doc["proposer_slashings"]:
                s = ProposerSlashing.decode(bytes.fromhex(hexed))
                prop_slashings[ProposerSlashing.hash_tree_root(s)] = s
            next_id = int(doc["next_id"])
            span_doc = doc["span"]
            planes = [
                _np.frombuffer(
                    base64.b64decode(p["data"]), dtype=_np.dtype(p["dtype"])
                ).reshape(p["shape"]).copy()
                for p in span_doc["planes"]
            ]
            n_pad = planes[0].shape[0]
            span_n, span_epoch = int(span_doc["n"]), int(span_doc["epoch"])
        except Exception:  # noqa: BLE001 — undecodable checkpoint: fresh start
            from ..utils.logging import get_logger

            get_logger("slasher").warning("Slasher checkpoint undecodable")
            return False
        with self._lock:
            for att_id, (att, root, data_root) in atts.items():
                self._atts[att_id] = att
                self._att_root[att_id] = data_root
                self._root_to_id[root] = att_id
                self._id_to_root[att_id] = root
                self._ids_by_target.setdefault(
                    int(att.data.target.epoch), set()
                ).add(att_id)
            self._records.update(records)
            self._proposals.update(proposals)
            self._attester_slashings.update(att_slashings)
            self._proposer_slashings.update(prop_slashings)
            self._next_id = max(self._next_id, next_id)
        span = self.span
        span.host = planes
        span.n = span_n
        span.n_pad = n_pad
        span.epoch = span_epoch
        span.ckpt_epoch = span.epoch
        span.journal.clear()
        if span.use_device:
            span.mode = "device" if span._try_upload() else "host"
        else:
            span.mode = "host"
        return True

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        snap = self.span.stats()
        snap.update(
            attestations_indexed=len(self._atts),
            shed_pairs=self.shed_pairs,
        )
        return snap
