"""Slasher service: gossip ingest + periodic batch processing
(ref slasher/service/src/service.rs).

The reference spawns a timer task that runs ``process_queued`` every
``update_period`` at ``slot_offset`` into the slot, then drains found
slashings into the op pool and optionally broadcasts them.  Here the service
exposes the same three edges — observe attestation, observe block, tick —
and the node/test driver supplies the clock.
"""

from __future__ import annotations


class SlasherService:
    def __init__(self, chain, slasher, op_pool=None):
        self.chain = chain
        self.slasher = slasher
        self.op_pool = op_pool if op_pool is not None else getattr(
            chain, "op_pool", None
        )
        self._last_pruned_epoch = -1

    # -- ingest edges ---------------------------------------------------------

    def attestation_observed(self, indexed_attestation) -> None:
        """Feed a gossip-verified indexed attestation (service.rs ingest)."""
        self.slasher.accept_attestation(indexed_attestation)

    def block_observed(self, signed_block) -> None:
        """Feed an imported block's signed header."""
        from ..types.containers import BeaconBlockHeader, SignedBeaconBlockHeader

        blk = signed_block.message
        header = SignedBeaconBlockHeader(
            message=BeaconBlockHeader(
                slot=blk.slot,
                proposer_index=blk.proposer_index,
                parent_root=bytes(blk.parent_root),
                state_root=bytes(blk.state_root),
                body_root=type(blk.body).hash_tree_root(blk.body),
            ),
            signature=bytes(signed_block.signature),
        )
        self.slasher.accept_block_header(header)

    # -- periodic processing --------------------------------------------------

    def tick(self, current_epoch: int | None = None) -> dict:
        """Process queues and drain slashings into the op pool; prunes the
        database once per epoch advance (service.rs prune cadence)."""
        spe = self.chain.spec.preset.SLOTS_PER_EPOCH
        if current_epoch is None:
            current_epoch = self.chain.current_slot() // spe
        stats = self.slasher.process_queued(current_epoch)
        if current_epoch > self._last_pruned_epoch:
            self.slasher.prune_database(current_epoch, spe)
            self._last_pruned_epoch = current_epoch
        if self.op_pool is not None:
            for s in self.slasher.get_attester_slashings():
                self.op_pool.insert_attester_slashing(s)
            for s in self.slasher.get_proposer_slashings():
                self.op_pool.insert_proposer_slashing(s)
        # checkpoint the engine's record index + span planes each tick when
        # a store is attached (restart-from-disk durability, ISSUE 12) —
        # a persistence failure is recorded, never silently dropped, and
        # the in-memory engine keeps serving
        persist = getattr(self.slasher, "persist", None)
        if persist is not None and getattr(self.slasher, "store", None) is not None:
            try:
                persist()
            except Exception as e:  # noqa: BLE001 — durable tick best-effort
                from ..resilience import faults

                faults.record_fault(
                    "slasher.persist", e, domain="slasher_device"
                )
        return stats
