"""Slasher configuration (ref slasher/src/config.rs).

The reference tiles its epoch axis into C=16-wide chunks because its update
loops walk epoch-by-epoch with early exit and it wants to touch as little of
the on-disk array as possible (config.rs:9-11, array.rs:16-28).  The TPU
redesign processes a validator-chunk row's FULL epoch window in one fused
kernel (see arrays.py), so the epoch-chunking degree of freedom disappears:
the unit of storage and compute is a whole ``[validator_chunk_size,
history_length]`` tile.  ``validator_chunk_size`` remains the row height and
``history_length`` the window width; both are validated like the reference
(config.rs:98-120).
"""

from __future__ import annotations

from dataclasses import dataclass

# ref slasher/src/array.rs:14 — distances are stored as u16 with this sentinel
MAX_DISTANCE = 0xFFFF

DEFAULT_VALIDATOR_CHUNK_SIZE = 256  # ref config.rs:10
DEFAULT_HISTORY_LENGTH = 4096  # ref config.rs:11
DEFAULT_UPDATE_PERIOD = 12  # seconds, ref config.rs:12
DEFAULT_SLOT_OFFSET = 10.5  # ref config.rs:13
MAX_HISTORY_LENGTH = 1 << 16  # ref config.rs:27


@dataclass(frozen=True)
class SlasherConfig:
    validator_chunk_size: int = DEFAULT_VALIDATOR_CHUNK_SIZE
    history_length: int = DEFAULT_HISTORY_LENGTH
    update_period: float = DEFAULT_UPDATE_PERIOD
    slot_offset: float = DEFAULT_SLOT_OFFSET
    broadcast: bool = False

    def validate(self) -> None:
        if self.validator_chunk_size <= 0 or self.history_length <= 0:
            raise ValueError("slasher config: zero-sized parameter")
        if self.history_length > MAX_HISTORY_LENGTH:
            raise ValueError(
                f"slasher history_length {self.history_length} exceeds "
                f"max {MAX_HISTORY_LENGTH}"
            )

    def validator_chunk_index(self, validator_index: int) -> int:
        return validator_index // self.validator_chunk_size

    def validator_offset(self, validator_index: int) -> int:
        return validator_index % self.validator_chunk_size

    def validator_indices_in_chunk(self, validator_chunk_index: int):
        base = validator_chunk_index * self.validator_chunk_size
        return range(base, base + self.validator_chunk_size)
