"""Fused device kernels for the whole-registry slasher engine.

The seed path (``arrays.py``) updates ``[validator_chunk_size,
history_length]`` *rows* on demand through a host-side DB: surveillance cost
grows with the number of touched rows and the planes round-trip through the
store every batch. This module is the same math taken to registry scale —
ONE ``[n_validators, history_length]`` pair of min/max distance planes (plus
a vote-hash plane for double-vote candidates) that stays device-resident
across ticks, with per-batch update + detection as a single jitted
scatter / cumulative-scan sweep:

  1. **window advance** — the current epoch moved by ``delta`` since the
     last sweep: distances are invariant under window shifts (the seed's
     per-row encoding, array.rs:14,84-99), so the advance is a roll along
     the epoch axis + neutral fill of the new columns. ``delta`` is a
     TRACED argument: epoch rolls never recompile.
  2. **scatter** — attestation ``(v, s, t)`` applies ``min`` over columns
     ``[window_start, s-1]`` and ``max`` over ``[s+1, current_epoch]``
     (array.rs:219-244,322-347); both intervals always extend to a window
     edge, so a batch collapses to a scatter-min of ``t`` at column ``s-1``
     (resp. scatter-max at ``s+1``) over the whole plane.
  3. **directional scans** — one reverse cumulative min (resp. forward
     cumulative max) along the epoch axis completes every interval.
  4. **per-pair reads** — each pair reads the post-update planes at its own
     source column (its own writes never touch that column), yielding
     surround / surrounded candidate flags; the vote-hash plane yields
     double-vote candidates (a different 32-bit data-root tag already
     recorded at the pair's target column, or two different tags landing on
     the same cell within the batch).

The kernel only FLAGS. Every flagged pair is re-confirmed host-side against
the fetched attestation record before a slashing is emitted — the
One-For-All attribution bar: an aggregate proves the *set* signed, only the
record proves *which* prior vote conflicts (engine.py). A 32-bit vote tag
can collide (two distinct data roots sharing a prefix suppress a candidate
with probability 2^-32 per conflicting pair); the host confirmation
compares full roots, so collisions can only suppress a candidate flag,
never produce a false slashing.

``lighthouse_tpu/slasher/engine.py`` holds the field-for-field numpy twin
(``sweep_numpy``) — this module is only imported on the device path, so the
``numpy`` backend never pays a jax import.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .config import MAX_DISTANCE

_INT_INF = np.int32(2**31 - 1)
_VOTE_NONE = np.uint32(0xFFFFFFFF)  # scatter-min identity for the vote plane

# Static headroom bound for the int32 target-domain arithmetic: every epoch
# the kernel sees must leave ``MAX_DISTANCE + history`` of int32 headroom.
# 2^24 epochs is ~6,800 years of chain time; the host wrappers enforce it.
MAX_EPOCH = 1 << 24


def sweep_impl(min_d, max_d, vote_h, delta, vidx, src, tgt, vh, valid, cur, *, n):
    """Advance + batch-update + candidate detection over the whole registry.

    min_d, max_d : uint16[V, N]  distance planes (linear window layout,
                                 newest epoch in the last column)
    vote_h       : uint32[V, N]  data-root tag recorded per target column
    delta        : int32         window advance (cur - stored_epoch), traced
    vidx,src,tgt : int32[P]      flattened (attestation x validator) pairs
    vh           : uint32[P]     nonzero data-root tag per pair
    valid        : bool[P]       padding mask
    cur          : int32         current epoch (last column's epoch)

    Returns ``(new_min_d, new_max_d, new_vote_h, min_target, max_target,
    min_flag, max_flag, dbl_flag)`` — targets are the per-pair post-update
    plane reads the host uses to fetch the existing record on a flagged
    candidate.
    """
    from ..ops.bls.fq import _cert

    # trace-time proof obligations (recorded by the bounds certifier when
    # its sink is installed; plain asserts otherwise)
    assert _cert(
        "slasher_distance_width", MAX_DISTANCE, 0xFFFF,
        "distance sentinel fits the u16 plane dtype",
    )
    assert _cert(
        "slasher_target_domain", MAX_EPOCH + MAX_DISTANCE + n, _INT_INF,
        "int32 target-domain arithmetic cannot wrap below MAX_EPOCH",
    )
    assert _cert(
        "slasher_window_width", n - 1, MAX_DISTANCE,
        "max in-window distance (n-1) representable in the u16 encoding",
    )

    base = cur - (n - 1)
    j = jnp.arange(n, dtype=jnp.int32)
    e = base + j  # epoch of each column

    # -- 1. window advance: roll left by delta, neutral-fill new columns.
    dl = jnp.clip(delta, 0, n)
    fresh = j >= n - dl
    min_d = jnp.where(fresh, jnp.uint16(MAX_DISTANCE), jnp.roll(min_d, -dl, axis=1))
    max_d = jnp.where(fresh, jnp.uint16(0), jnp.roll(max_d, -dl, axis=1))
    vote_h = jnp.where(fresh, jnp.uint32(0), jnp.roll(vote_h, -dl, axis=1))

    old_min_t = e[None, :] + min_d.astype(jnp.int32)
    old_max_t = e[None, :] + max_d.astype(jnp.int32)
    v_cap = min_d.shape[0]
    vi = jnp.clip(vidx, 0, v_cap - 1)

    # -- 2. scatter + directional scans in the int32 target domain.
    # Invalid / out-of-window columns are routed to index n, which scatter
    # mode="drop" discards.
    def route(col, ok):
        return jnp.where(ok & (col >= 0) & (col < n), col, n)

    col_min = route(src - 1 - base, valid)
    col_max = route(src + 1 - base, valid)
    col_t = route(tgt - base, valid)

    scat_min = jnp.full((v_cap, n), _INT_INF, jnp.int32).at[vi, col_min].min(
        tgt, mode="drop"
    )
    scat_max = jnp.full((v_cap, n), -_INT_INF, jnp.int32).at[vi, col_max].max(
        tgt, mode="drop"
    )
    # min_targets[e] aggregates attestations with source-1 >= e: suffix scan;
    # max_targets[e] aggregates attestations with source+1 <= e: prefix scan.
    suff_min = jax.lax.cummin(scat_min, axis=1, reverse=True)
    pref_max = jax.lax.cummax(scat_max, axis=1)

    new_min_t = jnp.minimum(old_min_t, suff_min)
    new_max_t = jnp.maximum(old_max_t, pref_max)
    new_min_d = jnp.clip(new_min_t - e[None, :], 0, MAX_DISTANCE).astype(jnp.uint16)
    new_max_d = jnp.clip(new_max_t - e[None, :], 0, MAX_DISTANCE).astype(jnp.uint16)

    # -- 3. vote-hash plane: first-seen tag wins (the record path keeps the
    # existing attestation, ref database.rs:585-640); candidates are a
    # pre-existing different tag or an intra-batch tag conflict.
    col_t_c = jnp.clip(col_t, 0, n - 1)
    in_w = col_t < n
    pre = jnp.where(in_w, vote_h[vi, col_t_c], jnp.uint32(0))
    smin = jnp.full((v_cap, n), _VOTE_NONE, jnp.uint32).at[vi, col_t].min(
        vh, mode="drop"
    )
    smax = jnp.zeros((v_cap, n), jnp.uint32).at[vi, col_t].max(vh, mode="drop")
    new_vote_h = jnp.where(
        vote_h != 0, vote_h, jnp.where(smin != _VOTE_NONE, smin, jnp.uint32(0))
    )
    smin_p = smin[vi, col_t_c]
    smax_p = smax[vi, col_t_c]
    dbl_flag = valid & in_w & (
        ((pre != 0) & (pre != vh)) | (smin_p != smax_p)
    )

    # -- 4. post-update surround reads at each pair's own source column.
    col_s = jnp.clip(src - base, 0, n - 1)
    min_target = new_min_d[vi, col_s].astype(jnp.int32) + e[col_s]
    max_target = new_max_d[vi, col_s].astype(jnp.int32) + e[col_s]
    min_flag = valid & (tgt > min_target)
    max_flag = valid & (tgt < max_target)
    return (
        new_min_d, new_max_d, new_vote_h,
        min_target, max_target, min_flag, max_flag, dbl_flag,
    )


# the serving entrypoint; the bounds certifier traces ``sweep_impl``
# directly so each backend/batch regime re-records its obligations instead
# of hitting the jit cache
sweep = functools.partial(jax.jit, static_argnames=("n",))(sweep_impl)
