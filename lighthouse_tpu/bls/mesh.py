"""Serving-mesh resolution + the jax glue for the sharded BLS serving tier.

The data-parallel serving tier (``firehose/sharding.py``) is deliberately
jax-free so its fault-domain logic stays unit-testable; everything that
touches devices lives here:

* **env knob** — ``LIGHTHOUSE_MESH_DEVICES`` selects the serving mesh size:
  unset/``0``/``1``/``off`` disables the mesh (the single-device engine,
  bit-identical to the pre-mesh code path), ``auto`` takes every visible
  device, an integer takes that many. The size is floored to a power of two
  (fixed-shape compile families; mesh halving stays shape-stable).
* **mesh cache** — one ``jax.sharding.Mesh`` per device subset, so the
  degradation ladder's shrunken meshes (N -> N/2 -> ...) reuse compiled
  programs across calls.
* **dispatch glue** — ``make_mesh_backend`` binds the per-shard-verdict
  kernels (``tpu_backend.verify_staged_pershard``) into the ``stage`` /
  ``dispatch`` / ``probe`` callables the jax-free ``MeshVerifier`` consumes.
"""

from __future__ import annotations

import functools
import os

import numpy as np

ENV_VAR = "LIGHTHOUSE_MESH_DEVICES"


def pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def requested_mesh_devices() -> int | str:
    """Raw knob value: 0 (disabled), an int, or "auto"."""
    raw = os.environ.get(ENV_VAR, "").strip().lower()
    if raw in ("", "0", "1", "off", "none", "no"):
        return 0
    if raw == "auto":
        return "auto"
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


def serving_mesh_size() -> int:
    """Resolved serving-mesh size: 1 when the mesh is disabled (the
    single-device engine — bit-identical to today), else the power-of-two
    floor of min(requested, visible devices). Never initiates a device
    probe beyond ``jax.devices()`` (callers have already pinned the
    platform)."""
    req = requested_mesh_devices()
    if req == 0:
        return 1
    try:
        import jax

        avail = len(jax.devices())
    except Exception:  # noqa: BLE001 — no usable backend: mesh off
        return 1
    n = avail if req == "auto" else min(req, avail)
    return pow2_floor(max(1, n))


@functools.lru_cache(maxsize=None)
def _mesh_for(device_ids: tuple) -> object:
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    return Mesh(np.array([devs[i] for i in device_ids]), axis_names=("sets",))


def get_mesh(device_ids) -> object:
    """Cached ``Mesh`` over the given device indices (``sets`` axis)."""
    return _mesh_for(tuple(int(i) for i in device_ids))


class MeshBackend:
    """The jax side of the serving tier: staging (host prep + per-shard
    async H2D), dispatch (the per-shard-verdict kernel family), and a
    per-device liveness probe for fault attribution. ``cache_fn`` resolves
    the device-resident pubkey cache at call time (it grows with the
    validator registry)."""

    def __init__(self, cache_fn):
        self.cache_fn = cache_fn

    def stage(self, shard_items, device_ids, shard_cap: int):
        """Host stage + sharded transfer for one tick's sub-batches —
        called from the firehose prep thread to double-buffer H2D against
        the device thread's in-flight verify."""
        from . import tpu_backend as tb

        mesh = get_mesh(device_ids)
        staged = tb.stage_indexed_shards(shard_items, shard_cap)
        return tb.put_staged(staged, mesh)

    def dispatch(self, shard_items, device_ids, staged=None,
                 shard_cap: int | None = None):
        """Per-shard verdicts for one tick. ``staged`` (from ``stage``)
        skips re-staging on the fast path; the ladder's re-staging rungs
        pass fresh ``shard_items``."""
        from . import tpu_backend as tb

        mesh = get_mesh(device_ids)
        if staged is None:
            staged = tb.stage_indexed_shards(
                shard_items,
                shard_cap or tb.bucket(
                    max((len(sh) for sh in shard_items), default=1)
                ),
            )
            staged = tb.put_staged(staged, mesh)
        oks = tb.verify_staged_pershard(self.cache_fn(), staged, mesh)
        return [bool(o) for o in np.asarray(oks)]

    def probe(self, device_id: int) -> None:
        """One tiny op pinned to one device — the fault-attribution probe
        the supervisor ladder runs after an unattributed mesh fault."""
        import jax
        import jax.numpy as jnp

        dev = jax.devices()[device_id]
        out = jax.device_put(jnp.arange(4, dtype=jnp.uint32), dev).sum()
        out.block_until_ready()


def make_mesh_backend(cache_fn) -> MeshBackend:
    return MeshBackend(cache_fn)
