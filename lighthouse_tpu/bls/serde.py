"""Vectorized compressed-point byte codecs (ZCash/Eth2 serialization).

G1 public keys: 48 bytes; G2 signatures: 96 bytes. Big-endian field elements
with 3 flag bits in the top byte: compression (must be 1), infinity, and
lex-largest-y sign. Parsing is numpy-vectorized: a [n, 48/96] uint8 matrix
becomes 16-bit limb arrays + flag/validity vectors in a handful of array ops —
no per-item Python. Parity: ``/root/reference/crypto/bls/src/generic_public_key_bytes.rs``
and blst's deserialize (flag semantics per the IETF/ZCash convention).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..ops.bls import fq
from ..ops.bls_oracle.fields import P

_P_LIMBS24 = np.array(
    [(P >> (16 * i)) & 0xFFFF for i in range(24)], dtype=np.uint64
)


def _be_bytes_to_limbs(chunk: np.ndarray) -> np.ndarray:
    """[n, 48] big-endian bytes (flags already cleared) -> [n, 25] uint64
    little-endian 16-bit limbs (raw residue, NOT Montgomery)."""
    n = chunk.shape[0]
    pairs = chunk.reshape(n, 24, 2).astype(np.uint64)
    limbs_be = (pairs[:, :, 0] << np.uint64(8)) | pairs[:, :, 1]
    limbs = limbs_be[:, ::-1]  # little-endian limb order
    return np.concatenate(
        [limbs, np.zeros((n, 1), dtype=np.uint64)], axis=1
    )


def _limbs_lt_p(limbs: np.ndarray) -> np.ndarray:
    """[n, 25] raw limbs < p? (vectorized big-endian compare on 24 limbs)."""
    a = limbs[:, :24]
    gt = np.zeros(a.shape[0], dtype=bool)
    lt = np.zeros(a.shape[0], dtype=bool)
    for i in range(23, -1, -1):
        ai, pi = a[:, i], _P_LIMBS24[i]
        gt |= ~lt & ~gt & (ai > pi)
        lt |= ~lt & ~gt & (ai < pi)
    return lt


def parse_g1_bytes(data: np.ndarray):
    """[n, 48] uint8 -> dict of host arrays:
    x_raw [n, 25] (flags cleared), s_flag [n], is_inf [n], wf_ok [n]
    (well-formed: compression bit set, canonical field element, legal flag
    combination, infinity pattern exact)."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    top = data[:, 0]
    c_flag = (top >> 7) & 1
    i_flag = (top >> 6) & 1
    s_flag = (top >> 5) & 1
    cleared = data.copy()
    cleared[:, 0] &= 0x1F
    x = _be_bytes_to_limbs(cleared)
    rest_zero = (cleared == 0).all(axis=1)
    wf = (c_flag == 1) & _limbs_lt_p(x)
    # infinity: i_flag set requires s_flag clear and x == 0
    inf_ok = (i_flag == 1) & (s_flag == 0) & rest_zero
    wf = wf & ((i_flag == 0) | inf_ok)
    return {
        "x": x,
        "s_flag": s_flag.astype(np.uint64),
        "is_inf": i_flag == 1,
        "wf_ok": wf,
    }


def parse_g2_bytes(data: np.ndarray):
    """[n, 96] uint8 -> x_c0/x_c1 [n, 25], s_flag, is_inf, wf_ok.
    Byte layout: x.c1 first (big-endian, with flags), then x.c0."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    top = data[:, 0]
    c_flag = (top >> 7) & 1
    i_flag = (top >> 6) & 1
    s_flag = (top >> 5) & 1
    cleared = data.copy()
    cleared[:, 0] &= 0x1F
    c1 = _be_bytes_to_limbs(cleared[:, 0:48])
    c0 = _be_bytes_to_limbs(cleared[:, 48:96])
    rest_zero = (cleared == 0).all(axis=1)
    wf = (c_flag == 1) & _limbs_lt_p(c0) & _limbs_lt_p(c1)
    inf_ok = (i_flag == 1) & (s_flag == 0) & rest_zero
    wf = wf & ((i_flag == 0) | inf_ok)
    return {
        "x_c0": c0,
        "x_c1": c1,
        "s_flag": s_flag.astype(np.uint64),
        "is_inf": i_flag == 1,
        "wf_ok": wf,
    }


def raw_to_mont(x):
    """Raw-residue limbs -> field-element limbs. The field layer works on plain
    residues (fq.py), so parsed canonical limbs ARE the element — no domain
    conversion, no per-batch multiply. Name kept for call sites."""
    return jnp.asarray(x)


def _limbs_to_be_bytes(limbs: np.ndarray) -> np.ndarray:
    """[n, 25] canonical raw limbs -> [n, 48] big-endian bytes."""
    n = limbs.shape[0]
    a = np.asarray(limbs[:, :24], dtype=np.uint64)[:, ::-1]  # big-endian limbs
    out = np.zeros((n, 24, 2), dtype=np.uint8)
    out[:, :, 0] = (a >> np.uint64(8)).astype(np.uint8)
    out[:, :, 1] = (a & np.uint64(0xFF)).astype(np.uint8)
    return out.reshape(n, 48)


def encode_g1_bytes(x_raw: np.ndarray, sign: np.ndarray, is_inf: np.ndarray):
    """Canonical raw affine-x limbs [n, 25] + sign bits + inf mask -> [n, 48]."""
    x_raw = np.where(is_inf[:, None], 0, np.asarray(x_raw, dtype=np.uint64))
    out = _limbs_to_be_bytes(x_raw)
    flags = 0x80 | np.where(is_inf, 0x40, np.where(sign.astype(bool), 0x20, 0))
    out[:, 0] |= flags.astype(np.uint8)
    return out


def encode_g2_bytes(c0_raw, c1_raw, sign, is_inf):
    c0_raw = np.where(is_inf[:, None], 0, np.asarray(c0_raw, dtype=np.uint64))
    c1_raw = np.where(is_inf[:, None], 0, np.asarray(c1_raw, dtype=np.uint64))
    out = np.concatenate(
        [_limbs_to_be_bytes(c1_raw), _limbs_to_be_bytes(c0_raw)], axis=1
    )
    flags = 0x80 | np.where(is_inf, 0x40, np.where(sign.astype(bool), 0x20, 0))
    out[:, 0] |= flags.astype(np.uint8)
    return out
