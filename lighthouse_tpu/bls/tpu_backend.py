"""TPU backend: batched random-linear-combination signature-set verification.

The device twin of blst's ``verify_multiple_aggregate_signatures``
(``/root/reference/crypto/bls/src/impls/blst.rs:37-119``):

    prod_i e(r_i * agg_pk_i, H(m_i)) * e(-g1, sum_i r_i * sig_i) == 1

Everything after message hashing runs on device in fixed shapes: per-set pubkey
aggregation (masked tree reduction), 64-bit random scalar multiplication, the
signature MSM, batched Miller loops, and ONE final exponentiation. Batch sizes
are bucketed to powers of two so XLA compiles a handful of shapes.

Per-set G2 subgroup checks mirror ``sigs_groupcheck`` (blst.rs:75-78); pubkeys
are assumed pre-validated on cache insert (``validator_pubkey_cache.rs`` parity
— infinity aggregates still fail the batch, as in blst).
"""

from __future__ import annotations

import functools
import secrets

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.bls import curve, fq, g1, g2, pairing, tower
from ..ops.bls_oracle import curves as _oc

RAND_BITS = 64  # blst.rs:16


def _shard_map():
    """shard_map across jax versions: top-level (newer jax exports
    ``jax.shard_map``) with the experimental namespace as the fallback —
    older builds raise ImportError from ``from jax import shard_map`` and
    used to FAIL the sharded tests instead of running them. Those older
    builds also lack a replication rule for ``while`` (the Miller loop's
    fori/scan), so the wrapper passes ``check_rep=False`` where the kwarg
    exists (its documented workaround) and drops it where it doesn't."""
    try:
        from jax import shard_map as sm
    except ImportError:  # pragma: no cover - version-dependent
        from jax.experimental.shard_map import shard_map as sm

    def wrapped(f, **kw):
        try:
            return sm(f, check_rep=False, **kw)
        except TypeError:  # pragma: no cover - newer jax: kwarg removed
            return sm(f, **kw)

    return wrapped

_MINUS_G1 = _oc.g1_neg(_oc.g1_generator())
_MG1_X = fq.from_int(_MINUS_G1[0])
_MG1_Y = fq.from_int(_MINUS_G1[1])


def bucket(n: int, floor: int = 4) -> int:
    b = floor
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=None)
def _aggregate_kernel(k_pad: int):
    """[n, k_pad, 3, 25] pubkey points + [n, k_pad] mask -> [n, 3, 25] sums."""

    @jax.jit
    def agg(pts, mask):
        return curve.point_sum(1, jnp.moveaxis(pts, 1, 0), jnp.moveaxis(mask, 1, 0))

    return agg


def _set_prologue(pk_agg, sig, scalars, valid):
    """Per-set validity checks + random scaling + masked signature sum.

    The security-critical prologue shared verbatim by the single-chip and
    sharded kernels: G2 subgroup check (blst.rs:75-78), infinity rejection,
    random-scalar scaling of pubkeys and signatures, and the masked G2 sum.

    The two G2 chains — the subgroup check's |x|-chain (psi(Q) == [x]Q) and
    the Fiat–Shamir random scaling [r]Q — multiply the SAME point, so they
    run as one fused windowed pass (curve.scale_u64_with_fixed): one
    precomputed multiples table, one doubling ladder, every kernel dispatch
    covering both chains. The G1 pubkey scaling is the same windowed ladder
    at k = 1."""
    from ..ops.bls_oracle.fields import BLS_X

    accs = curve.scale_u64_with_fixed(2, sig, scalars, (-BLS_X,))
    sig_scaled, abs_x_sig = accs[0], accs[1]
    # psi(Q) == [x]Q with x < 0: [x]Q = -[|x|]Q
    sig_grp = curve.point_eq(
        2, g2.psi(sig), curve.point_neg(2, abs_x_sig)
    )
    set_ok = ~valid | (sig_grp & ~g1.is_inf(pk_agg) & ~g2.is_inf(sig))
    pk_scaled = g1.scale_u64(pk_agg, scalars)
    sig_sum = g2.psum(sig_scaled, valid)
    return set_ok, pk_scaled, sig_sum


@functools.lru_cache(maxsize=None)
def _prologue_stage(n_pad: int):
    """Security prologue as its own compile unit: subgroup checks, random
    scaling, masked signature sum, then affine conversion."""

    @jax.jit
    def run(pk_agg, sig, scalars, valid):
        set_ok, pk_scaled, sig_acc = _set_prologue(pk_agg, sig, scalars, valid)
        pkx, pky = g1.to_affine(pk_scaled)
        sax, say = g2.to_affine(sig_acc)
        return pkx, pky, sax, say, set_ok

    return run


def _verify_kernel(n_pad: int):
    """Batch verification over n_pad sets (padded entries masked by `valid`)
    as two device stages (prologue, pairing) — intermediates stay on device;
    the stages compile and cache independently (see _gathered_kernel).

    Inputs: pk_agg [n,3,25] (G1 projective), sig [n,6,25] (G2 projective),
    msg affine (mx, my) [n,2,25] each, scalars [n] uint64, valid [n] bool.
    Returns scalar bool: the whole batch verifies.
    """
    pro = _prologue_stage(n_pad)
    pair = _pair_stage(n_pad)

    def verify(pk_agg, sig, mx, my, scalars, valid):
        pkx, pky, sax, say, set_ok = pro(pk_agg, sig, scalars, valid)
        return pair(pkx, pky, sax, say, mx, my, set_ok, valid)

    return verify


def _verify_kernel_h2c(n_pad: int):
    """_verify_kernel with the device h2c stage in front: takes hash_to_field
    residues (u0, u1) instead of pre-hashed message points."""
    h2c_k = _h2c_stage(n_pad)
    ver = _verify_kernel(n_pad)

    def verify(pk_agg, sig, u0, u1, scalars, valid):
        mx, my = h2c_k(u0, u1)
        return ver(pk_agg, sig, mx, my, scalars, valid)

    return verify


def verify_signature_sets_device_h2c(pk_agg, sig, u0, u1, n_real: int) -> bool:
    """Like verify_signature_sets_device but hashing on device (fused)."""
    n = pk_agg.shape[0]
    if n_real == 0:
        return False
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n)], dtype=np.uint64
    )
    valid = np.arange(n) < n_real
    ok = _verify_kernel_h2c(n)(
        pk_agg, sig, u0, u1, jnp.asarray(scalars), jnp.asarray(valid)
    )
    return bool(np.asarray(ok))


def aggregate_pubkeys_device(pts: list, k_pad: int | None = None):
    """List over sets of [k_i, 3, 25] device pubkey points -> [n, 3, 25]
    per-set aggregates (padded masked tree sum)."""
    n = len(pts)
    k_pad = k_pad or bucket(max((p.shape[0] for p in pts), default=1))
    buf = jnp.zeros((n, k_pad, 3, fq.NLIMBS), dtype=jnp.uint64)
    mask = np.zeros((n, k_pad), dtype=bool)
    for i, p in enumerate(pts):
        buf = buf.at[i, : p.shape[0]].set(p)
        mask[i, : p.shape[0]] = True
    return _aggregate_kernel(k_pad)(buf, jnp.asarray(mask))


@functools.lru_cache(maxsize=None)
def _h2c_stage(n_pad: int):
    """Stage 1 of the chain hot path: device SSWU + isogeny + cofactor
    clearing + affine conversion for the message points. Shape depends only
    on n_pad — one compile is shared across every keys-per-set bucket."""
    from ..ops.bls import h2c

    @jax.jit
    def run(u0, u1):
        return g2.to_affine(h2c.map_to_g2(u0, u1))

    return run


@functools.lru_cache(maxsize=None)
def _prep_stage(n_pad: int, k_pad: int):
    """Stage 2: signature decompression + cache gather + masked aggregation +
    the security prologue (subgroup checks, random scaling, signature sum),
    ending in affine coordinates for the pairing stage."""
    from ..ops.bls import curve
    from .serde import raw_to_mont

    @jax.jit
    def run(cache, idx, mask, sxc0, sxc1, s_flag, sig_wf, scalars, valid):
        x_mont = raw_to_mont(jnp.stack([sxc0, sxc1], axis=-2))
        sig, on_curve = g2.decompress(x_mont, s_flag)
        pts = cache[idx]                                 # [n, k, 3, 25]
        pk_agg = curve.point_sum(
            1, jnp.moveaxis(pts, 1, 0), jnp.moveaxis(mask, 1, 0)
        )
        set_ok, pk_scaled, sig_acc = _set_prologue(pk_agg, sig, scalars, valid)
        set_ok = set_ok & (~valid | (sig_wf & on_curve & jnp.any(mask, axis=1)))
        pkx, pky = g1.to_affine(pk_scaled)
        sax, say = g2.to_affine(sig_acc)
        return pkx, pky, sax, say, set_ok

    return run


@functools.lru_cache(maxsize=None)
def _pair_stage(n_pad: int):
    """Stage 3: batched Miller loops + ONE final exponentiation + verdict."""

    @jax.jit
    def run(pkx, pky, sax, say, mxa, mya, set_ok, valid):
        px = jnp.concatenate([pkx[:, 0, :], _MG1_X[None]], axis=0)
        py = jnp.concatenate([pky[:, 0, :], _MG1_Y[None]], axis=0)
        qx = jnp.concatenate([mxa, sax[None]], axis=0)
        qy = jnp.concatenate([mya, say[None]], axis=0)
        pair_valid = jnp.concatenate([valid, jnp.ones((1,), dtype=bool)])
        ok = pairing.multi_pairing_is_one(px, py, qx, qy, pair_valid)
        return ok & jnp.all(set_ok) & jnp.any(valid)

    return run


def _gathered_kernel(n_pad: int, k_pad: int):
    """The chain hot path: cache-gather + aggregate + device h2c + device
    signature decompression + RLC batch verification, as THREE separately
    jitted device stages (intermediates never leave the device).

    Staged, not fused: one fused program compiled superlinearly (the r3
    pathology — 461 s at toy shape, >50 min at 64x512 on the TPU server);
    the stages compile independently, persist separately in the compilation
    cache, and the h2c stage's shape does not depend on k_pad at all.

    Inputs:
      cache  [N, 3, 25]  device-resident decompressed pubkeys (projective)
      idx    [n, k] int32 validator indices into cache (0-padded)
      mask   [n, k] bool  which idx entries are real
      u0/u1  [n, 2, 25]   hash_to_field outputs per message (host SHA-256)
      sxc0/sxc1 [n, 25]   raw signature x limbs (flags cleared)
      s_flag [n] uint64   lex-sign bit; sig_wf [n] bool  well-formed encoding
      scalars [n] uint64  RLC scalars; valid [n] bool    real (non-pad) sets

    Zero per-batch host point conversion: the only H2D traffic is indices,
    96-byte signature limbs, and hash_to_field residues. Reference semantics:
    blst verify_multiple_aggregate_signatures (crypto/bls/src/impls/blst.rs:37-119).
    """
    h2c_k = _h2c_stage(n_pad)
    prep_k = _prep_stage(n_pad, k_pad)
    pair_k = _pair_stage(n_pad)

    def run(cache, idx, mask, u0, u1, sxc0, sxc1, s_flag, sig_wf, scalars, valid):
        mxa, mya = h2c_k(u0, u1)
        pkx, pky, sax, say, set_ok = prep_k(
            cache, idx, mask, sxc0, sxc1, s_flag, sig_wf, scalars, valid
        )
        return pair_k(pkx, pky, sax, say, mxa, mya, set_ok, valid)

    return run


def stage_lowerings(n_pad: int, k_pad: int, n_validators: int = 1024):
    """(name, jax Lowered) for each device stage of the gathered chain-hot-path
    kernel at the given shapes — shared by the compile probes and the bench's
    cost analysis (the staged design means there is no single fused program
    to introspect)."""
    u64 = jnp.uint64
    sd = jax.ShapeDtypeStruct
    u = sd((n_pad, 2, 25), u64)
    return [
        ("h2c", _h2c_stage(n_pad).lower(u, u)),
        (
            "prep",
            _prep_stage(n_pad, k_pad).lower(
                sd((n_validators, 3, 25), u64),
                sd((n_pad, k_pad), jnp.int32),
                sd((n_pad, k_pad), jnp.bool_),
                sd((n_pad, 25), u64),
                sd((n_pad, 25), u64),
                sd((n_pad,), u64),
                sd((n_pad,), jnp.bool_),
                sd((n_pad,), u64),
                sd((n_pad,), jnp.bool_),
            ),
        ),
        (
            "pair",
            _pair_stage(n_pad).lower(
                sd((n_pad, 1, 25), u64),
                sd((n_pad, 1, 25), u64),
                sd((2, 25), u64),
                sd((2, 25), u64),
                u,
                u,
                sd((n_pad,), jnp.bool_),
                sd((n_pad,), jnp.bool_),
            ),
        ),
    ]


def verify_indexed_sets_device(cache_arr, items) -> bool:
    """Verify signature sets given as (validator_indices, message, sig_bytes)
    triples against the device-resident pubkey cache.

    The chain's gossip path (attestation_verification/batch.rs semantics): one
    triple per unaggregated attestation; three per aggregate. Malformed
    signature bytes or empty index lists fail the batch (callers bisect via
    the per-set fallback, batch.rs:109-113).
    """
    from .serde import parse_g2_bytes
    from ..ops.bls import h2c
    from ..ops.bls_oracle.ciphersuite import DST

    n = len(items)
    if n == 0:
        return False
    n_pad = bucket(n)
    k_pad = bucket(max((len(ix) for ix, _, _ in items), default=1))

    idx = np.zeros((n_pad, k_pad), dtype=np.int32)
    mask = np.zeros((n_pad, k_pad), dtype=bool)
    sig_bytes = np.zeros((n_pad, 96), dtype=np.uint8)
    msgs = []
    for i, (indices, msg, sb) in enumerate(items):
        k = len(indices)
        if k > 0:
            idx[i, :k] = np.asarray(indices, dtype=np.int32)
            mask[i, :k] = True
        msgs.append(msg)
        sig_bytes[i] = np.frombuffer(sb, dtype=np.uint8)

    parsed = parse_g2_bytes(sig_bytes)
    sig_wf = parsed["wf_ok"] & ~parsed["is_inf"]
    u0, u1 = h2c.hash_to_field_batch(msgs, DST)
    if n_pad > n:  # pad by broadcast, not by hashing dummy messages
        u0 = jnp.concatenate(
            [u0, jnp.broadcast_to(u0[:1], (n_pad - n,) + u0.shape[1:])]
        )
        u1 = jnp.concatenate(
            [u1, jnp.broadcast_to(u1[:1], (n_pad - n,) + u1.shape[1:])]
        )

    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n_pad)], dtype=np.uint64
    )
    valid = np.arange(n_pad) < n
    ok = _gathered_kernel(n_pad, k_pad)(
        cache_arr,
        jnp.asarray(idx),
        jnp.asarray(mask),
        u0,
        u1,
        jnp.asarray(parsed["x_c0"]),
        jnp.asarray(parsed["x_c1"]),
        jnp.asarray(parsed["s_flag"]),
        jnp.asarray(sig_wf),
        jnp.asarray(scalars),
        jnp.asarray(valid),
    )
    return bool(np.asarray(ok))


@functools.lru_cache(maxsize=None)
def _sharded_h2c_stage(mesh, n_pad: int):
    """Sharded twin of ``_h2c_stage``: SSWU/isogeny/cofactor/affine on each
    device's local slice of the sets axis (purely local — no collectives)."""
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    from ..ops.bls import h2c

    def local(u0, u1):
        return g2.to_affine(h2c.map_to_g2(u0, u1))

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P("sets"),) * 2,
        out_specs=(P("sets"),) * 2,
    ))


def _local_prep_partials(cache, idx, mask, sxc0, sxc1, s_flag, sig_wf,
                         scalars, valid):
    """Shard-local body of ``_sharded_prep_stage``: signature decompression,
    replicated-cache gather, masked aggregation, and the security prologue
    over one device's slice, emitting the shard's G2 signature partial sum
    + combined set_ok. Module-level so the bounds certifier re-executes it
    as its own op graph (``analysis/bounds.graph_registry``)."""
    from .serde import raw_to_mont

    x_mont = raw_to_mont(jnp.stack([sxc0, sxc1], axis=-2))
    sig, on_curve = g2.decompress(x_mont, s_flag)
    pts = cache[idx]
    pk_agg = curve.point_sum(
        1, jnp.moveaxis(pts, 1, 0), jnp.moveaxis(mask, 1, 0)
    )
    set_ok, pk_scaled, sig_part = _set_prologue(pk_agg, sig, scalars, valid)
    set_ok = set_ok & (~valid | (sig_wf & on_curve & jnp.any(mask, axis=1)))
    pkx, pky = g1.to_affine(pk_scaled)
    return pkx, pky, sig_part[None], jnp.all(set_ok)[None]


@functools.lru_cache(maxsize=None)
def _sharded_prep_stage(mesh, n_pad: int, k_pad: int):
    """Sharded twin of ``_prep_stage``: pubkey cache REPLICATED (every chip
    holds the decompressed validator registry — validator_pubkey_cache.rs
    parity; ~100 MB at 1M validators, well within HBM); each device
    decompresses, gathers, and aggregates only its n/n_dev sets and emits
    per-device G2 signature partial sums + a per-device set_ok verdict."""
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    return jax.jit(shard_map(
        _local_prep_partials, mesh=mesh,
        in_specs=(P(),) + (P("sets"),) * 8,
        out_specs=(P("sets"),) * 4,
    ))


@functools.lru_cache(maxsize=None)
def _sharded_array_prologue_stage(mesh, n_pad: int):
    """Sharded twin of ``_prologue_stage`` (pre-aggregated pk/sig arrays)."""
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    def local(pk_agg, sig, scalars, valid):
        set_ok, pk_scaled, sig_part = _set_prologue(pk_agg, sig, scalars, valid)
        pkx, pky = g1.to_affine(pk_scaled)
        return pkx, pky, sig_part[None], jnp.all(set_ok)[None]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P("sets"),) * 4,
        out_specs=(P("sets"),) * 4,
    ))


@functools.lru_cache(maxsize=None)
def _sharded_miller_stage(mesh, n_pad: int):
    """Per-device Miller loops over the local sets plus the local Fq12
    product — one [n_dev, 12, 25] partial per device."""
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    def local(pkx, pky, mxa, mya, valid):
        # backend-dispatched product Miller stage (PR 6): on the digit
        # backend one shared fq12 accumulator covers the device's whole
        # shard; invalid pairs contribute the identity either way
        f = pairing.miller_product(
            pkx[:, 0, :], pky[:, 0, :], mxa, mya, valid
        )
        return f[None], jnp.any(valid)[None]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P("sets"),) * 5,
        out_specs=(P("sets"),) * 2,
    ))


@functools.lru_cache(maxsize=None)
def _sharded_combine_stage(mesh):
    """The cross-device epilogue: G2-MSM reduction of the per-device
    signature partials + Fq12 product of the per-device pairing partials
    (XLA inserts the collectives over the mesh from the sharded operands),
    one final Miller loop against -g1, ONE replicated final exponentiation,
    and the combined verdict."""

    @jax.jit
    def combine(partial_f, partial_sig, ok_parts, any_parts):
        sig_acc = g2.psum(partial_sig)
        f_all = pairing.fq12_prod(partial_f)
        sx, sy = g2.to_affine(sig_acc)
        f_last = pairing.miller_loop(_MG1_X, _MG1_Y, sx, sy)
        f = tower.fq12_mul(f_all, f_last)
        ok = tower.fq12_is_one(pairing.final_exponentiation(f))
        return ok & jnp.all(ok_parts) & jnp.any(any_parts)

    return combine


def _sharded_gathered_kernel(mesh, n_pad: int, k_pad: int):
    """Multi-chip twin of ``_gathered_kernel``: the chain hot path (cache
    gather + aggregate + device h2c + signature decompression + RLC
    verification) data-parallel over the mesh's ``sets`` axis, as FOUR
    separately jitted shard_map stages (h2c / prep / miller / combine —
    fused single programs compiled superlinearly, the r3 pathology; staged
    programs compile independently and cache persistently). Cross-device
    combines ride the mesh via XLA collectives in the combine stage.
    Reference semantics: ``crypto/bls/src/impls/blst.rs:37-119``.
    """
    h2c_k = _sharded_h2c_stage(mesh, n_pad)
    prep_k = _sharded_prep_stage(mesh, n_pad, k_pad)
    miller_k = _sharded_miller_stage(mesh, n_pad)
    combine_k = _sharded_combine_stage(mesh)

    def verify(cache, idx, mask, u0, u1, sxc0, sxc1, s_flag, sig_wf,
               scalars, valid):
        mxa, mya = h2c_k(u0, u1)
        pkx, pky, partial_sig, ok_parts = prep_k(
            cache, idx, mask, sxc0, sxc1, s_flag, sig_wf, scalars, valid
        )
        partial_f, any_parts = miller_k(pkx, pky, mxa, mya, valid)
        return combine_k(partial_f, partial_sig, ok_parts, any_parts)

    return verify


def verify_indexed_sets_sharded(cache_arr, items, mesh) -> bool:
    """``verify_indexed_sets_device`` over a mesh with a ``sets`` axis:
    mainnet-shape batches (ragged per-set key counts, 0-padded to a shared
    k bucket) data-parallel across chips, pubkey cache replicated."""
    from .serde import parse_g2_bytes
    from ..ops.bls import h2c
    from ..ops.bls_oracle.ciphersuite import DST

    n = len(items)
    if n == 0:
        return False
    n_dev = mesh.devices.size
    n_pad = ((bucket(max(n, n_dev)) + n_dev - 1) // n_dev) * n_dev
    k_pad = bucket(max((len(ix) for ix, _, _ in items), default=1))

    idx = np.zeros((n_pad, k_pad), dtype=np.int32)
    mask = np.zeros((n_pad, k_pad), dtype=bool)
    sig_bytes = np.zeros((n_pad, 96), dtype=np.uint8)
    msgs = []
    for i, (indices, msg, sb) in enumerate(items):
        k = len(indices)
        if k > 0:
            idx[i, :k] = np.asarray(indices, dtype=np.int32)
            mask[i, :k] = True
        msgs.append(msg)
        sig_bytes[i] = np.frombuffer(sb, dtype=np.uint8)

    parsed = parse_g2_bytes(sig_bytes)
    sig_wf = parsed["wf_ok"] & ~parsed["is_inf"]
    u0, u1 = h2c.hash_to_field_batch(msgs, DST)
    if n_pad > n:
        u0 = jnp.concatenate(
            [u0, jnp.broadcast_to(u0[:1], (n_pad - n,) + u0.shape[1:])]
        )
        u1 = jnp.concatenate(
            [u1, jnp.broadcast_to(u1[:1], (n_pad - n,) + u1.shape[1:])]
        )
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n_pad)], dtype=np.uint64
    )
    valid = np.arange(n_pad) < n
    ok = _sharded_gathered_kernel(mesh, n_pad, k_pad)(
        cache_arr,
        jnp.asarray(idx),
        jnp.asarray(mask),
        u0,
        u1,
        jnp.asarray(parsed["x_c0"]),
        jnp.asarray(parsed["x_c1"]),
        jnp.asarray(parsed["s_flag"]),
        jnp.asarray(sig_wf),
        jnp.asarray(scalars),
        jnp.asarray(valid),
    )
    return bool(np.asarray(ok))


def _local_pair_verdict(pkx, pky, mxa, mya, sig_part, ok_part, valid):
    """Shard-local pairing epilogue for the PER-SHARD-verdict serving path:
    the device's local Miller product, one local Miller loop of the shard's
    signature partial sum against -g1, and the shard's OWN final
    exponentiation — no cross-device collectives at all, so each shard's
    verdict stands alone (a poisoned or faulted shard condemns only its own
    sub-batch, never the whole mesh tick).

    Inputs are one device's slice: pkx/pky [c, 1, 25] affine scaled pubkeys,
    mxa/mya [c, 2, 25] affine message points, sig_part [6, 25] the shard's
    masked signature sum, ok_part scalar bool, valid [c]. Returns scalar
    bool. Registered in ``analysis/bounds.graph_registry`` (the serving
    tier's new op-graph composition)."""
    f_batch = pairing.miller_product(pkx[:, 0, :], pky[:, 0, :], mxa, mya, valid)
    sx, sy = g2.to_affine(sig_part)
    f_last = pairing.miller_loop(_MG1_X, _MG1_Y, sx, sy)
    f = tower.fq12_mul(f_batch, f_last)
    ok = tower.fq12_is_one(pairing.final_exponentiation(f))
    return ok & ok_part & jnp.any(valid)


@functools.lru_cache(maxsize=None)
def _sharded_verdict_stage(mesh, n_pad: int):
    """Per-shard verdict epilogue: each device runs ``_local_pair_verdict``
    on its own slice and emits ONE bool — the gathered [n_dev] output is the
    per-shard verdict vector (the cross-device combine of the serving tier:
    an output gather, no arithmetic collectives)."""
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    def local(pkx, pky, mxa, mya, sig_part, ok_part, valid):
        return _local_pair_verdict(
            pkx, pky, mxa, mya, sig_part[0], ok_part[0], valid
        )[None]

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P("sets"),) * 7, out_specs=P("sets"),
    ))


def stage_indexed_shards(shard_items, shard_cap: int, k_pad: int | None = None):
    """Host stage of the shard-aware serving path: N fixed-shape sub-batches
    (one per shard, each padded to ``shard_cap`` — padding per SHARD, not
    per mesh) from lists of (indices, message, sig_bytes) triples.

    Runs entirely on the host (SHA-256 hash_to_field, signature parsing,
    index/mask packing, fresh RLC scalars) so the firehose prep thread can
    stage batch N+1 while the device thread verifies batch N. Returns a dict
    of numpy/jnp arrays at n_pad = len(shard_items) * shard_cap rows, shard
    s owning rows [s*cap, (s+1)*cap)."""
    from .serde import parse_g2_bytes
    from ..ops.bls import h2c
    from ..ops.bls_oracle.ciphersuite import DST

    n_shards = len(shard_items)
    n_pad = n_shards * shard_cap
    k_pad = k_pad or bucket(
        max((len(ix) for sh in shard_items for ix, _, _ in sh), default=1)
    )
    idx = np.zeros((n_pad, k_pad), dtype=np.int32)
    mask = np.zeros((n_pad, k_pad), dtype=bool)
    sig_bytes = np.zeros((n_pad, 96), dtype=np.uint8)
    valid = np.zeros((n_pad,), dtype=bool)
    msgs, rows = [], []
    for s, sh in enumerate(shard_items):
        if len(sh) > shard_cap:
            raise ValueError(
                f"shard {s} holds {len(sh)} items > cap {shard_cap}"
            )
        for j, (indices, msg, sb) in enumerate(sh):
            r = s * shard_cap + j
            k = len(indices)
            if k > 0:
                idx[r, :k] = np.asarray(indices, dtype=np.int32)
                mask[r, :k] = True
            sig_bytes[r] = np.frombuffer(sb, dtype=np.uint8)
            valid[r] = True
            msgs.append(msg)
            rows.append(r)
    parsed = parse_g2_bytes(sig_bytes)
    sig_wf = parsed["wf_ok"] & ~parsed["is_inf"]
    # hash only the real messages; padded rows broadcast the first real one
    # (masked invalid — they only need to be SOME valid field element)
    u_shape = (n_pad, 2, 25)
    if msgs:
        ur0, ur1 = h2c.hash_to_field_batch(msgs, DST)
        ur0, ur1 = np.asarray(ur0), np.asarray(ur1)
        u0 = np.broadcast_to(ur0[:1], u_shape).copy()
        u1 = np.broadcast_to(ur1[:1], u_shape).copy()
        u0[rows], u1[rows] = ur0, ur1
    else:
        u0 = np.zeros(u_shape, dtype=np.uint64)
        u1 = np.zeros(u_shape, dtype=np.uint64)
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n_pad)],
        dtype=np.uint64,
    )
    return {
        "n_pad": n_pad,
        "k_pad": k_pad,
        "idx": idx,
        "mask": mask,
        "u0": u0,
        "u1": u1,
        "x_c0": np.asarray(parsed["x_c0"]),
        "x_c1": np.asarray(parsed["x_c1"]),
        "s_flag": np.asarray(parsed["s_flag"]),
        "sig_wf": np.asarray(sig_wf),
        "scalars": scalars,
        "valid": valid,
    }


_STAGED_SET_KEYS = (
    "idx", "mask", "u0", "u1", "x_c0", "x_c1", "s_flag", "sig_wf",
    "scalars", "valid",
)


def put_staged(staged: dict, mesh) -> dict:
    """Move one staged sub-batch family onto the mesh, per-set arrays
    sharded over the ``sets`` axis — one async H2D transfer per shard, so a
    prep thread staging batch N+1 double-buffers against the device thread
    verifying batch N (jax transfers are dispatched asynchronously)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("sets"))
    out = dict(staged)
    for k in _STAGED_SET_KEYS:
        out[k] = jax.device_put(staged[k], sh)
    return out


def verify_staged_pershard(cache_arr, staged: dict, mesh) -> np.ndarray:
    """Run the sharded serving pipeline (h2c / prep / per-shard verdict) on
    a staged sub-batch family. Returns the [n_dev] per-shard verdict vector:
    shard s's bool covers exactly its own ``shard_cap`` rows."""
    n_pad, k_pad = staged["n_pad"], staged["k_pad"]
    h2c_k = _sharded_h2c_stage(mesh, n_pad)
    prep_k = _sharded_prep_stage(mesh, n_pad, k_pad)
    verdict_k = _sharded_verdict_stage(mesh, n_pad)
    mxa, mya = h2c_k(staged["u0"], staged["u1"])
    pkx, pky, partial_sig, ok_parts = prep_k(
        cache_arr, staged["idx"], staged["mask"], staged["x_c0"],
        staged["x_c1"], staged["s_flag"], staged["sig_wf"],
        staged["scalars"], staged["valid"],
    )
    oks = verdict_k(
        pkx, pky, mxa, mya, partial_sig, ok_parts, staged["valid"]
    )
    return np.asarray(oks)


def verify_indexed_shards_pershard(cache_arr, shard_items, mesh) -> np.ndarray:
    """Per-shard-verdict verification of N per-shard sub-batches over the
    mesh (stage + transfer + dispatch in one call — the non-pipelined
    convenience used by tests and the degradation ladder's re-staging
    rungs). ``shard_items``: one list of (indices, message, sig_bytes)
    triples per device; sub-batches are padded per shard to a shared
    power-of-two cap. Returns the [n_dev] verdict vector."""
    n_dev = mesh.devices.size
    if len(shard_items) != n_dev:
        raise ValueError(f"{len(shard_items)} shards for a {n_dev}-device mesh")
    cap = bucket(max((len(sh) for sh in shard_items), default=1))
    staged = stage_indexed_shards(shard_items, cap)
    staged = put_staged(staged, mesh)
    return verify_staged_pershard(cache_arr, staged, mesh)


def _sharded_verify_kernel(mesh, n_pad: int):
    """Multi-chip twin of ``_verify_kernel``: dp over signature sets on the
    mesh's ``sets`` axis, as three staged shard_map jits (array prologue /
    miller / combine) sharing the gathered path's stages. Reference
    semantics: ``crypto/bls/src/impls/blst.rs:37-119``.
    """
    pro_k = _sharded_array_prologue_stage(mesh, n_pad)
    miller_k = _sharded_miller_stage(mesh, n_pad)
    combine_k = _sharded_combine_stage(mesh)

    def verify(pk_agg, sig, mx, my, scalars, valid):
        pkx, pky, partial_sig, ok_parts = pro_k(pk_agg, sig, scalars, valid)
        partial_f, any_parts = miller_k(pkx, pky, mx, my, valid)
        return combine_k(partial_f, partial_sig, ok_parts, any_parts)

    return verify


def verify_signature_sets_sharded(
    pk_agg, sig, msg_x, msg_y, n_real: int, mesh
) -> bool:
    """Sharded batch verification over a ``Mesh`` with a ``sets`` axis.

    Pads the batch up to a multiple of the mesh size (padded entries masked
    invalid), draws fresh 64-bit scalars host-side, and runs the dp +
    ICI-combine kernel.
    """
    if n_real == 0:
        return False
    n_dev = mesh.devices.size
    n = pk_agg.shape[0]
    # power-of-two bucket (shape-stable compiles), rounded to a mesh multiple
    n_pad = ((bucket(max(n, n_dev)) + n_dev - 1) // n_dev) * n_dev
    if n_pad != n:
        pad = n_pad - n

        def _pad(a):
            return jnp.concatenate(
                [a, jnp.zeros((pad,) + a.shape[1:], dtype=a.dtype)], axis=0
            )

        pk_agg, sig, msg_x, msg_y = map(_pad, (pk_agg, sig, msg_x, msg_y))
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n_pad)], dtype=np.uint64
    )
    valid = np.arange(n_pad) < n_real
    ok = _sharded_verify_kernel(mesh, n_pad)(
        pk_agg, sig, msg_x, msg_y, jnp.asarray(scalars), jnp.asarray(valid)
    )
    return bool(np.asarray(ok))


def verify_signature_sets_sharded_h2c(pk_agg, sig, u0, u1, n_real: int,
                                      mesh) -> bool:
    """Sharded twin of ``verify_signature_sets_device_h2c`` — the generic
    ``bls.verify_signature_sets`` seam's mesh path: device h2c + prologue +
    Miller partials data-parallel over the ``sets`` axis, cross-device
    G2-MSM / Fq12-product combine, ONE final exponentiation. Inputs may be
    padded to any length ≥ n_real; they are re-padded to a mesh-multiple
    bucket here (broadcast, masked invalid)."""
    if n_real == 0:
        return False
    n_dev = mesh.devices.size
    n = pk_agg.shape[0]
    n_pad = ((bucket(max(n, n_dev)) + n_dev - 1) // n_dev) * n_dev
    if n_pad != n:
        def _pad(a):
            return jnp.concatenate(
                [a, jnp.broadcast_to(a[:1], (n_pad - n,) + a.shape[1:])]
            )

        pk_agg, sig, u0, u1 = map(_pad, (pk_agg, sig, u0, u1))
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n_pad)],
        dtype=np.uint64,
    )
    valid = np.arange(n_pad) < n_real
    mx, my = _sharded_h2c_stage(mesh, n_pad)(u0, u1)
    ok = _sharded_verify_kernel(mesh, n_pad)(
        pk_agg, sig, mx, my, jnp.asarray(scalars), jnp.asarray(valid)
    )
    return bool(np.asarray(ok))


def verify_signature_sets_device(pk_agg, sig, msg_x, msg_y, n_real: int) -> bool:
    """pk_agg [n,3,25], sig [n,6,25], msg affine x/y [n,2,25]; first n_real
    entries are real. Draws fresh nonzero 64-bit scalars host-side."""
    n = pk_agg.shape[0]
    if n_real == 0:
        return False
    scalars = np.array(
        [secrets.randbits(RAND_BITS) or 1 for _ in range(n)], dtype=np.uint64
    )
    valid = np.arange(n) < n_real
    ok = _verify_kernel(n)(
        pk_agg, sig, msg_x, msg_y, jnp.asarray(scalars), jnp.asarray(valid)
    )
    return bool(np.asarray(ok))
