"""Backend-pluggable BLS12-381 seam — the TPU twin of ``crypto/bls``.

The reference exposes generic wrapper types made concrete per backend by the
``define_mod!`` macro (``/root/reference/crypto/bls/src/lib.rs:87-142``) with
backends selected by cargo feature (blst / fake_crypto). Here the same seam is
a module-level backend registry: ``oracle`` (pure-Python, the trusted
reference implementation) and ``tpu`` (JAX device kernels). Everything above
this package is backend-blind: it sees ``PublicKey``/``Signature``/
``AggregateSignature``/``SecretKey``/``SignatureSet`` and the free function
``verify_signature_sets``.

Wire formats match the reference exactly: 48-byte compressed G1 pubkeys,
96-byte compressed G2 signatures, 32-byte secret keys
(``generic_public_key.rs``, ``generic_signature.rs``, ``generic_secret_key.rs``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..ops.bls_oracle import ciphersuite as _cs
from ..ops.bls_oracle import curves as _oc
from ..ops.bls_oracle.fields import R as CURVE_ORDER

PUBLIC_KEY_BYTES_LEN = 48
SIGNATURE_BYTES_LEN = 96
SECRET_KEY_BYTES_LEN = 32

INFINITY_PUBLIC_KEY = b"\xc0" + b"\x00" * 47
INFINITY_SIGNATURE = b"\xc0" + b"\x00" * 95

_BACKEND = "tpu"


def set_backend(name: str) -> None:
    global _BACKEND
    if name not in ("tpu", "oracle", "native"):
        raise ValueError(f"unknown bls backend {name!r}")
    _BACKEND = name


def get_backend() -> str:
    return _BACKEND


def _native():
    from ..native.build import NativeBls

    return NativeBls()


class BlsError(Exception):
    """Deserialization / validation failure (reference: bls::Error)."""


@dataclass(frozen=True)
class PublicKey:
    """Validated G1 public key (decompressed, subgroup-checked on parse —
    key_validate semantics, blst.rs:75)."""

    point: tuple  # oracle affine G1 point

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        if len(data) != PUBLIC_KEY_BYTES_LEN:
            raise BlsError(f"invalid pubkey length {len(data)}")
        try:
            pt = _oc.g1_decompress(data)
        except ValueError as e:
            raise BlsError(str(e)) from None
        if pt is None or not _oc.g1_in_subgroup(pt):
            raise BlsError("pubkey not a valid subgroup point")
        return cls(pt)

    def serialize(self) -> bytes:
        return _oc.g1_compress(self.point)

    def __hash__(self):
        return hash(self.point)


@dataclass(frozen=True)
class Signature:
    """G2 signature. Parsed lazily-strict: bytes must decode to an on-curve
    point (or infinity); subgroup check happens at verification time, matching
    the reference's deserialize-then-groupcheck split."""

    point: object  # oracle affine G2 point or None (infinity)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        if len(data) != SIGNATURE_BYTES_LEN:
            raise BlsError(f"invalid signature length {len(data)}")
        try:
            pt = _oc.g2_decompress(data)
        except ValueError as e:
            raise BlsError(str(e)) from None
        return cls(pt)

    def serialize(self) -> bytes:
        return _oc.g2_compress(self.point)

    def verify(self, pubkey: PublicKey, message: bytes) -> bool:
        # single-op dispatch: native backend verifies in C++; the tpu backend
        # delegates singles to the oracle (device round-trips only pay off in
        # batches — verify_signature_sets is the batched path)
        if _BACKEND == "native":
            return _native().verify(
                pubkey.serialize(), message, _oc.g2_compress(self.point)
            )
        return _cs.verify(pubkey.point, message, self.point)


@dataclass(frozen=True)
class AggregateSignature:
    point: object

    @classmethod
    def infinity(cls) -> "AggregateSignature":
        return cls(None)

    @classmethod
    def aggregate(cls, sigs) -> "AggregateSignature":
        acc = None
        for s in sigs:
            acc = _oc.g2_add(acc, s.point)
        return cls(acc)

    def add_assign(self, sig: Signature) -> "AggregateSignature":
        return AggregateSignature(_oc.g2_add(self.point, sig.point))

    def serialize(self) -> bytes:
        return _oc.g2_compress(self.point)

    @classmethod
    def from_bytes(cls, data: bytes) -> "AggregateSignature":
        return cls(Signature.from_bytes(data).point)

    def fast_aggregate_verify(self, message: bytes, pubkeys) -> bool:
        if _BACKEND == "native":
            return _native().fast_aggregate_verify(
                [pk.serialize() for pk in pubkeys],
                message,
                _oc.g2_compress(self.point),
            )
        return _cs.fast_aggregate_verify(
            [pk.point for pk in pubkeys], message, self.point
        )

    def aggregate_verify(self, messages, pubkeys) -> bool:
        return _cs.aggregate_verify(
            [pk.point for pk in pubkeys], messages, self.point
        )


@dataclass(frozen=True)
class SecretKey:
    scalar: int

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != SECRET_KEY_BYTES_LEN:
            raise BlsError(f"invalid secret key length {len(data)}")
        sk = int.from_bytes(data, "big")
        if sk == 0 or sk >= CURVE_ORDER:
            raise BlsError("secret key out of range")
        return cls(sk)

    @classmethod
    def keygen(cls, ikm: bytes, key_info: bytes = b"") -> "SecretKey":
        return cls(_cs.keygen_from_ikm(ikm, key_info))

    def serialize(self) -> bytes:
        return self.scalar.to_bytes(32, "big")

    def public_key(self) -> PublicKey:
        return PublicKey(_cs.sk_to_pk(self.scalar))

    def sign(self, message: bytes) -> Signature:
        if _BACKEND == "native":
            return Signature.from_bytes(
                _native().sign(self.serialize(), message)
            )
        return Signature(_cs.sign(self.scalar, message))


@dataclass
class SignatureSet:
    """One batch-verification task (generic_signature_set.rs:61-72)."""

    signature: object       # Signature | AggregateSignature
    signing_keys: list      # list[PublicKey]
    message: bytes          # 32-byte signing root

    @classmethod
    def single_pubkey(cls, signature, signing_key, message) -> "SignatureSet":
        return cls(signature, [signing_key], message)

    @classmethod
    def multiple_pubkeys(cls, signature, signing_keys, message) -> "SignatureSet":
        return cls(signature, signing_keys, message)


def _verify_sets_oracle(sets) -> bool:
    return _cs.verify_signature_sets(
        [
            _cs.SignatureSet(
                s.signature.point, [pk.point for pk in s.signing_keys], s.message
            )
            for s in sets
        ]
    )


def _verify_sets_tpu(sets) -> bool:
    import jax.numpy as jnp

    from . import tpu_backend as tb
    from ..ops.bls import g1 as dg1, g2 as dg2
    from ..ops.bls import h2c as dh2c
    from ..ops.bls_oracle.ciphersuite import DST

    n = len(sets)
    if n == 0:
        return False
    for s in sets:
        if s.signature.point is None or not s.signing_keys:
            return False
    n_pad = tb.bucket(n)
    pk_pts = [
        dg1.from_oracle_batch([pk.point for pk in s.signing_keys]) for s in sets
    ]
    pk_agg = tb.aggregate_pubkeys_device(pk_pts)
    pk_agg = jnp.concatenate(
        [pk_agg, jnp.broadcast_to(pk_agg[:1], (n_pad - n,) + pk_agg.shape[1:])]
    ) if n_pad > n else pk_agg
    sig = dg2.from_oracle_batch([s.signature.point for s in sets])
    # device h2c: host SHA-256 hash_to_field; SSWU/isogeny/cofactor fuse into
    # the verification kernel (one jit) — no oracle pairing-tower hashing and
    # no eager op-by-op dispatch on the hot path
    u0, u1 = dh2c.hash_to_field_batch([s.message for s in sets], DST)
    if n_pad > n:  # pad by broadcast, not by hashing dummy messages
        pad = lambda a: jnp.concatenate(
            [a, jnp.broadcast_to(a[:1], (n_pad - n,) + a.shape[1:])]
        )
        sig, u0, u1 = pad(sig), pad(u0), pad(u1)
    # serving-mesh seam (LIGHTHOUSE_MESH_DEVICES): data-parallel over the
    # device mesh with a cross-device combine; off (the default) keeps the
    # single-device kernel bit-identical to the pre-mesh engine
    from . import mesh as bls_mesh

    n_mesh = bls_mesh.serving_mesh_size()
    if n_mesh > 1:
        return tb.verify_signature_sets_sharded_h2c(
            pk_agg, sig, u0, u1, n, bls_mesh.get_mesh(tuple(range(n_mesh)))
        )
    return tb.verify_signature_sets_device_h2c(pk_agg, sig, u0, u1, n)


def _verify_sets_native(sets) -> bool:
    import secrets

    from ..native.build import NativeBls
    from .tpu_backend import RAND_BITS

    nb = NativeBls()
    try:
        return nb.verify_signature_sets(
            [[pk.serialize() for pk in s.signing_keys] for s in sets],
            [s.message for s in sets],
            [s.signature.serialize() for s in sets],
            [secrets.randbits(RAND_BITS) or 1 for _ in sets],
        )
    except ValueError:
        return False


def verify_signature_sets(sets) -> bool:
    """Random-linear-combination batch verification over the active backend."""
    sets = list(sets)
    if _BACKEND == "oracle":
        return _verify_sets_oracle(sets)
    if _BACKEND == "native":
        return _verify_sets_native(sets)
    return _verify_sets_tpu(sets)


def verify_signature_sets_oracle(sets) -> bool:
    """Batch verification pinned to the pure-Python oracle regardless of the
    active backend — the degradation ladder's CPU rung of last resort
    (resilience.supervisor): always available, trusted, device-free."""
    return _verify_sets_oracle(list(sets))


def warmup(n_sets: int = 2) -> bool:
    """Pre-compile the active backend's verification kernels.

    On the device backend the first verify of each bucket shape triggers XLA
    compilation (tens of seconds on a cold TPU). Serving paths run this at
    startup (Client.start) so block publication never pays the compile inside
    an HTTP request — the analog of blst having no warm-up cost at all.
    Returns the verification verdict (True on a healthy backend)."""
    import hashlib

    sk = SecretKey.from_bytes((7).to_bytes(32, "big"))
    pk = sk.public_key()
    # messages must be 32-byte signing roots (the only shape the real
    # pipeline ever verifies; the native backend enforces it)
    msgs = [
        hashlib.sha256(b"lighthouse-tpu-warmup-%02d" % i).digest()
        for i in range(n_sets)
    ]
    sets = [
        SignatureSet.single_pubkey(sk.sign(m), pk, m) for m in msgs
    ]
    ok = verify_signature_sets(sets[:1])
    if n_sets > 1:
        ok = verify_signature_sets(sets) and ok
    return ok
