"""In-process test harness (test_utils.rs twin)."""

from .harness import StateHarness
