"""Light-client session fabricator shared by bench --light-clients, the
engine tests, and the conformance KATs.

Sessions are REAL: interop validators, real sync-committee aggregate
signatures over real signing roots — only the attested headers are
synthetic (deterministic per seed), since the signature check is blind to
whether the header root is on any chain. Heterogeneity knobs: per-session
bitfields, attested slots, and signature slots all vary.
"""

from __future__ import annotations

import numpy as np

from ..ops.bls_oracle.fields import R as CURVE_ORDER
from ..types.containers import BeaconBlockHeader, for_preset
from ..light_client.types import light_client_types
from ..light_client.verify import sync_signing_root


def fabricate_lc_sessions(harness, n_sessions: int, seed: int = 0):
    """Build ``n_sessions`` heterogeneous optimistic-update sessions signed
    by ``harness.state``'s current sync committee.

    Returns ``(sessions, genesis_validators_root)`` where sessions is a
    list of ``(update, sync_committee)`` pairs — the shape
    ``light_client.engine.verify_update_batch`` consumes."""
    spec = harness.spec
    state = harness.state
    ns = for_preset(spec.preset.name)
    fork = spec.fork_name_at_slot(int(state.slot))
    lc = light_client_types(spec.preset.name, fork)
    committee = state.current_sync_committee
    gvr = bytes(state.genesis_validators_root)
    pk_to_idx = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    c = len(committee.pubkeys)
    floor = int(spec.preset.MIN_SYNC_COMMITTEE_PARTICIPANTS)
    rng = np.random.default_rng(seed)
    sessions = []
    for i in range(n_sessions):
        bits = rng.random(c) < 0.75
        while bits.sum() < max(floor, 1):
            bits[rng.integers(0, c)] = True
        hdr = lc.LightClientHeader(
            beacon=BeaconBlockHeader(
                slot=int(state.slot) + i,
                proposer_index=i % max(1, len(state.validators)),
                parent_root=rng.bytes(32),
                state_root=rng.bytes(32),
                body_root=rng.bytes(32),
            )
        )
        update = lc.LightClientOptimisticUpdate(
            attested_header=hdr,
            sync_aggregate=ns.SyncAggregate(
                sync_committee_bits=np.array(bits, dtype=bool),
                sync_committee_signature=b"\x00" * 96,
            ),
            signature_slot=int(state.slot) + i + 1,
        )
        root = sync_signing_root(spec, update, gvr)
        agg_sk = 0
        for j in range(c):
            if bits[j]:
                idx = pk_to_idx[bytes(committee.pubkeys[j])]
                agg_sk = (agg_sk + harness.sks[idx]) % CURVE_ORDER
        update.sync_aggregate.sync_committee_signature = harness._nb.sign(
            agg_sk.to_bytes(32, "big"), root
        )
        sessions.append((update, committee))
    return sessions, gvr


def tamper_session(session, mode: str = "signature"):
    """Corrupted copy of a fabricated session for reject-path tests:
    ``signature`` flips a byte in the aggregate signature, ``header``
    re-signs nothing while changing the attested header (stale sig)."""
    update, committee = session
    u = type(update).decode(update.serialize())
    if mode == "signature":
        sig = bytearray(bytes(u.sync_aggregate.sync_committee_signature))
        sig[50] ^= 0x01
        u.sync_aggregate.sync_committee_signature = bytes(sig)
    elif mode == "header":
        u.attested_header.beacon.state_root = b"\xfe" * 32
    else:
        raise ValueError(f"unknown tamper mode {mode!r}")
    return (u, committee)
