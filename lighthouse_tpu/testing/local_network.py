"""LocalNetwork: N in-process beacon nodes over the loopback transport.

Twin of ``testing/simulator/src/local_network.rs:128`` + ``checks.rs``:
validators are partitioned across nodes, every slot the owning node proposes
and publishes the block over gossip, every node's validators attest over
gossip (feeding each node's op pool through the batched verification path),
and the checks assert finalization advances on ALL nodes.

Chaos harness (ISSUE 7): ``crash_node``/``restart_node`` plus the loopback
transport's seeded gossip loss and the ``LIGHTHOUSE_FAULT_INJECT`` device
fault injector make a deterministic multi-node churn scenario —
``tests/test_resilience.py`` runs N slots under injected device faults,
dropped gossip, and a node crash/restart, asserting liveness, zero
false-verifies, and the drop-rate SLO.

Crash-point harness (ISSUE 12): with ``datadir=`` every node persists into
its own WAL-backed store, the ``mode=kill``/``mode=tear`` injection plans
can kill a node at any persistence barrier mid-slot (``run_slot`` plays the
OS: it catches ``InjectedCrash``, attributes it via the store's owner tag,
and hard-crashes exactly that node), and ``restart_node(i, from_disk=True)``
recovers chain + fork choice + op pool + slasher checkpoint from disk —
``tests/test_crash_recovery.py`` sweeps the barriers and asserts the
recovery invariants.
"""

from __future__ import annotations

import hashlib

import numpy as np

from ..network import BeaconNodeService, LoopbackTransport
from ..state_transition import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_committee_count_per_slot,
    get_current_epoch,
    process_slots,
)
from ..types.containers import AttestationData, Checkpoint, SigningData
from ..types.helpers import compute_signing_root, get_domain
from ..types.spec import ChainSpec
from ..ssz import uint64
from ..utils.slot_clock import ManualSlotClock
from .harness import StateHarness


class LocalNetwork:
    def __init__(self, spec: ChainSpec, n_nodes: int, n_validators: int,
                 transport: str = "loopback", slasher: bool = False,
                 datadir: str | None = None, sync_committee: bool = False):
        assert n_validators % n_nodes == 0
        self.spec = spec
        self.mode = transport
        self.slasher_enabled = slasher
        # opt-in sync-committee duties (ISSUE 17): each slot every node's
        # owned committee members sign the head root over gossip, so altair+
        # blocks carry REAL sync aggregates and the light-client server
        # caches produce updates. Off by default: it adds one aggregate
        # pairing per imported block to every scenario that doesn't need it.
        self.sync_committee = sync_committee
        # per-node datadirs (loopback mode): each node persists into its
        # own WAL-backed hot/cold store, making restart_node(from_disk=True)
        # — and the crash-point sweep killing nodes at persistence barriers
        # — possible. None keeps the seed's in-memory stores.
        self.datadir = datadir
        self.recovery_reports: list[dict] = []  # one per from-disk restart
        self.dead: set[int] = set()   # crashed node indices (chaos harness)
        self.missed_proposals = 0     # invalid-on-own-chain proposals skipped
        self._chaos_seen = False      # any crash/loss ever armed this run
        self.clock = ManualSlotClock(0)
        # one harness supplies genesis + deterministic keys; each node only
        # "owns" (signs with) its shard of the validator set
        self.harness = StateHarness(spec, n_validators)
        self.nodes: list[BeaconNodeService] = []
        self.boot = None
        per = n_validators // n_nodes
        self.owned: list[range] = []
        if transport == "loopback":
            self.transport = LoopbackTransport()
            # a recipient's barrier firing mid-delivery kills THAT node
            # only; the publisher's fan-out continues (kill -9 semantics)
            self.transport.on_injected_crash = self._on_injected_crash
            for i in range(n_nodes):
                svc = BeaconNodeService(
                    f"node_{i}",
                    spec,
                    self.harness.state.copy(),
                    self.transport,
                    slot_clock=self.clock,
                    execution_layer=self.harness.el,
                    chain=self._make_chain(i),
                )
                self.nodes.append(svc)
                self.owned.append(range(i * per, (i + 1) * per))
            for svc in self.nodes:
                for peer in self.transport.peers(exclude=svc.node_id):
                    svc.connect(peer)
        elif transport == "sockets":
            # real TCP gossip/RPC + UDP boot-node discovery: the same node
            # stack over lighthouse_tpu.network.socket_transport
            import time as _time

            from ..network.boot_node import BootNode
            from ..network.gossipsub import GossipsubTransport

            self.boot = BootNode().start()
            for i in range(n_nodes):
                t = GossipsubTransport(spec)
                svc = BeaconNodeService(
                    t.local_addr,
                    spec,
                    self.harness.state.copy(),
                    t,
                    slot_clock=self.clock,
                    execution_layer=self.harness.el,
                )
                t.discover(self.boot.local_addr)
                self.nodes.append(svc)
                self.owned.append(range(i * per, (i + 1) * per))
            # wait for the mesh to fully connect under CANONICAL addresses
            # (HELLO rekeys accept-side ephemeral entries), then handshake
            addrs = {n.node_id for n in self.nodes}
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                if all(
                    set(n.transport.peers()) == addrs - {n.node_id}
                    for n in self.nodes
                ):
                    break
                _time.sleep(0.01)
            for svc in self.nodes:
                for peer in svc.transport.peers():
                    svc.connect(peer)
        else:
            raise ValueError(f"unknown transport mode {transport!r}")
        if slasher:
            for svc in self.nodes:
                self._attach_slasher(svc)
        self._msg_total = 0  # messages published so far (settle accounting)
        # PeerDAS (ISSUE 16): armed by enable_peerdas(); slot -> blob plan
        self.cell_ctx = None
        self._peerdas_cfg = None
        self._blob_plan: dict[int, tuple[list, set[int]]] = {}

    def _make_store(self, i: int):
        """Per-node WAL-backed hot/cold store under ``datadir`` (or None).
        fsync stays off — the chaos harness tears writes at the WAL frame
        layer deterministically; it does not simulate power loss — and the
        ``owner`` tag lets ``InjectedCrash`` name the node that died."""
        if self.datadir is None:
            return None
        import os

        from ..store.hot_cold import HotColdDB, StoreConfig
        from ..store.kv import LevelStore

        d = os.path.join(self.datadir, f"node_{i}")
        return HotColdDB(
            hot=LevelStore(
                os.path.join(d, "chain.db"), fsync=False, owner=f"node_{i}"
            ),
            cold=LevelStore(
                os.path.join(d, "freezer.db"), fsync=False, owner=f"node_{i}"
            ),
            config=StoreConfig(),
        )

    def _make_chain(self, i: int):
        """A chain over the node's durable store, or None (the service
        builds its own in-memory chain — the seed behavior)."""
        store = self._make_store(i)
        if store is None:
            return None
        from ..beacon_chain.chain import BeaconChain

        return BeaconChain(
            self.spec,
            self.harness.state.copy(),
            store=store,
            slot_clock=self.clock,
            execution_layer=self.harness.el,
        )

    def _attach_slasher(self, svc) -> None:
        """Per-node slasher service on the chain's ingest seams: every
        gossip-verified attestation and every imported block (gossip AND
        range sync) flows into the engine; ``run_slot`` ticks it so found
        slashings drain into the node's op pool and ride the next proposal
        (the full gossip -> slasher -> op_pool -> block-inclusion loop).
        With per-node datadirs the engine checkpoints into the node's hot
        store each tick and ``make_slasher`` restores the checkpoint on a
        from-disk restart — pre-restart votes still convict."""
        from ..slasher import SlasherConfig, SlasherService, make_slasher

        sl = make_slasher(
            svc.chain.store.hot if self.datadir is not None else None,
            svc.chain.ns,
            SlasherConfig(validator_chunk_size=16, history_length=64),
        )
        svc.slasher_service = SlasherService(svc.chain, sl, svc.op_pool)
        svc.chain.block_observers.append(svc.slasher_service.block_observed)
        svc.chain.attestation_observers.append(
            svc.slasher_service.attestation_observed
        )

    def settle(self, timeout: float = 5.0) -> None:
        """Wait until every node has RECEIVED and PROCESSED every message
        published so far (socket mode; loopback is synchronous). Exact
        accounting: each node's gossip dedup cache must hold all published
        message ids, and its processor must be idle."""
        if self.mode == "loopback":
            return
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            if all(
                n.transport.delivered + n.transport.published
                >= self._msg_total
                for n in self.nodes
            ):
                return
            _time.sleep(0.005)
        raise TimeoutError(
            f"gossip did not settle: want {self._msg_total}, have "
            f"{[(n.transport.delivered, n.transport.published) for n in self.nodes]}"
        )

    def stop(self) -> None:
        if self.mode == "sockets":
            for n in self.nodes:
                n.stop()
            if self.boot is not None:
                self.boot.stop()

    def _owner_of(self, validator_index: int) -> BeaconNodeService:
        return self.nodes[self._owner_index(validator_index)]

    def _owner_index(self, validator_index: int) -> int:
        for i, rng in enumerate(self.owned):
            if validator_index in rng:
                return i
        raise ValueError(validator_index)

    def _chaos_active(self) -> bool:
        """Has any chaos mechanism ever been armed (crash or gossip loss)?
        Non-chaos runs keep strict semantics: a proposal its own node
        rejects is a test failure, never a silently missed slot."""
        # sockets mode has per-node transports and no shared self.transport
        shared = getattr(self, "transport", None)
        return (
            self._chaos_seen
            or bool(self.dead)
            or getattr(shared, "_loss_rate", 0.0) > 0
        )

    def _alive_ref(self) -> BeaconNodeService:
        for i, node in enumerate(self.nodes):
            if i not in self.dead:
                return node
        raise RuntimeError("every node is crashed")

    # -- chaos harness (crash / restart; loopback mode) --------------------

    def crash_node(self, i: int) -> None:
        """Hard-crash node ``i``: unregister it from the transport (no more
        gossip/RPC in either direction). Its validators stop attesting and
        its proposal slots are simply missed — the liveness the chaos
        scenario asserts must survive that."""
        assert self.mode == "loopback", "crash/restart drives the loopback sim"
        node = self.nodes[i]
        self.transport.unregister(node.node_id)
        self.dead.add(i)
        self._chaos_seen = True

    def reconnect_all(self) -> None:
        """Status-handshake every live pair (the chaos epilogue): a
        straggler that missed tip blocks under gossip loss range-syncs
        back to the canonical head."""
        for i, svc in enumerate(self.nodes):
            if i in self.dead:
                continue
            for peer in self.transport.peers(exclude=svc.node_id):
                try:
                    svc.connect(peer)
                except ConnectionError:
                    pass

    def restart_node(self, i: int, from_disk: bool = False) -> None:
        """Restart node ``i`` under the same id and status-handshake every
        live peer.

        ``from_disk=False``: restart from genesis state (the datadir-wiped
        worst case) — range sync walks it back to the head, exactly the
        partitioned-node recovery path.

        ``from_disk=True`` (needs ``datadir``): reopen the node's stores —
        WAL replay truncates any torn tail — and rebuild chain + fork
        choice + op pool (+ the slasher checkpoint via ``_attach_slasher``)
        through ``beacon_chain.recovery``: the node comes back AT its last
        persisted head, no range sync from genesis. The recovery report is
        appended to ``self.recovery_reports``."""
        assert i in self.dead, f"node {i} is not crashed"
        if from_disk:
            assert self.datadir is not None, "from_disk needs datadirs"
            old_store = self.nodes[i].chain.store
            for kv in (old_store.hot, old_store.cold):
                try:
                    kv.close()  # release the dead process's file handles
                except Exception:  # noqa: BLE001 — already torn/closed
                    pass
            from ..beacon_chain.recovery import recover_node_state

            chain, op_pool, report = recover_node_state(
                self.spec,
                self.harness.state.copy(),
                self._make_store(i),
                slot_clock=self.clock,
                execution_layer=self.harness.el,
            )
            self.recovery_reports.append(report)
            svc = BeaconNodeService(
                f"node_{i}",
                self.spec,
                transport=self.transport,
                chain=chain,
                op_pool=op_pool,
            )
        else:
            # genesis restart deliberately ignores any datadir (it models
            # the wiped-disk case): in-memory stores, range sync rebuilds
            svc = BeaconNodeService(
                f"node_{i}",
                self.spec,
                self.harness.state.copy(),
                self.transport,
                slot_clock=self.clock,
                execution_layer=self.harness.el,
            )
        self.nodes[i] = svc
        self.dead.discard(i)
        if self.slasher_enabled:
            self._attach_slasher(svc)
        if self._peerdas_cfg is not None:
            # same node id digest => same custody set as before the crash
            self._enable_peerdas_on(svc)
        for peer in self.transport.peers(exclude=svc.node_id):
            try:
                svc.connect(peer)
            except ConnectionError:
                pass

    # -- PeerDAS (ISSUE 16) ------------------------------------------------

    def enable_peerdas(self, cell_ctx, custody_count: int | None = None,
                       samples_per_slot: int | None = None) -> None:
        """Arm column sampling on every node: each gets a deterministic
        node-id digest (so custody sets differ per node but are stable
        across restarts) and blob-carrying proposals gate availability on
        the sampler's custody + sampled columns."""
        assert self.mode == "loopback", "peerdas churn drives the loopback sim"
        self.cell_ctx = cell_ctx
        self._peerdas_cfg = (cell_ctx, custody_count, samples_per_slot)
        for svc in self.nodes:
            self._enable_peerdas_on(svc)

    def _enable_peerdas_on(self, svc) -> None:
        ctx, custody, samples = self._peerdas_cfg
        svc.chain.enable_peerdas(
            ctx,
            hashlib.sha256(svc.node_id.encode()).digest(),
            custody_count=custody,
            samples_per_slot=samples,
        )

    def schedule_blobs(self, slot: int, blobs: list,
                       withhold: set[int] | None = None) -> None:
        """The proposal at ``slot`` carries ``blobs`` as KZG commitments;
        columns whose index is in ``withhold`` are never built onto the
        wire (the withholding-attack scenario — the block must stay
        unavailable everywhere unless reconstruction can cover them)."""
        self._blob_plan[int(slot)] = (list(blobs), set(withhold or ()))

    def retry_columns(self, block_root: bytes) -> None:
        """Sampler retry tick: every live node with missing required
        columns re-fetches them over by-root RPC from each live peer (the
        gossip-loss repair path; reconstruction kicks in inside the
        availability check once >= 50% of columns are held)."""
        for i, svc in enumerate(self.nodes):
            if i in self.dead or svc.chain.peerdas is None:
                continue
            if not svc.chain.peerdas.missing_columns(block_root):
                self._guarded(svc._try_column_availability, block_root)
                continue
            for j, peer in enumerate(self.nodes):
                if j == i or j in self.dead:
                    continue
                self._guarded(
                    svc._fetch_missing_columns, block_root, peer.node_id
                )
                if not svc.chain.peerdas.missing_columns(block_root):
                    break

    # -- per-slot duties ---------------------------------------------------

    def _propose(self, slot: int) -> None:
        spec = self.spec
        # duty lookup on any live node's head (all agree or sync catches up)
        ref = self._alive_ref().chain
        state = ref.head.state.copy()
        if state.slot < slot:
            process_slots(spec, state, slot)
        proposer = get_beacon_proposer_index(spec, state)
        if self._owner_index(proposer) in self.dead:
            return  # a crashed node misses its proposal slot
        node = self._owner_of(proposer)

        chain = node.chain
        epoch = get_current_epoch(spec, state)
        domain_r = get_domain(spec, state, spec.DOMAIN_RANDAO, epoch=epoch)
        randao_root = SigningData(
            object_root=uint64.hash_tree_root(epoch), domain=domain_r
        ).tree_root()
        reveal = self.harness._sign(proposer, randao_root)
        atts = node.op_pool.get_attestations(state)
        # op_pool rides along so pooled slashing evidence (the slasher
        # service drains into it each slot) is included in the block
        block, _post = chain.produce_block_on_state(
            chain.head.state, slot, reveal, attestations=atts,
            op_pool=node.op_pool,
        )
        plan = self._blob_plan.get(slot)
        if plan is not None and self.cell_ctx is not None:
            # blob-carrying proposal: graft the commitments onto the
            # produced body, then recompute state_root against the SAME
            # pre-state the block was built on (the harness's genesis-based
            # resign recipe would miss every imported block)
            blobs, _withhold = plan
            block.body.blob_kzg_commitments = [
                self.cell_ctx.kzg.blob_to_kzg_commitment(b) for b in blobs
            ]
            from ..state_transition import (
                BlockSignatureStrategy,
                per_block_processing,
            )

            fork = spec.fork_name_at_epoch(epoch)
            block_cls = node.chain.ns.block_types[fork]
            trial = chain.head.state.copy()
            if trial.slot < slot:
                process_slots(spec, trial, slot)
            block.state_root = b"\x00" * 32
            per_block_processing(
                spec, trial, block_cls(message=block, signature=b"\x00" * 96),
                strategy=BlockSignatureStrategy.NO_VERIFICATION,
                verify_block_root=False,
            )
            block.state_root = trial.tree_root()
        fork = spec.fork_name_at_epoch(epoch)
        block_cls = node.chain.ns.block_types[fork]
        domain_b = get_domain(spec, state, spec.DOMAIN_BEACON_PROPOSER, epoch=epoch)
        sig = self.harness._sign(proposer, compute_signing_root(block, domain_b))
        signed = block_cls(message=block, signature=sig)
        from ..beacon_chain.chain import BlockPendingAvailability

        try:
            node.chain.process_block(signed)
        except BlockPendingAvailability:
            pass  # parked: imports once the proposer's own columns land
        except Exception:  # noqa: BLE001 — chaos realism: a proposer
            # whose head/pool diverged under gossip loss builds a block
            # its own chain rejects; a real network misses that slot
            if not self._chaos_active():
                raise
            self.missed_proposals += 1
            return
        node.publish_block(signed)
        self._msg_total += 1
        if plan is not None and self.cell_ctx is not None:
            self._publish_columns(node, signed, plan)

    def _publish_columns(self, node, signed, plan) -> None:
        """Build the proposal's column sidecars and fan them out. The
        loopback bus excludes the publisher, so the proposer self-ingests
        each column through the same verified gossip path; withheld
        indices never reach the wire at all."""
        from ..beacon_chain.data_columns import make_data_column_sidecars

        blobs, withhold = plan
        columns = make_data_column_sidecars(
            node.chain.ns, signed, blobs, self.cell_ctx
        )
        for sc in columns:
            if int(sc.index) in withhold:
                continue
            self._guarded(node.process_gossip_data_column, sc)
            node.publish_data_column(sc)
            self._msg_total += 1
        # straggler repair + availability re-check on every live node
        self.retry_columns(signed.message.tree_root())

    def _attest(self, slot: int) -> None:
        # per-node guard: one attester dying at its own barrier must not
        # cost the OTHER nodes their attestations for the slot
        for i, (node, owned) in enumerate(zip(self.nodes, self.owned)):
            if i in self.dead:
                continue
            self._guarded(self._attest_node, node, owned, slot)

    def _attest_node(self, node, owned, slot: int) -> None:
        spec = self.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        state = node.chain.head.state
        if state.slot < slot:
            state = state.copy()
            process_slots(spec, state, slot)
        head_root = node.chain.head.root
        target_root = (
            head_root
            if slot == spec.start_slot(epoch)
            else _block_root_at(spec, state, spec.start_slot(epoch))
        )
        domain = get_domain(
            spec, state, spec.DOMAIN_BEACON_ATTESTER, epoch=epoch
        )
        for index in range(get_committee_count_per_slot(spec, state, epoch)):
            committee = get_beacon_committee(spec, state, slot, index)
            data = AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=state.current_justified_checkpoint,
                target=Checkpoint(epoch=epoch, root=target_root),
            )
            root = compute_signing_root(data, domain)
            for pos, v in enumerate(committee):
                if int(v) not in owned:
                    continue
                bits = np.zeros(committee.size, dtype=bool)
                bits[pos] = True
                att = node.chain.ns.Attestation(
                    aggregation_bits=bits,
                    data=data,
                    signature=self.harness._sign(int(v), root),
                )
                node.op_pool.insert_attestation(att)
                node.publish_attestation(att)
                self._msg_total += 1

    def _sync_sign(self, slot: int) -> None:
        # per-node guard, like _attest: one signer dying at its own barrier
        # must not cost the other nodes their sync messages for the slot
        for i, (node, owned) in enumerate(zip(self.nodes, self.owned)):
            if i in self.dead:
                continue
            self._guarded(self._sync_sign_node, node, owned, slot)

    def _sync_sign_node(self, node, owned, slot: int) -> None:
        """Sync-committee duties for ``node``'s owned validators: one
        SyncCommitteeMessage per owned committee member over the node's own
        head root, self-ingested (the loopback bus excludes the publisher)
        and published. The NEXT slot's proposer pools them into its block's
        sync aggregate (``produce_block_on_state`` reads slot-1)."""
        from ..types.helpers import sync_committee_signing_root

        state = node.chain.head.state
        if not hasattr(state, "current_sync_committee"):
            return  # pre-altair: no sync committees yet
        head_root = node.chain.head.root
        root = sync_committee_signing_root(self.spec, state, slot, head_root)
        pk_to_idx = {
            bytes(v.pubkey): i for i, v in enumerate(state.validators)
        }
        msgs, seen = [], set()
        for pk in state.current_sync_committee.pubkeys:
            v = pk_to_idx[bytes(pk)]
            # one message per validator: the pool expands every committee
            # position a duplicated member occupies from the single message
            if v not in owned or v in seen:
                continue
            seen.add(v)
            msgs.append(
                node.chain.ns.SyncCommitteeMessage(
                    slot=slot,
                    beacon_block_root=head_root,
                    validator_index=v,
                    signature=self.harness._sign(v, root),
                )
            )
        if not msgs:
            return
        node.process_gossip_sync_message_batch(msgs)
        for m in msgs:
            node.publish_sync_message(m)
            self._msg_total += 1

    # -- crash-point attribution (ISSUE 12) --------------------------------

    def _on_injected_crash(self, exc) -> int:
        """An ``InjectedCrash`` surfaced mid-slot: the "operating system"
        half of the harness. The owner tag (set on each node's WAL stores)
        names the node whose persistence barrier fired; that node is
        hard-crashed and the slot continues for everyone else."""
        owner = getattr(exc, "owner", None)
        if not owner or not owner.startswith("node_"):
            raise exc  # unattributable: not a per-node store barrier
        i = int(owner.split("_", 1)[1])
        if i not in self.dead:
            self.crash_node(i)
        return i

    def _guarded(self, fn, *args) -> None:
        from ..resilience import InjectedCrash

        try:
            fn(*args)
        except InjectedCrash as e:
            self._on_injected_crash(e)

    def _persist_pools(self) -> None:
        """Durable-datadir cadence: each live node checkpoints its op pool
        once per slot (the ``persist.op_pool`` barrier; fork choice and the
        block/state batch persist inside the import path itself)."""
        from ..op_pool import persistence as pool_persist

        for i, node in enumerate(self.nodes):
            if i not in self.dead:
                # per-node guard: node i dying at its op-pool barrier must
                # not skip the checkpoint of the nodes after it
                self._guarded(
                    pool_persist.persist, node.chain.store, node.op_pool
                )

    def run_slot(self, slot: int) -> None:
        self.clock.set_slot(slot)
        self._guarded(self._propose, slot)
        self.settle()
        if self.sync_committee:
            self._sync_sign(slot)  # guards per node internally
            self.settle()
        self._attest(slot)  # guards per node internally
        self.settle()
        if self.slasher_enabled:
            epoch = slot // self.spec.preset.SLOTS_PER_EPOCH
            for i, node in enumerate(self.nodes):
                svc = getattr(node, "slasher_service", None)
                if i not in self.dead and svc is not None:
                    self._guarded(svc.tick, epoch)
        if self.datadir is not None:
            self._persist_pools()  # guards per node internally

    def run_until(self, last_slot: int, start: int = 1) -> None:
        for slot in range(start, last_slot + 1):
            self.run_slot(slot)

    # -- checks (simulator/src/checks.rs) ----------------------------------

    def head_slots(self) -> list[int]:
        return [n.chain.head.slot for n in self.nodes]

    def finalized_epochs(self) -> list[int]:
        return [
            int(n.chain.head.state.finalized_checkpoint.epoch)
            for n in self.nodes
        ]

    def heads_agree(self) -> bool:
        # crashed nodes are excluded: their head is frozen by definition
        roots = {
            n.chain.head.root
            for i, n in enumerate(self.nodes)
            if i not in self.dead
        }
        return len(roots) == 1


def _block_root_at(spec, state, slot: int) -> bytes:
    from ..state_transition import get_block_root_at_slot

    return get_block_root_at_slot(spec, state, slot)
