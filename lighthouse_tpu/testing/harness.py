"""State-transition harness: deterministic validators producing real blocks.

The state-level core of the reference's ``BeaconChainHarness``
(``/root/reference/beacon_node/beacon_chain/src/test_utils.rs:645``):
interop keypairs (``:367``), block production with valid proposer/randao
signatures, committee-complete attestation production, and slot advancement —
everything needed to drive ``per_block_processing`` end-to-end without a
network. The chain layer (stores, fork choice) wraps this later.
"""

from __future__ import annotations

import numpy as np

from ..ops.bls_oracle.fields import R as CURVE_ORDER
from ..types.containers import Checkpoint, for_preset
from ..types.helpers import compute_signing_root, get_domain
from ..types.spec import ChainSpec, fork_at_least
from ..ssz import uint64
from ..state_transition import (
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_current_epoch,
    per_block_processing,
    process_slots,
    BlockSignatureStrategy,
)
from ..state_transition.genesis import interop_genesis_state, interop_secret_keys
from ..state_transition.per_block import ConsensusContext


class StateHarness:
    def __init__(self, spec: ChainSpec, n_validators: int, genesis_time: int = 0):
        self.spec = spec
        self.ns = for_preset(spec.preset.name)
        self.sks = interop_secret_keys(n_validators)
        self.state = interop_genesis_state(spec, n_validators, genesis_time)
        # sign through the native C++ backend: the harness produces thousands
        # of signatures per multi-epoch test and the oracle takes ~1s each
        from ..native.build import NativeBls

        self._nb = NativeBls()
        # mock execution layer for merge-era forks (test_utils.rs:508-524)
        from ..execution_layer import MockExecutionLayer

        self.el = MockExecutionLayer()

    @staticmethod
    def head_root(state) -> bytes:
        """Canonical block root of the state's head: the latest block header
        with its zero state_root filled in (the pre-process_slot form)."""
        hdr = state.latest_block_header.copy()
        if bytes(hdr.state_root) == b"\x00" * 32:
            hdr.state_root = state.tree_root()
        return hdr.tree_root()

    # -- signing helpers ----------------------------------------------------------

    def _sign(self, sk_index: int, signing_root: bytes) -> bytes:
        return self._nb.sign(
            self.sks[sk_index].to_bytes(32, "big"), signing_root
        )

    def randao_reveal(self, state, proposer: int, epoch: int) -> bytes:
        domain = get_domain(self.spec, state, self.spec.DOMAIN_RANDAO, epoch=epoch)
        from ..types.containers import SigningData

        root = SigningData(
            object_root=uint64.hash_tree_root(epoch), domain=domain
        ).tree_root()
        return self._sign(proposer, root)

    # -- attestations -------------------------------------------------------------

    def attestations_for_slot(self, state, slot: int, head_root: bytes) -> list:
        """One fully-aggregated attestation per committee at ``slot``."""
        spec = self.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        target_root = (
            head_root
            if slot == spec.start_slot(epoch)
            else get_block_root_at_slot(spec, state, spec.start_slot(epoch))
        )
        atts = []
        domain = get_domain(spec, state, spec.DOMAIN_BEACON_ATTESTER, epoch=epoch)
        n_comm = get_committee_count_per_slot(spec, state, epoch)
        from ..types.containers import AttestationData

        electra = fork_at_least(
            spec.fork_name_at_epoch(epoch), "electra"
        )
        for index in range(n_comm):
            committee = get_beacon_committee(spec, state, slot, index)
            data = AttestationData(
                slot=slot,
                index=0 if electra else index,
                beacon_block_root=head_root,
                source=state.current_justified_checkpoint,
                target=Checkpoint(epoch=epoch, root=target_root),
            )
            root = compute_signing_root(data, domain)
            # aggregate of individual signatures == one signature by the
            # summed secret key (saves len(committee)-1 native signs)
            agg_sk = sum(self.sks[int(v)] for v in committee) % CURVE_ORDER
            sig = self._nb.sign(agg_sk.to_bytes(32, "big"), root)
            if electra:
                committee_bits = np.zeros(
                    spec.preset.MAX_COMMITTEES_PER_SLOT, dtype=bool
                )
                committee_bits[index] = True
                atts.append(
                    self.ns.AttestationElectra(
                        aggregation_bits=np.ones(committee.size, dtype=bool),
                        data=data,
                        signature=sig,
                        committee_bits=committee_bits,
                    )
                )
            else:
                atts.append(
                    self.ns.Attestation(
                        aggregation_bits=np.ones(committee.size, dtype=bool),
                        data=data,
                        signature=sig,
                    )
                )
        return atts

    def unaggregated_attestations_for_slot(
        self, state, slot: int, head_root: bytes
    ) -> list:
        """One single-bit attestation per committee member (the gossip-subnet
        shape that feeds batch_verify_unaggregated_attestations)."""
        spec = self.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        target_root = (
            head_root
            if slot == spec.start_slot(epoch)
            else get_block_root_at_slot(spec, state, spec.start_slot(epoch))
        )
        domain = get_domain(spec, state, spec.DOMAIN_BEACON_ATTESTER, epoch=epoch)
        from ..types.containers import AttestationData

        atts = []
        for index in range(get_committee_count_per_slot(spec, state, epoch)):
            committee = get_beacon_committee(spec, state, slot, index)
            data = AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=state.current_justified_checkpoint,
                target=Checkpoint(epoch=epoch, root=target_root),
            )
            root = compute_signing_root(data, domain)
            for pos, v in enumerate(committee):
                bits = np.zeros(committee.size, dtype=bool)
                bits[pos] = True
                atts.append(
                    self.ns.Attestation(
                        aggregation_bits=bits,
                        data=data,
                        signature=self._sign(int(v), root),
                    )
                )
        return atts

    def signed_aggregate_and_proofs(
        self, state, slot: int, head_root: bytes
    ) -> list:
        """One SignedAggregateAndProof per committee: the first committee
        member plays aggregator (selection-proof gossip checks are the
        scheduler's job; signatures here are real)."""
        spec = self.spec
        from ..types.containers import SigningData

        saps = []
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        dom_sel = get_domain(
            spec, state, spec.DOMAIN_SELECTION_PROOF, epoch=epoch
        )
        dom_ap = get_domain(
            spec, state, spec.DOMAIN_AGGREGATE_AND_PROOF, epoch=epoch
        )
        root_sel = SigningData(
            object_root=uint64.hash_tree_root(slot), domain=dom_sel
        ).tree_root()
        for index, att in enumerate(
            self.attestations_for_slot(state, slot, head_root)
        ):
            committee = get_beacon_committee(spec, state, slot, index)
            aggor = int(committee[0])
            agg = self.ns.AggregateAndProof(
                aggregator_index=aggor,
                aggregate=att,
                selection_proof=self._sign(aggor, root_sel),
            )
            sig = self._sign(aggor, compute_signing_root(agg, dom_ap))
            saps.append(
                self.ns.SignedAggregateAndProof(message=agg, signature=sig)
            )
        return saps

    # -- blocks -------------------------------------------------------------------

    def produce_block(self, slot: int, attestations=None):
        """Produce a signed block on top of the current state at ``slot``."""
        spec = self.spec
        state = self.state.copy()
        if state.slot < slot:
            process_slots(spec, state, slot)
        proposer = get_beacon_proposer_index(spec, state)
        epoch = get_current_epoch(spec, state)
        parent_root = state.latest_block_header.tree_root()

        fork = spec.fork_name_at_epoch(epoch)
        body_cls = self.ns.body_types[fork]
        block_cls = self.ns.block_types[fork]
        # fork boundary: drop attestations whose container shape predates the
        # body's list type (EIP-7549 changed the attestation wire format)
        att_elem = dict(body_cls.FIELDS)["attestations"].elem
        attestations = [
            a for a in (attestations or []) if isinstance(a, att_elem)
        ]
        body = body_cls(
            randao_reveal=self.randao_reveal(state, proposer, epoch),
            eth1_data=state.eth1_data,
            attestations=attestations,
        )
        if fork != "phase0":
            body.sync_aggregate = self._sync_aggregate(state, slot)
        if fork_at_least(fork, "bellatrix"):
            body.execution_payload = self._execution_payload(state, slot, fork)
        inner_cls = dict(block_cls.FIELDS)["message"]
        block = inner_cls(
            slot=slot,
            proposer_index=proposer,
            parent_root=parent_root,
            state_root=b"\x00" * 32,
            body=body,
        )
        # compute post-state root with signatures skipped
        trial = state.copy()
        signed_trial = block_cls(message=block, signature=b"\x00" * 96)
        per_block_processing(
            spec, trial, signed_trial,
            strategy=BlockSignatureStrategy.NO_VERIFICATION,
            verify_block_root=False,
        )
        block.state_root = trial.tree_root()
        # proposer signature
        domain = get_domain(spec, state, spec.DOMAIN_BEACON_PROPOSER, epoch=epoch)
        sig = self._sign(proposer, compute_signing_root(block, domain))
        return block_cls(message=block, signature=sig)

    def produce_block_with_blobs(self, slot: int, blobs: list, kzg):
        """Deneb: produce a signed block carrying blob commitments plus its
        gossip sidecars (the BlockContents production path)."""
        from ..beacon_chain.data_availability import make_blob_sidecars

        signed = self.produce_block(slot)
        block = signed.message
        commitments = [kzg.blob_to_kzg_commitment(b) for b in blobs]
        block.body.blob_kzg_commitments = commitments
        signed = self.resign_block(signed)
        proofs = [
            kzg.compute_blob_kzg_proof(b, c)
            for b, c in zip(blobs, commitments)
        ]
        sidecars = make_blob_sidecars(self.ns, signed, blobs, proofs)
        return signed, sidecars

    def resign_block(self, signed_block):
        """Recompute state_root + proposer signature after mutating a
        produced block's body (test-only convenience)."""
        block = signed_block.message
        spec = self.spec
        state = self.state.copy()
        if state.slot < block.slot:
            process_slots(spec, state, block.slot)
        trial = state.copy()
        block.state_root = b"\x00" * 32
        per_block_processing(
            spec, trial, type(signed_block)(message=block, signature=b"\x00" * 96),
            strategy=BlockSignatureStrategy.NO_VERIFICATION,
            verify_block_root=False,
        )
        block.state_root = trial.tree_root()
        epoch = get_current_epoch(spec, state)
        domain = get_domain(spec, state, spec.DOMAIN_BEACON_PROPOSER, epoch=epoch)
        sig = self._sign(
            int(block.proposer_index), compute_signing_root(block, domain)
        )
        return type(signed_block)(message=block, signature=sig)

    def _execution_payload(self, state, slot: int, fork: str):
        """Build the next mock execution payload on the state's payload head
        (MockExecutionLayer/ExecutionBlockGenerator parity)."""
        from ..state_transition import get_current_epoch, get_randao_mix
        from ..state_transition.per_block import (
            compute_timestamp_at_slot,
            _expected_withdrawals_list,
        )

        from ..execution_layer.mock import GENESIS_BLOCK_HASH
        from ..state_transition.per_block import is_merge_transition_complete

        payload_cls = self.ns.payload_types[fork]
        withdrawals = None
        if fork_at_least(fork, "capella"):
            withdrawals = _expected_withdrawals_list(self.spec, state)
        # pre-merge bellatrix state: this block IS the merge transition —
        # build the first payload on the mock EL's genesis block
        parent_hash = (
            bytes(state.latest_execution_payload_header.block_hash)
            if is_merge_transition_complete(state)
            else GENESIS_BLOCK_HASH
        )
        return self.el.generator.produce_payload(
            payload_cls,
            parent_hash=parent_hash,
            timestamp=compute_timestamp_at_slot(self.spec, state, slot),
            prev_randao=get_randao_mix(
                self.spec, state, get_current_epoch(self.spec, state)
            ),
            withdrawals=withdrawals,
        )

    def _sync_aggregate(self, state, slot: int):
        spec = self.spec
        prev_slot = max(slot, 1) - 1
        root = get_block_root_at_slot(spec, state, prev_slot)
        domain = get_domain(
            spec, state, spec.DOMAIN_SYNC_COMMITTEE,
            epoch=spec.compute_epoch_at_slot(prev_slot),
        )
        from ..types.containers import SigningData

        signing_root = SigningData(object_root=root, domain=domain).tree_root()
        pk_to_idx = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
        agg_sk = 0
        bits = []
        for pk in state.current_sync_committee.pubkeys:
            idx = pk_to_idx[bytes(pk)]
            bits.append(True)
            agg_sk = (agg_sk + self.sks[idx]) % CURVE_ORDER
        return self.ns.SyncAggregate(
            sync_committee_bits=np.array(bits, dtype=bool),
            sync_committee_signature=self._nb.sign(
                agg_sk.to_bytes(32, "big"), signing_root
            ),
        )

    def apply_block(self, signed_block, strategy=BlockSignatureStrategy.VERIFY_BULK):
        """Advance self.state through the block's slot and apply it."""
        spec = self.spec
        if self.state.slot < signed_block.message.slot:
            process_slots(spec, self.state, signed_block.message.slot)
        ctxt = per_block_processing(spec, self.state, signed_block, strategy=strategy)
        return ctxt

    def extend_chain(self, n_blocks: int, with_attestations: bool = True):
        """Produce + apply n blocks, attesting to each head (test_utils.rs
        extend_chain shape)."""
        for _ in range(n_blocks):
            slot = self.state.slot + 1
            atts = []
            if with_attestations and slot > 1:
                # attest to the previous slot's head from the pre-state; the
                # true block root needs the header's state_root filled in
                prev = self.state
                head_root = self.head_root(prev)
                att_slot = prev.slot
                if att_slot + self.spec.min_attestation_inclusion_delay <= slot:
                    atts = self.attestations_for_slot(prev, att_slot, head_root)
            block = self.produce_block(slot, attestations=atts)
            self.apply_block(block)
        return self.state
