"""Operator tooling: database manager, dev utilities, bulk validator manager.

Twin of the reference's L7 tool binaries:

  * ``database_manager`` (ref ``database_manager/``): inspect column sizes,
    report/force the schema version, prune payloads, compact.
  * ``lcli`` (ref ``lcli/``): skip-slots (state advance), transition-blocks
    (replay a block onto a pre-state), pretty-ssz (decode a container).
  * ``validator_manager`` (ref ``validator_manager/``): bulk create + import
    validators into a running VC through the keymanager API.

All reachable through ``python -m lighthouse_tpu <subcommand>``.
"""

from __future__ import annotations

import json
import os
import urllib.request

from .store.kv import DBColumn, LevelStore
from .types.containers import for_preset


# -- database manager --------------------------------------------------------


def db_inspect(datadir: str) -> dict:
    """Per-column key/byte counts for both stores (database_manager inspect)."""
    out = {}
    for name in ("chain.db", "freezer.db"):
        path = os.path.join(datadir, name)
        if not os.path.exists(path):
            continue
        store = LevelStore(path)
        cols = {}
        for col in DBColumn:
            n = size = 0
            for k, v in store.iter_column(col):
                n += 1
                size += len(v)
            if n:
                cols[col.name] = {"keys": n, "bytes": size}
        store.close()
        out[name] = cols
    return out


def _open_hot_cold(datadir: str):
    from .store.hot_cold import HotColdDB, StoreConfig

    return HotColdDB(
        hot=LevelStore(os.path.join(datadir, "chain.db")),
        cold=LevelStore(os.path.join(datadir, "freezer.db")),
        config=StoreConfig(),
    )


def _read_version(store) -> int:
    """Stamped version, or the version apply_schema_migrations would infer
    for an unstamped store (v1 when cold data exists — metadata.py:57-64)."""
    from .store.metadata import CURRENT_SCHEMA_VERSION

    raw = store.cold.get(DBColumn.Metadata, b"schema_version")
    if raw:
        return int.from_bytes(raw, "little")
    has_v1_data = any(True for _ in store.cold.iter_column(DBColumn.ColdState))
    return 1 if has_v1_data else CURRENT_SCHEMA_VERSION


def db_version(datadir: str) -> dict:
    """Schema version stamp (store/metadata.rs)."""
    from .store.metadata import CURRENT_SCHEMA_VERSION

    store = _open_hot_cold(datadir)
    try:
        return {
            "schema_version": _read_version(store),
            "current": CURRENT_SCHEMA_VERSION,
        }
    finally:
        store.hot.close()
        store.cold.close()


def db_migrate(datadir: str) -> dict:
    """Apply pending schema migrations in place (database_manager migrate)."""
    from .store.metadata import apply_schema_migrations

    store = _open_hot_cold(datadir)
    try:
        before = _read_version(store)
        apply_schema_migrations(store)
        return {"from": before, "to": _read_version(store)}
    finally:
        store.hot.close()
        store.cold.close()


def db_compact(datadir: str) -> dict:
    for name in ("chain.db", "freezer.db"):
        path = os.path.join(datadir, name)
        if os.path.exists(path):
            s = LevelStore(path)
            s.compact()
            s.close()
    return {"compacted": True}


# -- lcli utilities ----------------------------------------------------------


def skip_slots(spec, state_ssz: bytes, slots: int) -> bytes:
    """Advance a state ``slots`` empty slots (lcli skip-slots)."""
    from .state_transition import process_slots

    ns = for_preset(spec.preset.name)
    state, fork = _decode_state(spec, ns, state_ssz)
    process_slots(spec, state, int(state.slot) + slots)
    fork_out = spec.fork_name_at_slot(int(state.slot))
    return ns.state_types[fork_out].encode(state)


def transition_blocks(spec, state_ssz: bytes, blocks_ssz: list[bytes]) -> bytes:
    """Replay signed blocks onto a pre-state (lcli transition-blocks, via
    the BlockReplayer)."""
    from .state_transition.block_replayer import BlockReplayer

    ns = for_preset(spec.preset.name)
    state, _ = _decode_state(spec, ns, state_ssz)
    blocks = [_decode_block(spec, ns, b) for b in blocks_ssz]
    replayer = BlockReplayer(spec, state)
    replayer.apply_blocks(blocks)
    fork_out = spec.fork_name_at_slot(int(replayer.state.slot))
    return ns.state_types[fork_out].encode(replayer.state)


def pretty_ssz(spec, type_name: str, data: bytes) -> dict:
    """Decode an SSZ container to plain JSON-able python (lcli pretty-ssz)."""
    ns = for_preset(spec.preset.name)
    cls = getattr(ns, type_name, None)
    if cls is None:
        from .types import containers as _c

        cls = getattr(_c, type_name)
    obj = cls.decode(data)
    return _to_jsonable(obj)


def _decode_state(spec, ns, raw: bytes):
    # fork variants have different SSZ layouts: newest-first trial decode
    last_err = None
    for fork in reversed(list(ns.state_types)):
        try:
            return ns.state_types[fork].decode(raw), fork
        except Exception as e:  # noqa: BLE001 — try the next fork
            last_err = e
    raise ValueError(f"undecodable state: {last_err}")


def _decode_block(spec, ns, raw: bytes):
    last_err = None
    for fork in reversed(list(ns.block_types)):
        try:
            return ns.block_types[fork].decode(raw)
        except Exception as e:  # noqa: BLE001 — try the next fork
            last_err = e
    raise ValueError(f"undecodable block: {last_err}")


def _to_jsonable(obj):
    import numpy as np

    if isinstance(obj, (bytes, bytearray)):
        return "0x" + bytes(obj).hex()
    if isinstance(obj, (bool, int, str)) or obj is None:
        return obj
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.ndarray):
        return [_to_jsonable(x) for x in obj.tolist()]
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    fields = getattr(type(obj), "FIELDS", None)
    if fields is not None:
        return {name: _to_jsonable(getattr(obj, name)) for name, _ in fields}
    return str(obj)


# -- validator manager -------------------------------------------------------


def vm_create(output_dir: str, count: int, password: str, seed_hex: str | None,
              first_index: int = 0) -> list[str]:
    """Bulk-create EIP-2335 keystores (validator_manager create)."""
    from .keys.derivation import derive_sk_from_path
    from .keys.keystore import Keystore

    os.makedirs(output_dir, exist_ok=True)
    seed = bytes.fromhex(seed_hex) if seed_hex else os.urandom(32)
    written = []
    for i in range(first_index, first_index + count):
        path = f"m/12381/3600/{i}/0/0"
        sk = derive_sk_from_path(seed, path)
        ks = Keystore.encrypt(sk.to_bytes(32, "big"), password, path=path)
        name = f"keystore-{i}.json"
        with open(os.path.join(output_dir, name), "w") as fh:
            fh.write(ks.to_json())
        written.append(name)
    return written


def vm_import(keystores_dir: str, password: str, vc_url: str) -> list[dict]:
    """Import a keystore directory into a running VC through the keymanager
    API (validator_manager import)."""
    keystores, passwords = [], []
    for name in sorted(os.listdir(keystores_dir)):
        if not (name.startswith("keystore") and name.endswith(".json")):
            continue
        with open(os.path.join(keystores_dir, name)) as fh:
            keystores.append(fh.read())
        passwords.append(password)
    body = json.dumps(
        {"keystores": keystores, "passwords": passwords}
    ).encode()
    req = urllib.request.Request(
        vc_url.rstrip("/") + "/eth/v1/keystores", data=body, method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode())["data"]


def vm_list(vc_url: str) -> list[dict]:
    with urllib.request.urlopen(
        vc_url.rstrip("/") + "/eth/v1/keystores", timeout=30
    ) as resp:
        return json.loads(resp.read().decode())["data"]
