"""Generate the golden conformance vectors under tests/vectors/.

Run once (``python -m lighthouse_tpu.conformance.generate``) and commit the
output. Vectors are produced from the trusted oracle ciphersuite and the
state harness — the runner (handler.py) then exercises the real verification
and state-transition paths against them, per backend. The reference's
equivalent inputs are the official consensus-spec-tests; here they are
self-generated because the environment has no network (SURVEY §4 tier 1).
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np


def _w(path: str, name: str, data) -> None:
    os.makedirs(path, exist_ok=True)
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(os.path.join(path, name), mode) as f:
        if isinstance(data, bytes):
            f.write(data)
        else:
            json.dump(data, f, indent=1)


def _case_dir(root, config, fork, runner, handler, idx):
    return os.path.join(root, config, fork, runner, handler, f"case_{idx}")


def gen_bls(root: str, config: str = "general") -> None:
    from ..ops.bls_oracle import ciphersuite as cs
    from ..ops.bls_oracle import curves as oc

    fork = "phase0"

    def hx(b: bytes) -> str:
        return b.hex()

    sks = [cs.keygen_from_ikm(bytes([i]) * 32) for i in range(1, 5)]
    pks = [oc.g1_compress(cs.sk_to_pk(sk)).hex() for sk in sks]
    msg = b"\x11" * 32
    sigs = [oc.g2_compress(cs.sign(sk, msg)).hex() for sk in sks]

    # sign
    for i, sk in enumerate(sks[:2]):
        _w(
            _case_dir(root, config, fork, "bls", "sign", i),
            "data.json",
            {
                "input": {"privkey": sk.to_bytes(32, "big").hex(), "message": hx(msg)},
                "output": sigs[i],
            },
        )
    # verify: valid, wrong message, wrong key, infinity sig
    cases = [
        ({"pubkey": pks[0], "message": hx(msg), "signature": sigs[0]}, True),
        ({"pubkey": pks[0], "message": hx(b"\x22" * 32), "signature": sigs[0]}, False),
        ({"pubkey": pks[1], "message": hx(msg), "signature": sigs[0]}, False),
        (
            {
                "pubkey": pks[0],
                "message": hx(msg),
                "signature": (b"\xc0" + b"\x00" * 95).hex(),
            },
            False,
        ),
    ]
    for i, (inp, out) in enumerate(cases):
        _w(
            _case_dir(root, config, fork, "bls", "verify", i),
            "data.json",
            {"input": inp, "output": out},
        )
    # aggregate
    agg = None
    for sk in sks:
        agg = oc.g2_add(agg, cs.sign(sk, msg))
    _w(
        _case_dir(root, config, fork, "bls", "aggregate", 0),
        "data.json",
        {"input": sigs, "output": oc.g2_compress(agg).hex()},
    )
    # fast_aggregate_verify: valid + one wrong-key
    _w(
        _case_dir(root, config, fork, "bls", "fast_aggregate_verify", 0),
        "data.json",
        {
            "input": {
                "pubkeys": pks,
                "message": hx(msg),
                "signature": oc.g2_compress(agg).hex(),
            },
            "output": True,
        },
    )
    _w(
        _case_dir(root, config, fork, "bls", "fast_aggregate_verify", 1),
        "data.json",
        {
            "input": {
                "pubkeys": pks[:3],
                "message": hx(msg),
                "signature": oc.g2_compress(agg).hex(),
            },
            "output": False,
        },
    )
    # batch_verify: all valid; one poisoned
    msgs = [bytes([i]) * 32 for i in range(3)]
    sets = []
    for i, m in enumerate(msgs):
        a = None
        for sk in sks[: i + 2]:
            a = oc.g2_add(a, cs.sign(sk, m))
        sets.append(
            {
                "pubkeys": pks[: i + 2],
                "message": m.hex(),
                "signature": oc.g2_compress(a).hex(),
            }
        )
    _w(
        _case_dir(root, config, fork, "bls", "batch_verify", 0),
        "data.json",
        {"input": {"sets": sets}, "output": True},
    )
    poisoned = [dict(s) for s in sets]
    poisoned[1]["signature"] = poisoned[0]["signature"]
    _w(
        _case_dir(root, config, fork, "bls", "batch_verify", 1),
        "data.json",
        {"input": {"sets": poisoned}, "output": False},
    )


def gen_shuffling(root: str, config: str = "minimal") -> None:
    from ..ops.shuffle import shuffle_list
    from ..types.spec import mainnet_spec, minimal_spec

    spec = minimal_spec() if config == "minimal" else mainnet_spec()
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    for i, (seed_byte, count) in enumerate([(0x42, 8), (0x07, 33), (0xA5, 100)]):
        seed = bytes([seed_byte]) * 32
        mapping = np.asarray(
            shuffle_list(np.arange(count, dtype=np.uint64), seed, rounds)
        ).tolist()
        _w(
            _case_dir(root, config, "phase0", "shuffling", "core", i),
            "mapping.json",
            {"seed": seed.hex(), "count": count, "mapping": mapping},
        )


FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


def fork_overrides(fork: str, at_epoch: int = 0) -> dict:
    """Spec overrides activating every fork up to ``fork`` at ``at_epoch``
    (0 = genesis-active, the per-fork vector convention)."""
    return {f"{f}_fork_epoch": at_epoch for f in FORKS[1 : FORKS.index(fork) + 1]}


def _harness(fork: str, n=32):
    from ..testing.harness import StateHarness
    from ..types.spec import minimal_spec

    return StateHarness(minimal_spec(**fork_overrides(fork)), n)


def gen_ssz_static(root: str, config: str = "minimal") -> None:
    for fork in FORKS:
        h = _harness(fork)
        h.extend_chain(3)
        state = h.state
        block = h.produce_block(state.slot + 1)
        objs = {
            "BeaconState": (type(state), state),
            "SignedBeaconBlock": (type(block), block),
        }
        atts = h.attestations_for_slot(
            state, state.slot, state.latest_block_header.tree_root()
        )
        if atts:
            objs["Attestation"] = (type(atts[0]), atts[0])
        for name, (cls, value) in objs.items():
            d = _case_dir(root, config, fork, "ssz_static", name, 0)
            _w(d, "serialized.ssz", cls.encode(value))
            _w(d, "root.json", {"root": value.tree_root().hex()})


def gen_operations(root: str, config: str = "minimal") -> None:
    from ..state_transition import process_slots
    from ..types.helpers import compute_signing_root, get_domain

    fork = "phase0"
    h = _harness(fork)
    h.extend_chain(2)
    spec = h.spec
    state_cls = type(h.state)

    # --- attestation: valid + bad-target error case
    prev = h.state
    att = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))[0]
    pre = prev.copy()
    process_slots(spec, pre, prev.slot + spec.min_attestation_inclusion_delay)
    d = _case_dir(root, config, fork, "operations", "attestation", 0)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(att).encode(att))
    post = pre.copy()
    from .handler import _op_attestation

    _op_attestation(spec, post, att)
    _w(d, "post.ssz", state_cls.encode(post))

    bad = type(att).decode(type(att).encode(att))
    bad.data.target.root = b"\xde" * 32
    d = _case_dir(root, config, fork, "operations", "attestation", 1)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(bad).encode(bad))
    _w(d, "meta.json", {"error": True})

    # --- voluntary exit: advance past shard_committee_period
    from ..types.containers import SignedVoluntaryExit, VoluntaryExit

    exit_state = h.state.copy()
    target_epoch = spec.shard_committee_period + 1
    process_slots(spec, exit_state, target_epoch * spec.preset.SLOTS_PER_EPOCH)
    exit_msg = VoluntaryExit(epoch=target_epoch, validator_index=3)
    domain = get_domain(
        spec, exit_state, spec.DOMAIN_VOLUNTARY_EXIT, epoch=target_epoch
    )
    sig = h._sign(3, compute_signing_root(exit_msg, domain))
    sve = SignedVoluntaryExit(message=exit_msg, signature=sig)
    d = _case_dir(root, config, fork, "operations", "voluntary_exit", 0)
    _w(d, "pre.ssz", state_cls.encode(exit_state))
    _w(d, "voluntary_exit.ssz", SignedVoluntaryExit.encode(sve))
    post = exit_state.copy()
    from .handler import _op_exit

    _op_exit(spec, post, sve)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: wrong signature
    bad = SignedVoluntaryExit(message=exit_msg, signature=h._sign(4, b"\x00" * 32))
    d = _case_dir(root, config, fork, "operations", "voluntary_exit", 1)
    _w(d, "pre.ssz", state_cls.encode(exit_state))
    _w(d, "voluntary_exit.ssz", SignedVoluntaryExit.encode(bad))
    _w(d, "meta.json", {"error": True})

    # --- proposer slashing: two conflicting headers by validator 0
    from ..types.containers import BeaconBlockHeader, SignedBeaconBlockHeader
    from ..types.containers import ProposerSlashing

    st = h.state
    slot = st.slot
    proposer = 0
    hdrs = []
    for i, body_root in enumerate((b"\x01" * 32, b"\x02" * 32)):
        header = BeaconBlockHeader(
            slot=slot,
            proposer_index=proposer,
            parent_root=b"\x03" * 32,
            state_root=b"\x04" * 32,
            body_root=body_root,
        )
        dom = get_domain(
            spec, st, spec.DOMAIN_BEACON_PROPOSER,
            epoch=spec.compute_epoch_at_slot(slot),
        )
        hdrs.append(
            SignedBeaconBlockHeader(
                message=header,
                signature=h._sign(proposer, compute_signing_root(header, dom)),
            )
        )
    ps = ProposerSlashing(signed_header_1=hdrs[0], signed_header_2=hdrs[1])
    d = _case_dir(root, config, fork, "operations", "proposer_slashing", 0)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "proposer_slashing.ssz", ProposerSlashing.encode(ps))
    post = st.copy()
    from .handler import _op_proposer_slashing

    _op_proposer_slashing(spec, post, ps)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: identical headers (not slashable)
    same = ProposerSlashing(signed_header_1=hdrs[0], signed_header_2=hdrs[0])
    d = _case_dir(root, config, fork, "operations", "proposer_slashing", 1)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "proposer_slashing.ssz", ProposerSlashing.encode(same))
    _w(d, "meta.json", {"error": True})

    # --- attester slashing: double vote by one committee
    from ..state_transition import get_beacon_committee
    from ..types.containers import AttestationData, Checkpoint

    st2 = h.state
    committee = get_beacon_committee(spec, st2, st2.slot, 0)
    epoch = spec.compute_epoch_at_slot(st2.slot)
    datas = []
    for root_byte in (0x0A, 0x0B):
        datas.append(
            AttestationData(
                slot=st2.slot,
                index=0,
                beacon_block_root=bytes([root_byte]) * 32,
                source=st2.current_justified_checkpoint,
                target=Checkpoint(epoch=epoch, root=bytes([root_byte]) * 32),
            )
        )
    dom = get_domain(spec, st2, spec.DOMAIN_BEACON_ATTESTER, epoch=epoch)
    ns = h.ns
    from ..ops.bls_oracle.fields import R as CURVE_ORDER

    indexed = []
    for data in datas:
        agg_sk = sum(h.sks[int(v)] for v in committee) % CURVE_ORDER
        indexed.append(
            ns.IndexedAttestation(
                attesting_indices=sorted(int(v) for v in committee),
                data=data,
                signature=h._nb.sign(
                    agg_sk.to_bytes(32, "big"), compute_signing_root(data, dom)
                ),
            )
        )
    aslash = ns.AttesterSlashing(attestation_1=indexed[0], attestation_2=indexed[1])
    d = _case_dir(root, config, fork, "operations", "attester_slashing", 0)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "attester_slashing.ssz", ns.AttesterSlashing.encode(aslash))
    post = st2.copy()
    from .handler import _op_attester_slashing

    _op_attester_slashing(spec, post, aslash)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: same attestation twice
    same = ns.AttesterSlashing(attestation_1=indexed[0], attestation_2=indexed[0])
    d = _case_dir(root, config, fork, "operations", "attester_slashing", 1)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "attester_slashing.ssz", ns.AttesterSlashing.encode(same))
    _w(d, "meta.json", {"error": True})


def gen_epoch_processing(root: str, config: str = "minimal") -> None:
    from ..state_transition import process_epoch, process_slots

    for fork in FORKS:
        h = _harness(fork)
        h.extend_chain(h.spec.preset.SLOTS_PER_EPOCH + 2)
        state = h.state.copy()
        # advance to the last slot of the epoch; pre = state ready for epoch proc
        spe = h.spec.preset.SLOTS_PER_EPOCH
        target = (state.slot // spe + 1) * spe - 1
        process_slots(h.spec, state, target)
        state_cls = type(state)
        d = _case_dir(root, config, fork, "epoch_processing", "full", 0)
        _w(d, "pre.ssz", state_cls.encode(state))
        post = state.copy()
        process_epoch(h.spec, post)
        _w(d, "post.ssz", state_cls.encode(post))


def gen_rewards(root: str, config: str = "minimal") -> None:
    """Per-fork rewards vectors: pre.ssz is a state on the last slot of its
    epoch, deltas.json the per-validator balance deltas the rewards stages
    must produce. Generator and runner share ``handler._apply_rewards``, so
    the vectors freeze today's columnar-numpy truth — the exact outputs the
    device epoch kernels (including electra) are parity-tested against.
    Case 1 is a leak twin: finality rolled back far past
    MIN_EPOCHS_TO_INACTIVITY_PENALTY with participation gutted, so the
    inactivity-leak branch pays real penalties."""
    from ..state_transition import process_slots
    from ..types.containers import Checkpoint

    from .handler import _apply_rewards

    for fork in FORKS:
        h = _harness(fork)
        h.extend_chain(h.spec.preset.SLOTS_PER_EPOCH + 2)
        spe = h.spec.preset.SLOTS_PER_EPOCH
        state = h.state.copy()
        target = (state.slot // spe + 1) * spe - 1
        process_slots(h.spec, state, target)
        state_cls = type(state)

        def emit(idx, st):
            d = _case_dir(root, config, fork, "rewards", "core", idx)
            _w(d, "pre.ssz", state_cls.encode(st))
            post = st.copy()
            _apply_rewards(h.spec, post)
            _w(
                d,
                "deltas.json",
                {
                    "deltas": [
                        int(a) - int(b)
                        for a, b in zip(post.balances, st.balances)
                    ]
                },
            )

        emit(0, state)

        # leak twin: park the state deep in an unfinalized stretch. The
        # slot jump skips the block-roots history on purpose — target/head
        # lookups then miss, which IS the leak's non-participation.
        leak = state.copy()
        leak.slot = 8 * spe - 1
        leak.finalized_checkpoint = Checkpoint(epoch=0, root=b"\x00" * 32)
        leak.justification_bits = np.zeros(4, dtype=bool)
        if fork == "phase0":
            leak.previous_epoch_attestations = list(
                leak.previous_epoch_attestations
            )[:1]
        else:
            for field in (
                "previous_epoch_participation",
                "current_epoch_participation",
            ):
                part = np.asarray(getattr(leak, field), dtype=np.uint8)
                part[::2] = 0  # half the set stops attesting: no 2/3 quorum
                setattr(leak, field, part)
            scores = np.asarray(leak.inactivity_scores, dtype=np.uint64)
            scores[:] = 50  # a standing score makes the penalty term bite
            leak.inactivity_scores = scores
        emit(1, leak)


def gen_finality(root: str, config: str = "minimal") -> None:
    """Finality vectors (cases/finality.rs shape): pre.ssz + a multi-epoch
    block chain -> post.ssz, meta.json pinning the justified/finalized
    checkpoints the full transition must reach. One fork per epoch-kernel
    family (phase0 / altair / electra) — bellatrix, capella and deneb share
    the altair family's epoch stage sequence bit-for-bit, so their four-epoch
    signed-block chains would re-verify ~100 block signatures each for zero
    added epoch coverage (their block-level differences are pinned by the
    operations and epoch_processing families); tier-1 wall clock matters
    (ISSUE 19: keep added tier-1 tests lean)."""
    for fork in ("phase0", "altair", "electra"):
        h = _harness(fork)
        h.extend_chain(2)
        pre = h.state.copy()
        state_cls = type(pre)
        spe = h.spec.preset.SLOTS_PER_EPOCH
        blocks = []
        while h.state.slot < 4 * spe + 1:
            slot = h.state.slot + 1
            prev = h.state
            atts = []
            if prev.slot + h.spec.min_attestation_inclusion_delay <= slot:
                atts = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))
            block = h.produce_block(slot, attestations=atts)
            h.apply_block(block)
            blocks.append(block)
        post = h.state
        assert int(post.finalized_checkpoint.epoch) >= 2, (
            f"{fork}: finality never advanced"
        )
        d = _case_dir(root, config, fork, "finality", "core", 0)
        _w(
            d,
            "meta.json",
            {
                "finalized_epoch": int(post.finalized_checkpoint.epoch),
                "justified_epoch": int(
                    post.current_justified_checkpoint.epoch
                ),
            },
        )
        _w(d, "pre.ssz", state_cls.encode(pre))
        for i, b in enumerate(blocks):
            _w(d, f"blocks_{i}.ssz", type(b).encode(b))
        _w(d, "post.ssz", state_cls.encode(post))


def gen_sanity_blocks(root: str, config: str = "minimal") -> None:
    for fork in FORKS:
        h = _harness(fork)
        h.extend_chain(2)
        pre = h.state.copy()
        state_cls = type(pre)
        blocks = []
        for _ in range(3):
            slot = h.state.slot + 1
            atts = []
            prev = h.state
            if prev.slot + h.spec.min_attestation_inclusion_delay <= slot:
                atts = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))
            block = h.produce_block(slot, attestations=atts)
            h.apply_block(block)
            blocks.append(block)
        d = _case_dir(root, config, fork, "sanity_blocks", "chain", 0)
        _w(d, "pre.ssz", state_cls.encode(pre))
        for i, b in enumerate(blocks):
            _w(d, f"blocks_{i}.ssz", type(b).encode(b))
        _w(d, "post.ssz", state_cls.encode(h.state))


def gen_operations_merge(root: str, config: str = "minimal") -> None:
    """Fork-specific operation vectors: execution payloads (bellatrix),
    withdrawals + credential rotation (capella), EL-triggered requests and
    committee-bits attestations (electra). Mirrors the per-fork handler dirs
    of testing/ef_tests/src/cases/operations.rs."""
    from ..state_transition import process_slots
    from ..types.helpers import compute_domain, compute_signing_root

    # --- bellatrix: execution_payload valid + wrong-parent error twin
    h = _harness("bellatrix")
    h.extend_chain(3)
    st = h.state.copy()
    process_slots(h.spec, st, st.slot + 1)
    state_cls = type(st)
    payload = h._execution_payload(st, st.slot, "bellatrix")
    payload_cls = type(payload)
    d = _case_dir(root, config, "bellatrix", "operations", "execution_payload", 0)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "execution_payload.ssz", payload_cls.encode(payload))
    post = st.copy()
    from ..state_transition.per_block import process_execution_payload

    process_execution_payload(h.spec, post, payload)
    _w(d, "post.ssz", state_cls.encode(post))
    bad = payload_cls.decode(payload_cls.encode(payload))
    bad.parent_hash = b"\xbe" * 32
    d = _case_dir(root, config, "bellatrix", "operations", "execution_payload", 1)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "execution_payload.ssz", payload_cls.encode(bad))
    _w(d, "meta.json", {"error": True})

    # --- capella: withdrawals sweep + bls_to_execution_change
    h = _harness("capella")
    h.extend_chain(3)
    st = h.state.copy()
    # give a validator inside the sweep window (the cursor advances
    # MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP per block) an eth1 credential +
    # excess balance -> partial withdrawal
    wv = (int(st.next_withdrawal_validator_index) + 1) % len(st.validators)
    st.validators[wv].withdrawal_credentials = (
        b"\x01" + b"\x00" * 11 + b"\x11" * 20
    )
    st.balances[wv] = int(st.balances[wv]) + 5_000_000_000
    state_cls = type(st)
    from ..state_transition.per_block import (
        _expected_withdrawals_list,
        process_withdrawals,
    )

    ns = h.ns
    wlist = _expected_withdrawals_list(h.spec, st)
    assert wlist, "capella withdrawals vector needs a non-empty sweep"
    payload = h._execution_payload(st, st.slot, "capella")
    payload.withdrawals = wlist
    payload_cls = type(payload)
    d = _case_dir(root, config, "capella", "operations", "withdrawals", 0)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "execution_payload.ssz", payload_cls.encode(payload))
    post = st.copy()
    process_withdrawals(h.spec, post, payload)
    _w(d, "post.ssz", state_cls.encode(post))
    bad = payload_cls.decode(payload_cls.encode(payload))
    bad.withdrawals = []
    d = _case_dir(root, config, "capella", "operations", "withdrawals", 1)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "execution_payload.ssz", payload_cls.encode(bad))
    _w(d, "meta.json", {"error": True})

    # bls_to_execution_change: interop credentials are 0x00||sha256(pk)[1:]
    from ..types.containers import BLSToExecutionChange, SignedBLSToExecutionChange

    st2 = h.state.copy()
    change = BLSToExecutionChange(
        validator_index=2,
        from_bls_pubkey=bytes(st2.validators[2].pubkey),
        to_execution_address=b"\x22" * 20,
    )
    domain = compute_domain(
        h.spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        h.spec.genesis_fork_version,
        bytes(st2.genesis_validators_root),
    )
    signed = SignedBLSToExecutionChange(
        message=change,
        signature=h._sign(2, compute_signing_root(change, domain)),
    )
    d = _case_dir(root, config, "capella", "operations", "bls_to_execution_change", 0)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "address_change.ssz", SignedBLSToExecutionChange.encode(signed))
    post = st2.copy()
    from ..state_transition.per_block import process_bls_to_execution_change

    process_bls_to_execution_change(h.spec, post, signed, verify=True)
    _w(d, "post.ssz", state_cls.encode(post))
    badsig = SignedBLSToExecutionChange(
        message=change, signature=h._sign(3, b"\x00" * 32)
    )
    d = _case_dir(root, config, "capella", "operations", "bls_to_execution_change", 1)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "address_change.ssz", SignedBLSToExecutionChange.encode(badsig))
    _w(d, "meta.json", {"error": True})

    # --- electra: EL-triggered requests + committee-bits attestation
    h = _harness("electra")
    h.extend_chain(3)
    spec = h.spec
    ns = h.ns
    st = h.state.copy()
    state_cls = type(st)
    from ..state_transition.electra import (
        process_consolidation_request,
        process_deposit_request,
        process_withdrawal_request,
    )

    # deposit_request: appends to pending_deposits (EIP-6110; no failure path)
    dreq = ns.DepositRequest(
        pubkey=bytes(st.validators[0].pubkey),
        withdrawal_credentials=b"\x01" + b"\x00" * 11 + b"\x33" * 20,
        amount=32_000_000_000,
        signature=b"\x0a" * 96,
        index=7,
    )
    d = _case_dir(root, config, "electra", "operations", "deposit_request", 0)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "deposit_request.ssz", ns.DepositRequest.encode(dreq))
    post = st.copy()
    process_deposit_request(spec, post, dreq)
    _w(d, "post.ssz", state_cls.encode(post))

    # withdrawal_request full-exit: validator 4 owns an execution credential.
    # Invalid requests are spec'd as NO-OPS (post == pre), not errors. Exit
    # requests require shard_committee_period epochs of activity first.
    addr = b"\x44" * 20
    st_w = st.copy()
    process_slots(
        spec,
        st_w,
        (spec.shard_committee_period + 1) * spec.preset.SLOTS_PER_EPOCH,
    )
    st_w.validators[4].withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    wreq = ns.WithdrawalRequest(
        source_address=addr,
        validator_pubkey=bytes(st_w.validators[4].pubkey),
        amount=0,  # FULL_EXIT_REQUEST_AMOUNT
    )
    d = _case_dir(root, config, "electra", "operations", "withdrawal_request", 0)
    _w(d, "pre.ssz", state_cls.encode(st_w))
    _w(d, "withdrawal_request.ssz", ns.WithdrawalRequest.encode(wreq))
    post = st_w.copy()
    process_withdrawal_request(spec, post, wreq)
    assert post.tree_root() != st_w.tree_root(), "exit request must take effect"
    _w(d, "post.ssz", state_cls.encode(post))
    wrong = ns.WithdrawalRequest(
        source_address=b"\x55" * 20,
        validator_pubkey=bytes(st_w.validators[4].pubkey),
        amount=0,
    )
    d = _case_dir(root, config, "electra", "operations", "withdrawal_request", 1)
    _w(d, "pre.ssz", state_cls.encode(st_w))
    _w(d, "withdrawal_request.ssz", ns.WithdrawalRequest.encode(wrong))
    _w(d, "post.ssz", state_cls.encode(st_w))  # no-op: post == pre

    # consolidation_request self-switch to compounding (0x01 -> 0x02)
    st_c = st.copy()
    st_c.validators[5].withdrawal_credentials = b"\x01" + b"\x00" * 11 + addr
    creq = ns.ConsolidationRequest(
        source_address=addr,
        source_pubkey=bytes(st_c.validators[5].pubkey),
        target_pubkey=bytes(st_c.validators[5].pubkey),
    )
    d = _case_dir(root, config, "electra", "operations", "consolidation_request", 0)
    _w(d, "pre.ssz", state_cls.encode(st_c))
    _w(d, "consolidation_request.ssz", ns.ConsolidationRequest.encode(creq))
    post = st_c.copy()
    process_consolidation_request(spec, post, creq)
    assert post.tree_root() != st_c.tree_root(), "switch must take effect"
    _w(d, "post.ssz", state_cls.encode(post))

    # electra attestation (committee_bits + index=0 wire shape, EIP-7549)
    prev = h.state
    att = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))[0]
    pre = prev.copy()
    process_slots(spec, pre, prev.slot + spec.min_attestation_inclusion_delay)
    d = _case_dir(root, config, "electra", "operations", "attestation", 0)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(att).encode(att))
    post = pre.copy()
    from .handler import _op_attestation

    _op_attestation(spec, post, att)
    _w(d, "post.ssz", state_cls.encode(post))
    badatt = type(att).decode(type(att).encode(att))
    badatt.data.index = 3  # electra: non-zero data.index is invalid
    d = _case_dir(root, config, "electra", "operations", "attestation", 1)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(att).encode(badatt))
    _w(d, "meta.json", {"error": True})


def gen_transition(root: str, config: str = "minimal") -> None:
    """Fork-boundary vectors: start one epoch before the fork, run blocks
    across it (cases/transition.rs). pre decodes as the old fork's state,
    post as the new fork's; blocks switch class at the boundary slot."""
    from ..testing.harness import StateHarness
    from ..types.spec import minimal_spec

    for i in range(1, len(FORKS)):
        pre_fork, post_fork = FORKS[i - 1], FORKS[i]
        overrides = fork_overrides(pre_fork)
        overrides[f"{post_fork}_fork_epoch"] = 1
        spec = minimal_spec(**overrides)
        h = StateHarness(spec, 32)
        spe = spec.preset.SLOTS_PER_EPOCH
        h.extend_chain(2)
        pre = h.state.copy()
        blocks = []
        # cross the boundary: blocks up to one slot past the fork epoch start
        while h.state.slot < spe + 1:
            slot = h.state.slot + 1
            prev = h.state
            atts = []
            if prev.slot + spec.min_attestation_inclusion_delay <= slot:
                atts = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))
            block = h.produce_block(slot, attestations=atts)
            h.apply_block(block)
            blocks.append(block)
        d = _case_dir(root, config, post_fork, "transition", "core", 0)
        _w(
            d,
            "meta.json",
            {"pre_fork": pre_fork, "post_fork": post_fork, "fork_epoch": 1},
        )
        _w(d, "pre.ssz", type(pre).encode(pre))
        for j, b in enumerate(blocks):
            _w(d, f"blocks_{j}.ssz", type(b).encode(b))
        _w(d, "post.ssz", type(h.state).encode(h.state))


# deterministic insecure trusted-setup geometry shared with the handler
KZG_SETUP_N = 8
KZG_SETUP_G2 = 4
KZG_CELLS = 8


def _kzg_pair():
    from ..kzg import Kzg
    from ..kzg.setup import insecure_setup

    kzg = Kzg(insecure_setup(KZG_SETUP_N, n_g2=KZG_SETUP_G2))
    return kzg


def _blob(kzg, seed: int) -> bytes:
    from ..ops.bls_oracle.fields import R

    rng = np.random.default_rng(seed)
    out = b""
    for _ in range(kzg.n):
        out += (int.from_bytes(rng.bytes(31), "big") % R).to_bytes(32, "big")
    return out


def gen_kzg(root: str, config: str = "general") -> None:
    """KZG vectors (cases/kzg_*.rs families), deneb blob families + fulu cell
    families, generated from the host path and checked per backend by the
    handler. Geometry rides an insecure deterministic setup (meta.json)."""
    kzg = _kzg_pair()
    meta = {"setup_n": KZG_SETUP_N, "setup_n_g2": KZG_SETUP_G2}
    blobs = [_blob(kzg, s) for s in (1, 2, 3)]
    comms = [kzg.blob_to_kzg_commitment(b) for b in blobs]

    for i, (b, c) in enumerate(zip(blobs, comms)):
        d = _case_dir(root, config, "deneb", "kzg", "blob_to_kzg_commitment", i)
        _w(d, "data.json", {"input": {"blob": b.hex()}, "output": c.hex(), **meta})

    z = (7).to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blobs[0], z)
    d = _case_dir(root, config, "deneb", "kzg", "compute_kzg_proof", 0)
    _w(
        d,
        "data.json",
        {
            "input": {"blob": blobs[0].hex(), "z": z.hex()},
            "output": [proof.hex(), y.hex()],
            **meta,
        },
    )
    for i, (zv, yv, pv, ok) in enumerate(
        [
            (z, y, proof, True),
            (z, (int.from_bytes(y, "big") ^ 1).to_bytes(32, "big"), proof, False),
        ]
    ):
        d = _case_dir(root, config, "deneb", "kzg", "verify_kzg_proof", i)
        _w(
            d,
            "data.json",
            {
                "input": {
                    "commitment": comms[0].hex(),
                    "z": zv.hex(),
                    "y": yv.hex(),
                    "proof": pv.hex(),
                },
                "output": ok,
                **meta,
            },
        )

    bproofs = [
        kzg.compute_blob_kzg_proof(b, c) for b, c in zip(blobs, comms)
    ]
    d = _case_dir(root, config, "deneb", "kzg", "compute_blob_kzg_proof", 0)
    _w(
        d,
        "data.json",
        {
            "input": {"blob": blobs[0].hex(), "commitment": comms[0].hex()},
            "output": bproofs[0].hex(),
            **meta,
        },
    )
    for i, (b, c, p, ok) in enumerate(
        [
            (blobs[0], comms[0], bproofs[0], True),
            (blobs[0], comms[1], bproofs[0], False),
        ]
    ):
        d = _case_dir(root, config, "deneb", "kzg", "verify_blob_kzg_proof", i)
        _w(
            d,
            "data.json",
            {
                "input": {
                    "blob": b.hex(),
                    "commitment": c.hex(),
                    "proof": p.hex(),
                },
                "output": ok,
                **meta,
            },
        )
    for i, (bs, cs, ps, ok) in enumerate(
        [
            (blobs, comms, bproofs, True),
            (blobs, comms, [bproofs[1], bproofs[0], bproofs[2]], False),
        ]
    ):
        d = _case_dir(
            root, config, "deneb", "kzg", "verify_blob_kzg_proof_batch", i
        )
        _w(
            d,
            "data.json",
            {
                "input": {
                    "blobs": [b.hex() for b in bs],
                    "commitments": [c.hex() for c in cs],
                    "proofs": [p.hex() for p in ps],
                },
                "output": ok,
                **meta,
            },
        )

    # fulu cell families on the same setup
    from ..kzg.cells import CellContext

    ctx = CellContext(kzg, cells_per_ext_blob=KZG_CELLS)
    meta_c = {**meta, "cells_per_ext_blob": KZG_CELLS}
    cells, cproofs = ctx.compute_cells_and_kzg_proofs(blobs[0])
    d = _case_dir(
        root, config, "fulu", "kzg_cells", "compute_cells_and_kzg_proofs", 0
    )
    _w(
        d,
        "data.json",
        {
            "input": {"blob": blobs[0].hex()},
            "output": [
                [c.hex() for c in cells],
                [p.hex() for p in cproofs],
            ],
            **meta_c,
        },
    )
    half = list(range(0, ctx.cells, 2))
    d = _case_dir(
        root, config, "fulu", "kzg_cells", "recover_cells_and_kzg_proofs", 0
    )
    _w(
        d,
        "data.json",
        {
            "input": {
                "cell_indices": half,
                "cells": [cells[j].hex() for j in half],
            },
            "output": [
                [c.hex() for c in cells],
                [p.hex() for p in cproofs],
            ],
            **meta_c,
        },
    )
    tampered = bytearray(cells[1])
    tampered[0] ^= 1
    for i, (idxs, cs, ps, ok) in enumerate(
        [
            (
                list(range(ctx.cells)),
                [c.hex() for c in cells],
                [p.hex() for p in cproofs],
                True,
            ),
            (
                [0, 1],
                [cells[0].hex(), bytes(tampered).hex()],
                [cproofs[0].hex(), cproofs[1].hex()],
                False,
            ),
        ]
    ):
        d = _case_dir(
            root, config, "fulu", "kzg_cells", "verify_cell_kzg_proof_batch", i
        )
        _w(
            d,
            "data.json",
            {
                "input": {
                    "commitment": comms[0].hex(),
                    "cell_indices": idxs,
                    "cells": cs,
                    "proofs": ps,
                },
                "output": ok,
                **meta_c,
            },
        )


def main(root: str | None = None) -> None:
    from .handler import default_vector_root

    root = root or default_vector_root()
    if os.path.isdir(root):
        shutil.rmtree(root)
    gen_bls(root)
    gen_shuffling(root)
    gen_ssz_static(root)
    gen_operations(root)
    gen_operations_merge(root)
    gen_rewards(root)
    gen_finality(root)
    gen_epoch_processing(root)
    gen_sanity_blocks(root)
    gen_transition(root)
    gen_kzg(root)
    n = sum(len(fs) for _, _, fs in os.walk(root))
    print(f"wrote {n} vector files under {root}")


if __name__ == "__main__":
    main()
