"""Generate the golden conformance vectors under tests/vectors/.

Run once (``python -m lighthouse_tpu.conformance.generate``) and commit the
output. Vectors are produced from the trusted oracle ciphersuite and the
state harness — the runner (handler.py) then exercises the real verification
and state-transition paths against them, per backend. The reference's
equivalent inputs are the official consensus-spec-tests; here they are
self-generated because the environment has no network (SURVEY §4 tier 1).
"""

from __future__ import annotations

import json
import os
import shutil

import numpy as np


def _w(path: str, name: str, data) -> None:
    os.makedirs(path, exist_ok=True)
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(os.path.join(path, name), mode) as f:
        if isinstance(data, bytes):
            f.write(data)
        else:
            json.dump(data, f, indent=1)


def _case_dir(root, config, fork, runner, handler, idx):
    return os.path.join(root, config, fork, runner, handler, f"case_{idx}")


def gen_bls(root: str, config: str = "general") -> None:
    from ..ops.bls_oracle import ciphersuite as cs
    from ..ops.bls_oracle import curves as oc

    fork = "phase0"

    def hx(b: bytes) -> str:
        return b.hex()

    sks = [cs.keygen_from_ikm(bytes([i]) * 32) for i in range(1, 5)]
    pks = [oc.g1_compress(cs.sk_to_pk(sk)).hex() for sk in sks]
    msg = b"\x11" * 32
    sigs = [oc.g2_compress(cs.sign(sk, msg)).hex() for sk in sks]

    # sign
    for i, sk in enumerate(sks[:2]):
        _w(
            _case_dir(root, config, fork, "bls", "sign", i),
            "data.json",
            {
                "input": {"privkey": sk.to_bytes(32, "big").hex(), "message": hx(msg)},
                "output": sigs[i],
            },
        )
    # verify: valid, wrong message, wrong key, infinity sig
    cases = [
        ({"pubkey": pks[0], "message": hx(msg), "signature": sigs[0]}, True),
        ({"pubkey": pks[0], "message": hx(b"\x22" * 32), "signature": sigs[0]}, False),
        ({"pubkey": pks[1], "message": hx(msg), "signature": sigs[0]}, False),
        (
            {
                "pubkey": pks[0],
                "message": hx(msg),
                "signature": (b"\xc0" + b"\x00" * 95).hex(),
            },
            False,
        ),
    ]
    for i, (inp, out) in enumerate(cases):
        _w(
            _case_dir(root, config, fork, "bls", "verify", i),
            "data.json",
            {"input": inp, "output": out},
        )
    # aggregate
    agg = None
    for sk in sks:
        agg = oc.g2_add(agg, cs.sign(sk, msg))
    _w(
        _case_dir(root, config, fork, "bls", "aggregate", 0),
        "data.json",
        {"input": sigs, "output": oc.g2_compress(agg).hex()},
    )
    # fast_aggregate_verify: valid + one wrong-key
    _w(
        _case_dir(root, config, fork, "bls", "fast_aggregate_verify", 0),
        "data.json",
        {
            "input": {
                "pubkeys": pks,
                "message": hx(msg),
                "signature": oc.g2_compress(agg).hex(),
            },
            "output": True,
        },
    )
    _w(
        _case_dir(root, config, fork, "bls", "fast_aggregate_verify", 1),
        "data.json",
        {
            "input": {
                "pubkeys": pks[:3],
                "message": hx(msg),
                "signature": oc.g2_compress(agg).hex(),
            },
            "output": False,
        },
    )
    # batch_verify: all valid; one poisoned
    msgs = [bytes([i]) * 32 for i in range(3)]
    sets = []
    for i, m in enumerate(msgs):
        a = None
        for sk in sks[: i + 2]:
            a = oc.g2_add(a, cs.sign(sk, m))
        sets.append(
            {
                "pubkeys": pks[: i + 2],
                "message": m.hex(),
                "signature": oc.g2_compress(a).hex(),
            }
        )
    _w(
        _case_dir(root, config, fork, "bls", "batch_verify", 0),
        "data.json",
        {"input": {"sets": sets}, "output": True},
    )
    poisoned = [dict(s) for s in sets]
    poisoned[1]["signature"] = poisoned[0]["signature"]
    _w(
        _case_dir(root, config, fork, "bls", "batch_verify", 1),
        "data.json",
        {"input": {"sets": poisoned}, "output": False},
    )


def gen_shuffling(root: str, config: str = "minimal") -> None:
    from ..ops.shuffle import shuffle_list
    from ..types.spec import mainnet_spec, minimal_spec

    spec = minimal_spec() if config == "minimal" else mainnet_spec()
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    for i, (seed_byte, count) in enumerate([(0x42, 8), (0x07, 33), (0xA5, 100)]):
        seed = bytes([seed_byte]) * 32
        mapping = np.asarray(
            shuffle_list(np.arange(count, dtype=np.uint64), seed, rounds)
        ).tolist()
        _w(
            _case_dir(root, config, "phase0", "shuffling", "core", i),
            "mapping.json",
            {"seed": seed.hex(), "count": count, "mapping": mapping},
        )


def _harness(fork: str, n=32):
    from ..testing.harness import StateHarness
    from ..types.spec import minimal_spec

    spec = minimal_spec(altair_fork_epoch=0) if fork == "altair" else minimal_spec()
    return StateHarness(spec, n)


def gen_ssz_static(root: str, config: str = "minimal") -> None:
    for fork in ("phase0", "altair"):
        h = _harness(fork)
        h.extend_chain(3)
        state = h.state
        block = h.produce_block(state.slot + 1)
        objs = {
            "BeaconState": (type(state), state),
            "SignedBeaconBlock": (type(block), block),
        }
        atts = h.attestations_for_slot(
            state, state.slot, state.latest_block_header.tree_root()
        )
        if atts:
            objs["Attestation"] = (type(atts[0]), atts[0])
        for name, (cls, value) in objs.items():
            d = _case_dir(root, config, fork, "ssz_static", name, 0)
            _w(d, "serialized.ssz", cls.encode(value))
            _w(d, "root.json", {"root": value.tree_root().hex()})


def gen_operations(root: str, config: str = "minimal") -> None:
    from ..state_transition import process_slots
    from ..types.helpers import compute_signing_root, get_domain

    fork = "phase0"
    h = _harness(fork)
    h.extend_chain(2)
    spec = h.spec
    state_cls = type(h.state)

    # --- attestation: valid + bad-target error case
    prev = h.state
    att = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))[0]
    pre = prev.copy()
    process_slots(spec, pre, prev.slot + spec.min_attestation_inclusion_delay)
    d = _case_dir(root, config, fork, "operations", "attestation", 0)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(att).encode(att))
    post = pre.copy()
    from .handler import _op_attestation

    _op_attestation(spec, post, att)
    _w(d, "post.ssz", state_cls.encode(post))

    bad = type(att).decode(type(att).encode(att))
    bad.data.target.root = b"\xde" * 32
    d = _case_dir(root, config, fork, "operations", "attestation", 1)
    _w(d, "pre.ssz", state_cls.encode(pre))
    _w(d, "attestation.ssz", type(bad).encode(bad))
    _w(d, "meta.json", {"error": True})

    # --- voluntary exit: advance past shard_committee_period
    from ..types.containers import SignedVoluntaryExit, VoluntaryExit

    exit_state = h.state.copy()
    target_epoch = spec.shard_committee_period + 1
    process_slots(spec, exit_state, target_epoch * spec.preset.SLOTS_PER_EPOCH)
    exit_msg = VoluntaryExit(epoch=target_epoch, validator_index=3)
    domain = get_domain(
        spec, exit_state, spec.DOMAIN_VOLUNTARY_EXIT, epoch=target_epoch
    )
    sig = h._sign(3, compute_signing_root(exit_msg, domain))
    sve = SignedVoluntaryExit(message=exit_msg, signature=sig)
    d = _case_dir(root, config, fork, "operations", "voluntary_exit", 0)
    _w(d, "pre.ssz", state_cls.encode(exit_state))
    _w(d, "voluntary_exit.ssz", SignedVoluntaryExit.encode(sve))
    post = exit_state.copy()
    from .handler import _op_exit

    _op_exit(spec, post, sve)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: wrong signature
    bad = SignedVoluntaryExit(message=exit_msg, signature=h._sign(4, b"\x00" * 32))
    d = _case_dir(root, config, fork, "operations", "voluntary_exit", 1)
    _w(d, "pre.ssz", state_cls.encode(exit_state))
    _w(d, "voluntary_exit.ssz", SignedVoluntaryExit.encode(bad))
    _w(d, "meta.json", {"error": True})

    # --- proposer slashing: two conflicting headers by validator 0
    from ..types.containers import BeaconBlockHeader, SignedBeaconBlockHeader
    from ..types.containers import ProposerSlashing

    st = h.state
    slot = st.slot
    proposer = 0
    hdrs = []
    for i, body_root in enumerate((b"\x01" * 32, b"\x02" * 32)):
        header = BeaconBlockHeader(
            slot=slot,
            proposer_index=proposer,
            parent_root=b"\x03" * 32,
            state_root=b"\x04" * 32,
            body_root=body_root,
        )
        dom = get_domain(
            spec, st, spec.DOMAIN_BEACON_PROPOSER,
            epoch=spec.compute_epoch_at_slot(slot),
        )
        hdrs.append(
            SignedBeaconBlockHeader(
                message=header,
                signature=h._sign(proposer, compute_signing_root(header, dom)),
            )
        )
    ps = ProposerSlashing(signed_header_1=hdrs[0], signed_header_2=hdrs[1])
    d = _case_dir(root, config, fork, "operations", "proposer_slashing", 0)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "proposer_slashing.ssz", ProposerSlashing.encode(ps))
    post = st.copy()
    from .handler import _op_proposer_slashing

    _op_proposer_slashing(spec, post, ps)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: identical headers (not slashable)
    same = ProposerSlashing(signed_header_1=hdrs[0], signed_header_2=hdrs[0])
    d = _case_dir(root, config, fork, "operations", "proposer_slashing", 1)
    _w(d, "pre.ssz", state_cls.encode(st))
    _w(d, "proposer_slashing.ssz", ProposerSlashing.encode(same))
    _w(d, "meta.json", {"error": True})

    # --- attester slashing: double vote by one committee
    from ..state_transition import get_beacon_committee
    from ..types.containers import AttestationData, Checkpoint

    st2 = h.state
    committee = get_beacon_committee(spec, st2, st2.slot, 0)
    epoch = spec.compute_epoch_at_slot(st2.slot)
    datas = []
    for root_byte in (0x0A, 0x0B):
        datas.append(
            AttestationData(
                slot=st2.slot,
                index=0,
                beacon_block_root=bytes([root_byte]) * 32,
                source=st2.current_justified_checkpoint,
                target=Checkpoint(epoch=epoch, root=bytes([root_byte]) * 32),
            )
        )
    dom = get_domain(spec, st2, spec.DOMAIN_BEACON_ATTESTER, epoch=epoch)
    ns = h.ns
    from ..ops.bls_oracle.fields import R as CURVE_ORDER

    indexed = []
    for data in datas:
        agg_sk = sum(h.sks[int(v)] for v in committee) % CURVE_ORDER
        indexed.append(
            ns.IndexedAttestation(
                attesting_indices=sorted(int(v) for v in committee),
                data=data,
                signature=h._nb.sign(
                    agg_sk.to_bytes(32, "big"), compute_signing_root(data, dom)
                ),
            )
        )
    aslash = ns.AttesterSlashing(attestation_1=indexed[0], attestation_2=indexed[1])
    d = _case_dir(root, config, fork, "operations", "attester_slashing", 0)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "attester_slashing.ssz", ns.AttesterSlashing.encode(aslash))
    post = st2.copy()
    from .handler import _op_attester_slashing

    _op_attester_slashing(spec, post, aslash)
    _w(d, "post.ssz", state_cls.encode(post))
    # error twin: same attestation twice
    same = ns.AttesterSlashing(attestation_1=indexed[0], attestation_2=indexed[0])
    d = _case_dir(root, config, fork, "operations", "attester_slashing", 1)
    _w(d, "pre.ssz", state_cls.encode(st2))
    _w(d, "attester_slashing.ssz", ns.AttesterSlashing.encode(same))
    _w(d, "meta.json", {"error": True})


def gen_epoch_processing(root: str, config: str = "minimal") -> None:
    from ..state_transition import process_epoch, process_slots

    for fork in ("phase0", "altair"):
        h = _harness(fork)
        h.extend_chain(h.spec.preset.SLOTS_PER_EPOCH + 2)
        state = h.state.copy()
        # advance to the last slot of the epoch; pre = state ready for epoch proc
        spe = h.spec.preset.SLOTS_PER_EPOCH
        target = (state.slot // spe + 1) * spe - 1
        process_slots(h.spec, state, target)
        state_cls = type(state)
        d = _case_dir(root, config, fork, "epoch_processing", "full", 0)
        _w(d, "pre.ssz", state_cls.encode(state))
        post = state.copy()
        process_epoch(h.spec, post)
        _w(d, "post.ssz", state_cls.encode(post))


def gen_sanity_blocks(root: str, config: str = "minimal") -> None:
    for fork in ("phase0", "altair"):
        h = _harness(fork)
        h.extend_chain(2)
        pre = h.state.copy()
        state_cls = type(pre)
        blocks = []
        for _ in range(3):
            slot = h.state.slot + 1
            atts = []
            prev = h.state
            if prev.slot + h.spec.min_attestation_inclusion_delay <= slot:
                atts = h.attestations_for_slot(prev, prev.slot, h.head_root(prev))
            block = h.produce_block(slot, attestations=atts)
            h.apply_block(block)
            blocks.append(block)
        d = _case_dir(root, config, fork, "sanity_blocks", "chain", 0)
        _w(d, "pre.ssz", state_cls.encode(pre))
        for i, b in enumerate(blocks):
            _w(d, f"blocks_{i}.ssz", type(b).encode(b))
        _w(d, "post.ssz", state_cls.encode(h.state))


def main(root: str | None = None) -> None:
    from .handler import default_vector_root

    root = root or default_vector_root()
    if os.path.isdir(root):
        shutil.rmtree(root)
    gen_bls(root)
    gen_shuffling(root)
    gen_ssz_static(root)
    gen_operations(root)
    gen_epoch_processing(root)
    gen_sanity_blocks(root)
    n = sum(len(fs) for _, _, fs in os.walk(root))
    print(f"wrote {n} vector files under {root}")


if __name__ == "__main__":
    main()
