"""Conformance runner: walk the vector tree, run every case, consume every file.

Twin of ``testing/ef_tests/src/handler.rs:13-99`` (Handler walks
fork/handler/suite dirs, one Case impl per family) combined with the
``check_all_files_accessed.py`` discipline (``Makefile:126-131``): ``run_all``
records every file each case reads and fails if ANY file under the vector
root was not consumed.
"""

from __future__ import annotations

import json
import os

import numpy as np


class ConformanceError(AssertionError):
    pass


class CaseContext:
    """Tracks file consumption for one case directory."""

    def __init__(self, path: str, tracker: set):
        self.path = path
        self._tracker = tracker

    def read(self, name: str) -> bytes:
        p = os.path.join(self.path, name)
        with open(p, "rb") as f:
            data = f.read()
        self._tracker.add(os.path.abspath(p))
        return data

    def json(self, name: str):
        return json.loads(self.read(name).decode())

    def has(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.path, name))


# ---------------------------------------------------------------------------
# Case implementations, keyed by runner name (directory level under the fork)
# ---------------------------------------------------------------------------


def _ns_and_spec(config: str, fork: str):
    from ..types.containers import for_preset
    from ..types.spec import mainnet_spec, minimal_spec

    from .generate import fork_overrides

    mk = minimal_spec if config == "minimal" else mainnet_spec
    # vectors for a fork are generated with that fork active from genesis
    spec = mk(**fork_overrides(fork))
    return for_preset(spec.preset.name), spec


def _ssz_type(ns, fork: str, name: str):
    """Resolve a container class by its spec name for the given fork."""
    per_fork = {
        "BeaconState": ns.state_types,
        "SignedBeaconBlock": ns.block_types,
        "Attestation": ns.attestation_types,
        "IndexedAttestation": ns.indexed_attestation_types,
        "AttesterSlashing": ns.attester_slashing_types,
    }
    if name in per_fork:
        return per_fork[name][fork]
    fixed = {
        "AggregateAndProof": ns.AggregateAndProof,
        "SignedAggregateAndProof": ns.SignedAggregateAndProof,
        "SyncAggregate": ns.SyncAggregate,
        "SyncCommittee": ns.SyncCommittee,
        "ExecutionPayload": ns.payload_types.get(fork),
        "DepositRequest": getattr(ns, "DepositRequest", None),
        "WithdrawalRequest": getattr(ns, "WithdrawalRequest", None),
        "ConsolidationRequest": getattr(ns, "ConsolidationRequest", None),
    }
    if fixed.get(name) is not None:
        return fixed[name]
    from ..types import containers as c

    return getattr(c, name)


def case_ssz_static(ctx: CaseContext, config: str, fork: str, handler: str):
    """serialized.ssz must decode, re-encode byte-identical, and tree-root to
    root.json (ssz_static family, testing/ef_tests/src/cases/ssz_static.rs)."""
    ns, _ = _ns_and_spec(config, fork)
    cls = _ssz_type(ns, fork, handler)
    data = ctx.read("serialized.ssz")
    expected = ctx.json("root.json")
    value = cls.decode(data)
    if cls.encode(value) != data:
        raise ConformanceError(f"{ctx.path}: ssz round-trip mismatch")
    root = value.tree_root() if hasattr(value, "tree_root") else cls.hash_tree_root(value)
    if root.hex() != expected["root"]:
        raise ConformanceError(
            f"{ctx.path}: root {root.hex()} != {expected['root']}"
        )


def case_shuffling(ctx: CaseContext, config: str, fork: str, handler: str):
    """Full-list mapping + per-index agreement (cases/shuffling.rs)."""
    from ..ops.shuffle import compute_shuffled_index, shuffle_list
    from ..types.spec import mainnet_spec, minimal_spec

    spec = minimal_spec() if config == "minimal" else mainnet_spec()
    data = ctx.json("mapping.json")
    seed = bytes.fromhex(data["seed"])
    count = data["count"]
    expected = data["mapping"]
    rounds = spec.preset.SHUFFLE_ROUND_COUNT
    got = np.asarray(
        shuffle_list(np.arange(count, dtype=np.uint64), seed, rounds)
    ).tolist()
    if got != expected:
        raise ConformanceError(f"{ctx.path}: shuffle_list mismatch")
    for i in range(count):
        j = compute_shuffled_index(i, count, seed, rounds)
        if expected[j] != i:
            raise ConformanceError(
                f"{ctx.path}: compute_shuffled_index({i}) inconsistent"
            )


def _bls_backends():
    backends = ["oracle", "native"]
    if os.environ.get("LIGHTHOUSE_CONFORMANCE_TPU"):
        backends.append("tpu")
    return backends


def case_bls(ctx: CaseContext, config: str, fork: str, handler: str):
    """BLS families over the seam, run per backend (cases/bls_*.rs; the
    reference runs its whole EF matrix once per crypto backend)."""
    from .. import bls

    data = ctx.json("data.json")
    prev = bls.get_backend()
    try:
        for backend in _bls_backends():
            bls.set_backend(backend)
            _run_bls_case(handler, data, backend)
    finally:
        bls.set_backend(prev)


def _run_bls_case(handler: str, data: dict, backend: str):
    from .. import bls

    def pk(h):
        return bls.PublicKey.from_bytes(bytes.fromhex(h))

    if handler == "sign":
        sk = bls.SecretKey.from_bytes(bytes.fromhex(data["input"]["privkey"]))
        sig = sk.sign(bytes.fromhex(data["input"]["message"]))
        if sig.serialize().hex() != data["output"]:
            raise ConformanceError(f"bls/sign [{backend}]: mismatch")
    elif handler == "verify":
        ok_expected = data["output"]
        try:
            p = pk(data["input"]["pubkey"])
            sig = bls.Signature.from_bytes(bytes.fromhex(data["input"]["signature"]))
            ok = sig.verify(p, bytes.fromhex(data["input"]["message"]))
        except bls.BlsError:
            ok = False
        if ok != ok_expected:
            raise ConformanceError(f"bls/verify [{backend}]: {ok} != {ok_expected}")
    elif handler == "aggregate":
        sigs = [
            bls.Signature.from_bytes(bytes.fromhex(h)) for h in data["input"]
        ]
        agg = bls.AggregateSignature.aggregate(sigs)
        if agg.serialize().hex() != data["output"]:
            raise ConformanceError(f"bls/aggregate [{backend}]: mismatch")
    elif handler == "fast_aggregate_verify":
        ok_expected = data["output"]
        try:
            pks = [pk(h) for h in data["input"]["pubkeys"]]
            agg = bls.AggregateSignature.from_bytes(
                bytes.fromhex(data["input"]["signature"])
            )
            ok = agg.fast_aggregate_verify(
                bytes.fromhex(data["input"]["message"]), pks
            )
        except bls.BlsError:
            ok = False
        if ok != ok_expected:
            raise ConformanceError(
                f"bls/fast_aggregate_verify [{backend}]: {ok} != {ok_expected}"
            )
    elif handler == "batch_verify":
        sets = []
        for s in data["input"]["sets"]:
            sets.append(
                bls.SignatureSet.multiple_pubkeys(
                    bls.Signature.from_bytes(bytes.fromhex(s["signature"])),
                    [pk(h) for h in s["pubkeys"]],
                    bytes.fromhex(s["message"]),
                )
            )
        ok = bls.verify_signature_sets(sets)
        if ok != data["output"]:
            raise ConformanceError(
                f"bls/batch_verify [{backend}]: {ok} != {data['output']}"
            )
    else:
        raise ConformanceError(f"unknown bls handler {handler}")


def _op_attestation(spec, state, op):
    from ..state_transition.per_block import ConsensusContext, process_attestation

    process_attestation(spec, state, op, 0, ConsensusContext(), verify=True)


def _op_exit(spec, state, op):
    from ..state_transition.per_block import process_exit

    process_exit(spec, state, op, verify=True)


def _op_proposer_slashing(spec, state, op):
    from ..state_transition.per_block import (
        ConsensusContext,
        process_proposer_slashing,
    )

    process_proposer_slashing(spec, state, op, ConsensusContext(), verify=True)


def _op_attester_slashing(spec, state, op):
    from ..state_transition.per_block import process_attester_slashing

    process_attester_slashing(spec, state, op, verify=True)


def _op_execution_payload(spec, state, op):
    from ..state_transition.per_block import process_execution_payload

    process_execution_payload(spec, state, op)


def _op_withdrawals(spec, state, op):
    from ..state_transition.per_block import process_withdrawals

    process_withdrawals(spec, state, op)


def _op_bls_change(spec, state, op):
    from ..state_transition.per_block import process_bls_to_execution_change

    process_bls_to_execution_change(spec, state, op, verify=True)


def _op_deposit_request(spec, state, op):
    from ..state_transition.electra import process_deposit_request

    process_deposit_request(spec, state, op)


def _op_withdrawal_request(spec, state, op):
    from ..state_transition.electra import process_withdrawal_request

    process_withdrawal_request(spec, state, op)


def _op_consolidation_request(spec, state, op):
    from ..state_transition.electra import process_consolidation_request

    process_consolidation_request(spec, state, op)


def case_operations(ctx: CaseContext, config: str, fork: str, handler: str):
    """pre.ssz + <op>.ssz -> post.ssz, or meta.json {"error": true}
    (cases/operations.rs shape). EL-request handlers (electra) treat invalid
    inputs as spec'd no-ops, so their "failure" vectors have post == pre."""
    from ..state_transition.per_block import BlockProcessingError

    ns, spec = _ns_and_spec(config, fork)
    state_cls = _ssz_type(ns, fork, "BeaconState")
    state = state_cls.decode(ctx.read("pre.ssz"))
    expect_error = ctx.has("meta.json") and ctx.json("meta.json").get("error")

    op_files = {
        "attestation": ("attestation.ssz", "Attestation", _op_attestation),
        "voluntary_exit": (
            "voluntary_exit.ssz", "SignedVoluntaryExit", _op_exit,
        ),
        "proposer_slashing": (
            "proposer_slashing.ssz", "ProposerSlashing", _op_proposer_slashing,
        ),
        "attester_slashing": (
            "attester_slashing.ssz", "AttesterSlashing", _op_attester_slashing,
        ),
        "execution_payload": (
            "execution_payload.ssz", "ExecutionPayload", _op_execution_payload,
        ),
        "withdrawals": (
            "execution_payload.ssz", "ExecutionPayload", _op_withdrawals,
        ),
        "bls_to_execution_change": (
            "address_change.ssz", "SignedBLSToExecutionChange", _op_bls_change,
        ),
        "deposit_request": (
            "deposit_request.ssz", "DepositRequest", _op_deposit_request,
        ),
        "withdrawal_request": (
            "withdrawal_request.ssz", "WithdrawalRequest",
            _op_withdrawal_request,
        ),
        "consolidation_request": (
            "consolidation_request.ssz", "ConsolidationRequest",
            _op_consolidation_request,
        ),
    }
    fname, cls_name, op_fn = op_files[handler]
    op_cls = _ssz_type(ns, fork, cls_name)
    op = op_cls.decode(ctx.read(fname))
    try:
        op_fn(spec, state, op)
        failed = False
    except BlockProcessingError:
        failed = True
    if expect_error:
        if not failed:
            raise ConformanceError(f"{ctx.path}: expected rejection, op applied")
        return
    if failed:
        raise ConformanceError(f"{ctx.path}: valid operation rejected")
    post = state_cls.decode(ctx.read("post.ssz"))
    if state.tree_root() != post.tree_root():
        raise ConformanceError(f"{ctx.path}: post-state root mismatch")


def _apply_rewards(spec, state):
    """The rewards sub-transition slice shared by the generator and the
    rewards runner: justification/finalization first (it feeds the
    finality-delay / leak terms), then (altair+) inactivity updates, then
    rewards/penalties — the head of ``_process_epoch_phase0`` /
    ``_process_epoch_altair``, in production order."""
    from ..state_transition import per_epoch as pe

    cols = pe._Cols(state)
    if getattr(state, "fork_name", "phase0") == "phase0":
        pe.process_justification_and_finalization_phase0(spec, state, cols)
        pe.process_rewards_and_penalties_phase0(spec, state, cols)
    else:
        pe.process_justification_and_finalization_altair(spec, state, cols)
        pe.process_inactivity_updates(spec, state, cols)
        pe.process_rewards_and_penalties_altair(spec, state, cols)


def case_rewards(ctx: CaseContext, config: str, fork: str, handler: str):
    """pre.ssz + deltas.json: the per-validator balance deltas the rewards
    stages must produce (cases/rewards.rs shape, fused across components).
    These pin the exact columnar-numpy outputs the device epoch kernels are
    parity-tested against — including the electra fork family."""
    ns, spec = _ns_and_spec(config, fork)
    state_cls = _ssz_type(ns, fork, "BeaconState")
    state = state_cls.decode(ctx.read("pre.ssz"))
    pre_bal = [int(b) for b in state.balances]
    _apply_rewards(spec, state)
    expected = ctx.json("deltas.json")["deltas"]
    got = [int(a) - b for a, b in zip(state.balances, pre_bal)]
    if got != expected:
        diffs = [i for i, (g, e) in enumerate(zip(got, expected)) if g != e]
        raise ConformanceError(
            f"{ctx.path}: reward deltas mismatch at validators {diffs[:8]}"
        )


def case_finality(ctx: CaseContext, config: str, fork: str, handler: str):
    """pre.ssz + a multi-epoch block chain -> post.ssz, with meta.json
    pinning the justified/finalized checkpoints the full transition must
    reach (cases/finality.rs). The chain crosses epoch boundaries, so every
    epoch stage — device-kernel or columnar — is on the hook."""
    from ..state_transition import (
        BlockSignatureStrategy,
        per_block_processing,
        process_slots,
    )

    ns, spec = _ns_and_spec(config, fork)
    state_cls = _ssz_type(ns, fork, "BeaconState")
    block_cls = _ssz_type(ns, fork, "SignedBeaconBlock")
    meta = ctx.json("meta.json")
    state = state_cls.decode(ctx.read("pre.ssz"))
    i = 0
    while ctx.has(f"blocks_{i}.ssz"):
        sb = block_cls.decode(ctx.read(f"blocks_{i}.ssz"))
        if state.slot < sb.message.slot:
            process_slots(spec, state, sb.message.slot)
        per_block_processing(
            spec, state, sb, strategy=BlockSignatureStrategy.VERIFY_BULK
        )
        i += 1
    if int(state.finalized_checkpoint.epoch) != meta["finalized_epoch"]:
        raise ConformanceError(
            f"{ctx.path}: finalized epoch "
            f"{int(state.finalized_checkpoint.epoch)} != "
            f"{meta['finalized_epoch']}"
        )
    if int(state.current_justified_checkpoint.epoch) != meta["justified_epoch"]:
        raise ConformanceError(
            f"{ctx.path}: justified epoch "
            f"{int(state.current_justified_checkpoint.epoch)} != "
            f"{meta['justified_epoch']}"
        )
    post = state_cls.decode(ctx.read("post.ssz"))
    if state.tree_root() != post.tree_root():
        raise ConformanceError(f"{ctx.path}: finality post-state mismatch")


def case_epoch_processing(ctx: CaseContext, config: str, fork: str, handler: str):
    """pre.ssz -> process_epoch -> post.ssz (cases/epoch_processing.rs, fused
    single-pass instead of per-sub-transition)."""
    from ..state_transition import process_epoch

    ns, spec = _ns_and_spec(config, fork)
    state_cls = _ssz_type(ns, fork, "BeaconState")
    state = state_cls.decode(ctx.read("pre.ssz"))
    process_epoch(spec, state)
    post = state_cls.decode(ctx.read("post.ssz"))
    if state.tree_root() != post.tree_root():
        raise ConformanceError(f"{ctx.path}: epoch post-state mismatch")


def case_sanity_blocks(ctx: CaseContext, config: str, fork: str, handler: str):
    """pre.ssz + blocks_N.ssz... -> post.ssz with full signature verification
    (cases/sanity_blocks.rs)."""
    from ..state_transition import BlockSignatureStrategy, per_block_processing, process_slots

    ns, spec = _ns_and_spec(config, fork)
    state_cls = _ssz_type(ns, fork, "BeaconState")
    block_cls = _ssz_type(ns, fork, "SignedBeaconBlock")
    state = state_cls.decode(ctx.read("pre.ssz"))
    i = 0
    while ctx.has(f"blocks_{i}.ssz"):
        sb = block_cls.decode(ctx.read(f"blocks_{i}.ssz"))
        if state.slot < sb.message.slot:
            process_slots(spec, state, sb.message.slot)
        per_block_processing(
            spec, state, sb, strategy=BlockSignatureStrategy.VERIFY_BULK
        )
        i += 1
    post = state_cls.decode(ctx.read("post.ssz"))
    if state.tree_root() != post.tree_root():
        raise ConformanceError(f"{ctx.path}: sanity post-state mismatch")


def case_transition(ctx: CaseContext, config: str, fork: str, handler: str):
    """Cross-fork chain: pre decodes as the old fork's state, blocks switch
    class at the boundary, post decodes as the new fork's state
    (cases/transition.rs)."""
    from ..state_transition import (
        BlockSignatureStrategy,
        per_block_processing,
        process_slots,
    )
    from ..types.containers import for_preset
    from ..types.spec import mainnet_spec, minimal_spec

    from .generate import fork_overrides

    meta = ctx.json("meta.json")
    pre_fork, fork_epoch = meta["pre_fork"], meta["fork_epoch"]
    overrides = fork_overrides(pre_fork)
    overrides[f"{fork}_fork_epoch"] = fork_epoch
    mk = minimal_spec if config == "minimal" else mainnet_spec
    spec = mk(**overrides)
    ns = for_preset(spec.preset.name)
    state = ns.state_types[pre_fork].decode(ctx.read("pre.ssz"))
    i = 0
    while ctx.has(f"blocks_{i}.ssz"):
        raw = ctx.read(f"blocks_{i}.ssz")
        # the block's slot (bytes 100..108 of any SignedBeaconBlock: 4-byte
        # message offset + 96-byte signature, then the fixed slot field)
        slot = int.from_bytes(raw[100:108], "little")
        block_fork = spec.fork_name_at_epoch(spec.compute_epoch_at_slot(slot))
        sb = ns.block_types[block_fork].decode(raw)
        if state.slot < sb.message.slot:
            process_slots(spec, state, sb.message.slot)
        per_block_processing(
            spec, state, sb, strategy=BlockSignatureStrategy.VERIFY_BULK
        )
        i += 1
    post = ns.state_types[fork].decode(ctx.read("post.ssz"))
    if state.tree_root() != post.tree_root():
        raise ConformanceError(f"{ctx.path}: transition post-state mismatch")


def _kzg_from_meta(data: dict):
    from ..kzg import Kzg
    from ..kzg.setup import insecure_setup

    return Kzg(insecure_setup(data["setup_n"], n_g2=data["setup_n_g2"]))


def case_kzg(ctx: CaseContext, config: str, fork: str, handler: str):
    """Deneb blob families (cases/kzg_*.rs) on the vector's setup geometry."""
    from ..kzg import KzgError

    data = ctx.json("data.json")
    kzg = _kzg_from_meta(data)
    inp, expected = data["input"], data["output"]
    try:
        if handler == "blob_to_kzg_commitment":
            got = kzg.blob_to_kzg_commitment(bytes.fromhex(inp["blob"])).hex()
        elif handler == "compute_kzg_proof":
            proof, y = kzg.compute_kzg_proof(
                bytes.fromhex(inp["blob"]), bytes.fromhex(inp["z"])
            )
            got = [proof.hex(), y.hex()]
        elif handler == "verify_kzg_proof":
            got = kzg.verify_kzg_proof(
                bytes.fromhex(inp["commitment"]),
                bytes.fromhex(inp["z"]),
                bytes.fromhex(inp["y"]),
                bytes.fromhex(inp["proof"]),
            )
        elif handler == "compute_blob_kzg_proof":
            got = kzg.compute_blob_kzg_proof(
                bytes.fromhex(inp["blob"]), bytes.fromhex(inp["commitment"])
            ).hex()
        elif handler == "verify_blob_kzg_proof":
            got = kzg.verify_blob_kzg_proof(
                bytes.fromhex(inp["blob"]),
                bytes.fromhex(inp["commitment"]),
                bytes.fromhex(inp["proof"]),
            )
        elif handler == "verify_blob_kzg_proof_batch":
            got = kzg.verify_blob_kzg_proof_batch(
                [bytes.fromhex(b) for b in inp["blobs"]],
                [bytes.fromhex(c) for c in inp["commitments"]],
                [bytes.fromhex(p) for p in inp["proofs"]],
            )
        else:
            raise ConformanceError(f"unknown kzg handler {handler}")
    except KzgError:
        got = False
    if got != expected:
        raise ConformanceError(f"{ctx.path}: kzg/{handler} mismatch")


def case_kzg_cells(ctx: CaseContext, config: str, fork: str, handler: str):
    """Fulu/PeerDAS cell families on the vector's setup geometry."""
    from ..kzg import KzgError
    from ..kzg.cells import CellContext

    data = ctx.json("data.json")
    cc = CellContext(
        _kzg_from_meta(data), cells_per_ext_blob=data["cells_per_ext_blob"]
    )
    inp, expected = data["input"], data["output"]
    try:
        if handler == "compute_cells_and_kzg_proofs":
            cells, proofs = cc.compute_cells_and_kzg_proofs(
                bytes.fromhex(inp["blob"])
            )
            got = [[c.hex() for c in cells], [p.hex() for p in proofs]]
        elif handler == "recover_cells_and_kzg_proofs":
            cells, proofs = cc.recover_cells_and_kzg_proofs(
                inp["cell_indices"],
                [bytes.fromhex(c) for c in inp["cells"]],
            )
            got = [[c.hex() for c in cells], [p.hex() for p in proofs]]
        elif handler == "verify_cell_kzg_proof_batch":
            got = cc.verify_cell_kzg_proof_batch(
                [bytes.fromhex(inp["commitment"])] * len(inp["cell_indices"]),
                inp["cell_indices"],
                [bytes.fromhex(c) for c in inp["cells"]],
                [bytes.fromhex(p) for p in inp["proofs"]],
            )
        else:
            raise ConformanceError(f"unknown kzg_cells handler {handler}")
    except KzgError:
        got = False
    if got != expected:
        raise ConformanceError(f"{ctx.path}: kzg_cells/{handler} mismatch")


_RUNNERS = {
    "ssz_static": case_ssz_static,
    "shuffling": case_shuffling,
    "bls": case_bls,
    "operations": case_operations,
    "rewards": case_rewards,
    "finality": case_finality,
    "epoch_processing": case_epoch_processing,
    "sanity_blocks": case_sanity_blocks,
    "transition": case_transition,
    "kzg": case_kzg,
    "kzg_cells": case_kzg_cells,
}


# ---------------------------------------------------------------------------
# Walker
# ---------------------------------------------------------------------------


def default_vector_root() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(here, "tests", "vectors")


def run_all(root: str | None = None, runners: list[str] | None = None) -> int:
    """Run every case under root; fail on any unconsumed file. Returns the
    number of cases run."""
    root = root or default_vector_root()
    if not os.path.isdir(root):
        raise ConformanceError(f"no vector tree at {root} (run generate.py)")
    consumed: set = set()
    n_cases = 0

    def _subdirs(path):
        # stray FILES at intermediate levels are left unconsumed on purpose:
        # the all-files-consumed check below reports them with a clean error
        return sorted(
            e for e in os.listdir(path) if os.path.isdir(os.path.join(path, e))
        )

    for config in _subdirs(root):
        for fork in _subdirs(os.path.join(root, config)):
            fork_dir = os.path.join(root, config, fork)
            for runner in _subdirs(fork_dir):
                if runners and runner not in runners:
                    raise ConformanceError(
                        f"runner {runner} present on disk but not requested — "
                        "all vectors must be consumed"
                    )
                fn = _RUNNERS.get(runner)
                if fn is None:
                    raise ConformanceError(f"no case impl for runner {runner!r}")
                runner_dir = os.path.join(fork_dir, runner)
                for handler in _subdirs(runner_dir):
                    handler_dir = os.path.join(runner_dir, handler)
                    for case in _subdirs(handler_dir):
                        ctx = CaseContext(
                            os.path.join(handler_dir, case), consumed
                        )
                        fn(ctx, config, fork, handler)
                        n_cases += 1
    # all-files-consumed check
    all_files = set()
    for dirpath, _, files in os.walk(root):
        for f in files:
            all_files.add(os.path.abspath(os.path.join(dirpath, f)))
    missed = all_files - consumed
    if missed:
        listing = "\n  ".join(sorted(missed)[:20])
        raise ConformanceError(
            f"{len(missed)} vector file(s) never consumed:\n  {listing}"
        )
    return n_cases
