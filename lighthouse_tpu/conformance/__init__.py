"""Spec-conformance harness — the twin of the reference's EF-test runner.

The reference pins its state transition and BLS backends to the official
``consensus-spec-tests`` vectors via a Handler/Case runner
(``/root/reference/testing/ef_tests/src/handler.rs:13-99``) plus a script
asserting every vector file on disk was consumed (``Makefile:126-131``,
``check_all_files_accessed.py``). This environment has no network, so the
vectors here are GOLDEN vectors generated once from the trusted oracle +
harness (``generate.py``) and checked in under ``tests/vectors/``; the runner
(``handler.py``) walks the tree with the same all-files-consumed discipline —
any vector file the runner does not consume fails the run, so silently
skipped coverage is impossible.

Layout (mirrors consensus-spec-tests):
    tests/vectors/<config>/<fork>/<runner>/<handler>/<case>/...
"""

from .handler import ConformanceError, run_all  # noqa: F401
