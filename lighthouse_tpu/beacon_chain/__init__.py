"""Beacon chain runtime (beacon_node/beacon_chain twin)."""

from .chain import BeaconChain, BlockError
from .pubkey_cache import ValidatorPubkeyCache
