"""Restart-from-disk recovery: rebuild a node's serving state from its
persistent store (ISSUE 12).

One function — ``recover_node_state`` — is the whole boot-from-datadir
path, shared by the production client builder and the chaos harness's
``restart_node(from_disk=True)``:

1. open the stores (the WAL replay inside ``LevelStore.__init__`` has
   already truncated any torn tail and discarded any stale ``.compact``);
2. build the chain on its genesis anchor, then adopt the persisted
   fork-choice snapshot (head, attestation weight, finalized checkpoint)
   and rehydrate the unfinalized blocks it references from the store —
   the node restarts AT its last persisted head instead of range-syncing
   from genesis;
3. rehydrate the operation pool.

Every recovery emits a report (records replayed, torn bytes truncated,
fork-choice nodes restored, wall clock) onto the ``resilience_recovery_*``
metric families and into a module aggregate the bench integrity stamp
reads — a run that silently recovered mid-measurement is visible in the
record.
"""

from __future__ import annotations

import threading
import time

from ..utils.logging import get_logger
from ..utils.metrics import (
    RESILIENCE_RECOVERIES,
    RESILIENCE_RECOVERY_REPLAYED,
    RESILIENCE_RECOVERY_TIMES,
    RESILIENCE_RECOVERY_TRUNCATED,
)

log = get_logger("beacon_chain.recovery")

_TOTALS_LOCK = threading.Lock()
_TOTALS = {
    "recoveries": 0,
    "replayed_records": 0,
    "truncated_bytes": 0,
    "stale_compact_removed": 0,
}


def snapshot_recovery_totals() -> dict:
    """Process-wide recovery aggregate (the bench stamp's view)."""
    with _TOTALS_LOCK:
        return dict(_TOTALS)


def _store_replay_stats(store) -> dict:
    """Sum the WAL replay stats over the hot + cold backends (MemoryStore
    backends contribute zeros — they have no replay)."""
    out = {
        "replayed_frames": 0,
        "replayed_records": 0,
        "truncated_bytes": 0,
        "stale_compact_removed": 0,
    }
    for kv in (store.hot, store.cold):
        stats = getattr(kv, "recovery_stats", None)
        if not stats:
            continue
        for k in out:
            out[k] += int(stats.get(k, 0))
    return out


def recover_node_state(
    spec,
    anchor_state,
    store,
    slot_clock=None,
    execution_layer=None,
):
    """Rebuild ``(chain, op_pool, report)`` from ``store``.

    ``anchor_state`` is the same genesis/checkpoint anchor the node
    originally booted from (the interop genesis is deterministic, so the
    harness and the client both re-derive it). The persisted fork-choice
    snapshot is only adopted when it belongs to this anchor's chain; a
    missing/foreign/corrupt snapshot falls back to a fresh anchor boot —
    recovery degrades, it never refuses to start.
    """
    from .chain import BeaconChain
    from ..fork_choice import persistence as fc_persist
    from ..op_pool import OperationPool
    from ..op_pool import persistence as pool_persist

    t0 = time.perf_counter()
    chain = BeaconChain(
        spec,
        anchor_state,
        store=store,
        slot_clock=slot_clock,
        execution_layer=execution_layer,
    )
    report: dict = {"fork_choice_restored": False, "fc_nodes": 0,
                    "pool_restored": 0}
    report.update(_store_replay_stats(store))

    blob = store.get_meta(fc_persist.META_KEY)
    if blob:
        fresh_fc = chain.fork_choice
        try:
            restored = fc_persist.restore_fork_choice(spec, blob)
            if chain.genesis_block_root in restored.proto.indices:
                # rehydrate the unfinalized blocks the restored graph
                # references — imports, production and serving all key off
                # the chain's block/seen maps
                for node in restored.proto.nodes:
                    raw = store.get_block(node.root)
                    if raw is not None:
                        fork = spec.fork_name_at_slot(node.slot)
                        chain._blocks[node.root] = chain.ns.block_types[
                            fork
                        ].decode(raw)
                    chain._seen_blocks.add(node.root)
                # rehydrate their post-states too: the finalization
                # migrator iterates the in-memory state map, so a state
                # left only in the hot DB would never be frozen into the
                # cold hierarchy nor pruned when finality passes it (a
                # permanent per-crash leak + replay gap). HOT reads only —
                # a state already frozen to cold is already migrated, and
                # the cold fallback's block-replay reconstruction is far
                # too expensive to run per node on the recovery path
                from ..store.kv import DBColumn

                for node in restored.proto.nodes:
                    if (
                        node.root == chain.genesis_block_root
                        or node.root in chain._states
                    ):
                        continue
                    signed = chain._blocks.get(node.root)
                    if signed is None:
                        continue
                    ssz = store.hot.get(
                        DBColumn.BeaconState,
                        bytes(signed.message.state_root),
                    )
                    if ssz is None:
                        continue  # already frozen/pruned: nothing leaks
                    cls = chain.ns.state_types[
                        spec.fork_name_at_slot(int(signed.message.slot))
                    ]
                    try:
                        chain._states[node.root] = cls.decode(ssz)
                    except Exception:  # noqa: BLE001 — foreign bytes:
                        continue  # leave it to the on-demand loader
                chain.fork_choice = restored
                # finality is already migrated below this watermark: the
                # restarted migrator must not re-walk it from slot 0
                fin_epoch, _fin_root = restored.store.finalized_checkpoint
                chain.migrator.last_finalized_slot = spec.start_slot(
                    int(fin_epoch)
                )
                chain.recompute_head()
                report["fork_choice_restored"] = True
                report["fc_nodes"] = len(restored.proto.nodes)
            else:
                chain.fork_choice = fresh_fc
                log.warning(
                    "Fork choice snapshot is foreign to this anchor "
                    "(different genesis?); recovering as a fresh boot"
                )
        except Exception as e:  # noqa: BLE001 — stale/foreign snapshot
            chain.fork_choice = fresh_fc
            log.warning("Fork choice restore failed", error=str(e))
    # validators that activated since genesis live in the head state
    chain.pubkey_cache.import_new_pubkeys(chain.head.state)

    op_pool = OperationPool(spec, chain.ns.Attestation)
    blob = store.get_meta(pool_persist.META_KEY)
    if blob:
        try:
            report["pool_restored"] = pool_persist.restore_pool(
                op_pool, chain.ns, blob
            )
        except Exception as e:  # noqa: BLE001 — stale snapshot
            log.warning("Op pool restore failed", error=str(e))

    report["head_slot"] = int(chain.head.slot)
    report["head_root"] = bytes(chain.head.root)
    report["finalized_epoch"] = int(
        chain.fork_choice.store.finalized_checkpoint[0]
    )
    report["recovery_s"] = time.perf_counter() - t0

    RESILIENCE_RECOVERIES.inc()
    RESILIENCE_RECOVERY_REPLAYED.inc(report["replayed_records"])
    RESILIENCE_RECOVERY_TRUNCATED.inc(report["truncated_bytes"])
    RESILIENCE_RECOVERY_TIMES.observe(report["recovery_s"])
    with _TOTALS_LOCK:
        _TOTALS["recoveries"] += 1
        _TOTALS["replayed_records"] += report["replayed_records"]
        _TOTALS["truncated_bytes"] += report["truncated_bytes"]
        _TOTALS["stale_compact_removed"] += report["stale_compact_removed"]
    log.info(
        "Recovered from disk",
        head_slot=report["head_slot"],
        finalized_epoch=report["finalized_epoch"],
        replayed=report["replayed_records"],
        truncated_bytes=report["truncated_bytes"],
        fc_nodes=report["fc_nodes"],
        seconds=round(report["recovery_s"], 3),
    )
    return chain, op_pool, report
