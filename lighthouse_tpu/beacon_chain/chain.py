"""The BeaconChain orchestrator.

Twin of ``/root/reference/beacon_node/beacon_chain/src/beacon_chain.rs``:
``process_block`` (:3289) with the typestate pipeline collapsed into explicit
stages (gossip checks → batched signature verification → state transition →
``import_block`` (:3717) store writes + fork-choice update), attestation
verification with the batch path (``attestation_verification/batch.rs``),
head tracking (``canonical_head.rs:474``), and block production
(``produce_block_with_verification``, :4553).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import bls
from ..fork_choice import ForkChoice
from ..fork_choice.proto_array import ExecutionStatus
from ..state_transition import (
    BlockSignatureStrategy,
    get_beacon_proposer_index,
    get_current_epoch,
    get_indexed_attestation,
    per_block_processing,
    process_slots,
)
from ..state_transition.per_block import BlockProcessingError, ConsensusContext
from ..store import HotColdDB
from ..types.containers import for_preset
from ..types.spec import ChainSpec
from ..utils.logging import get_logger
from ..utils.metrics import (
    ATTESTATION_BATCH_SETUP_TIMES,
    ATTESTATION_BATCH_VERIFY_TIMES,
    BLOCK_PROCESSING_TIMES,
    FORK_CHOICE_GET_HEAD_TIMES,
)
from ..utils.slot_clock import ManualSlotClock, SlotClock
from .pubkey_cache import ValidatorPubkeyCache

log = get_logger("beacon_chain")


class BlockError(Exception):
    pass


class BlockPendingAvailability(BlockError):
    """Deneb block parked until its blob sidecars arrive
    (AvailabilityProcessingStatus::MissingComponents)."""

    def __init__(self, block_root: bytes):
        super().__init__(f"pending blob availability: {block_root.hex()[:16]}")
        self.block_root = block_root


class AttestationError(Exception):
    pass


@dataclass
class ChainHead:
    root: bytes
    slot: int
    state: object


class BeaconChain:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_state,
        store: HotColdDB | None = None,
        slot_clock: SlotClock | None = None,
        execution_layer=None,
        kzg=None,
    ):
        self.spec = spec
        self.ns = for_preset(spec.preset.name)
        self.store = store or HotColdDB()
        # fork choice persists on EVERY import only when the hot store is
        # durable (a WAL store with replay): persisting per-import into a
        # MemoryStore buys nothing — it dies with the process — and the
        # serialize cost is real on import-heavy paths. Shutdown persist
        # (Client.stop) stays unconditional.
        self._durable = hasattr(self.store.hot, "recovery_stats")
        self.slot_clock = slot_clock or ManualSlotClock(0)
        self.execution_layer = execution_layer
        self.eth1_service = None  # optional deposit/eth1-data bridge (eth1/)
        from ..op_pool.sync_aggregation import SyncContributionPool

        self.sync_contribution_pool = SyncContributionPool(
            spec.preset.SYNC_COMMITTEE_SIZE
        )
        from ..op_pool.naive_aggregation import NaiveAggregationPool

        self.naive_aggregation_pool = NaiveAggregationPool(self.ns.Attestation)
        from .data_availability import DataAvailabilityChecker

        self.da_checker = DataAvailabilityChecker(
            spec, kzg=kzg, is_known=lambda root: root in self._seen_blocks
        )
        # PeerDAS (ISSUE 16): the column cache is created HERE — not lazily
        # by the network service — so every mutation happens under
        # ``self.lock`` via ``put_data_column`` and the cache is pruned with
        # the availability horizon instead of growing without bound.
        self.data_column_cache: dict[bytes, dict[int, object]] = {}
        self.cell_context = None  # CellContext when column sampling enabled
        self.peerdas = None       # PeerDasSampler when enabled
        self.pubkey_cache = ValidatorPubkeyCache()
        self.pubkey_cache.import_new_pubkeys(genesis_state)
        # attester/shuffling cache tier (firehose/attester_cache.py): gossip
        # committee resolution without cloning or slot-advancing full states
        from ..firehose.attester_cache import AttesterCacheTier

        self.attester_cache = AttesterCacheTier(
            spec,
            genesis_validators_root=bytes(genesis_state.genesis_validators_root),
            ancestor_at_slot=self._known_ancestor_at_slot,
            state_fallback=self._state_for_committee,
        )
        # firehose hot path prunes the naive pool at most once per slot
        self._naive_pool_pruned_slot = -1
        # early-attester cache (early_attester_cache.rs): attestation data
        # for the current head served without a state read; primed on every
        # head update under the chain lock
        from .early_attester_cache import EarlyAttesterCache

        self.early_attester_cache = EarlyAttesterCache()
        # sharded serving tier (firehose/sharding.py over bls/mesh.py):
        # resolved lazily on the first batch verify — None when
        # LIGHTHOUSE_MESH_DEVICES leaves the mesh off (the single-device
        # path, bit-identical to the pre-mesh engine). Creation is guarded
        # by its own small lock (gossip + HTTP threads race the first
        # verify); dispatches never hold it
        import threading as _threading

        self._mesh_planner_state = "unset"
        self._mesh_planner_lock = _threading.Lock()

        # genesis anchor: the canonical block root needs the header's
        # state_root filled (it is zero until the next process_slot)
        hdr = genesis_state.latest_block_header.copy()
        if bytes(hdr.state_root) == b"\x00" * 32:
            hdr.state_root = genesis_state.tree_root()
        genesis_root = hdr.tree_root()
        self.genesis_state = genesis_state
        self.genesis_block_root = genesis_root
        # anchor checkpoint: epoch 0 at genesis, the state's epoch when
        # booting from a checkpoint-sync state (get_forkchoice_store)
        jc = (spec.compute_epoch_at_slot(int(genesis_state.slot)), genesis_root)
        self.fork_choice = ForkChoice.from_anchor(
            spec,
            genesis_root,
            genesis_state.slot,
            jc,
            jc,
            np.asarray(genesis_state.balances, dtype=np.uint64),
        )
        # Serializes chain mutation across whatever threads drive this chain
        # (HTTP handlers, network router, simulator loops). The reference
        # reaches the same guarantee through canonical_head's documented
        # lock ordering (canonical_head.rs module docs).
        import threading

        self.lock = threading.RLock()
        from ..store.migrate import BackgroundMigrator

        self.migrator = BackgroundMigrator(self.store)
        self.store.state_cls_for_slot = lambda slot: self.ns.state_types[
            spec.fork_name_at_slot(slot)
        ]
        self._states: dict[bytes, object] = {genesis_root: genesis_state}
        self._blocks: dict[bytes, object] = {}
        # bounded FIFO of store-decoded frozen blocks (get_signed_block)
        self._cold_block_cache: dict[bytes, object] = {}
        self._COLD_BLOCK_CACHE_MAX = 512
        self.head = ChainHead(
            root=genesis_root, slot=genesis_state.slot, state=genesis_state
        )
        # Device epoch engine (lighthouse_tpu/epoch_engine): when the
        # backend seam selects the device path, bind the anchor state's
        # registry mirror up front so the chain's first epoch boundary is a
        # journal-delta sync, not a full Python-object gather. process_slots
        # reaches the engine through the process_epoch seam on every
        # subsequent boundary.
        from .. import epoch_engine

        self.epoch_engine = epoch_engine
        if epoch_engine.device_backend_active():
            try:
                epoch_engine.prepare_state(genesis_state)
            except Exception as e:  # noqa: BLE001 — engine warm-up best-effort
                log.warning("epoch engine warm-up failed", error=str(e))
        self._seen_blocks: set[bytes] = {genesis_root}
        # backfill anchor (historical_blocks.rs): the oldest canonical block
        # we hold; checkpoint-synced chains fill backwards from here
        self._oldest_block_slot = int(genesis_state.slot)
        self._oldest_block_parent = bytes(hdr.parent_root)
        # Ingest seams for auxiliary services (the reference's slasher
        # service subscribes to gossip/import events, service.rs): called
        # with (signed_block) after import / (indexed_attestation) after
        # successful gossip verification. Observer errors never fail the
        # hot path.
        self.block_observers: list = []
        self.attestation_observers: list = []
        # SSE event bus (beacon_chain/src/events.rs): subscribers are
        # per-connection queues; emission never blocks the hot path
        self.event_subscribers: list = []  # list[(topics, queue.Queue)]
        self._event_lock = threading.Lock()
        # Liveness tracking for doppelganger protection (the reference's
        # ObservedAttesters / ObservedBlockProducers caches feeding
        # /eth/v1/validator/liveness): epoch -> validator indices seen
        # attesting or proposing. Pruned to the last few epochs.
        self._observed_attesters: dict[int, set[int]] = {}
        self._observed_proposers: dict[int, set[int]] = {}
        # light-client server: bootstraps + latest optimistic/finality
        # updates from imported sync aggregates (light_client_server_cache.rs)
        from ..light_client import LightClientServerCache

        self.light_client_cache = LightClientServerCache(self)

    def _record_liveness(self, table: dict, epoch: int, indices) -> None:
        s = table.setdefault(epoch, set())
        s.update(int(i) for i in indices)
        for old in [e for e in table if e < epoch - 4]:
            del table[old]

    def validator_liveness(self, epoch: int, indices) -> list[bool]:
        """Was each validator index observed attesting or proposing in
        ``epoch``? (http_api liveness endpoint, consumed by the VC's
        doppelganger service.)"""
        seen = self._observed_attesters.get(epoch, set()) | (
            self._observed_proposers.get(epoch, set())
        )
        return [int(i) in seen for i in indices]

    def subscribe_events(self, topics) -> "object":
        import queue as _q

        q = _q.Queue(maxsize=256)
        with self._event_lock:
            self.event_subscribers.append((set(topics), q))
        return q

    def unsubscribe_events(self, q) -> None:
        with self._event_lock:
            self.event_subscribers = [
                (t, qq) for (t, qq) in self.event_subscribers if qq is not q
            ]

    def _emit_event(self, topic: str, payload_fn) -> None:
        """``payload_fn`` is called lazily — zero cost with no subscriber."""
        with self._event_lock:
            targets = [
                q for topics, q in self.event_subscribers if topic in topics
            ]
        if not targets:
            return
        payload = payload_fn()
        for q in targets:
            try:
                q.put_nowait((topic, payload))
            except Exception:
                pass  # slow consumer: drop (events are best-effort)

    def _notify_block_observers(self, signed_block) -> None:
        blk = signed_block.message
        self._record_liveness(
            self._observed_proposers,
            self.spec.compute_epoch_at_slot(int(blk.slot)),
            [int(blk.proposer_index)],
        )
        for obs in self.block_observers:
            try:
                obs(signed_block)
            except Exception:
                pass
        self._emit_event(
            "block",
            lambda: {
                "slot": str(int(blk.slot)),
                "block": "0x" + type(blk).hash_tree_root(blk).hex(),
            },
        )

    def _notify_attestation_observers(self, indexed) -> None:
        self._record_liveness(
            self._observed_attesters,
            int(indexed.data.target.epoch),
            indexed.attesting_indices,
        )
        for obs in self.attestation_observers:
            try:
                obs(indexed)
            except Exception:
                pass

    # -- time --------------------------------------------------------------------

    def current_slot(self) -> int:
        return self.slot_clock.now() or 0

    def state_by_root(self, block_root: bytes):
        """Post-state of an imported block, or None (public accessor for the
        API layer; insulates callers from the chain's state-cache layout).
        Falls back to the store for states migrated out of memory."""
        state = self._states.get(block_root)
        if state is not None:
            return state
        if block_root == self.genesis_block_root:
            return self.genesis_state
        return self._load_state_from_store(block_root)

    def _load_state_from_store(self, block_root: bytes):
        """Reload a frozen/persisted state by block root (hot bytes, else
        the cold hierarchy; replay-layer slots reconstruct the nearest
        stored anchor and replay stored canonical blocks)."""
        signed = self.get_signed_block(block_root)
        if signed is None:
            return None
        state_root = bytes(signed.message.state_root)
        from ..store.kv import DBColumn

        ssz = self.store.hot.get(DBColumn.BeaconState, state_root)
        if ssz is not None:
            cls = self.ns.state_types[
                self.spec.fork_name_at_slot(int(signed.message.slot))
            ]
            try:
                return cls.decode(ssz)
            except Exception:
                return None
        # cold path: typed reconstruction directly (no bytes round-trip),
        # else nearest stored anchor + canonical block replay
        slot = self.store.cold_slot_for_root(state_root)
        if slot is None:
            return None
        state = self.store.get_cold_state(slot)
        if state is not None:
            return state
        anchor = self.store.replay_anchor(slot)
        base = self.store.get_cold_state(anchor)
        if base is None:
            return None
        from ..state_transition.block_replayer import BlockReplayer

        blocks = []
        for s in range(anchor + 1, slot + 1):
            summary = self.store.cold_summary_at_slot(s)
            if summary is None:
                continue
            raw_b = self.store.get_block(summary[1])
            if raw_b is None:
                continue
            fork = self.spec.fork_name_at_slot(s)
            blocks.append(self.ns.block_types[fork].decode(raw_b))
        return (
            BlockReplayer(self.spec, base).apply_blocks(blocks, slot).state
        )

    # -- block import pipeline -----------------------------------------------------

    def get_state_for_block(self, parent_root: bytes, slot: int):
        parent_state = self._states.get(parent_root)
        if parent_state is None and parent_root in self._seen_blocks:
            # restart path: a known block whose state lives only in the
            # store (e.g. the restored head) — load and re-cache it
            try:
                parent_state = self.state_by_root(parent_root)
                if parent_state is not None:
                    self._states[parent_root] = parent_state
            except Exception:  # noqa: BLE001 — treated as unknown below
                parent_state = None
        if parent_state is None:
            raise BlockError(f"unknown parent {parent_root.hex()[:16]}")
        state = parent_state.copy()
        if state.slot < slot:
            process_slots(self.spec, state, slot)
        return state

    def process_block(self, signed_block, is_first_block_in_slot: bool = True) -> bytes:
        """Full import: signature batch verify + state transition + store +
        fork choice. Returns the block root."""
        block = signed_block.message
        block_root = type(block).hash_tree_root(block)
        with self.lock, BLOCK_PROCESSING_TIMES.time():
            root = self._process_block_locked(
                signed_block, block, block_root, is_first_block_in_slot
            )
        log.debug(
            "Block imported", slot=int(block.slot), root=block_root.hex()[:16]
        )
        return root

    def _process_block_locked(
        self,
        signed_block,
        block,
        block_root,
        is_first_block_in_slot,
        check_availability: bool = True,
    ) -> bytes:
        if block_root in self._seen_blocks:
            return block_root
        if block.slot > self.current_slot():
            raise BlockError("block from the future")
        if check_availability and self.da_checker._required(signed_block):
            res = self.da_checker.put_block(block_root, signed_block)
            if res is None:
                raise BlockPendingAvailability(block_root)

        state = self.get_state_for_block(bytes(block.parent_root), block.slot)
        ctxt = ConsensusContext()
        ctxt.get_pubkey_index = self.pubkey_cache.get_index
        try:
            ctxt = per_block_processing(
                self.spec,
                state,
                signed_block,
                strategy=BlockSignatureStrategy.VERIFY_BULK,
                ctxt=ctxt,
                get_pubkey=self.pubkey_cache.get,
            )
        except (BlockProcessingError, bls.BlsError) as e:
            raise BlockError(str(e)) from None
        execution_status = self._notify_execution_layer(signed_block)
        self._import_block(
            signed_block, block_root, state, ctxt,
            is_first_block_in_slot=is_first_block_in_slot,
            execution_status=execution_status,
        )
        self._notify_block_observers(signed_block)
        return block_root

    def process_gossip_blob(self, sidecar) -> bytes | None:
        """Verify a gossiped BlobSidecar and, if it completes a parked
        block's blob set, import that block. Returns the imported block
        root, or None while components are still missing
        (process_gossip_blob -> process_availability in the reference)."""
        from ..state_transition.signature_sets import _header_signature_ok
        from ..types.containers import BeaconBlockHeader

        ns = self.ns
        self.da_checker.verify_blob_sidecar(ns, sidecar)
        hdr = sidecar.signed_block_header
        proposer_pk = self.pubkey_cache.get(int(hdr.message.proposer_index))
        if proposer_pk is None or not _header_signature_ok(
            self.spec, self.head.state, hdr, proposer_pk
        ):
            from .data_availability import BlobError

            raise BlobError("invalid blob header signature")
        res = self.da_checker.put_blob(sidecar)
        if res is None:
            return None
        blk, blobs = res
        root = BeaconBlockHeader.hash_tree_root(hdr.message)
        with self.lock:
            imported = self._process_block_locked(
                blk, blk.message, root, True, check_availability=False
            )
        # persist the sidecars beside the block (the reference's blobs DB) —
        # serves /eth/v1/beacon/blob_sidecars and BlobsByRoot RPC
        if imported is not None and blobs:
            self.store.put_blob_sidecars(
                root, [type(sc).encode(sc) for sc in blobs]
            )
        return imported

    # -- PeerDAS columns ----------------------------------------------------

    def enable_peerdas(self, cell_ctx, node_id: bytes,
                       custody_count: int | None = None,
                       samples_per_slot: int | None = None):
        """Turn on column sampling: availability for blob-carrying blocks is
        then decided by the sampler's custody + sampled column set instead
        of per-blob sidecar arrival (see ``peerdas.PeerDasSampler``)."""
        from .peerdas import PeerDasSampler

        kwargs = {}
        if custody_count is not None:
            kwargs["custody_count"] = custody_count
        if samples_per_slot is not None:
            kwargs["samples_per_slot"] = samples_per_slot
        self.cell_context = cell_ctx
        self.peerdas = PeerDasSampler(self, cell_ctx, node_id, **kwargs)
        self.da_checker.set_column_gate(self.peerdas.is_available)
        return self.peerdas

    def put_data_column(self, sidecar) -> bytes:
        """Retain a VERIFIED column sidecar, keyed by block root. All
        mutation happens under the chain lock; the cache is LRU-bounded to
        the availability checker's pending window and entries at or below
        the finalized horizon are dropped."""
        root = sidecar.signed_block_header.message.tree_root()
        with self.lock:
            cache = self.data_column_cache
            cols = cache.pop(root, None) or {}
            cols[int(sidecar.index)] = sidecar
            cache[root] = cols
            fin_slot = self.spec.start_slot(
                int(self.fork_choice.store.finalized_checkpoint[0])
            )
            for r in [
                r for r, cs in cache.items()
                if r != root and cs and all(
                    int(s.signed_block_header.message.slot) <= fin_slot
                    for s in cs.values()
                )
            ]:
                del cache[r]
            while len(cache) > self.da_checker.MAX_PENDING:
                cache.pop(next(iter(cache)))
        return root

    def data_columns_for(self, block_root: bytes) -> dict:
        """Snapshot of the held columns for one block (index -> sidecar)."""
        with self.lock:
            return dict(self.data_column_cache.get(block_root, {}))

    def _notify_execution_layer(self, signed_block):
        """engine_newPayload for merge-era blocks; maps the EL verdict onto
        fork choice's optimistic-sync statuses (block_verification.rs
        ExecutionPendingBlock -> payload_verification_status)."""
        from ..state_transition.per_block import payload_is_default

        payload = getattr(signed_block.message.body, "execution_payload", None)
        if payload is None or payload_is_default(payload):
            # pre-merge block (or pre-bellatrix fork): nothing to verify
            return ExecutionStatus.IRRELEVANT
        if self.execution_layer is None:
            return ExecutionStatus.OPTIMISTIC
        from ..execution_layer import PayloadStatus

        st = self.execution_layer.notify_new_payload(payload)
        if st.status == PayloadStatus.VALID:
            return ExecutionStatus.VALID
        if st.status in (PayloadStatus.SYNCING, PayloadStatus.ACCEPTED):
            return ExecutionStatus.OPTIMISTIC
        raise BlockError(f"execution payload invalid: {st.validation_error}")

    def process_chain_segment(self, blocks, blobs_by_root=None) -> list:
        """Batch-verify ALL signatures of a segment in one bls call, then
        apply blocks with NoVerification (signature_verify_chain_segment,
        block_verification.rs:590-636).

        ``blobs_by_root``: {block_root: [BlobSidecar]} for deneb segments —
        range sync couples blob downloads with block downloads (the
        reference's block_sidecar_coupling.rs); a block whose commitments
        have no matching verified sidecars here fails availability."""
        roots = []
        if not blocks:
            return roots
        with self.lock:
            return self._process_chain_segment_locked(
                blocks, roots, blobs_by_root or {}
            )

    @property
    def oldest_block_slot(self) -> int:
        """Slot of the oldest canonical block held (backfill progress)."""
        return self._oldest_block_slot

    @property
    def backfill_complete(self) -> bool:
        return (
            self._oldest_block_slot <= 1
            or self._oldest_block_parent == b"\x00" * 32
        )

    @property
    def anchor_block_missing(self) -> bool:
        """Checkpoint boot holds the anchor's header (inside the state) but
        not the anchor block itself; it must be fetched by root before the
        chain can serve a gap-free history."""
        return (
            self.genesis_block_root not in self._blocks
            and self._oldest_block_slot > 0
        )

    def get_signed_block(self, block_root: bytes):
        """Decoded SignedBeaconBlock by root: the in-memory hot map first,
        else the persistent store. The finalization migration drops the
        decoded copies of frozen canonical blocks from ``_blocks`` (bounding
        memory), which used to truncate ``blocks_by_range`` serving at the
        finalized horizon — a from-genesis peer could then NEVER range-sync
        past our finalized epoch (every served segment started with an
        unknown parent). Req/Resp serving must read through to the store.

        Store-decoded blocks are kept in a small bounded FIFO cache: a
        range-sync serving a long history walks the same frozen parents
        once per BlocksByRange request, and re-decoding them per request
        would make segment serving quadratic in chain length. The cache is
        separate from ``_blocks`` so the finalization migration's memory
        bound still holds."""
        sb = self._blocks.get(block_root)
        if sb is not None:
            return sb
        sb = self._cold_block_cache.get(block_root)
        if sb is not None:
            return sb
        raw = self.store.get_block(block_root)
        if raw is None:
            return None
        for fork in reversed(list(self.ns.block_types)):
            try:
                sb = self.ns.block_types[fork].decode(raw)
            except Exception:  # noqa: BLE001 — wrong fork schema: keep trying
                continue
            while len(self._cold_block_cache) >= self._COLD_BLOCK_CACHE_MAX:
                self._cold_block_cache.pop(
                    next(iter(self._cold_block_cache))
                )
            self._cold_block_cache[block_root] = sb
            return sb
        return None

    def import_anchor_block(self, signed_block) -> None:
        """Accept the checkpoint anchor block itself. No signature check
        needed: its root is pinned by the trusted checkpoint state
        (checkpoint-sync block fetch, client/src/builder.rs)."""
        with self.lock:
            root = signed_block.message.tree_root()
            if root != self.genesis_block_root:
                raise BlockError("anchor block root mismatch")
            self._blocks[root] = signed_block
            self._seen_blocks.add(root)
            self.store.put_block(root, type(signed_block).encode(signed_block))

    def import_historical_blocks(self, blocks) -> int:
        """Backwards history fill below the anchor
        (``beacon_chain/src/historical_blocks.rs``): ``blocks`` are
        consecutive ascending-slot signed blocks whose LAST element must be
        the parent of our oldest known block. Linkage is checked as a
        parent-root hash chain and all proposer signatures are verified in
        ONE batch against the pubkey cache; no state transition is run —
        finality already covers these slots. Valid blocks become servable
        history and move the backfill anchor down."""
        if not blocks:
            return 0
        from ..types.helpers import compute_domain, compute_signing_root

        with self.lock:
            roots = [sb.message.tree_root() for sb in blocks]
            if roots[-1] != self._oldest_block_parent:
                raise BlockError(
                    "backfill segment does not link to the oldest known block"
                )
            for i in range(len(blocks) - 1):
                if bytes(blocks[i + 1].message.parent_root) != roots[i]:
                    raise BlockError("backfill segment is not a hash chain")
            state = self.head.state
            gvr = bytes(state.genesis_validators_root)
            items = []
            for sb in blocks:
                epoch = self.spec.compute_epoch_at_slot(int(sb.message.slot))
                # the full fork schedule, not state.fork: backfill spans
                # arbitrarily many forks below the anchor
                domain = compute_domain(
                    self.spec.DOMAIN_BEACON_PROPOSER,
                    self.spec.fork_version_at_epoch(epoch),
                    gvr,
                )
                items.append(
                    (
                        [int(sb.message.proposer_index)],
                        compute_signing_root(sb.message, domain),
                        bytes(sb.signature),
                    )
                )
            if not self._batch_verify_items(items):
                raise BlockError("backfill segment signatures invalid")
            # the segment was validated as a unit; persist it as ONE atomic
            # frame so a crash mid-backfill never leaves a gappy history
            from ..store.kv import DBColumn

            self.store.do_atomically(
                [
                    ("put", DBColumn.BeaconBlock, root, type(sb).encode(sb))
                    for sb, root in zip(blocks, roots)
                ]
            )
            for sb, root in zip(blocks, roots):
                self._blocks[root] = sb
                self._seen_blocks.add(root)
            self._oldest_block_slot = int(blocks[0].message.slot)
            self._oldest_block_parent = bytes(blocks[0].message.parent_root)
            return len(blocks)

    def _check_segment_availability(self, sb, block_root, blobs_by_root):
        """Deneb: segment blocks with commitments need their sidecars
        verified (KZG batch + inclusion proofs) before import. With PeerDAS
        enabled the gate is the column sampler instead: the block passes
        once every custody + sampled column has been verified — the sync
        manager couples the column fetch to the block download and retries
        (block_sidecar_coupling.rs), so pending availability here is a
        retriable condition, not a bad segment."""
        required = self.da_checker._required(sb)
        if required == 0:
            return
        if self.cell_context is not None and self.peerdas is not None:
            if self.peerdas.is_available(block_root):
                return
            raise BlockPendingAvailability(block_root)
        from .data_availability import BlobError

        sidecars = blobs_by_root.get(block_root)
        if sidecars is None or len(sidecars) < required:
            raise BlockPendingAvailability(block_root)
        self.da_checker.verify_blob_sidecar_batch(self.ns, sidecars)
        comms = sb.message.body.blob_kzg_commitments
        by_index = {int(sc.index): sc for sc in sidecars}
        for i in range(required):
            sc = by_index.get(i)
            if sc is None or bytes(sc.kzg_commitment) != bytes(comms[i]):
                raise BlobError(f"segment blob {i} missing or mismatched")

    # the looped write below is each block's blob sidecars AFTER that
    # block's atomic import: one single-key put per block, independent per
    # block (a crash between two blocks' sidecar writes tears nothing; a
    # missing sidecar set re-arrives via sync)
    # lint: allow(torn-write)
    def _process_chain_segment_locked(self, blocks, roots, blobs_by_root) -> list:
        from ..state_transition.per_block import BlockSignatureVerifier

        # deneb availability first: fail the segment before any expensive work
        for sb in blocks:
            block_root = type(sb.message).hash_tree_root(sb.message)
            self._check_segment_availability(sb, block_root, blobs_by_root)

        # thread ONE state through the segment: collect each block's signature
        # sets against its pre-state, apply the transition unverified, and
        # only import after the whole segment's batch verifies
        first = blocks[0].message
        state = self.get_state_for_block(bytes(first.parent_root), first.slot)
        all_sets = []
        prepared = []
        try:
            for sb in blocks:
                block = sb.message
                if state.slot < block.slot:
                    process_slots(self.spec, state, block.slot)
                v = BlockSignatureVerifier(self.spec, state, self.pubkey_cache.get)
                ctxt = ConsensusContext()
                ctxt.get_pubkey_index = self.pubkey_cache.get_index
                v.include_all_signatures(sb, ctxt)
                all_sets.extend(v.sets)
                per_block_processing(
                    self.spec, state, sb,
                    strategy=BlockSignatureStrategy.NO_VERIFICATION,
                    ctxt=ctxt,
                )
                prepared.append((sb, state.copy(), ctxt))
        except (BlockProcessingError, bls.BlsError) as e:
            raise BlockError(str(e)) from None
        if not bls.verify_signature_sets(all_sets):
            raise BlockError("chain segment signature verification failed")
        for sb, post_state, ctxt in prepared:
            block = sb.message
            root = type(block).hash_tree_root(block)
            self._import_block(
                sb, root, post_state, ctxt,
                execution_status=self._notify_execution_layer(sb),
            )
            # range-synced blocks carry slashing evidence too (the slasher
            # subscription must see every import path, not just gossip)
            self._notify_block_observers(sb)
            sidecars = blobs_by_root.get(root)
            if sidecars:
                self.store.put_blob_sidecars(
                    root, [type(sc).encode(sc) for sc in sidecars]
                )
            roots.append(root)
        return roots

    def _justified_balances(self, justified_root: bytes, fallback_state):
        """Effective balances of validators active at the justified epoch,
        zero otherwise (BeaconForkChoiceStore/JustifiedBalances parity)."""
        from ..types.helpers import is_active_validator

        state = self._states.get(justified_root, fallback_state)
        epoch = get_current_epoch(self.spec, state)
        return np.array(
            [
                v.effective_balance if is_active_validator(v, epoch) else 0
                for v in state.validators
            ],
            dtype=np.uint64,
        )

    def _import_block(
        self, signed_block, block_root, state, ctxt,
        is_first_block_in_slot: bool = True,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ) -> None:
        block = signed_block.message
        self.pubkey_cache.import_new_pubkeys(state)
        # the block-import persistence barrier: block + post-state + slot
        # summary as ONE atomic frame (a kill mid-import can never leave a
        # block whose post-state is missing, or vice versa)
        self.store.atomic_block_import(
            block_root,
            type(signed_block).encode(signed_block),
            state.tree_root(),
            type(state).encode(state),
            int(state.slot),
        )
        self._states[block_root] = state
        self._blocks[block_root] = signed_block
        self._seen_blocks.add(block_root)

        self.fork_choice.on_block(
            self.current_slot(),
            block,
            block_root,
            state,
            justified_balances=self._justified_balances(
                bytes(state.current_justified_checkpoint.root), state
            ),
            execution_status=execution_status,
            is_first_block_in_slot=is_first_block_in_slot,
        )
        # apply the block's attestations to fork choice (import_block does)
        for indexed in ctxt.indexed_attestations.values():
            try:
                self.fork_choice.on_attestation(
                    self.current_slot(), indexed, is_from_block=True
                )
            except Exception:
                pass
        self.recompute_head()
        # fork-choice persistence barrier (persisted_fork_choice.rs runs on
        # every import too): a crash after this point restarts at THIS head;
        # a crash between the block batch and here restarts one block back
        # and re-imports it from gossip/sync — never from genesis
        if self._durable:
            self.persist_fork_choice()

    def persist_fork_choice(self) -> None:
        """Snapshot fork choice into the store's metadata bucket (the
        restart-from-disk anchor). Runs under the chain lock on the import
        path; also the shutdown path's persistence hook."""
        from ..fork_choice import persistence as fc_persist

        fc_persist.persist(self.store, self.fork_choice)

    # -- attestations ---------------------------------------------------------------

    def _known_ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        """Fork-choice ancestor walk for the attester-cache decision key;
        None for blocks fork choice does not know (cache unusable)."""
        if root not in self.fork_choice.proto.indices:
            return None
        return self.fork_choice._ancestor_at_slot(root, slot)

    def _state_for_committee(self, block_root: bytes, slot: int):
        """Shuffling-cache miss path: a state of the attestation's chain
        advanced to its slot (the pre-cache full-state behavior)."""
        state = self._states.get(bytes(block_root))
        if state is None:
            return None
        if state.slot < slot:
            state = state.copy()
            process_slots(self.spec, state, slot)
        return state

    def _committee_and_indexed(self, att):
        """(committee, indexed attestation) with ONE committee lookup
        through the attester-cache tier — no state clone or slot advance on
        the hot path. Electra committee_bits attestations (multi-committee)
        take the full-state path."""
        if hasattr(att, "committee_bits"):
            state = self._attestation_state(att)
            from ..state_transition import get_beacon_committee

            committee = get_beacon_committee(
                self.spec, state, int(att.data.slot), int(att.data.index)
            )
            return committee, get_indexed_attestation(self.spec, state, att)
        committee = self.attester_cache.committee_for(att.data)
        if committee is None:
            raise AttestationError("unknown beacon block root")
        bits = np.asarray(att.aggregation_bits, dtype=bool)
        if bits.size != committee.size:
            raise AttestationError(
                "aggregation bits length != committee size"
            )
        indexed = self.ns.IndexedAttestation(
            attesting_indices=sorted(int(i) for i in committee[bits]),
            data=att.data,
            signature=att.signature,
        )
        return committee, indexed

    def _indexed_attestation_fast(self, att):
        return self._committee_and_indexed(att)[1]

    def _attester_item_fast(self, indexed):
        """(indices, signing root, signature bytes) from the cache tier's
        state-free domain (schedule fork version + genesis validators root
        — identical to get_domain for any on-schedule state)."""
        return (
            [int(i) for i in indexed.attesting_indices],
            self.attester_cache.signing_root(indexed.data),
            bytes(indexed.signature),
        )

    def _batch_verify_items(self, items) -> bool:
        """Verify (validator_indices, message, signature_bytes) triples as one
        RLC batch. On the tpu backend this is the fully-fused device path:
        cache gather + device h2c + device signature decompression, zero
        per-batch oracle-point conversion. Other backends go through the
        generic SignatureSet seam.

        Every backend call runs inside the ``bls_device`` fault domain
        (resilience.supervisor): watchdog deadline, bounded transient
        retries, and the degradation ladder full device shape -> halved
        batch shape -> pure-Python oracle. A batch whose every rung faults
        fails CLOSED (False -> bisection -> per-group rejection): work may
        be dropped and counted, but nothing is ever falsely verified."""
        if not items:
            return False
        from ..resilience import SupervisedFault

        with ATTESTATION_BATCH_VERIFY_TIMES.time():
            try:
                return self._batch_verify_items_inner(items)
            except SupervisedFault:
                return False  # every rung faulted (recorded): fail closed

    def _mesh_planner(self):
        """The sharded serving tier for this chain, or None when the mesh
        is off (``LIGHTHOUSE_MESH_DEVICES`` unset/1 — the single-device
        path stays bit-identical to the pre-mesh engine). Resolved once;
        the verifier itself holds no state, so it is shared by the batch
        paths and the firehose threads."""
        if self._mesh_planner_state == "unset":
            with self._mesh_planner_lock:
                if self._mesh_planner_state == "unset":
                    self._mesh_planner_state = self._build_mesh_planner()
        return self._mesh_planner_state

    def _build_mesh_planner(self):
        if bls.get_backend() != "tpu":
            return None
        from ..bls import mesh as bls_mesh

        n = bls_mesh.serving_mesh_size()
        if n <= 1:
            return None
        from ..bls import tpu_backend as tb
        from ..firehose.sharding import MeshVerifier

        backend = bls_mesh.make_mesh_backend(self.pubkey_cache.device_array)
        return MeshVerifier(
            n,
            dispatch_fn=backend.dispatch,
            stage_fn=backend.stage,
            probe_fn=backend.probe,
            single_fn=lambda its: tb.verify_indexed_sets_device(
                self.pubkey_cache.device_array(), its
            ),
            oracle_fn=lambda its: self._verify_items_via_sets(
                its, oracle=True
            ),
        )

    def _batch_verify_items_inner(self, items) -> bool:
        from ..resilience import bls_supervisor

        mesh = self._mesh_planner()
        if mesh is not None:
            # the mesh verifier carries its own fault-domain ladder
            # (mesh N -> N/2 -> single device -> CPU oracle) — wrapping it
            # in the bls_device supervisor too would double-wrap
            return mesh.verify_items(items)
        sup = bls_supervisor()
        if bls.get_backend() == "tpu":
            from ..bls import tpu_backend as tb

            cache = self.pubkey_cache.device_array()

            def full():
                return tb.verify_indexed_sets_device(cache, items)

            def reduced():
                # halved n-bucket: the OOM rung — everything still verifies,
                # in two smaller fixed-shape dispatches
                mid = (len(items) + 1) // 2
                if mid == len(items):
                    return tb.verify_indexed_sets_device(cache, items)
                return tb.verify_indexed_sets_device(
                    cache, items[:mid]
                ) and tb.verify_indexed_sets_device(cache, items[mid:])

            return sup.run_ladder(
                "bls.batch_verify",
                (
                    ("device_full", full),
                    ("device_reduced", reduced),
                    ("cpu_oracle", lambda: self._verify_items_via_sets(
                        items, oracle=True
                    )),
                ),
            )
        return sup.run_ladder(
            "bls.batch_verify",
            (
                ("primary", lambda: self._verify_items_via_sets(items)),
                ("cpu_oracle", lambda: self._verify_items_via_sets(
                    items, oracle=True
                )),
            ),
        )

    def _verify_items_via_sets(self, items, oracle: bool = False) -> bool:
        """The generic SignatureSet path for item triples; ``oracle=True``
        pins the pure-Python oracle (the ladder's device-free last rung)."""
        sets = []
        for indices, msg, sig_bytes in items:
            try:
                keys = [self.pubkey_cache.get(int(i)) for i in indices]
                if not keys or any(k is None for k in keys):
                    return False
                sets.append(
                    bls.SignatureSet.multiple_pubkeys(
                        bls.Signature.from_bytes(sig_bytes), keys, msg
                    )
                )
            except bls.BlsError:
                return False
        if oracle:
            return bls.verify_signature_sets_oracle(sets)
        return bls.verify_signature_sets(sets)

    def _attester_item(self, state, indexed):
        """(indices, signing root, signature bytes) for an indexed attestation."""
        from ..types.helpers import compute_signing_root, get_domain

        domain = get_domain(
            self.spec, state, self.spec.DOMAIN_BEACON_ATTESTER,
            epoch=indexed.data.target.epoch,
        )
        root = compute_signing_root(indexed.data, domain)
        return (
            [int(i) for i in indexed.attesting_indices],
            root,
            bytes(indexed.signature),
        )

    def verify_unaggregated_attestations(self, attestations) -> list:
        """Batch gossip verification: one signature set per attestation, one
        bls batch; a poisoned batch is isolated by bisection (split-and-retry,
        firehose/bisect.py) instead of n per-set re-verifies
        (batch_verify_unaggregated_attestations, batch.rs:133-211).
        Committee resolution rides the attester-cache tier — no state clone
        on the hot path. Returns list of (attestation, indexed | error)."""
        from ..firehose.bisect import bisect_verify

        prepared = []
        with ATTESTATION_BATCH_SETUP_TIMES.time():
            for att in attestations:
                try:
                    indexed = self._indexed_attestation_fast(att)
                    item = self._attester_item_fast(indexed)
                    prepared.append((att, indexed, item))
                except Exception as e:
                    prepared.append((att, AttestationError(str(e)), None))
        items = [p[2] for p in prepared if p[2] is not None]
        results = []
        if items and self._batch_verify_items(items):
            for att, indexed, _ in prepared:
                results.append((att, indexed))
        else:
            # poisoned batch: bisection isolates the bad set(s) in
            # O(bad * log n) batched calls with exact error fidelity
            verdicts = iter(
                bisect_verify(
                    [[item] for item in items],
                    self._batch_verify_items,
                    assume_failed=bool(items),
                )
            )
            for att, indexed, item in prepared:
                if item is None:
                    results.append((att, indexed))
                elif next(verdicts):
                    results.append((att, indexed))
                else:
                    results.append(
                        (att, AttestationError("invalid attestation signature"))
                    )
        with self.lock:
            for att, indexed in results:
                if not isinstance(indexed, Exception):
                    try:
                        self.fork_choice.on_attestation(
                            self.current_slot(), indexed
                        )
                    except Exception:
                        pass
                    self.naive_aggregation_pool.insert(att)
                    self._notify_attestation_observers(indexed)
            # prune under the same lock that serializes inserts — gossip
            # workers and HTTP threads call this path concurrently
            self.naive_aggregation_pool.prune(self.current_slot())
        return results

    def _prepare_aggregate(self, sap):
        """Signature-set group (selection proof, envelope, attester set) for
        one SignedAggregateAndProof via the attester-cache tier — raises
        AttestationError on any pre-crypto rejection."""
        from ..ssz import uint64 as ssz_u64
        from ..types.containers import SigningData
        from ..types.helpers import compute_signing_root

        agg = sap.message
        att = agg.aggregate
        committee, indexed = self._committee_and_indexed(att)
        aggor = int(agg.aggregator_index)
        if self.pubkey_cache.get(aggor) is None:
            raise AttestationError("unknown aggregator index")
        # spec is_aggregator: the selection proof must actually
        # select this validator for the committee (the signature
        # check alone lets ANY committee member aggregate)
        import hashlib as _hl

        if aggor not in [int(v) for v in committee]:
            raise AttestationError("aggregator not in committee")
        modulo = max(
            1,
            committee.size // self.spec.target_aggregators_per_committee,
        )
        digest = _hl.sha256(bytes(agg.selection_proof)).digest()
        if int.from_bytes(digest[0:8], "little") % modulo != 0:
            raise AttestationError("selection proof does not select")
        epoch = self.spec.compute_epoch_at_slot(att.data.slot)
        root_sel = SigningData(
            object_root=ssz_u64.hash_tree_root(att.data.slot),
            domain=self._domain_at(self.spec.DOMAIN_SELECTION_PROOF, epoch),
        ).tree_root()
        root_ap = compute_signing_root(
            agg, self._domain_at(self.spec.DOMAIN_AGGREGATE_AND_PROOF, epoch)
        )
        items = [
            ([aggor], root_sel, bytes(agg.selection_proof)),
            ([aggor], root_ap, bytes(sap.signature)),
            self._attester_item_fast(indexed),
        ]
        return indexed, items

    def _domain_at(self, domain_type: bytes, epoch: int) -> bytes:
        """State-free signing domain from the fork schedule + genesis
        validators root (equals get_domain for on-schedule states)."""
        from ..types.helpers import compute_domain

        return compute_domain(
            domain_type,
            self.spec.fork_version_at_epoch(int(epoch)),
            self.attester_cache.genesis_validators_root,
        )

    def verify_aggregated_attestations(self, signed_aggregates) -> list:
        """Gossip aggregate verification: THREE signature sets per
        SignedAggregateAndProof — selection proof, aggregate-and-proof
        envelope, and the indexed attestation — batched across aggregates;
        a poisoned batch bisects down to the bad aggregate group(s)
        (batch_verify_aggregated_attestations, batch.rs:28-113).
        Returns list of (signed_aggregate, indexed | error)."""
        from ..firehose.bisect import bisect_verify

        prepared = []
        for sap in signed_aggregates:
            try:
                indexed, items = self._prepare_aggregate(sap)
                prepared.append((sap, indexed, items))
            except Exception as e:
                prepared.append((sap, AttestationError(str(e)), None))
        groups = [its for _, _, its in prepared if its]
        all_items = [it for g in groups for it in g]
        results = []
        if all_items and self._batch_verify_items(all_items):
            for sap, indexed, _ in prepared:
                results.append((sap, indexed))
        else:
            verdicts = iter(
                bisect_verify(
                    groups, self._batch_verify_items,
                    assume_failed=bool(all_items),
                )
            )
            for sap, indexed, its in prepared:
                if its is None:
                    results.append((sap, indexed))
                elif next(verdicts):
                    results.append((sap, indexed))
                else:
                    results.append(
                        (sap, AttestationError("invalid aggregate signature"))
                    )
        with self.lock:
            for sap, indexed in results:
                if not isinstance(indexed, Exception):
                    try:
                        self.fork_choice.on_attestation(
                            self.current_slot(), indexed
                        )
                    except Exception:
                        pass
                    self._notify_attestation_observers(indexed)
        return results

    # -- firehose (streaming gossip verification) ---------------------------------

    def create_firehose(self, config=None, synchronous: bool = False):
        """Streaming verification engine for the gossip firehose: adaptive
        batching + double-buffered host/device pipeline + back-pressure,
        with the host stage wired to the attester-cache tier and the device
        stage to the batched BLS backend with bisection fallback
        (firehose/engine.py). Handles BOTH firehose-eligible payload kinds:
        unaggregated Attestations (one set) and SignedAggregateAndProofs
        (three sets); verdicts apply to fork choice / the naive pool
        exactly like the verify_* batch paths.

        Fault-domain note: the verify stage IS ``_batch_verify_items``,
        which already runs inside the ``bls_device`` supervisor (watchdog,
        retries, degradation ladder down to the pure-Python oracle) — the
        engine is deliberately built WITHOUT its own supervisor so device
        calls are never double-wrapped."""
        from ..firehose import FirehoseEngine

        def prepare(payloads):
            out = []
            for p in payloads:
                try:
                    if hasattr(p, "message"):  # SignedAggregateAndProof
                        indexed, items = self._prepare_aggregate(p)
                        out.append((items, indexed))
                    else:
                        indexed = self._indexed_attestation_fast(p)
                        out.append(
                            ([self._attester_item_fast(indexed)], indexed)
                        )
                except Exception as e:  # noqa: BLE001 — pre-crypto rejection
                    out.append(AttestationError(str(e)))
            return out

        engine = FirehoseEngine(
            prepare_fn=prepare,
            verify_items_fn=self._batch_verify_items,
            config=config,
            synchronous=synchronous,
            # sharded serving tier (None when the mesh is off): per-shard
            # sub-batches with prep-thread H2D staging, per-shard verdicts,
            # per-shard fault domains — aggregates stream through it as
            # atomic 3-set groups exactly like single-set attestations
            shard_planner=self._mesh_planner(),
        )
        engine.default_callback = self._apply_verified_attestation
        return engine

    def _apply_verified_attestation(self, payload, ok: bool, indexed) -> None:
        """Post-verdict application for firehose-verified gossip work (the
        tail of the verify_* batch paths). Unaggregated attestations also
        merge into the naive aggregation pool; the pool is pruned at most
        once per slot (not per item — the stream path is hot)."""
        if not ok or indexed is None:
            return
        with self.lock:
            try:
                self.fork_choice.on_attestation(self.current_slot(), indexed)
            except Exception:
                pass
            if not hasattr(payload, "message"):  # unaggregated Attestation
                self.naive_aggregation_pool.insert(payload)
            self._notify_attestation_observers(indexed)
            slot = self.current_slot()
            if slot != self._naive_pool_pruned_slot:
                self._naive_pool_pruned_slot = slot
                self.naive_aggregation_pool.prune(slot)

    # -- sync committee messages (sync_committee_verification.rs) ----------

    def _sync_signing_root(self, state, slot: int, beacon_block_root: bytes):
        from ..types.helpers import sync_committee_signing_root

        return sync_committee_signing_root(
            self.spec, state, slot, beacon_block_root
        )

    def sync_committee_positions(self, state, validator_index: int) -> list[int]:
        if not 0 <= int(validator_index) < len(state.validators):
            return []
        pk = bytes(state.validators[int(validator_index)].pubkey)
        return [
            i
            for i, cpk in enumerate(state.current_sync_committee.pubkeys)
            if bytes(cpk) == pk
        ]

    def verify_sync_committee_messages(self, messages) -> list:
        """Batch gossip verification of SyncCommitteeMessages; on success the
        message is merged into the sync contribution pool. Returns
        (message, committee_positions | error) pairs
        (verify_sync_committee_message_for_gossip + the naive pool insert)."""
        state = self.head.state
        prepared = []
        for msg in messages:
            try:
                positions = self.sync_committee_positions(
                    state, int(msg.validator_index)
                )
                if not positions:
                    raise AttestationError("not in current sync committee")
                root = self._sync_signing_root(
                    state, int(msg.slot), bytes(msg.beacon_block_root)
                )
                item = ([int(msg.validator_index)], root, bytes(msg.signature))
                prepared.append((msg, positions, item))
            except AttestationError as e:
                prepared.append((msg, e, None))
        items = [p[2] for p in prepared if p[2] is not None]
        results = []
        if items and self._batch_verify_items(items):
            for msg, positions, _ in prepared:
                results.append((msg, positions))
        else:
            for msg, positions, item in prepared:
                if item is None:
                    results.append((msg, positions))
                elif self._batch_verify_items([item]):
                    results.append((msg, positions))
                else:
                    results.append(
                        (msg, AttestationError("invalid sync signature"))
                    )
        for msg, verdict in results:
            if not isinstance(verdict, Exception):
                self.sync_contribution_pool.insert_message(
                    int(msg.slot), bytes(msg.beacon_block_root), verdict,
                    bytes(msg.signature),
                )
        return results

    def verify_sync_contributions(self, signed_contributions) -> list:
        """Gossip verification of SignedContributionAndProofs — THREE sets
        each (selection proof, contribution-and-proof envelope, and the
        subcommittee aggregate), batched with per-item fallback
        (sync_committee_verification.rs contribution path). Verified
        contributions merge into the sync contribution pool."""
        from ..types.helpers import compute_signing_root, get_domain

        state = self.head.state
        sub_size = self.spec.preset.SYNC_COMMITTEE_SIZE // 4
        prepared = []
        for sc in signed_contributions:
            try:
                cp = sc.message
                contribution = cp.contribution
                aggor = int(cp.aggregator_index)
                if self.pubkey_cache.get(aggor) is None:
                    raise AttestationError("unknown aggregator index")
                sub = int(contribution.subcommittee_index)
                if sub >= 4:
                    raise AttestationError("subcommittee index out of range")
                epoch = self.spec.compute_epoch_at_slot(int(contribution.slot))
                sel_data = self.ns.SyncAggregatorSelectionData(
                    slot=int(contribution.slot), subcommittee_index=sub
                )
                dom_sel = get_domain(
                    self.spec, state,
                    self.spec.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
                    epoch=epoch,
                )
                root_sel = compute_signing_root(sel_data, dom_sel)
                dom_cp = get_domain(
                    self.spec, state, self.spec.DOMAIN_CONTRIBUTION_AND_PROOF,
                    epoch=epoch,
                )
                root_cp = compute_signing_root(cp, dom_cp)
                # participants: committee seats in this subcommittee at the
                # set bits, resolved to validator indices via the pubkey cache
                bits = np.asarray(contribution.aggregation_bits, dtype=bool)
                indices = []
                for pos, bit in enumerate(bits):
                    if not bit:
                        continue
                    pk = bytes(
                        state.current_sync_committee.pubkeys[
                            sub * sub_size + pos
                        ]
                    )
                    idx = self.pubkey_cache.get_index(pk)
                    if idx is None:
                        raise AttestationError("unknown committee pubkey")
                    indices.append(idx)
                if not indices:
                    raise AttestationError("empty contribution")
                root_msg = self._sync_signing_root(
                    state, int(contribution.slot),
                    bytes(contribution.beacon_block_root),
                )
                items = [
                    ([aggor], root_sel, bytes(cp.selection_proof)),
                    ([aggor], root_cp, bytes(sc.signature)),
                    (indices, root_msg, bytes(contribution.signature)),
                ]
                prepared.append((sc, items))
            except AttestationError as e:
                prepared.append((sc, e))
        all_items = [it for _, its in prepared if not isinstance(its, Exception) for it in its]
        results = []
        if all_items and self._batch_verify_items(all_items):
            for sc, its in prepared:
                results.append(
                    (sc, its if isinstance(its, Exception) else True)
                )
        else:
            for sc, its in prepared:
                if isinstance(its, Exception):
                    results.append((sc, its))
                elif self._batch_verify_items(its):
                    results.append((sc, True))
                else:
                    results.append(
                        (sc, AttestationError("invalid contribution signature"))
                    )
        for sc, verdict in results:
            if not isinstance(verdict, Exception):
                self.sync_contribution_pool.insert_contribution(
                    sc.message.contribution
                )
        return results

    def _attestation_state(self, att):
        root = bytes(att.data.beacon_block_root)
        state = self._states.get(root)
        if state is None:
            raise AttestationError("unknown beacon block root")
        if state.slot < att.data.slot:
            state = state.copy()
            process_slots(self.spec, state, att.data.slot)
        return state

    # -- head ------------------------------------------------------------------------

    def recompute_head(self) -> bytes:
        with self.lock:
            return self._recompute_head_locked()

    def _recompute_head_locked(self) -> bytes:
        with FORK_CHOICE_GET_HEAD_TIMES.time():
            head_root = self.fork_choice.get_head(self.current_slot())
        self.sync_contribution_pool.prune(self.current_slot())
        self._maybe_migrate()
        if head_root != self.head.root:
            state = self._states.get(head_root)
            if state is None:
                # restart path: the restored fork choice can point at a head
                # whose state lives only in the store (persisted_fork_choice)
                try:
                    state = self.state_by_root(head_root)
                except Exception:  # noqa: BLE001 — keep the old head
                    state = None
            if state is not None:
                self.head = ChainHead(
                    root=head_root, slot=state.slot, state=state
                )
                try:
                    self.early_attester_cache.prime(self.spec, head_root, state)
                except Exception:  # noqa: BLE001 — cache priming best-effort
                    self.early_attester_cache.evict()
                self._emit_event(
                    "head",
                    lambda: {
                        "slot": str(int(state.slot)),
                        "block": "0x" + head_root.hex(),
                        # the head block commits to its post-state root —
                        # no re-merkleization under the chain lock
                        "state": "0x"
                        + bytes(
                            state.latest_block_header.state_root
                        ).hex(),
                    },
                )
        return self.head.root

    def _maybe_migrate(self) -> None:
        """Freeze + prune when finalization advances (migrate.rs trigger)."""
        fin_epoch, fin_root = self.fork_choice.store.finalized_checkpoint
        fin_slot = self.spec.start_slot(int(fin_epoch))
        if fin_slot > self.migrator.last_finalized_slot and fin_root in self._states:
            self.migrator.process_finalization(self, bytes(fin_root), fin_slot)
            self._emit_event(
                "finalized_checkpoint",
                lambda: {
                    "epoch": str(int(fin_epoch)),
                    "block": "0x" + bytes(fin_root).hex(),
                },
            )

    # -- production -------------------------------------------------------------------

    def _produce_payload(self, state, slot: int, fork: str):
        """engine_forkchoiceUpdated(attributes) -> engine_getPayload — the
        production half of the engine API (execution_layer get_payload flow).
        Returns None pre-merge (default payload stands in)."""
        from ..execution_layer.engine import PayloadAttributes
        from ..state_transition.per_block import (
            compute_timestamp_at_slot,
            is_merge_transition_complete,
            _expected_withdrawals_list,
        )
        from ..state_transition import get_randao_mix
        from ..types.spec import fork_at_least

        if not is_merge_transition_complete(state):
            return None  # pre-merge: the default payload is the right body
        head_hash = bytes(state.latest_execution_payload_header.block_hash)
        withdrawals = (
            _expected_withdrawals_list(self.spec, state)
            if fork_at_least(fork, "capella")
            else None
        )
        attrs = PayloadAttributes(
            timestamp=compute_timestamp_at_slot(self.spec, state, slot),
            prev_randao=get_randao_mix(
                self.spec, state, get_current_epoch(self.spec, state)
            ),
            suggested_fee_recipient=b"\x00" * 20,
            withdrawals=withdrawals,
            # deneb+: V3 attributes carry the parent beacon block root
            parent_beacon_block_root=(
                bytes(state.latest_block_header.tree_root())
                if fork_at_least(fork, "deneb")
                else None
            ),
        )
        # the engine wants an EXECUTION hash for finalizedBlockHash, not the
        # beacon checkpoint root (zeros when the finalized block is unknown
        # or pre-merge — the engine-API's defined "none" value)
        finalized = b"\x00" * 32
        fin_block = self._blocks.get(bytes(state.finalized_checkpoint.root))
        if fin_block is not None:
            fin_payload = getattr(
                fin_block.message.body, "execution_payload", None
            )
            if fin_payload is not None:
                finalized = bytes(fin_payload.block_hash)
        _status, payload_id = self.execution_layer.forkchoice_updated(
            head_hash, finalized, attrs
        )
        if payload_id is None:
            return None
        return self.execution_layer.get_payload(
            payload_id, self.ns.payload_types[fork]
        )

    def produce_block_on_state(self, state, slot, randao_reveal, attestations=None,
                               graffiti: bytes = b"\x00" * 32, op_pool=None):
        spec = self.spec
        state = state.copy()
        if state.slot < slot:
            process_slots(spec, state, slot)
        proposer = get_beacon_proposer_index(spec, state)
        parent_root = state.latest_block_header.tree_root()
        fork = spec.fork_name_at_epoch(get_current_epoch(spec, state))
        body_cls = self.ns.body_types[fork]
        block_cls = self.ns.block_types[fork]
        body_fields = {n for n, _ in body_cls.FIELDS}
        sync_aggregate = None
        if "sync_aggregate" in body_fields:
            # altair+: best pooled aggregate for the parent root at slot-1,
            # else the empty INFINITY aggregate (a zero default signature is
            # not a valid empty aggregate, blst INFINITY convention)
            sync_aggregate = self.sync_contribution_pool.get_sync_aggregate(
                self.ns, slot - 1, parent_root
            )
        eth1_data = state.eth1_data
        deposits = []
        if self.eth1_service is not None:
            eth1_data = self.eth1_service.eth1_data_vote(state)
            # deposits must match the eth1_data the block's own processing
            # ends up with: process_eth1_data may adopt OUR vote mid-block
            # when it reaches the period majority (eth1_chain.rs computes
            # against the post-vote data for exactly this reason)
            votes = list(state.eth1_data_votes) + [eth1_data]
            period = spec.preset.slots_per_eth1_voting_period
            adopted = (
                eth1_data
                if sum(1 for v in votes if v == eth1_data) * 2 > period
                else state.eth1_data
            )
            deposits = self.eth1_service.deposits_for_inclusion(
                state, eth1_data=adopted
            )
        body_kwargs = dict(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=graffiti,
            attestations=attestations or [],
            deposits=deposits,
        )
        if sync_aggregate is not None:
            body_kwargs["sync_aggregate"] = sync_aggregate
        if (
            "execution_payload" in body_fields
            and self.execution_layer is not None
        ):
            payload = self._produce_payload(state, slot, fork)
            if payload is not None:
                body_kwargs["execution_payload"] = payload
        if op_pool is not None:
            # pooled slashing evidence + exits (+ capella credential
            # rotations) ride the block (get_slashings_and_exits,
            # operation_pool/src/lib.rs:388)
            proposer_sl, attester_sl, exits = op_pool.get_slashings_and_exits(
                state
            )
            body_kwargs["proposer_slashings"] = proposer_sl
            body_kwargs["attester_slashings"] = attester_sl
            body_kwargs["voluntary_exits"] = exits
            if "bls_to_execution_changes" in body_fields:
                body_kwargs["bls_to_execution_changes"] = (
                    op_pool.get_bls_to_execution_changes(state)
                )
        body = body_cls(**body_kwargs)
        inner_cls = dict(block_cls.FIELDS)["message"]
        block = inner_cls(
            slot=slot, proposer_index=proposer, parent_root=parent_root,
            state_root=b"\x00" * 32, body=body,
        )
        trial = state.copy()
        per_block_processing(
            spec, trial, block_cls(message=block, signature=b"\x00" * 96),
            strategy=BlockSignatureStrategy.NO_VERIFICATION,
            verify_block_root=False,
        )
        block.state_root = trial.tree_root()
        return block, trial
