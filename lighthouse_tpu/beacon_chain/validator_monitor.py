"""Validator monitor: per-validator duty tracking on the beacon node.

Twin of ``beacon_chain/src/validator_monitor.rs``: operators register
validator indices; the monitor taps the chain's attestation/block observer
seams, records per-epoch participation (attestations seen on gossip, head
correctness, blocks proposed), logs a per-epoch summary, and feeds the
Prometheus registry.
"""

from __future__ import annotations

from collections import defaultdict

from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY

log = get_logger("validator_monitor")

MONITOR_ATTESTATIONS = REGISTRY.counter(
    "validator_monitor_attestations_total",
    "Gossip attestations seen from monitored validators",
)
MONITOR_BLOCKS = REGISTRY.counter(
    "validator_monitor_blocks_total",
    "Blocks proposed by monitored validators",
)


class ValidatorMonitor:
    def __init__(self, chain, indices=(), auto: bool = False):
        """``auto`` monitors every validator (validator_monitor.rs
        auto-register mode)."""
        self.chain = chain
        self.auto = auto
        self.indices: set[int] = {int(i) for i in indices}
        # epoch -> index -> {"attested": n, "head_correct": n, "blocks": n}
        self._epochs: dict[int, dict[int, dict]] = defaultdict(
            lambda: defaultdict(lambda: {"attested": 0, "head_correct": 0,
                                         "blocks": 0})
        )
        self._last_logged_epoch = -1
        chain.attestation_observers.append(self._on_attestation)
        chain.block_observers.append(self._on_block)

    def add_validator(self, index: int) -> None:
        self.indices.add(int(index))

    def _tracked(self, index: int) -> bool:
        return self.auto or int(index) in self.indices

    # -- observer taps ------------------------------------------------------

    def _on_attestation(self, indexed) -> None:
        epoch = int(indexed.data.target.epoch)
        head_ok = bytes(indexed.data.beacon_block_root) in self.chain._seen_blocks
        for i in indexed.attesting_indices:
            if not self._tracked(i):
                continue
            rec = self._epochs[epoch][int(i)]
            rec["attested"] += 1
            if head_ok:
                rec["head_correct"] += 1
            MONITOR_ATTESTATIONS.inc()
        self._maybe_log(epoch)

    def _on_block(self, signed_block) -> None:
        blk = signed_block.message
        epoch = self.chain.spec.compute_epoch_at_slot(int(blk.slot))
        proposer = int(blk.proposer_index)
        if self._tracked(proposer):
            self._epochs[epoch][proposer]["blocks"] += 1
            MONITOR_BLOCKS.inc()
        self._maybe_log(epoch)

    # -- reporting ----------------------------------------------------------

    def epoch_summary(self, epoch: int) -> dict:
        recs = self._epochs.get(epoch, {})
        return {
            "epoch": epoch,
            "validators": len(recs),
            "attestations": sum(r["attested"] for r in recs.values()),
            "head_correct": sum(r["head_correct"] for r in recs.values()),
            "blocks": sum(r["blocks"] for r in recs.values()),
        }

    def validator_record(self, epoch: int, index: int) -> dict | None:
        recs = self._epochs.get(epoch)
        if recs is None or int(index) not in recs:
            return None
        return dict(recs[int(index)])

    def _maybe_log(self, epoch: int) -> None:
        """One summary line per completed epoch (the reference's
        per-epoch validator monitor logs)."""
        done = epoch - 1
        if done <= self._last_logged_epoch or done < 0:
            return
        if done in self._epochs:
            log.info("Validator monitor epoch summary",
                     **self.epoch_summary(done))
        self._last_logged_epoch = done
        for old in [e for e in self._epochs if e < done - 2]:
            del self._epochs[old]
