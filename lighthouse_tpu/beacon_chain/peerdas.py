"""PeerDAS per-slot column sampling (ISSUE 16).

Twin of the reference's ``network/src/sync/peer_sampling.rs`` +
``beacon_chain/src/data_column_verification.rs`` availability semantics,
scaled to this stack: every node custodies a deterministic
``custody_columns`` subset and samples ``SAMPLES_PER_SLOT`` additional
columns per block (hash-derived from node id + block root, so the set is
stable across retries and reproducible in tests). A block with blob
commitments becomes available ONLY when every custody + sampled column has
been cryptographically verified (the fail-closed gate wired into
``DataAvailabilityChecker.column_gate``); when at least half the columns
are held, ``recover_cells_and_kzg_proofs`` rebuilds the missing ones so a
supermajority-seeded network converges without every column ever riding
gossip.

The sampler holds no sidecars itself — verified columns live in
``chain.data_column_cache`` (chain-lock guarded, availability-horizon
pruned); this class tracks only the verified-index sets and the
availability verdict, so the gate callback is non-blocking.
"""

from __future__ import annotations

import hashlib
import threading

from .data_columns import CUSTODY_REQUIREMENT, custody_columns

SAMPLES_PER_SLOT = 8  # spec get_extended_sample_count baseline


class PeerDasSampler:
    def __init__(self, chain, cell_ctx, node_id: bytes,
                 custody_count: int = CUSTODY_REQUIREMENT,
                 samples_per_slot: int = SAMPLES_PER_SLOT):
        self.chain = chain
        self.ctx = cell_ctx
        self.node_id = bytes(node_id)
        self.n_columns = min(
            getattr(chain.ns, "NUMBER_OF_COLUMNS", cell_ctx.cells),
            cell_ctx.cells,
        )
        self.custody = custody_columns(
            self.node_id, custody_count, self.n_columns
        )
        self.samples_per_slot = min(samples_per_slot, self.n_columns)
        self._lock = threading.Lock()
        # block_root -> verified column indices (insertion-ordered LRU,
        # bounded alongside the chain's column cache)
        self._verified: dict[bytes, set[int]] = {}
        self._max_tracked = chain.da_checker.MAX_PENDING

    # -- column selection ---------------------------------------------------

    def sample_columns(self, block_root: bytes) -> list[int]:
        """The per-block sampling set: deterministic in (node id, root)."""
        out: set[int] = set()
        i = 0
        while len(out) < self.samples_per_slot:
            h = hashlib.sha256(
                self.node_id + bytes(block_root) + i.to_bytes(8, "little")
            ).digest()
            out.add(int.from_bytes(h[:8], "little") % self.n_columns)
            i += 1
        return sorted(out)

    def required_columns(self, block_root: bytes) -> list[int]:
        return sorted(set(self.custody) | set(self.sample_columns(block_root)))

    # -- verification tracking ----------------------------------------------

    def on_verified_column(self, block_root: bytes, index: int) -> None:
        """Record a column that passed ``verify_data_column_sidecar``.
        Callers verify BEFORE calling this — the sampler trusts nothing."""
        root = bytes(block_root)
        with self._lock:
            have = self._verified.pop(root, None) or set()
            have.add(int(index))
            self._verified[root] = have
            while len(self._verified) > self._max_tracked:
                self._verified.pop(next(iter(self._verified)))

    def verified_columns(self, block_root: bytes) -> set[int]:
        with self._lock:
            return set(self._verified.get(bytes(block_root), ()))

    def missing_columns(self, block_root: bytes) -> list[int]:
        have = self.verified_columns(block_root)
        return [c for c in self.required_columns(block_root) if c not in have]

    def is_available(self, block_root: bytes) -> bool:
        """The availability gate: every custody + sampled column verified.
        Non-blocking — safe under the DA checker's cache lock."""
        return not self.missing_columns(block_root)

    # -- reconstruction -----------------------------------------------------

    def can_reconstruct(self, block_root: bytes) -> bool:
        held = self.chain.data_columns_for(bytes(block_root))
        return 2 * len(held) >= self.ctx.cells

    def reconstruct(self, block_root: bytes):
        """Rebuild ALL column sidecars from the >= 50% held set
        (``recover_cells_and_kzg_proofs`` per blob row), or None when too
        few columns are held. Raises ``KzgError`` when held data is
        inconsistent — callers keep the block unavailable in that case."""
        root = bytes(block_root)
        held = self.chain.data_columns_for(root)
        if 2 * len(held) < self.ctx.cells:
            return None
        indices = sorted(held)
        template = held[indices[0]]
        n_blobs = len(template.column)
        bpc = self.ctx.bytes_per_cell
        # recover row-by-row: blob b's cells across the held columns
        cell_rows, proof_rows = [], []
        for b in range(n_blobs):
            rec_cells, rec_proofs = self.ctx.recover_cells_and_kzg_proofs(
                indices, [bytes(held[i].column[b])[:bpc] for i in indices]
            )
            cell_rows.append(rec_cells)
            proof_rows.append(rec_proofs)
        ns = self.chain.ns
        width = getattr(ns, "BYTES_PER_CELL", bpc)
        pad = b"\x00" * (width - bpc)
        return [
            ns.DataColumnSidecar(
                index=col,
                column=[cell_rows[b][col] + pad for b in range(n_blobs)],
                kzg_commitments=[bytes(c) for c in template.kzg_commitments],
                kzg_proofs=[proof_rows[b][col] for b in range(n_blobs)],
                signed_block_header=template.signed_block_header,
                kzg_commitments_inclusion_proof=[
                    bytes(h)
                    for h in template.kzg_commitments_inclusion_proof
                ],
            )
            for col in range(self.ctx.cells)
        ]
