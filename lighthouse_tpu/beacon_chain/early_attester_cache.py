"""Early-attester cache: head-block attestation data without a state read.

Parity target: ``beacon_chain/src/early_attester_cache.rs`` — when a block
becomes head, everything an attester needs for the rest of its epoch
(``beacon_block_root``, source and target checkpoints) is fixed, so the
``attestation_data`` serving path caches it once per head update and answers
the validator-client stampede at the attestation deadline without touching
(let alone slot-advancing) a ``BeaconState``.

One entry — the current head. A request hits when it attests to the cached
head (same chain), in the cached epoch, at or after the head's slot; any
head change or epoch rollover re-primes or evicts. The target root needs
one subtlety: for slots strictly after the epoch-start slot the target is
the epoch-start block root (read from the head state's ``block_roots`` ONCE
at prime time); for the epoch-start slot itself the head block (at or
before that slot) is its own target.

Hit/miss/evict counts land in ``utils.metrics`` (``early_attester_cache_total``)
so the cache's effectiveness is observable next to the shuffling cache tier.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..utils.metrics import EARLY_ATTESTER_CACHE


@dataclass(frozen=True)
class EarlyAttesterEntry:
    epoch: int
    head_root: bytes
    head_slot: int
    source_epoch: int
    source_root: bytes
    target_root: bytes


class EarlyAttesterCache:
    """Single-entry head-attestation cache (module docstring). Thread-safe:
    primed under the chain lock on head updates, read lock-free-ish (one
    small mutex) from HTTP handler threads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entry: EarlyAttesterEntry | None = None
        self.hits = 0
        self.misses = 0

    # -- priming (head-update path, chain lock held by the caller) ----------

    def prime(self, spec, head_root: bytes, state) -> None:
        """Cache the attestation view of the new head. ``state`` is the
        head's post-state (state.slot == head slot); the one
        ``block_roots`` read here is the state access every later request
        skips."""
        head_slot = int(state.slot)
        epoch = spec.compute_epoch_at_slot(head_slot)
        start = spec.start_slot(epoch)
        if head_slot <= start:
            target_root = bytes(head_root)
        else:
            from ..state_transition import get_block_root_at_slot

            target_root = bytes(get_block_root_at_slot(spec, state, start))
        src = state.current_justified_checkpoint
        entry = EarlyAttesterEntry(
            epoch=int(epoch),
            head_root=bytes(head_root),
            head_slot=head_slot,
            source_epoch=int(src.epoch),
            source_root=bytes(src.root),
            target_root=target_root,
        )
        with self._lock:
            self._entry = entry

    def evict(self) -> None:
        with self._lock:
            if self._entry is not None:
                self._entry = None
                EARLY_ATTESTER_CACHE.inc(result="evict")

    # -- the serving path ---------------------------------------------------

    def try_attestation_data(
        self, spec, slot: int, committee_index: int, head_root: bytes
    ):
        """AttestationData for (slot, index) served purely from the cache,
        or None on a miss (caller falls back to the state path). Serves
        only when the caller's current head is the cached head, the request
        epoch is the cached epoch, and the slot is at/after the head's slot
        (attesting to the head as an ancestor)."""
        slot = int(slot)
        with self._lock:
            e = self._entry
        epoch = spec.compute_epoch_at_slot(slot)
        if (
            e is None
            or e.head_root != bytes(head_root)
            or epoch != e.epoch
            or slot < e.head_slot
        ):
            with self._lock:
                self.misses += 1
            EARLY_ATTESTER_CACHE.inc(result="miss")
            return None
        from ..types.containers import AttestationData, Checkpoint

        with self._lock:
            self.hits += 1
        EARLY_ATTESTER_CACHE.inc(result="hit")
        return AttestationData(
            slot=slot,
            index=int(committee_index),
            beacon_block_root=e.head_root,
            source=Checkpoint(epoch=e.source_epoch, root=e.source_root),
            target=Checkpoint(epoch=e.epoch, root=e.target_root),
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "primed": self._entry is not None,
            }
