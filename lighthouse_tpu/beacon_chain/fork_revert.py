"""Fork revert: recover from an unusable head (fork_revert.rs).

Twin of ``beacon_chain/src/fork_revert.rs`` ``revert_to_fork_boundary``:
when the head chain turns out to be unusable — a corrupt head state, or an
execution payload the EL later declared invalid — the node must not die or
stay wedged on the bad branch. The recovery rebuilds fork choice from the
finalized checkpoint (the last point with an absolute guarantee) and
re-plays every known block that does NOT descend from the bad block, so
healthy competing branches keep their place and the bad subtree is erased
from block/state maps and fork-choice alike.
"""

from __future__ import annotations

import numpy as np

from ..fork_choice.fork_choice import ForkChoice
from ..fork_choice.proto_array import ExecutionStatus
from ..utils.logging import get_logger

log = get_logger("fork_revert")


def _descends_from(chain, root: bytes, ancestor: bytes, stop: bytes) -> bool:
    """Does ``root`` have ``ancestor`` on its parent path (walking at most
    to ``stop``)?"""
    seen = 0
    while root in chain._blocks and seen < 2**20:
        if root == ancestor:
            return True
        if root == stop:
            return False
        root = bytes(chain._blocks[root].message.parent_root)
        seen += 1
    return root == ancestor


def revert_to_fork_boundary(chain, bad_root: bytes) -> bytes:
    """Rebuild fork choice anchored at the finalized checkpoint, dropping
    the subtree rooted at ``bad_root``. Returns the new head root."""
    spec = chain.spec
    fin_epoch, fin_root = chain.fork_choice.store.finalized_checkpoint
    anchor_root = (
        fin_root
        if fin_root in chain._seen_blocks
        and (fin_root in chain._blocks or fin_root == chain.genesis_block_root)
        else chain.genesis_block_root
    )
    with chain.lock:
        anchor_state = chain.state_by_root(anchor_root)
        jc = (
            max(int(fin_epoch), spec.compute_epoch_at_slot(int(anchor_state.slot))),
            anchor_root,
        )
        fc = ForkChoice.from_anchor(
            spec,
            anchor_root,
            int(anchor_state.slot),
            jc,
            jc,
            np.asarray(anchor_state.balances, dtype=np.uint64),
        )
        # drop the bad subtree, then replay survivors in slot order
        doomed = {
            root
            for root in chain._blocks
            if _descends_from(chain, root, bad_root, anchor_root)
        }
        for root in doomed:
            chain._blocks.pop(root, None)
            chain._states.pop(root, None)
            chain._seen_blocks.discard(root)
        survivors = sorted(
            (
                (int(sb.message.slot), root, sb)
                for root, sb in chain._blocks.items()
                if root != anchor_root
                and _descends_from(chain, root, anchor_root, b"")
                and int(sb.message.slot) > int(anchor_state.slot)
            ),
        )
        current_slot = max(
            (s for s, _, _ in survivors), default=int(anchor_state.slot)
        )
        fc.update_time(current_slot)
        replayed = 0
        for slot, root, sb in survivors:
            state = chain._states.get(root)
            if state is None:
                try:
                    state = chain.state_by_root(root)
                except Exception:  # noqa: BLE001 — unloadable: drop it too
                    chain._blocks.pop(root, None)
                    chain._seen_blocks.discard(root)
                    continue
            try:
                fc.on_block(
                    current_slot,
                    sb.message,
                    root,
                    state,
                    justified_balances=chain._justified_balances(
                        bytes(state.current_justified_checkpoint.root), state
                    ),
                    execution_status=ExecutionStatus.OPTIMISTIC
                    if getattr(sb.message.body, "execution_payload", None)
                    is not None
                    else ExecutionStatus.IRRELEVANT,
                )
                replayed += 1
            except Exception as e:  # noqa: BLE001 — unviable after revert
                log.warn(
                    "Dropped block during revert",
                    root=root.hex()[:12], error=str(e),
                )
                chain._blocks.pop(root, None)
                chain._states.pop(root, None)
                chain._seen_blocks.discard(root)
        chain.fork_choice = fc
        new_head = chain.recompute_head()
    log.warn(
        "Chain reverted to fork boundary",
        anchor=anchor_root.hex()[:12],
        dropped=len(doomed),
        replayed=replayed,
        new_head=new_head.hex()[:12],
    )
    return new_head
