"""Validator pubkey cache: every key decompressed once, resident for batches.

Parity: ``/root/reference/beacon_node/beacon_chain/src/validator_pubkey_cache.rs:12-25``
— "keeps all validator pubkeys decompressed in memory". TPU-first upgrade: in
addition to host-side oracle points (for the CPU backend), the cache maintains
a device-resident projective-coordinate array ``[n, 3, 25]`` so batched
verification gathers keys on device without per-batch H2D of 48-byte blobs
(SURVEY §7.6: the feed for 1M-validator batches).
"""

from __future__ import annotations

import numpy as np

from .. import bls
from ..ops.bls_oracle import curves as oc


class ValidatorPubkeyCache:
    def __init__(self):
        self._points: list = []          # oracle affine points
        self._pubkeys: list[bls.PublicKey] = []
        self._bytes_to_index: dict[bytes, int] = {}
        self._device = None              # [n, 3, 25] uint64 (lazily built)
        self._device_len = 0

    def __len__(self) -> int:
        return len(self._points)

    def import_new_pubkeys(self, state) -> None:
        """Decompress + subgroup-check any validators beyond the cache length
        (import_new_pubkeys in the reference; invalid keys are impossible in a
        valid state, so errors raise)."""
        for v in state.validators[len(self._points):]:
            pk_bytes = bytes(v.pubkey)
            pk = bls.PublicKey.from_bytes(pk_bytes)
            self._bytes_to_index[pk_bytes] = len(self._points)
            self._points.append(pk.point)
            self._pubkeys.append(pk)

    def get(self, index: int) -> bls.PublicKey | None:
        return self._pubkeys[index] if index < len(self._pubkeys) else None

    def get_index(self, pubkey_bytes: bytes) -> int | None:
        return self._bytes_to_index.get(bytes(pubkey_bytes))

    def get_point(self, index: int):
        return self._points[index] if index < len(self._points) else None

    # -- device residency --------------------------------------------------------

    def device_array(self):
        """[n, 3, 25] device projective points, built incrementally."""
        import jax.numpy as jnp

        from ..ops.bls import g1

        n = len(self._points)
        if self._device is None or self._device_len < n:
            new = g1.from_oracle_batch(self._points[self._device_len:])
            self._device = (
                new
                if self._device is None
                else jnp.concatenate([self._device, new], axis=0)
            )
            self._device_len = n
        return self._device

    def device_gather(self, indices) -> "object":
        """Gather [k, 3, 25] pubkey points for validator indices on device."""
        arr = self.device_array()
        import jax.numpy as jnp

        return arr[jnp.asarray(np.asarray(indices, dtype=np.int64))]


def device_pubkeys_from_raw(raw: "np.ndarray"):
    """Bulk-load raw affine pubkeys ([n, 96] uint8: x||y big-endian, the
    native backend's bls_pk_decompress output) into the device-resident
    projective array [n, 3, 25] — the fast path for building a large cache
    without per-key Python point objects."""
    import jax.numpy as jnp

    from ..bls.serde import _be_bytes_to_limbs, raw_to_mont
    from ..ops.bls import tower

    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    n = raw.shape[0]
    x = _be_bytes_to_limbs(raw[:, :48])
    y = _be_bytes_to_limbs(raw[:, 48:])
    xm = raw_to_mont(jnp.asarray(x))
    ym = raw_to_mont(jnp.asarray(y))
    one = jnp.broadcast_to(tower.one(1), (n, 1, xm.shape[-1]))
    return jnp.concatenate([xm[:, None, :], ym[:, None, :], one], axis=1)
