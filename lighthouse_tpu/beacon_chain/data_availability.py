"""Blob sidecar verification + data availability tracking (deneb).

Twin of ``beacon_node/beacon_chain/src/{blob_verification.rs,
data_availability_checker.rs}``: gossip sidecars are checked structurally
(index bound, header signature, commitment inclusion proof against the body
root) then cryptographically (KZG proof batch); blocks with commitments wait
in the availability cache until every blob index has arrived, and only then
import (``Availability::Available`` vs ``MissingComponents``).

The KZG batch check rides the same RLC pairing path as signature
verification — one 2-pairing check per gossip batch of sidecars.
"""

from __future__ import annotations

import threading

from ..ssz.merkle import fold_merkle_branch, merkle_branch_from_chunks
from ..types.containers import BeaconBlockHeader, SignedBeaconBlockHeader


class BlobError(Exception):
    pass


class AvailabilityCheckError(BlobError):
    pass


def _commitments_field_index(body_cls) -> int:
    return [n for n, _ in body_cls.FIELDS].index("blob_kzg_commitments")


def body_field_branch(body, field_index: int) -> list[bytes]:
    """Sibling branch for one top-level field under the body root."""
    import numpy as np

    from ..ssz.merkle import next_pow2

    body_cls = type(body)
    field_roots = np.stack(
        [
            np.frombuffer(t.hash_tree_root(getattr(body, n)), dtype=np.uint8)
            for n, t in body_cls.FIELDS
        ]
    )
    return merkle_branch_from_chunks(
        field_roots, next_pow2(len(body_cls.FIELDS)), field_index
    )


def commitment_inclusion_proof(ns, body, index: int) -> list[bytes]:
    """Branch proving body.blob_kzg_commitments[index] under the body root."""
    import numpy as np

    p = ns.preset
    body_cls = type(body)
    comm_t = dict(body_cls.FIELDS)["blob_kzg_commitments"]
    elem_t = comm_t.elem
    roots = np.stack(
        [
            np.frombuffer(elem_t.hash_tree_root(c), dtype=np.uint8)
            for c in body.blob_kzg_commitments
        ]
    )
    branch = merkle_branch_from_chunks(
        roots, p.MAX_BLOB_COMMITMENTS_PER_BLOCK, index
    )
    # length mix-in level: sibling is the little-endian length chunk
    length_chunk = len(body.blob_kzg_commitments).to_bytes(8, "little") + b"\x00" * 24
    branch.append(length_chunk)
    # body-fields level
    branch.extend(body_field_branch(body, _commitments_field_index(body_cls)))
    return branch


def _inclusion_proof_index(ns, body_cls, blob_index: int) -> int:
    """Direction bits for folding the inclusion branch: blob index bits,
    then the mix-in level (left child = 0), then the body field index."""
    p = ns.preset
    comm_depth = (p.MAX_BLOB_COMMITMENTS_PER_BLOCK - 1).bit_length()
    fi = _commitments_field_index(body_cls)
    return blob_index | (fi << (comm_depth + 1))


def verify_commitment_inclusion(ns, sidecar, body_cls=None) -> bool:
    """Check sidecar.kzg_commitment_inclusion_proof against the header's
    body_root (blob_verification.rs verify_blob_sidecar_inclusion_proof)."""
    from ..types.containers import KZGCommitment

    body_cls = body_cls or ns.BeaconBlockBodyDeneb
    leaf = KZGCommitment.hash_tree_root(bytes(sidecar.kzg_commitment))
    idx = _inclusion_proof_index(ns, body_cls, int(sidecar.index))
    root = fold_merkle_branch(
        leaf,
        [bytes(h) for h in sidecar.kzg_commitment_inclusion_proof],
        idx,
    )
    return root == bytes(sidecar.signed_block_header.message.body_root)


def make_blob_sidecars(ns, signed_block, blobs, proofs, kzg=None):
    """Produce gossip sidecars for a block's blobs (the production path:
    blob_sidecar.rs BlobSidecar::new)."""
    blk = signed_block.message
    header = SignedBeaconBlockHeader(
        message=BeaconBlockHeader(
            slot=blk.slot,
            proposer_index=blk.proposer_index,
            parent_root=bytes(blk.parent_root),
            state_root=bytes(blk.state_root),
            body_root=type(blk.body).hash_tree_root(blk.body),
        ),
        signature=bytes(signed_block.signature),
    )
    out = []
    for i, (blob, proof) in enumerate(zip(blobs, proofs)):
        out.append(
            ns.BlobSidecar(
                index=i,
                blob=blob,
                kzg_commitment=bytes(blk.body.blob_kzg_commitments[i]),
                kzg_proof=proof,
                signed_block_header=header,
                kzg_commitment_inclusion_proof=commitment_inclusion_proof(
                    ns, blk.body, i
                ),
            )
        )
    return out


class DataAvailabilityChecker:
    """Pending-components cache gating block import on blob arrival
    (data_availability_checker.rs / overflow_lru_cache.rs semantics,
    memory-resident)."""

    MAX_PENDING = 64  # LRU bound (the reference's OverflowLRUCache capacity role)

    def __init__(self, spec, kzg=None, is_known=None):
        self.spec = spec
        self.kzg = kzg
        # chain callback: roots already imported must not be resurrected by
        # late/duplicate gossip sidecars
        self.is_known = is_known or (lambda root: False)
        self._lock = threading.Lock()
        # block_root -> {"block": signed_block | None, "blobs": {index: sidecar}}
        # insertion-ordered: oldest entries evicted past MAX_PENDING
        self._pending: dict[bytes, dict] = {}
        # PeerDAS mode: when set, availability for blob-carrying blocks is
        # decided by the sampling gate (custody + sampled columns verified)
        # instead of per-blob sidecar arrival. fn(block_root) -> bool.
        self.column_gate = None

    def set_column_gate(self, gate) -> None:
        """Switch this checker to column sampling (PeerDAS): ``gate`` is
        called under the cache lock and must be non-blocking — it reads the
        sampler's verified-column state, it never verifies in-line."""
        self.column_gate = gate

    def _touch(self, root: bytes) -> dict:
        entry = self._pending.pop(root, None)
        if entry is None:
            entry = {"block": None, "blobs": {}}
        self._pending[root] = entry
        while len(self._pending) > self.MAX_PENDING:
            self._pending.pop(next(iter(self._pending)))
        return entry

    # -- gossip verification ------------------------------------------------

    def verify_blob_sidecar(self, ns, sidecar) -> None:
        """Structural + KZG checks; raises BlobError (gossip path;
        blob_verification.rs GossipVerifiedBlob). Header signature is the
        caller's job (it needs the proposer pubkey from the chain)."""
        p = self.spec.preset
        if int(sidecar.index) >= p.MAX_BLOBS_PER_BLOCK:
            raise BlobError(f"blob index {int(sidecar.index)} out of range")
        if not verify_commitment_inclusion(ns, sidecar):
            raise BlobError("invalid commitment inclusion proof")
        if self.kzg is not None:
            ok = self.kzg.verify_blob_kzg_proof_batch(
                [bytes(sidecar.blob)],
                [bytes(sidecar.kzg_commitment)],
                [bytes(sidecar.kzg_proof)],
            )
            if not ok:
                raise BlobError("kzg proof verification failed")

    def verify_blob_sidecar_batch(self, ns, sidecars) -> None:
        """Batch variant: one RLC pairing check across all sidecars."""
        for sc in sidecars:
            p = self.spec.preset
            if int(sc.index) >= p.MAX_BLOBS_PER_BLOCK:
                raise BlobError(f"blob index {int(sc.index)} out of range")
            if not verify_commitment_inclusion(ns, sc):
                raise BlobError("invalid commitment inclusion proof")
        if self.kzg is not None and sidecars:
            ok = self.kzg.verify_blob_kzg_proof_batch(
                [bytes(sc.blob) for sc in sidecars],
                [bytes(sc.kzg_commitment) for sc in sidecars],
                [bytes(sc.kzg_proof) for sc in sidecars],
            )
            if not ok:
                raise BlobError("kzg batch proof verification failed")

    # -- availability tracking ----------------------------------------------

    @staticmethod
    def _required(signed_block) -> int:
        comms = getattr(signed_block.message.body, "blob_kzg_commitments", None)
        return 0 if comms is None else len(comms)

    def put_block(self, block_root: bytes, signed_block):
        """Returns the available (block, blobs-in-order) or None if blobs
        are still missing."""
        required = self._required(signed_block)
        if required == 0:
            return signed_block, []
        with self._lock:
            entry = self._touch(block_root)
            entry["block"] = signed_block
            return self._check_available(block_root, entry)

    def put_blob(self, sidecar):
        """Returns the now-available (block, blobs) or None."""
        root = BeaconBlockHeader.hash_tree_root(
            sidecar.signed_block_header.message
        )
        if self.is_known(root):
            return None  # already imported; don't resurrect the entry
        with self._lock:
            entry = self._touch(root)
            entry["blobs"][int(sidecar.index)] = sidecar
            return self._check_available(root, entry)

    def notify_columns(self, block_root: bytes):
        """Column-sampling progress signal: re-evaluate a pending block
        against the column gate. Returns the now-available (block, [])
        or None (no pending block / gate still unsatisfied)."""
        if self.is_known(block_root):
            return None
        with self._lock:
            entry = self._pending.get(block_root)
            if entry is None:
                return None
            return self._check_available(block_root, entry)

    def _check_available(self, root, entry):
        blk = entry["block"]
        if blk is None:
            return None
        if self.column_gate is not None:
            # PeerDAS: the sampling state machine owns the verdict; blobs
            # are reconstructed from columns, never waited on individually
            if self.column_gate(root):
                self._pending.pop(root, None)
                return blk, []
            return None
        required = self._required(blk)
        comms = blk.message.body.blob_kzg_commitments
        if any(i not in entry["blobs"] for i in range(required)):
            return None
        # commitments must line up sidecar-by-sidecar
        for i in range(required):
            if bytes(entry["blobs"][i].kzg_commitment) != bytes(comms[i]):
                raise AvailabilityCheckError(
                    f"sidecar {i} commitment does not match the block"
                )
        self._pending.pop(root, None)
        return blk, [entry["blobs"][i] for i in range(required)]

    def missing_blob_ids(self, block_root: bytes) -> list[int]:
        with self._lock:
            entry = self._pending.get(block_root)
            if entry is None or entry["block"] is None:
                return []
            required = self._required(entry["block"])
            return [i for i in range(required) if i not in entry["blobs"]]

    def prune(self, keep_roots) -> None:
        with self._lock:
            for root in list(self._pending):
                if root not in keep_roots:
                    del self._pending[root]
