"""Data-column sidecars: PeerDAS construction + verification groundwork.

Twin of ``consensus/types/src/data_column_sidecar.rs`` (construction from a
block's blobs: build the cell matrix with ``compute_cells_and_kzg_proofs``
then transpose — column j carries cell j of every blob) and the column half
of ``beacon_chain/src/data_column_verification.rs`` (inclusion proof of the
whole commitments list under the body root, then a cell KZG proof batch).
Sampling (``network/src/sync/peer_sampling.rs``) consumes these through the
``CUSTODY_REQUIREMENT`` subset helper.
"""

from __future__ import annotations

import hashlib

from ..ssz.merkle import fold_merkle_branch
from ..types.containers import BeaconBlockHeader, SignedBeaconBlockHeader
from .data_availability import (
    BlobError,
    _commitments_field_index,
    body_field_branch,
)

CUSTODY_REQUIREMENT = 4  # columns every node custodies (spec minimum)


class DataColumnError(BlobError):
    pass


def commitments_list_inclusion_proof(body) -> list[bytes]:
    """Branch proving the WHOLE blob_kzg_commitments list under body root."""
    return body_field_branch(body, _commitments_field_index(type(body)))


def verify_commitments_inclusion(ns, sidecar, body_cls=None) -> bool:
    """data_column_sidecar.rs verify_inclusion_proof."""
    body_cls = body_cls or ns.BeaconBlockBodyDeneb
    comm_t = dict(body_cls.FIELDS)["blob_kzg_commitments"]
    leaf = comm_t.hash_tree_root(list(sidecar.kzg_commitments))
    fi = _commitments_field_index(body_cls)
    root = fold_merkle_branch(
        leaf,
        [bytes(h) for h in sidecar.kzg_commitments_inclusion_proof],
        fi,
    )
    return root == bytes(sidecar.signed_block_header.message.body_root)


def make_data_column_sidecars(ns, signed_block, blobs, cell_ctx):
    """Build every column sidecar for a block's blobs
    (DataColumnSidecar construction, data_column_sidecar.rs:66+)."""
    blk = signed_block.message
    commitments = [bytes(c) for c in blk.body.blob_kzg_commitments]
    if len(commitments) != len(blobs):
        raise DataColumnError("blob count != commitment count")
    header = SignedBeaconBlockHeader(
        message=BeaconBlockHeader(
            slot=blk.slot,
            proposer_index=blk.proposer_index,
            parent_root=blk.parent_root,
            state_root=blk.state_root,
            body_root=blk.body.tree_root(),
        ),
        signature=signed_block.signature,
    )
    proof = commitments_list_inclusion_proof(blk.body)
    # cell matrix: row = blob, column = cell index
    cell_rows, proof_rows = [], []
    for blob in blobs:
        cells, proofs = cell_ctx.compute_cells_and_kzg_proofs(blob)
        cell_rows.append(cells)
        proof_rows.append(proofs)
    # container cells are spec-sized (BYTES_PER_CELL); smaller test
    # geometries zero-pad on the wire and slice back at verification
    width = getattr(ns, "BYTES_PER_CELL", cell_ctx.bytes_per_cell)
    pad = width - cell_ctx.bytes_per_cell

    sidecars = []
    for col in range(cell_ctx.cells):
        sidecars.append(
            ns.DataColumnSidecar(
                index=col,
                column=[row[col] + b"\x00" * pad for row in cell_rows],
                kzg_commitments=commitments,
                kzg_proofs=[row[col] for row in proof_rows],
                signed_block_header=header,
                kzg_commitments_inclusion_proof=proof,
            )
        )
    return sidecars


def verify_data_column_sidecar(ns, sidecar, cell_ctx) -> None:
    """Structural + cryptographic column verification
    (data_column_verification.rs verify_kzg_for_data_column)."""
    n_cols = getattr(ns, "NUMBER_OF_COLUMNS", cell_ctx.cells)
    if not 0 <= int(sidecar.index) < min(n_cols, cell_ctx.cells):
        raise DataColumnError(f"column index {int(sidecar.index)} out of range")
    if len(sidecar.column) != len(sidecar.kzg_commitments) or len(
        sidecar.column
    ) != len(sidecar.kzg_proofs):
        raise DataColumnError("column/commitments/proofs length mismatch")
    if len(sidecar.column) == 0:
        raise DataColumnError("empty column")
    if not verify_commitments_inclusion(ns, sidecar):
        raise DataColumnError("commitments inclusion proof invalid")
    cells = []
    for c in sidecar.column:
        raw = bytes(c)
        if any(raw[cell_ctx.bytes_per_cell :]):
            # the sidecar's identity (tree root) covers the pad region, so
            # non-zero padding must fail — not be silently sliced away
            raise DataColumnError("cell padding not zero")
        cells.append(raw[: cell_ctx.bytes_per_cell])
    from ..kzg.engine import verify_cell_proof_batch

    # backend-dispatched (LIGHTHOUSE_KZG_BACKEND): host per-cell loop or
    # the device engine under the kzg_device ladder — fails CLOSED either way
    ok = verify_cell_proof_batch(
        cell_ctx,
        [bytes(c) for c in sidecar.kzg_commitments],
        [int(sidecar.index)] * len(sidecar.column),
        cells,
        [bytes(p) for p in sidecar.kzg_proofs],
    )
    if not ok:
        raise DataColumnError("cell KZG proof batch failed")


def custody_columns(node_id: bytes, custody_count: int = CUSTODY_REQUIREMENT,
                    n_columns: int = 128) -> list[int]:
    """Deterministic custody column subset for a node id (spec
    get_custody_columns: hash-derived, uniform, stable)."""
    out, i = set(), 0
    while len(out) < min(custody_count, n_columns):
        h = hashlib.sha256(node_id + i.to_bytes(8, "little")).digest()
        out.add(int.from_bytes(h[:8], "little") % n_columns)
        i += 1
    return sorted(out)
