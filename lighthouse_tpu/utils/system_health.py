"""Host health snapshot (ref common/system_health): process + system stats
for the /health surface and the monitoring push."""

from __future__ import annotations

import os
import shutil


def system_health(datadir: str | None = None) -> dict:
    out: dict = {"pid": os.getpid()}
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        out["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError):
        pass
    try:
        load1, load5, load15 = os.getloadavg()
        out["loadavg"] = [round(load1, 2), round(load5, 2), round(load15, 2)]
    except OSError:
        pass
    out["cpu_count"] = os.cpu_count()
    try:
        usage = shutil.disk_usage(datadir or "/")
        out["disk_total_bytes"] = usage.total
        out["disk_free_bytes"] = usage.free
    except OSError:
        pass
    # fault-domain health (resilience.supervisor): backend states, recent
    # classified faults — degradation must be visible from /health
    try:
        from ..resilience import health_snapshot

        out["fault_domains"] = health_snapshot()
    except Exception:  # noqa: BLE001 — health must never fail the probe
        pass
    return out
