"""Structured key-value logging (ref common/logging: slog drains bridged to
tracing layers).

``get_logger("beacon_chain")`` yields a component logger whose records
carry key=value fields slog-style; a metrics layer counts log events per
component/level as Prometheus counters, mirroring
``tracing_metrics_layer.rs``'s accounting of dependency logs.
"""

from __future__ import annotations

import logging
import sys
import threading
import time

from .metrics import REGISTRY

LOG_EVENTS = REGISTRY.counter(
    "log_events_total",
    "Log events by component and level (tracing_metrics_layer.rs)",
    label_names=("component", "level"),
)

_configured = False
_lock = threading.Lock()


class _KVFormatter(logging.Formatter):
    def format(self, record):
        ts = time.strftime("%b %d %H:%M:%S", time.localtime(record.created))
        fields = getattr(record, "kv", {})
        kv = "".join(f", {k}: {v}" for k, v in fields.items())
        return (
            f"{ts} {record.levelname:5s} {record.getMessage()}{kv}, "
            f"module: {record.name}"
        )


class StructuredLogger:
    """slog-style: ``log.info("Block imported", slot=5, root="0xab..")``."""

    def __init__(self, component: str):
        self.component = component
        self._log = logging.getLogger(f"lighthouse_tpu.{component}")

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        LOG_EVENTS.inc(
            component=self.component, level=logging.getLevelName(level).lower()
        )
        self._log.log(level, msg, extra={"kv": kv})

    def debug(self, msg: str, **kv) -> None:
        self._emit(logging.DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit(logging.INFO, msg, kv)

    def warn(self, msg: str, **kv) -> None:
        self._emit(logging.WARNING, msg, kv)

    # stdlib-logging name; same level (callers use either spelling)
    warning = warn

    def error(self, msg: str, **kv) -> None:
        self._emit(logging.ERROR, msg, kv)

    def child(self, sub: str) -> "StructuredLogger":
        return StructuredLogger(f"{self.component}.{sub}")


def init_logging(level: str = "info", stream=None) -> None:
    """Install the root handler once (EnvironmentBuilder's logger init)."""
    global _configured
    with _lock:
        root = logging.getLogger("lighthouse_tpu")
        if _configured:
            root.setLevel(level.upper())
            return
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(_KVFormatter())
        root.addHandler(handler)
        root.setLevel(level.upper())
        root.propagate = False
        _configured = True


def get_logger(component: str) -> StructuredLogger:
    return StructuredLogger(component)
