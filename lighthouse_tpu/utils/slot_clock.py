"""Slot clocks (common/slot_clock twin): system-time and manual test clocks."""

from __future__ import annotations

import time


class SlotClock:
    def now(self) -> int | None:
        raise NotImplementedError

    def seconds_into_slot(self) -> float:
        raise NotImplementedError


class SystemTimeSlotClock(SlotClock):
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def now(self) -> int | None:
        t = time.time()
        if t < self.genesis_time:
            return None
        return int(t - self.genesis_time) // self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        t = time.time()
        return (t - self.genesis_time) % self.seconds_per_slot

    def start_of(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot


class ManualSlotClock(SlotClock):
    """Test clock advanced by hand (TestingSlotClock, slot_clock/src/manual_slot_clock.rs)."""

    def __init__(self, slot: int = 0):
        self._slot = slot

    def now(self) -> int | None:
        return self._slot

    def set_slot(self, slot: int) -> None:
        self._slot = slot

    def advance_slot(self) -> None:
        self._slot += 1

    def seconds_into_slot(self) -> float:
        return 0.0
