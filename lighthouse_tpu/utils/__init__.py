"""Cross-cutting commons (common/* twin): slot clocks, task executor, metrics."""

from .slot_clock import ManualSlotClock, SlotClock, SystemTimeSlotClock
