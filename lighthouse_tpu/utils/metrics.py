"""Prometheus-style metrics registry (ref common/metrics + the per-subsystem
``metrics.rs`` files; scraped by ``http_metrics``).

Metric NAMES follow the reference so dashboards transfer — e.g. the
attestation batch timers of ``attestation_verification/batch.rs:57,106``
keep their ``beacon_attestation_batch_*`` families. Collectors are
process-global and cheap enough for hot paths (a timer observe is a couple
of dict ops); exposition is the Prometheus text format.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: tuple = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def collect(self):
        with self._lock:
            items = list(self._values.items())
        for key, v in items:
            yield key, "", v


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, label_names)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def collect(self):
        with self._lock:
            items = list(self._values.items())
        for key, v in items:
            yield key, "", v


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    @contextmanager
    def time(self, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def collect(self):
        with self._lock:
            snapshot = [
                (key, list(counts), self._totals[key], self._sums[key])
                for key, counts in self._counts.items()
            ]
        for key, counts, total, total_sum in snapshot:
            for b, c in zip(self.buckets, counts):
                yield key, f'le="{b}"', c
            yield key, 'le="+Inf"', total
            yield key, "__sum__", total_sum
            yield key, "__count__", total


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, cls, name, help_text, label_names=(), **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_text, label_names, **kw)
                self._metrics[name] = m
            return m

    def counter(self, name, help_text, label_names=()):
        return self._register(Counter, name, help_text, label_names)

    def gauge(self, name, help_text, label_names=()):
        return self._register(Gauge, name, help_text, label_names)

    def histogram(self, name, help_text, label_names=(), buckets=_DEFAULT_BUCKETS):
        return self._register(
            Histogram, name, help_text, label_names, buckets=buckets
        )

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for key, extra, value in m.collect():
                labels = [
                    f'{n}="{v}"' for n, v in zip(m.label_names, key) if v != ""
                ]
                if extra == "__sum__":
                    name, labels_s = f"{m.name}_sum", ",".join(labels)
                elif extra == "__count__":
                    name, labels_s = f"{m.name}_count", ",".join(labels)
                elif extra:
                    name = f"{m.name}_bucket"
                    labels_s = ",".join(labels + [extra])
                else:
                    name, labels_s = m.name, ",".join(labels)
                body = f"{{{labels_s}}}" if labels_s else ""
                out.append(f"{name}{body} {value}")
        return "\n".join(out) + "\n"


# process-global registry (the reference's lazy_static metric statics)
REGISTRY = Registry()

# -- canonical metric families (names mirror the reference) ----------------------

BLOCK_PROCESSING_TIMES = REGISTRY.histogram(
    "beacon_block_processing_seconds",
    "Full runtime of block processing (beacon_chain/src/metrics.rs)",
)
ATTESTATION_BATCH_SETUP_TIMES = REGISTRY.histogram(
    "beacon_attestation_batch_signature_setup_times",
    "Batch attestation signature-set construction "
    "(attestation_verification/batch.rs:57)",
)
ATTESTATION_BATCH_VERIFY_TIMES = REGISTRY.histogram(
    "beacon_attestation_batch_signature_verify_times",
    "Batch attestation signature verification "
    "(attestation_verification/batch.rs:106)",
)
FORK_CHOICE_GET_HEAD_TIMES = REGISTRY.histogram(
    "beacon_fork_choice_get_head_seconds",
    "Fork-choice head computation",
)
PROCESSOR_WORK_EVENTS = REGISTRY.counter(
    "beacon_processor_work_events_total",
    "Work events accepted by the beacon processor",
    label_names=("work_type",),
)
PROCESSOR_QUEUE_LENGTH = REGISTRY.gauge(
    "beacon_processor_queue_length",
    "Current per-work-type queue length",
    label_names=("work_type",),
)
PROCESSOR_OVERFLOW_DROPS = REGISTRY.counter(
    "beacon_processor_overflow_drops_total",
    "Work dropped on queue overflow, per work type",
    label_names=("work_type",),
)
PROCESSOR_EXPIRED_DROPS = REGISTRY.counter(
    "beacon_processor_expired_drops_total",
    "Work dropped past its deadline before dispatch, per work type",
    label_names=("work_type",),
)
GOSSIP_VERDICT_LATENCY = REGISTRY.histogram(
    "gossip_verdict_latency_seconds",
    "End-to-end wire-ingest to verification-verdict latency",
)
ADMISSION_LEVEL = REGISTRY.gauge(
    "loadshed_admission_level",
    "Current admission level (0=HEALTHY 1=BUSY 2=SATURATED)",
)
ADMISSION_TRANSITIONS = REGISTRY.counter(
    "loadshed_admission_transitions_total",
    "Admission-level transitions",
    label_names=("from_level", "to_level"),
)
SHED_REQUESTS = REGISTRY.counter(
    "loadshed_shed_total",
    "Requests shed by admission control, per surface and priority class",
    label_names=("surface", "priority"),
)
RPC_EXPIRED = REGISTRY.counter(
    "rpc_server_expired_total",
    "Req/Resp requests dropped server-side past the client deadline",
    label_names=("method",),
)
RPC_RTT = REGISTRY.histogram(
    "rpc_rtt_seconds",
    "Req/Resp round-trip times feeding the adaptive timeout estimator",
)
FIREHOSE_EXPIRED = REGISTRY.counter(
    "firehose_expired_total",
    "Firehose items dropped past their deadline before device dispatch",
    label_names=("work_type",),
)
FIREHOSE_INTAKE_DEPTH = REGISTRY.gauge(
    "firehose_intake_depth",
    "Buffered items per work type in the firehose intake",
    label_names=("work_type",),
)
FIREHOSE_DROPPED = REGISTRY.counter(
    "firehose_dropped_total",
    "Items shed by firehose back-pressure, per work type",
    label_names=("work_type",),
)
FIREHOSE_BATCHES_FORMED = REGISTRY.counter(
    "firehose_batches_formed_total",
    "Device batches formed by the adaptive batcher",
    label_names=("work_type",),
)
FIREHOSE_BATCH_FILL = REGISTRY.histogram(
    "firehose_batch_fill",
    "Items per formed firehose batch (pre-padding)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
)
FIREHOSE_QUEUE_LATENCY = REGISTRY.histogram(
    "firehose_queue_latency_seconds",
    "Intake-to-verdict latency through the firehose pipeline",
)
FIREHOSE_VERIFIED = REGISTRY.counter(
    "firehose_items_total",
    "Firehose verification outcomes (ok / bad_signature / prep_error)",
    label_names=("result",),
)
FIREHOSE_SHUFFLING_CACHE = REGISTRY.counter(
    "firehose_shuffling_cache_total",
    "Attester/shuffling cache tier lookups (hit / miss)",
    label_names=("result",),
)
EARLY_ATTESTER_CACHE = REGISTRY.counter(
    "early_attester_cache_total",
    "Head-block attestation-data cache lookups (hit / miss / evict)",
    label_names=("result",),
)
MESH_ACTIVE_DEVICES = REGISTRY.gauge(
    "mesh_active_devices",
    "Devices serving the last sharded verification dispatch per mesh domain",
    label_names=("domain",),
)
MESH_SHARD_VERDICTS = REGISTRY.counter(
    "mesh_shard_verdicts_total",
    "Per-shard verdicts from the sharded serving tier (ok / failed)",
    label_names=("result",),
)
RESILIENCE_FAULTS = REGISTRY.counter(
    "resilience_faults_total",
    "Classified device-path faults (resilience/faults.py taxonomy)",
    label_names=("domain", "stage", "kind"),
)
RESILIENCE_HEALTH = REGISTRY.gauge(
    "resilience_health_state",
    "Fault-domain health (0 healthy, 1 degraded, 2 quarantined)",
    label_names=("domain",),
)
RESILIENCE_DEMOTIONS = REGISTRY.counter(
    "resilience_demotions_total",
    "Health-state demotions per fault domain",
    label_names=("domain",),
)
RESILIENCE_PROMOTIONS = REGISTRY.counter(
    "resilience_promotions_total",
    "Health-state re-promotions per fault domain",
    label_names=("domain",),
)
RESILIENCE_RETRIES = REGISTRY.counter(
    "resilience_retries_total",
    "Transient-fault retries on a supervised stage",
    label_names=("domain", "stage"),
)
RESILIENCE_FALLBACK_CALLS = REGISTRY.counter(
    "resilience_fallback_calls_total",
    "Supervised calls answered below the full device rung",
    label_names=("domain", "rung"),
)
RESILIENCE_WATCHDOG_TIMEOUTS = REGISTRY.counter(
    "resilience_watchdog_timeouts_total",
    "Supervised calls that blew the watchdog deadline (hangs)",
    label_names=("domain", "stage"),
)
RESILIENCE_RECOVERIES = REGISTRY.counter(
    "resilience_recoveries_total",
    "Restart-from-disk recoveries (beacon_chain/recovery.py)",
)
RESILIENCE_RECOVERY_REPLAYED = REGISTRY.counter(
    "resilience_recovery_replayed_records_total",
    "WAL records replayed across restart-from-disk recoveries",
)
RESILIENCE_RECOVERY_TRUNCATED = REGISTRY.counter(
    "resilience_recovery_truncated_bytes_total",
    "Torn-tail bytes truncated by WAL replay across recoveries",
)
RESILIENCE_RECOVERY_TIMES = REGISTRY.histogram(
    "resilience_recovery_seconds",
    "Restart-from-disk recovery wall clock (store replay -> serving head)",
)
SLASHER_CHUNKS_UPDATED = REGISTRY.counter(
    "slasher_chunks_updated_total",
    "Slasher target-array rows updated (slasher/src/metrics.rs)",
    label_names=("array",),
)
SLASHER_PAIRS_SWEPT = REGISTRY.counter(
    "slasher_pairs_swept_total",
    "(attestation x validator) pairs through the span-store sweep, by the "
    "rung that served them (device / host)",
    label_names=("backend",),
)
SLASHER_SURVEILLANCE_GAP = REGISTRY.counter(
    "slasher_surveillance_gap_total",
    "Evidence pairs the slasher engine SHED (intake overflow, exhausted "
    "batch retries) — any nonzero rate is a surveillance gap, never a "
    "silent drop",
    label_names=("reason",),
)
STORE_FREEZE_TIMES = REGISTRY.histogram(
    "store_beacon_state_freeze_seconds",
    "Cold-migration time per state (store/src/metrics.rs)",
)
EPOCH_MIRROR_BYTES = REGISTRY.gauge(
    "epoch_mirror_bytes",
    "Device-resident bytes of the epoch-engine registry mirror columns, "
    "set at every (re)grow/full-gather (epoch_engine/mirror.py; the static "
    "twin is analysis.memory.epoch_mirror_bytes)",
)
SLASHER_SPAN_PLANE_BYTES = REGISTRY.gauge(
    "slasher_span_plane_bytes",
    "Device-resident bytes of the slasher span planes (min/max distance + "
    "vote history), set at every capacity regrow/upload (slasher/engine.py; "
    "static twin analysis.memory.slasher_span_bytes)",
)
LC_COMMITTEE_CACHE_BYTES = REGISTRY.gauge(
    "lc_committee_cache_bytes",
    "Device-resident bytes of the light-client per-period committee cache, "
    "set at every cache rebuild (light_client/engine.py; static twin "
    "analysis.memory.lc_committee_cache_bytes)",
)
KZG_TABLE_BYTES = REGISTRY.gauge(
    "kzg_table_bytes",
    "Device-resident bytes of the KZG cell-verification tables, set when "
    "the CellEngine lazily builds them (kzg/engine.py; static twin "
    "analysis.memory.kzg_table_bytes)",
)
