"""Trusted-setup loading (ref crypto/kzg/src/trusted_setup.rs).

``setup_mainnet.bin`` is the public KZG ceremony output (the same constants
every consensus client embeds, cf. the reference's trusted_setup.json),
converted once to decompressed affine coordinates with every point
on-curve/subgroup-validated by the oracle backend during conversion.

Layout: ``KZGS`` magic + u32 counts (lagrange, monomial, g2), then raw
big-endian affine coords — G1 as x||y (96B), G2 as x.c0||x.c1||y.c0||y.c1
(192B). Lagrange points are stored in natural index order; ``load()``
applies the bit-reversal permutation so they align with the bit-reversed
evaluation domain (spec ``load_trusted_setup``).
"""

from __future__ import annotations

import functools
import os
import struct

from .fr import bit_reversal_permutation

_BIN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "setup_mainnet.bin")


class TrustedSetup:
    def __init__(self, g1_lagrange_brp, g1_monomial, g2_monomial):
        self.g1_lagrange_brp = g1_lagrange_brp  # oracle affine, brp order
        self.g1_monomial = g1_monomial
        self.g2_monomial = g2_monomial

    @property
    def field_elements_per_blob(self) -> int:
        return len(self.g1_lagrange_brp)


def insecure_setup(n: int, tau: int = 0x1234ABCD, n_g2: int = 2) -> TrustedSetup:
    """TEST ONLY: a setup with known tau at domain size ``n``.

    Lets the full commit/prove/verify cycle run at small blob sizes (the
    reference's fake_crypto analog for KZG). L_i(tau) is computed in Fr via
    the barycentric form, so the Lagrange points are exactly consistent with
    the monomial points — the same invariant the ceremony output satisfies.
    """
    from ..ops.bls_oracle import curves as oc
    from ..ops.bls_oracle.fields import R

    from .fr import compute_roots_of_unity

    g1, g2 = oc.g1_generator(), oc.g2_generator()
    roots_brp = compute_roots_of_unity(n)
    zn = (pow(tau, n, R) - 1) % R
    inv_n = pow(n, R - 2, R)
    lagrange_brp = [
        oc.g1_mul(g1, zn * w % R * pow((tau - w) % R, R - 2, R) % R * inv_n % R)
        for w in roots_brp
    ]
    monomial = [oc.g1_mul(g1, pow(tau, i, R)) for i in range(n)]
    # cell proofs pair against [tau^k]_2, so setups can carry more G2 powers
    # (the ceremony output ships 65 for exactly this reason)
    g2s = [oc.g2_mul(g2, pow(tau, i, R)) for i in range(max(2, n_g2))]
    return TrustedSetup(lagrange_brp, monomial, g2s)


@functools.lru_cache(maxsize=1)
def load() -> TrustedSetup:
    with open(_BIN, "rb") as fh:
        raw = fh.read()
    magic, n_lag, n_mono, n_g2 = struct.unpack_from("<4sIII", raw)
    if magic != b"KZGS":
        raise ValueError("bad trusted setup file")
    off = 16

    def g1(o):
        x = int.from_bytes(raw[o : o + 48], "big")
        y = int.from_bytes(raw[o + 48 : o + 96], "big")
        return (x, y)

    def g2(o):
        from ..ops.bls_oracle.fields import Fq2

        c = [int.from_bytes(raw[o + i * 48 : o + (i + 1) * 48], "big") for i in range(4)]
        return (Fq2(c[0], c[1]), Fq2(c[2], c[3]))

    lag = [g1(off + i * 96) for i in range(n_lag)]
    off += n_lag * 96
    mono = [g1(off + i * 96) for i in range(n_mono)]
    off += n_mono * 96
    g2s = [g2(off + i * 192) for i in range(n_g2)]
    return TrustedSetup(bit_reversal_permutation(lag), mono, g2s)
