"""KZG cells: erasure-extended blobs for data-availability sampling.

Twin of the reference's PeerDAS cell API (``crypto/kzg/src/lib.rs:220-274``:
``compute_cells_and_proofs`` / ``verify_cell_proof_batch`` /
``recover_cells_and_kzg_proofs``, backed there by rust_eth_kzg; spec:
EIP-7594 polynomial-commitments-sampling). The blob polynomial (degree < n,
given in bit-reversed evaluation form) is Reed-Solomon extended onto the
2n-th roots of unity; the bit-reversed extended domain chunks into
``CELLS_PER_EXT_BLOB`` cosets of the (2n/cells)-subgroup ("cells"). Each
cell carries one KZG multi-opening proof:

    q(X) = (p(X) - I(X)) / Z_H(X),  Z_H(X) = X^k - h^k

with I the interpolant of p on coset H and the proof a monomial-basis
commitment to q. Verification is the pairing check
``e(C - [I(tau)], G2) == e(proof, [Z_H(tau)]_2)``, needing G2 powers of tau
up to k. Recovery from >= 50% of cells runs the vanishing-polynomial method
over the extended domain (cosets are the erasure granularity, so Z_missing
is a product of sparse ``X^k - d`` factors).

Cell geometry derives from the trusted-setup size so the full cycle runs at
test scale (the reference pins n = 4096, cells = 128, k = 64).
"""

from __future__ import annotations

import functools

from ..ops.bls_oracle import curves as oc
from ..ops.bls_oracle.fields import R
from . import fr
from .fr import bit_reversal_permutation as brp
from .kzg import Kzg, KzgError
from .msm import msm

CELLS_PER_EXT_BLOB = 128  # spec constant (mainnet geometry)
BYTES_PER_FIELD_ELEMENT = 32
RECOVERY_SHIFT = 7  # coset shift for the division-by-Z step


def _fft(vals: list[int], root: int, invert: bool = False) -> list[int]:
    """Iterative radix-2 NTT over Fr, natural order in and out."""
    n = len(vals)
    if n == 1:
        return list(vals)
    if invert:
        root = pow(root, R - 2, R)
    a = list(vals)
    # bit-reversal reorder
    j = 0
    for i in range(1, n):
        bit = n >> 1
        while j & bit:
            j ^= bit
            bit >>= 1
        j |= bit
        if i < j:
            a[i], a[j] = a[j], a[i]
    length = 2
    while length <= n:
        w_len = pow(root, n // length, R)
        for i in range(0, n, length):
            w = 1
            half = length // 2
            for k in range(i, i + half):
                u, v = a[k], a[k + half] * w % R
                a[k] = (u + v) % R
                a[k + half] = (u - v) % R
                w = w * w_len % R
        length *= 2
    if invert:
        inv_n = pow(n, R - 2, R)
        a = [x * inv_n % R for x in a]
    return a


class CellContext:
    """Cell geometry + domains for one trusted setup."""

    def __init__(self, kzg: Kzg, cells_per_ext_blob: int = CELLS_PER_EXT_BLOB,
                 msm_backend: str | None = None):
        self.kzg = kzg
        # every MSM below routes through the one kzg/msm.py dispatch seam;
        # None defers to bls.get_backend() (the historical behaviour)
        self.msm_backend = msm_backend
        self.n = kzg.n
        self.ext = 2 * self.n
        self.cells = min(cells_per_ext_blob, self.ext)
        self.k = self.ext // self.cells  # field elements per cell
        self.bytes_per_cell = self.k * BYTES_PER_FIELD_ELEMENT
        if len(kzg.setup.g2_monomial) <= self.k:
            raise KzgError(
                f"trusted setup has {len(kzg.setup.g2_monomial)} G2 powers; "
                f"cell proofs need tau^{self.k}"
            )
        # natural-order ext roots + their brp view (chunking order)
        self.w_n = pow(
            fr.PRIMITIVE_ROOT_OF_UNITY, (R - 1) // self.n, R
        )
        self.w_ext = pow(
            fr.PRIMITIVE_ROOT_OF_UNITY, (R - 1) // self.ext, R
        )
        self.ext_roots_nat = [pow(self.w_ext, i, R) for i in range(self.ext)]
        self.ext_roots_brp = brp(self.ext_roots_nat)
        self.mu = pow(self.w_ext, self.cells, R)  # k-th root for cosets
        self._mu_pows = [pow(self.mu, j, R) for j in range(self.k)]
        self.g2_gen = oc.g2_generator()

    # -- coset helpers -----------------------------------------------------

    def coset_points(self, cell_index: int) -> list[int]:
        """The chunk of brp extended roots backing cell ``cell_index``."""
        return self.ext_roots_brp[
            cell_index * self.k : (cell_index + 1) * self.k
        ]

    def _coset_base(self, pts: list[int]) -> int:
        """The coset is {c * mu^j}; return c (the chunk's j=0 element)."""
        c = pts[0]
        members = {c * m % R for m in self._mu_pows}
        if set(pts) != members:
            raise KzgError("cell chunk is not a mu-coset")  # geometry bug
        return c

    def _interpolant_coeffs(self, pts: list[int], vals: list[int]) -> list[int]:
        """Coefficients of I with I(pts[j]) = vals[j] (|pts| = k)."""
        c = self._coset_base(pts)
        # natural coset order c*mu^j: map chunk order -> j by lookup
        inv_c = pow(c, R - 2, R)
        order = {m: j for j, m in enumerate(self._mu_pows)}
        nat = [0] * self.k
        for p, v in zip(pts, vals):
            nat[order[p * inv_c % R]] = v
        b = _fft(nat, self.mu, invert=True)
        inv_ci = fr.batch_inverse([pow(c, j, R) for j in range(self.k)])
        return [b[j] * inv_ci[j] % R for j in range(self.k)]

    # -- compute -----------------------------------------------------------

    def blob_to_coeffs(self, blob: bytes) -> list[int]:
        evals_brp = self.kzg._blob_to_polynomial(blob)
        return _fft(brp(evals_brp), self.w_n, invert=True)

    def cells_from_coeffs(self, coeffs: list[int]) -> list[list[int]]:
        ext_evals = _fft(coeffs + [0] * (self.ext - len(coeffs)), self.w_ext)
        ext_brp = brp(ext_evals)
        return [
            ext_brp[i * self.k : (i + 1) * self.k]
            for i in range(self.cells)
        ]

    def _cell_proof(self, coeffs: list[int], cell_index: int,
                    cell_vals: list[int]) -> bytes:
        pts = self.coset_points(cell_index)
        interp = self._interpolant_coeffs(pts, cell_vals)
        d = pow(self._coset_base(pts), self.k, R)
        # (p - I) / (X^k - d) by synthetic division; remainder must vanish
        rem = list(coeffs)
        for j, a in enumerate(interp):
            rem[j] = (rem[j] - a) % R
        q = [0] * (len(rem) - self.k)
        for i in range(len(rem) - 1, self.k - 1, -1):
            q[i - self.k] = rem[i]
            rem[i - self.k] = (rem[i - self.k] + d * rem[i]) % R
            rem[i] = 0
        if any(rem[: self.k]):
            raise KzgError("cell does not lie on the blob polynomial")
        proof = msm(
            self.kzg.setup.g1_monomial[: len(q)], q,
            backend=self.msm_backend,
        )
        return oc.g1_compress(proof)

    def compute_cells_and_kzg_proofs(
        self, blob: bytes
    ) -> tuple[list[bytes], list[bytes]]:
        return self._emit(self.blob_to_coeffs(blob))

    # -- verify ------------------------------------------------------------

    def _cell_to_fields(self, cell: bytes) -> list[int]:
        if len(cell) != self.bytes_per_cell:
            raise KzgError(f"cell must be {self.bytes_per_cell} bytes")
        return [
            fr.bytes_to_bls_field(cell[i * 32 : (i + 1) * 32])
            for i in range(self.k)
        ]

    @functools.lru_cache(maxsize=256)
    def _coset_verify_consts(self, cell_index: int):
        """(pts, [Z(tau)]_2) per coset — identical for every repeated index
        in a batch (each data column repeats one index per blob)."""
        pts = tuple(self.coset_points(cell_index))
        d = pow(self._coset_base(list(pts)), self.k, R)
        z2 = oc.g2_add(
            self.kzg.setup.g2_monomial[self.k],
            oc.g2_neg(oc.g2_mul(self.g2_gen, d)),
        )
        return pts, z2

    def verify_cell_kzg_proof(
        self, commitment: bytes, cell_index: int, cell: bytes, proof: bytes
    ) -> bool:
        if not 0 <= cell_index < self.cells:
            return False
        try:
            vals = self._cell_to_fields(cell)
            c_pt = self.kzg._parse_g1(commitment, "commitment")
            q_pt = self.kzg._parse_g1(proof, "proof")
        except KzgError:
            return False
        pts_t, z2 = self._coset_verify_consts(cell_index)
        pts = list(pts_t)
        interp = self._interpolant_coeffs(pts, vals)
        i_commit = msm(
            self.kzg.setup.g1_monomial[: self.k], interp,
            backend=self.msm_backend,
        )
        from ..ops.bls_oracle.pairing import multi_pairing_is_one

        lhs = oc.g1_add(c_pt, oc.g1_neg(i_commit)) if c_pt else (
            oc.g1_neg(i_commit) if i_commit else None
        )
        # e(C - [I], G2) * e(-proof, [Z(tau)]_2) == 1
        pairs = []
        if lhs is not None:
            pairs.append((lhs, self.g2_gen))
        if q_pt is not None:
            pairs.append((oc.g1_neg(q_pt), z2))
        if not pairs:
            return True  # C == [I] and proof at infinity: identity holds
        return multi_pairing_is_one(pairs)

    def verify_cell_kzg_proof_batch(
        self, commitments: list[bytes], cell_indices: list[int],
        cells: list[bytes], proofs: list[bytes],
    ) -> bool:
        if not (
            len(commitments) == len(cell_indices) == len(cells) == len(proofs)
        ):
            return False
        return all(
            self.verify_cell_kzg_proof(c, i, cell, pr)
            for c, i, cell, pr in zip(commitments, cell_indices, cells, proofs)
        )

    # -- recover -----------------------------------------------------------

    def recover_cells_and_kzg_proofs(
        self, cell_indices: list[int], cells: list[bytes]
    ) -> tuple[list[bytes], list[bytes]]:
        """Rebuild ALL cells + proofs from >= 50% of them (spec
        recover_cells_and_kzg_proofs; ref ``crypto/kzg/src/lib.rs:274``)."""
        have = dict(zip(cell_indices, cells))
        if len(have) != len(cell_indices):
            raise KzgError("duplicate cell indices")
        if len(have) * 2 < self.cells:
            raise KzgError("recovery needs at least half the cells")
        if any(not 0 <= i < self.cells for i in have):
            raise KzgError("cell index out of range")
        missing = [i for i in range(self.cells) if i not in have]
        if not missing:
            # nothing to recover; still recompute proofs from the data
            ext_brp = []
            for i in range(self.cells):
                ext_brp.extend(self._cell_to_fields(have[i]))
            coeffs = self._coeffs_from_full_ext(ext_brp)
            return self._emit(coeffs)

        # E: known evals, zero at missing positions (natural ext order)
        ext_brp_vals = [0] * self.ext
        for i, cell in have.items():
            vals = self._cell_to_fields(cell)
            ext_brp_vals[i * self.k : (i + 1) * self.k] = vals
        e_nat = self._unbrp(ext_brp_vals)

        # Z_missing(X) = prod over missing cosets (X^k - d_i): sparse factors
        z_coeffs = [1]
        for i in missing:
            d = pow(self._coset_base(self.coset_points(i)), self.k, R)
            nxt = [0] * (len(z_coeffs) + self.k)
            for j, a in enumerate(z_coeffs):
                nxt[j + self.k] = (nxt[j + self.k] + a) % R
                nxt[j] = (nxt[j] - d * a) % R
            z_coeffs = nxt
        z_nat = _fft(z_coeffs + [0] * (self.ext - len(z_coeffs)), self.w_ext)

        # (p*Z) agrees with (E*Z) on the whole extended domain
        pz_coeffs = _fft(
            [e * z % R for e, z in zip(e_nat, z_nat)], self.w_ext, invert=True
        )
        # divide by Z on a shifted coset where Z never vanishes
        s = RECOVERY_SHIFT
        s_pows = [pow(s, i, R) for i in range(self.ext)]
        pz_shift = _fft(
            [c * sp % R for c, sp in zip(pz_coeffs, s_pows)], self.w_ext
        )
        z_shift = _fft(
            [
                c * sp % R
                for c, sp in zip(
                    z_coeffs + [0] * (self.ext - len(z_coeffs)), s_pows
                )
            ],
            self.w_ext,
        )
        p_shift = [
            a * b % R
            for a, b in zip(pz_shift, fr.batch_inverse(z_shift))
        ]
        p_scaled = _fft(p_shift, self.w_ext, invert=True)
        inv_s = fr.batch_inverse(s_pows)
        coeffs = [c * i % R for c, i in zip(p_scaled, inv_s)]
        if any(coeffs[self.n :]):
            raise KzgError("recovered data is not a degree < n polynomial")
        coeffs = coeffs[: self.n]
        out_cells, out_proofs = self._emit(coeffs)
        # sanity: recovery must reproduce the supplied cells
        for i, cell in have.items():
            if out_cells[i] != cell:
                raise KzgError("recovered cells disagree with inputs")
        return out_cells, out_proofs

    def _unbrp(self, vals_brp: list[int]) -> list[int]:
        idx = brp(list(range(self.ext)))
        out = [0] * self.ext
        for pos, v in zip(idx, vals_brp):
            out[pos] = v
        return out

    def _coeffs_from_full_ext(self, ext_brp_vals: list[int]) -> list[int]:
        nat = self._unbrp(ext_brp_vals)
        coeffs = _fft(nat, self.w_ext, invert=True)
        if any(coeffs[self.n :]):
            raise KzgError("data is not a degree < n polynomial")
        return coeffs[: self.n]

    def _emit(self, coeffs: list[int]) -> tuple[list[bytes], list[bytes]]:
        cell_vals = self.cells_from_coeffs(coeffs)
        cells = [
            b"".join(fr.bls_field_to_bytes(v) for v in vals)
            for vals in cell_vals
        ]
        proofs = [
            self._cell_proof(coeffs, i, vals)
            for i, vals in enumerate(cell_vals)
        ]
        return cells, proofs


@functools.lru_cache(maxsize=4)
def cell_context(kzg: Kzg = None, cells_per_ext_blob: int = CELLS_PER_EXT_BLOB):
    return CellContext(kzg or Kzg(), cells_per_ext_blob)
