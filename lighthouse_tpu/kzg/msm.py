"""G1 multi-scalar multiplication over the trusted-setup points.

Backend seam mirrors ``lighthouse_tpu.bls``: the oracle path uses the
pure-Python Pippenger (ops/bls_oracle/curves.g1_msm); the device path keeps
the setup resident as a ``[N, 3, 25]`` limb array (one-time upload, the
KZG analog of the device pubkey cache) and runs the whole MSM as one
255-step double-and-add scan over all N lanes followed by a tree reduce —
shape-stable, no per-call H2D beyond the 255x N bit matrix.
"""

from __future__ import annotations

import numpy as np

from ..ops.bls_oracle import curves as oc
from ..ops.bls_oracle.fields import R

_SCALAR_BITS = 255

_device_setups: dict[int, object] = {}


def _device_points(points):
    # keyed by identity; the cache entry pins the host list so the id can't
    # be recycled by the allocator
    entry = _device_setups.get(id(points))
    if entry is None:
        from ..ops.bls import g1 as dg1

        entry = (points, dg1.from_oracle_batch(points))
        _device_setups[id(points)] = entry
    return entry[1]


def pippenger(points, scalars, window: int = 8):
    """Host bucket MSM: ceil(255/w) windows of bucket-accumulate + sweep,
    all in Jacobian coordinates (one affine normalization at the end).

    ~6x fewer group ops than per-scalar double-and-add at blob size; the
    oracle's naive g1_msm stays as the differential-testing reference."""
    ops = oc.OPS_FQ
    sc = [int(s) % R for s in scalars]
    jac = [oc._to_jac(p, ops) if p is not None else None for p in points]
    n_windows = (_SCALAR_BITS + window - 1) // window
    acc = None
    for wi in range(n_windows - 1, -1, -1):
        if acc is not None:
            for _ in range(window):
                acc = oc._jac_double(acc, ops)
        shift = wi * window
        buckets = [None] * (1 << window)
        for p, s in zip(jac, sc):
            d = (s >> shift) & ((1 << window) - 1)
            if d:
                buckets[d] = oc._jac_add(buckets[d], p, ops)
        running, win_sum = None, None
        for b in range(len(buckets) - 1, 0, -1):
            running = oc._jac_add(running, buckets[b], ops)
            win_sum = oc._jac_add(win_sum, running, ops)
        acc = oc._jac_add(acc, win_sum, ops)
    return oc._to_affine(acc, ops)


def msm(points, scalars, backend: str | None = None):
    """sum scalars[i] * points[i] (oracle affine in, oracle affine out).

    THE MSM dispatch seam (ISSUE 16 satellite): every host-side setup MSM —
    blob commitments, cell proofs, interpolant commitments, the engine's
    table construction — funnels through here. ``backend`` accepts both the
    kzg seam's names (``host`` / ``device``) and the bls seam's
    (``oracle`` / ``native`` / ``tpu``); ``None`` defers to
    ``bls.get_backend()`` as before."""
    from .. import bls

    backend = backend or bls.get_backend()
    if backend in ("host", "oracle", "native"):
        backend = "pippenger"
    elif backend == "device":
        backend = "tpu"
    if backend != "tpu":
        return pippenger(points, scalars)

    import jax.numpy as jnp

    from ..ops.bls import g1 as dg1

    dev = _device_points(points)
    raw = b"".join((int(s) % R).to_bytes(32, "big") for s in scalars)
    all_bits = np.unpackbits(
        np.frombuffer(raw, dtype=np.uint8).reshape(len(scalars), 32), axis=1
    )
    # 256-bit rows, scalars < 2^255: drop the always-zero top bit, MSB first
    bits = all_bits[:, 256 - _SCALAR_BITS :].T.astype(np.uint64)
    from ..ops.bls import curve

    scaled = curve.scale_bits(dg1.K, dev, jnp.asarray(bits))
    return dg1.to_oracle(dg1.psum(scaled))
