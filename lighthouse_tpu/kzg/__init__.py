"""KZG commitments (ref crypto/kzg): blob commitments, proofs, batch verify
on the framework's own BLS12-381 stack with a backend-pluggable MSM."""

from .kzg import (
    BYTES_PER_BLOB,
    BYTES_PER_COMMITMENT,
    BYTES_PER_FIELD_ELEMENT,
    BYTES_PER_PROOF,
    FIELD_ELEMENTS_PER_BLOB,
    Kzg,
    KzgError,
    VERSIONED_HASH_VERSION_KZG,
    kzg_commitment_to_versioned_hash,
)
from .setup import TrustedSetup, load as load_trusted_setup

__all__ = [
    "BYTES_PER_BLOB",
    "BYTES_PER_COMMITMENT",
    "BYTES_PER_FIELD_ELEMENT",
    "BYTES_PER_PROOF",
    "FIELD_ELEMENTS_PER_BLOB",
    "Kzg",
    "KzgError",
    "TrustedSetup",
    "VERSIONED_HASH_VERSION_KZG",
    "kzg_commitment_to_versioned_hash",
    "load_trusted_setup",
]
