"""KZG polynomial commitments for blobs (ref crypto/kzg/src/lib.rs:1-281).

The reference wraps c-kzg + rust_eth_kzg; here the scheme is implemented
directly on the framework's own BLS12-381 stack — the consensus-spec
evaluation-form algorithms (blob in Lagrange basis on bit-reversed roots of
unity, barycentric evaluation, quotient-polynomial proofs) with commitments
and proofs produced by the backend-pluggable G1 MSM (msm.py: device
scan-MSM over the resident setup, oracle Pippenger otherwise) and pairing
checks through the oracle pairing.

Wire formats match the reference: 48-byte compressed commitments/proofs
(kzg_commitment.rs, kzg_proof.rs), 131072-byte blobs, 32-byte field
elements. Fiat-Shamir domains follow the consensus spec
(``FSBLOBVERIFY_V1_`` / ``RCKZGBATCH___V1_``); EF-vector cross-validation is
wired through the conformance harness when vectors are present.
"""

from __future__ import annotations

from hashlib import sha256

from ..ops.bls_oracle import curves as oc
from ..ops.bls_oracle.pairing import multi_pairing_is_one
from ..ops.bls_oracle.fields import R as BLS_MODULUS
from . import fr
from .msm import msm, pippenger
from .setup import TrustedSetup, load

BYTES_PER_COMMITMENT = 48
BYTES_PER_PROOF = 48
BYTES_PER_FIELD_ELEMENT = 32
FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_BLOB = BYTES_PER_FIELD_ELEMENT * FIELD_ELEMENTS_PER_BLOB

FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"

VERSIONED_HASH_VERSION_KZG = 0x01


class KzgError(Exception):
    pass


def kzg_commitment_to_versioned_hash(commitment: bytes) -> bytes:
    """0x01 || sha256(commitment)[1:] (kzg_commitment.rs:8-13)."""
    return bytes([VERSIONED_HASH_VERSION_KZG]) + sha256(commitment).digest()[1:]


class Kzg:
    """Holds the trusted setup; mirrors the reference's ``Kzg`` surface."""

    def __init__(self, setup: TrustedSetup | None = None):
        self.setup = setup or load()
        self.n = self.setup.field_elements_per_blob
        self.bytes_per_blob = self.n * BYTES_PER_FIELD_ELEMENT
        self.roots = fr.compute_roots_of_unity(self.n)
        self._g2_gen = oc.g2_generator()
        self._g2_tau = self.setup.g2_monomial[1]

    # -- parsing -----------------------------------------------------------

    def _blob_to_polynomial(self, blob: bytes) -> list[int]:
        if len(blob) != self.bytes_per_blob:
            raise KzgError(
                f"blob must be {self.bytes_per_blob} bytes, got {len(blob)}"
            )
        try:
            return [
                fr.bytes_to_bls_field(blob[i * 32 : (i + 1) * 32])
                for i in range(self.n)
            ]
        except ValueError as e:
            raise KzgError(str(e)) from None

    @staticmethod
    def _parse_g1(data: bytes, what: str):
        if len(data) != 48:
            raise KzgError(f"{what} must be 48 bytes")
        try:
            pt = oc.g1_decompress(data)
        except ValueError as e:
            raise KzgError(f"bad {what}: {e}") from None
        if pt is not None and not oc.g1_in_subgroup(pt):
            raise KzgError(f"{what} not in subgroup")
        return pt

    # -- commitments -------------------------------------------------------

    def blob_to_kzg_commitment(self, blob: bytes) -> bytes:
        poly = self._blob_to_polynomial(blob)
        return oc.g1_compress(msm(self.setup.g1_lagrange_brp, poly))

    # -- single-point proofs ----------------------------------------------

    def compute_kzg_proof(self, blob: bytes, z_bytes: bytes):
        """(proof, y) proving f(z) = y (spec compute_kzg_proof)."""
        poly = self._blob_to_polynomial(blob)
        z = fr.bytes_to_bls_field(z_bytes)
        proof, y = self._compute_proof_impl(poly, z)
        return proof, fr.bls_field_to_bytes(y)

    def _compute_proof_impl(self, poly: list[int], z: int):
        r = BLS_MODULUS
        roots = self.roots
        y = fr.evaluate_polynomial_in_evaluation_form(poly, z, roots)
        # quotient q(x) = (f(x) - y) / (x - z) in evaluation form
        if z in roots:
            m = roots.index(z)
            q = [0] * len(poly)
            inv_wm = pow(roots[m], r - 2, r)
            # off-diagonal terms + the removable-singularity row m
            denoms = [(w - z) % r if i != m else 1 for i, w in enumerate(roots)]
            inv_d = fr.batch_inverse(denoms)
            for i, (f, w) in enumerate(zip(poly, roots)):
                if i == m:
                    continue
                q[i] = (f - y) % r * inv_d[i] % r
                # q_m += (f_i - y) * w_i / (w_m * (w_m - w_i));
                # 1/(w_m - w_i) = -inv_d[i] since z = w_m
                q[m] = (q[m] + (f - y) * w % r * (-inv_d[i]) % r * inv_wm) % r
        else:
            denoms = [(w - z) % r for w in roots]
            inv_d = fr.batch_inverse(denoms)
            q = [(f - y) % r * inv % r for f, inv in zip(poly, inv_d)]
        proof = msm(self.setup.g1_lagrange_brp, q)
        return oc.g1_compress(proof), y

    def verify_kzg_proof(
        self, commitment: bytes, z_bytes: bytes, y_bytes: bytes, proof: bytes
    ) -> bool:
        """Pairing check e(C - [y]G1, [1]G2) == e(proof, [tau - z]G2)."""
        c = self._parse_g1(commitment, "commitment")
        q = self._parse_g1(proof, "proof")
        z = fr.bytes_to_bls_field(z_bytes)
        y = fr.bytes_to_bls_field(y_bytes)
        return self._verify_impl(c, z, y, q)

    def _verify_impl(self, c, z: int, y: int, q) -> bool:
        g1 = oc.g1_generator()
        p_minus_y = oc.g1_add(c, oc.g1_neg(oc.g1_mul(g1, y)))
        x_minus_z = oc.g2_add(
            self._g2_tau, oc.g2_neg(oc.g2_mul(self._g2_gen, z))
        )
        # e(C - yG, -G2) * e(Q, (tau - z)G2) == 1
        return multi_pairing_is_one(
            [
                (p_minus_y, oc.g2_neg(self._g2_gen)),
                (q, x_minus_z),
            ]
        )

    # -- blob proofs -------------------------------------------------------

    def _compute_challenge(self, blob: bytes, commitment: bytes) -> int:
        data = (
            FIAT_SHAMIR_PROTOCOL_DOMAIN
            + self.n.to_bytes(16, "big")
            + blob
            + commitment
        )
        return fr.hash_to_bls_field(data)

    def compute_blob_kzg_proof(self, blob: bytes, commitment: bytes) -> bytes:
        if len(commitment) != 48:
            raise KzgError("commitment must be 48 bytes")
        poly = self._blob_to_polynomial(blob)
        z = self._compute_challenge(blob, commitment)
        proof, _y = self._compute_proof_impl(poly, z)
        return proof

    def verify_blob_kzg_proof(
        self, blob: bytes, commitment: bytes, proof: bytes
    ) -> bool:
        c = self._parse_g1(commitment, "commitment")
        q = self._parse_g1(proof, "proof")
        poly = self._blob_to_polynomial(blob)
        z = self._compute_challenge(blob, commitment)
        y = fr.evaluate_polynomial_in_evaluation_form(poly, z, self.roots)
        return self._verify_impl(c, z, y, q)

    def verify_blob_kzg_proof_batch(
        self, blobs: list[bytes], commitments: list[bytes], proofs: list[bytes]
    ) -> bool:
        """Random-linear-combination batch: one MSM over proofs/commitments
        and a single 2-pairing check (spec verify_kzg_proof_batch; the
        reference's batch entry point is lib.rs:155-182)."""
        if not (len(blobs) == len(commitments) == len(proofs)):
            raise KzgError("batch length mismatch")
        if not blobs:
            return True
        r_mod = BLS_MODULUS
        cs, qs, zs, ys = [], [], [], []
        for blob, commitment, proof in zip(blobs, commitments, proofs):
            cs.append(self._parse_g1(commitment, "commitment"))
            qs.append(self._parse_g1(proof, "proof"))
            poly = self._blob_to_polynomial(blob)
            z = self._compute_challenge(blob, commitment)
            zs.append(z)
            ys.append(
                fr.evaluate_polynomial_in_evaluation_form(poly, z, self.roots)
            )
        data = (
            RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
            + self.n.to_bytes(8, "big")
            + len(blobs).to_bytes(8, "big")
        )
        for commitment, z, y, proof in zip(commitments, zs, ys, proofs):
            data += commitment + fr.bls_field_to_bytes(z) + fr.bls_field_to_bytes(y) + proof
        r = fr.hash_to_bls_field(data)
        powers, acc = [], 1
        for _ in range(len(blobs)):
            powers.append(acc)
            acc = acc * r % r_mod
        # C' = sum r^i (C_i - [y_i]G1 + z_i Q_i);  Q' = sum r^i Q_i
        # check e(C', -G2) * e(Q', tau G2) == 1
        g1 = oc.g1_generator()
        terms, scalars = [], []
        for c, q, z, y, p in zip(cs, qs, zs, ys, powers):
            terms.extend([c, g1, q])
            scalars.extend([p, (-p * y) % r_mod, p * z % r_mod])
        c_prime = pippenger(terms, scalars)
        q_prime = pippenger(qs, powers)
        return multi_pairing_is_one(
            [
                (c_prime, oc.g2_neg(self._g2_gen)),
                (q_prime, self._g2_tau),
            ]
        )
