"""Device-batched KZG cell-proof engine — the second cryptosystem on the
plan compiler (ISSUE 16).

``CellContext.verify_cell_kzg_proof_batch`` runs one host pairing per cell;
this engine folds a whole batch into ONE combined pairing check on the
device (see ``ops/kzg/verify`` for the math) behind the
``LIGHTHOUSE_KZG_BACKEND = auto | device | host`` seam that mirrors the
BLS / epoch / slasher seams:

* ``host``   — the existing ``CellContext`` per-cell loop (parity oracle).
* ``device`` — the batched graph: Fr limb math on the ``fq`` conv seam,
  setup-time coset tables compiled as ``chain_plans`` fixed-scalar plans,
  one ``scale_bits`` scan for every scalar multiply, one Miller product +
  final exponentiation. Data-parallel over columns via the PR-10 shard
  planner when more than one local device is visible (whole columns per
  shard; each shard is still one combined check).
* ``auto``   — ``device`` iff JAX is backed by an accelerator.

The device path runs under the ``kzg_device`` resilience domain
(injection stage ``kzg.cell_batch_verify``): ``device_full`` →
``device_reduced`` (split halves, fresh transcripts) → ``cpu_oracle``
(the host loop). A fully faulted ladder returns ``False`` — data
availability FAILS CLOSED, a broken device can never mark a column
verified.
"""

from __future__ import annotations

import functools
import hashlib
import os
from collections import OrderedDict

import numpy as np

from ..ops.bls_oracle.fields import R
from ..resilience import SupervisedFault, kzg_supervisor
from .cells import CellContext
from .kzg import Kzg, KzgError

_BACKEND = os.environ.get("LIGHTHOUSE_KZG_BACKEND", "auto")
_AUTO_DECISION: bool | None = None

TRANSCRIPT_TAG = b"LHTPU_KZG_CELL_BATCH_V1"


def set_kzg_backend(name: str) -> None:
    global _BACKEND, _AUTO_DECISION
    if name not in ("auto", "device", "host"):
        raise ValueError(f"unknown kzg backend {name!r}")
    _BACKEND = name
    _AUTO_DECISION = None


def get_kzg_backend() -> str:
    return _BACKEND


def _accelerator_present() -> bool:
    global _AUTO_DECISION
    if _AUTO_DECISION is None:
        try:
            import jax

            _AUTO_DECISION = jax.devices()[0].platform in ("tpu", "gpu")
        except Exception:  # noqa: BLE001 — no jax / no devices: host path
            _AUTO_DECISION = False
    return _AUTO_DECISION


def device_backend_active() -> bool:
    if _BACKEND == "host":
        return False
    if _BACKEND == "device":
        return True
    return _accelerator_present()


# --------------------------------------------------------------------------------------
# Host-side marshalling
# --------------------------------------------------------------------------------------


def _fq_limbs(vals) -> np.ndarray:
    """Base-field ints -> uint64 [n, 25] limb rows (little-endian 16-bit)."""
    raw = b"".join(int(v).to_bytes(50, "little") for v in vals)
    return np.frombuffer(raw, dtype="<u2").reshape(len(vals), 25).astype(
        np.uint64
    )


class _PointCache:
    """Bytes-keyed bounded LRU over ``Kzg._parse_g1`` (columns repeat the
    same commitments every slot; proofs are one-shot but cheap to keep)."""

    def __init__(self, maxsize: int = 4096):
        self._store: OrderedDict[bytes, object] = OrderedDict()
        self._maxsize = maxsize

    def parse(self, data: bytes, what: str):
        hit = self._store.get(data)
        if hit is not None:
            self._store.move_to_end(data)
            return hit[0]
        pt = Kzg._parse_g1(data, what)  # raises KzgError on bad encodings
        self._store[data] = (pt,)
        if len(self._store) > self._maxsize:
            self._store.popitem(last=False)
        return pt


# --------------------------------------------------------------------------------------
# The engine
# --------------------------------------------------------------------------------------


class CellEngine:
    """Device tables + jitted graphs for one ``CellContext`` geometry.

    Everything static — the coset permutation, the shared inverse-NTT
    matrix, descale/shift rows, the setup points, and the chain-plans
    ``[tau^k - d_i]G2`` table — is built once (lazily, on first device
    verify) and embedded into the jitted graphs as constants."""

    def __init__(self, ctx: CellContext):
        self.ctx = ctx
        self._tables = None
        self._z2_tab = None
        self._points = _PointCache()
        self._jit_batch = {}
        self._jit_single = None

    # -- table construction (host, once) -----------------------------------

    def _build_tables(self):
        if self._tables is not None:
            return self._tables
        import jax.numpy as jnp

        from ..ops.bls import chain_plans, curve, g1 as dg1, g2 as dg2
        from ..ops.bls_oracle import curves as oc
        from ..ops.kzg import frops
        from ..ops.kzg.verify import VerifyTables

        ctx, k = self.ctx, self.ctx.k
        # chunk order -> natural coset order must be the SAME static
        # permutation for every coset (brp within the chunk); validate it
        # against the context geometry for every cell index
        order = {m: j for j, m in enumerate(ctx._mu_pows)}
        perm = None
        bases = []
        for i in range(ctx.cells):
            pts = ctx.coset_points(i)
            c = ctx._coset_base(pts)
            bases.append(c)
            inv_c = pow(c, R - 2, R)
            js = [order[p * inv_c % R] for p in pts]
            pm = np.zeros(k, dtype=np.int64)
            pm[js] = np.arange(k)
            if perm is None:
                perm = pm
            elif not np.array_equal(perm, pm):
                raise KzgError("coset chunk order is not uniform")
        perm = perm.astype(np.int32)

        inv_k = pow(k, R - 2, R)
        inv_mu = pow(ctx.mu, R - 2, R)
        idft = frops.fr_to_limbs(
            [
                pow(inv_mu, j * t, R) * inv_k % R
                for t in range(k)
                for j in range(k)
            ]
        ).reshape(k, k, 25)
        cinv = frops.fr_to_limbs(
            [
                pow(c, (R - 2) * t, R)
                for c in bases
                for t in range(k)
            ]
        ).reshape(ctx.cells, k, 25)
        d_ints = [pow(c, k, R) for c in bases]
        dtab = frops.fr_to_limbs(d_ints)

        setup = np.asarray(
            dg1.from_oracle_batch(ctx.kzg.setup.g1_monomial[:k])
        )
        g2_gen = np.asarray(dg2.from_oracle(oc.g2_generator()))
        t2 = np.asarray(dg2.from_oracle(ctx.kzg.setup.g2_monomial[k]))

        self._tables = VerifyTables(
            perm=perm, idft=np.asarray(idft), cinv=np.asarray(cinv),
            dtab=np.asarray(dtab), setup=setup,
            g2x=g2_gen[0:2], g2y=g2_gen[2:4], t2x=t2[0:2], t2y=t2[2:4],
        )

        # coset-shift table [tau^k - d_i]G2 as ONE chain-plans fixed-scalar
        # plan: the d_i are host-known setup constants, so all ``cells``
        # chains share a joint odd-multiple table and one scan
        schedule = chain_plans.compile_chains(tuple(-d for d in d_ints))
        gens = jnp.broadcast_to(
            jnp.asarray(g2_gen), (ctx.cells,) + g2_gen.shape
        )
        neg_d_g2 = chain_plans.run_point_chains(2, gens, schedule)
        t2_proj = jnp.broadcast_to(jnp.asarray(t2), neg_d_g2.shape)
        self._z2_tab = np.asarray(curve.point_add(2, t2_proj, neg_d_g2))
        from ..utils import metrics

        metrics.KZG_TABLE_BYTES.set(
            sum(a.nbytes for a in self._tables) + self._z2_tab.nbytes
        )
        return self._tables

    # -- jitted graphs ------------------------------------------------------

    def _batch_fn(self, n_pad: int):
        fn = self._jit_batch.get(n_pad)
        if fn is None:
            import jax

            from ..ops.kzg import verify

            tables = self._build_tables()
            fn = jax.jit(functools.partial(verify.cell_batch_check, tables))
            self._jit_batch[n_pad] = fn
        return fn

    def _single_fn(self):
        if self._jit_single is None:
            import jax

            from ..ops.kzg import verify

            tables = self._build_tables()
            self._jit_single = jax.jit(
                functools.partial(
                    verify.cell_single_check, self._z2_tab, tables=tables
                )
            )
        return self._jit_single

    # -- transcript ---------------------------------------------------------

    def _rlc_weights(self, commitments, cell_indices, cells, proofs):
        """Fiat-Shamir batch weights: one transcript hash over the whole
        claim, then per-item field derivations (nonzero by construction —
        a zero weight would let its cell escape the check)."""
        from .fr import hash_to_bls_field

        h = hashlib.sha256()
        h.update(TRANSCRIPT_TAG)
        h.update(self.ctx.cells.to_bytes(8, "little"))
        h.update(self.ctx.k.to_bytes(8, "little"))
        h.update(len(cells).to_bytes(8, "little"))
        for c, i, cell, p in zip(commitments, cell_indices, cells, proofs):
            h.update(c)
            h.update(int(i).to_bytes(8, "little"))
            h.update(cell)
            h.update(p)
        seed = h.digest()
        return [
            hash_to_bls_field(seed + j.to_bytes(8, "little")) or 1
            for j in range(len(cells))
        ]

    # -- marshalling --------------------------------------------------------

    def _marshal(self, commitments, cell_indices, cells, proofs, n_pad: int):
        """Host lists -> padded device arrays. Raises KzgError on any
        malformed input (caller maps that to a False verdict, like the
        oracle). Pad rows carry (r = 0, v = 0, C = Q = inf): both sides of
        the combined check see the identity."""
        from ..ops.kzg import frops

        ctx, n = self.ctx, len(cells)
        r_ints = self._rlc_weights(commitments, cell_indices, cells, proofs)
        vals: list[int] = []
        c_pts, q_pts = [], []
        for c, cell, p in zip(commitments, cells, proofs):
            vals.extend(ctx._cell_to_fields(cell))
            c_pts.append(self._points.parse(c, "commitment"))
            q_pts.append(self._points.parse(p, "proof"))

        pad = n_pad - n
        v = np.zeros((n_pad, ctx.k, 25), dtype=np.uint64)
        v[:n] = frops.fr_to_limbs(vals).reshape(n, ctx.k, 25)
        r = np.zeros((n_pad, 25), dtype=np.uint64)
        r[:n] = frops.fr_to_limbs(r_ints)
        idx = np.zeros(n_pad, dtype=np.int32)
        idx[:n] = np.asarray(cell_indices, dtype=np.int32)

        def affine(pts):
            inf = np.array(
                [p is None for p in pts] + [True] * pad, dtype=bool
            )
            x = _fq_limbs(
                [0 if p is None else p[0] for p in pts] + [0] * pad
            )
            y = _fq_limbs(
                [0 if p is None else p[1] for p in pts] + [0] * pad
            )
            return x, y, inf

        cx, cy, cinf = affine(c_pts)
        qx, qy, qinf = affine(q_pts)
        return v, r, idx, cx, cy, cinf, qx, qy, qinf

    # -- verify -------------------------------------------------------------

    def _check_shapes(self, commitments, cell_indices, cells, proofs):
        if not (
            len(commitments) == len(cell_indices) == len(cells) == len(proofs)
        ):
            return False
        return all(0 <= int(i) < self.ctx.cells for i in cell_indices)

    def _run_one(self, commitments, cell_indices, cells, proofs) -> bool:
        from ..firehose.sharding import _bucket

        n = len(cells)
        if n == 0:
            return True
        n_pad = _bucket(n, floor=4)
        try:
            arrays = self._marshal(
                commitments, cell_indices, cells, proofs, n_pad
            )
        except KzgError:
            return False
        return bool(np.asarray(self._batch_fn(n_pad)(*arrays)))

    def verify_batch(
        self, commitments, cell_indices, cells, proofs
    ) -> bool:
        """ONE combined pairing check for the whole batch (per shard when
        a multi-device mesh splits columns)."""
        if not self._check_shapes(commitments, cell_indices, cells, proofs):
            return False
        n = len(cells)
        if n == 0:
            return True
        try:
            import jax

            n_dev = jax.local_device_count()
        except Exception:  # noqa: BLE001 — no jax: host semantics
            n_dev = 1
        groups = _column_groups(cell_indices)
        if n_dev > 1 and len(groups) > 1:
            from ..firehose.sharding import plan_shards

            plan = plan_shards(groups, min(n_dev, len(groups)))
            for shard in plan.shard_items:
                if not shard:
                    continue
                sel = list(shard)
                if not self._run_one(
                    [commitments[i] for i in sel],
                    [cell_indices[i] for i in sel],
                    [cells[i] for i in sel],
                    [proofs[i] for i in sel],
                ):
                    return False
            return True
        return self._run_one(commitments, cell_indices, cells, proofs)

    def verify_cell(
        self, commitment: bytes, cell_index: int, cell: bytes, proof: bytes
    ) -> bool:
        """Single-cell device check through the chain-plans coset table."""
        if not 0 <= int(cell_index) < self.ctx.cells:
            return False
        from ..ops.kzg import frops

        try:
            vals = self.ctx._cell_to_fields(cell)
            c_pt = self._points.parse(commitment, "commitment")
            q_pt = self._points.parse(proof, "proof")
        except KzgError:
            return False
        self._build_tables()
        v = frops.fr_to_limbs(vals).reshape(1, self.ctx.k, 25)
        one = frops.fr_to_limbs([1])
        idx = np.asarray([cell_index], dtype=np.int32)

        def aff(p):
            return (
                _fq_limbs([0 if p is None else p[0]]),
                _fq_limbs([0 if p is None else p[1]]),
                np.asarray([p is None], dtype=bool),
            )

        cx, cy, cinf = aff(c_pt)
        qx, qy, qinf = aff(q_pt)
        return bool(
            np.asarray(
                self._single_fn()(v, one, idx, cx, cy, cinf, qx, qy, qinf)
            )
        )

    # -- instrumentation ----------------------------------------------------

    def compile_probe(self, batch: int) -> dict:
        """Trace (don't run) the batch graph and report what the LOWERED
        program contains: pairing checks, pairs per check, scale scans.
        This is the 'one combined check per batch' proof the bench embeds."""
        import jax

        from ..ops.bls import fq
        from ..ops.kzg import verify

        n_pad = batch
        tables = self._build_tables()
        before = dict(verify.PROBE)
        k = self.ctx.k
        u64 = np.uint64
        specs = (
            jax.ShapeDtypeStruct((n_pad, k, 25), u64),      # v
            jax.ShapeDtypeStruct((n_pad, 25), u64),          # r
            jax.ShapeDtypeStruct((n_pad,), np.int32),        # idx
            jax.ShapeDtypeStruct((n_pad, 25), u64),          # cx
            jax.ShapeDtypeStruct((n_pad, 25), u64),          # cy
            jax.ShapeDtypeStruct((n_pad,), bool),            # cinf
            jax.ShapeDtypeStruct((n_pad, 25), u64),          # qx
            jax.ShapeDtypeStruct((n_pad, 25), u64),          # qy
            jax.ShapeDtypeStruct((n_pad,), bool),            # qinf
        )
        jax.jit(functools.partial(verify.cell_batch_check, tables)).lower(
            *specs
        )
        return {
            "batch": n_pad,
            "pairing_checks_per_batch_trace": (
                verify.PROBE["pairing_checks"] - before["pairing_checks"]
            ),
            "pairs_per_check": (
                (verify.PROBE["pairs"] - before["pairs"])
                // max(
                    1,
                    verify.PROBE["pairing_checks"]
                    - before["pairing_checks"],
                )
            ),
            "scale_scans_per_batch_trace": (
                verify.PROBE["scale_scans"] - before["scale_scans"]
            ),
            "conv_impl": fq.conv_backend(),
        }


def _column_groups(cell_indices) -> list[list[int]]:
    """Group batch positions by cell index (one data column repeats one
    index per blob) — the shard planner's whole-group unit."""
    by_col: dict[int, list[int]] = {}
    for pos, i in enumerate(cell_indices):
        by_col.setdefault(int(i), []).append(pos)
    return [by_col[i] for i in sorted(by_col)]


# --------------------------------------------------------------------------------------
# Module-level dispatch (the seam everything above the kzg package calls)
# --------------------------------------------------------------------------------------

_engines: dict[int, tuple] = {}


def get_engine(ctx: CellContext) -> CellEngine:
    entry = _engines.get(id(ctx))
    if entry is None:
        entry = (ctx, CellEngine(ctx))
        _engines[id(ctx)] = entry
    return entry[1]


def verify_cell_proof_batch(
    ctx: CellContext, commitments, cell_indices, cells, proofs
) -> bool:
    """Backend-dispatched batch verification — THE entry point for data
    availability. Host backend: the per-cell oracle loop. Device backend:
    the batched engine under the ``kzg_device`` degradation ladder; a fully
    faulted ladder FAILS CLOSED (returns False, the column stays
    unverified)."""
    if not (
        len(commitments) == len(cell_indices) == len(cells) == len(proofs)
    ):
        return False
    if not device_backend_active():
        return ctx.verify_cell_kzg_proof_batch(
            commitments, cell_indices, cells, proofs
        )
    # engine construction (table build + fixed-scalar chain compiles) is
    # deferred INTO the device rungs: a ladder demoted to cpu_oracle — or
    # one whose device rungs fault before running — never pays it
    def device_full():
        return get_engine(ctx).verify_batch(
            commitments, cell_indices, cells, proofs
        )

    def device_reduced():
        # halved batches, fresh transcripts: a shape-specific compile or
        # size-dependent numeric fault on the full graph doesn't take the
        # device path down with it
        eng = get_engine(ctx)
        mid = max(1, len(cells) // 2)
        for lo, hi in ((0, mid), (mid, len(cells))):
            if lo == hi:
                continue
            if not eng.verify_batch(
                commitments[lo:hi], cell_indices[lo:hi],
                cells[lo:hi], proofs[lo:hi],
            ):
                return False
        return True

    def cpu_oracle():
        return ctx.verify_cell_kzg_proof_batch(
            commitments, cell_indices, cells, proofs
        )

    try:
        return bool(
            kzg_supervisor().run_ladder(
                "kzg.cell_batch_verify",
                (
                    ("device_full", device_full),
                    ("device_reduced", device_reduced),
                    ("cpu_oracle", cpu_oracle),
                ),
            )
        )
    except SupervisedFault:
        return False  # fail CLOSED: never available off a faulted ladder
