"""Scalar-field (Fr) helpers for KZG: roots of unity, bit-reversal order,
barycentric evaluation with batched inversion.

The blob polynomial lives in *evaluation form* on the 4096th roots of unity
in bit-reversal permutation, per the consensus spec's polynomial-commitments
scheme that the reference wraps via c-kzg (crypto/kzg/src/lib.rs:14-20).
"""

from __future__ import annotations

from ..ops.bls_oracle.fields import R as BLS_MODULUS

BYTES_PER_FIELD_ELEMENT = 32
PRIMITIVE_ROOT_OF_UNITY = 7


def bytes_to_bls_field(b: bytes) -> int:
    """Big-endian 32-byte scalar; must be canonical (< r)."""
    if len(b) != BYTES_PER_FIELD_ELEMENT:
        raise ValueError(f"field element must be 32 bytes, got {len(b)}")
    v = int.from_bytes(b, "big")
    if v >= BLS_MODULUS:
        raise ValueError("non-canonical field element")
    return v


def bls_field_to_bytes(v: int) -> bytes:
    return int(v % BLS_MODULUS).to_bytes(32, "big")


def hash_to_bls_field(data: bytes) -> int:
    from hashlib import sha256

    return int.from_bytes(sha256(data).digest(), "big") % BLS_MODULUS


def bit_reversal_permutation(seq):
    n = len(seq)
    bits = n.bit_length() - 1
    assert 1 << bits == n, "length must be a power of two"
    return [seq[int(format(i, f"0{bits}b")[::-1], 2)] for i in range(n)]


def compute_roots_of_unity(order: int) -> list[int]:
    """Bit-reversed list of the ``order``-th roots of unity."""
    assert (BLS_MODULUS - 1) % order == 0
    w = pow(PRIMITIVE_ROOT_OF_UNITY, (BLS_MODULUS - 1) // order, BLS_MODULUS)
    roots, acc = [], 1
    for _ in range(order):
        roots.append(acc)
        acc = acc * w % BLS_MODULUS
    return bit_reversal_permutation(roots)


def batch_inverse(values: list[int]) -> list[int]:
    """Montgomery's trick: n inversions for one modexp + 3n mulmods."""
    r = BLS_MODULUS
    prefix = [1] * (len(values) + 1)
    for i, v in enumerate(values):
        if v % r == 0:
            raise ZeroDivisionError("batch_inverse: zero element")
        prefix[i + 1] = prefix[i] * v % r
    inv_all = pow(prefix[-1], r - 2, r)
    out = [0] * len(values)
    for i in range(len(values) - 1, -1, -1):
        out[i] = prefix[i] * inv_all % r
        inv_all = inv_all * values[i] % r
    return out


def evaluate_polynomial_in_evaluation_form(
    poly: list[int], z: int, roots: list[int]
) -> int:
    """Barycentric formula: f(z) = (z^N - 1)/N * sum f_i * w_i / (z - w_i),
    with the exact-evaluation special case when z is one of the roots."""
    r = BLS_MODULUS
    n = len(poly)
    if z in roots:
        return poly[roots.index(z)]
    diffs = [(z - w) % r for w in roots]
    inv_diffs = batch_inverse(diffs)
    total = 0
    for f, w, inv in zip(poly, roots, inv_diffs):
        total = (total + f * w % r * inv) % r
    zn = pow(z, n, r)
    return total * (zn - 1) % r * pow(n, r - 2, r) % r
