"""Prometheus scrape endpoint (ref beacon_node/http_metrics/src/lib.rs).

Serves the process-global registry at ``/metrics``; ``/health`` reports
liveness (common/system_health's role at its smallest)."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.metrics import REGISTRY


class MetricsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, registry=None,
                 datadir: str | None = None):
        self.registry = registry or REGISTRY
        self.datadir = datadir
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/metrics":
                    body = server.registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                elif self.path == "/health":
                    import json

                    from ..utils.system_health import system_health

                    payload = {"status": "ok"}
                    payload.update(system_health(server.datadir))
                    body = json.dumps(payload).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                else:
                    body = b"not found"
                    self.send_response(404)
                    self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"
