"""Stepwise TPU compile probe: times compile + run of each verification
kernel shape, smallest first, so a pathological compile is isolated to a
shape instead of wedging the whole bench. Writes one line per step."""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def main():
    # the persistent compile cache comes from lighthouse_tpu's package init
    print(f"platform: {jax.devices()[0].platform}", flush=True)
    from lighthouse_tpu.bls import tpu_backend as tb
    from lighthouse_tpu.ops.bls import fq

    steps = [
        ("mont_mul[64]", lambda: jax.jit(fq.mont_mul).lower(
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
        )),
        ("verify[4]", lambda: tb._verify_kernel(4).lower(
            jax.ShapeDtypeStruct((4, 3, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4, 6, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4,), jnp.uint64),
            jax.ShapeDtypeStruct((4,), jnp.bool_),
        )),
        ("gathered[8,16]", lambda: tb._gathered_kernel(8, 16).lower(
            jax.ShapeDtypeStruct((1024, 3, 25), jnp.uint64),
            jax.ShapeDtypeStruct((8, 16), jnp.int32),
            jax.ShapeDtypeStruct((8, 16), jnp.bool_),
            jax.ShapeDtypeStruct((8, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((8, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((8, 25), jnp.uint64),
            jax.ShapeDtypeStruct((8, 25), jnp.uint64),
            jax.ShapeDtypeStruct((8,), jnp.uint64),
            jax.ShapeDtypeStruct((8,), jnp.bool_),
            jax.ShapeDtypeStruct((8,), jnp.uint64),
            jax.ShapeDtypeStruct((8,), jnp.bool_),
        )),
        ("gathered[64,512]", lambda: tb._gathered_kernel(64, 512).lower(
            jax.ShapeDtypeStruct((16384, 3, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 512), jnp.int32),
            jax.ShapeDtypeStruct((64, 512), jnp.bool_),
            jax.ShapeDtypeStruct((64, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 2, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64,), jnp.uint64),
            jax.ShapeDtypeStruct((64,), jnp.bool_),
            jax.ShapeDtypeStruct((64,), jnp.uint64),
            jax.ShapeDtypeStruct((64,), jnp.bool_),
        )),
    ]
    for name, mk in steps:
        t0 = time.perf_counter()
        lowered = mk()
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
        print(
            f"{name}: lower {t_lower:.1f}s compile {t_compile:.1f}s",
            flush=True,
        )
    print("probe done", flush=True)


if __name__ == "__main__":
    main()
