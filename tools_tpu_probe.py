"""Stepwise TPU compile probe: times compile + run of each verification
kernel shape, smallest first, so a pathological compile is isolated to a
shape instead of wedging the whole bench. Writes one line per step."""

import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def main():
    # the persistent compile cache comes from lighthouse_tpu's package init
    print(f"platform: {jax.devices()[0].platform}", flush=True)
    from lighthouse_tpu.bls import tpu_backend as tb
    from lighthouse_tpu.ops.bls import fq

    steps = [
        ("mont_mul[64]", lambda: jax.jit(fq.mont_mul).lower(
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
            jax.ShapeDtypeStruct((64, 25), jnp.uint64),
        )),
        ("prologue[4]", lambda: tb._prologue_stage(4).lower(
            jax.ShapeDtypeStruct((4, 3, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4, 6, 25), jnp.uint64),
            jax.ShapeDtypeStruct((4,), jnp.uint64),
            jax.ShapeDtypeStruct((4,), jnp.bool_),
        )),
    ]
    for name, mk in steps:
        t0 = time.perf_counter()
        lowered = mk()
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0
        print(
            f"{name}: lower {t_lower:.1f}s compile {t_compile:.1f}s",
            flush=True,
        )
    # staged chain-hot-path shapes: time each stage's lower+compile separately
    # (stage_lowerings traces all three up front; lower time is reported as
    # one line so nothing is misattributed per stage)
    for n_pad, k_pad, n_val in [(8, 16, 1024), (64, 512, 16384)]:
        t0 = time.perf_counter()
        lowerings = tb.stage_lowerings(n_pad, k_pad, n_val)
        print(
            f"lower all 3 stages[{n_pad},{k_pad}]: "
            f"{time.perf_counter() - t0:.1f}s",
            flush=True,
        )
        for st_name, lowered in lowerings:
            t0 = time.perf_counter()
            lowered.compile()
            print(
                f"{st_name}[{n_pad},{k_pad}]: compile "
                f"{time.perf_counter() - t0:.1f}s",
                flush=True,
            )
    print("probe done", flush=True)


if __name__ == "__main__":
    main()
